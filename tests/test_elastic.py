"""Elastic training supervisor + deterministic fault injection.

The reference fixes worker membership at job start
(``SharedTrainingWrapper.java:131-156``) and delegates fault tolerance to
Spark retry; ``tests/test_multiprocess.py`` already proves kill-and-resume
*choreographed by the test*. These tests prove the LIBRARY composition
(``parallel/elastic.py`` + ``util/faultinject.py``):

- the supervisor state machine — restart budgeting under exponential
  backoff, shrink-to-surviving-slice, startup-flake forgiveness, heartbeat
  stall detection, job deadline — driven entirely by a fake launcher and a
  ``ManualTimeSource`` (injectable clock, **no real sleeps**), with
  ``elastic_restarts_total`` and the ``elastic_recovery`` spans asserted;
- generation fencing: checkpoints stamped by a fenced (superseded)
  generation are never chosen for restore, even when the zombie keeps
  writing;
- the ``FaultPlan`` schema/lint/hooks, including the corrupt-checkpoint
  fault exercising ``OrbaxCheckpointManager.restore(fallback=True)`` and
  the DCN drop/duplicate faults exercising the bridge's sequence dedup;
- the CI acceptance proof on real subprocess CPU workers: a 3-process job
  whose worker is SIGKILLed mid-training by a fault plan automatically
  shrinks to the surviving 2-process slice and converges, with final
  params EQUAL to a clean 2-process-shaped run resumed from the same
  checkpoint step.
"""

import json
import os
import sys
import threading

import numpy as np
import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
TOOLS = os.path.join(REPO, "tools")
if TOOLS not in sys.path:
    sys.path.insert(0, TOOLS)

from validate_fault_plan import validate_file, validate_plan  # noqa: E402

from deeplearning4j_tpu.observe import (  # noqa: E402
    MetricsRegistry,
    TraceRecorder,
    Tracer,
    disable_tracing,
    enable_tracing,
    parse_prometheus_text,
)
from deeplearning4j_tpu.parallel import elastic  # noqa: E402
from deeplearning4j_tpu.parallel.elastic import (  # noqa: E402
    BackoffPolicy,
    ElasticJobFailed,
    ElasticJobSupervisor,
    ElasticWorkerContext,
    GenerationLedger,
    StaleGenerationError,
    WorkerSpec,
    read_step_stamps,
    write_step_stamp,
)
from deeplearning4j_tpu.parallel.time_source import ManualTimeSource  # noqa: E402
from deeplearning4j_tpu.util import faultinject  # noqa: E402


@pytest.fixture(autouse=True)
def _no_leaked_fault_plan():
    """Every test starts and ends with fault injection inactive."""
    faultinject.set_plan(None)
    yield
    faultinject.set_plan(None)


# ---------------------------------------------------------------------------
# fake process world: supervisor unit tests with zero sleeps/subprocesses
# ---------------------------------------------------------------------------

class FakeProc:
    def __init__(self):
        self.rc = None
        self.kill_calls = 0

    def poll(self):
        return self.rc

    def kill(self):
        self.kill_calls += 1
        if self.rc is None:
            self.rc = -9

    def wait(self, timeout=None):
        return self.rc


class FakeWorld:
    """Launcher + scripted scheduler: ``sleep_fn`` advances the manual
    clock and runs the test's script — the supervisor believes time passes
    and processes live/die, but nothing real happens."""

    def __init__(self, clock, tick_seconds=1.0):
        self.clock = clock
        self.tick_seconds = tick_seconds
        self.generations = []  # one {slot: (env, FakeProc)} per generation
        self.script = lambda world: None
        self._beats = 0

    # -- launcher interface ----------------------------------------------
    def launch(self, argv, env, cwd, log_path):
        gen = int(env[elastic.ENV_GENERATION])
        while len(self.generations) < gen:
            self.generations.append({})
        p = FakeProc()
        self.generations[gen - 1][int(env[elastic.ENV_SLOT])] = (env, p)
        return p

    # -- scripting helpers ------------------------------------------------
    @property
    def current(self):
        return self.generations[-1]

    def beat(self, slot):
        env, proc = self.current[slot]
        if proc.rc is not None:
            return
        self._beats += 1
        with open(env[elastic.ENV_HEARTBEAT], "w", encoding="utf-8") as fh:
            fh.write(f"beat{self._beats}")

    def exit(self, slot, rc):
        self.current[slot][1].rc = rc

    def sleep(self, seconds):
        # the supervisor's poll/backoff sleeps all land here: advance the
        # virtual clock by the REQUESTED amount and run one script tick
        self.clock.advance(seconds=max(seconds, self.tick_seconds))
        self.script(self)


class GenTicker:
    """Per-generation tick counter for FakeWorld scripts."""

    def __init__(self):
        self.gen = 0
        self.tick = 0

    def __call__(self, world):
        if len(world.generations) != self.gen:
            self.gen = len(world.generations)
            self.tick = 0
        self.tick += 1
        return self.gen, self.tick


def make_supervisor(tmp_path, num_workers, **kw):
    clock = ManualTimeSource(start_ms=1_000)
    world = FakeWorld(clock)
    reg = MetricsRegistry()
    ports = iter(range(40000, 41000))
    sup = ElasticJobSupervisor(
        WorkerSpec(argv=["worker"], env={}), num_workers,
        ckpt_dir=str(tmp_path / "ckpt"), clock=clock,
        sleep_fn=world.sleep, launcher=world, metrics=reg,
        port_fn=lambda: next(ports), poll_interval_s=1.0, **kw)
    return sup, world, reg


class TestSupervisorStateMachine:
    def test_all_workers_exit_zero_completes(self, tmp_path):
        sup, world, reg = make_supervisor(tmp_path, 2, min_workers=1)
        ticker = GenTicker()

        def script(w):
            _, tick = ticker(w)
            if tick == 1:
                for slot in list(w.current):
                    w.beat(slot)
            elif tick == 2:
                for slot in list(w.current):
                    w.exit(slot, 0)
        world.script = script
        result = sup.run()
        assert result.status == "completed"
        assert len(result.generations) == 1
        assert result.restarts_total == 0
        assert result.final_world == [0, 1]
        # both workers launched with renumbered ids and the shared world
        envs = [world.current[s][0] for s in (0, 1)]
        assert [e[elastic.ENV_PROCESS_ID] for e in envs] == ["0", "1"]
        assert {e[elastic.ENV_NUM_PROCESSES] for e in envs} == {"2"}
        assert len({e[elastic.ENV_TOKEN] for e in envs}) == 1

    def test_crash_loop_exhausts_budget_and_fails_loudly(self, tmp_path):
        """The acceptance-criteria crash loop: a worker that dies after
        every restart burns its budget under backoff (manual clock, no
        sleeps) and the job fails with metrics + recovery spans
        recorded."""
        policy = BackoffPolicy(base_s=2.0, factor=2.0, max_s=60.0,
                               jitter=0.25, max_restarts=2)
        sup, world, reg = make_supervisor(
            tmp_path, 2, min_workers=2, backoff=policy)
        ticker = GenTicker()

        def script(w):
            _, tick = ticker(w)
            if tick == 1:
                for slot in list(w.current):
                    w.beat(slot)   # both live: deaths charge the budget
            elif tick == 2:
                w.exit(0, 1)       # slot 0 crashes, every generation
        world.script = script

        recorder = TraceRecorder()
        enable_tracing(Tracer(recorder), jax_hook=False)
        try:
            with pytest.raises(ElasticJobFailed) as ei:
                sup.run()
        finally:
            disable_tracing()
        result = ei.value.result
        assert result.status == "failed"
        assert "restart budget" in str(ei.value)
        assert "min_workers" in str(ei.value)
        # two budgeted restarts, then the failing third recovery
        assert result.restarts_total == 2
        assert [g.decision for g in result.generations] == \
            ["restart", "restart", "fail"]
        assert result.generations[-1].outcome == "failed"
        assert all(g.primary_slot == 0 for g in result.generations)
        # backoff delays are exactly the policy's deterministic schedule
        expected = [policy.delay(a, seed=f"elastic:0") for a in (1, 2)]
        assert result.backoff_delays == expected
        assert expected[0] != 2.0  # jitter applied
        # metrics: restarts by decision, deaths by reason
        series = parse_prometheus_text(reg.exposition())
        assert series["elastic_restarts_total"][
            (("decision", "restart"),)] == 2
        assert series["elastic_worker_deaths_total"][
            (("reason", "exit"),)] == 3
        # recovery spans: one per recovery round, attributed to the slot
        spans = [s for s in recorder.spans() if s.name == "elastic_recovery"]
        assert len(spans) == 3
        assert all(s.attrs["primary_slot"] == 0 for s in spans)

    def test_shrinks_to_surviving_slice_and_completes(self, tmp_path):
        sup, world, reg = make_supervisor(
            tmp_path, 3, min_workers=2,
            backoff=BackoffPolicy(max_restarts=0))
        ticker = GenTicker()

        def script(w):
            gen, tick = ticker(w)
            if tick == 1:
                for slot in list(w.current):
                    w.beat(slot)
            elif tick == 2 and gen == 1:
                w.exit(1, -9)  # SIGKILL-style death of slot 1
            elif tick == 2:
                for slot in list(w.current):
                    w.exit(slot, 0)
        world.script = script
        result = sup.run()
        assert result.status == "completed"
        assert [g.decision for g in result.generations] == ["shrink", None]
        assert result.generations[0].primary_slot == 1
        assert result.generations[1].world == [0, 2]
        assert result.final_world == [0, 2]
        # surviving slots renumbered to contiguous process ids in slot order
        envs = {s: world.current[s][0] for s in (0, 2)}
        assert envs[0][elastic.ENV_PROCESS_ID] == "0"
        assert envs[2][elastic.ENV_PROCESS_ID] == "1"
        assert envs[0][elastic.ENV_NUM_PROCESSES] == "2"
        # fresh coordinator port + new generation token after recovery
        g1 = world.generations[0][0][0]
        g2 = envs[0]
        assert g1[elastic.ENV_COORDINATOR] != g2[elastic.ENV_COORDINATOR]
        assert g1[elastic.ENV_TOKEN] != g2[elastic.ENV_TOKEN]
        series = parse_prometheus_text(reg.exposition())
        assert series["elastic_restarts_total"][
            (("decision", "shrink"),)] == 1
        assert series["elastic_world_size"][()] == 2
        assert series["elastic_generation"][()] == 2

    def test_startup_flake_retries_without_charging_budget(self, tmp_path):
        """A worker that dies before its first heartbeat is a port race /
        startup flake: relaunched free of charge, budget untouched."""
        sup, world, reg = make_supervisor(
            tmp_path, 2, min_workers=2,
            backoff=BackoffPolicy(max_restarts=0))
        ticker = GenTicker()

        def script(w):
            gen, tick = ticker(w)
            if gen == 1:
                if tick == 1:
                    w.exit(0, 1)  # dies before ever heartbeating
            else:
                if tick == 1:
                    for slot in list(w.current):
                        w.beat(slot)
                elif tick == 2:
                    for slot in list(w.current):
                        w.exit(slot, 0)
        world.script = script
        result = sup.run()
        assert result.status == "completed"
        assert result.generations[0].decision == "restart"
        assert result.backoff_delays == []  # startup retry: no backoff
        # with max_restarts=0 a BUDGET charge would have failed the job;
        # completing proves the death was treated as a startup flake

    def test_heartbeat_stall_is_killed_and_recovered(self, tmp_path):
        sup, world, reg = make_supervisor(
            tmp_path, 2, min_workers=2, heartbeat_timeout_s=5.0,
            backoff=BackoffPolicy(max_restarts=1, base_s=1.0, jitter=0.0))
        ticker = GenTicker()

        def script(w):
            gen, tick = ticker(w)
            if gen == 1:
                if tick == 1:
                    for slot in list(w.current):
                        w.beat(slot)
                else:
                    w.beat(1)  # slot 0 goes silent but stays running
            else:
                if tick == 1:
                    for slot in list(w.current):
                        w.beat(slot)
                elif tick == 2:
                    for slot in list(w.current):
                        w.exit(slot, 0)
        world.script = script
        result = sup.run()
        assert result.status == "completed"
        assert result.restarts_total == 1
        stalled = world.generations[0][0][1]
        assert stalled.kill_calls >= 1  # supervisor killed the hung proc
        series = parse_prometheus_text(reg.exposition())
        assert series["elastic_worker_deaths_total"][
            (("reason", "stall"),)] == 1

    def test_job_deadline_fails_loudly(self, tmp_path):
        sup, world, reg = make_supervisor(
            tmp_path, 1, job_deadline_s=30.0)
        world.script = lambda w: w.beat(0)  # beats forever, never exits
        with pytest.raises(ElasticJobFailed) as ei:
            sup.run()
        assert "deadline" in str(ei.value)
        assert world.current[0][1].kill_calls >= 1

    def test_constructor_validates_worker_counts(self, tmp_path):
        with pytest.raises(ValueError):
            ElasticJobSupervisor(WorkerSpec(argv=["w"]), 2, min_workers=3,
                                 ckpt_dir=str(tmp_path))
        with pytest.raises(ValueError):
            ElasticJobSupervisor(WorkerSpec(argv=["w"]), 0,
                                 ckpt_dir=str(tmp_path))


class TestBackoffPolicy:
    def test_deterministic_and_bounded(self):
        p = BackoffPolicy(base_s=1.0, factor=2.0, max_s=8.0, jitter=0.1,
                          max_restarts=5)
        a = [p.delay(i, seed="job:0") for i in range(1, 7)]
        b = [p.delay(i, seed="job:0") for i in range(1, 7)]
        assert a == b  # no RNG state: pure function of (seed, attempt)
        for i, d in enumerate(a, start=1):
            nominal = min(8.0, 1.0 * 2.0 ** (i - 1))
            assert abs(d - nominal) <= nominal * 0.1 + 1e-9

    def test_jitter_desynchronizes_seeds(self):
        p = BackoffPolicy(base_s=10.0, jitter=0.2)
        delays = {p.delay(1, seed=f"job:{s}") for s in range(8)}
        assert len(delays) > 1

    def test_zero_jitter_is_exact_exponential(self):
        p = BackoffPolicy(base_s=0.5, factor=3.0, max_s=100.0, jitter=0.0)
        assert [p.delay(i) for i in (1, 2, 3)] == [0.5, 1.5, 4.5]


# ---------------------------------------------------------------------------
# generation fencing
# ---------------------------------------------------------------------------

class TestGenerationFencing:
    def test_post_fence_zombie_stamp_is_not_eligible(self, tmp_path):
        d = str(tmp_path)
        ledger = GenerationLedger(d)
        ledger.open_generation(1, "t1", [0, 1])
        write_step_stamp(d, 1, "t1", 1, 2)
        write_step_stamp(d, 2, "t1", 1, 2)
        assert ledger.eligible("t1", 1) and ledger.eligible("t1", 2)
        ledger.fence("t1")
        # a zombie from generation 1 keeps writing after the fence
        write_step_stamp(d, 3, "t1", 1, 2)
        assert ledger.eligible("t1", 2)        # committed before the fence
        assert not ledger.eligible("t1", 3)    # written after it
        assert not ledger.eligible("unknown", 1)

    def test_supervisor_restore_choice_respects_fence(self, tmp_path):
        sup, world, reg = make_supervisor(tmp_path, 1)
        d = sup.ckpt_dir
        sup.ledger.open_generation(1, "t1", [0])
        write_step_stamp(d, 1, "t1", 1, 1)
        assert sup.latest_eligible_step() == 1
        sup.ledger.fence("t1")
        write_step_stamp(d, 5, "t1", 1, 1)  # zombie write: newest on disk
        assert sup.latest_eligible_step() == 1
        sup.ledger.open_generation(2, "t2", [0])
        write_step_stamp(d, 2, "t2", 2, 1)
        assert sup.latest_eligible_step() == 2

    def test_new_ledger_over_existing_dir_fences_old_lineage(self, tmp_path):
        d = str(tmp_path)
        first = GenerationLedger(d)
        first.open_generation(1, "t1", [0])
        write_step_stamp(d, 1, "t1", 1, 1)
        # supervisor crashed without fencing; a NEW supervisor loads the
        # ledger: the old generation is fenced against current stamps
        second = GenerationLedger(d)
        assert second.eligible("t1", 1)
        write_step_stamp(d, 9, "t1", 1, 1)  # zombie writes post-takeover
        assert not second.eligible("t1", 9)

    def test_torn_stamp_is_skipped(self, tmp_path):
        d = str(tmp_path)
        write_step_stamp(d, 1, "t1", 1, 2)
        with open(os.path.join(d, "elastic_step_00000002.json"), "w") as fh:
            fh.write('{"step": 2, "tok')  # torn mid-write
        stamps = read_step_stamps(d)
        assert [s["step"] for s in stamps] == [1]

    def test_worker_check_fence_raises_when_superseded(self, tmp_path):
        d = str(tmp_path)
        ctx = ElasticWorkerContext(
            coordinator="127.0.0.1:1", num_processes=2, process_id=0,
            slot=0, generation=1, token="t1", ckpt_dir=d,
            heartbeat_path=os.path.join(d, "hb"), restore_step=None)
        ctx.check_fence()  # no generation file: standalone run, fine
        elastic._atomic_write(
            os.path.join(d, elastic.GENERATION_FILE),
            json.dumps({"generation": 1, "token": "t1", "world_size": 2}))
        ctx.check_fence()  # own generation: fine
        elastic._atomic_write(
            os.path.join(d, elastic.GENERATION_FILE),
            json.dumps({"generation": 2, "token": "t2", "world_size": 1}))
        with pytest.raises(StaleGenerationError):
            ctx.check_fence()
        with pytest.raises(StaleGenerationError):
            ctx.save_checkpoint(3, model=None)  # fence precedes any write

    def test_worker_context_env_round_trip(self, tmp_path):
        env = {
            elastic.ENV_COORDINATOR: "127.0.0.1:999",
            elastic.ENV_NUM_PROCESSES: "3",
            elastic.ENV_PROCESS_ID: "1",
            elastic.ENV_SLOT: "2",
            elastic.ENV_GENERATION: "4",
            elastic.ENV_TOKEN: "g4-abc",
            elastic.ENV_CKPT_DIR: str(tmp_path),
            elastic.ENV_HEARTBEAT: str(tmp_path / "hb"),
            elastic.ENV_RESTORE_STEP: "7",
        }
        ctx = ElasticWorkerContext.from_env(env)
        assert (ctx.num_processes, ctx.process_id, ctx.slot) == (3, 1, 2)
        assert ctx.restore_step == 7
        env[elastic.ENV_RESTORE_STEP] = ""
        assert ElasticWorkerContext.from_env(env).restore_step is None
        assert ElasticWorkerContext.from_env({}) is None
        ctx.heartbeat(5)
        with open(ctx.heartbeat_path, encoding="utf-8") as fh:
            assert fh.read() == "4:5:1"
        # master-state paths are keyed by world size AND rank
        assert ctx.master_state_path(7).endswith(
            "master_state.step00000007.w3.r1.npz")
        # fence-eligible steps ride the env too (fallback allow-list)
        env[elastic.ENV_ELIGIBLE_STEPS] = "3,5,7"
        assert ElasticWorkerContext.from_env(env).eligible_steps == [3, 5, 7]
        env[elastic.ENV_ELIGIBLE_STEPS] = ""
        assert ElasticWorkerContext.from_env(env).eligible_steps == []
        del env[elastic.ENV_ELIGIBLE_STEPS]
        assert ElasticWorkerContext.from_env(env).eligible_steps is None

    def test_prune_unretained_drops_rotated_stamps_and_master_state(
            self, tmp_path):
        """Orbax rotation caps model-checkpoint disk; the stamps and the
        model-sized per-rank master-state shards for rotated-away steps
        must go with it."""
        d = str(tmp_path)
        ctx = ElasticWorkerContext(
            coordinator="", num_processes=2, process_id=0, slot=0,
            generation=1, token="t1", ckpt_dir=d,
            heartbeat_path=str(tmp_path / "hb"), restore_step=None)
        for s in (1, 2, 3):
            write_step_stamp(d, s, "t1", 1, 2)
            for r in (0, 1):
                with open(ctx.master_state_path(s, rank=r), "wb") as fh:
                    fh.write(b"x")

        class _Mgr:
            def all_steps(self):
                return [2, 3]  # step 1 rotated away

        ctx._prune_unretained(_Mgr())
        assert [s["step"] for s in read_step_stamps(d)] == [2, 3]
        assert not os.path.exists(ctx.master_state_path(1, rank=0))
        assert not os.path.exists(ctx.master_state_path(1, rank=1))
        assert os.path.exists(ctx.master_state_path(2, rank=0))
        assert os.path.exists(ctx.master_state_path(3, rank=1))


# ---------------------------------------------------------------------------
# fault plan: schema, lint, hooks
# ---------------------------------------------------------------------------

class TestFaultPlan:
    def test_parse_and_find(self):
        plan = faultinject.FaultPlan.parse({"faults": [
            {"type": "kill", "worker": 1, "step": 10},
            {"type": "drop_dcn", "worker": "*", "step": 3},
        ]})
        assert plan.find("kill", 1, 10) is not None
        assert plan.find("kill", 0, 10) is None
        assert plan.find("kill", 1, 9) is None
        assert plan.find("drop_dcn", "anything", 3) is not None

    @pytest.mark.parametrize("bad,msg", [
        ({"faults": "x"}, "list"),
        ({"faults": [{"type": "nope", "step": 1}]}, "unknown type"),
        ({"faults": [{"type": "kill", "worker": -1, "step": 1}]}, "worker"),
        ({"faults": [{"type": "kill", "step": -2}]}, "step"),
        ({"faults": [{"type": "corrupt_checkpoint", "step": 1,
                      "mode": "zap"}]}, "mode"),
        ({"faults": [{"type": "kill", "step": 1,
                      "signal": "NOSUCH"}]}, "signal"),
        ({"faults": [{"type": "kill", "step": 1, "bogus": 1}]}, "unknown"),
        ({}, "faults"),
    ])
    def test_schema_errors(self, bad, msg):
        with pytest.raises(ValueError, match=msg):
            faultinject.FaultPlan.parse(bad)

    def test_lint_duplicates_and_shadowed(self):
        plan = faultinject.FaultPlan.parse({"faults": [
            {"type": "kill", "worker": 1, "step": 5},
            {"type": "kill", "worker": 1, "step": 5},
            {"type": "stall_heartbeat", "worker": 1, "step": 9},
        ]})
        problems = plan.lint()
        assert any("duplicates" in p for p in problems)
        assert any("can never fire" in p for p in problems)
        clean = faultinject.FaultPlan.parse({"faults": [
            {"type": "kill", "worker": 1, "step": 5},
            {"type": "stall_heartbeat", "worker": 2, "step": 9},
        ]})
        assert clean.lint() == []

    def test_load_inline_json_and_file(self, tmp_path):
        spec = '{"faults": [{"type": "kill", "worker": 0, "step": 1}]}'
        assert len(faultinject.FaultPlan.load(spec).faults) == 1
        p = tmp_path / "plan.json"
        p.write_text(spec)
        assert len(faultinject.FaultPlan.load(str(p)).faults) == 1

    def test_hooks_are_noops_without_a_plan(self):
        assert faultinject.active_plan() is None
        faultinject.on_step(0, 1)
        assert faultinject.on_heartbeat(0, 1) is True
        assert faultinject.on_dcn_send(0, 1, b"x") == [b"x"]
        faultinject.on_checkpoint_saved(0, 1, "/nonexistent")

    def test_on_step_kill_fires_exactly_at_trigger(self, monkeypatch):
        killed = []
        monkeypatch.setattr(faultinject, "_kill",
                            lambda pid, sig: killed.append((pid, sig)))
        faultinject.set_plan(faultinject.FaultPlan.parse({"faults": [
            {"type": "kill", "worker": 1, "step": 10, "signal": "KILL"}]}))
        faultinject.on_step(1, 9)
        faultinject.on_step(0, 10)
        assert killed == []
        faultinject.on_step(1, 10)
        assert killed == [(os.getpid(), 9)]

    def test_on_step_stall_sleeps_for_duration(self, monkeypatch):
        slept = []
        monkeypatch.setattr(faultinject, "_sleep", slept.append)
        faultinject.set_plan(faultinject.FaultPlan.parse({"faults": [
            {"type": "stall", "worker": 0, "step": 3, "duration_s": 7.5}]}))
        faultinject.on_step(0, 3)
        assert slept == [7.5]

    def test_heartbeat_suppression_is_sticky(self):
        faultinject.set_plan(faultinject.FaultPlan.parse({"faults": [
            {"type": "stall_heartbeat", "worker": 2, "step": 5}]}))
        assert faultinject.on_heartbeat(2, 4) is True
        assert faultinject.on_heartbeat(2, 5) is False
        assert faultinject.on_heartbeat(2, 50) is False  # never resumes
        assert faultinject.on_heartbeat(1, 50) is True

    def test_dcn_drop_and_duplicate(self):
        faultinject.set_plan(faultinject.FaultPlan.parse({"faults": [
            {"type": "drop_dcn", "worker": "A", "step": 1},
            {"type": "duplicate_dcn", "worker": "A", "step": 2}]}))
        assert faultinject.on_dcn_send("A", 0, b"f") == [b"f"]
        assert faultinject.on_dcn_send("A", 1, b"f") == []
        assert faultinject.on_dcn_send("A", 2, b"f") == [b"f", b"f"]

    def test_corrupt_checkpoint_modes(self, tmp_path):
        f = tmp_path / "ckpt.bin"
        f.write_bytes(b"x" * 100)
        faultinject.corrupt_checkpoint(str(f), mode="truncate")
        assert f.stat().st_size == 50
        f.write_bytes(b"x" * 100)
        faultinject.corrupt_checkpoint(str(f), mode="garbage")
        assert b"\xff" in f.read_bytes()
        d = tmp_path / "stepdir" / "inner"
        d.mkdir(parents=True)
        (d / "a.bin").write_bytes(b"y" * 10)
        touched = faultinject.corrupt_checkpoint(
            str(tmp_path / "stepdir"), mode="delete")
        assert len(touched) == 1 and not (d / "a.bin").exists()
        with pytest.raises(FileNotFoundError):
            faultinject.corrupt_checkpoint(str(tmp_path / "nope"))
        with pytest.raises(ValueError):
            faultinject.corrupt_checkpoint(str(f), mode="zap")


class TestFaultPlanValidator:
    def test_shipped_example_plan_is_clean(self):
        path = os.path.join(REPO, "examples", "fault_plan.json")
        assert validate_file(path) == []
        assert validate_file(path, num_workers=3) == []

    def test_schema_and_lint_problems_reported(self, tmp_path):
        assert validate_plan({"faults": []}) == ["schema: no faults defined"]
        problems = validate_plan({"faults": [
            {"type": "kill", "worker": 0, "step": 1},
            {"type": "kill", "worker": 0, "step": 1}]})
        assert any(p.startswith("lint:") for p in problems)
        assert validate_plan({"faults": [{"type": "wat", "step": 1}]})[0] \
            .startswith("schema:")
        bad = tmp_path / "bad.json"
        bad.write_text("{not json")
        assert "unreadable" in validate_file(str(bad))[0]

    def test_workers_bound_check(self):
        problems = validate_plan(
            {"faults": [{"type": "kill", "worker": 5, "step": 1}]},
            num_workers=3)
        assert any("5" in p and "3 workers" in p for p in problems)


# ---------------------------------------------------------------------------
# DCN sequence dedup (satellite of the duplicate_dcn fault)
# ---------------------------------------------------------------------------

class _FrameQueue:
    def __init__(self):
        self.frames = []

    def publish(self, frame):
        self.frames.append(frame)

    def poll(self, timeout=0.0):
        return self.frames.pop(0) if self.frames else None


class TestDcnSequenceDedup:
    def _bridge_pair(self):
        from deeplearning4j_tpu.parallel.dcn import CrossSliceGradientBridge
        a_out, b_out = _FrameQueue(), _FrameQueue()
        a = CrossSliceGradientBridge(a_out, b_out, threshold=1e-3,
                                     slice_id="A")
        b = CrossSliceGradientBridge(b_out, a_out, threshold=1e-3,
                                     slice_id="B")
        return a, b, a_out

    def test_replayed_frame_applied_once(self):
        a, b, a_out = self._bridge_pair()
        params_a = [{"w": np.zeros(32, np.float32)}]
        a.publish_update(params_a)  # first call: baseline, no frame
        params_a = [{"w": np.full(32, 0.5, np.float32)}]
        assert a.publish_update(params_a) > 0
        frame = a_out.frames[-1]
        a_out.frames.append(frame)  # broker re-delivery: same frame twice
        params_b = [{"w": np.zeros(32, np.float32)}]
        params_b, applied = b.poll_and_apply(params_b)
        assert applied == 1  # duplicate dropped, update applied ONCE
        np.testing.assert_allclose(np.asarray(params_b[0]["w"]), 0.5,
                                   atol=2e-3)

    def test_duplicate_dcn_fault_sends_twice_receiver_dedups(self):
        a, b, a_out = self._bridge_pair()
        params_a = [{"w": np.zeros(16, np.float32)}]
        a.publish_update(params_a)  # all-zero baseline: no frame, seq unused
        faultinject.set_plan(faultinject.FaultPlan.parse({"faults": [
            {"type": "duplicate_dcn", "worker": "A", "step": 0}]}))
        params_a = [{"w": np.ones(16, np.float32)}]
        a.publish_update(params_a)
        assert len(a_out.frames) == 2  # the fault duplicated seq 0
        params_b = [{"w": np.zeros(16, np.float32)}]
        params_b, applied = b.poll_and_apply(params_b)
        assert applied == 1
        # sparse frames carry ±threshold quanta: ONE application leaves
        # exactly one quantum — a double-apply would show 2e-3
        np.testing.assert_allclose(np.asarray(params_b[0]["w"]), 1e-3,
                                   rtol=1e-5)

    def test_drop_dcn_fault_loses_frame_in_transit(self):
        a, b, a_out = self._bridge_pair()
        params_a = [{"w": np.zeros(16, np.float32)}]
        a.publish_update(params_a)  # all-zero baseline: no frame, seq unused
        faultinject.set_plan(faultinject.FaultPlan.parse({"faults": [
            {"type": "drop_dcn", "worker": "A", "step": 0}]}))
        a.publish_update([{"w": np.ones(16, np.float32)}])
        assert a_out.frames == []  # dropped in transit
        faultinject.set_plan(None)
        # the NEXT exchange still carries fresh movement (seq advanced)
        assert a.publish_update([{"w": np.full(16, 2.0, np.float32)}]) > 0
        meta_len = int.from_bytes(a_out.frames[-1][:4], "big")
        meta = json.loads(a_out.frames[-1][4:4 + meta_len])
        assert meta["seq"] == 1

    def test_restarted_sender_is_not_mistaken_for_a_replay(self):
        """Elastic recovery rebuilds the bridge with its seq back at 0;
        the fresh incarnation token must keep the peer from discarding
        every post-restart frame as a duplicate."""
        from deeplearning4j_tpu.parallel.dcn import CrossSliceGradientBridge
        a, b, a_out = self._bridge_pair()
        a.publish_update([{"w": np.zeros(16, np.float32)}])
        assert a.publish_update([{"w": np.ones(16, np.float32)}]) > 0
        old_frame = a_out.frames[-1]
        params_b = [{"w": np.zeros(16, np.float32)}]
        params_b, applied = b.poll_and_apply(params_b)
        assert applied == 1  # peer's high-water mark for A is now seq 0
        # A's process restarts: new bridge object, same slice id, seq=0
        a2 = CrossSliceGradientBridge(a_out, _FrameQueue(), threshold=1e-3,
                                      slice_id="A")
        a2.publish_update([{"w": np.zeros(16, np.float32)}])
        assert a2.publish_update([{"w": np.ones(16, np.float32)}]) > 0
        params_b, applied = b.poll_and_apply(params_b)
        assert applied == 1  # new incarnation accepted, not dropped
        # ...while a broker redelivering a frame from A's PREVIOUS life
        # is still recognized as already applied
        a_out.frames.append(old_frame)
        params_b, applied = b.poll_and_apply(params_b)
        assert applied == 0


# ---------------------------------------------------------------------------
# checkpoint integrity satellites
# ---------------------------------------------------------------------------

def _tiny_net(seed=1):
    from deeplearning4j_tpu.nn.conf.network import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers.core import DenseLayer
    from deeplearning4j_tpu.nn.layers.output import OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    conf = (NeuralNetConfiguration.builder().seed(seed).updater("adam")
            .list()
            .layer(DenseLayer(n_in=3, n_out=8, activation="relu"))
            .layer(OutputLayer(n_in=8, n_out=2))
            .build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.RandomState(0)
    x = rng.randn(16, 3).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[rng.randint(0, 2, 16)]
    return net, x, y


class TestOrbaxIntegrityFallback:
    def test_corrupt_latest_falls_back_to_previous_retained(self, tmp_path):
        from deeplearning4j_tpu.util.orbax_checkpoint import (
            OrbaxCheckpointManager)
        net, x, y = _tiny_net()
        d = str(tmp_path / "rot")
        with OrbaxCheckpointManager(d, max_to_keep=3) as mgr:
            for step in (1, 2):
                net.fit(x, y)
                mgr.save(step, net)
                mgr.wait_until_finished()
            good = [np.asarray(v) for layer in net.params
                    for v in layer.values()]
            del good  # params at step 2; step-1 params are older
            # the fault injector's torn checkpoint: damage EVERY file of
            # the newest step so no quiet partial restore is possible
            faultinject.corrupt_checkpoint(os.path.join(d, "2"),
                                           mode="truncate")
            with pytest.raises(ValueError,
                               match="unrestorable|truncated or corrupt"):
                mgr.restore(2)
            restored = mgr.restore(2, fallback=True)
            assert mgr.restored_step == 1
            assert restored.iteration > 0
        with OrbaxCheckpointManager(d, max_to_keep=3) as mgr2:
            again = mgr2.restore(fallback=True)  # latest → walks back
            assert mgr2.restored_step == 1
            np.testing.assert_allclose(np.asarray(again.output(x)),
                                       np.asarray(restored.output(x)),
                                       rtol=1e-6)

    def test_every_step_corrupt_raises_with_all_errors(self, tmp_path):
        from deeplearning4j_tpu.util.orbax_checkpoint import (
            OrbaxCheckpointManager)
        net, x, y = _tiny_net()
        d = str(tmp_path / "allbad")
        with OrbaxCheckpointManager(d, max_to_keep=2) as mgr:
            mgr.save(1, net)
            mgr.wait_until_finished()
            faultinject.corrupt_checkpoint(os.path.join(d, "1"),
                                           mode="delete")
            with pytest.raises(ValueError, match="no restorable checkpoint"):
                mgr.restore(1, fallback=True)

    def test_overwrite_existing_rewrites_a_corrupt_finalized_step(
            self, tmp_path):
        """Re-training a step whose finalized-but-corrupt dir survived a
        fallback restore: a plain orbax save silently declines (returns
        False, writes nothing); overwrite_existing clears the stale dir
        so the step is actually rewritten — the elastic commit path
        refuses to stamp otherwise."""
        from deeplearning4j_tpu.util.orbax_checkpoint import (
            OrbaxCheckpointManager)
        net, x, y = _tiny_net()
        d = str(tmp_path / "rewrite")
        with OrbaxCheckpointManager(d) as mgr:
            assert mgr.save(1, net)
            mgr.wait_until_finished()
            faultinject.corrupt_checkpoint(os.path.join(d, "1"),
                                           mode="truncate")
        with OrbaxCheckpointManager(d) as mgr2:
            net.fit(x, y)
            assert mgr2.save(1, net) is False      # orbax declines
            assert mgr2.save(1, net, overwrite_existing=True)
            mgr2.wait_until_finished()
            restored = mgr2.restore(1)
            assert restored.iteration == net.iteration


class TestModelZipIntegrity:
    def test_truncated_zip_fails_fast_with_clear_error(self, tmp_path):
        from deeplearning4j_tpu.util import model_serializer
        net, _, _ = _tiny_net()
        p = str(tmp_path / "m.zip")
        model_serializer.write_model(net, p)
        assert model_serializer.validate_model_zip(p) == []
        data = open(p, "rb").read()
        open(p, "wb").write(data[:len(data) // 2])
        problems = model_serializer.validate_model_zip(p)
        assert problems, "truncated zip must fail validation"
        with pytest.raises(ValueError, match="integrity"):
            model_serializer.restore_model(p)

    def test_crc_damage_detected(self, tmp_path):
        from deeplearning4j_tpu.util import model_serializer
        net, _, _ = _tiny_net()
        p = str(tmp_path / "m.zip")
        model_serializer.write_model(net, p)
        data = bytearray(open(p, "rb").read())
        # flip payload bytes early in the archive (member data, not the
        # central directory at the tail) — CRC catches it
        for i in range(64, 96):
            data[i] ^= 0xFF
        open(p, "wb").write(bytes(data))
        problems = model_serializer.validate_model_zip(p)
        assert problems
        with pytest.raises(ValueError, match="integrity"):
            model_serializer.restore_model(p)


class TestPreemptionArmOffMainThread:
    def test_arm_off_main_thread_raises_clear_error(self):
        from deeplearning4j_tpu.util.preemption import PreemptionHandler
        caught = []

        def worker():
            try:
                PreemptionHandler(None, "/tmp/never-written.zip").arm()
            except Exception as e:  # noqa: BLE001
                caught.append(e)
        t = threading.Thread(target=worker)
        t.start()
        t.join(timeout=30)
        assert len(caught) == 1
        assert isinstance(caught[0], RuntimeError)
        assert "main thread" in str(caught[0])
        assert "ElasticJobSupervisor" in str(caught[0])


class TestShardingFinalizeGuard:
    def test_unfinalized_conf_raises_loudly(self):
        import types

        from deeplearning4j_tpu.parallel.sharding import tp_param_specs
        fake = types.SimpleNamespace(
            conf=types.SimpleNamespace(_finalized=False))
        with pytest.raises(RuntimeError, match="init\\(\\)"):
            tp_param_specs(fake)

    def test_initialized_net_passes_guard(self):
        from deeplearning4j_tpu.parallel.sharding import tp_param_specs
        net, _, _ = _tiny_net()
        specs = tp_param_specs(net)  # finalized conf: no raise
        assert len(specs) == len(net.params)


# ---------------------------------------------------------------------------
# master compression-state round trip across a mesh reshape (elastic shrink)
# ---------------------------------------------------------------------------

class TestMasterStateAcrossReshape:
    def _master_with_residual(self, workers, batch):
        import jax

        from deeplearning4j_tpu.datasets.dataset import (DataSet,
                                                         ListDataSetIterator)
        from deeplearning4j_tpu.parallel import (DistributedMultiLayerNetwork,
                                                 SharedTrainingMaster)
        from deeplearning4j_tpu.parallel.mesh import make_mesh
        net, _, _ = _tiny_net(seed=3)
        rng = np.random.RandomState(1)
        x = rng.randn(4 * batch, 3).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[rng.randint(0, 2, 4 * batch)]
        mesh = make_mesh({"data": workers},
                         devices=jax.devices()[:workers])
        master = SharedTrainingMaster(batch_size_per_worker=batch // workers,
                                      threshold=1e-3, mesh=mesh)
        DistributedMultiLayerNetwork(net, master).fit(
            ListDataSetIterator(DataSet(x, y), batch), epochs=2)
        return master, x, y

    def test_residual_mass_and_threshold_survive_3_to_2(self, tmp_path):
        import jax

        master3, x, y = self._master_with_residual(3, 24)
        path = str(tmp_path / "state.npz")
        master3.save_state(path)
        saved = np.load(path)
        res_keys = sorted((k for k in saved.files if k.startswith("res")),
                          key=lambda k: int(k[3:]))
        assert res_keys, "training must have accumulated a residual"
        assert saved[res_keys[0]].shape[0] == 3  # stacked per-worker

        from deeplearning4j_tpu.parallel import SharedTrainingMaster
        from deeplearning4j_tpu.parallel.mesh import make_mesh
        mesh2 = make_mesh({"data": 2}, devices=jax.devices()[:2])
        master2 = SharedTrainingMaster(batch_size_per_worker=12,
                                       threshold=1e-3, mesh=mesh2)
        master2.load_state(path)
        assert master2.threshold == master3.threshold  # adapted value kept
        assert master2._steps_done == master3._steps_done
        # place the restored 3-worker stack onto the 2-worker mesh shape:
        # un-transmitted mass is conserved (summed then spread evenly)
        zeros = [np.zeros((2,) + tuple(saved[k].shape[1:]), np.float32)
                 for k in res_keys]
        placed = master2._place_restored_residual(zeros, mp=False,
                                                  shard_spec=None)
        for k, arr in zip(res_keys, placed):
            np.testing.assert_allclose(
                np.asarray(arr).sum(axis=0),
                np.asarray(saved[k], np.float64).sum(axis=0),
                rtol=1e-5, atol=1e-7,
                err_msg=f"{k}: residual mass lost across the reshape")

    def test_resumed_training_runs_after_reshape(self, tmp_path):
        import jax

        from deeplearning4j_tpu.datasets.dataset import (DataSet,
                                                         ListDataSetIterator)
        from deeplearning4j_tpu.parallel import (DistributedMultiLayerNetwork,
                                                 SharedTrainingMaster)
        from deeplearning4j_tpu.parallel.mesh import make_mesh
        master3, x, y = self._master_with_residual(3, 24)
        path = str(tmp_path / "state.npz")
        master3.save_state(path)
        net2, _, _ = _tiny_net(seed=3)
        mesh2 = make_mesh({"data": 2}, devices=jax.devices()[:2])
        master2 = SharedTrainingMaster(batch_size_per_worker=12,
                                       threshold=1e-3, mesh=mesh2)
        master2.load_state(path)  # deferred placement: applied on next fit
        front = DistributedMultiLayerNetwork(net2, master2)
        front.fit(ListDataSetIterator(DataSet(x, y), 24), epochs=1)
        assert np.isfinite(float(net2.score_))

    def test_architecture_mismatch_still_fails_loudly(self, tmp_path):
        import jax

        from deeplearning4j_tpu.parallel import SharedTrainingMaster
        from deeplearning4j_tpu.parallel.mesh import make_mesh
        master3, _, _ = self._master_with_residual(3, 24)
        path = str(tmp_path / "state.npz")
        master3.save_state(path)
        mesh2 = make_mesh({"data": 2}, devices=jax.devices()[:2])
        master2 = SharedTrainingMaster(mesh=mesh2)
        master2.load_state(path)
        saved = np.load(path)
        res_keys = sorted((k for k in saved.files if k.startswith("res")),
                          key=lambda k: int(k[3:]))
        # same leaf count, but per-parameter shapes from a DIFFERENT model
        zeros = [np.zeros((2, 5, 7), np.float32) for _ in res_keys]
        with pytest.raises(ValueError, match="different architecture"):
            master2._place_restored_residual(zeros, mp=False,
                                             shard_spec=None)


# ---------------------------------------------------------------------------
# real subprocess supervision
# ---------------------------------------------------------------------------

def _sub_env(extra=None):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.update(extra or {})
    return env


@pytest.mark.multiprocess
class TestRealSubprocesses:
    def test_trivial_workers_complete(self, tmp_path):
        script = ("import os\n"
                  "open(os.environ['DL4J_TPU_ELASTIC_HEARTBEAT_FILE'],"
                  "'w').write('up')\n")
        sup = ElasticJobSupervisor(
            WorkerSpec(argv=[sys.executable, "-c", script],
                       env=_sub_env()),
            2, ckpt_dir=str(tmp_path / "ckpt"), metrics=MetricsRegistry(),
            poll_interval_s=0.05, job_deadline_s=120)
        result = sup.run()
        assert result.status == "completed"
        assert len(result.generations) == 1
        logs = os.listdir(os.path.join(sup.ckpt_dir, "logs"))
        assert sorted(logs) == ["gen001_slot0.log", "gen001_slot1.log"]

    def test_crash_looping_worker_fails_after_budget(self, tmp_path):
        sup = ElasticJobSupervisor(
            WorkerSpec(argv=[sys.executable, "-c",
                             "import sys; print('boom'); sys.exit(3)"],
                       env=_sub_env()),
            1, ckpt_dir=str(tmp_path / "ckpt"), metrics=MetricsRegistry(),
            backoff=BackoffPolicy(max_restarts=1, base_s=0.01, max_s=0.02),
            startup_retries=1, poll_interval_s=0.05, job_deadline_s=120)
        with pytest.raises(ElasticJobFailed) as ei:
            sup.run()
        assert "restart budget" in str(ei.value)
        # captured worker output is reachable for postmortem
        assert "boom" in sup.tail_log(0, 1)


# ---------------------------------------------------------------------------
# the CI acceptance proof: SIGKILL mid-training → shrink 3→2 → converge,
# equal to a clean 2-worker-shaped resume from the same checkpoint
# ---------------------------------------------------------------------------

SAMPLES, FEATURES, CLASSES = 240, 6, 3
BATCH = 24          # divisible by 3 AND 2: survives the shrink
EPOCHS = 3          # 10 iterations/epoch
KILL_STEP = 14      # mid-epoch-2: checkpoint step 1 committed, step 2 not


def _make_job_inputs(tmp_path):
    from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.nn.updaters import Adam
    from deeplearning4j_tpu.util import model_serializer
    conf = (NeuralNetConfiguration.builder().seed(7).updater(Adam(0.01))
            .list()
            .layer(DenseLayer(n_out=12, activation="tanh"))
            .layer(OutputLayer(n_out=CLASSES))
            .set_input_type(InputType.feed_forward(FEATURES)).build())
    net = MultiLayerNetwork(conf).init()
    model_path = str(tmp_path / "model.zip")
    model_serializer.write_model(net, model_path)
    rng = np.random.default_rng(0)
    yc = rng.integers(0, CLASSES, SAMPLES)
    x = rng.normal(size=(SAMPLES, FEATURES)).astype(np.float32)
    x[np.arange(SAMPLES), yc] += 2.5
    y = np.eye(CLASSES, dtype=np.float32)[yc]
    data_path = str(tmp_path / "data.npz")
    np.savez(data_path, features=x, labels=y)
    return model_path, data_path, x, y


@pytest.mark.multiprocess
def test_elastic_shrink_to_surviving_slice_converges_and_matches(tmp_path):
    """ISSUE 7 acceptance: a 3-process job whose worker 1 is SIGKILLed at
    iteration 14 by the fault plan automatically shrinks to the surviving
    2-process slice [0, 2] and completes; the final params EQUAL a clean
    2-worker-shaped run resumed from the same checkpoint step."""
    model_path, data_path, x, y = _make_job_inputs(tmp_path)
    out_path = str(tmp_path / "final.zip")
    plan_path = str(tmp_path / "plan.json")
    with open(plan_path, "w", encoding="utf-8") as fh:
        json.dump({"faults": [{"type": "kill", "worker": 1,
                               "step": KILL_STEP, "signal": "KILL"}]}, fh)
    assert validate_file(plan_path, num_workers=3) == []

    spec = WorkerSpec(
        argv=[sys.executable, "-m",
              "deeplearning4j_tpu.parallel.elastic_worker",
              "--modelPath", model_path, "--dataPath", data_path,
              "--out", out_path, "--batchSize", str(BATCH),
              "--epochs", str(EPOCHS), "--threshold", "1e-3"],
        env=_sub_env({"DL4J_TPU_FAULT_PLAN": plan_path}))
    reg = MetricsRegistry()
    sup = ElasticJobSupervisor(
        spec, 3, min_workers=2, ckpt_dir=str(tmp_path / "ckpt"),
        backoff=BackoffPolicy(max_restarts=0),
        metrics=reg, poll_interval_s=0.2,
        job_deadline_s=540)  # hard bound: the job can never hang CI
    result = sup.run()

    def _debug():
        out = []
        for g in result.generations:
            for slot in g.world:
                out.append(f"--- gen {g.generation} slot {slot} ---\n"
                           + sup.tail_log(slot, g.generation, 2000))
        return "\n".join(out)

    assert result.status == "completed", _debug()
    assert len(result.generations) == 2, _debug()
    g1, g2 = result.generations
    assert g1.decision == "shrink"
    assert g1.primary_slot == 1
    assert g2.world == [0, 2]
    # the shrunk generation resumed from the only committed step
    assert g2.restore_step == 1, _debug()
    series = parse_prometheus_text(reg.exposition())
    assert series["elastic_restarts_total"][(("decision", "shrink"),)] == 1
    assert series["elastic_world_size"][()] == 2

    # ---- comparator: clean 2-worker-shaped resume from the SAME step ----
    import jax

    from deeplearning4j_tpu.datasets.dataset import (DataSet,
                                                     ListDataSetIterator)
    from deeplearning4j_tpu.parallel import (DistributedMultiLayerNetwork,
                                             SharedTrainingMaster)
    from deeplearning4j_tpu.parallel.mesh import make_mesh
    from deeplearning4j_tpu.util import model_serializer
    from deeplearning4j_tpu.util.orbax_checkpoint import (
        OrbaxCheckpointManager)

    with OrbaxCheckpointManager(sup.ckpt_dir, active_processes={0},
                                barrier_sync_key_prefix="cmp") as mgr:
        net_b = mgr.restore(1)
    assert int(net_b.epoch) == 1
    mesh2 = make_mesh({"data": 2}, devices=jax.devices()[:2])
    master = SharedTrainingMaster(batch_size_per_worker=BATCH,
                                  threshold=1e-3, mesh=mesh2)
    front = DistributedMultiLayerNetwork(net_b, master)
    for _ in range(int(net_b.epoch), EPOCHS):
        front.fit(ListDataSetIterator(DataSet(x, y), BATCH), epochs=1)

    elastic_net = model_serializer.restore_model(out_path)
    assert int(elastic_net.epoch) == EPOCHS
    for i, (a, b) in enumerate(zip(elastic_net.params, net_b.params)):
        for k in a:
            np.testing.assert_allclose(
                np.asarray(a[k]), np.asarray(b[k]), rtol=2e-5, atol=2e-6,
                err_msg=f"layer {i} param {k}: elastic shrink diverged "
                        "from the clean 2-worker resume")
