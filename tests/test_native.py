"""Native C++ runtime tests: threshold codec and prefetching data loader
(libnd4j thresholdEncode/Decode + native ETL roles, SURVEY.md §2.a)."""

import numpy as np
import pytest

from deeplearning4j_tpu.native import (
    NativeDataSetIterator,
    decode_threshold,
    encode_threshold,
    native_available,
)


class TestBuild:
    def test_native_library_builds(self):
        # g++ is part of the baked toolchain: the native path must be live
        assert native_available()


class TestThresholdCodec:
    def test_round_trip(self, rng):
        r = rng.normal(0, 1e-3, size=2048).astype(np.float32)
        thr = 1e-3
        msg = encode_threshold(r, thr)
        assert msg is not None
        dense = decode_threshold(msg, thr, len(r))
        expect = np.where(np.abs(r) >= thr, np.sign(r) * thr, 0.0).astype(np.float32)
        np.testing.assert_allclose(dense, expect, atol=1e-7)

    def test_capacity_exceeded_returns_none(self, rng):
        r = np.ones(100, np.float32)
        assert encode_threshold(r, 0.5, capacity=10) is None

    def test_matches_numpy_fallback(self, rng):
        from deeplearning4j_tpu import native as n
        r = rng.normal(0, 2e-3, size=4096).astype(np.float32)
        thr = 1.5e-3
        native_msg = encode_threshold(r, thr)
        lib, n._lib = n._lib, None
        failed, n._build_failed = n._build_failed, True
        try:
            py_msg = encode_threshold(r, thr)
        finally:
            n._lib, n._build_failed = lib, failed
        np.testing.assert_array_equal(native_msg, py_msg)

    def test_decode_additive(self):
        msg = np.array([1, -3], np.int32)  # +thr at 0, -thr at 2
        base = np.array([1.0, 1.0, 1.0], np.float32)
        out = decode_threshold(msg, 0.5, 3, out=base)
        np.testing.assert_allclose(out, [1.5, 1.0, 0.5])
        assert out is base  # true in-place application

    def test_decode_rejects_noncontiguous_out(self):
        msg = np.array([1], np.int32)
        with pytest.raises(ValueError):
            decode_threshold(msg, 0.5, 2,
                             out=np.zeros(4, np.float32)[::2])
        with pytest.raises(ValueError):
            decode_threshold(msg, 0.5, 2, out=np.zeros(2, np.float64))

    def test_extract_and_count(self, rng):
        from deeplearning4j_tpu.native import count_threshold, extract_threshold
        r = rng.normal(0, 2e-3, size=1024).astype(np.float32)
        thr = 2e-3
        expected = int(np.sum(np.abs(r) >= thr))
        assert count_threshold(r, thr) == expected
        msg = encode_threshold(r, thr)
        before = r.copy()
        extract_threshold(r, thr, msg)
        # extracted residual has the quantized mass removed
        np.testing.assert_allclose(
            r, before - decode_threshold(msg, thr, len(r)), atol=1e-7)
        # every encoded element lost exactly one ±threshold quantum
        idx = np.abs(msg) - 1
        np.testing.assert_allclose(np.abs(before[idx] - r[idx]), thr, atol=1e-7)

    def test_agrees_with_jax_compression_module(self, rng):
        """Native codec and the on-device codec must select the same elements
        with the same signs."""
        import jax.numpy as jnp
        from deeplearning4j_tpu.parallel.compression import threshold_encode
        r = rng.normal(0, 2e-3, size=512).astype(np.float32)
        thr = 2e-3
        native_msg = encode_threshold(r, thr, capacity=512)
        enc, _ = threshold_encode(jnp.asarray(r), thr, capacity=512)
        cnt = int(enc.count)
        jax_signed = ((np.asarray(enc.indices)[:cnt] + 1)
                      * np.asarray(enc.signs)[:cnt].astype(np.int32))
        np.testing.assert_array_equal(np.sort(native_msg), np.sort(jax_signed))


class TestNativeLoader:
    def test_mem_loader_covers_all_examples(self, rng):
        x = rng.normal(size=(100, 7)).astype(np.float32)
        y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, 100)]
        it = NativeDataSetIterator(x, y, batch_size=16, shuffle=False)
        rows = [ds.features for ds in it]
        assert [r.shape[0] for r in rows] == [16, 16, 16, 16, 16, 16, 4]
        np.testing.assert_allclose(np.concatenate(rows), x, rtol=1e-6)

    def test_shuffle_and_reset_reshuffles(self, rng):
        x = np.arange(64, dtype=np.float32).reshape(64, 1)
        y = np.zeros((64, 1), np.float32)
        it = NativeDataSetIterator(x, y, batch_size=64, shuffle=True, seed=9)
        first = next(iter(it)).features.ravel().copy()
        it.reset()
        second = next(iter(it)).features.ravel().copy()
        assert sorted(first) == sorted(second) == list(range(64))
        assert not np.array_equal(first, second)  # new epoch, new order
        assert not np.array_equal(first, np.arange(64))

    def test_drop_last(self, rng):
        x = rng.normal(size=(50, 3)).astype(np.float32)
        y = rng.normal(size=(50, 2)).astype(np.float32)
        it = NativeDataSetIterator(x, y, batch_size=16, drop_last=True)
        assert [ds.features.shape[0] for ds in it] == [16, 16, 16]

    def test_abandoned_iteration_restarts_from_zero(self, rng):
        x = np.arange(60, dtype=np.float32).reshape(60, 1)
        y = np.zeros((60, 1), np.float32)
        it = NativeDataSetIterator(x, y, batch_size=10, shuffle=False)
        peek = iter(it)          # abandoned generator, kept ALIVE
        first = next(peek)
        assert first.features[0, 0] == 0.0
        # a fresh iteration must restart at batch 0 even while the abandoned
        # generator has not been finalized
        full = np.concatenate([b.features for b in it]).ravel()
        np.testing.assert_array_equal(full, x.ravel())
        del peek

    def test_multiple_epochs(self, rng):
        x = rng.normal(size=(40, 3)).astype(np.float32)
        y = rng.normal(size=(40, 2)).astype(np.float32)
        it = NativeDataSetIterator(x, y, batch_size=10, shuffle=True, seed=1)
        for _ in range(3):
            assert sum(ds.features.shape[0] for ds in it) == 40
            it.reset()

    @pytest.fixture
    def idx_files(self, tmp_path, rng):
        n, rows, cols = 30, 4, 4
        images = rng.integers(0, 256, size=(n, rows, cols), dtype=np.uint8)
        labels = rng.integers(0, 3, size=n, dtype=np.uint8)
        ip = tmp_path / "images.idx"
        with open(ip, "wb") as f:
            f.write(np.array([0x803, n, rows, cols], ">u4").tobytes())
            f.write(images.tobytes())
        lp = tmp_path / "labels.idx"
        with open(lp, "wb") as f:
            f.write(np.array([0x801, n], ">u4").tobytes())
            f.write(labels.tobytes())
        return str(ip), str(lp), images, labels

    def test_idx_loader(self, idx_files):
        ip, lp, images, labels = idx_files
        it = NativeDataSetIterator(images_path=ip, labels_path=lp,
                                   n_classes=3, batch_size=10)
        batches = list(it)
        assert sum(b.features.shape[0] for b in batches) == 30
        b0 = batches[0]
        assert b0.features.shape == (10, 4, 4, 1)  # inferred square shape
        np.testing.assert_allclose(
            b0.features[0].ravel(), images[0].ravel() / 255.0, atol=1e-6)
        assert np.argmax(b0.labels[0]) == labels[0]

    def test_idx_bad_file_raises(self, tmp_path):
        bad = tmp_path / "bad.idx"
        bad.write_bytes(b"\x00" * 20)
        with pytest.raises(ValueError):
            NativeDataSetIterator(images_path=str(bad), labels_path=str(bad),
                                  n_classes=3)

    def test_trains_network(self, rng):
        from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration, InputType
        from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        y_idx = rng.integers(0, 2, 256)
        x = rng.normal(size=(256, 5)).astype(np.float32)
        x[np.arange(256), y_idx] += 2.0
        y = np.eye(2, dtype=np.float32)[y_idx]
        it = NativeDataSetIterator(x, y, batch_size=64, shuffle=True, seed=3)
        conf = (NeuralNetConfiguration.builder().seed(1).list()
                .layer(DenseLayer(n_out=8, activation="relu"))
                .layer(OutputLayer(n_out=2))
                .set_input_type(InputType.feed_forward(5)).build())
        net = MultiLayerNetwork(conf).init()
        net.fit(it, epochs=10)
        ev = net.evaluate(it)
        assert ev.accuracy() > 0.9


class TestCorpusScan:
    """Native multithreaded vocab scan (VocabConstructor.java:31 hot loop):
    identical counts to the Python fallback, identical vocab downstream."""

    def _corpus(self, tmp_path):
        import numpy as np
        words = ["alpha", "beta", "Gamma", "delta-x", "ALPHA", "beta", "émile"]
        rng = np.random.default_rng(0)
        text = " ".join(rng.choice(words, 800)) + "\nTab\tsep\r\nmore  spaces"
        p = tmp_path / "corpus.txt"
        p.write_text(text)
        return str(p)

    def test_counts_match_python_fallback(self, tmp_path, monkeypatch):
        from collections import Counter

        from deeplearning4j_tpu import native
        from deeplearning4j_tpu.nlp.vocab import scan_corpus_file

        p = self._corpus(tmp_path)
        got_native = scan_corpus_file(p, n_threads=3, to_lower=True)
        monkeypatch.setattr(native, "_load", lambda: None)
        got_py = scan_corpus_file(p, n_threads=3, to_lower=True)
        assert dict(got_native) == dict(got_py)
        want = Counter(w.decode("utf-8", errors="replace")
                       for w in open(p, "rb").read().lower().split())
        assert dict(got_native) == dict(want)
        # deterministic order: count desc, then word asc
        items = list(got_native.items())
        assert items == sorted(items, key=lambda kv: (-kv[1], kv[0]))

    def test_case_preserving_scan(self, tmp_path):
        from deeplearning4j_tpu.nlp.vocab import scan_corpus_file

        p = self._corpus(tmp_path)
        got = scan_corpus_file(p, to_lower=False)
        assert "Gamma" in got and "ALPHA" in got

    def test_vocab_from_file_equals_sequence_path(self, tmp_path):
        from deeplearning4j_tpu.nlp.vocab import VocabConstructor

        p = self._corpus(tmp_path)
        vc = VocabConstructor(min_word_frequency=2)
        cache_f = vc.build_vocab_from_file(p, to_lower=True)
        seqs = [line.lower().split()
                for line in open(p, encoding="utf-8").read().split("\n")]
        cache_s = vc.build_vocab(seqs)
        f_words = sorted((w.word, w.frequency) for w in cache_f._by_index)
        s_words = sorted((w.word, w.frequency) for w in cache_s._by_index)
        assert f_words == s_words
        # Huffman codes assigned on both paths
        assert all(w.code for w in cache_f._by_index)

    def test_missing_file_raises_oserror(self, tmp_path):
        from deeplearning4j_tpu.nlp.vocab import scan_corpus_file
        import pytest

        with pytest.raises(OSError):
            scan_corpus_file(str(tmp_path / "nope.txt"))

    def test_block_streaming_boundary(self, tmp_path, monkeypatch):
        # tokens spanning internal read-block boundaries must not split;
        # exercised indirectly here via exact-count equality on a file
        # larger than one small synthetic block is impractical in-tree, so
        # instead lock byte-collision summing: distinct byte tokens that
        # decode to the same replacement string SUM their counts
        from deeplearning4j_tpu import native
        from deeplearning4j_tpu.nlp.vocab import scan_corpus_file

        p = tmp_path / "latin1.txt"
        p.write_bytes(b"\xff \xfe \xff word")
        got = scan_corpus_file(str(p))
        assert got["�"] == 3 and got["word"] == 1
        monkeypatch.setattr(native, "_load", lambda: None)
        assert scan_corpus_file(str(p)) == got
