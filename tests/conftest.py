"""Test configuration: run on CPU with 8 virtual devices.

Must set env vars BEFORE jax is imported anywhere (SURVEY.md test strategy:
distributed semantics are validated on a virtual device mesh the way the
reference validates Spark training in local[N] mode).
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax

# The axon TPU plugin force-sets jax_platforms at import; override back to CPU
# (tests must run on the virtual 8-device CPU mesh, not the tunnel'd chip).
jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "smoke: cheap end-to-end harness checks run on every CI tier")
    config.addinivalue_line(
        "markers",
        "multiprocess: spawns real OS worker processes (jax.distributed "
        "or the elastic supervisor); every such test carries a hard "
        "subprocess timeout/deadline so a hung worker cannot wedge CI")
    config.addinivalue_line(
        "markers",
        "multihost: simulated multi-host jobs — worker processes grouped "
        "into host failure domains on localhost (elastic num_hosts); "
        "implies multiprocess discipline: a hard job_deadline_s / "
        "subprocess timeout is mandatory so a partitioned or hung host "
        "group cannot wedge CI")


@pytest.fixture
def rng():
    return np.random.default_rng(12345)
