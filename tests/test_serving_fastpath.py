"""Serving fast path (round 9): AOT bucket warmup, persistent compile
cache, int8 quantized inference, keep-alive client, dispatcher hot path.

The load-bearing oracle is the ``observe/jaxhook.py`` compile counter: a
fresh ``Tracer`` counts ``/jax/core/compile/backend_compile_duration``
events process-wide, so "zero XLA compiles during steady-state serving"
and "exactly one compile per bucket at registration" are directly
assertable — no timing, no flakes.
"""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.observe import Tracer, disable_tracing, enable_tracing
from deeplearning4j_tpu.parallel.inference import ParallelInference
from deeplearning4j_tpu.serving import (MetricsRegistry, ModelRegistry,
                                        ModelServer, ModelServingClient,
                                        QuantizedModel, ServingError,
                                        quantize_model)
from deeplearning4j_tpu.serving.quantize import (QTensor, calibrate,
                                                 param_nbytes,
                                                 quantize_array)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def small_net(seed=7, n_in=12, n_out=4):
    conf = (NeuralNetConfiguration.builder().seed(seed)
            .list()
            .layer(DenseLayer(n_in=n_in, n_out=16, activation="tanh"))
            .layer(OutputLayer(n_in=16, n_out=n_out, activation="softmax",
                               loss="negativeloglikelihood"))
            .build())
    return MultiLayerNetwork(conf).init()


@pytest.fixture
def tracer():
    """A fresh tracer purely for its process-wide compile counter."""
    t = enable_tracing(Tracer())
    yield t
    disable_tracing()


class _GateModel:
    """Blocks in ``output`` until released; used to hold warmup open so the
    cold-bucket readiness window is deterministic. Carries a fake ``conf``-
    free surface, so the row spec must come from ``input_shape=``."""

    def __init__(self, n_out=2):
        self.gate = threading.Event()
        self.entered = threading.Event()
        self.n_out = n_out

    def output(self, x):
        self.entered.set()
        assert self.gate.wait(10.0), "test forgot to release the gate"
        x = np.asarray(x)
        return np.zeros((x.shape[0], self.n_out), np.float32)


# ------------------------------------------------------------------- buckets
class TestDeclaredBuckets:
    def test_default_buckets_are_pow2_to_max(self):
        pi = ParallelInference(small_net(), max_batch_size=32)
        try:
            assert pi.buckets == (1, 2, 4, 8, 16, 32)
        finally:
            pi.shutdown()

    def test_explicit_buckets_and_fallback(self):
        pi = ParallelInference(small_net(), max_batch_size=32,
                               buckets=[4, 16])
        try:
            assert pi.buckets == (4, 16)
            assert pi._bucket_for(1) == (4, True)
            assert pi._bucket_for(4) == (4, True)
            assert pi._bucket_for(5) == (16, True)
            # beyond every declared bucket: pow2 fallback, flagged cold
            assert pi._bucket_for(17) == (32, False)
        finally:
            pi.shutdown()

    def test_mesh_rounds_buckets_to_data_axis(self):
        from deeplearning4j_tpu.parallel.mesh import make_mesh
        mesh = make_mesh()
        d = mesh.shape.get("data", 1)
        pi = ParallelInference(small_net(), max_batch_size=8, mesh=mesh,
                               buckets=[1, 2, 8])
        try:
            assert all(b % d == 0 for b in pi.buckets)
        finally:
            pi.shutdown()

    def test_bad_buckets_rejected(self):
        with pytest.raises(ValueError):
            ParallelInference(small_net(), buckets=[])
        with pytest.raises(ValueError):
            ParallelInference(small_net(), buckets=[0, 4])

    def test_coalescing_never_exceeds_largest_bucket(self, rng):
        """Two 12-row requests against buckets=[16] must dispatch as two
        16-padded batches, not one cold 32-batch (the carry path)."""
        seen = []

        class Spy:
            def output(self, x):
                x = np.asarray(x)
                seen.append(x.shape[0])
                return x[:, :4]

        pi = ParallelInference(Spy(), max_batch_size=16, buckets=[16],
                               wait_ms=50.0)
        try:
            xs = rng.normal(size=(12, 12)).astype(np.float32)
            results = []
            ts = [threading.Thread(
                target=lambda: results.append(pi.output(xs)))
                for _ in range(2)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            assert len(results) == 2
            assert all(r.shape == (12, 4) for r in results)
            assert seen and all(s == 16 for s in seen)
        finally:
            pi.shutdown()

    def test_cold_counter_catches_unwarmed_dtype(self, rng):
        """A declared bucket hit with a dtype warmup never executed is a
        new jit signature → must count as a cold dispatch."""
        from deeplearning4j_tpu.serving import MetricsRegistry, \
            parse_prometheus_text
        metrics = MetricsRegistry()
        pi = ParallelInference(small_net(), max_batch_size=4, buckets=[4],
                               wait_ms=0.0, metrics=metrics,
                               metrics_name="m")
        try:
            pi.warmup((12,), dtype=np.float32)

            def cold_count():
                parsed = parse_prometheus_text(metrics.exposition())
                series = parsed.get("inference_cold_dispatches_total", {})
                return series.get((("model", "m"),), 0)

            pi.output(rng.normal(size=(2, 12)).astype(np.float32))
            assert cold_count() == 0
            pi.output(rng.integers(0, 2, size=(2, 12)).astype(np.int32))
            assert cold_count() == 1
        finally:
            pi.shutdown()

    def test_cold_counter_catches_update_model_without_warmup(self, rng):
        """update_model() publishes a model whose jit cache is cold — its
        first dispatches must count cold even though the OLD model's
        signatures were warmed (warm state cannot cross a swap)."""
        from deeplearning4j_tpu.serving import MetricsRegistry, \
            parse_prometheus_text
        metrics = MetricsRegistry()
        pi = ParallelInference(small_net(seed=1), max_batch_size=4,
                               buckets=[4], wait_ms=0.0, metrics=metrics,
                               metrics_name="m")
        try:
            pi.warmup((12,), dtype=np.float32)

            def cold_count():
                parsed = parse_prometheus_text(metrics.exposition())
                series = parsed.get("inference_cold_dispatches_total", {})
                return series.get((("model", "m"),), 0)

            x = rng.normal(size=(2, 12)).astype(np.float32)
            pi.output(x)
            assert cold_count() == 0
            pi.update_model(small_net(seed=2))  # never warmed
            pi.output(x)
            assert cold_count() == 1
            pi.warmup((12,), dtype=np.float32)  # re-warm the new model
            pi.output(x)
            assert cold_count() == 1
        finally:
            pi.shutdown()

    def test_pad_buffer_reused_and_zeroed(self, rng):
        """Same bucket twice → one buffer; the second batch's tail must not
        contain the first batch's rows."""
        captured = []

        class Capture:
            def output(self, x):
                captured.append(np.asarray(x).copy())
                return np.asarray(x)[:, :2]

        pi = ParallelInference(Capture(), max_batch_size=8, buckets=[8],
                               wait_ms=0.0)
        try:
            a = np.full((6, 3), 7.0, np.float32)
            b = np.full((2, 3), 3.0, np.float32)
            pi.output(a)
            pi.output(b)
            assert len(pi._pad_buffers) == 1
            second = captured[1]
            assert np.all(second[:2] == 3.0)
            assert np.all(second[2:] == 0.0)  # rows of `a` fully cleared
        finally:
            pi.shutdown()

    def test_pad_buffer_cache_is_bounded(self):
        """Clients pick row shape/dtype on the binary path — the per-
        signature buffers must not grow without bound."""

        class Echo:
            def output(self, x):
                return np.asarray(x)

        pi = ParallelInference(Echo(), max_batch_size=4, buckets=[4],
                               wait_ms=0.0)
        try:
            cap = pi._max_pad_buffers
            for width in range(1, cap + 8):  # each width = a new signature
                pi.output(np.zeros((2, width), np.float32))
            assert len(pi._pad_buffers) <= cap
        finally:
            pi.shutdown()


# -------------------------------------------------------------- AOT warmup
class TestWarmupCompiles:
    def test_zero_compiles_steady_state_and_exact_per_bucket(self, tracer,
                                                             rng):
        """THE acceptance oracle: (a) registration warms every declared
        bucket; (b) a SECOND identical-architecture registration pays
        exactly one XLA compile per bucket (utility kernels are process-
        warm by then); (c) steady-state traffic spanning the buckets pays
        ZERO."""
        buckets = [4, 8]
        metrics = MetricsRegistry()
        registry = ModelRegistry(metrics=metrics, buckets=buckets,
                                 warmup="sync")
        try:
            registry.register("a", small_net(1))  # utility kernels warm now
            c0 = tracer.compile_count
            registry.register("b", small_net(2))
            per_bucket = tracer.compile_count - c0
            assert per_bucket == len(buckets), \
                f"expected one compile per bucket, saw {per_bucket}"
            state = registry.warmup_state("b")
            assert state["status"] == "warm"
            assert state["warm"] == buckets
            # steady state: every reachable batch size, repeatedly
            c1 = tracer.compile_count
            for rows in (1, 2, 3, 4, 5, 8, 7, 1, 8):
                out = registry.predict(
                    "b", rng.normal(size=(rows, 12)).astype(np.float32))
                assert out.shape == (rows, 4)
            assert tracer.compile_count == c1, \
                "XLA compile leaked into steady-state serving"
        finally:
            registry.shutdown()

    def test_hot_swap_keeps_warm(self, tracer, rng):
        """v2 is warmed at ITS registration; activating it must not compile
        anything, and serving v2 stays compile-free."""
        registry = ModelRegistry(buckets=[4], warmup="sync")
        try:
            registry.register("m", small_net(1))
            registry.register("m", small_net(2), activate=False)
            c0 = tracer.compile_count
            registry.activate("m", 2)
            for _ in range(3):
                registry.predict(
                    "m", rng.normal(size=(3, 12)).astype(np.float32))
            assert tracer.compile_count == c0
            # and rollback lands on the still-warm v1
            registry.rollback("m")
            registry.predict("m",
                             rng.normal(size=(2, 12)).astype(np.float32))
            assert tracer.compile_count == c0
        finally:
            registry.shutdown()

    def test_rewarm_is_idempotent(self, tracer):
        """Warming an already-warm model compiles nothing — proof the
        warmup path is byte-identical to the dispatch path."""
        registry = ModelRegistry(buckets=[2, 4], warmup="sync")
        try:
            registry.register("m", small_net())
            served = registry.get("m")
            c0 = tracer.compile_count
            served.inference.warmup((12,))
            assert tracer.compile_count == c0
        finally:
            registry.shutdown()

    def test_warmup_metrics_exported(self):
        metrics = MetricsRegistry()
        registry = ModelRegistry(metrics=metrics, buckets=[2, 4],
                                 warmup="sync")
        try:
            registry.register("m", small_net())
            from deeplearning4j_tpu.serving import parse_prometheus_text
            parsed = parse_prometheus_text(metrics.exposition())
            assert parsed["serving_buckets_warm"][(("model", "m"),)] == 2
            assert parsed["serving_warmup_seconds"][(("model", "m"),)] > 0
        finally:
            registry.shutdown()

    def test_stub_without_spec_skips_warmup_and_stays_ready(self):
        registry = ModelRegistry(warmup="sync")
        server = ModelServer(registry)
        server.start()
        try:
            gate = _GateModel()
            gate.gate.set()  # never blocks: warmup is skipped entirely
            registry.register("stub", gate)
            state = registry.warmup_state("stub")
            assert state["status"] == "skipped"
            assert "input spec" in state["reason"]
            ready, body = server.readiness_detail()
            assert ready and body["reason"] == "ok"
        finally:
            server.stop(drain=False)
            registry.shutdown()

    def test_warmup_off_restores_lazy_behavior(self):
        registry = ModelRegistry(warmup="off", buckets=[2])
        try:
            registry.register("m", small_net())
            assert registry.warmup_state("m")["status"] == "skipped"
            assert registry.warmed()  # off == no readiness gate
        finally:
            registry.shutdown()

    def test_warmup_failure_is_contained(self):
        """A model whose forward raises records an error state instead of
        killing registration; /readyz lists its buckets as cold AND names
        the failure so an operator can tell it from a running warmup."""

        class Boom:
            def output(self, x):
                raise RuntimeError("kaboom")

        registry = ModelRegistry(warmup="sync", buckets=[2])
        try:
            registry.register("bad", Boom(), input_shape=(3,))
            state = registry.warmup_state("bad")
            assert state["status"] == "error"
            assert "kaboom" in state["reason"]
            assert registry.cold_buckets() == {"bad": [2]}
            assert "kaboom" in registry.warmup_errors()["bad"]
            ready, body = ModelServer(registry).readiness_detail()
            assert ready is False
            assert "kaboom" in body["warmup_errors"]["bad"]
        finally:
            registry.shutdown()

    def test_rewarm_recovers_failed_warmup(self):
        """rewarm() is the no-restart recovery path: a transient failure
        at registration-time warmup must be repairable in-process."""

        class FlakyOnce:
            def __init__(self):
                self.calls = 0

            def output(self, x):
                self.calls += 1
                if self.calls == 1:
                    raise RuntimeError("transient device hiccup")
                return np.asarray(x)[:, :1]

        registry = ModelRegistry(warmup="sync", buckets=[2])
        try:
            registry.register("flaky", FlakyOnce(), input_shape=(3,))
            assert registry.warmup_state("flaky")["status"] == "error"
            assert not registry.warmed()
            registry.rewarm("flaky")
            assert registry.warmup_state("flaky")["status"] == "warm"
            assert registry.warmed()
            assert registry.warmup_errors() == {}
        finally:
            registry.shutdown()

    def test_float64_sample_input_warms_the_float32_wire_dtype(self):
        """np.random defaults to float64, but requests arrive float32
        (JSON parse); warming '<f8' would leave every live dispatch
        falsely counted cold."""
        registry = ModelRegistry(warmup="sync", buckets=[2])
        try:
            spec = registry._resolve_row_spec(
                small_net(), None, np.random.default_rng(0).normal(
                    size=(4, 12)))  # float64 sample
            assert spec == ((12,), np.float32)
        finally:
            registry.shutdown()

    def test_async_activate_defers_hot_swap_until_warm(self):
        """Registering v2 with warmup='async' must NOT swap live traffic
        onto the still-cold version — activation happens when its warmup
        completes."""
        gate = _GateModel()
        registry = ModelRegistry(warmup="async", buckets=[2])
        try:
            registry.register("m", small_net(seed=1))
            deadline = time.monotonic() + 10.0
            while (time.monotonic() < deadline
                   and registry.warmup_state("m")["status"] != "warm"):
                time.sleep(0.02)
            assert registry.warmup_state("m")["status"] == "warm"
            v2 = registry.register("m", gate, input_shape=(5,))
            assert gate.entered.wait(5.0)  # v2 warmup underway...
            assert registry.get("m").describe()["current_version"] == 1
            gate.gate.set()
            deadline = time.monotonic() + 10.0
            while (time.monotonic() < deadline
                   and registry.get("m").describe()["current_version"] != v2):
                time.sleep(0.02)
            assert registry.get("m").describe()["current_version"] == v2
            assert registry.warmup_state("m", v2)["status"] == "warm"
        finally:
            gate.gate.set()
            registry.shutdown()


# ------------------------------------------------------- readiness & async
class TestReadyzColdBuckets:
    def test_readyz_503_lists_cold_buckets_until_warm(self):
        """Async warmup held open by a gate: /readyz must answer 503 with
        the cold bucket list, then flip to 200 when warmup finishes."""
        gate = _GateModel()
        registry = ModelRegistry(warmup="async", buckets=[2, 4])
        server = ModelServer(registry)
        server.start()
        client = ModelServingClient(server.url)
        try:
            registry.register("g", gate, input_shape=(5,))
            assert gate.entered.wait(5.0)  # warmup thread is inside bucket 1
            with pytest.raises(ServingError) as ei:
                client._request("/readyz")
            assert ei.value.status == 503
            body = json.loads(ei.value.message or "{}") \
                if ei.value.message.startswith("{") else None
            # the client surfaces .message from the "error" key only; go
            # to the wire for the full body
            import urllib.request
            try:
                urllib.request.urlopen(server.url + "/readyz", timeout=5)
                pytest.fail("expected 503")
            except urllib.error.HTTPError as e:
                payload = json.loads(e.read().decode())
            assert payload["ready"] is False
            assert payload["reason"] == "warmup incomplete"
            assert payload["cold_buckets"]["g"], payload
            gate.gate.set()
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                if client.ready():
                    break
                time.sleep(0.02)
            assert client.ready()
            assert registry.warmup_state("g")["status"] == "warm"
            assert registry.cold_buckets() == {}
        finally:
            gate.gate.set()
            client.close()
            server.stop(drain=False)
            registry.shutdown()


# ------------------------------------------------------------- quantization
class TestInt8Inference:
    def test_quantize_array_shapes_and_passthrough(self, rng):
        w = rng.normal(size=(32, 16)).astype(np.float32)
        qt = quantize_array(w)
        assert isinstance(qt, QTensor)
        assert np.asarray(qt.q).dtype == np.int8
        assert np.asarray(qt.scale).shape == (1, 16)  # per output channel
        # reconstruction error bounded by half a quantization step
        back = np.asarray(qt.dequantize())
        step = np.asarray(qt.scale)
        assert np.all(np.abs(back - w) <= step / 2 + 1e-7)
        # tiny and 1-d leaves pass through untouched
        b = rng.normal(size=(16,)).astype(np.float32)
        assert quantize_array(b) is b

    def test_int8_output_tolerance_vs_float32(self, rng):
        net = small_net(3, n_in=24, n_out=6)
        q = quantize_model(net, "int8")
        x = rng.normal(size=(8, 24)).astype(np.float32)
        stats = calibrate(net, q, x)
        # softmax outputs: int8 weight error stays in the third decimal
        assert stats["max_abs_err"] < 0.05
        assert stats["rel_err"] < 0.05
        got = np.asarray(q.output(x))
        ref = np.asarray(net.output(x))
        np.testing.assert_allclose(got, ref, atol=0.05)

    def test_bf16_policy(self, rng):
        net = small_net(4)
        q = quantize_model(net, "bf16")
        x = rng.normal(size=(4, 12)).astype(np.float32)
        np.testing.assert_allclose(np.asarray(q.output(x)),
                                   np.asarray(net.output(x)), atol=0.05)
        assert q.param_nbytes < param_nbytes(net.params)

    def test_float32_policy_is_identity(self):
        net = small_net()
        assert quantize_model(net, "float32") is net
        assert quantize_model(net, None) is net

    def test_path_loaded_int8_releases_float_params(self, tmp_path, rng):
        """A registry-owned checkpoint load must not pin a full float
        param copy next to the quantized one; a live-object registration
        must (the caller may still train it)."""
        from deeplearning4j_tpu.util.model_serializer import write_model
        net = small_net(seed=9)
        zip_path = tmp_path / "m.zip"
        write_model(net, zip_path)
        registry = ModelRegistry(warmup="sync", buckets=[2])
        try:
            registry.register("frompath", path=str(zip_path),
                              dtype_policy="int8")
            q = registry.get("frompath").versions[1].model
            assert isinstance(q, QuantizedModel)
            assert q.base.params is None  # float copy released
            x = rng.normal(size=(2, 12)).astype(np.float32)
            assert np.asarray(q.output(x)).shape == (2, 4)  # still serves
            registry.register("live", net, dtype_policy="int8")
            live = registry.get("live").versions[1].model
            assert live.base.params is not None  # caller's object untouched
        finally:
            registry.shutdown()

    def test_graph_model_quantizes(self, rng):
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        conf = (NeuralNetConfiguration.builder().seed(5).graph_builder()
                .add_inputs("in")
                .add_layer("d", DenseLayer(n_in=10, n_out=32,
                                           activation="relu"), "in")
                .add_layer("out", OutputLayer(n_in=32, n_out=3,
                                              activation="softmax",
                                              loss="mcxent"), "d")
                .set_outputs("out").build())
        g = ComputationGraph(conf).init()
        q = quantize_model(g, "int8")
        x = rng.normal(size=(6, 10)).astype(np.float32)
        np.testing.assert_allclose(np.asarray(q.output(x)),
                                   np.asarray(g.output(x)), atol=0.05)

    def test_registry_serves_int8_version_with_metadata(self, rng):
        registry = ModelRegistry(buckets=[4], warmup="sync")
        server = ModelServer(registry)
        server.start()
        client = ModelServingClient(server.url)
        try:
            net = small_net(6)
            sample = rng.normal(size=(4, 12)).astype(np.float32)
            v = registry.register("m", net, dtype_policy="int8",
                                  sample_input=sample)
            served = registry.get("m")
            mv = served.versions[v]
            assert isinstance(mv.model, QuantizedModel)
            assert mv.dtype_policy == "int8"
            assert mv.quant_error["rel_err"] < 0.05
            desc = client.model("m")
            vd = desc["versions"][-1]
            assert vd["dtype_policy"] == "int8"
            assert "quant_error" in vd
            out = client.predict("m", sample)
            np.testing.assert_allclose(
                out, np.asarray(net.output(sample)), atol=0.05)
        finally:
            client.close()
            server.stop(drain=False)
            registry.shutdown()

    def test_quant_tolerance_rejects_at_registration(self, rng):
        registry = ModelRegistry(warmup="off")
        try:
            with pytest.raises(ValueError, match="tolerance"):
                registry.register(
                    "m", small_net(8), dtype_policy="int8",
                    sample_input=rng.normal(size=(4, 12)).astype(np.float32),
                    quant_tolerance=1e-9)
            assert not registry.has("m")
        finally:
            registry.shutdown()

    def test_unknown_policy_rejected(self):
        registry = ModelRegistry(warmup="off")
        try:
            with pytest.raises(ValueError, match="dtype_policy"):
                registry.register("m", small_net(), dtype_policy="fp4")
        finally:
            registry.shutdown()


# -------------------------------------------------------- persistent cache
class TestPersistentCompileCache:
    def test_registry_populates_cache_dir(self, tmp_path):
        cache = tmp_path / "xla-cache"
        registry = ModelRegistry(buckets=[2], warmup="sync",
                                 compile_cache_dir=str(cache))
        try:
            registry.register("m", small_net())
            files = list(cache.iterdir())
            assert files, "warmup wrote nothing into the compile cache"
        finally:
            registry.shutdown()

    def test_retarget_rejected(self, tmp_path):
        from deeplearning4j_tpu.util.compile_cache import (
            enable_persistent_compile_cache, persistent_compile_cache_dir)
        active = persistent_compile_cache_dir()
        assert active is not None  # latched by the test above or this one
        with pytest.raises(ValueError, match="already active"):
            enable_persistent_compile_cache(str(tmp_path / "elsewhere"))


# ------------------------------------------------------- keep-alive client
class TestClientKeepAlive:
    def test_connection_reused_across_predicts(self, rng):
        registry = ModelRegistry(buckets=[4], warmup="sync")
        server = ModelServer(registry)
        server.start()
        client = ModelServingClient(server.url)
        try:
            registry.register("m", small_net())
            client.predict("m", rng.normal(size=(2, 12)).astype(np.float32))
            conn = client._connection()
            sock = conn.sock
            assert sock is not None  # still open after the response
            for _ in range(3):
                client.predict("m",
                               rng.normal(size=(1, 12)).astype(np.float32))
            assert client._connection() is conn
            assert client._connection().sock is sock
        finally:
            client.close()
            assert client._connection().sock is None or True
            server.stop(drain=False)
            registry.shutdown()

    def test_connection_survives_error_responses(self, rng):
        """4xx must not poison the persistent connection (body drained)."""
        registry = ModelRegistry(buckets=[4], warmup="sync")
        server = ModelServer(registry)
        server.start()
        client = ModelServingClient(server.url)
        try:
            registry.register("m", small_net())
            with pytest.raises(ServingError) as ei:
                client.predict("nope", [[0.0] * 12])
            assert ei.value.status == 404
            conn = client._connection()
            out = client.predict("m", rng.normal(size=(2, 12))
                                 .astype(np.float32))
            assert out.shape == (2, 4)
            assert client._connection() is conn
        finally:
            client.close()
            server.stop(drain=False)
            registry.shutdown()

    def test_reconnects_after_server_restart(self, rng):
        """A server bounce (new listener, same port) looks like a dropped
        keep-alive connection; the client must reconnect transparently."""
        registry = ModelRegistry(buckets=[2], warmup="sync")
        server = ModelServer(registry)
        port = server.start()
        client = ModelServingClient(server.url)
        try:
            registry.register("m", small_net())
            client.predict("m", rng.normal(size=(1, 12)).astype(np.float32))
            server.stop(drain=False)
            server2 = ModelServer(registry, port=port)
            server2.start()
            try:
                out = client.predict(
                    "m", rng.normal(size=(1, 12)).astype(np.float32))
                assert out.shape == (1, 4)
            finally:
                server2.stop(drain=False)
        finally:
            client.close()
            registry.shutdown()


# ------------------------------------------------------------ bench --check
@pytest.mark.smoke
class TestBenchServingCheck:
    def test_check_mode_passes_against_committed_series(self):
        """The regression harness itself is exercised every run: tiny
        model, 2 buckets, deterministic oracles (schema, warm coverage,
        zero steady-state compiles, keep-alive)."""
        committed = os.path.join(REPO_ROOT, "BENCH_SERVING_r01.json")
        assert os.path.exists(committed), \
            "BENCH_SERVING_r01.json must be committed with the series"
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO_ROOT, "bench_serving.py"),
             "--check", committed],
            capture_output=True, text=True, timeout=300,
            env=dict(os.environ, JAX_PLATFORMS="cpu"))
        assert proc.returncode == 0, \
            f"stdout: {proc.stdout}\nstderr: {proc.stderr}"
        assert "check OK" in proc.stdout

    def test_committed_series_records_acceptance_numbers(self):
        """The acceptance criteria live in the committed JSON: warm p99 and
        cold first-request latency for at least two model configs."""
        with open(os.path.join(REPO_ROOT, "BENCH_SERVING_r01.json")) as f:
            rec = json.load(f)
        assert rec["series"] == "BENCH_SERVING"
        ok = [c for c in rec["configs"].values()
              if "error" not in c
              and c["closed_loop"].get("p99_ms") is not None
              and c["cold_first_request_ms"] > 0
              and c["warm_first_request_ms"] > 0
              and c["steady_state_compiles"] == 0]
        assert len(ok) >= 2, "need >= 2 clean configs in the series"
