"""The observability loop closed: structured logs, watchdogs, alerts.

ISSUE-5 acceptance coverage, all deterministic (injected clocks, no
sleeps in assertions):

- a log record emitted inside an active span carries that span's
  ``trace_id``/``span_id`` — directly AND through the stdlib-``logging``
  bridge;
- an injected NaN loss fires the ``TrainingWatchdog`` with the
  configured action policy (log / raise / callback), the raise path
  propagates out of a REAL ``fit()``, ``EarlyStoppingTrainer`` converts
  it into an ``Error`` termination, and ``PreemptionHandler.rollback``
  restores the pre-divergence checkpoint;
- a synthetic error-ratio series crosses a multiwindow burn-rate rule →
  the alert fires, notifies a sink exactly once, then resolves;
- serving health folds dispatcher/admission/registry state into one
  report served on ``/livez``, and ``/alerts`` exposes the manager.
"""

import json
import logging
import math
import os
import sys
import urllib.error
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.observe import (disable_structured_logging,
                                        disable_tracing,
                                        enable_structured_logging,
                                        enable_tracing)
from deeplearning4j_tpu.observe import log as slog
from deeplearning4j_tpu.observe.alerts import (AbsenceRule, AlertManager,
                                               BurnRateRule, CallbackSink,
                                               Notification,
                                               RateOfChangeRule, SLOSpec,
                                               ThresholdRule, WebhookSink,
                                               load_rules, series_sum)
from deeplearning4j_tpu.observe.health import (HealthCheck, HealthReport,
                                               ServingHealth,
                                               TrainingWatchdog,
                                               WatchdogAlarm,
                                               attach_observability)
from deeplearning4j_tpu.observe.metrics import (MetricsRegistry,
                                                parse_prometheus_text)
from deeplearning4j_tpu.observe.trace import TraceRecorder, Tracer
from deeplearning4j_tpu.parallel.time_source import ManualTimeSource

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOLS = os.path.join(REPO, "tools")
if TOOLS not in sys.path:
    sys.path.insert(0, TOOLS)
from validate_alert_rules import validate_file, validate_rules  # noqa: E402


@pytest.fixture
def hub():
    h = enable_structured_logging(capacity=256)
    yield h
    disable_structured_logging()


@pytest.fixture
def tracer():
    tr = enable_tracing(Tracer(TraceRecorder(capacity=1024)), jax_hook=False)
    yield tr
    disable_tracing()


# ------------------------------------------------------------ structured log

class TestStructuredLog:
    def test_record_in_span_carries_trace_ids(self, hub, tracer):
        log = slog.get_logger("t")
        with tracer.span("op") as sp:
            rec = log.info("inside", k=1)
        assert rec.trace_id == sp.trace_id
        assert rec.span_id == sp.span_id
        out = hub.ring.records()[-1]
        assert out is rec and out.fields == {"k": 1}

    def test_record_outside_span_has_no_ids(self, hub, tracer):
        rec = slog.get_logger("t").info("outside")
        assert rec.trace_id is None
        assert "trace_id" not in rec.to_dict()

    def test_stdlib_bridge_correlates(self, hub, tracer):
        stdlog = logging.getLogger("some.existing.module")
        with tracer.span("op") as sp:
            stdlog.warning("legacy %s call", "logging")
        recs = [r for r in hub.ring.records()
                if r.logger == "some.existing.module"]
        assert len(recs) == 1
        assert recs[0].message == "legacy logging call"
        assert recs[0].trace_id == sp.trace_id
        assert recs[0].span_id == sp.span_id
        assert recs[0].level == "warning"

    def test_bridge_removed_on_disable(self, tracer):
        enable_structured_logging(capacity=16)
        first = slog.get_active_hub()
        disable_structured_logging()
        logging.getLogger("x").warning("after disable")
        assert slog.get_active_hub() is None
        assert all(r.message != "after disable"
                   for r in first.ring.records())

    def test_ring_drop_accounting(self):
        ring = slog.LogRing(capacity=4)
        for i in range(10):
            ring.add(slog.LogRecord(0.0, 20, "l", str(i), None, None,
                                    "t", {}))
        assert len(ring) == 4
        assert ring.total_recorded == 10
        assert ring.dropped == 6
        assert [r.message for r in ring.records()] == ["6", "7", "8", "9"]

    def test_json_line_strict_and_round_trips(self, hub):
        rec = slog.get_logger("j").warning(
            "nan loss", score=float("nan"), arr=np.float32(2.5),
            nested={"a": [1, float("inf")]})
        d = json.loads(rec.to_json())  # one strict-JSON line
        assert d["score"] == "nan"
        assert d["arr"] == 2.5
        assert d["nested"]["a"] == [1, "inf"]
        assert d["level"] == "warning" and d["logger"] == "j"

    def test_reserved_keys_win_over_fields(self, hub):
        rec = slog.get_logger("j").info("msg", message="spoof", level="x")
        d = rec.to_dict()
        assert d["message"] == "msg" and d["level"] == "info"

    def test_level_filtering(self):
        hub = enable_structured_logging(capacity=16, level="warning")
        try:
            log = slog.get_logger("lvl")
            assert log.debug("quiet") is None
            assert log.info("quiet") is None
            assert log.error("loud") is not None
            assert [r.message for r in hub.ring.records()] == ["loud"]
        finally:
            disable_structured_logging()

    def test_path_stream_writes_json_lines(self, tmp_path, tracer):
        p = tmp_path / "log.jsonl"
        enable_structured_logging(path=str(p))
        try:
            log = slog.get_logger("f")
            with tracer.span("op") as sp:
                log.info("one", i=1)
            log.info("two")
        finally:
            disable_structured_logging()
        lines = [json.loads(l) for l in p.read_text().splitlines()]
        assert len(lines) == 2
        assert lines[0]["trace_id"] == sp.trace_id
        assert "trace_id" not in lines[1]

    def test_noop_without_hub(self):
        assert slog.get_active_hub() is None
        assert slog.get_logger("n").info("dropped") is None

    def test_every_n_gate(self):
        gate = slog.every_n(3)
        assert [gate() for _ in range(7)] == [True, False, False, True,
                                              False, False, True]

    def test_at_most_every_gate_injected_clock(self):
        t = [0.0]
        gate = slog.at_most_every(10.0, clock=lambda: t[0])
        assert gate() is True
        assert gate() is False
        t[0] = 9.999
        assert gate() is False
        t[0] = 10.0
        assert gate() is True
        assert gate() is False


# ----------------------------------------------------------------- watchdog

class _StubModel:
    def __init__(self):
        self.score_ = 1.0
        self.params = [{"W": np.ones((2, 2), np.float32)}]
        self.listeners = []


class TestTrainingWatchdog:
    def _drive(self, wd, model, scores, start_iter=0):
        for i, s in enumerate(scores):
            model.score_ = s
            wd.iteration_done(model, start_iter + i, 0)

    def test_nan_loss_log_action_records_event(self):
        reg = MetricsRegistry()
        wd = TrainingWatchdog(action="log", metrics=reg, model_name="m")
        self._drive(wd, _StubModel(), [1.0, float("nan")])
        assert [e.check for e in wd.events] == ["nan_loss"]
        assert reg.get("watchdog_events_total").value(
            model="m", check="nan_loss") == 1

    def test_nan_loss_raise_action(self):
        wd = TrainingWatchdog(action="raise")
        with pytest.raises(WatchdogAlarm, match="nan_loss"):
            self._drive(wd, _StubModel(), [float("inf")])

    def test_nan_loss_callback_action(self):
        seen = []
        wd = TrainingWatchdog(action=seen.append)
        self._drive(wd, _StubModel(), [float("nan")])
        assert len(seen) == 1 and seen[0].check == "nan_loss"

    def test_per_check_action_override(self):
        wd = TrainingWatchdog(action="log", actions={"nan_loss": "raise"})
        with pytest.raises(WatchdogAlarm):
            self._drive(wd, _StubModel(), [float("nan")])

    def test_unknown_check_name_rejected(self):
        with pytest.raises(ValueError, match="unknown watchdog checks"):
            TrainingWatchdog(actions={"nan_losss": "raise"})

    def test_loss_divergence_after_k_windows(self):
        wd = TrainingWatchdog(divergence_windows=3)
        # dip resets the streak; then 3 consecutive rises fire
        self._drive(wd, _StubModel(), [5.0, 4.0, 4.5, 4.2, 4.4, 4.6, 4.8])
        assert [e.check for e in wd.events] == ["loss_divergence"]
        assert wd.events[0].iteration == 6

    def test_steady_loss_never_fires(self):
        wd = TrainingWatchdog(divergence_windows=2)
        self._drive(wd, _StubModel(), [3.0, 2.0, 2.0, 1.5, 1.2])
        assert wd.events == []

    def test_stall_detection_injected_clock(self):
        t = [0.0]
        wd = TrainingWatchdog(stall_factor=10.0, stall_min_history=5,
                              clock=lambda: t[0])
        model = _StubModel()
        wd.on_epoch_start(model)
        for i in range(6):  # steady 1s steps build the median baseline
            t[0] += 1.0
            wd.iteration_done(model, i, 0)
        t[0] += 30.0  # one 30s step vs median 1s
        wd.iteration_done(model, 6, 0)
        assert [e.check for e in wd.events] == ["step_stall"]
        assert wd.events[0].value == pytest.approx(30.0)

    def test_epoch_boundary_not_a_stall(self):
        t = [0.0]
        wd = TrainingWatchdog(stall_factor=2.0, stall_min_history=3,
                              clock=lambda: t[0])
        model = _StubModel()
        wd.on_epoch_start(model)
        for i in range(5):
            t[0] += 1.0
            wd.iteration_done(model, i, 0)
        wd.on_epoch_end(model)
        t[0] += 500.0  # between-epoch work (eval, checkpointing)
        wd.on_epoch_start(model)
        t[0] += 1.0
        wd.iteration_done(model, 5, 1)
        assert wd.events == []

    def test_gradient_explosion_and_vanishing_ewma(self):
        wd = TrainingWatchdog(grad_warmup=3, grad_explode_factor=10.0,
                              grad_vanish_factor=1e-3)
        for _ in range(4):
            wd.observe_gradient_norm(1.0)
        wd.observe_gradient_norm(100.0)  # 100x baseline
        assert [e.check for e in wd.events] == ["gradient_explosion"]
        # the spike did not poison the EWMA baseline
        wd.observe_gradient_norm(1e-5)
        assert [e.check for e in wd.events] == ["gradient_explosion",
                                                "gradient_vanishing"]
        wd.observe_gradient_norm(float("nan"))
        assert wd.events[-1].check == "nan_gradient"

    def test_nan_params_scan(self):
        wd = TrainingWatchdog(check_params_every=2)
        model = _StubModel()
        model.params = [{"W": np.array([[1.0, np.nan]], np.float32)}]
        model.score_ = 0.5
        wd.iteration_done(model, 1, 0)  # not a scan iteration
        assert wd.events == []
        wd.iteration_done(model, 2, 0)
        assert [e.check for e in wd.events] == ["nan_params"]

    def test_injected_nan_loss_fires_through_real_fit(self):
        """Acceptance: an injected NaN loss fires the watchdog with the
        configured action inside an actual fit loop."""
        net = _tiny_net()
        x = np.ones((8, 4), np.float32)
        x[0, 0] = np.nan  # poisons the loss on the first step
        y = np.eye(2, dtype=np.float32)[np.zeros(8, int)]
        attach_observability(net, trace=False,
                             watchdog={"action": "raise"})
        with pytest.raises(WatchdogAlarm, match="nan_loss"):
            net.fit(x, y, epochs=1)

    def test_early_stopping_converts_alarm_to_error_termination(self):
        from deeplearning4j_tpu.datasets.dataset import (DataSet,
                                                         ListDataSetIterator)
        from deeplearning4j_tpu.optimize.earlystopping import (
            DataSetLossCalculator, EarlyStoppingConfiguration,
            EarlyStoppingTrainer, MaxEpochsTerminationCondition)
        net = _tiny_net()
        x = np.ones((8, 4), np.float32)
        x[0, 0] = np.nan
        y = np.eye(2, dtype=np.float32)[np.zeros(8, int)]
        it = ListDataSetIterator(DataSet(x, y), 4)
        attach_observability(net, trace=False,
                             watchdog={"action": "raise"})
        cfg = EarlyStoppingConfiguration(
            score_calculator=DataSetLossCalculator(it),
            epoch_termination_conditions=[MaxEpochsTerminationCondition(5)])
        result = EarlyStoppingTrainer(cfg, net, it).fit()
        assert result.termination_reason == "Error"
        assert "nan_loss" in result.termination_details

    def test_rollback_restores_pre_divergence_checkpoint(self, tmp_path):
        from deeplearning4j_tpu.util.preemption import PreemptionHandler
        net = _tiny_net()
        x = np.ones((8, 4), np.float32)
        y = np.eye(2, dtype=np.float32)[np.zeros(8, int)]
        handler = PreemptionHandler(net, str(tmp_path / "good.zip"))
        handler.save()
        x[0, 0] = np.nan
        attach_observability(net, trace=False,
                             watchdog={"action": "raise"})
        with pytest.raises(WatchdogAlarm):
            net.fit(x, y, epochs=1)
        restored, state = handler.rollback()
        for group in restored.params:
            for name, arr in group.items():
                assert np.all(np.isfinite(np.asarray(arr))), name

    def test_attach_observability_single_path(self, tracer):
        from deeplearning4j_tpu.observe.listener import TraceListener
        net = _tiny_net()
        attached = attach_observability(net, tracer=tracer,
                                        metrics=MetricsRegistry(),
                                        watchdog=True)
        assert [type(l).__name__ for l in attached] == \
            ["TraceListener", "TrainingWatchdog"]
        assert all(l in net.listeners for l in attached)

    def test_watchdog_logs_structured_with_trace_ids(self, hub, tracer):
        wd = TrainingWatchdog(action="log")
        model = _StubModel()
        with tracer.span("train") as sp:
            model.score_ = float("nan")
            wd.iteration_done(model, 3, 1)
        recs = [r for r in hub.ring.records()
                if r.fields.get("check") == "nan_loss"]
        assert len(recs) == 1
        assert recs[0].trace_id == sp.trace_id
        assert recs[0].fields["iteration"] == 3


def _tiny_net(seed=7):
    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.nn.updaters import Sgd
    conf = (NeuralNetConfiguration.builder().seed(seed).updater(Sgd(0.1))
            .list()
            .layer(DenseLayer(n_in=4, n_out=8, activation="tanh"))
            .layer(OutputLayer(n_in=8, n_out=2, activation="softmax",
                               loss="mcxent"))
            .build())
    return MultiLayerNetwork(conf).init()


# ------------------------------------------------------------ serving health

class TestServingHealth:
    def test_report_status_lattice(self):
        ok = HealthReport([HealthCheck("a", True)])
        degraded = HealthReport([HealthCheck("a", True),
                                 HealthCheck("b", False)])
        down = HealthReport([HealthCheck("a", False, critical=True)])
        assert (ok.status, degraded.status, down.status) == \
            ("ok", "degraded", "down")
        assert down.healthy is False and degraded.healthy is True
        assert degraded.to_dict()["checks"][1]["healthy"] is False

    def test_admission_saturation_and_drain(self):
        from deeplearning4j_tpu.serving.admission import AdmissionController
        adm = AdmissionController(2)
        health = ServingHealth(admission=adm)
        assert health.report().status == "ok"
        s1, s2 = adm.admit(), adm.admit()
        rep = health.report()
        assert rep.status == "degraded"
        assert any(c.name == "admission_saturation" and not c.healthy
                   for c in rep.checks)
        s1.release(), s2.release()
        adm.begin_drain()
        assert any(c.name == "admission_drain"
                   for c in health.report().checks)

    def test_registry_dispatcher_death_is_down(self):
        from deeplearning4j_tpu.serving.registry import ModelRegistry

        class M:
            def output(self, x):
                return np.asarray(x)

        registry = ModelRegistry()
        health = ServingHealth(registry=registry)
        assert health.report().status == "degraded"  # no models yet
        registry.register("m", model=M())
        assert health.report().status == "ok"
        registry.get("m").inference.shutdown()
        rep = health.report()
        assert rep.status == "down"
        assert any(c.name == "dispatcher:m" and c.critical
                   and not c.healthy for c in rep.checks)

    def test_extra_probe_plugs_in(self):
        health = ServingHealth(extra_probes=[
            lambda: HealthCheck("custom", False, "broken")])
        rep = health.report()
        assert rep.status == "degraded"
        assert rep.checks[-1].detail == "broken"


class TestServerEndpoints:
    @pytest.fixture
    def served(self):
        from deeplearning4j_tpu.serving import ModelRegistry, ModelServer

        class M:
            def output(self, x):
                return np.asarray(x).sum(axis=-1, keepdims=True)

        metrics = MetricsRegistry()
        registry = ModelRegistry(metrics=metrics)
        registry.register("m", model=M())
        # an absence rule on a metric nothing exports fires on the first
        # evaluation — a deterministic "firing" state for the endpoint
        rules = [AbsenceRule("always", "never_exported_total",
                             severity="info")]
        mgr = AlertManager(metrics, rules, sinks=[],
                           time_source=ManualTimeSource(0))
        server = ModelServer(registry, metrics=metrics, alerts=mgr)
        server.start()
        try:
            yield server, mgr
        finally:
            server.stop(drain=False, shutdown_registry=True)

    def _get(self, url):
        try:
            with urllib.request.urlopen(url, timeout=5) as r:
                return r.status, json.load(r)
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read().decode())

    def test_livez_ok_and_verbose(self, served):
        server, _ = served
        code, body = self._get(f"{server.url}/livez")
        assert code == 200 and body == {"status": "ok"}
        code, body = self._get(f"{server.url}/livez?verbose=1")
        assert code == 200 and body["status"] == "ok"
        names = [c["name"] for c in body["checks"]]
        assert "dispatcher:m" in names and "admission_saturation" in names

    def test_livez_503_on_dead_dispatcher(self, served):
        server, _ = served
        server.registry.get("m").inference.shutdown()
        code, body = self._get(f"{server.url}/livez?verbose=1")
        assert code == 503 and body["status"] == "down"

    def test_alerts_endpoint_serves_manager_state(self, served):
        server, mgr = served
        mgr.evaluate_once(now=1.0)
        code, body = self._get(f"{server.url}/alerts")
        assert code == 200
        assert body["firing"] == ["always"]
        assert body["rules"][0]["state"] == "firing"
        assert body["evaluations"] == 1

    def test_alerts_404_without_manager(self):
        from deeplearning4j_tpu.serving import ModelRegistry, ModelServer
        server = ModelServer(ModelRegistry(), metrics=MetricsRegistry())
        server.start()
        try:
            code, _ = self._get(f"{server.url}/alerts")
            assert code == 404
        finally:
            server.stop(drain=False)


# ------------------------------------------------------- exposition contract

class TestExpositionFormat:
    """The alert engine reads metrics THROUGH the Prometheus text
    exposition (`parse_prometheus_text(registry.exposition())`), so the
    round trip through escaping and the `+Inf` conventions IS the
    contract between the metrics core and the rules."""

    def test_escaped_label_values_round_trip(self):
        reg = MetricsRegistry()
        c = reg.counter("esc_total", "escape torture", ("path",))
        nasty = ['back\\slash', 'quo"te', 'new\nline', 'trail\\',
                 '\\n-literal', 'mix\\"\nall', '{brace}', 'a=b,c', '']
        for i, v in enumerate(nasty):
            c.inc(i + 1, path=v)
        series = parse_prometheus_text(reg.exposition())["esc_total"]
        assert len(series) == len(nasty)
        for i, v in enumerate(nasty):
            assert series[(("path", v),)] == i + 1

    def test_escaped_newline_keeps_one_line_per_series(self):
        reg = MetricsRegistry()
        reg.counter("nl_total", "", ("k",)).inc(k="a\nb")
        lines = [l for l in reg.exposition().splitlines()
                 if l.startswith("nl_total{")]
        assert len(lines) == 1
        assert '\\n' in lines[0] and "\n" not in lines[0]

    def test_histogram_inf_bucket_synthesized_and_parsed(self):
        reg = MetricsRegistry()
        h = reg.histogram("lat", "", buckets=(0.1, 1.0))  # no +Inf given
        h.observe(0.05)
        h.observe(5.0)  # beyond every finite bound
        parsed = parse_prometheus_text(reg.exposition())
        assert parsed["lat_bucket"][(("le", "0.1"),)] == 1
        assert parsed["lat_bucket"][(("le", "1"),)] == 1
        assert parsed["lat_bucket"][(("le", "+Inf"),)] == 2
        assert parsed["lat_count"][()] == 2

    def test_histogram_explicit_inf_bucket_not_duplicated(self):
        reg = MetricsRegistry()
        h = reg.histogram("lat2", "", buckets=(0.5, math.inf))
        assert h.buckets == (0.5, math.inf)
        h.observe(0.2)
        inf_lines = [l for l in reg.exposition().splitlines()
                     if 'le="+Inf"' in l]
        assert len(inf_lines) == 1

    def test_histogram_boundary_lands_in_finite_bucket(self):
        reg = MetricsRegistry()
        h = reg.histogram("lat3", "", buckets=(1.0, 2.0))
        h.observe(1.0)  # le is INCLUSIVE (the Prometheus convention)
        parsed = parse_prometheus_text(reg.exposition())
        assert parsed["lat3_bucket"][(("le", "1"),)] == 1
        assert parsed["lat3_bucket"][(("le", "+Inf"),)] == 1

    def test_histogram_inf_observation_round_trips(self):
        reg = MetricsRegistry()
        h = reg.histogram("h_inf", "", buckets=(1.0,))
        h.observe(math.inf)
        parsed = parse_prometheus_text(reg.exposition())
        assert parsed["h_inf_bucket"][(("le", "1"),)] == 0
        assert parsed["h_inf_bucket"][(("le", "+Inf"),)] == 1
        assert parsed["h_inf_sum"][()] == math.inf  # "+Inf" in the text
        assert parsed["h_inf_count"][()] == 1
        h.observe(-math.inf)  # -Inf lands in the lowest bucket, sum -> nan
        parsed = parse_prometheus_text(reg.exposition())
        assert parsed["h_inf_bucket"][(("le", "1"),)] == 1
        assert parsed["h_inf_count"][()] == 2

    def test_alert_rule_matches_escaped_series(self):
        # the satellite's point: a rule selecting on a label value that
        # needs escaping must still see the series after the round trip
        path = 'v1/models/we"ird\\name\n'
        reg = MetricsRegistry()
        reg.counter("esc_req_total", "", ("path",)).inc(9, path=path)
        clock = ManualTimeSource(0)
        seen = []
        mgr = AlertManager(
            reg, [ThresholdRule("esc", "esc_req_total", ">", 5,
                                labels={"path": path})],
            [CallbackSink(seen.append)], time_source=clock)
        mgr.evaluate_once()
        assert [n.state for n in seen] == ["firing"]


# -------------------------------------------------------------------- alerts

class TestAlertRules:
    def test_series_sum_subset_match(self):
        reg = MetricsRegistry()
        c = reg.counter("req", "", ("server", "status"))
        c.inc(3, server="a", status="200")
        c.inc(2, server="a", status="500")
        c.inc(7, server="b", status="200")
        from deeplearning4j_tpu.observe.metrics import parse_prometheus_text
        sample = parse_prometheus_text(reg.exposition())
        assert series_sum(sample, "req") == 12
        assert series_sum(sample, "req", {"server": "a"}) == 5
        assert series_sum(sample, "req", {"status": "500"}) == 2
        assert series_sum(sample, "req", {"status": "404"}) is None
        assert series_sum(sample, "missing") is None

    def _mgr(self, rules, reg=None, **kw):
        reg = reg if reg is not None else MetricsRegistry()
        seen = []
        clock = ManualTimeSource(0)
        mgr = AlertManager(reg, rules, [CallbackSink(seen.append)],
                           time_source=clock, **kw)
        return mgr, reg, seen, clock

    def test_threshold_fire_dedup_resolve(self):
        mgr, reg, seen, clock = self._mgr(
            [ThresholdRule("hot", "depth", ">", 5)])
        g = reg.gauge("depth", "")
        g.set(3)
        mgr.evaluate_once()
        assert seen == [] and mgr.firing() == []
        g.set(9)
        clock.advance(seconds=10)
        mgr.evaluate_once()
        assert [n.state for n in seen] == ["firing"]
        assert mgr.firing() == ["hot"]
        clock.advance(seconds=10)
        mgr.evaluate_once()  # still hot: deduped, no second notification
        assert [n.state for n in seen] == ["firing"]
        g.set(1)
        clock.advance(seconds=10)
        mgr.evaluate_once()
        assert [n.state for n in seen] == ["firing", "resolved"]
        assert mgr.firing() == []
        assert reg.get("alerts_firing").value(rule="hot") == 0

    def test_threshold_for_s_pending(self):
        mgr, reg, seen, clock = self._mgr(
            [ThresholdRule("slow", "depth", ">", 5, for_s=30)])
        g = reg.gauge("depth", "")
        g.set(9)
        mgr.evaluate_once()
        assert seen == []  # pending, not firing
        clock.advance(seconds=10)
        mgr.evaluate_once()
        assert seen == []
        clock.advance(seconds=25)  # 35s > for_s
        mgr.evaluate_once()
        assert [n.state for n in seen] == ["firing"]
        # a dip mid-pending resets the timer
        mgr2, reg2, seen2, clock2 = self._mgr(
            [ThresholdRule("slow", "depth", ">", 5, for_s=30)])
        g2 = reg2.gauge("depth", "")
        g2.set(9)
        mgr2.evaluate_once()
        g2.set(1)
        clock2.advance(seconds=10)
        mgr2.evaluate_once()
        g2.set(9)
        clock2.advance(seconds=25)
        mgr2.evaluate_once()  # only 0s into the NEW pending window
        assert seen2 == []

    def test_absence_rule(self):
        mgr, reg, seen, clock = self._mgr(
            [AbsenceRule("gone", "heartbeat_total")])
        mgr.evaluate_once()
        assert [n.state for n in seen] == ["firing"]
        reg.counter("heartbeat_total", "").inc()
        clock.advance(seconds=5)
        mgr.evaluate_once()
        assert [n.state for n in seen] == ["firing", "resolved"]

    def test_rate_of_change_rule(self):
        mgr, reg, seen, clock = self._mgr(
            [RateOfChangeRule("surge", "req_total", ">", 10.0, 60.0)])
        c = reg.counter("req_total", "")
        mgr.evaluate_once()          # t=0, v=0 — no window yet
        c.inc(100)
        clock.advance(seconds=30)
        mgr.evaluate_once()          # history spans only 30s < 60s
        assert seen == []
        c.inc(2000)
        clock.advance(seconds=40)    # t=70; sample at t=0 is in window
        mgr.evaluate_once()          # rate = 2100/70 = 30/s > 10
        assert [n.state for n in seen] == ["firing"]
        clock.advance(seconds=3600)  # no new increments → rate 0
        mgr.evaluate_once()
        assert [n.state for n in seen] == ["firing", "resolved"]

    def test_burn_rate_multiwindow_fire_once_resolve(self):
        """Acceptance: synthetic error-ratio series crosses a multiwindow
        burn-rate rule → fires, notifies exactly once, then resolves."""
        slo = SLOSpec("http_requests_total", {"status": "500"},
                      objective=0.99)
        rule = BurnRateRule("burn", slo,
                            [(3600.0, 300.0, 14.4)], severity="page")
        mgr, reg, seen, clock = self._mgr([rule])
        c = reg.counter("http_requests_total", "", ("status",))
        c.inc(1000, status="200")
        mgr.evaluate_once()          # baseline at t=0
        # 50% errors over the next minute: burn = 0.5/0.01 = 50x >= 14.4x
        c.inc(100, status="200")
        c.inc(100, status="500")
        clock.advance(seconds=60)
        fired = mgr.evaluate_once()
        assert [n.state for n in fired] == ["firing"]
        assert fired[0].severity == "page"
        assert fired[0].value >= 14.4
        # still elevated long-window, but the SHORT window goes clean:
        # healthy traffic only, clock past the short window
        c.inc(500, status="200")
        clock.advance(seconds=301)
        resolved = mgr.evaluate_once()
        assert [n.state for n in resolved] == ["resolved"]
        assert [n.state for n in seen] == ["firing", "resolved"]

    def test_burn_rate_ignores_quiet_total(self):
        slo = SLOSpec("req_total", {"status": "500"}, objective=0.9)
        mgr, reg, seen, clock = self._mgr(
            [BurnRateRule("b", slo, [(600.0, 60.0, 2.0)])])
        mgr.evaluate_once()  # metric absent, zero traffic: burn 0, no fire
        clock.advance(seconds=120)
        mgr.evaluate_once()
        assert seen == [] and mgr.firing() == []

    def test_slo_spec_validation(self):
        with pytest.raises(ValueError, match="objective"):
            SLOSpec("m", {"status": "500"}, objective=1.0)
        with pytest.raises(ValueError, match="error_labels"):
            SLOSpec("m", {})
        with pytest.raises(ValueError, match="short window"):
            BurnRateRule("b", SLOSpec("m", {"s": "1"}),
                         [(60.0, 600.0, 2.0)])

    def test_duplicate_rule_names_rejected(self):
        rules = [ThresholdRule("x", "m", ">", 1),
                 AbsenceRule("x", "m")]
        with pytest.raises(ValueError, match="duplicate"):
            AlertManager(MetricsRegistry(), rules)

    def test_bad_rule_contained_others_still_evaluate(self):
        class Broken(ThresholdRule):
            def evaluate(self, history, now):
                raise RuntimeError("boom")

        mgr, reg, seen, clock = self._mgr(
            [Broken("bad", "m", ">", 1), AbsenceRule("gone", "nope")])
        mgr.evaluate_once()
        assert [n.rule for n in seen] == ["gone"]

    def test_background_evaluator_start_stop(self):
        import time as _time
        mgr, reg, seen, clock = self._mgr(
            [AbsenceRule("gone", "nope")], interval_s=0.01)
        mgr.start()
        deadline = _time.time() + 5.0
        while mgr.evaluations < 2 and _time.time() < deadline:
            _time.sleep(0.01)
        mgr.stop()
        assert mgr.evaluations >= 2
        assert mgr.firing() == ["gone"]


class TestSinks:
    def _note(self):
        return Notification("r", "warning", "firing", 1.0, "d", 0.0)

    def test_webhook_retries_with_backoff_then_delivers(self):
        calls, sleeps = [], []

        def post(url, body):
            calls.append(json.loads(body))
            return 503 if len(calls) < 3 else 200

        sink = WebhookSink("http://x/hook", retries=3, backoff_s=0.5,
                           post=post, sleep=sleeps.append)
        sink.notify(self._note())
        assert len(calls) == 3 and sink.delivered == 1 and sink.failed == 0
        assert sleeps == [0.5, 1.0]  # exponential backoff
        assert calls[0]["rule"] == "r" and calls[0]["state"] == "firing"

    def test_webhook_gives_up_after_retries(self):
        def post(url, body):
            raise OSError("connection refused")

        sink = WebhookSink("http://x/hook", retries=2, backoff_s=0.1,
                           post=post, sleep=lambda s: None)
        sink.notify(self._note())  # never raises into the evaluator
        assert sink.failed == 1 and sink.delivered == 0
        assert "connection refused" in sink.last_error

    def test_failing_sink_contained_by_manager(self):
        class Bomb:
            def notify(self, n):
                raise RuntimeError("sink down")

        seen = []
        reg = MetricsRegistry()
        mgr = AlertManager(reg, [AbsenceRule("gone", "nope")],
                           [Bomb(), CallbackSink(seen.append)],
                           time_source=ManualTimeSource(0))
        mgr.evaluate_once()
        assert [n.rule for n in seen] == ["gone"]
        assert reg.get("alert_notifications_total").value(
            rule="gone", state="firing") == 1


# ------------------------------------------------------------- rule loading

class TestRuleLoading:
    GOOD = {"rules": [
        {"type": "threshold", "name": "t", "metric": "m", "op": ">",
         "value": 5, "labels": {"server": "a"}, "for_s": 10,
         "severity": "critical"},
        {"type": "absence", "name": "a", "metric": "m2"},
        {"type": "rate_of_change", "name": "r", "metric": "m3",
         "op": ">=", "value": 1.5, "window_s": 60},
        {"type": "burn_rate", "name": "b",
         "slo": {"metric": "req", "error_labels": {"status": "500"},
                 "objective": 0.999},
         "windows": [{"long_s": 3600, "short_s": 300, "factor": 14.4},
                     {"long_s": 21600, "short_s": 1800, "factor": 6.0}]},
    ]}

    def test_load_all_types(self):
        rules = load_rules(self.GOOD)
        assert [type(r).__name__ for r in rules] == \
            ["ThresholdRule", "AbsenceRule", "RateOfChangeRule",
             "BurnRateRule"]
        assert rules[0].for_s == 10 and rules[0].severity == "critical"
        assert rules[3].slo.objective == 0.999
        assert len(rules[3].windows) == 2

    def test_load_from_file_and_json_string(self, tmp_path):
        p = tmp_path / "rules.json"
        p.write_text(json.dumps(self.GOOD))
        assert len(load_rules(str(p))) == 4
        assert len(load_rules(json.dumps(self.GOOD))) == 4

    def test_schema_errors_carry_rule_index(self):
        with pytest.raises(ValueError, match=r"rules\[0\].*unknown type"):
            load_rules({"rules": [{"type": "nope", "name": "x"}]})
        with pytest.raises(ValueError, match=r"rules\[0\].*missing field"):
            load_rules({"rules": [{"type": "threshold", "name": "x"}]})
        with pytest.raises(ValueError, match="duplicate"):
            load_rules({"rules": [
                {"type": "absence", "name": "x", "metric": "m"},
                {"type": "absence", "name": "x", "metric": "m2"}]})
        with pytest.raises(ValueError, match="rules"):
            load_rules({"not_rules": []})

    def test_validator_tool_ok_and_fail(self, tmp_path):
        good = tmp_path / "good.json"
        good.write_text(json.dumps(self.GOOD))
        assert validate_file(str(good)) == []
        bad = tmp_path / "bad.json"
        bad.write_text(json.dumps(
            {"rules": [{"type": "threshold", "name": "x", "metric": "m",
                        "op": "~", "value": 1}]}))
        errs = validate_file(str(bad))
        assert errs and "unknown op" in errs[0]
        unreadable = validate_file(str(tmp_path / "missing.json"))
        assert unreadable and "unreadable" in unreadable[0]
        assert validate_rules({"rules": []}) == ["schema: no rules defined"]

    def test_shipped_example_rules_validate(self):
        """The smoke-tier lint: the example's shipped rules file must pass
        the validator (schema + dry-run) forever."""
        rules_path = os.path.join(REPO, "examples", "alert_rules.json")
        assert validate_file(rules_path) == []
