"""DL4J configuration import tests.

Fixtures are hand-written in the reference's MultiLayerConfiguration JSON
dialect (WRAPPER_OBJECT layer entries per ``nn/conf/layers/Layer.java:54``
subtype names; ``@class`` activation/loss/updater wrappers of the 0.9-1.0
era) so migration works without any Java in the loop.
"""

import json
import zipfile

import numpy as np
import pytest

from deeplearning4j_tpu.modelimport.dl4j import (
    InvalidDl4jConfigurationException,
    UnsupportedDl4jConfigurationException,
    import_dl4j_configuration,
    import_dl4j_zip,
    restore_multi_layer_network_configuration,
)
from deeplearning4j_tpu.nn.layers import (
    BatchNormalizationLayer,
    ConvolutionLayer,
    DenseLayer,
    GravesLSTMLayer,
    OutputLayer,
    RnnOutputLayer,
    SubsamplingLayer,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.updaters import Adam, Nesterovs


def mlp_json():
    return json.dumps({
        "backprop": True,
        "backpropType": "Standard",
        "confs": [
            {"seed": 42, "layer": {"dense": {
                "layerName": "h0",
                "activationFn": {"@class": "org.nd4j.linalg.activations.impl.ActivationReLU"},
                "nin": 4, "nout": 16, "l2": 1e-4,
                "weightInit": "XAVIER",
                "iUpdater": {"@class": "org.nd4j.linalg.learning.config.Adam",
                             "learningRate": 0.01, "beta1": 0.9, "beta2": 0.999},
            }}},
            {"layer": {"output": {
                "layerName": "out",
                "activationFn": {"@class": "org.nd4j.linalg.activations.impl.ActivationSoftmax"},
                "lossFn": {"@class": "org.nd4j.linalg.lossfunctions.impl.LossMCXENT"},
                "nin": 16, "nout": 3,
            }}},
        ],
    })


class TestMlpImport:
    def test_structure_and_fields(self):
        conf = import_dl4j_configuration(mlp_json())
        layers = conf.layers
        assert isinstance(layers[0], DenseLayer)
        assert layers[0].n_in == 4 and layers[0].n_out == 16
        assert layers[0].activation == "relu"
        assert layers[0].l2 == pytest.approx(1e-4)
        assert isinstance(layers[0].updater, Adam)
        assert layers[0].updater.learning_rate == pytest.approx(0.01)
        assert isinstance(layers[1], OutputLayer)
        assert layers[1].loss == "mcxent"
        assert layers[1].activation == "softmax"

    def test_imported_config_trains(self):
        conf = import_dl4j_configuration(mlp_json())
        net = MultiLayerNetwork(conf).init()
        rng = np.random.default_rng(0)
        cls = rng.integers(0, 3, 128)
        x = rng.normal(size=(128, 4)).astype(np.float32)
        x[np.arange(128), cls] += 2.0
        y = np.eye(3, dtype=np.float32)[cls]
        from deeplearning4j_tpu.datasets.dataset import DataSet
        s0 = net.score(DataSet(x, y))
        net.fit(x, y, epochs=30)
        assert net.score_ < s0


class TestCnnAndRnnImport:
    def test_lenet_style(self):
        conf = import_dl4j_configuration(json.dumps({
            "confs": [
                {"layer": {"convolution": {
                    "activationFn": {"Identity": {}},
                    "kernelSize": [5, 5], "stride": [1, 1], "padding": [0, 0],
                    "convolutionMode": "Truncate", "nin": 1, "nout": 20,
                }}},
                {"layer": {"batchNormalization": {"eps": 1e-5, "decay": 0.9}}},
                {"layer": {"subsampling": {
                    "poolingType": "MAX", "kernelSize": [2, 2],
                    "stride": [2, 2], "convolutionMode": "Truncate",
                }}},
                {"layer": {"output": {
                    "lossFn": {"@class": "org.nd4j.linalg.lossfunctions.impl.LossNegativeLogLikelihood"},
                    "activationFn": {"@class": "org.nd4j.linalg.activations.impl.ActivationSoftmax"},
                    "nout": 10,
                }}},
            ],
        }))
        l0, l1, l2, l3 = conf.layers
        assert isinstance(l0, ConvolutionLayer) and l0.kernel_size == (5, 5)
        assert isinstance(l1, BatchNormalizationLayer)
        assert isinstance(l2, SubsamplingLayer) and l2.pooling_type == "max"
        assert l3.loss == "mcxent"  # NLL maps to mcxent

    def test_graves_char_rnn_with_tbptt(self):
        conf = import_dl4j_configuration(json.dumps({
            "backpropType": "TruncatedBPTT",
            "tbpttFwdLength": 50, "tbpttBackLength": 50,
            "confs": [
                {"layer": {"gravesLSTM": {
                    "activationFn": {"@class": "org.nd4j.linalg.activations.impl.ActivationTanH"},
                    "nin": 80, "nout": 256, "forgetGateBiasInit": 1.0,
                    "iUpdater": {"@class": "org.nd4j.linalg.learning.config.Nesterovs",
                                 "learningRate": 0.1, "momentum": 0.95},
                }}},
                {"layer": {"rnnoutput": {
                    "lossFn": {"@class": "org.nd4j.linalg.lossfunctions.impl.LossMCXENT"},
                    "activationFn": {"@class": "org.nd4j.linalg.activations.impl.ActivationSoftmax"},
                    "nin": 256, "nout": 80,
                }}},
            ],
        }))
        l0, l1 = conf.layers
        assert isinstance(l0, GravesLSTMLayer) and l0.n_out == 256
        assert isinstance(l0.updater, Nesterovs)
        assert l0.updater.momentum == pytest.approx(0.95)
        assert isinstance(l1, RnnOutputLayer)
        assert conf.tbptt_fwd_length == 50


class TestZipImport:
    def test_model_serializer_zip(self, tmp_path):
        p = str(tmp_path / "dl4j_model.zip")
        with zipfile.ZipFile(p, "w") as z:
            z.writestr("configuration.json", mlp_json())
            z.writestr("coefficients.bin", b"\x00" * 16)  # external ND4J blob
            z.writestr("updaterState.bin", b"\x00" * 8)
        conf, meta = import_dl4j_zip(p)
        assert meta["has_coefficients"] and meta["has_updater_state"]
        assert not meta["has_normalizer"]
        net = restore_multi_layer_network_configuration(p).init()
        out = net.output(np.zeros((2, 4), np.float32))
        assert np.asarray(out).shape == (2, 3)

    def test_bad_zip_rejected(self, tmp_path):
        p = str(tmp_path / "bad.zip")
        with zipfile.ZipFile(p, "w") as z:
            z.writestr("readme.txt", "nope")
        with pytest.raises(InvalidDl4jConfigurationException):
            import_dl4j_zip(p)


class TestErrors:
    def test_unknown_layer_type(self):
        with pytest.raises(UnsupportedDl4jConfigurationException):
            import_dl4j_configuration(json.dumps(
                {"confs": [{"layer": {"quantumLayer": {}}}]}))

    def test_not_multilayer_json(self):
        with pytest.raises(InvalidDl4jConfigurationException):
            import_dl4j_configuration(json.dumps({"vertices": {}}))


class TestGraphImport:
    def graph_json(self):
        dense = lambda nin, nout, act, name: {"dense": {
            "layerName": name, "nin": nin, "nout": nout,
            "activationFn": {"@class": f"org.nd4j.linalg.activations.impl.Activation{act}"}}}
        return json.dumps({
            "networkInputs": ["in"],
            "networkOutputs": ["out"],
            "vertices": {
                "a": {"LayerVertex": {"layerConf": {"layer": dense(6, 8, "ReLU", "a")}}},
                "b": {"LayerVertex": {"layerConf": {"layer": dense(6, 8, "TanH", "b")}}},
                "ew": {"ElementWiseVertex": {"op": "Add"}},
                "scaled": {"ScaleVertex": {"scaleFactor": 0.5}},
                "out": {"LayerVertex": {"layerConf": {"layer": {"output": {
                    "layerName": "out", "nin": 8, "nout": 2,
                    "activationFn": {"@class": "org.nd4j.linalg.activations.impl.ActivationSoftmax"},
                    "lossFn": {"@class": "org.nd4j.linalg.lossfunctions.impl.LossMCXENT"},
                }}}}},
            },
            "vertexInputs": {
                "a": ["in"], "b": ["in"], "ew": ["a", "b"],
                "scaled": ["ew"], "out": ["scaled"],
            },
        })

    def test_graph_import_runs(self):
        from deeplearning4j_tpu.modelimport.dl4j import import_dl4j_graph_configuration
        from deeplearning4j_tpu.nn.graph import ComputationGraph

        conf = import_dl4j_graph_configuration(self.graph_json())
        net = ComputationGraph(conf).init()
        x = np.random.default_rng(0).normal(size=(4, 6)).astype(np.float32)
        out = np.asarray(net.output(x))
        assert out.shape == (4, 2)
        np.testing.assert_allclose(out.sum(-1), 1.0, rtol=1e-5)

    def test_graph_zip_dispatch(self, tmp_path):
        p = str(tmp_path / "graph.zip")
        with zipfile.ZipFile(p, "w") as z:
            z.writestr("configuration.json", self.graph_json())
            z.writestr("coefficients.bin", b"\x00")
        conf, meta = import_dl4j_zip(p)
        from deeplearning4j_tpu.nn.conf.graph_conf import ComputationGraphConfiguration
        assert isinstance(conf, ComputationGraphConfiguration)
        assert meta["has_coefficients"]

    def test_unknown_vertex_rejected(self):
        from deeplearning4j_tpu.modelimport.dl4j import import_dl4j_graph_configuration
        with pytest.raises(UnsupportedDl4jConfigurationException):
            import_dl4j_graph_configuration(json.dumps({
                "networkInputs": ["in"], "networkOutputs": ["x"],
                "vertices": {"x": {"WarpVertex": {}}},
                "vertexInputs": {"x": ["in"]}}))


class TestConstraintImport:
    """Serialized per-layer ``constraints`` (BaseConstraint.java Jackson
    entries) must import as real projection chains, not silently drop."""

    def _conf(self, entries):
        return import_dl4j_configuration(json.dumps({"confs": [
            {"layer": {"dense": {
                "nin": 4, "nout": 8, "activationFn": "relu",
                "constraints": entries}}},
            {"layer": {"output": {"nin": 8, "nout": 2,
                                  "activationFn": "softmax"}}},
        ]}))

    def test_all_four_classes_map(self):
        from deeplearning4j_tpu.nn.constraints import (
            MaxNormConstraint, MinMaxNormConstraint, NonNegativeConstraint,
            UnitNormConstraint)
        pre = "org.deeplearning4j.nn.conf.constraint."
        conf = self._conf([
            {"@class": pre + "MaxNormConstraint", "maxNorm": 2.5,
             "params": ["W"], "epsilon": 1e-6, "dimensions": [1]},
            {"@class": pre + "MinMaxNormConstraint", "min": 0.1, "max": 3.0,
             "rate": 0.5, "params": ["W"], "dimensions": [1]},
            {"@class": pre + "UnitNormConstraint", "params": ["W"],
             "dimensions": [1]},
            {"@class": pre + "NonNegativeConstraint", "params": ["b"]},
        ])
        cs = conf.layers[0].constraints
        assert isinstance(cs[0], MaxNormConstraint)
        assert cs[0].max_norm == pytest.approx(2.5)
        assert cs[0].param_names == ("W",)
        assert isinstance(cs[1], MinMaxNormConstraint)
        assert cs[1].min_norm == pytest.approx(0.1)
        assert cs[1].rate == pytest.approx(0.5)
        assert isinstance(cs[2], UnitNormConstraint)
        assert isinstance(cs[3], NonNegativeConstraint)
        assert cs[3].param_names == ("b",)

    def test_constrained_import_trains_and_projects(self):
        import jax.numpy as jnp
        pre = "org.deeplearning4j.nn.conf.constraint."
        conf = self._conf([{"@class": pre + "MaxNormConstraint",
                            "maxNorm": 0.5, "params": ["W"],
                            "dimensions": [1]}])
        net = MultiLayerNetwork(conf).init()
        rng = np.random.default_rng(0)
        x = rng.normal(size=(32, 4)).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 32)]
        net.fit(x, y, epochs=3)
        norms = jnp.linalg.norm(net.params[0]["W"], axis=tuple(
            range(net.params[0]["W"].ndim - 1)))
        assert float(norms.max()) <= 0.5 + 1e-4

    def test_unknown_constraint_warns(self):
        import warnings
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            self._conf([{"@class": "com.example.WeirdConstraint"}])
        assert any("WeirdConstraint" in str(x.message) for x in w)

    def test_noncanonical_dimensions_warn(self):
        import warnings
        pre = "org.deeplearning4j.nn.conf.constraint."
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            self._conf([{"@class": pre + "MaxNormConstraint", "maxNorm": 1.0,
                         "params": ["W"], "dimensions": [0]}])
        assert any("non-canonical" in str(x.message) for x in w)

    def test_conv_canonical_dims_are_123(self):
        import warnings
        pre = "org.deeplearning4j.nn.conf.constraint."
        conv_conf = lambda dims: json.dumps({"confs": [
            {"layer": {"convolution": {
                "nin": 1, "nout": 4, "kernelSize": [3, 3],
                "stride": [1, 1], "activationFn": "relu",
                "constraints": [{"@class": pre + "MaxNormConstraint",
                                 "maxNorm": 1.0, "params": ["W"],
                                 "dimensions": dims}]}}},
            {"layer": {"output": {"nout": 2, "activationFn": "softmax"}}},
        ]})
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            import_dl4j_configuration(conv_conf([1, 2, 3]))
        assert not any("non-canonical" in str(x.message) for x in w)
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            import_dl4j_configuration(conv_conf([1]))  # dense-style dims on conv
        assert any("non-canonical" in str(x.message) for x in w)
