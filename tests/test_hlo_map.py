"""tools/hlo_map.py — the HLO-text analysis behind the perf roofline.

The parser is load-bearing for the recorded perf evidence (ROOFLINE_r03),
so its subtle parts are locked here: TPU layout-annotated type tokens,
computation-local operand namespaces (param_N collides globally), balanced
operand-list scanning, valid-tap conv FLOP counting (XLA's canonicalized
backward convs bury a 1x1's work under a 55x55 window of padding), and
metadata/structure-based classification.
"""

import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools"))

from hlo_map import HloModule, shape_of  # noqa: E402


MINI_HLO = """
HloModule step, entry_computation_layout={()->()}

%fused_computation.1 (param_0.1: bf16[8,6,6,4], param_1.2: bf16[3,3,4,16]) -> bf16[8,6,6,16] {
  %param_0.1 = bf16[8,6,6,4]{3,2,1,0:T(8,128)(2,1)} parameter(0)
  %param_1.2 = bf16[3,3,4,16]{3,2,1,0:T(8,128)(2,1)} parameter(1)
  ROOT %conv.1 = bf16[8,6,6,16]{3,2,1,0:T(8,128)(2,1)} convolution(%param_0.1, %param_1.2), window={size=3x3 pad=1_1x1_1}, dim_labels=b01f_01io->b01f, metadata={op_name="jit(step)/jvp()/conv_general_dilated"}
}

%fused_computation.2 (param_0.3: bf16[16], param_1.4: bf16[8,6,6,16]) -> bf16[8,6,6,16] {
  %param_0.3 = bf16[16]{0:T(256)(128)(2,1)} parameter(0)
  %param_1.4 = bf16[8,6,6,16]{3,2,1,0:T(8,128)(2,1)} parameter(1)
  %broadcast.1 = bf16[8,6,6,16]{3,2,1,0} broadcast(%param_0.3), dimensions={3}
  ROOT %add.1 = bf16[8,6,6,16]{3,2,1,0:T(8,128)(2,1)} add(%param_1.4, %broadcast.1)
}

ENTRY %step () -> bf16[8,6,6,16] {
  %p0 = bf16[8,6,6,4]{3,2,1,0:T(8,128)(2,1)} parameter(0)
  %p1 = bf16[3,3,4,16]{3,2,1,0:T(8,128)(2,1)} parameter(1)
  %p2 = bf16[16]{0:T(256)(128)(2,1)} parameter(2)
  %fusion.1 = bf16[8,6,6,16]{3,2,1,0:T(8,128)(2,1)} fusion(%p0, %p1), kind=kOutput, calls=%fused_computation.1
  %fusion.2 = bf16[8,6,6,16]{3,2,1,0:T(8,128)(2,1)} fusion(%p2, %fusion.1), kind=kLoop, calls=%fused_computation.2
  %bwd_in = bf16[8,6,6,4]{3,2,1,0:T(8,128)(2,1)} convolution(%fusion.2, %p1), window={size=3x3 pad=1_1x1_1 rhs_reversal=1x1}, dim_labels=b01f_01oi->b01f, metadata={op_name="jit(step)/transpose(jvp())/conv_general_dilated"}
  %canon = bf16[8,6,6,4]{3,2,1,0:T(8,128)(2,1)} convolution(%p1, %fusion.2), window={size=6x6 pad=5_5x5_5}, dim_labels=01bf_o01i->f01b
  %big0 = bf16[64,6,6,4]{3,2,1,0:T(8,128)(2,1)} parameter(3)
  %big1 = bf16[64,6,6,16]{3,2,1,0:T(8,128)(2,1)} parameter(4)
  %grad_w = bf16[3,3,4,16]{3,2,1,0:T(8,128)(2,1)} convolution(%big0, %big1), window={size=6x6 pad=1_1x1_1}, dim_labels=f01b_i01o->01bf
  %mp = bf16[8,3,3,16]{3,2,1,0} reduce-window(%fusion.2, %p2), window={size=1x2x2x1 stride=1x2x2x1}
  %sas = bf16[8,6,6,16]{3,2,1,0} select-and-scatter(%fusion.2, %mp, %p2), window={size=1x2x2x1}
  ROOT %out = bf16[8,6,6,16]{3,2,1,0:T(8,128)(2,1)} copy(%fusion.2)
}
"""


@pytest.fixture(scope="module")
def mod():
    return HloModule(MINI_HLO)


class TestParsing:
    def test_shape_of_handles_tpu_layouts(self):
        n, shape, dt = shape_of("bf16[8,6,6,16]{3,2,1,0:T(8,128)(2,1)}")
        assert shape == (8, 6, 6, 16) and dt == "bf16"
        assert n == 8 * 6 * 6 * 16
        n2, shape2, dt2 = shape_of("f32[256]{0:T(256)(128)(2,1)S(1)}")
        assert shape2 == (256,) and dt2 == "f32"

    def test_entry_and_computations_indexed(self, mod):
        assert "fusion.1" in mod.entry and "bwd_in" in mod.entry
        assert "conv.1" in mod.comp_members["fused_computation.1"]

    def test_param_names_resolve_computation_locally(self, mod):
        # param_0.* differs per computation; conv.1's lhs must resolve to
        # the [8,6,6,4] input of ITS computation, not another's param
        info = mod.by_comp["fused_computation.1"]["conv.1"]
        ops = mod.operand_shapes(info["line"], info["comp"])
        assert ops[0][1] == (8, 6, 6, 4)
        assert ops[1][1] == (3, 3, 4, 16)

    def test_operand_scan_survives_layout_parens(self, mod):
        info = mod.instr["fusion.2"]
        ops = mod.operand_shapes(info["line"], "__entry__")
        assert [o[1] for o in ops] == [(16,), (8, 6, 6, 16)]


class TestConvFlops:
    def test_forward_conv_flops(self, mod):
        info = mod.by_comp["fused_computation.1"]["conv.1"]
        flops, out_shape = mod.conv_flops(info)
        assert out_shape == (8, 6, 6, 16)
        # 'same' 3x3 over 6x6: interior taps = sum over positions of valid
        # taps = (6*3 - 2)^2 per dim pair; per-dim: 16 valid (6 pos * 3 - 2)
        taps_1d = sum(1 for o in range(6) for w in range(3)
                      if 0 <= o + w - 1 < 6)
        assert flops == 2 * (8 * 16) * 4 * taps_1d * taps_1d

    def test_canonicalized_backward_conv_not_overcounted(self, mod):
        # window=6x6 pad=5_5: only one valid tap per output position —
        # nominal counting would overstate by 36x
        info = mod.instr["canon"]
        flops, _ = mod.conv_flops(info)
        # lhs spatial is 3 (the [3,3,4,16] "kernel-as-input"): valid taps
        # per dim = #{o,w in 0..5 : 0 <= o+w-5 < 3} = 15, vs the naive
        # window count of 36 per dim — a 5.8x per-dim overcount avoided
        taps_1d = sum(1 for o in range(6) for w in range(6)
                      if 0 <= o + w - 5 < 3)
        assert taps_1d == 15
        # kernel operand is %fusion.2 [8,6,6,16] with spec o01i -> i=16
        assert flops == 2 * (8 * 4) * 16 * taps_1d * taps_1d


class TestClassification:
    def test_fused_forward_conv(self, mod):
        cat, flops = mod.classify("fusion.1", 8)
        assert cat == "conv_fwd" and flops > 0

    def test_elementwise_fusion(self, mod):
        cat, flops = mod.classify("fusion.2", 8)
        assert cat == "elementwise" and flops == 0

    def test_bwd_input_by_rhs_reversal(self, mod):
        cat, _ = mod.classify("bwd_in", 8)
        assert cat == "conv_bwd_input"

    def test_bwd_filter_by_small_output(self, mod):
        cat, _ = mod.classify("grad_w", 8)
        assert cat == "conv_bwd_filter"

    def test_pool_and_scatter_and_copy(self, mod):
        assert mod.classify("mp", 8)[0] == "pool_fwd"
        assert mod.classify("sas", 8)[0] == "maxpool_bwd"
        assert mod.classify("out", 8)[0] == "copy"

    def test_unmatched(self, mod):
        assert mod.classify("nonexistent.999", 8)[0] == "unmatched"


class TestStreamBytes:
    def test_fusion_counts_params_and_output(self, mod):
        # fusion.2: out [8,6,6,16] bf16 + params [16] + [8,6,6,16]
        b = mod.stream_bytes("fusion.2")
        big = 8 * 6 * 6 * 16 * 2
        assert b == big + 16 * 2 + big

    def test_unfused_copy_counts_operand_reads(self, mod):
        b = mod.stream_bytes("out")
        big = 8 * 6 * 6 * 16 * 2
        assert b == 2 * big  # read + write
