"""Keras import equivalence tests.

Mirrors the reference's ``deeplearning4j-modelimport/src/test`` strategy
(``KerasWeightSettingTests.java``: imported model output must equal the
original framework's output on the same input). Fixtures are generated
in-process with the installed Keras and saved in legacy HDF5 format.
"""

import json

import numpy as np
import pytest

keras = pytest.importorskip("keras")

from deeplearning4j_tpu.modelimport.keras import KerasModelImport  # noqa: E402


def _save(model, tmp_path, name, loss=None):
    if loss:
        model.compile(loss=loss, optimizer="sgd")
    p = str(tmp_path / name)
    model.save(p)
    return p


def _assert_close(ours, theirs, tol=1e-4):
    ours = np.asarray(ours)
    assert ours.shape == theirs.shape, (ours.shape, theirs.shape)
    np.testing.assert_allclose(ours, theirs, atol=tol, rtol=1e-3)


class TestSequentialImport:
    def test_mlp(self, tmp_path):
        kl = keras.layers
        m = keras.Sequential([
            kl.Input((8,)),
            kl.Dense(16, activation="relu", name="d1"),
            kl.Dense(3, activation="softmax", name="d2"),
        ])
        p = _save(m, tmp_path, "mlp.h5", loss="categorical_crossentropy")
        x = np.random.RandomState(0).rand(4, 8).astype(np.float32)
        expected = m.predict(x, verbose=0)
        net = KerasModelImport.import_keras_model_and_weights(p)
        _assert_close(net.output(x), expected)

    def test_mlp_trains_after_import(self, tmp_path):
        kl = keras.layers
        m = keras.Sequential([
            kl.Input((8,)),
            kl.Dense(16, activation="relu", name="d1"),
            kl.Dense(3, activation="softmax", name="d2"),
        ])
        p = _save(m, tmp_path, "mlp2.h5", loss="categorical_crossentropy")
        net = KerasModelImport.import_keras_model_and_weights(p)
        rng = np.random.RandomState(0)
        x = rng.rand(32, 8).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.randint(0, 3, 32)]
        from deeplearning4j_tpu.datasets.dataset import DataSet
        s0 = net.score(DataSet(x, y))
        net.fit(x, y, epochs=5)
        assert net.score_ < s0

    def test_cnn(self, tmp_path):
        kl = keras.layers
        m = keras.Sequential([
            kl.Input((12, 12, 1)),
            kl.Conv2D(4, (3, 3), activation="relu", name="c1"),
            kl.MaxPooling2D((2, 2), name="p1"),
            kl.Conv2D(6, (3, 3), padding="same", activation="relu", name="c2"),
            kl.Flatten(name="f"),
            kl.Dense(5, activation="softmax", name="out"),
        ])
        p = _save(m, tmp_path, "cnn.h5", loss="categorical_crossentropy")
        x = np.random.RandomState(1).rand(2, 12, 12, 1).astype(np.float32)
        expected = m.predict(x, verbose=0)
        net = KerasModelImport.import_keras_model_and_weights(p)
        _assert_close(net.output(x), expected)

    def test_cnn_batchnorm(self, tmp_path):
        kl = keras.layers
        m = keras.Sequential([
            kl.Input((8, 8, 2)),
            kl.Conv2D(4, (3, 3), name="c1"),
            kl.BatchNormalization(name="bn"),
            kl.Activation("relu", name="a"),
            kl.GlobalAveragePooling2D(name="gap"),
            kl.Dense(3, activation="softmax", name="out"),
        ])
        p = _save(m, tmp_path, "cnnbn.h5")
        x = np.random.RandomState(2).rand(3, 8, 8, 2).astype(np.float32)
        expected = m.predict(x, verbose=0)
        net = KerasModelImport.import_keras_model_and_weights(p)
        _assert_close(net.output(x), expected)

    def test_lstm_return_sequences(self, tmp_path):
        kl = keras.layers
        m = keras.Sequential([
            kl.Input((7, 5)),
            kl.LSTM(6, return_sequences=True, name="l1"),
            kl.Dense(3, activation="softmax", name="out"),
        ])
        p = _save(m, tmp_path, "lstm.h5")
        x = np.random.RandomState(3).rand(2, 7, 5).astype(np.float32)
        expected = m.predict(x, verbose=0)
        net = KerasModelImport.import_keras_model_and_weights(p)
        _assert_close(net.output(x), expected)

    def test_lstm_last_step(self, tmp_path):
        kl = keras.layers
        m = keras.Sequential([
            kl.Input((7, 5)),
            kl.LSTM(6, return_sequences=False, name="l1"),
            kl.Dense(2, activation="softmax", name="out"),
        ])
        p = _save(m, tmp_path, "lstm2.h5")
        x = np.random.RandomState(4).rand(2, 7, 5).astype(np.float32)
        expected = m.predict(x, verbose=0)
        net = KerasModelImport.import_keras_model_and_weights(p)
        _assert_close(net.output(x), expected)

    def test_lstm_variable_length(self, tmp_path):
        kl = keras.layers
        m = keras.Sequential([
            kl.Input((None, 5)),
            kl.LSTM(6, return_sequences=True, name="l1"),
        ])
        p = _save(m, tmp_path, "lstmvar.h5")
        x = np.random.RandomState(12).rand(2, 9, 5).astype(np.float32)
        expected = m.predict(x, verbose=0)
        net = KerasModelImport.import_keras_model_and_weights(p)
        _assert_close(net.output(x), expected)

    def test_simple_rnn(self, tmp_path):
        kl = keras.layers
        m = keras.Sequential([
            kl.Input((5, 4)),
            kl.SimpleRNN(6, return_sequences=True, name="r1"),
            kl.Dense(2, name="out"),
        ])
        p = _save(m, tmp_path, "rnn.h5")
        x = np.random.RandomState(5).rand(2, 5, 4).astype(np.float32)
        expected = m.predict(x, verbose=0)
        net = KerasModelImport.import_keras_model_and_weights(p)
        _assert_close(net.output(x), expected)

    def test_embedding_lstm(self, tmp_path):
        kl = keras.layers
        m = keras.Sequential([
            kl.Input((6,)),
            kl.Embedding(20, 8, name="emb"),
            kl.LSTM(5, return_sequences=True, name="l1"),
        ])
        p = _save(m, tmp_path, "emb.h5")
        x = np.random.RandomState(6).randint(0, 20, (3, 6)).astype(np.float32)
        expected = m.predict(x, verbose=0)
        net = KerasModelImport.import_keras_model_and_weights(p)
        _assert_close(net.output(x), expected)

    def test_bidirectional_lstm(self, tmp_path):
        kl = keras.layers
        m = keras.Sequential([
            kl.Input((5, 4)),
            kl.Bidirectional(kl.LSTM(3, return_sequences=True), name="bi"),
        ])
        p = _save(m, tmp_path, "bi.h5")
        x = np.random.RandomState(7).rand(2, 5, 4).astype(np.float32)
        expected = m.predict(x, verbose=0)
        net = KerasModelImport.import_keras_model_and_weights(p)
        _assert_close(net.output(x), expected)

    def test_dropout_inference_identity(self, tmp_path):
        kl = keras.layers
        m = keras.Sequential([
            kl.Input((6,)),
            kl.Dense(8, activation="relu", name="d1"),
            kl.Dropout(0.5, name="drop"),
            kl.Dense(2, name="d2"),
        ])
        p = _save(m, tmp_path, "drop.h5")
        x = np.random.RandomState(8).rand(4, 6).astype(np.float32)
        expected = m.predict(x, verbose=0)
        net = KerasModelImport.import_keras_model_and_weights(p)
        _assert_close(net.output(x), expected)


class TestFunctionalImport:
    def test_two_branch_concat(self, tmp_path):
        kl = keras.layers
        inp = kl.Input((10,), name="in0")
        a = kl.Dense(8, activation="relu", name="branch_a")(inp)
        b = kl.Dense(8, activation="tanh", name="branch_b")(inp)
        merged = kl.Concatenate(name="cat")([a, b])
        out = kl.Dense(3, activation="softmax", name="head")(merged)
        m = keras.Model(inp, out)
        p = _save(m, tmp_path, "func.h5", loss="categorical_crossentropy")
        x = np.random.RandomState(9).rand(4, 10).astype(np.float32)
        expected = m.predict(x, verbose=0)
        net = KerasModelImport.import_keras_model_and_weights(p)
        got = net.output(x)
        got = got[0] if isinstance(got, list) else got
        _assert_close(got, expected)

    def test_residual_add(self, tmp_path):
        kl = keras.layers
        inp = kl.Input((6,), name="in0")
        h = kl.Dense(6, activation="relu", name="d1")(inp)
        s = kl.Add(name="add")([h, inp])
        out = kl.Dense(2, name="d2")(s)
        m = keras.Model(inp, out)
        p = _save(m, tmp_path, "res.h5")
        x = np.random.RandomState(10).rand(3, 6).astype(np.float32)
        expected = m.predict(x, verbose=0)
        net = KerasModelImport.import_keras_model_and_weights(p)
        got = net.output(x)
        got = got[0] if isinstance(got, list) else got
        _assert_close(got, expected)

    def test_cnn_functional_flatten(self, tmp_path):
        kl = keras.layers
        inp = kl.Input((8, 8, 1), name="img")
        h = kl.Conv2D(3, (3, 3), activation="relu", name="c1")(inp)
        h = kl.Flatten(name="flat")(h)
        out = kl.Dense(4, activation="softmax", name="fc")(h)
        m = keras.Model(inp, out)
        p = _save(m, tmp_path, "fcnn.h5")
        x = np.random.RandomState(11).rand(2, 8, 8, 1).astype(np.float32)
        expected = m.predict(x, verbose=0)
        net = KerasModelImport.import_keras_model_and_weights(p)
        got = net.output(x)
        got = got[0] if isinstance(got, list) else got
        _assert_close(got, expected)


class TestReshapeFlattenRanks:
    """ADVICE r4: Reshape→Flatten must compose by the reshape target's
    rank, with Keras Flatten semantics ([N, prod(dims)]), not a
    hard-coded cnn_to_ff."""

    def test_rank2_reshape_then_flatten(self, tmp_path):
        kl = keras.layers
        m = keras.Sequential([
            kl.Input((12,)),
            kl.Reshape((3, 4), name="rs"),
            kl.Flatten(name="fl"),
            kl.Dense(5, activation="softmax", name="d"),
        ])
        p = _save(m, tmp_path, "r2flat.h5", loss="categorical_crossentropy")
        x = np.random.RandomState(3).rand(4, 12).astype(np.float32)
        expected = m.predict(x, verbose=0)
        net = KerasModelImport.import_keras_model_and_weights(p)
        _assert_close(net.output(x), expected)

    def test_rank1_reshape_then_flatten(self, tmp_path):
        kl = keras.layers
        m = keras.Sequential([
            kl.Input((6,)),
            kl.Reshape((6,), name="rs"),
            kl.Flatten(name="fl"),
            kl.Dense(2, activation="softmax", name="d"),
        ])
        p = _save(m, tmp_path, "r1flat.h5", loss="categorical_crossentropy")
        x = np.random.RandomState(4).rand(3, 6).astype(np.float32)
        expected = m.predict(x, verbose=0)
        net = KerasModelImport.import_keras_model_and_weights(p)
        _assert_close(net.output(x), expected)

    def test_rank4_reshape_then_flatten(self, tmp_path):
        kl = keras.layers
        m = keras.Sequential([
            kl.Input((48,)),
            kl.Reshape((2, 2, 4, 3), name="rs"),
            kl.Flatten(name="fl"),
            kl.Dense(3, activation="softmax", name="d"),
        ])
        p = _save(m, tmp_path, "r4flat.h5", loss="categorical_crossentropy")
        x = np.random.RandomState(7).rand(4, 48).astype(np.float32)
        expected = m.predict(x, verbose=0)
        net = KerasModelImport.import_keras_model_and_weights(p)
        _assert_close(net.output(x), expected)

    def test_double_flatten_after_reshape(self, tmp_path):
        kl = keras.layers
        m = keras.Sequential([
            kl.Input((24,)),
            kl.Reshape((2, 3, 4), name="rs"),
            kl.Flatten(name="f1"),
            kl.Flatten(name="f2"),  # no-op on flat input
            kl.Dense(3, activation="softmax", name="d"),
        ])
        p = _save(m, tmp_path, "dflat.h5", loss="categorical_crossentropy")
        x = np.random.RandomState(6).rand(4, 24).astype(np.float32)
        expected = m.predict(x, verbose=0)
        net = KerasModelImport.import_keras_model_and_weights(p)
        _assert_close(net.output(x), expected)

    def test_rank3_reshape_then_flatten_still_works(self, tmp_path):
        kl = keras.layers
        m = keras.Sequential([
            kl.Input((24,)),
            kl.Reshape((2, 3, 4), name="rs"),
            kl.Flatten(name="fl"),
            kl.Dense(3, activation="softmax", name="d"),
        ])
        p = _save(m, tmp_path, "r3flat.h5", loss="categorical_crossentropy")
        x = np.random.RandomState(5).rand(4, 24).astype(np.float32)
        expected = m.predict(x, verbose=0)
        net = KerasModelImport.import_keras_model_and_weights(p)
        _assert_close(net.output(x), expected)


class TestMixedDataFormatRejected:
    def test_mixed_channels_orders_raise(self):
        from deeplearning4j_tpu.modelimport.keras.layers import (
            UnsupportedKerasConfigurationException)
        from deeplearning4j_tpu.modelimport.keras.model import (
            _channels_first)

        mixed = [
            {"class_name": "Conv2D",
             "config": {"data_format": "channels_first"}},
            {"class_name": "Conv2D",
             "config": {"data_format": "channels_last"}},
        ]
        with pytest.raises(UnsupportedKerasConfigurationException,
                           match="mixes"):
            _channels_first(mixed)
        # uniform declarations still resolve
        assert _channels_first(mixed[:1]) is True
        assert _channels_first(mixed[1:]) is False
        assert _channels_first([]) is False
        # benign mix: a pass-through layer's default data_format does not
        # conflict with the convs that actually bear the layout
        benign = [mixed[0],
                  {"class_name": "Flatten",
                   "config": {"data_format": "channels_last"}}]
        assert _channels_first(benign) is True


class TestConfigOnlyImport:
    def test_json_config_roundtrip(self, tmp_path):
        kl = keras.layers
        m = keras.Sequential([
            kl.Input((8,)),
            kl.Dense(16, activation="relu", name="d1"),
            kl.Dense(3, activation="softmax", name="d2"),
        ])
        jp = tmp_path / "conf.json"
        jp.write_text(m.to_json())
        conf = KerasModelImport.import_keras_model_configuration(str(jp))
        assert conf.num_params() == 8 * 16 + 16 + 16 * 3 + 3


class TestTransformerImport:
    """BERT-style encoder import (BASELINE.md config: "Keras-import
    BERT-base — import + train via attention ops")."""

    def _encoder_block(self, t=10, d=32, heads=4, ff=64):
        kl = keras.layers
        inp = kl.Input((t, d), name="tokens")
        att = kl.MultiHeadAttention(num_heads=heads, key_dim=d // heads,
                                    name="mha")(inp, inp)
        res1 = kl.Add(name="res1")([inp, att])
        ln1 = kl.LayerNormalization(name="ln1")(res1)
        ffn = kl.Dense(ff, activation="gelu", name="ffn_up")(ln1)
        ffn = kl.Dense(d, name="ffn_down")(ffn)
        res2 = kl.Add(name="res2")([ln1, ffn])
        out = kl.LayerNormalization(name="ln2")(res2)
        return keras.Model(inp, out)

    def test_encoder_block_output_equivalence(self, tmp_path):
        m = self._encoder_block()
        p = _save(m, tmp_path, "encoder.h5")
        x = np.random.RandomState(3).rand(2, 10, 32).astype(np.float32)
        expected = m.predict(x, verbose=0)
        net = KerasModelImport.import_keras_model_and_weights(p)
        got = net.output(x)
        got = got[0] if isinstance(got, list) else got
        _assert_close(got, expected, tol=5e-4)

    def test_imported_encoder_trains(self, tmp_path):
        m = self._encoder_block(t=6, d=16, heads=2, ff=32)
        p = _save(m, tmp_path, "encoder2.h5", loss="mse")
        net = KerasModelImport.import_keras_model_and_weights(p)
        from deeplearning4j_tpu.datasets.dataset import DataSet
        rng = np.random.RandomState(0)
        x = rng.rand(8, 6, 16).astype(np.float32)
        y = rng.rand(8, 6, 16).astype(np.float32)
        net.fit(DataSet(x, y))
        before = float(net.score_)
        for _ in range(10):
            net.fit(DataSet(x, y))
        assert float(net.score_) < before

    def test_cross_attention_output_equivalence(self, tmp_path):
        kl = keras.layers
        a = kl.Input((5, 16), name="a")
        b = kl.Input((7, 16), name="b")
        att = kl.MultiHeadAttention(num_heads=2, key_dim=8,
                                    name="xatt")(a, b)
        m = keras.Model([a, b], att)
        p = _save(m, tmp_path, "cross.h5")
        rng = np.random.RandomState(9)
        xa = rng.rand(2, 5, 16).astype(np.float32)
        xb = rng.rand(2, 7, 16).astype(np.float32)
        expected = m.predict([xa, xb], verbose=0)
        net = KerasModelImport.import_keras_model_and_weights(p)
        _assert_close(net.output(xa, xb), expected)

    def test_cross_attention_distinct_value_dim(self, tmp_path):
        kl = keras.layers
        a = kl.Input((4, 12), name="a")
        b = kl.Input((6, 10), name="b")
        att = kl.MultiHeadAttention(num_heads=2, key_dim=5, value_dim=7,
                                    name="xatt")(a, b)
        m = keras.Model([a, b], att)
        p = _save(m, tmp_path, "cross2.h5")
        rng = np.random.RandomState(10)
        xa = rng.rand(2, 4, 12).astype(np.float32)
        xb = rng.rand(2, 6, 10).astype(np.float32)
        expected = m.predict([xa, xb], verbose=0)
        net = KerasModelImport.import_keras_model_and_weights(p)
        _assert_close(net.output(xa, xb), expected)


class TestGruAndTimeDistributed:
    def test_gru_output_equivalence(self, tmp_path):
        kl = keras.layers
        m = keras.Sequential([
            kl.Input((7, 5)),
            kl.GRU(12, return_sequences=True, name="gru1"),
            kl.GRU(6, return_sequences=False, name="gru2"),
            kl.Dense(3, activation="softmax", name="out"),
        ])
        p = _save(m, tmp_path, "gru.h5", loss="categorical_crossentropy")
        x = np.random.RandomState(2).rand(4, 7, 5).astype(np.float32)
        expected = m.predict(x, verbose=0)
        net = KerasModelImport.import_keras_model_and_weights(p)
        _assert_close(net.output(x), expected)

    def test_gru_trains_after_import(self, tmp_path):
        kl = keras.layers
        m = keras.Sequential([
            kl.Input((6, 4)),
            kl.GRU(8, return_sequences=True, name="g"),
            kl.Dense(2, activation="softmax", name="o"),
        ])
        p = _save(m, tmp_path, "gru2.h5", loss="categorical_crossentropy")
        net = KerasModelImport.import_keras_model_and_weights(p)
        from deeplearning4j_tpu.datasets.dataset import DataSet
        rng = np.random.RandomState(0)
        x = rng.rand(16, 6, 4).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[rng.randint(0, 2, (16, 6))]
        net.fit(DataSet(x, y))
        first = float(net.score_)
        for _ in range(10):
            net.fit(DataSet(x, y))
        assert float(net.score_) < first

    def test_time_distributed_dense(self, tmp_path):
        kl = keras.layers
        m = keras.Sequential([
            kl.Input((5, 6)),
            kl.TimeDistributed(kl.Dense(4, activation="relu"), name="td"),
            kl.GRU(3, return_sequences=True, name="g"),
        ])
        p = _save(m, tmp_path, "td.h5")
        x = np.random.RandomState(1).rand(2, 5, 6).astype(np.float32)
        expected = m.predict(x, verbose=0)
        net = KerasModelImport.import_keras_model_and_weights(p)
        _assert_close(net.output(x), expected)


class TestConvLSTMAndTimeDistributed:
    """Rank-5 (image sequence) import paths: ConvLSTM2D and
    TimeDistributed(Conv*). Reference scope note: DL4J's Keras importer maps
    ConvLSTM via ``layers/convolutional/KerasConvLSTM2D.java``-era mappers;
    here the layer is TPU-native (hoisted input conv + lax.scan)."""

    def test_convlstm_return_sequences(self, tmp_path):
        kl = keras.layers
        m = keras.Sequential([
            kl.Input((4, 6, 6, 2)),
            kl.ConvLSTM2D(3, (2, 2), padding="same", return_sequences=True,
                          name="cl"),
        ])
        p = _save(m, tmp_path, "convlstm.h5")
        x = np.random.RandomState(5).rand(2, 4, 6, 6, 2).astype(np.float32)
        expected = m.predict(x, verbose=0)
        net = KerasModelImport.import_keras_model_and_weights(p)
        _assert_close(net.output(x), expected)

    def test_convlstm_last_step_into_dense(self, tmp_path):
        kl = keras.layers
        m = keras.Sequential([
            kl.Input((3, 5, 5, 1)),
            kl.ConvLSTM2D(4, (3, 3), padding="valid", strides=(2, 2),
                          return_sequences=False, name="cl"),
            kl.Flatten(name="f"),
            kl.Dense(3, activation="softmax", name="out"),
        ])
        p = _save(m, tmp_path, "convlstm2.h5", loss="categorical_crossentropy")
        x = np.random.RandomState(6).rand(2, 3, 5, 5, 1).astype(np.float32)
        expected = m.predict(x, verbose=0)
        net = KerasModelImport.import_keras_model_and_weights(p)
        _assert_close(net.output(x), expected)

    def test_convlstm_trains_after_import(self, tmp_path):
        kl = keras.layers
        m = keras.Sequential([
            kl.Input((3, 5, 5, 1)),
            kl.ConvLSTM2D(4, (3, 3), padding="same", return_sequences=False,
                          name="cl"),
            kl.Flatten(name="f"),
            kl.Dense(2, activation="softmax", name="out"),
        ])
        p = _save(m, tmp_path, "convlstm3.h5", loss="categorical_crossentropy")
        net = KerasModelImport.import_keras_model_and_weights(p)
        rng = np.random.RandomState(0)
        x = rng.rand(16, 3, 5, 5, 1).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[rng.randint(0, 2, 16)]
        from deeplearning4j_tpu.datasets.dataset import DataSet
        s0 = net.score(DataSet(x, y))
        net.fit(x, y, epochs=5)
        assert net.score_ < s0

    def test_time_distributed_conv(self, tmp_path):
        kl = keras.layers
        m = keras.Sequential([
            kl.Input((4, 8, 8, 1)),
            kl.TimeDistributed(kl.Conv2D(3, (3, 3), activation="relu"),
                               name="tdc"),
            kl.TimeDistributed(kl.MaxPooling2D((2, 2)), name="tdp"),
            kl.TimeDistributed(kl.Flatten(), name="tdf"),
            kl.LSTM(5, return_sequences=False, name="l"),
            kl.Dense(2, activation="softmax", name="out"),
        ])
        p = _save(m, tmp_path, "tdconv.h5")
        x = np.random.RandomState(7).rand(2, 4, 8, 8, 1).astype(np.float32)
        expected = m.predict(x, verbose=0)
        net = KerasModelImport.import_keras_model_and_weights(p)
        _assert_close(net.output(x), expected)


class TestTensorFlowScopeImport:
    """TF-scope weight files (the reference's ``tfscope`` fixtures,
    ``KerasModelImportTest.java:38-56``): Keras-1 dialect configs whose layer
    names contain scope slashes ("dense_1/xxx/yyy") and whose weight groups
    nest extra TF scope levels ("global/policy_net/dense_2_W:0"). Fixtures
    are synthesized in-format here so the test is self-contained."""

    @staticmethod
    def _write_fixture(tmp_path, scoped):
        import h5py
        rng = np.random.RandomState(3)
        w1 = rng.rand(7, 6).astype(np.float32)
        b1 = rng.rand(6).astype(np.float32)
        w2 = rng.rand(6, 2).astype(np.float32)
        b2 = rng.rand(2).astype(np.float32)
        d1 = "dense_1/xxx/yyy" if scoped else "dense_1"
        cfg = {"class_name": "Sequential", "config": [
            {"class_name": "InputLayer",
             "config": {"batch_input_shape": [None, 7], "name": "input_1"}},
            {"class_name": "Dense",
             "config": {"name": d1, "input_dim": 7, "output_dim": 6,
                        "activation": "tanh", "bias": True}},
            {"class_name": "Dense",
             "config": {"name": "dense_2", "input_dim": 6, "output_dim": 2,
                        "activation": "linear", "bias": True}},
        ]}
        wpath = str(tmp_path / f"w_{scoped}.h5")
        with h5py.File(wpath, "w") as f:
            if scoped:
                g1 = f.create_group(d1).create_group("global").create_group("shared")
                g1.create_dataset("yyy_W:0", data=w1)
                g1.create_dataset("yyy_b:0", data=b1)
                g2 = f.create_group("dense_2/global/policy_net")
                g2.create_dataset("dense_2_W:0", data=w2)
                g2.create_dataset("dense_2_b:0", data=b2)
            else:
                g1 = f.create_group("dense_1")
                g1.create_dataset("dense_1_W:0", data=w1)
                g1.create_dataset("dense_1_b:0", data=b1)
                g2 = f.create_group("dense_2")
                g2.create_dataset("dense_2_W:0", data=w2)
                g2.create_dataset("dense_2_b:0", data=b2)
        jpath = str(tmp_path / f"m_{scoped}.json")
        with open(jpath, "w") as f:
            json.dump(cfg, f)
        return jpath, wpath, (w1, b1, w2, b2)

    @pytest.mark.parametrize("scoped", [False, True])
    def test_json_plus_weights_two_file_import(self, tmp_path, scoped):
        jpath, wpath, (w1, b1, w2, b2) = self._write_fixture(tmp_path, scoped)
        net = KerasModelImport.import_keras_model_and_weights(jpath, wpath)
        x = np.random.RandomState(0).rand(3, 7).astype(np.float32)
        want = np.tanh(x @ w1 + b1) @ w2 + b2
        _assert_close(net.output(x), want)


class TestLambdaImport:
    """Keras Lambda layers via the user registry
    (``KerasLayer.registerLambdaLayer`` pattern): arbitrary serialized Python
    is never executed; the user supplies the implementation by layer name."""

    def test_registered_lambda(self, tmp_path):
        from deeplearning4j_tpu.modelimport.keras import (
            clear_lambda_layers, register_lambda_layer)
        kl = keras.layers
        m = keras.Sequential([
            kl.Input((4,)),
            kl.Dense(5, activation="relu", name="d"),
            kl.Lambda(lambda t: t * 2.0 + 1.0, name="scale_shift"),
        ])
        p = _save(m, tmp_path, "lam.h5")
        register_lambda_layer("scale_shift", lambda t: t * 2.0 + 1.0)
        try:
            x = np.random.RandomState(0).rand(3, 4).astype(np.float32)
            expected = m.predict(x, verbose=0)
            net = KerasModelImport.import_keras_model_and_weights(p)
            _assert_close(net.output(x), expected)
        finally:
            clear_lambda_layers()

    def test_unregistered_lambda_rejected(self, tmp_path):
        from deeplearning4j_tpu.modelimport.keras import (
            UnsupportedKerasConfigurationException, clear_lambda_layers)
        kl = keras.layers
        m = keras.Sequential([
            kl.Input((4,)),
            kl.Lambda(lambda t: t + 1.0, name="mystery"),
        ])
        p = _save(m, tmp_path, "lam2.h5")
        clear_lambda_layers()
        with pytest.raises(UnsupportedKerasConfigurationException,
                           match="register_lambda_layer"):
            KerasModelImport.import_keras_model_and_weights(p)


class TestNoiseLayersAndConstraints:
    """Keras noise layers import as their REAL implementations (not a
    plain-dropout approximation), ThresholdedReLU keeps theta, and
    kernel/bias constraints arrive as post-update constraints."""

    def test_thresholded_relu_keeps_theta(self, tmp_path):
        # Keras 3 dropped ThresholdedReLU, so drive the importer on a
        # hand-authored Keras-2 config (the dialect the reference's fixtures
        # use) and check the math f(x) = x·1[x > θ] directly
        conf = {
            "class_name": "Sequential",
            "config": {"name": "m", "layers": [
                {"class_name": "InputLayer",
                 "config": {"name": "in", "batch_input_shape": [None, 6]}},
                {"class_name": "ThresholdedReLU",
                 "config": {"name": "t", "theta": 0.7}},
            ]},
        }
        jp = tmp_path / "trelu.json"
        jp.write_text(json.dumps(conf))
        net_conf = KerasModelImport.import_keras_model_configuration(str(jp))
        layer = net_conf.layers[0]
        act = layer.activation
        assert act[0] == "thresholdedrelu" and act[1]["theta"] == 0.7
        x = np.random.RandomState(1).randn(5, 6).astype(np.float32)
        y, _ = layer.forward({}, x)
        np.testing.assert_allclose(np.asarray(y), np.where(x > 0.7, x, 0.0),
                                   rtol=1e-6)

    def test_noise_layers_map_to_real_variants(self, tmp_path):
        from deeplearning4j_tpu.nn.dropout import (AlphaDropout,
                                                   GaussianDropout,
                                                   GaussianNoise)
        kl = keras.layers
        m = keras.Sequential([
            kl.Input((8,)),
            kl.AlphaDropout(0.3, name="a"),
            kl.GaussianDropout(0.2, name="g"),
            kl.GaussianNoise(0.4, name="n"),
            kl.Dense(2, activation="softmax", name="d"),
        ])
        p = _save(m, tmp_path, "noise.h5", loss="categorical_crossentropy")
        net = KerasModelImport.import_keras_model_and_weights(p)
        kinds = [getattr(l, "dropout", None) for l in net.conf.layers[:3]]
        assert isinstance(kinds[0], AlphaDropout)
        assert np.isclose(kinds[0].p, 0.7)        # keep = 1 - rate
        assert isinstance(kinds[1], GaussianDropout)
        assert np.isclose(kinds[1].rate, 0.2)
        assert isinstance(kinds[2], GaussianNoise)
        assert np.isclose(kinds[2].stddev, 0.4)
        # inference: identity, so outputs equal Keras inference
        x = np.random.RandomState(2).randn(4, 8).astype(np.float32)
        _assert_close(net.output(x), m.predict(x, verbose=0))

    def test_spatial_dropout_imports_channel_semantics(self, tmp_path):
        from deeplearning4j_tpu.nn.dropout import SpatialDropout
        kl = keras.layers
        m = keras.Sequential([
            kl.Input((6, 6, 3)),
            kl.SpatialDropout2D(0.5, name="sd"),
            kl.Conv2D(4, 3, activation="relu", name="c"),
            kl.Flatten(),
            kl.Dense(2, activation="softmax", name="d"),
        ])
        p = _save(m, tmp_path, "sdrop.h5", loss="categorical_crossentropy")
        net = KerasModelImport.import_keras_model_and_weights(p)
        sd = net.conf.layers[0].dropout
        assert isinstance(sd, SpatialDropout) and np.isclose(sd.p, 0.5)
        x = np.random.RandomState(3).randn(2, 6, 6, 3).astype(np.float32)
        _assert_close(net.output(x), m.predict(x, verbose=0))

    def test_alpha_dropout_training_moments(self, tmp_path):
        # the imported AlphaDropout must preserve mean/variance at train
        # time (the plain-dropout stand-in it replaces does not)
        import jax
        kl = keras.layers
        m = keras.Sequential([kl.Input((2000,)),
                              kl.AlphaDropout(0.1, name="a"),
                              kl.Dense(2, activation="softmax", name="d")])
        p = _save(m, tmp_path, "amom.h5", loss="categorical_crossentropy")
        net = KerasModelImport.import_keras_model_and_weights(p)
        ad = net.conf.layers[0].dropout
        x = np.random.RandomState(4).randn(100, 2000).astype(np.float32)
        out = np.asarray(ad.apply(x, jax.random.PRNGKey(0), True))
        assert abs(out.mean()) < 0.02 and abs(out.std() - 1.0) < 0.02

    def test_recurrent_constraints_name_their_params(self, tmp_path):
        from deeplearning4j_tpu.nn.constraints import (MaxNormConstraint,
                                                       UnitNormConstraint)
        kl = keras.layers
        m = keras.Sequential([
            kl.Input((5, 4)),
            kl.LSTM(6, name="l", return_sequences=True,
                    kernel_constraint=keras.constraints.MaxNorm(2.0),
                    recurrent_constraint=keras.constraints.UnitNorm()),
            kl.Dense(2, activation="softmax", name="d"),
        ])
        p = _save(m, tmp_path, "rconstr.h5", loss="categorical_crossentropy")
        net = KerasModelImport.import_keras_model_and_weights(p)
        cs = net.conf.layers[0].constraints
        by_names = {c.param_names: c for c in cs}
        assert isinstance(by_names[("W",)], MaxNormConstraint)
        assert isinstance(by_names[("RW",)], UnitNormConstraint)

    def test_unknown_constraint_rejected_loudly(self, tmp_path):
        from deeplearning4j_tpu.modelimport.keras.layers import (
            UnsupportedKerasConfigurationException, _one_constraint)
        with pytest.raises(UnsupportedKerasConfigurationException,
                           match="constraint"):
            _one_constraint({"class_name": "RadialConstraint", "config": {}},
                            "weights")

    def test_kernel_and_bias_constraints(self, tmp_path):
        from deeplearning4j_tpu.nn.constraints import (MaxNormConstraint,
                                                       NonNegativeConstraint)
        kl = keras.layers
        m = keras.Sequential([
            kl.Input((8,)),
            kl.Dense(6, name="d1",
                     kernel_constraint=keras.constraints.MaxNorm(1.5),
                     bias_constraint=keras.constraints.NonNeg()),
            kl.Dense(2, activation="softmax", name="d2"),
        ])
        p = _save(m, tmp_path, "constr.h5", loss="categorical_crossentropy")
        net = KerasModelImport.import_keras_model_and_weights(p)
        cs = net.conf.layers[0].constraints
        assert any(isinstance(c, MaxNormConstraint) and c.max_norm == 1.5
                   and c.scope == "weights" for c in cs)
        assert any(isinstance(c, NonNegativeConstraint) and c.scope == "bias"
                   for c in cs)
        # and they actually run post-update: train with large lr, check cap
        from deeplearning4j_tpu.datasets.dataset import DataSet
        rng = np.random.RandomState(5)
        x = rng.rand(32, 8).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[rng.randint(0, 2, 32)]
        for _ in range(5):
            net.fit(DataSet(x, y))
        w = np.asarray(net.params[0]["W"])
        assert (np.sqrt((w ** 2).sum(axis=0)) <= 1.5 + 1e-3).all()
        assert (np.asarray(net.params[0]["b"]) >= 0).all()


class TestConstraintAxisDefault:
    def test_omitted_axis_means_keras_default_zero(self):
        # keras.constraints' default is axis=0; a hand-written/older config
        # that omits the field must NOT get this framework's all-but-last
        # default (different projection for HWIO conv kernels).
        from deeplearning4j_tpu.modelimport.keras.layers import _one_constraint
        c = _one_constraint({"class_name": "MaxNorm",
                             "config": {"max_value": 2.0}}, "weights")
        assert c.dimensions == (0,)

    def test_explicit_axis_passes_through(self):
        from deeplearning4j_tpu.modelimport.keras.layers import _one_constraint
        c = _one_constraint({"class_name": "MaxNorm",
                             "config": {"max_value": 2.0, "axis": [0, 1, 2]}},
                            "weights")
        assert c.dimensions == (0, 1, 2)
