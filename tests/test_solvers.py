"""Solver family tests: line search, CG, LBFGS convergence and the model-level
Solver front end (reference `optimize/solvers/` behavior)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.optimize.solvers import (
    BackTrackLineSearch,
    ConjugateGradient,
    LBFGS,
    LineGradientDescent,
    Solver,
    StochasticGradientDescent,
)


def _quadratic(scales):
    """f(x) = 0.5 * sum(scales * x^2) — condition number = max/min scale."""
    s = jnp.asarray(scales, jnp.float32)

    @jax.jit
    def vag(x):
        def f(x):
            return 0.5 * jnp.sum(s * x * x)
        return jax.value_and_grad(f)(x)

    return vag


def _rosenbrock():
    @jax.jit
    def vag(x):
        def f(x):
            return jnp.sum(100.0 * (x[1:] - x[:-1] ** 2) ** 2
                           + (1.0 - x[:-1]) ** 2)
        return jax.value_and_grad(f)(x)
    return vag


class TestBackTrackLineSearch:
    def test_full_step_when_sufficient(self):
        vag = _quadratic([1.0, 1.0])
        x = jnp.array([1.0, -2.0])
        score, grad = vag(x)
        ls = BackTrackLineSearch(lambda p: vag(p)[0], max_iterations=5)
        step = ls.optimize(x, float(score), np.asarray(grad), np.asarray(grad))
        assert step > 0
        new_score = float(vag(x - step * grad)[0])
        assert new_score < float(score)

    def test_backtracks_on_overshoot(self):
        # steep quadratic: full step along raw gradient overshoots
        vag = _quadratic([100.0])
        x = jnp.array([1.0])
        score, grad = vag(x)
        ls = BackTrackLineSearch(lambda p: vag(p)[0], max_iterations=10)
        step = ls.optimize(x, float(score), np.asarray(grad), np.asarray(grad))
        assert 0 < step < 1.0
        assert float(vag(x - step * grad)[0]) < float(score)

    def test_zero_for_ascent_direction(self):
        vag = _quadratic([1.0])
        x = jnp.array([1.0])
        score, grad = vag(x)
        ls = BackTrackLineSearch(lambda p: vag(p)[0])
        step = ls.optimize(x, float(score), np.asarray(grad), -np.asarray(grad))
        assert step == 0.0


class TestOptimizers:
    @pytest.mark.parametrize("cls", [LineGradientDescent, ConjugateGradient, LBFGS])
    def test_quadratic_convergence(self, cls):
        vag = _quadratic([1.0, 10.0, 100.0])
        x0 = jnp.array([5.0, -3.0, 2.0])
        opt = cls(max_iterations=200, line_search_iterations=10)
        x = opt.optimize(vag, x0)
        assert float(vag(x)[0]) < 1e-4
        # scores non-increasing up to float32 evaluation noise
        hist = opt.score_history
        assert all(b <= a + 1e-6 + 1e-6 * abs(a) for a, b in zip(hist, hist[1:]))

    def test_lbfgs_beats_gd_on_rosenbrock(self):
        # curved valley: curvature information must beat steepest descent
        x0 = jnp.zeros(6)

        def final(cls):
            opt = cls(max_iterations=80, line_search_iterations=15,
                      termination_conditions=[])
            x = opt.optimize(_rosenbrock(), x0)
            return float(_rosenbrock()(x)[0])

        assert final(LBFGS) < final(LineGradientDescent) * 0.5

    def test_lbfgs_rosenbrock(self):
        x0 = jnp.zeros(4)
        opt = LBFGS(m=6, max_iterations=400, line_search_iterations=20,
                    termination_conditions=[])
        x = opt.optimize(_rosenbrock(), x0)
        assert float(_rosenbrock()(x)[0]) < 1e-3
        np.testing.assert_allclose(np.asarray(x), np.ones(4), atol=0.05)

    def test_sgd_descends(self):
        vag = _quadratic([1.0, 2.0])
        opt = StochasticGradientDescent(learning_rate=0.1, max_iterations=50)
        x = opt.optimize(vag, jnp.array([4.0, 4.0]))
        assert float(vag(x)[0]) < 0.1


class TestModelSolver:
    def _net_and_data(self, algo):
        from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.conf.inputs import InputType
        from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        from deeplearning4j_tpu.datasets.dataset import DataSet

        rng = np.random.default_rng(0)
        n = 256
        y_idx = rng.integers(0, 3, n)
        x = rng.normal(size=(n, 8)).astype(np.float32)
        x[np.arange(n), y_idx] += 3.0  # separable signal
        y = np.eye(3, dtype=np.float32)[y_idx]
        conf = (NeuralNetConfiguration.builder().seed(7)
                .optimization_algo(algo)
                .list()
                .layer(DenseLayer(n_out=16, activation="tanh"))
                .layer(OutputLayer(n_out=3, activation="softmax",
                                   loss="negativeloglikelihood"))
                .set_input_type(InputType.feed_forward(8))
                .build())
        net = MultiLayerNetwork(conf).init()
        return net, DataSet(x, y)

    @pytest.mark.parametrize("algo", ["lbfgs", "conjugate_gradient",
                                      "line_gradient_descent"])
    def test_solver_trains_classifier(self, algo):
        net, ds = self._net_and_data(algo)
        solver = Solver(net, max_iterations=60)
        assert solver.algo == algo
        score0 = solver.score_history[0] if hasattr(solver, "score_history") else None
        final = solver.optimize(ds)
        assert final < solver.score_history[0]
        from deeplearning4j_tpu.datasets.dataset import ListDataSetIterator
        ev = net.evaluate(ListDataSetIterator(ds, 128))
        assert ev.accuracy() > 0.9

    def test_builder(self):
        net, ds = self._net_and_data("lbfgs")
        s = (Solver.Builder().model(net).configure(net.conf.global_conf)
             .max_iterations(5).build())
        assert s.algo == "lbfgs"
        s.optimize(ds)

    def test_unknown_algo_raises(self):
        net, ds = self._net_and_data("lbfgs")
        with pytest.raises(ValueError):
            Solver(net, algo="newton").optimize(ds)


class TestHpo:
    """optimize/hpo.py — the Arbiter role: spaces, random + grid search."""

    def test_spaces_sample_in_range(self):
        from deeplearning4j_tpu.optimize.hpo import (Choice, IntRange,
                                                     LogUniform, Uniform)
        rng = np.random.default_rng(0)
        assert Choice("a", "b").sample(rng) in ("a", "b")
        assert 2 <= IntRange(2, 5).sample(rng) <= 5
        assert 0.1 <= Uniform(0.1, 0.2).sample(rng) < 0.2
        v = LogUniform(1e-4, 1e-1).sample(rng)
        assert 1e-4 <= v < 1e-1
        assert IntRange(1, 3).grid_values() == [1, 2, 3]

    def test_random_search_finds_planted_optimum(self):
        from deeplearning4j_tpu.optimize.hpo import (LogUniform,
                                                     RandomSearch, Choice)
        calls = []

        def model_fn(p):
            calls.append(p)
            return p

        def score_fn(model, p):
            # quadratic bowl around lr=1e-2 plus a penalty for width 8
            return (np.log10(p["lr"]) + 2) ** 2 + (0.5 if p["width"] == 8
                                                   else 0.0)

        rs = RandomSearch({"lr": LogUniform(1e-4, 1e0),
                           "width": Choice(8, 16)},
                          model_fn, score_fn)
        best = rs.optimize(n_trials=40, seed=1)
        assert len(rs.trials) == 40 and len(calls) == 40
        assert best.params["width"] == 16
        assert 3e-3 < best.params["lr"] < 3e-2

    def test_grid_search_enumerates_product(self):
        from deeplearning4j_tpu.optimize.hpo import Choice, GridSearch, IntRange
        gs = GridSearch({"a": Choice(1, 2), "b": IntRange(0, 2)},
                        lambda p: p, lambda m, p: p["a"] * 10 + p["b"])
        best = gs.optimize()
        assert len(gs.trials) == 6
        assert best.params == {"a": 1, "b": 0}
        from deeplearning4j_tpu.optimize.hpo import Uniform
        import pytest as _pytest
        with _pytest.raises(NotImplementedError, match="continuous"):
            GridSearch({"u": Uniform(0, 1)}, lambda p: p,
                       lambda m, p: 0.0).optimize()

    def test_end_to_end_tiny_training_search(self):
        from deeplearning4j_tpu.datasets.dataset import DataSet, ListDataSetIterator
        from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        from deeplearning4j_tpu.nn.updaters import Adam
        from deeplearning4j_tpu.optimize.hpo import Choice, GridSearch

        rng = np.random.default_rng(0)
        cls = rng.integers(0, 2, 64)
        x = rng.normal(size=(64, 4)).astype(np.float32)
        x[np.arange(64), cls] += 2.0
        y = np.eye(2, dtype=np.float32)[cls]

        def model_fn(p):
            conf = (NeuralNetConfiguration.builder().seed(0)
                    .updater(Adam(p["lr"])).list()
                    .layer(DenseLayer(n_in=4, n_out=p["width"],
                                      activation="relu"))
                    .layer(OutputLayer(n_in=p["width"], n_out=2))
                    .build())
            net = MultiLayerNetwork(conf).init()
            for _ in range(10):
                net.fit(x, y)
            return net

        def score_fn(net, p):
            e = net.evaluate(ListDataSetIterator(DataSet(x, y), 32))
            return 1.0 - e.accuracy()

        best = GridSearch({"lr": Choice(1e-5, 5e-2), "width": Choice(8)},
                          model_fn, score_fn, keep_models=True).optimize()
        assert best.params["lr"] == 5e-2  # the learnable configuration wins
        assert best.score < 0.2
        assert best.model is not None
