"""Gradient checks — port of the reference's gradientcheck suites
(deeplearning4j-core/src/test/.../gradientcheck/: CNN, BN, LSTM, RNN, masking,
global pooling, loss functions). Finite differences vs jax.grad in float64.
"""

import numpy as np
import pytest

from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import (
    ActivationLayer,
    AutoEncoderLayer,
    BatchNormalizationLayer,
    BidirectionalWrapper,
    CnnLossLayer,
    ConvolutionLayer,
    Deconvolution2DLayer,
    DenseLayer,
    DepthwiseConvolution2DLayer,
    ElementWiseMultiplicationLayer,
    EmbeddingLayer,
    GlobalPoolingLayer,
    GravesLSTMLayer,
    LastTimeStepWrapper,
    LayerNormalizationLayer,
    LocalResponseNormalizationLayer,
    LSTMLayer,
    OutputLayer,
    RnnOutputLayer,
    SelfAttentionLayer,
    SeparableConvolution2DLayer,
    SimpleRnnLayer,
    SubsamplingLayer,
    Upsampling1DLayer,
    UpsamplingLayer,
    VariationalAutoencoderLayer,
    ZeroPaddingLayer,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.updaters import Sgd
from deeplearning4j_tpu.util.gradient_check import check_model_gradients

RNG = np.random.default_rng(42)


def build(layers, input_type):
    b = NeuralNetConfiguration.builder().seed(7).updater(Sgd(0.1)).weight_init("xavier").list()
    for l in layers:
        b.layer(l)
    conf = b.set_input_type(input_type).build()
    return MultiLayerNetwork(conf).init()


def onehot(idx, n):
    return np.eye(n, dtype=np.float64)[idx]


class TestDenseGradients:
    @pytest.mark.parametrize("act", ["tanh", "sigmoid", "softplus", "elu", "cube"])
    def test_dense_activations(self, act):
        m = build([DenseLayer(n_out=6, activation=act),
                   OutputLayer(n_out=3)], InputType.feed_forward(4))
        x = RNG.normal(size=(5, 4))
        y = onehot(RNG.integers(0, 3, 5), 3)
        assert check_model_gradients(m, x, y, subset=40, print_results=True)

    @pytest.mark.parametrize("loss,act", [
        ("mse", "identity"), ("mcxent", "softmax"), ("xent", "sigmoid"),
        ("l1", "tanh"), ("negativeloglikelihood", "softmax"),
        ("squared_hinge", "identity"), ("poisson", "softplus"),
    ])
    def test_loss_functions(self, loss, act):
        m = build([DenseLayer(n_out=5, activation="tanh"),
                   OutputLayer(n_out=3, loss=loss, activation=act)],
                  InputType.feed_forward(4))
        x = RNG.normal(size=(4, 4))
        if loss in ("mcxent", "negativeloglikelihood"):
            y = onehot(RNG.integers(0, 3, 4), 3)
        elif loss == "xent":
            y = (RNG.random((4, 3)) > 0.5).astype(np.float64)
        elif loss == "squared_hinge":
            y = np.where(RNG.random((4, 3)) > 0.5, 1.0, -1.0)
        elif loss == "poisson":
            y = RNG.integers(0, 5, (4, 3)).astype(np.float64)
        else:
            y = RNG.normal(size=(4, 3))
        assert check_model_gradients(m, x, y, subset=40, print_results=True)

    def test_l1_l2_regularization(self):
        b = (NeuralNetConfiguration.builder().seed(7).updater(Sgd(0.1))
             .l1(0.01).l2(0.02).list())
        b.layer(DenseLayer(n_out=5, activation="tanh"))
        b.layer(OutputLayer(n_out=3))
        m = MultiLayerNetwork(b.set_input_type(InputType.feed_forward(4)).build()).init()
        x = RNG.normal(size=(4, 4)) + 0.1  # avoid |w|=0 kink
        y = onehot(RNG.integers(0, 3, 4), 3)
        assert check_model_gradients(m, x, y, subset=40, print_results=True)

    def test_elementwise_mult(self):
        m = build([ElementWiseMultiplicationLayer(activation="tanh"),
                   OutputLayer(n_out=2)], InputType.feed_forward(4))
        x = RNG.normal(size=(3, 4))
        y = onehot(RNG.integers(0, 2, 3), 2)
        assert check_model_gradients(m, x, y, subset=40, print_results=True)

    def test_embedding(self):
        m = build([EmbeddingLayer(n_in=10, n_out=5, activation="tanh"),
                   OutputLayer(n_out=3)], InputType.feed_forward(10))
        x = RNG.integers(0, 10, (6, 1)).astype(np.float64)
        y = onehot(RNG.integers(0, 3, 6), 3)
        assert check_model_gradients(m, x, y, subset=60, print_results=True)


class TestCnnGradients:
    def test_cnn_basic(self):
        m = build([ConvolutionLayer(n_out=3, kernel_size=(2, 2), activation="tanh"),
                   OutputLayer(n_out=2)], InputType.convolutional(5, 5, 2))
        x = RNG.normal(size=(3, 5, 5, 2))
        y = onehot(RNG.integers(0, 2, 3), 2)
        assert check_model_gradients(m, x, y, subset=40, print_results=True)

    def test_cnn_pool_dense(self):
        m = build([ConvolutionLayer(n_out=3, kernel_size=(2, 2), activation="tanh"),
                   SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2), pooling_type="max"),
                   DenseLayer(n_out=6, activation="tanh"),
                   OutputLayer(n_out=2)], InputType.convolutional(6, 6, 1))
        x = RNG.normal(size=(3, 6, 6, 1))
        y = onehot(RNG.integers(0, 2, 3), 2)
        assert check_model_gradients(m, x, y, subset=30, print_results=True)

    @pytest.mark.parametrize("pool", ["avg", "pnorm"])
    def test_pool_types(self, pool):
        m = build([SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2), pooling_type=pool),
                   OutputLayer(n_out=2)], InputType.convolutional(4, 4, 2))
        x = RNG.normal(size=(3, 4, 4, 2))
        y = onehot(RNG.integers(0, 2, 3), 2)
        assert check_model_gradients(m, x, y, subset=40, print_results=True)

    def test_batchnorm(self):
        m = build([ConvolutionLayer(n_out=2, kernel_size=(2, 2), activation="identity"),
                   BatchNormalizationLayer(),
                   ActivationLayer(activation="tanh"),
                   OutputLayer(n_out=2)], InputType.convolutional(4, 4, 1))
        x = RNG.normal(size=(4, 4, 4, 1))
        y = onehot(RNG.integers(0, 2, 4), 2)
        assert check_model_gradients(m, x, y, subset=40, print_results=True)

    def test_deconv(self):
        m = build([Deconvolution2DLayer(n_out=2, kernel_size=(2, 2), stride=(2, 2),
                                        activation="tanh"),
                   OutputLayer(n_out=2)], InputType.convolutional(3, 3, 2))
        x = RNG.normal(size=(2, 3, 3, 2))
        y = onehot(RNG.integers(0, 2, 2), 2)
        assert check_model_gradients(m, x, y, subset=40, print_results=True)

    def test_separable_depthwise(self):
        m = build([SeparableConvolution2DLayer(n_out=3, kernel_size=(2, 2), activation="tanh"),
                   DepthwiseConvolution2DLayer(kernel_size=(2, 2), depth_multiplier=2,
                                               activation="tanh"),
                   OutputLayer(n_out=2)], InputType.convolutional(5, 5, 2))
        x = RNG.normal(size=(2, 5, 5, 2))
        y = onehot(RNG.integers(0, 2, 2), 2)
        assert check_model_gradients(m, x, y, subset=30, print_results=True)

    def test_padding_upsampling_lrn(self):
        m = build([ZeroPaddingLayer(padding=(1, 1)),
                   ConvolutionLayer(n_out=2, kernel_size=(3, 3), activation="tanh"),
                   UpsamplingLayer(size=(2, 2)),
                   LocalResponseNormalizationLayer(),
                   OutputLayer(n_out=2)], InputType.convolutional(4, 4, 1))
        x = RNG.normal(size=(2, 4, 4, 1))
        y = onehot(RNG.integers(0, 2, 2), 2)
        assert check_model_gradients(m, x, y, subset=30, print_results=True)

    def test_cnn_loss_layer(self):
        m = build([ConvolutionLayer(n_out=2, kernel_size=(1, 1), activation="identity"),
                   CnnLossLayer(loss="mse", activation="sigmoid")],
                  InputType.convolutional(3, 3, 2))
        x = RNG.normal(size=(2, 3, 3, 2))
        y = RNG.random((2, 3, 3, 2))
        assert check_model_gradients(m, x, y, subset=40, print_results=True)

    def test_global_pooling_cnn(self):
        m = build([ConvolutionLayer(n_out=3, kernel_size=(2, 2), activation="tanh"),
                   GlobalPoolingLayer(pooling_type="avg"),
                   OutputLayer(n_out=2)], InputType.convolutional(4, 4, 1))
        x = RNG.normal(size=(3, 4, 4, 1))
        y = onehot(RNG.integers(0, 2, 3), 2)
        assert check_model_gradients(m, x, y, subset=40, print_results=True)


class TestRnnGradients:
    def test_lstm(self):
        m = build([LSTMLayer(n_out=4, activation="tanh"),
                   RnnOutputLayer(n_out=2)], InputType.recurrent(3, 4))
        x = RNG.normal(size=(2, 4, 3))
        y = onehot(RNG.integers(0, 2, (2, 4)), 2)
        assert check_model_gradients(m, x, y, subset=40, print_results=True)

    def test_graves_lstm(self):
        m = build([GravesLSTMLayer(n_out=4, activation="tanh"),
                   RnnOutputLayer(n_out=2)], InputType.recurrent(3, 4))
        x = RNG.normal(size=(2, 4, 3))
        y = onehot(RNG.integers(0, 2, (2, 4)), 2)
        assert check_model_gradients(m, x, y, subset=40, print_results=True)

    def test_simple_rnn(self):
        m = build([SimpleRnnLayer(n_out=4),
                   RnnOutputLayer(n_out=2)], InputType.recurrent(3, 5))
        x = RNG.normal(size=(2, 5, 3))
        y = onehot(RNG.integers(0, 2, (2, 5)), 2)
        assert check_model_gradients(m, x, y, subset=40, print_results=True)

    def test_bidirectional_concat(self):
        m = build([BidirectionalWrapper(layer=LSTMLayer(n_out=3), mode="concat"),
                   RnnOutputLayer(n_out=2)], InputType.recurrent(2, 4))
        x = RNG.normal(size=(2, 4, 2))
        y = onehot(RNG.integers(0, 2, (2, 4)), 2)
        assert check_model_gradients(m, x, y, subset=40, print_results=True)

    def test_last_time_step(self):
        m = build([LastTimeStepWrapper(layer=LSTMLayer(n_out=4)),
                   OutputLayer(n_out=2)], InputType.recurrent(3, 5))
        x = RNG.normal(size=(2, 5, 3))
        y = onehot(RNG.integers(0, 2, 2), 2)
        assert check_model_gradients(m, x, y, subset=40, print_results=True)

    def test_masked_rnn(self):
        m = build([LSTMLayer(n_out=4),
                   RnnOutputLayer(n_out=2)], InputType.recurrent(3, 5))
        x = RNG.normal(size=(3, 5, 3))
        y = onehot(RNG.integers(0, 2, (3, 5)), 2)
        mask = np.ones((3, 5))
        mask[0, 3:] = 0
        mask[1, 2:] = 0
        assert check_model_gradients(m, x, y, features_mask=mask, labels_mask=mask,
                                     subset=40, print_results=True)

    def test_global_pooling_masked(self):
        m = build([LSTMLayer(n_out=4),
                   GlobalPoolingLayer(pooling_type="avg"),
                   OutputLayer(n_out=2)], InputType.recurrent(3, 5))
        x = RNG.normal(size=(3, 5, 3))
        y = onehot(RNG.integers(0, 2, 3), 2)
        mask = np.ones((3, 5))
        mask[1, 2:] = 0
        assert check_model_gradients(m, x, y, features_mask=mask,
                                     subset=40, print_results=True)

    def test_attention(self):
        m = build([SelfAttentionLayer(n_heads=2, n_out=4),
                   RnnOutputLayer(n_out=2)], InputType.recurrent(4, 5))
        x = RNG.normal(size=(2, 5, 4))
        y = onehot(RNG.integers(0, 2, (2, 5)), 2)
        assert check_model_gradients(m, x, y, subset=40, print_results=True)


class TestPretrainGradients:
    def test_autoencoder_loss(self):
        import jax
        import jax.numpy as jnp
        from deeplearning4j_tpu.util.gradient_check import check_gradients_fn
        layer = AutoEncoderLayer(n_in=5, n_out=3, corruption_level=0.0,
                                 activation="sigmoid", weight_init="xavier")
        with jax.enable_x64(True):
            params = layer.init_params(jax.random.PRNGKey(0), jnp.float64)
            x = jnp.asarray(RNG.random((4, 5)))
            assert check_gradients_fn(lambda p: layer.pretrain_loss(p, x, None),
                                      params, subset=40, print_results=True)

    def test_vae_elbo(self):
        import jax
        import jax.numpy as jnp
        from deeplearning4j_tpu.util.gradient_check import check_gradients_fn
        layer = VariationalAutoencoderLayer(
            n_in=4, n_out=2, encoder_layer_sizes=(5,), decoder_layer_sizes=(5,),
            activation="tanh", weight_init="xavier")
        with jax.enable_x64(True):
            params = layer.init_params(jax.random.PRNGKey(0), jnp.float64)
            x = jnp.asarray((RNG.random((3, 4)) > 0.5).astype(np.float64))
            key = jax.random.PRNGKey(5)
            assert check_gradients_fn(lambda p: layer.pretrain_loss(p, x, key),
                                      params, subset=40, print_results=True)


class TestNormAttentionGradients:
    def test_layer_norm(self):
        m = build([DenseLayer(n_out=6, activation="tanh"),
                   LayerNormalizationLayer(),
                   OutputLayer(n_out=3)],
                  InputType.feed_forward(5))
        x = RNG.normal(size=(4, 5))
        y = onehot(RNG.integers(0, 3, 4), 3)
        assert check_model_gradients(m, x, y, subset=40, print_results=True)

    def test_self_attention(self):
        m = build([SelfAttentionLayer(n_heads=2, head_size=3),
                   RnnOutputLayer(n_out=2)],
                  InputType.recurrent(6, 5))
        x = RNG.normal(size=(3, 5, 6))
        y = onehot(RNG.integers(0, 2, (3, 5)), 2)
        assert check_model_gradients(m, x, y, subset=40, print_results=True)

    def test_attention_layer_norm_stack(self):
        """Transformer-style stack: attention + layer norm + ffn."""
        m = build([SelfAttentionLayer(n_heads=2, head_size=2),
                   LayerNormalizationLayer(),
                   DenseLayer(n_out=8, activation="gelu"),
                   RnnOutputLayer(n_out=3)],
                  InputType.recurrent(4, 6))
        x = RNG.normal(size=(2, 6, 4))
        y = onehot(RNG.integers(0, 3, (2, 6)), 3)
        assert check_model_gradients(m, x, y, subset=30, print_results=True)


class TestGruGradients:
    def test_gru_reset_after(self):
        from deeplearning4j_tpu.nn.layers import GRULayer
        m = build([GRULayer(n_out=6), RnnOutputLayer(n_out=3)],
                  InputType.recurrent(4, 5))
        x = RNG.normal(size=(3, 5, 4))
        y = onehot(RNG.integers(0, 3, (3, 5)), 3)
        assert check_model_gradients(m, x, y, subset=40, print_results=True)

    def test_gru_classic(self):
        from deeplearning4j_tpu.nn.layers import GRULayer
        m = build([GRULayer(n_out=5, reset_after=False),
                   RnnOutputLayer(n_out=2)],
                  InputType.recurrent(3, 4))
        x = RNG.normal(size=(2, 4, 3))
        y = onehot(RNG.integers(0, 2, (2, 4)), 2)
        assert check_model_gradients(m, x, y, subset=40, print_results=True)


class TestCenterLossGradients:
    def test_center_loss_output(self):
        from deeplearning4j_tpu.nn.layers import CenterLossOutputLayer
        m = build([DenseLayer(n_out=6, activation="tanh"),
                   CenterLossOutputLayer(n_out=3, alpha=0.1, lambda_=0.01)],
                  InputType.feed_forward(5))
        x = RNG.normal(size=(6, 5))
        y = onehot(RNG.integers(0, 3, 6), 3)
        assert check_model_gradients(m, x, y, subset=40, print_results=True)

    def test_center_loss_tightens_clusters(self):
        """The auxiliary term must reduce intra-class feature spread vs a
        plain output layer (the FaceNet-center-loss tutorial property)."""
        from deeplearning4j_tpu.datasets.dataset import DataSet, ListDataSetIterator
        from deeplearning4j_tpu.nn.layers import CenterLossOutputLayer
        from deeplearning4j_tpu.nn.updaters import Adam

        rng = np.random.default_rng(0)
        y_idx = rng.integers(0, 3, 384)
        x = rng.normal(size=(384, 8)).astype(np.float32)
        x[np.arange(384), y_idx] += 2.0
        ds = DataSet(x, onehot(y_idx, 3).astype(np.float32))

        def spread(lambda_):
            conf = (NeuralNetConfiguration.builder().seed(3).updater(Adam(5e-3))
                    .list()
                    .layer(DenseLayer(n_out=8, activation="tanh"))
                    .layer(CenterLossOutputLayer(n_out=3, lambda_=lambda_))
                    .set_input_type(InputType.feed_forward(8)).build())
            net = MultiLayerNetwork(conf).init()
            net.fit(ListDataSetIterator(ds, 128, shuffle=True), epochs=25)
            feats = np.asarray(net.feed_forward(x)[1])  # dense activations
            total = 0.0
            for c in range(3):
                f = feats[y_idx == c]
                total += float(np.mean((f - f.mean(0)) ** 2))
            return total

        assert spread(0.5) < spread(0.0)


class TestYoloGradients:
    def _data(self, rng, n=2, h=4, w=4, b=2, c=3):
        x = rng.normal(size=(n, h, w, b * (5 + c)))
        labels = np.zeros((n, h, w, 5 + c))
        # one object per image at a random cell
        for i in range(n):
            gy, gx = rng.integers(0, h), rng.integers(0, w)
            # absolute grid coords: cell index + in-cell offset
            labels[i, gy, gx, 0:2] = [gx + rng.random(), gy + rng.random()]
            labels[i, gy, gx, 2:4] = 0.5 + rng.random(2)    # w, h (grid units)
            labels[i, gy, gx, 4] = 1.0                      # objectness
            labels[i, gy, gx, 5 + int(rng.integers(0, c))] = 1.0
        return x, labels

    def test_yolo_loss_gradients(self):
        """Full gradient check of the YOLO loss with the confidence target
        FROZEN at the evaluation point: finite differences cannot express
        stop_gradient (they see the moving IoU target; autodiff by design
        does not), so the checkable object is the loss with a constant
        target — which exercises every differentiable path (coords, class,
        obj/no-obj confidence)."""
        import jax
        import jax.numpy as jnp
        from deeplearning4j_tpu.nn.layers import Yolo2OutputLayer
        from deeplearning4j_tpu.util.gradient_check import check_gradients_fn

        rng = np.random.default_rng(6)
        layer = Yolo2OutputLayer(boxes=((1.0, 1.0), (2.0, 0.5)), n_classes=3)
        layer.set_n_in(InputType.convolutional(4, 4, 16))
        _, labels = self._data(rng)
        # conv producing the grid from an image (params under check)
        conv = ConvolutionLayer(n_out=16, kernel_size=(1, 1),
                                activation="identity")
        conv.set_n_in(InputType.convolutional(4, 4, 6))
        params = conv.init_params(jax.random.PRNGKey(0))  # harness casts to f64
        img = rng.normal(size=(2, 4, 4, 6))  # numpy f64: the harness
        # casts params to f64; inputs follow via p["W"].dtype below

        def preds_of(p):
            h, _ = conv.forward(p, jnp.asarray(img, p["W"].dtype))
            return h

        # freeze the target at the check point
        lab = jnp.asarray(labels)
        cx, cy, wh, _, _ = layer._split_predictions(preds_of(params))
        lab_cxy, lab_wh = lab[..., 0:2], lab[..., 2:4]
        frozen = np.asarray(layer._iou(
            cx, cy, wh, lab_cxy[..., None, 0], lab_cxy[..., None, 1],
            lab_wh[..., None, :]))

        def loss_fn(p):
            dt = p["W"].dtype
            return layer.compute_loss({}, preds_of(p),
                                      jnp.asarray(labels, dt),
                                      conf_target=jnp.asarray(frozen, dt))

        assert check_gradients_fn(loss_fn, params, subset=60,
                                  print_results=True)

    def test_yolo_stop_gradient_semantics(self):
        """The default loss treats the IoU target as constant: its gradient
        equals the frozen-target gradient evaluated with target = iou(p)."""
        import jax
        import jax.numpy as jnp
        from deeplearning4j_tpu.nn.layers import Yolo2OutputLayer

        rng = np.random.default_rng(3)
        layer = Yolo2OutputLayer(boxes=((1.0, 1.0), (2.0, 0.5)), n_classes=3)
        layer.set_n_in(InputType.convolutional(4, 4, 16))
        x, labels = self._data(rng)
        xj, lab = jnp.asarray(x), jnp.asarray(labels)
        g_default = jax.grad(
            lambda v: layer.compute_loss({}, v, lab))(xj)
        cx, cy, wh, _, _ = layer._split_predictions(xj)
        frozen = layer._iou(cx, cy, wh, lab[..., None, 0], lab[..., None, 1],
                            lab[..., 2:4][..., None, :])
        g_frozen = jax.grad(
            lambda v: layer.compute_loss({}, v, lab,
                                         conf_target=frozen))(xj)
        np.testing.assert_allclose(np.asarray(g_default),
                                   np.asarray(g_frozen), rtol=1e-6, atol=1e-8)

    def test_yolo_loss_penalizes_misses(self):
        """Loss must be higher when confidence is high in empty cells and
        low at the object cell than for well-placed predictions."""
        import jax.numpy as jnp
        from deeplearning4j_tpu.nn.layers import Yolo2OutputLayer
        layer = Yolo2OutputLayer(boxes=((1.0, 1.0), (2.0, 0.5)), n_classes=3)
        layer.set_n_in(InputType.convolutional(4, 4, 2 * 8))
        x, labels = self._data(np.random.default_rng(1))
        base = float(layer.compute_loss({}, jnp.asarray(x), jnp.asarray(labels)))
        # push all confidences strongly positive everywhere (false alarms)
        x_bad = x.copy()
        for bi in range(2):
            x_bad[..., bi * 8 + 4] = 6.0
        bad = float(layer.compute_loss({}, jnp.asarray(x_bad), jnp.asarray(labels)))
        assert np.isfinite(base) and np.isfinite(bad)
        assert bad > base


class TestConvLSTMGradients:
    def test_convlstm_output(self):
        from deeplearning4j_tpu.nn.layers import ConvLSTM2DLayer

        m = build([LastTimeStepWrapper(layer=ConvLSTM2DLayer(
                       n_out=2, kernel_size=(2, 2), convolution_mode="same")),
                   OutputLayer(n_out=2)],
                  InputType.recurrent_convolutional(4, 4, 1, 3))
        x = RNG.normal(size=(2, 3, 4, 4, 1))
        y = onehot(RNG.integers(0, 2, 2), 2)
        assert check_model_gradients(m, x, y, subset=40, print_results=True)

    def test_convlstm_masked(self):
        from deeplearning4j_tpu.nn.layers import ConvLSTM2DLayer

        m = build([ConvLSTM2DLayer(n_out=2, kernel_size=(2, 2),
                                   convolution_mode="same"),
                   RnnOutputLayer(n_out=2)],
                  InputType.recurrent_convolutional(3, 3, 1, 4))
        x = RNG.normal(size=(2, 4, 3, 3, 1))
        y = onehot(RNG.integers(0, 2, (2, 4)), 2)
        mask = np.ones((2, 4))
        mask[0, 2:] = 0
        assert check_model_gradients(m, x, y, features_mask=mask,
                                     labels_mask=mask, subset=40,
                                     print_results=True)

    def test_time_distributed_conv_gradients(self):
        from deeplearning4j_tpu.nn.layers import TimeDistributedWrapper

        m = build([TimeDistributedWrapper(layer=ConvolutionLayer(
                       n_out=2, kernel_size=(2, 2), convolution_mode="same",
                       activation="tanh")),
                   LSTMLayer(n_out=3),
                   RnnOutputLayer(n_out=2)],
                  InputType.recurrent_convolutional(3, 3, 1, 4))
        x = RNG.normal(size=(2, 4, 3, 3, 1))
        y = onehot(RNG.integers(0, 2, (2, 4)), 2)
        assert check_model_gradients(m, x, y, subset=40, print_results=True)

    def test_bidirectional_convlstm_masked(self):
        # locks the rank-agnostic masked reverse in BidirectionalWrapper
        from deeplearning4j_tpu.nn.layers import ConvLSTM2DLayer

        m = build([BidirectionalWrapper(layer=ConvLSTM2DLayer(
                       n_out=2, kernel_size=(2, 2), convolution_mode="same"),
                       mode="concat"),
                   RnnOutputLayer(n_out=2)],
                  InputType.recurrent_convolutional(3, 3, 1, 4))
        x = RNG.normal(size=(2, 4, 3, 3, 1))
        y = onehot(RNG.integers(0, 2, (2, 4)), 2)
        mask = np.ones((2, 4))
        mask[0, 2:] = 0
        assert check_model_gradients(m, x, y, features_mask=mask,
                                     labels_mask=mask, subset=40,
                                     print_results=True)


class TestSpaceToDepthStem:
    def test_s2d_conv_equivalence(self):
        # space_to_depth_stem must be bit-for-bit the same math
        import jax
        import jax.numpy as jnp
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.normal(size=(2, 30, 30, 3)).astype(np.float32))
        base = ConvolutionLayer(n_in=3, n_out=8, kernel_size=(7, 7), stride=(2, 2))
        p = base.init_params(jax.random.PRNGKey(0))
        s2d = ConvolutionLayer(n_in=3, n_out=8, kernel_size=(7, 7), stride=(2, 2),
                               space_to_depth_stem=True)
        y_ref, _ = base.forward(p, x)
        y_new, _ = s2d.forward(p, x)
        np.testing.assert_allclose(np.asarray(y_ref), np.asarray(y_new),
                                   atol=1e-5)
        g_ref = jax.grad(lambda pp: jnp.sum(jnp.sin(base.forward(pp, x)[0])))(p)
        g_new = jax.grad(lambda pp: jnp.sum(jnp.sin(s2d.forward(pp, x)[0])))(p)
        for k in g_ref:
            np.testing.assert_allclose(np.asarray(g_ref[k]), np.asarray(g_new[k]),
                                       atol=1e-4)

    def test_s2d_falls_back_when_inapplicable(self):
        import jax
        import jax.numpy as jnp
        rng = np.random.default_rng(1)
        # odd spatial size: must silently use the plain conv path
        x = jnp.asarray(rng.normal(size=(1, 15, 15, 3)).astype(np.float32))
        l = ConvolutionLayer(n_in=3, n_out=4, kernel_size=(7, 7), stride=(2, 2),
                             space_to_depth_stem=True)
        p = l.init_params(jax.random.PRNGKey(0))
        base = ConvolutionLayer(n_in=3, n_out=4, kernel_size=(7, 7), stride=(2, 2))
        y, _ = l.forward(p, x)
        y_ref, _ = base.forward(p, x)
        np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref), atol=1e-6)


class TestCausalAttentionGradients:
    """Gradient checks through the causal layer's two execution paths:
    the full-sequence masked forward and the KV-cache forward_seq (the path
    TBPTT trains through)."""

    def test_causal_self_attention(self):
        from deeplearning4j_tpu.nn.layers import CausalSelfAttentionLayer
        m = build([CausalSelfAttentionLayer(n_out=4, n_heads=2, head_size=2),
                   RnnOutputLayer(n_out=2)], InputType.recurrent(4, 5))
        x = RNG.normal(size=(2, 5, 4))
        y = onehot(RNG.integers(0, 2, (2, 5)), 2)
        assert check_model_gradients(m, x, y, subset=40, print_results=True)

    def test_causal_attention_with_padding_mask(self):
        from deeplearning4j_tpu.nn.layers import CausalSelfAttentionLayer
        m = build([CausalSelfAttentionLayer(n_out=4, n_heads=2, head_size=2),
                   RnnOutputLayer(n_out=2)], InputType.recurrent(4, 5))
        x = RNG.normal(size=(2, 5, 4))
        y = onehot(RNG.integers(0, 2, (2, 5)), 2)
        mask = np.ones((2, 5), np.float32)
        mask[1, 3:] = 0.0
        assert check_model_gradients(m, x, y, features_mask=mask,
                                     labels_mask=mask, subset=40,
                                     print_results=True)

    def test_kv_cache_path_gradients(self):
        # TBPTT trains THROUGH forward_seq with a carry: finite differences
        # vs jax.grad on that exact path
        import jax
        import jax.numpy as jnp
        from deeplearning4j_tpu.nn.layers import CausalSelfAttentionLayer
        from deeplearning4j_tpu.util.gradient_check import check_gradients_fn

        l = CausalSelfAttentionLayer(n_in=4, n_out=4, n_heads=2, head_size=2,
                                     max_cache=8)
        params = l.init_params(jax.random.PRNGKey(0))
        # hermetic rng: with the shared module RNG this check's input (and
        # so its finite-difference conditioning) depended on which tests
        # ran before it — near the 1e-5 threshold that made it flaky
        x_np = np.random.default_rng(1234).normal(size=(2, 3, 4))

        def loss(p):
            # f64 carry/input: the checker runs in x64 and an f32 cache
            # would truncate the finite differences
            x = jnp.asarray(x_np, jnp.float64)
            carry = l.init_carry(2, jnp.float64)
            y1, carry = l.forward_seq(p, x, carry=carry)
            y2, _ = l.forward_seq(p, x, carry=carry)  # second chunk
            return jnp.sum(y1 ** 2) + jnp.sum(y2 ** 2)

        assert check_gradients_fn(loss, params, subset=40)
