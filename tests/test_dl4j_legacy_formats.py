"""Legacy DL4J JSON regression corpus — the four checkpoint generations.

The reference locks its checkpoint-format compatibility with
``regressiontest/RegressionTest050.java`` / 060 / 071 / 080: each
deserializes model zips produced by that release and asserts layer-by-layer
config fields. The original zips live in the external ``dl4j-test-resources``
artifact (not in the snapshot), so the JSON below is hand-authored in each
generation's serde dialect from those tests' assertions:

- 0.5.0 era: WRAPPER_OBJECT layer names, activation as a PLAIN lowercase
  string, loss as an enum string (``lossFunction``), updater as an ENUM on
  the layer plus flat ``learningRate``/``momentum``/``rmsDecay`` fields,
  ``dropOut`` double, ``dist`` as WRAPPER_OBJECT; no convolutionMode field
  (defaults to Truncate).
- 0.6.0 / 0.7.1: same dialect; 0.7.x adds ``convolutionMode``.
- 0.8.0: activation and loss become ``@class``-tagged objects
  (``ActivationLReLU``/``LossMCXENT``); updater still the legacy enum.

Every assertion below mirrors one from the corresponding Java test.
"""

import json

import pytest

from deeplearning4j_tpu.modelimport.dl4j import import_dl4j_configuration
from deeplearning4j_tpu.nn.dropout import Dropout
from deeplearning4j_tpu.nn.layers import (
    ConvolutionLayer,
    DenseLayer,
    GravesBidirectionalLSTMLayer,
    GravesLSTMLayer,
    OutputLayer,
    RnnOutputLayer,
    SubsamplingLayer,
)
from deeplearning4j_tpu.nn.updaters import AdaDelta, Nesterovs, RmsProp
from deeplearning4j_tpu.nn.weights import Distribution

A = 1e-6


def _act_legacy(name):
    """Pre-0.8: plain string."""
    return name


def _act_080(name):
    """0.8.0: @class-tagged IActivation object."""
    return {"@class": f"org.nd4j.linalg.activations.impl.Activation{name}"}


def _loss_legacy(name):
    return name


def _loss_080(name):
    return {"@class": f"org.nd4j.linalg.lossfunctions.impl.Loss{name}"}


def mlp1(act, loss):
    """RegressionTest*.regressionTestMLP1: dense(relu 3→4, XAVIER,
    Nesterovs(0.15, 0.9)) → output(softmax, MCXENT, 4→5)."""
    nest = {"updater": "NESTEROVS", "learningRate": 0.15, "momentum": 0.9}
    return {"backprop": True, "confs": [
        {"layer": {"dense": {"activationFn": act("ReLU") if act is _act_080
                             else "relu",
                             "nin": 3, "nout": 4, "weightInit": "XAVIER",
                             **nest}}},
        {"layer": {"output": {"activationFn": act("Softmax") if act is _act_080
                              else "softmax",
                              "lossFunction": loss("MCXENT"),
                              "nin": 4, "nout": 5, "weightInit": "XAVIER",
                              **nest}}},
    ]}


def check_mlp1(conf):
    l0, l1 = conf.layers
    assert isinstance(l0, DenseLayer)
    assert l0.activation == "relu"
    assert l0.n_in == 3 and l0.n_out == 4
    assert l0.weight_init == "xavier"
    assert isinstance(l0.updater, Nesterovs)
    assert l0.updater.learning_rate == pytest.approx(0.15, abs=A)
    assert l0.updater.momentum == pytest.approx(0.9, abs=A)
    assert isinstance(l1, OutputLayer)
    assert l1.activation == "softmax" and l1.loss == "mcxent"
    assert l1.n_in == 4 and l1.n_out == 5
    assert isinstance(l1.updater, Nesterovs)
    assert l1.updater.learning_rate == pytest.approx(0.15, abs=A)


def mlp2(act, loss):
    """regressionTestMLP2: dense(leakyrelu, DISTRIBUTION N(0.1, 1.2),
    RmsProp(0.15, rmsDecay 0.96), Dropout(0.6), l1 0.1 l2 0.2) →
    output(identity, MSE)."""
    rms = {"updater": "RMSPROP", "learningRate": 0.15, "rmsDecay": 0.96}
    reg = {"l1": 0.1, "l2": 0.2, "dropOut": 0.6,
           "weightInit": "DISTRIBUTION",
           "dist": {"normal": {"mean": 0.1, "std": 1.2}}}
    return {"backprop": True, "confs": [
        {"layer": {"dense": {"activationFn": act("LReLU") if act is _act_080
                             else "leakyrelu",
                             "nin": 3, "nout": 4, **rms, **reg}}},
        {"layer": {"output": {"activationFn": act("Identity") if act is _act_080
                              else "identity",
                              "lossFunction": loss("MSE"),
                              "nin": 4, "nout": 5, **rms, **reg}}},
    ]}


def check_mlp2(conf):
    l0, l1 = conf.layers
    a = l0.activation
    assert (a == "leakyrelu" or (isinstance(a, tuple) and a[0] == "leakyrelu"))
    assert l0.weight_init == "distribution"
    assert l0.distribution == Distribution(kind="normal", mean=0.1, std=1.2)
    assert isinstance(l0.updater, RmsProp)
    assert l0.updater.learning_rate == pytest.approx(0.15, abs=A)
    assert l0.updater.rms_decay == pytest.approx(0.96, abs=A)
    assert l0.dropout == pytest.approx(0.6, abs=A)  # Dropout(0.6) retain prob
    assert l0.l1 == pytest.approx(0.1, abs=A)
    assert l0.l2 == pytest.approx(0.2, abs=A)
    assert isinstance(l1, OutputLayer)
    assert l1.activation == "identity" and l1.loss == "mse"
    assert l1.distribution == Distribution(kind="normal", mean=0.1, std=1.2)
    assert l1.dropout == pytest.approx(0.6, abs=A)
    assert l1.l1 == pytest.approx(0.1, abs=A)
    assert l1.l2 == pytest.approx(0.2, abs=A)


def cnn1(act, loss, with_conv_mode):
    """regressionTestCNN1: conv(tanh, 3→3, RELU init, RmsProp, k2x2 s1x1
    p0x0) → subsampling(max k2x2 s1x1) → output(sigmoid, NLL, 26·26·3→5).
    Pre-0.7.0 JSON has NO convolutionMode — must default to Truncate."""
    rms = {"updater": "RMSPROP", "learningRate": 0.15, "rmsDecay": 0.96}
    mode = {"convolutionMode": "Truncate"} if with_conv_mode else {}
    return {"backprop": True, "confs": [
        {"layer": {"convolution": {"activationFn": act("TanH") if act is _act_080
                                   else "tanh",
                                   "nin": 3, "nout": 3, "weightInit": "RELU",
                                   "kernelSize": [2, 2], "stride": [1, 1],
                                   "padding": [0, 0], **rms, **mode}}},
        {"layer": {"subsampling": {"poolingType": "MAX",
                                   "kernelSize": [2, 2], "stride": [1, 1],
                                   "padding": [0, 0], **mode}}},
        {"layer": {"output": {"activationFn": act("Sigmoid") if act is _act_080
                              else "sigmoid",
                              "lossFunction": loss("NegativeLogLikelihood"),
                              "nin": 26 * 26 * 3, "nout": 5, **rms}}},
    ],
        "inputPreProcessors": {"2": {"cnnToFeedForward": {
            "inputHeight": 26, "inputWidth": 26, "numChannels": 3}}}}


def check_cnn1(conf):
    l0, l1, l2 = conf.layers
    assert isinstance(l0, ConvolutionLayer)
    assert l0.activation == "tanh"
    assert l0.n_in == 3 and l0.n_out == 3
    assert l0.weight_init == "relu"
    assert isinstance(l0.updater, RmsProp)
    assert l0.kernel_size == (2, 2) and l0.stride == (1, 1)
    assert l0.padding == (0, 0)
    assert l0.convolution_mode == "truncate"  # default when field absent
    assert isinstance(l1, SubsamplingLayer)
    assert l1.pooling_type == "max"
    assert l1.kernel_size == (2, 2) and l1.stride == (1, 1)
    assert l1.convolution_mode == "truncate"
    assert isinstance(l2, OutputLayer)
    assert l2.activation == "sigmoid"
    assert l2.loss == "mcxent"  # NLL maps onto mcxent here
    assert l2.n_in == 26 * 26 * 3 and l2.n_out == 5
    assert 2 in conf.preprocessors  # cnnToFeedForward honored


def lstm1(act, loss):
    """regressionTestLSTM1 (060/071/080): gravesLSTM(tanh, 3→4, clip 1.5) →
    gravesBidirectionalLSTM(softsign, 4→4) → rnnoutput(softmax, MCXENT,
    4→5)."""
    clip = {"gradientNormalization": "ClipElementWiseAbsoluteValue",
            "gradientNormalizationThreshold": 1.5}
    return {"backprop": True, "confs": [
        {"layer": {"gravesLSTM": {"activationFn": act("TanH") if act is _act_080
                                  else "tanh",
                                  "nin": 3, "nout": 4, **clip}}},
        {"layer": {"gravesBidirectionalLSTM": {
            "activationFn": act("SoftSign") if act is _act_080 else "softsign",
            "nin": 4, "nout": 4, **clip}}},
        {"layer": {"rnnoutput": {"activationFn": act("Softmax") if act is _act_080
                                 else "softmax",
                                 "lossFunction": loss("MCXENT"),
                                 "nin": 4, "nout": 5}}},
    ]}


def check_lstm1(conf):
    l0, l1, l2 = conf.layers
    assert isinstance(l0, GravesLSTMLayer)
    assert l0.activation == "tanh"
    assert l0.n_in == 3 and l0.n_out == 4
    assert l0.gradient_normalization == "clip_element_wise_absolute_value"
    assert l0.gradient_normalization_threshold == pytest.approx(1.5, abs=1e-5)
    assert isinstance(l1, GravesBidirectionalLSTMLayer)
    assert l1.activation == "softsign"
    assert l1.n_in == 4 and l1.n_out == 4
    assert l1.gradient_normalization == "clip_element_wise_absolute_value"
    assert isinstance(l2, RnnOutputLayer)
    assert l2.activation == "softmax" and l2.loss == "mcxent"
    assert l2.n_in == 4 and l2.n_out == 5


def cg_lstm1(act, loss):
    """regressionTestCGLSTM1: the same three layers as a ComputationGraph
    with numerically-named vertices."""
    lv = lambda layer: {"LayerVertex": {"layerConf": {"layer": layer}}}
    mlp = lstm1(act, loss)
    layers = [c["layer"] for c in mlp["confs"]]
    return {
        "networkInputs": ["in"], "networkOutputs": ["2"],
        "vertices": {"0": lv(layers[0]), "1": lv(layers[1]),
                     "2": lv(layers[2])},
        "vertexInputs": {"0": ["in"], "1": ["0"], "2": ["1"]},
    }


GENERATIONS = {
    # generation → (activation dialect, loss dialect, has convolutionMode)
    "050": (_act_legacy, _loss_legacy, False),
    "060": (_act_legacy, _loss_legacy, False),
    "071": (_act_legacy, _loss_legacy, True),
    "080": (_act_080, _loss_080, True),
}


@pytest.mark.parametrize("gen", sorted(GENERATIONS))
class TestLegacyGenerations:
    def test_mlp1(self, gen):
        act, loss, _ = GENERATIONS[gen]
        check_mlp1(import_dl4j_configuration(json.dumps(mlp1(act, loss))))

    def test_mlp2(self, gen):
        act, loss, _ = GENERATIONS[gen]
        check_mlp2(import_dl4j_configuration(json.dumps(mlp2(act, loss))))

    def test_cnn1(self, gen):
        act, loss, cm = GENERATIONS[gen]
        check_cnn1(import_dl4j_configuration(json.dumps(cnn1(act, loss, cm))))

    def test_lstm1(self, gen):
        if gen == "050":
            pytest.skip("no 0.5.0 LSTM regression fixture in the reference")
        act, loss, _ = GENERATIONS[gen]
        check_lstm1(import_dl4j_configuration(json.dumps(lstm1(act, loss))))

    def test_cg_lstm1(self, gen):
        if gen == "050":
            pytest.skip("no 0.5.0 CG regression fixture in the reference")
        from deeplearning4j_tpu.modelimport.dl4j import (
            import_dl4j_graph_configuration)
        act, loss, _ = GENERATIONS[gen]
        conf = import_dl4j_graph_configuration(
            json.dumps(cg_lstm1(act, loss)))
        names = ["0", "1", "2"]
        l0 = conf.vertices[names[0]].obj
        l1 = conf.vertices[names[1]].obj
        l2 = conf.vertices[names[2]].obj
        assert isinstance(l0, GravesLSTMLayer) and l0.n_out == 4
        assert l0.gradient_normalization == "clip_element_wise_absolute_value"
        assert isinstance(l1, GravesBidirectionalLSTMLayer)
        assert l1.activation == "softsign"
        assert isinstance(l2, RnnOutputLayer) and l2.loss == "mcxent"


class TestLegacyNetsRun:
    """Beyond field equality: each generation's configs must build nets that
    actually run forward (the point of migration)."""

    @pytest.mark.parametrize("gen", sorted(GENERATIONS))
    def test_mlp2_trains(self, gen):
        import numpy as np
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        act, loss, _ = GENERATIONS[gen]
        conf = import_dl4j_configuration(json.dumps(mlp2(act, loss)))
        net = MultiLayerNetwork(conf).init()
        x = np.random.default_rng(0).normal(size=(8, 3)).astype(np.float32)
        y = np.random.default_rng(1).normal(size=(8, 5)).astype(np.float32)
        net.fit(x, y)
        assert np.isfinite(float(net.score_))

    def test_1x_era_idropout_object(self):
        # 1.0.0-beta dialect: iDropout as @class-tagged object
        from deeplearning4j_tpu.nn.dropout import AlphaDropout
        conf = import_dl4j_configuration(json.dumps({"confs": [
            {"layer": {"dense": {
                "activationFn": {"@class": "org.nd4j.linalg.activations.impl.ActivationSELU"},
                "nin": 3, "nout": 4,
                "iDropout": {"@class": "org.deeplearning4j.nn.conf.dropout.AlphaDropout",
                             "p": 0.8}}}},
            {"layer": {"output": {
                "activationFn": {"@class": "org.nd4j.linalg.activations.impl.ActivationSoftmax"},
                "lossFn": {"@class": "org.nd4j.linalg.lossfunctions.impl.LossMCXENT"},
                "nin": 4, "nout": 2,
                "iDropout": {"@class": "org.deeplearning4j.nn.conf.dropout.Dropout",
                             "p": 0.7}}}},
        ]}))
        assert isinstance(conf.layers[0].dropout, AlphaDropout)
        assert conf.layers[0].dropout.p == pytest.approx(0.8)
        assert conf.layers[1].dropout == pytest.approx(0.7)


class TestReviewDrivenFixes:
    def test_tuple_activation_json_round_trip(self):
        import numpy as np
        from deeplearning4j_tpu.nn.conf.network import MultiLayerConfiguration
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        conf = import_dl4j_configuration(json.dumps({"confs": [
            {"layer": {"dense": {"activationFn": "leakyrelu",
                                 "leakyreluAlpha": 0.3, "nin": 3, "nout": 4}}},
            {"layer": {"output": {"activationFn": "softmax",
                                  "lossFunction": "MCXENT",
                                  "nin": 4, "nout": 2}}},
        ]}))
        conf2 = MultiLayerConfiguration.from_json(conf.to_json())
        a = conf2.layers[0].activation
        assert a == ("leakyrelu", {"alpha": 0.3})
        net = MultiLayerNetwork(conf2).init()
        out = np.asarray(net.output(np.ones((2, 3), np.float32)))
        assert out.shape == (2, 2) and np.isfinite(out).all()

    def test_legacy_adamax_nadam_none_enums(self):
        from deeplearning4j_tpu.nn.updaters import AdaMax, Nadam, NoOp
        conf = import_dl4j_configuration(json.dumps({"confs": [
            {"layer": {"dense": {"activationFn": "relu", "nin": 2, "nout": 3,
                                 "updater": "ADAMAX", "learningRate": 0.1}}},
            {"layer": {"dense": {"activationFn": "relu", "nin": 3, "nout": 3,
                                 "updater": "NADAM", "learningRate": 0.2}}},
            {"layer": {"output": {"activationFn": "softmax",
                                  "lossFunction": "MCXENT", "nin": 3,
                                  "nout": 2, "updater": "NONE"}}},
        ]}))
        assert isinstance(conf.layers[0].updater, AdaMax)
        assert conf.layers[0].updater.learning_rate == pytest.approx(0.1)
        assert isinstance(conf.layers[1].updater, Nadam)
        assert isinstance(conf.layers[2].updater, NoOp)  # frozen, not default

    def test_extended_distributions(self):
        for cls, kind, extra in (
                ("TruncatedNormalDistribution", "truncated_normal",
                 {"mean": 0.0, "std": 0.5}),
                ("LogNormalDistribution", "log_normal",
                 {"mean": 0.0, "std": 0.5}),
                ("OrthogonalDistribution", "orthogonal", {"gain": 1.2}),
                ("ConstantDistribution", "constant", {"value": 0.25})):
            conf = import_dl4j_configuration(json.dumps({"confs": [
                {"layer": {"dense": {
                    "activationFn": "relu", "nin": 2, "nout": 3,
                    "weightInit": "DISTRIBUTION",
                    "dist": {"@class": f"org.deeplearning4j.nn.conf.distribution.{cls}",
                             **extra}}}},
                {"layer": {"output": {"activationFn": "softmax",
                                      "lossFunction": "MCXENT",
                                      "nin": 3, "nout": 2}}},
            ]}))
            assert conf.layers[0].distribution.kind == kind, cls

    def test_spatial_dropout_and_unknown_idropout_warns(self):
        import warnings
        from deeplearning4j_tpu.nn.dropout import SpatialDropout
        conf = import_dl4j_configuration(json.dumps({"confs": [
            {"layer": {"convolution": {"activationFn": "relu", "nin": 1,
                "nout": 2, "kernelSize": [3, 3],
                "iDropout": {"@class": "org.deeplearning4j.nn.conf.dropout.SpatialDropout",
                             "p": 0.8}}}},
            {"layer": {"output": {"activationFn": "softmax",
                                  "lossFunction": "MCXENT", "nout": 2}}},
        ]}))
        sd = conf.layers[0].dropout
        assert isinstance(sd, SpatialDropout) and sd.p == pytest.approx(0.8)
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            import_dl4j_configuration(json.dumps({"confs": [
                {"layer": {"dense": {"activationFn": "relu", "nin": 2,
                    "nout": 3,
                    "iDropout": {"@class": "x.y.FancyCustomDropout", "p": 0.5}}}},
                {"layer": {"output": {"activationFn": "softmax",
                                      "lossFunction": "MCXENT", "nin": 3,
                                      "nout": 2}}},
            ]}))
        assert any("iDropout" in str(x.message) for x in w)


class TestParameterizedActivationImport:
    def test_080_lrelu_alpha_preserved(self):
        conf = import_dl4j_configuration(json.dumps({"confs": [
            {"layer": {"dense": {
                "activationFn": {"@class": "org.nd4j.linalg.activations.impl.ActivationLReLU",
                                 "alpha": 0.3},
                "nin": 3, "nout": 4}}},
            {"layer": {"output": {"activationFn": "softmax",
                                  "lossFunction": "MCXENT",
                                  "nin": 4, "nout": 2}}},
        ]}))
        assert conf.layers[0].activation == ("leakyrelu", {"alpha": 0.3})

    def test_iupdater_string_dialect(self):
        # updater enum found under the NEW key name must still resolve
        conf = import_dl4j_configuration(json.dumps({"confs": [
            {"layer": {"dense": {"activationFn": "relu", "nin": 2, "nout": 3,
                                 "iUpdater": "RMSPROP", "learningRate": 0.15,
                                 "rmsDecay": 0.96}}},
            {"layer": {"output": {"activationFn": "softmax",
                                  "lossFunction": "MCXENT", "nin": 3,
                                  "nout": 2}}},
        ]}))
        assert isinstance(conf.layers[0].updater, RmsProp)
        assert conf.layers[0].updater.learning_rate == pytest.approx(0.15)

    def test_thresholdedrelu_theta_preserved(self):
        import numpy as np
        conf = import_dl4j_configuration(json.dumps({"confs": [
            {"layer": {"dense": {
                "activationFn": {"@class": "org.nd4j.linalg.activations.impl.ActivationThresholdedReLU",
                                 "theta": 0.4},
                "nin": 2, "nout": 3}}},
            {"layer": {"output": {"activationFn": "softmax",
                                  "lossFunction": "MCXENT",
                                  "nin": 3, "nout": 2}}},
        ]}))
        assert conf.layers[0].activation == ("thresholdedrelu", {"theta": 0.4})
        from deeplearning4j_tpu.nn import activations
        f = activations.resolve(conf.layers[0].activation)
        np.testing.assert_allclose(np.asarray(f(np.array([0.3, 0.5]))),
                                   [0.0, 0.5])
