"""Helper-seam tests: Pallas fused LSTM must match the built-in XLA path
(the reference's ValidateCudnnLSTM / CuDNNGradientChecks pattern)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.nn import helpers
from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import LSTMLayer, RnnOutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.pallas_kernels import PallasLSTMHelper


@pytest.fixture(autouse=True)
def _clean_registry():
    helpers.clear_all_helpers()
    yield
    helpers.clear_all_helpers()


def _net(seed=1):
    conf = (NeuralNetConfiguration.builder().seed(seed).list()
            .layer(LSTMLayer(n_out=24))
            .layer(RnnOutputLayer(n_out=4))
            .set_input_type(InputType.recurrent(8)).build())
    return MultiLayerNetwork(conf).init()


def _data(rng, b=8, t=12, c=8, k=4):
    x = rng.normal(size=(b, t, c)).astype(np.float32)
    y = np.eye(k, dtype=np.float32)[rng.integers(0, k, size=(b, t))]
    return x, y


class TestRegistry:
    def test_set_get_clear(self):
        h = PallasLSTMHelper(interpret=True)
        helpers.set_helper("lstm", h)
        assert helpers.get_helper("lstm") is h
        helpers.clear_helper("lstm")
        assert helpers.get_helper("lstm") is None

    def test_unknown_kind_raises(self):
        with pytest.raises(ValueError):
            helpers.set_helper("quantum", object())

    def test_supports_gating(self):
        h = PallasLSTMHelper(interpret=True)
        std = LSTMLayer(n_in=4, n_out=8)
        assert h.supports(std, None)
        assert not h.supports(std, np.ones((2, 3)))  # masked → built-in path
        from deeplearning4j_tpu.nn.layers import GravesLSTMLayer
        graves = GravesLSTMLayer(n_in=4, n_out=8)
        assert not h.supports(graves, None)  # peepholes → built-in path


class TestPallasLSTMEquivalence:
    def test_forward_matches_builtin(self, rng):
        """Same-math validation (ValidateCudnnLSTM pattern). Registration
        after a compiled call must still take effect (registry version is in
        the jit cache key) — and the helper must actually be consulted."""
        net = _net()
        x, _ = _data(rng)
        base = np.asarray(net.output(x))  # compiles the stock path first

        calls = []
        orig = PallasLSTMHelper.forward_seq

        class Spy(PallasLSTMHelper):
            def forward_seq(self, layer, params, xx, carry):
                calls.append(1)
                return orig(self, layer, params, xx, carry)

        helpers.set_helper("lstm", Spy(interpret=True))
        fused = np.asarray(net.output(x))
        assert calls, "helper was never consulted after registration"
        np.testing.assert_allclose(fused, base, rtol=2e-5, atol=2e-6)
        # clearing restores the stock path without manual cache clearing
        helpers.clear_helper("lstm")
        calls.clear()
        np.asarray(net.output(x))
        assert not calls

    def test_gradients_match_builtin(self, rng):
        """CuDNNGradientChecks pattern: grads through the helper == grads
        through the built-in path (custom_vjp reuses the reference scan)."""
        net = _net()
        x, y = _data(rng)
        g_base, loss_base = net.compute_gradient_and_score(x, y)
        helpers.set_helper("lstm", PallasLSTMHelper(interpret=True))
        g_fused, loss_fused = net.compute_gradient_and_score(x, y)
        assert abs(loss_base - loss_fused) < 1e-5
        for lb, lf in zip(g_base, g_fused):
            for k in lb:
                np.testing.assert_allclose(np.asarray(lf[k]), np.asarray(lb[k]),
                                           rtol=1e-4, atol=1e-6)

    def test_training_with_helper(self, rng):
        net = _net()
        helpers.set_helper("lstm", PallasLSTMHelper(interpret=True))
        from deeplearning4j_tpu.datasets.dataset import DataSet
        x, y = _data(rng, b=16)
        before = float(net.score(DataSet(x, y)))
        net.fit(DataSet(x, y))
        net.fit(DataSet(x, y))
        after = float(net.score(DataSet(x, y)))
        assert after < before

    def test_stateful_inference_carry(self, rng):
        """rnn_time_step carry flows through the fused kernel."""
        net = _net()
        x, _ = _data(rng, b=4, t=6)
        base_full = np.asarray(net.rnn_time_step(x))
        net.rnn_clear_previous_state()
        helpers.set_helper("lstm", PallasLSTMHelper(interpret=True))
        step1 = np.asarray(net.rnn_time_step(x[:, :3]))
        step2 = np.asarray(net.rnn_time_step(x[:, 3:]))
        fused_full = np.concatenate([step1, step2], axis=1)
        np.testing.assert_allclose(fused_full, base_full, rtol=2e-5, atol=2e-6)


class TestFlashAttentionHelper:
    def test_supports_gating(self):
        from deeplearning4j_tpu.nn.pallas_kernels import PallasFlashAttentionHelper
        h = PallasFlashAttentionHelper()
        on_tpu = jax.default_backend() == "tpu"
        # shape gate holds regardless of backend (backend gate may veto)
        assert h.supports(None, (2, 8, 256, 64), None, False) == on_tpu
        assert not h.supports(None, (2, 8, 200, 64), None, False)  # T % 128
        assert not h.supports(None, (2, 8, 256, 48), None, False)  # dh
        assert not h.supports(None, (2, 8, 256, 64), np.ones((2, 256)), False)
        assert not h.supports(None, (2, 8, 256, 64), None, True)  # dropout

    def test_matches_einsum_on_tpu(self, rng):
        if jax.default_backend() != "tpu":
            pytest.skip("flash attention kernel requires the TPU backend")
        from deeplearning4j_tpu.nn.pallas_kernels import PallasFlashAttentionHelper
        from deeplearning4j_tpu.nn.layers.attention import dot_product_attention
        q = jnp.asarray(rng.normal(size=(2, 4, 256, 64)).astype(np.float32))
        k = jnp.asarray(rng.normal(size=(2, 4, 256, 64)).astype(np.float32))
        v = jnp.asarray(rng.normal(size=(2, 4, 256, 64)).astype(np.float32))
        base = dot_product_attention(q, k, v)
        helpers.set_helper("attention", PallasFlashAttentionHelper())
        fused = dot_product_attention(q, k, v)
        np.testing.assert_allclose(np.asarray(fused), np.asarray(base),
                                   rtol=2e-2, atol=2e-3)


class TestCausalFlashAttentionHelper:
    """causal=True flash helper serves causal layers through the seam (the
    causal flag is part of the request since the decoder work); measured on
    v5e: 1.45x LM train step at T=2048, 2.64x at T=4096 (BASELINE.md)."""

    def test_causal_gating(self):
        from deeplearning4j_tpu.nn.pallas_kernels import PallasFlashAttentionHelper
        on_tpu = jax.default_backend() == "tpu"
        h = PallasFlashAttentionHelper(causal=True)
        assert h.supports(None, (2, 8, 256, 64), None, False,
                          causal=True) == on_tpu
        # a causal kernel must never serve a bidirectional request
        assert not h.supports(None, (2, 8, 256, 64), None, False)
        # and a non-causal kernel must never serve a causal one
        h2 = PallasFlashAttentionHelper()
        assert not h2.supports(None, (2, 8, 256, 64), None, False, causal=True)

    def test_causal_lm_outputs_unchanged_on_tpu(self, rng):
        if jax.default_backend() != "tpu":
            pytest.skip("flash attention kernel requires the TPU backend")
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        from deeplearning4j_tpu.nn.pallas_kernels import PallasFlashAttentionHelper
        from deeplearning4j_tpu.zoo.models import TransformerLM

        m = TransformerLM(vocab_size=100, max_length=256, n_layers=1,
                          d_model=128, n_heads=2, d_ff=256, seed=1)  # dh=64
        net = ComputationGraph(m.conf()).init()
        ids = rng.integers(0, 100, (2, 256)).astype(np.float32)
        ref = np.asarray(net.output(ids))

        calls = []

        class Spy(PallasFlashAttentionHelper):
            def attend(self, q, k, v):
                calls.append(q.shape)
                return super().attend(q, k, v)

        helpers.set_helper("attention", Spy(causal=True))
        try:
            out = np.asarray(net.output(ids))
        finally:
            helpers.clear_helper("attention")
        assert calls, "causal flash helper was never consulted"
        np.testing.assert_allclose(out, ref, rtol=2e-2, atol=2e-3)


class TestAutoFlashAttention:
    """With NO helper registered, causal attention at T >= 2048 auto-uses
    the causal flash kernel (opt-out via set_auto_flash_attention) — the
    measured LM-training win should not depend on knowing the seam exists."""

    def _spy(self, calls):
        class Spy:
            def supports(self, layer, q_shape, mask, dropout_active,
                         causal=False):
                return causal
            def attend(self, q, k, v):
                calls.append(q.shape)
                # distinguishable-but-wrong output is fine: only SELECTION
                # is under test here (numerics are covered on TPU above)
                return q * 0 + 7.0
        return Spy()

    def _qkv(self, t):
        import jax.numpy as jnp
        shape = (1, 2, t, 64)
        q = jnp.ones(shape, jnp.float32)
        return q, q, q

    def test_auto_used_in_win_region_only(self, monkeypatch):
        from deeplearning4j_tpu.nn.layers import attention as A
        calls = []
        monkeypatch.setattr(A, "_auto_flash_helper", lambda: self._spy(calls))
        q, k, v = self._qkv(2048)
        out = A.dot_product_attention(q, k, v, causal=True)
        assert len(calls) == 1 and float(out[0, 0, 0, 0]) == 7.0
        # below the threshold: einsum path
        q2, k2, v2 = self._qkv(1024)
        A.dot_product_attention(q2, k2, v2, causal=True)
        assert len(calls) == 1
        # non-causal: never auto (the kernel's semantics are causal)
        A.dot_product_attention(q, k, v, causal=False)
        assert len(calls) == 1

    def test_opt_out_and_version_bump(self, monkeypatch):
        from deeplearning4j_tpu.nn.layers import attention as A
        calls = []
        monkeypatch.setattr(A, "_auto_flash_helper", lambda: self._spy(calls))
        q, k, v = self._qkv(2048)
        v0 = helpers.version()
        helpers.set_auto_flash_attention(False)
        try:
            assert helpers.version() == v0 + 1  # compiled nets must retrace
            A.dot_product_attention(q, k, v, causal=True)
            assert not calls
        finally:
            helpers.set_auto_flash_attention(True)
        assert helpers.version() == v0 + 2
        A.dot_product_attention(q, k, v, causal=True)
        assert len(calls) == 1

    def test_registered_helper_takes_precedence(self, monkeypatch):
        from deeplearning4j_tpu.nn.layers import attention as A
        auto_calls, reg_calls = [], []
        monkeypatch.setattr(A, "_auto_flash_helper",
                            lambda: self._spy(auto_calls))
        helpers.set_helper("attention", self._spy(reg_calls))
        try:
            q, k, v = self._qkv(2048)
            A.dot_product_attention(q, k, v, causal=True)
            assert reg_calls and not auto_calls
        finally:
            helpers.clear_helper("attention")


def _mlp_net(updater, seed=5, width=48):
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    conf = (NeuralNetConfiguration.builder().seed(seed).updater(updater)
            .list()
            .layer(DenseLayer(n_out=width, activation="relu"))
            .layer(OutputLayer(n_out=4))
            .set_input_type(InputType.feed_forward(16)).build())
    return MultiLayerNetwork(conf).init()


def _mlp_data(rng, b=32):
    from deeplearning4j_tpu.datasets.dataset import DataSet
    x = rng.normal(size=(b, 16)).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, size=b)]
    return DataSet(x, y)


def _count_pallas_eqns(jaxpr):
    """pallas_call equations, recursing into pjit/scan/cond sub-jaxprs."""
    n = 0
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "pallas_call":
            n += 1
        for v in eqn.params.values():
            for u in (v if isinstance(v, (tuple, list)) else (v,)):
                inner = getattr(u, "jaxpr", u)
                if hasattr(inner, "eqns"):
                    n += _count_pallas_eqns(inner)
    return n


def _train_step_jaxpr(net, ds):
    fn = net._get_train_step(False)
    return jax.make_jaxpr(fn)(
        net.params, net.states, net.updater_states,
        jnp.float32(0.0), jnp.float32(0.0),
        jnp.asarray(np.asarray(ds.features)),
        jnp.asarray(np.asarray(ds.labels)),
        None, None, jax.random.PRNGKey(0), None).jaxpr


class TestPallasUpdaterHelper:
    """Fused optimizer-update kernel behind the "updater" helper seam: the
    whole param+m+v read-modify-write as ONE kernel over donated buffers.
    Same validation contract as the fused LSTM (ValidateCudnnLSTM pattern):
    numerics vs stock XLA, consult/clear behavior, launch-count oracle."""

    ALL_UPDATERS = "Sgd NoOp Nesterovs Adam AdaMax Nadam AMSGrad " \
                   "AdaGrad AdaDelta RmsProp".split()

    def test_supports_gating(self):
        from deeplearning4j_tpu.nn.pallas_kernels import PallasUpdaterHelper
        from deeplearning4j_tpu.nn.updaters import Adam, Sgd
        h = PallasUpdaterHelper()
        p = jnp.zeros((24, 16), jnp.float32)
        assert h.supports(Adam(1e-3), p, p)
        assert not h.supports(Sgd(1e-2), p, p)  # no state to fuse
        # EXACT types only: a subclass may override update() — its math is
        # unknown to the kernel, so it must take the stock path

        class TweakedAdam(Adam):
            pass

        assert not h.supports(TweakedAdam(1e-3), p, p)
        assert not h.supports(Adam(1e-3), p.astype(jnp.bfloat16),
                              p.astype(jnp.bfloat16))
        assert not h.supports(Adam(1e-3), p, jnp.zeros((24, 8), jnp.float32))

    @pytest.mark.parametrize("name", ALL_UPDATERS)
    def test_matches_stock_every_updater(self, rng, name):
        """Twin nets, 3 identical steps: fused-registered params must land
        on the stock-path params within 2e-5 for EVERY shipped updater —
        fused classes agree through the kernel, the rest must be untouched
        by the seam (exact fallback)."""
        import deeplearning4j_tpu.nn.updaters as U
        from deeplearning4j_tpu.nn.pallas_kernels import PallasUpdaterHelper
        upd = getattr(U, name)(1e-2)
        ds = _mlp_data(rng)
        stock = _mlp_net(upd)
        fused = _mlp_net(upd)
        for _ in range(3):
            stock._fit_batch(ds)
        helpers.set_helper("updater", PallasUpdaterHelper())
        for _ in range(3):
            fused._fit_batch(ds)
        for lb, lf in zip(stock.params, fused.params):
            for k in lb:
                np.testing.assert_allclose(
                    np.asarray(lf[k]), np.asarray(lb[k]),
                    rtol=2e-5, atol=2e-5,
                    err_msg=f"{name}: fused diverged from stock on {k}")

    def test_consulted_and_clear_restores_stock(self, rng):
        from deeplearning4j_tpu.nn.pallas_kernels import PallasUpdaterHelper
        from deeplearning4j_tpu.nn.updaters import Adam
        net = _mlp_net(Adam(1e-3))
        ds = _mlp_data(rng)
        net._fit_batch(ds)  # compiles the stock step first

        calls = []

        class Spy(PallasUpdaterHelper):
            def apply(self, updater, param, grad, state, lr, t):
                calls.append(param.shape)
                return super().apply(updater, param, grad, state, lr, t)

        helpers.set_helper("updater", Spy())
        net._fit_batch(ds)
        # consulted once per fusable tensor (w+b per layer), despite the
        # already-compiled stock step: registry version keys the jit cache
        assert len(calls) == 4
        helpers.clear_helper("updater")
        calls.clear()
        net._fit_batch(ds)
        assert not calls

    def test_one_kernel_launch_per_tensor(self, rng):
        """HLO/compile-count oracle: with the fused updater registered the
        train step carries exactly ONE pallas_call per fusable parameter
        tensor — and none at all without it (no silent leftovers)."""
        from deeplearning4j_tpu.nn.pallas_kernels import PallasUpdaterHelper
        from deeplearning4j_tpu.nn.updaters import Adam
        net = _mlp_net(Adam(1e-3))
        ds = _mlp_data(rng)
        assert _count_pallas_eqns(_train_step_jaxpr(net, ds)) == 0
        helpers.set_helper("updater", PallasUpdaterHelper())
        assert _count_pallas_eqns(_train_step_jaxpr(net, ds)) == 4

    def test_nonsquare_and_vector_params_pad_correctly(self, rng):
        """The (R,128) lane-tiling flattens/zero-pads every shape; padding
        must never leak into the real elements (Adam math is closed under
        zero rows: 0-grad 0-state rows stay 0)."""
        from deeplearning4j_tpu.nn.pallas_kernels import PallasUpdaterHelper
        from deeplearning4j_tpu.nn.updaters import Adam
        h = PallasUpdaterHelper(interpret=True)
        u = Adam(1e-3)
        rng_np = np.random.default_rng(3)
        for shape in ((5,), (3, 7), (129,), (130, 257)):
            p = jnp.asarray(rng_np.normal(size=shape).astype(np.float32))
            g = jnp.asarray(rng_np.normal(size=shape).astype(np.float32))
            state = {"m": jnp.zeros_like(p), "v": jnp.zeros_like(p)}
            upd_ref, s_ref = u.update(g, state, 1e-3, 1.0)
            p_ref = p - upd_ref
            p_new, s_new = h.apply(u, p, g, state, 1e-3, 1.0)
            assert p_new.shape == p.shape
            np.testing.assert_allclose(np.asarray(p_new), np.asarray(p_ref),
                                       rtol=2e-5, atol=2e-6)
            for k in s_ref:
                np.testing.assert_allclose(
                    np.asarray(s_new[k]), np.asarray(s_ref[k]),
                    rtol=2e-5, atol=2e-6)


class TestAutoFusedLSTM:
    """With NO helper registered, LSTM forward at T >= 256 and lane-aligned
    modest H auto-uses the fused kernel (opt-out via set_auto_fused_lstm) —
    the same promotion pattern as the causal-flash auto fallback."""

    def _spy(self, calls):
        class Spy:
            def supports(self, layer, mask):
                return mask is None

            def forward_seq(self, layer, params, x, carry):
                calls.append(x.shape)
                # distinguishable-but-wrong output: only SELECTION is under
                # test (numerics are covered by TestPallasLSTMEquivalence)
                return jnp.zeros(x.shape[:2] + (layer.n_out,)) + 7.0, carry
        return Spy()

    def _layer(self, h=128):
        layer = LSTMLayer(n_in=8, n_out=h)
        params = layer.init_params(jax.random.PRNGKey(0))
        return layer, params

    def test_win_region_predicate(self):
        from deeplearning4j_tpu.nn.layers import recurrent as R
        x = np.zeros((2, 256, 8), np.float32)
        short = np.zeros((2, 128, 8), np.float32)
        assert R._auto_lstm_win_region(LSTMLayer(n_in=8, n_out=128), x)
        assert R._auto_lstm_win_region(LSTMLayer(n_in=8, n_out=256), x)
        assert not R._auto_lstm_win_region(LSTMLayer(n_in=8, n_out=128), short)
        assert not R._auto_lstm_win_region(LSTMLayer(n_in=8, n_out=96), x)
        assert not R._auto_lstm_win_region(LSTMLayer(n_in=8, n_out=384), x)

    def test_auto_used_in_win_region_only(self, monkeypatch):
        from deeplearning4j_tpu.nn.layers import recurrent as R
        calls = []
        monkeypatch.setattr(R, "_auto_lstm_helper", lambda: self._spy(calls))
        layer, params = self._layer()
        x = jnp.ones((2, 256, 8), jnp.float32)
        y, _ = layer.forward_seq(params, x)
        assert len(calls) == 1 and float(y[0, 0, 0]) == 7.0
        # below the threshold: the stock scan path
        y2, _ = layer.forward_seq(params, jnp.ones((2, 16, 8), jnp.float32))
        assert len(calls) == 1 and float(y2[0, 0, 0]) != 7.0
        # masked sequences: the helper's supports() veto is honored
        layer.forward_seq(params, x, mask=jnp.ones((2, 256), jnp.float32))
        assert len(calls) == 1

    def test_opt_out_and_version_bump(self, monkeypatch):
        from deeplearning4j_tpu.nn.layers import recurrent as R
        calls = []
        monkeypatch.setattr(R, "_auto_lstm_helper", lambda: self._spy(calls))
        layer, params = self._layer()
        x = jnp.ones((2, 256, 8), jnp.float32)
        v0 = helpers.version()
        helpers.set_auto_fused_lstm(False)
        try:
            assert helpers.version() == v0 + 1  # compiled nets must retrace
            layer.forward_seq(params, x)
            assert not calls
        finally:
            helpers.set_auto_fused_lstm(True)
        assert helpers.version() == v0 + 2
        layer.forward_seq(params, x)
        assert len(calls) == 1

    def test_registered_helper_takes_precedence(self, monkeypatch):
        from deeplearning4j_tpu.nn.layers import recurrent as R
        auto_calls, reg_calls = [], []
        monkeypatch.setattr(R, "_auto_lstm_helper",
                            lambda: self._spy(auto_calls))
        helpers.set_helper("lstm", self._spy(reg_calls))
        layer, params = self._layer()
        layer.forward_seq(params, jnp.ones((2, 256, 8), jnp.float32))
        assert reg_calls and not auto_calls

    def test_off_tpu_factory_declines(self):
        from deeplearning4j_tpu.nn.layers import recurrent as R
        if jax.default_backend() == "tpu":
            assert R._auto_lstm_helper() is not None
        else:
            # interpret-mode would be a slowdown, not a win — never auto
            assert R._auto_lstm_helper() is None
