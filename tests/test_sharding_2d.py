"""GSPMD 2-D parallelism: rule-based sharding, the zero-all-gather vocab
path, resharded restore, sharded serving.

The oracle throughout is the compiled HLO itself (the same surface
``bench.py --sharding-2d`` records into ``MULTICHIP_r07.json``): on a
DP×MP mesh the Megatron rule set must produce a forward with ZERO
all-gathers — a row-sharded embedding ``take`` in, column-sharded logits
with LSE cross-entropy out. Rule semantics follow the fmengine/EasyLM
``match_partition_rules`` pattern: first regex match over the
'/'-joined param path wins, scalars never partition, unmatched paths
fail loudly.
"""

import json
import os
import re
import subprocess
import sys
import threading

import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.parallel.mesh import (format_mesh_axes, make_mesh,
                                              parse_mesh_axes)
from deeplearning4j_tpu.parallel.sharding import (
    DEFAULT_2D_RULES, P, lint_partition_rules, load_sharding_rules,
    match_partition_rules, place_batch, shard_model_with_rules)
from deeplearning4j_tpu.zoo.models import TransformerLM, lm_labels

VOCAB, T, BATCH = 64, 8, 8

_COLLECTIVE = re.compile(
    r"\b(all-gather|all-reduce|reduce-scatter|collective-permute"
    r"|all-to-all)\b")


def tiny_lm_2d(mesh=None, rules=None, seed=7):
    """1-layer LM whose dims divide every mesh used here (model axis up
    to 4: vocab 64, d_model 16, heads 4, d_ff 32)."""
    net = TransformerLM(vocab_size=VOCAB, max_length=T, n_layers=1,
                        d_model=16, n_heads=4, d_ff=32, seed=seed).init()
    if mesh is not None:
        shard_model_with_rules(net, mesh, rules)
    return net


def lm_batch(seed=3):
    import jax.numpy as jnp
    rng = np.random.default_rng(seed)
    toks = rng.integers(0, VOCAB, size=(BATCH, T))
    return DataSet(toks.astype(np.float32),
                   np.asarray(lm_labels(jnp.asarray(toks), VOCAB)))


def collective_counts(hlo):
    counts = {}
    for m in _COLLECTIVE.finditer(hlo):
        counts[m.group(1)] = counts.get(m.group(1), 0) + 1
    return counts


def forward_hlo(net, ds, mesh):
    import jax.numpy as jnp
    xj = place_batch(jnp.asarray(np.asarray(ds.features)), mesh)
    return net._output_fn().lower(net.params, net.states, {"tokens": xj},
                                  None).compile().as_text()


def step_hlo(net, ds, mesh):
    import jax.numpy as jnp
    step = net._get_train_step()
    it, ep, rng_k = net._device_tick()
    xj = place_batch(jnp.asarray(np.asarray(ds.features)), mesh)
    yj = place_batch(jnp.asarray(np.asarray(ds.labels)), mesh)
    return step.lower(net.params, net.states, net.updater_states, it, ep,
                      {"tokens": xj}, [yj], None, None,
                      rng_k).compile().as_text()


def leaf_paths(params):
    import jax
    return {"/".join(str(getattr(k, "key", getattr(k, "idx", k)))
                     for k in path): leaf
            for path, leaf in
            jax.tree_util.tree_flatten_with_path(params)[0]}


# ----------------------------------------------------------- rule matching
class TestMatchPartitionRules:
    def test_first_match_wins(self):
        params = {"block/ff1": {"W": np.zeros((4, 8))}}
        specs = match_partition_rules(
            [("ff1/W", P(None, "model")), (".*", P())], params)
        assert specs["block/ff1"]["W"] == P(None, "model")
        # reversed order: the catch-all shadows the specific rule
        specs = match_partition_rules(
            [(".*", P()), ("ff1/W", P(None, "model"))], params)
        assert specs["block/ff1"]["W"] == P()

    def test_scalar_leaves_never_partitioned(self):
        params = {"layer": {"W": np.zeros((4, 4)), "step": np.float32(3.0),
                            "one": np.zeros((1,))}}
        specs = match_partition_rules([(".*", P("model"))], params)
        assert specs["layer"]["W"] == P("model")
        assert specs["layer"]["step"] == P()   # 0-d: never partitioned
        assert specs["layer"]["one"] == P()    # size-1: never partitioned

    def test_unmatched_path_fails_loudly(self):
        params = {"embed": {"W": np.zeros((8, 4))}}
        with pytest.raises(ValueError, match="Partition rule not found"):
            match_partition_rules([("ff1/W", P())], params)

    def test_default_rules_cover_transformer_lm(self):
        net = tiny_lm_2d()
        specs = leaf_paths(match_partition_rules(DEFAULT_2D_RULES,
                                                 net.params))
        embed = [s for n, s in specs.items() if "embed" in n and
                 n.endswith("/W")]
        out_w = [s for n, s in specs.items()
                 if re.search(r"(out|output|logits|lm_head)[^/]*/W$", n)]
        assert embed and all(s == P("model", None) for s in embed)
        assert out_w and all(s == P(None, "model") for s in out_w)

    def test_lint_flags_unmatched_dead_and_shadowed(self):
        params = {"embed": {"W": np.zeros((8, 4))},
                  "out": {"W": np.zeros((4, 8))}}
        warnings = lint_partition_rules(
            [("embed/W", P("model", None)),   # live
             ("qkv/W", P(None, "model")),     # dead: matches nothing
             ("embed/.*", P())],              # fully shadowed by rule 0
            params)
        text = "\n".join(warnings)
        assert "'out/W' matches no rule" in text
        assert "matches no param" in text and "qkv/W" in text
        assert "fully shadowed" in text
        # the shipped default set lints clean against the LM it targets
        assert lint_partition_rules(DEFAULT_2D_RULES,
                                    tiny_lm_2d().params) == []

    def test_load_sharding_rules_schema(self, tmp_path):
        spec = {"rules": [["embed/W$", ["model", None]], [".*", []]]}
        rules = load_sharding_rules(spec)
        assert rules[0][1] == P("model", None)
        assert rules[1][1] == P()
        path = tmp_path / "rules.json"
        path.write_text(json.dumps(spec))
        assert load_sharding_rules(str(path)) == rules
        with pytest.raises(ValueError):
            load_sharding_rules({"rules": [["(unclosed", []]]})
        with pytest.raises((ValueError, KeyError, TypeError)):
            load_sharding_rules({"rules": "not-a-list"})


# ------------------------------------------------------------ mesh grammar
class TestMeshGrammar:
    def test_parse_format_round_trip(self):
        axes = parse_mesh_axes("data=4,model=2")
        assert axes == {"data": 4, "model": 2}
        assert format_mesh_axes(axes) == "data=4,model=2"
        assert parse_mesh_axes("data=-1,model=2") == {"data": -1,
                                                      "model": 2}

    @pytest.mark.parametrize("bad", ["", "data", "data=x", "data=0",
                                     "data=4,data=2", "data=-1,model=-1",
                                     "data=-2"])
    def test_rejects_bad_specs(self, bad):
        with pytest.raises(ValueError):
            parse_mesh_axes(bad)

    def test_make_mesh_infers_one_axis(self):
        mesh = make_mesh(parse_mesh_axes("data=-1,model=2"))
        assert dict(mesh.shape) == {"data": 4, "model": 2}

    def test_2d_rules_on_data_only_mesh_degrade_to_replicated(self):
        # naming an absent axis must not KeyError — the leaf replicates,
        # same as a non-dividing dim (bench's dp8 baseline relies on it)
        mesh = make_mesh({"data": 8})
        net = tiny_lm_2d(mesh=mesh)  # DEFAULT_2D_RULES name "model"
        for v in leaf_paths(net.params).values():
            assert v.sharding.spec == P()


# --------------------------------------------- vocab-path HLO oracle tests
class TestVocabPathHLO:
    """The acceptance oracle: compiled-HLO collective counts on the
    8-device CPU mesh (conftest forces it)."""

    def test_forward_zero_all_gathers(self):
        mesh = make_mesh({"data": 4, "model": 2})
        net, ds = tiny_lm_2d(mesh=mesh), lm_batch()
        counts = collective_counts(forward_hlo(net, ds, mesh))
        assert counts.get("all-gather", 0) == 0
        # ...and the model really is sharded: row-parallel partial sums
        # surface as all-reduces, not as a gather of replicated params
        assert counts.get("all-reduce", 0) > 0

    def test_forward_zero_all_gathers_after_fit(self):
        # placement-pinning regression: one train step must leave params
        # exactly where the rules put them (GSPMD picking its own output
        # shardings for the updated params would re-introduce gathers)
        mesh = make_mesh({"data": 4, "model": 2})
        net, ds = tiny_lm_2d(mesh=mesh), lm_batch()
        net.fit(ds)
        emb = [v for n, v in leaf_paths(net.params).items()
               if "embed" in n and n.endswith("/W")]
        assert emb and emb[0].sharding.spec == P("model", None)
        counts = collective_counts(forward_hlo(net, ds, mesh))
        assert counts.get("all-gather", 0) == 0

    def test_train_step_zero_all_gathers(self):
        mesh = make_mesh({"data": 2, "model": 4})
        net, ds = tiny_lm_2d(mesh=mesh), lm_batch()
        counts = collective_counts(step_hlo(net, ds, mesh))
        assert counts.get("all-gather", 0) == 0
        assert counts.get("all-reduce", 0) > 0  # grad sync over data


# --------------------------------------------------- end-to-end DP×MP fit
class TestEndToEnd2D:
    def test_graph_2d_fit_matches_replicated(self):
        ds = lm_batch()
        ref = tiny_lm_2d(seed=11)
        net = tiny_lm_2d(mesh=make_mesh({"data": 4, "model": 2}), seed=11)
        for _ in range(2):
            ref.fit(ds)
            net.fit(ds)
        assert np.isfinite(float(net.score_))
        assert float(net.score_) == pytest.approx(float(ref.score_),
                                                  abs=1e-4)
        ref_p, net_p = leaf_paths(ref.params), leaf_paths(net.params)
        assert set(ref_p) == set(net_p)
        for name in ref_p:
            np.testing.assert_allclose(np.asarray(net_p[name]),
                                       np.asarray(ref_p[name]), atol=2e-5,
                                       err_msg=name)
        out = np.asarray(net.output(ds.features))
        np.testing.assert_allclose(out, np.asarray(ref.output(ds.features)),
                                   atol=1e-4)

    def test_mln_2d_fit_honors_rules(self):
        from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        conf = (NeuralNetConfiguration.builder().seed(5).list()
                .layer(DenseLayer(n_in=12, n_out=16, activation="tanh"))
                .layer(OutputLayer(n_in=16, n_out=4, activation="softmax",
                                   loss="negativeloglikelihood"))
                .build())
        net = MultiLayerNetwork(conf).init()
        mesh = make_mesh({"data": 4, "model": 2})
        # Megatron pair over the hidden layer: column then row
        shard_model_with_rules(net, mesh, [
            ("(^|/)0/W$", P(None, "model")), ("(^|/)0/b$", P("model")),
            ("(^|/)1/W$", P("model", None)), (".*", P())])
        placed = leaf_paths(net.params)
        assert placed["0/W"].sharding.spec == P(None, "model")
        assert placed["1/W"].sharding.spec == P("model", None)
        rng = np.random.default_rng(0)
        x = rng.standard_normal((BATCH, 12)).astype(np.float32)
        y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, BATCH)]
        net.fit(DataSet(x, y))
        assert np.isfinite(float(net.score_))
        # pinning holds on MLN too
        assert leaf_paths(net.params)["0/W"].sharding.spec == \
            P(None, "model")
        assert np.asarray(net.output(x)).shape == (BATCH, 4)


# ----------------------------------------------- Keras-imported BERT, 2-D
class TestKerasBert2D:
    def _bert(self, keras, vocab=128, t=12, d=64, heads=16, ff=256,
              blocks=2):
        """BERT-large's architecture family (post-LN encoder: fused-QKV
        MHA + GELU 4x FFN + token-embedding in, vocab-projection out) at
        CI dims; layer names target the shipped DEFAULT_2D_RULES."""
        kl = keras.layers
        inp = kl.Input((t,), name="tokens")
        h = kl.Embedding(vocab, d, name="embed")(inp)
        for i in range(blocks):
            att = kl.MultiHeadAttention(num_heads=heads,
                                        key_dim=d // heads,
                                        name=f"mha{i}")(h, h)
            h = kl.LayerNormalization(name=f"ln_a{i}")(
                kl.Add(name=f"res_a{i}")([h, att]))
            f = kl.Dense(ff, activation="gelu", name=f"ff1_{i}")(h)
            f = kl.Dense(d, name=f"ff2_{i}")(f)
            h = kl.LayerNormalization(name=f"ln_f{i}")(
                kl.Add(name=f"res_f{i}")([h, f]))
        out = kl.Dense(vocab, activation="softmax", name="lm_head")(h)
        return keras.Model(inp, out)

    def test_imported_bert_trains_2d_zero_all_gather_vocab(self, tmp_path):
        keras = pytest.importorskip("keras")
        from deeplearning4j_tpu.modelimport.keras import KerasModelImport
        m = self._bert(keras)
        m.compile(loss="categorical_crossentropy", optimizer="sgd")
        path = str(tmp_path / "bert.h5")
        m.save(path)
        net = KerasModelImport.import_keras_model_and_weights(path)
        mesh = make_mesh({"data": 4, "model": 2})
        shard_model_with_rules(net, mesh)  # the shipped Megatron rules
        placed = leaf_paths(net.params)
        emb = [v for n, v in placed.items()
               if "embed" in n and n.endswith("/W")]
        head = [v for n, v in placed.items()
                if "lm_head" in n and n.endswith("/W")]
        assert emb[0].sharding.spec == P("model", None)   # row: take
        assert head[0].sharding.spec == P(None, "model")  # column: logits

        rng = np.random.default_rng(4)
        toks = rng.integers(0, 128, size=(BATCH, 12)).astype(np.float32)
        y = np.eye(128, dtype=np.float32)[
            rng.integers(0, 128, size=(BATCH, 12))]
        ds = DataSet(toks, y)
        net.fit(ds)
        first = float(net.score_)
        for _ in range(3):
            net.fit(ds)
        assert np.isfinite(first) and float(net.score_) < first

        # the vocab path of the IMPORTED model compiles gather-free,
        # after training (placement pinning) — the acceptance oracle
        import jax.numpy as jnp
        xj = place_batch(jnp.asarray(toks), mesh)
        hlo = net._output_fn().lower(net.params, net.states,
                                     {"tokens": xj},
                                     None).compile().as_text()
        counts = collective_counts(hlo)
        assert counts.get("all-gather", 0) == 0
        assert counts.get("all-reduce", 0) > 0


# ------------------------------------------------------- resharded restore
class TestReshardedRestore:
    def test_2x4_save_restores_onto_1x4(self, tmp_path):
        """A host-failure shrink: save on data=2×model=4, restore onto
        data=1×model=4; one further step must equal a clean resume."""
        from deeplearning4j_tpu.util.orbax_checkpoint import (restore_model,
                                                              save_model)
        ds = lm_batch()
        net = tiny_lm_2d(mesh=make_mesh({"data": 2, "model": 4}), seed=13)
        for _ in range(2):
            net.fit(ds)
        save_model(net, str(tmp_path / "ckpt"))

        clean = restore_model(str(tmp_path / "ckpt"))  # replicated resume
        shrunk_mesh = make_mesh({"data": 1, "model": 4})
        shrunk = restore_model(str(tmp_path / "ckpt"), mesh=shrunk_mesh,
                               sharding_rules=None)
        # restored STRAIGHT INTO the rule placement on the shrunk mesh
        emb = [v for n, v in leaf_paths(shrunk.params).items()
               if "embed" in n and n.endswith("/W")][0]
        assert emb.sharding.spec == P("model", None)
        assert dict(emb.sharding.mesh.shape) == {"data": 1, "model": 4}

        clean.fit(ds)
        shrunk.fit(ds)
        c_p, s_p = leaf_paths(clean.params), leaf_paths(shrunk.params)
        assert set(c_p) == set(s_p)
        for name in c_p:
            np.testing.assert_allclose(np.asarray(s_p[name]),
                                       np.asarray(c_p[name]), atol=2e-5,
                                       err_msg=name)


# --------------------------------------------------------- sharded serving
class TestShardedServing:
    def _dense(self, seed):
        from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
        conf = (NeuralNetConfiguration.builder().seed(seed).list()
                .layer(DenseLayer(n_in=12, n_out=16, activation="tanh"))
                .layer(OutputLayer(n_in=16, n_out=4, activation="softmax",
                                   loss="negativeloglikelihood"))
                .build())
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        return MultiLayerNetwork(conf).init()

    def test_sharded_register_warmup_hot_swap_zero_compiles(self):
        """Register a version GSPMD-sharded, warm it, hot-swap to a
        second sharded version UNDER LOAD — buckets round to the data
        axis and the steady state after the swap compiles nothing."""
        from deeplearning4j_tpu.observe import (Tracer, disable_tracing,
                                                enable_tracing)
        from deeplearning4j_tpu.serving import ModelRegistry
        mesh = make_mesh({"data": 4, "model": 2})
        rules = [("(^|/)0/W$", P(None, "model")),
                 ("(^|/)1/W$", P("model", None)), (".*", P())]
        tr = enable_tracing(Tracer())
        reg = ModelRegistry(max_batch_size=8, warmup="sync")
        try:
            v1 = reg.register("clf", self._dense(1), mesh=mesh,
                              sharding_rules=rules, input_shape=(12,))
            served = reg._models["clf"]
            # buckets rounded to the data-axis size
            assert all(b % 4 == 0 for b in served.inference.buckets)
            assert served.describe()["versions"][0]["mesh"] == \
                {"data": 4, "model": 2}
            x = np.zeros((3, 12), np.float32)
            assert reg.predict("clf", x).shape == (3, 4)

            stop = threading.Event()
            errors = []

            def hammer():
                while not stop.is_set():
                    try:
                        reg.predict("clf", x)
                    except Exception as e:  # pragma: no cover - fail loud
                        errors.append(e)
                        return

            t = threading.Thread(target=hammer)
            t.start()
            try:
                # sync warmup compiles v2's buckets BEFORE activation —
                # the swap lands on an already-compiled forward
                v2 = reg.register("clf", self._dense(2), mesh=mesh,
                                  sharding_rules=rules, input_shape=(12,))
            finally:
                stop.set()
                t.join(30.0)
            assert not errors
            assert v2 == v1 + 1
            assert served.describe()["current_version"] == v2
            # steady state: every bucket is warm, nothing compiles
            baseline = tr.compile_count
            for n in (1, 3, 4, 8):
                out = reg.predict("clf", np.zeros((n, 12), np.float32))
                assert out.shape == (n, 4)
            assert tr.compile_count == baseline
        finally:
            reg.shutdown()
            disable_tracing()

    def test_sharded_register_rejects_quantized_policy(self):
        from deeplearning4j_tpu.serving import ModelRegistry
        reg = ModelRegistry(warmup="off")
        try:
            with pytest.raises(ValueError, match="float32"):
                reg.register("q", self._dense(3),
                             mesh=make_mesh({"data": 4, "model": 2}),
                             dtype_policy="int8")
        finally:
            reg.shutdown()


# ---------------------------------------------------- pod-mesh plumbing
class TestPodMeshSpec:
    def test_worker_spec_mesh_slice(self):
        from deeplearning4j_tpu.parallel.elastic import WorkerSpec
        spec = WorkerSpec(argv=["x"], mesh_axes={"model": 2},
                          env={"XLA_FLAGS":
                               "--xla_force_host_platform_device_count=8 "
                               "--xla_dump_to=/tmp/d"})
        assert spec.local_mesh_devices() == 2
        flags = spec.environment()["XLA_FLAGS"]
        # the parent's 8-device multiplier is replaced by the slice size;
        # unrelated operator flags survive
        assert "--xla_force_host_platform_device_count=2" in flags
        assert flags.count("device_count") == 1
        assert "--xla_dump_to=/tmp/d" in flags
        # classic one-device worker: the multiplier is stripped outright
        one = WorkerSpec(argv=["x"], env={
            "XLA_FLAGS": "--xla_force_host_platform_device_count=8"})
        assert one.local_mesh_devices() == 1
        assert "XLA_FLAGS" not in one.environment()

    def test_worker_context_pod_mesh_axes(self, tmp_path):
        from deeplearning4j_tpu.parallel import elastic
        from deeplearning4j_tpu.parallel.elastic import ElasticWorkerContext
        env = {
            elastic.ENV_COORDINATOR: "127.0.0.1:999",
            elastic.ENV_NUM_PROCESSES: "3",
            elastic.ENV_PROCESS_ID: "1",
            elastic.ENV_SLOT: "1",
            elastic.ENV_GENERATION: "2",
            elastic.ENV_TOKEN: "g2-abc",
            elastic.ENV_CKPT_DIR: str(tmp_path),
            elastic.ENV_HEARTBEAT: str(tmp_path / "hb"),
            elastic.ENV_MESH: "model=2",
            elastic.ENV_SHARDING_RULES: "/tmp/rules.json",
        }
        ctx = ElasticWorkerContext.from_env(env)
        assert ctx.mesh_axes == {"model": 2}
        assert ctx.sharding_rules_path == "/tmp/rules.json"
        # data spans the generation's processes; model lives in-host
        assert ctx.pod_mesh_axes() == {"data": 3, "model": 2}
        env.pop(elastic.ENV_MESH)
        env.pop(elastic.ENV_SHARDING_RULES)
        ctx = ElasticWorkerContext.from_env(env)
        assert ctx.mesh_axes is None
        assert ctx.pod_mesh_axes() == {"data": 3}


# ------------------------------------------------- committed bench record
@pytest.mark.smoke
class TestMultichipR07Check:
    """The committed MULTICHIP_r07 series must keep passing its own
    --check (same pattern as BENCH_TRAIN in the smoke tier): schema +
    collective-count consistency, plus the zero-all-gather vocab-path
    invariant re-proven LIVE before and after a train step."""

    REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    COMMITTED = os.path.join(REPO, "MULTICHIP_r07.json")

    def test_committed_record_schema(self):
        with open(self.COMMITTED, encoding="utf-8") as fh:
            rec = json.load(fh)
        assert rec["metric"] == "sharding_2d"
        assert rec["series"] == "MULTICHIP_r07"
        cfgs = rec["configs"]
        assert set(cfgs) == {"dp8", "dp4_mp2", "dp2_mp4"}
        for name, cfg in cfgs.items():
            assert cfg["wall_ms_per_step"] > 0
            assert cfg["forward"]["all_gather"] == 0
            if name != "dp8":  # 2-D: grads sync AND rows partial-sum
                assert cfg["train_step"]["all_reduce"] > 0

    def test_check_passes(self):
        proc = subprocess.run(
            [sys.executable, os.path.join(self.REPO, "bench.py"),
             "--sharding-2d", "--check", self.COMMITTED],
            env=dict(os.environ, JAX_PLATFORMS="cpu"), cwd=self.REPO,
            capture_output=True, text=True, timeout=560)
        assert proc.returncode == 0, proc.stderr + proc.stdout
        assert "sharding-2d check OK" in proc.stdout


# ------------------------------------------------------------ CLI contract
class TestCliValidation:
    ARGS = ["--modelPath", "/nonexistent/m.zip",
            "--dataPath", "/nonexistent/d.npz",
            "--modelOutputPath", "/nonexistent/out.zip"]

    def _train(self, extra):
        from deeplearning4j_tpu.cli import parallel_wrapper_main
        with pytest.raises(SystemExit) as exc:
            parallel_wrapper_main(self.ARGS + extra)
        assert exc.value.code == 2

    def test_train_rejects_bad_mesh_grammar(self, capsys):
        self._train(["--mesh", "data=4,model"])
        assert "--mesh" in capsys.readouterr().err

    def test_train_rejects_workers_plus_mesh(self, capsys):
        self._train(["--mesh", "data=4", "--workers", "4"])
        assert "both size the data axis" in capsys.readouterr().err

    def test_train_rejects_rules_without_mesh(self, capsys):
        self._train(["--sharding-rules", "/tmp/rules.json"])
        assert "needs --mesh" in capsys.readouterr().err

    def test_train_rejects_unreadable_rules(self, capsys):
        self._train(["--mesh", "data=4,model=2",
                     "--sharding-rules", "/nonexistent/rules.json"])
        assert "--sharding-rules" in capsys.readouterr().err

    def test_elastic_rejects_pinned_data_axis(self, tmp_path, capsys):
        self._train(["--elastic", "2", "--ckpt-dir", str(tmp_path),
                     "--mesh", "data=4,model=2"])
        assert "cannot be pinned" in capsys.readouterr().err

    def test_serve_rejects_mesh_plus_quantization(self, capsys):
        from deeplearning4j_tpu.cli import serve_main
        with pytest.raises(SystemExit) as exc:
            serve_main(["--model", "m=/nonexistent/m.zip",
                        "--mesh", "data=4,model=2",
                        "--dtype-policy", "m=int8"], block=False)
        assert exc.value.code == 2
        assert "float32-only" in capsys.readouterr().err
