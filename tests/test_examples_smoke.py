"""Examples smoke tier (VERDICT r4 Weak #4): every runnable script under
``examples/`` must exit 0, so four rounds of API evolution can never
silently rot them again. Each example runs as its own subprocess on the
virtual 8-device CPU mesh (the same environment the rest of the suite
uses) with a hard per-script timeout.

Measured runtimes on the 1-core CI host range 5 s (02) to ~4 min (12,
the serving example's TTL windows); the tier totals ~22 min — the price
of executing the documentation for real, exactly what the reference
never does for its tutorials.

Set ``DL4J_TPU_SKIP_EXAMPLES=1`` to skip the tier for quick local runs.
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
EXAMPLES_DIR = os.path.join(REPO, "examples")

EXAMPLES = sorted(
    f for f in os.listdir(EXAMPLES_DIR)
    if f.endswith(".py") and f[0].isdigit()
)

pytestmark = pytest.mark.skipif(
    os.environ.get("DL4J_TPU_SKIP_EXAMPLES") == "1",
    reason="examples tier disabled via DL4J_TPU_SKIP_EXAMPLES=1")


def test_all_examples_present():
    assert len(EXAMPLES) >= 28, EXAMPLES


def test_shipped_alert_rules_lint_clean():
    """The smoke tier lints the shipped ``--alerts`` rules file with the
    real validator CLI (schema + dry-run against empty and sampled
    registries), exactly as a user would before deploying it."""
    proc = subprocess.run(
        [sys.executable,
         os.path.join(REPO, "tools", "validate_alert_rules.py"),
         os.path.join(EXAMPLES_DIR, "alert_rules.json")],
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
        timeout=300, capture_output=True, text=True)
    assert proc.returncode == 0, (
        f"validator exited {proc.returncode}\n{proc.stdout}\n{proc.stderr}")
    assert proc.stdout.startswith("OK"), proc.stdout


def test_shipped_elastic_alert_rules_lint_clean():
    """The restart-storm / shrunk-world rules shipped for the elastic
    supervisor pass the same rule validator."""
    proc = subprocess.run(
        [sys.executable,
         os.path.join(REPO, "tools", "validate_alert_rules.py"),
         os.path.join(EXAMPLES_DIR, "elastic_alert_rules.json")],
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
        timeout=300, capture_output=True, text=True)
    assert proc.returncode == 0, (
        f"validator exited {proc.returncode}\n{proc.stdout}\n{proc.stderr}")
    assert proc.stdout.startswith("OK"), proc.stdout


def test_shipped_sharding_rules_lint_clean():
    """The shipped ``--sharding-rules`` file (the JSON rendition of the
    Megatron 2-D rule set) passes ``tools/validate_sharding_rules.py``:
    schema + dry-run lint against the sample TransformerLM, with every
    spec axis checked against a data=4,model=2 mesh."""
    proc = subprocess.run(
        [sys.executable,
         os.path.join(REPO, "tools", "validate_sharding_rules.py"),
         "--mesh", "data=4,model=2",
         os.path.join(EXAMPLES_DIR, "sharding_rules.json")],
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
        timeout=300, capture_output=True, text=True)
    assert proc.returncode == 0, (
        f"validator exited {proc.returncode}\n{proc.stdout}\n{proc.stderr}")
    assert proc.stdout.startswith("OK"), proc.stdout


def test_shipped_pipeline_config_lints_clean():
    """The continuous-training pipeline config shipped for example 27 /
    the ``pipeline`` CLI subcommand passes
    ``tools/validate_pipeline_config.py`` (schema + dry-run lint)."""
    proc = subprocess.run(
        [sys.executable,
         os.path.join(REPO, "tools", "validate_pipeline_config.py"),
         os.path.join(EXAMPLES_DIR, "pipeline_config.json")],
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
        timeout=300, capture_output=True, text=True)
    assert proc.returncode == 0, (
        f"validator exited {proc.returncode}\n{proc.stdout}\n{proc.stderr}")
    assert proc.stdout.startswith("OK"), proc.stdout


def test_shipped_fault_plan_lints_clean():
    """The example ``DL4J_TPU_FAULT_PLAN`` ships lint-clean through
    ``tools/validate_fault_plan.py`` (schema + dry run, no fault executed)
    — the alert-rules validator convention for the fault harness."""
    proc = subprocess.run(
        [sys.executable,
         os.path.join(REPO, "tools", "validate_fault_plan.py"),
         "--workers", "3",
         os.path.join(EXAMPLES_DIR, "fault_plan.json")],
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
        timeout=300, capture_output=True, text=True)
    assert proc.returncode == 0, (
        f"validator exited {proc.returncode}\n{proc.stdout}\n{proc.stderr}")
    assert proc.stdout.startswith("OK"), proc.stdout


def test_shipped_serving_fault_plan_lints_clean():
    """The serving chaos plan (crash_forward / slow_forward /
    reject_admission / drop_response keyed on model + request seq) ships
    lint-clean, with ``--models`` confirming every fault names a model
    the documented ``serve`` invocation registers."""
    proc = subprocess.run(
        [sys.executable,
         os.path.join(REPO, "tools", "validate_fault_plan.py"),
         "--models", "mnist",
         os.path.join(EXAMPLES_DIR, "serving_fault_plan.json")],
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
        timeout=300, capture_output=True, text=True)
    assert proc.returncode == 0, (
        f"validator exited {proc.returncode}\n{proc.stdout}\n{proc.stderr}")
    assert proc.stdout.startswith("OK"), proc.stdout


def test_shipped_serving_alert_rules_lint_clean():
    """The breaker/brownout/restart-storm rules shipped for the serving
    resilience tier pass the alert-rule validator."""
    proc = subprocess.run(
        [sys.executable,
         os.path.join(REPO, "tools", "validate_alert_rules.py"),
         os.path.join(EXAMPLES_DIR, "serving_alert_rules.json")],
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
        timeout=300, capture_output=True, text=True)
    assert proc.returncode == 0, (
        f"validator exited {proc.returncode}\n{proc.stdout}\n{proc.stderr}")
    assert proc.stdout.startswith("OK"), proc.stdout


def test_shipped_slo_config_lints_clean():
    """The SLO definitions shipped for ``serve --slo`` / ``train --slo``
    pass ``tools/validate_slo_config.py`` (schema + burn-rule dry run
    against empty and sampled registries, /slo payload assembly)."""
    proc = subprocess.run(
        [sys.executable,
         os.path.join(REPO, "tools", "validate_slo_config.py"),
         os.path.join(EXAMPLES_DIR, "slo_config.json")],
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
        timeout=300, capture_output=True, text=True)
    assert proc.returncode == 0, (
        f"validator exited {proc.returncode}\n{proc.stdout}\n{proc.stderr}")
    assert proc.stdout.startswith("OK"), proc.stdout


def test_incident_validator_over_injected_kill_bundle(tmp_path):
    """The incident flight recorder's validator, run as a user would:
    a smoke-tier supervisor (manual clock, scripted launcher) suffers an
    injected worker kill, shrinks, writes an ``incident_*`` bundle —
    and ``tools/validate_incident.py`` passes it via the real CLI."""
    from deeplearning4j_tpu.observe import MetricsRegistry
    from deeplearning4j_tpu.parallel import elastic
    from deeplearning4j_tpu.parallel.elastic import (BackoffPolicy,
                                                     ElasticJobSupervisor,
                                                     WorkerSpec)
    from deeplearning4j_tpu.parallel.time_source import ManualTimeSource

    class _Proc:
        rc = None

        def poll(self):
            return self.rc

        def kill(self):
            self.rc = -9 if self.rc is None else self.rc

        def wait(self, timeout=None):
            return self.rc

    class _World:
        def __init__(self, clock):
            self.clock = clock
            self.procs = {}
            self.tick = 0

        def launch(self, argv, env, cwd, log_path):
            p = _Proc()
            self.procs[int(env[elastic.ENV_SLOT])] = (env, p)
            with open(log_path, "w", encoding="utf-8") as fh:
                fh.write("worker boot\n")
            return p

        def sleep(self, seconds):
            self.clock.advance(seconds=max(seconds, 1.0))
            self.tick += 1
            for slot, (env, p) in self.procs.items():
                if p.rc is not None:
                    continue
                with open(env[elastic.ENV_HEARTBEAT], "w",
                          encoding="utf-8") as fh:
                    fh.write(f"1:{self.tick}:{self.tick}")
            if self.tick == 2:
                self.procs[1][1].rc = -9   # the injected kill
            elif self.tick >= 3:
                for slot, (env, p) in self.procs.items():
                    if p.rc is None:
                        p.rc = 0

    clock = ManualTimeSource(start_ms=1_000)
    world = _World(clock)
    sup = ElasticJobSupervisor(
        WorkerSpec(argv=["worker"], env={}), 2, min_workers=1,
        ckpt_dir=str(tmp_path / "ckpt"), clock=clock,
        sleep_fn=world.sleep, launcher=world, metrics=MetricsRegistry(),
        port_fn=lambda: 45999, poll_interval_s=1.0,
        backoff=BackoffPolicy(max_restarts=0))
    result = sup.run()
    assert result.status == "completed"
    assert sup.incidents is not None and len(sup.incidents.bundles) == 1
    bundle = sup.incidents.bundles[0]

    proc = subprocess.run(
        [sys.executable,
         os.path.join(REPO, "tools", "validate_incident.py"), bundle],
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
        timeout=300, capture_output=True, text=True)
    assert proc.returncode == 0, (
        f"validator exited {proc.returncode}\n{proc.stdout}\n{proc.stderr}")
    assert proc.stdout.startswith("OK"), proc.stdout
    assert "shrink" in proc.stdout and "victim slot 1" in proc.stdout


@pytest.mark.parametrize("script", EXAMPLES)
def test_example_runs_clean(script):
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=8",
        PYTHONPATH=os.pathsep.join(
            p for p in (REPO, os.environ.get("PYTHONPATH", "")) if p),
    )
    proc = subprocess.run(
        [sys.executable, os.path.join(EXAMPLES_DIR, script)],
        env=env, timeout=600, capture_output=True, text=True)
    assert proc.returncode == 0, (
        f"{script} exited {proc.returncode}\n"
        f"--- stdout tail ---\n{proc.stdout[-2000:]}\n"
        f"--- stderr tail ---\n{proc.stderr[-2000:]}")
