"""Transfer learning + early stopping behavior tests.

Reference patterns: ``deeplearning4j-core/src/test/.../nn/transferlearning/``
(TransferLearningMLNTest, TransferLearningCompGraphTest) and
``.../earlystopping/TestEarlyStopping.java``.
"""

import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import DataSet, ListDataSetIterator
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.network import NeuralNetConfiguration
from deeplearning4j_tpu.nn.graph import ComputationGraph
from deeplearning4j_tpu.nn.layers import (
    ConvolutionLayer, DenseLayer, OutputLayer, SubsamplingLayer,
)
from deeplearning4j_tpu.nn.layers.wrappers import FrozenLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.transferlearning import (
    FineTuneConfiguration, TransferLearning, TransferLearningHelper,
)
from deeplearning4j_tpu.nn.updaters import Adam, Sgd
from deeplearning4j_tpu.optimize.earlystopping import (
    BestScoreEpochTerminationCondition,
    ClassificationScoreCalculator,
    DataSetLossCalculator,
    EarlyStoppingConfiguration,
    EarlyStoppingTrainer,
    InvalidScoreIterationTerminationCondition,
    MaxEpochsTerminationCondition,
    ScoreImprovementEpochTerminationCondition,
)


def _mlp(seed=7):
    conf = (NeuralNetConfiguration.builder().seed(seed).updater(Adam(0.01)).list()
            .layer(DenseLayer(n_out=16, activation="relu"))
            .layer(DenseLayer(n_out=12, activation="relu"))
            .layer(OutputLayer(n_out=3))
            .set_input_type(InputType.feed_forward(8)).build())
    return MultiLayerNetwork(conf).init()


def _data(n=64, d=8, k=3, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.rand(n, d).astype(np.float32)
    yi = rng.randint(0, k, n)
    x[np.arange(n), yi] += 2.0
    y = np.eye(k, dtype=np.float32)[yi]
    return x, y


class TestTransferLearningMLN:
    def test_freeze_keeps_params_fixed(self):
        net = _mlp()
        x, y = _data()
        new = (TransferLearning.Builder(net)
               .fine_tune_configuration(FineTuneConfiguration(updater=Sgd(0.5)))
               .set_feature_extractor(0)
               .build())
        assert isinstance(new.layers[0], FrozenLayer)
        w0_before = np.asarray(new.params[0]["W"])
        w1_before = np.asarray(new.params[1]["W"])
        new.fit(x, y, epochs=2)
        assert np.array_equal(np.asarray(new.params[0]["W"]), w0_before)
        assert not np.array_equal(np.asarray(new.params[1]["W"]), w1_before)

    def test_params_copied_from_source(self):
        net = _mlp()
        new = TransferLearning.Builder(net).set_feature_extractor(0).build()
        for i in range(3):
            assert np.array_equal(np.asarray(new.params[i]["W"]),
                                  np.asarray(net.params[i]["W"]))

    def test_nout_replace_reinitializes_consumer(self):
        net = _mlp()
        new = (TransferLearning.Builder(net)
               .n_out_replace(1, 20, weight_init="xavier")
               .build())
        assert new.layers[1].n_out == 20
        assert new.params[1]["W"].shape == (16, 20)
        assert new.params[2]["W"].shape == (20, 3)
        # untouched layer 0 keeps its params
        assert np.array_equal(np.asarray(new.params[0]["W"]),
                              np.asarray(net.params[0]["W"]))

    def test_remove_and_add_output_layer(self):
        net = _mlp()
        new = (TransferLearning.Builder(net)
               .remove_output_layer()
               .add_layer(OutputLayer(n_out=5, activation="softmax"))
               .build())
        assert new.layers[-1].n_out == 5
        x, _ = _data()
        out = new.output(x)
        assert out.shape == (64, 5)

    def test_fine_tune_updater_override(self):
        net = _mlp()
        new = (TransferLearning.Builder(net)
               .fine_tune_configuration(FineTuneConfiguration(updater=Sgd(0.123)))
               .build())
        assert type(new.conf.global_conf.updater).__name__ == "Sgd"

    def test_helper_featurize(self):
        net = _mlp()
        x, y = _data()
        helper = TransferLearningHelper(net, frozen_till=0)
        feat = helper.featurize(DataSet(x, y))
        assert feat.features.shape == (64, 16)
        before = np.asarray(net.output(x))
        helper.fit_featurized(feat, epochs=2)
        out = helper.output_from_featurized(feat.features)
        assert out.shape == (64, 3)
        # original trunk untouched
        assert np.array_equal(before, np.asarray(net.output(x)))

    def test_helper_featurize_cnn_flatten(self):
        conf = (NeuralNetConfiguration.builder().seed(1).updater(Adam(0.01)).list()
                .layer(ConvolutionLayer(n_out=4, kernel_size=(3, 3), activation="relu"))
                .layer(SubsamplingLayer())
                .layer(DenseLayer(n_out=10, activation="relu"))
                .layer(OutputLayer(n_out=3))
                .set_input_type(InputType.convolutional(12, 12, 1)).build())
        net = MultiLayerNetwork(conf).init()
        x = np.random.RandomState(0).rand(8, 12, 12, 1).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[np.random.RandomState(1).randint(0, 3, 8)]
        helper = TransferLearningHelper(net, frozen_till=1)
        feat = helper.featurize(DataSet(x, y))
        assert feat.features.ndim == 2  # flattened for the dense head
        helper.fit_featurized(feat, epochs=1)


class TestTransferLearningGraph:
    def _graph(self):
        conf = (NeuralNetConfiguration.builder().seed(5).updater(Adam(0.01))
                .graph_builder()
                .add_inputs("in")
                .add_layer("d0", DenseLayer(n_out=16, activation="relu"), "in")
                .add_layer("d1", DenseLayer(n_out=12, activation="relu"), "d0")
                .add_layer("out", OutputLayer(n_out=3), "d1")
                .set_outputs("out")
                .set_input_types(InputType.feed_forward(8))
                .build())
        return ComputationGraph(conf).init()

    def test_freeze_ancestors(self):
        g = self._graph()
        x, y = _data()
        new = (TransferLearning.GraphBuilder(g)
               .set_feature_extractor("d1")
               .build())
        assert isinstance(new.conf.vertices["d0"].obj, FrozenLayer)
        assert isinstance(new.conf.vertices["d1"].obj, FrozenLayer)
        assert not isinstance(new.conf.vertices["out"].obj, FrozenLayer)
        w_before = np.asarray(new.params["d0"]["W"])
        new.fit(x, y, epochs=2)
        assert np.array_equal(np.asarray(new.params["d0"]["W"]), w_before)

    def test_replace_output(self):
        g = self._graph()
        new = (TransferLearning.GraphBuilder(g)
               .remove_vertex("out")
               .add_layer("newout", OutputLayer(n_out=7), "d1")
               .set_outputs("newout")
               .build())
        x, _ = _data()
        out = new.output(x)
        out = out[0] if isinstance(out, list) else out
        assert out.shape == (64, 7)
        assert np.array_equal(np.asarray(new.params["d0"]["W"]),
                              np.asarray(g.params["d0"]["W"]))

    def test_nout_replace_graph(self):
        g = self._graph()
        new = (TransferLearning.GraphBuilder(g)
               .n_out_replace("d1", 24)
               .build())
        assert new.params["d1"]["W"].shape == (16, 24)
        assert new.params["out"]["W"].shape == (24, 3)


class TestEarlyStopping:
    def test_max_epochs(self):
        net = _mlp()
        x, y = _data()
        it = ListDataSetIterator(DataSet(x, y), 16)
        cfg = EarlyStoppingConfiguration(
            score_calculator=DataSetLossCalculator(ListDataSetIterator(DataSet(x, y), 32)),
            epoch_termination_conditions=[MaxEpochsTerminationCondition(3)])
        result = EarlyStoppingTrainer(cfg, net, it).fit()
        assert result.total_epochs == 3
        assert result.termination_reason == "EpochTerminationCondition"
        assert result.best_model is not None
        assert len(result.score_vs_epoch) == 3

    def test_score_improvement_patience(self):
        net = _mlp()
        x, y = _data()
        it = ListDataSetIterator(DataSet(x, y), 16)
        cfg = EarlyStoppingConfiguration(
            score_calculator=DataSetLossCalculator(ListDataSetIterator(DataSet(x, y), 32)),
            epoch_termination_conditions=[
                MaxEpochsTerminationCondition(50),
                ScoreImprovementEpochTerminationCondition(2, min_improvement=10.0)])
        result = EarlyStoppingTrainer(cfg, net, it).fit()
        # 10.0 improvement never reached -> patience of 2 fires at epoch 3
        assert result.total_epochs == 3
        assert result.termination_details == "ScoreImprovementEpochTerminationCondition"

    def test_best_score_condition_and_best_model(self):
        net = _mlp()
        x, y = _data(n=128)
        it = ListDataSetIterator(DataSet(x, y), 32, shuffle=True)
        cfg = EarlyStoppingConfiguration(
            score_calculator=ClassificationScoreCalculator(
                ListDataSetIterator(DataSet(x, y), 64), "accuracy"),
            epoch_termination_conditions=[
                MaxEpochsTerminationCondition(30),
                BestScoreEpochTerminationCondition(0.02)])
        result = EarlyStoppingTrainer(cfg, net, it).fit()
        assert result.best_model_score <= 0.05
        ev = result.best_model.evaluate(ListDataSetIterator(DataSet(x, y), 64))
        assert ev.accuracy() >= 0.95

    def test_invalid_score_stops(self):
        net = _mlp()

        class Boom(InvalidScoreIterationTerminationCondition):
            pass

        x, y = _data()
        y_bad = y * np.nan
        it = ListDataSetIterator(DataSet(x, y_bad), 16)
        cfg = EarlyStoppingConfiguration(
            score_calculator=DataSetLossCalculator(ListDataSetIterator(DataSet(x, y), 32)),
            epoch_termination_conditions=[MaxEpochsTerminationCondition(5)],
            iteration_termination_conditions=[Boom()])
        result = EarlyStoppingTrainer(cfg, net, it).fit()
        assert result.termination_reason == "IterationTerminationCondition"
