"""Nearest-neighbors / clustering / t-SNE tests.

Mirrors the reference test approach (nearestneighbor-core src/test): exact
small-case assertions plus cross-implementation equivalence (tree search must
match brute force — the cuDNN-vs-builtin validation pattern)."""

import numpy as np
import pytest

from deeplearning4j_tpu.clustering import (
    BruteForceNearestNeighbors, HyperRect, KDTree, KMeansClustering, Point,
    QuadTree, RandomProjectionLSH, SpTree, VPTree, VPTreeFillSearch, knn,
    pairwise_distance,
)


def _blobs(n_per=30, centers=((0, 0), (10, 10), (-10, 10)), d=2, seed=0):
    rng = np.random.default_rng(seed)
    pts = []
    for c in centers:
        base = np.zeros(d)
        base[: len(c)] = c
        pts.append(base + rng.standard_normal((n_per, d)))
    return np.concatenate(pts).astype(np.float32)


class TestBruteForce:
    def test_euclidean_matches_numpy(self):
        rng = np.random.default_rng(1)
        q = rng.standard_normal((5, 8)).astype(np.float32)
        c = rng.standard_normal((20, 8)).astype(np.float32)
        d = np.asarray(pairwise_distance(q, c))
        expected = np.sqrt(((q[:, None, :] - c[None, :, :]) ** 2).sum(-1))
        np.testing.assert_allclose(d, expected, rtol=1e-4, atol=1e-4)

    @pytest.mark.parametrize("metric", ["euclidean", "cosine", "manhattan",
                                        "chebyshev", "dot"])
    def test_knn_orders_by_metric(self, metric):
        rng = np.random.default_rng(2)
        corpus = rng.standard_normal((50, 4)).astype(np.float32)
        q = corpus[7:8] + 0.01
        d, i = knn(q, corpus, 3, metric)
        assert int(np.asarray(i)[0, 0]) == 7
        d = np.asarray(d)[0]
        assert np.all(np.diff(d) >= -1e-6)

    def test_search_excluding_self(self):
        pts = _blobs()
        index = BruteForceNearestNeighbors(pts)
        d, i = index.search_excluding_self(5)
        assert i.shape == (len(pts), 5)
        for r in range(len(pts)):
            assert r not in i[r]


class TestVPTree:
    def test_matches_brute_force(self):
        pts = _blobs(n_per=25, d=6)
        tree = VPTree(pts)
        bf = BruteForceNearestNeighbors(pts)
        rng = np.random.default_rng(3)
        for _ in range(5):
            q = rng.standard_normal(6).astype(np.float32)
            td, ti = tree.search(q, 7)
            bd, bi = bf.search(q, 7)
            np.testing.assert_allclose(np.sort(td), np.sort(bd[0]),
                                       rtol=1e-4, atol=1e-4)

    def test_exact_self_query(self):
        pts = _blobs(n_per=10)
        tree = VPTree(pts)
        d, i = tree.search(pts[4], 1)
        assert i[0] == 4 and d[0] < 1e-5

    def test_cosine_metric(self):
        pts = _blobs(n_per=10, d=4)
        tree = VPTree(pts, distance="cosine")
        d, i = tree.search(pts[0] * 3.0, 1)  # scaled → same direction
        assert d[0] < 1e-5

    def test_fill_search_returns_k(self):
        pts = _blobs(n_per=5)
        tree = VPTree(pts)
        fs = VPTreeFillSearch(tree, 9, pts[0])
        fs.run()
        assert len(fs.results) == 9
        assert len(np.unique(fs.results)) == 9


class TestKDTree:
    def test_insert_nn(self):
        tree = KDTree(2)
        for p in [(0, 0), (1, 1), (5, 5), (2, 2)]:
            tree.insert(p)
        d, p = tree.nn((1.1, 1.1))
        np.testing.assert_allclose(p, [1, 1])
        assert tree.size == 4

    def test_knn_matches_brute(self):
        pts = _blobs(n_per=20, d=3)
        tree = KDTree(3)
        for p in pts:
            tree.insert(p)
        bf = BruteForceNearestNeighbors(pts)
        q = np.array([0.5, 0.5, 0.5], np.float32)
        dists, _ = tree.knn(q, 5)
        bd, _ = bf.search(q, 5)
        np.testing.assert_allclose(dists, bd[0], rtol=1e-4, atol=1e-4)

    def test_delete(self):
        tree = KDTree(2)
        pts = [(0, 0), (1, 1), (5, 5)]
        for p in pts:
            tree.insert(p)
        assert tree.delete((1, 1))
        assert tree.size == 2
        d, p = tree.nn((1, 1))
        assert not np.array_equal(p, [1, 1])
        assert not tree.delete((9, 9))

    def test_range(self):
        tree = KDTree(2)
        for p in [(0, 0), (1, 1), (5, 5), (2, 2)]:
            tree.insert(p)
        inside = tree.range((0.5, 0.5), (3, 3))
        got = {tuple(p) for p in inside}
        assert got == {(1.0, 1.0), (2.0, 2.0)}

    def test_hyperrect(self):
        r = HyperRect((0, 0), (2, 2))
        assert r.contains(np.array([1, 1]))
        assert not r.contains(np.array([3, 1]))
        assert r.min_distance(np.array([3, 1])) == pytest.approx(1.0)


class TestKMeans:
    def test_recovers_blobs(self):
        pts = _blobs(n_per=40)
        km = KMeansClustering.setup(3, 100)
        centers = km.fit(pts)
        assert centers.shape == (3, 2)
        expected = {(0, 0), (10, 10), (-10, 10)}
        for e in expected:
            d = np.linalg.norm(centers - np.array(e), axis=1)
            assert d.min() < 1.5

    def test_apply_to_cluster_set(self):
        pts = _blobs(n_per=15)
        points = Point.to_points(pts)
        cs = KMeansClustering.setup(3, 50).apply_to(points)
        assert cs.cluster_count == 3
        assert sum(len(c.points) for c in cs.clusters) == len(points)
        pc = cs.classify_point(points[0])
        assert pc.cluster is not None and not pc.new_location

    def test_cost_decreases(self):
        pts = _blobs(n_per=30, seed=5)
        km = KMeansClustering.setup(3, 50, seed=1)
        km.fit(pts)
        costs = km.iteration_costs
        assert costs[-1] <= costs[0] + 1e-6


class TestLSH:
    def test_bucket_and_search(self):
        pts = _blobs(n_per=50, d=8, centers=((0,) * 8, (20,) * 8))
        lsh = RandomProjectionLSH(hash_length=8, num_tables=6, radius=10.0)
        lsh.make_index(pts)
        cand = lsh.bucket(pts[3])
        assert 3 in cand
        d, i = lsh.search(pts[3], 10.0)
        assert 3 in i
        assert np.all(d <= 10.0)

    def test_knn_recall(self):
        pts = _blobs(n_per=60, d=8, centers=((0,) * 8, (20,) * 8))
        lsh = RandomProjectionLSH(hash_length=6, num_tables=8)
        lsh.make_index(pts)
        bf = BruteForceNearestNeighbors(pts)
        bd, bi = bf.search(pts[10], 5)
        d, i = lsh.get_all_nearest_neighbors(pts[10], 5)
        # candidates come from matching buckets: recall over true 5-NN >= 3/5
        assert len(set(i[:5]) & set(bi[0])) >= 3


class TestSpTree:
    def test_center_of_mass_and_count(self):
        pts = np.array([[0, 0], [1, 0], [0, 1], [1, 1]], np.float64)
        tree = SpTree(pts)
        assert tree.cum_size == 4
        np.testing.assert_allclose(tree.center_of_mass, [0.5, 0.5])

    def test_duplicates_do_not_blow_up(self):
        pts = np.zeros((10, 2))
        tree = SpTree(pts)
        assert tree.cum_size == 10
        assert tree.depth() < 5

    def test_non_edge_forces_match_exact(self):
        rng = np.random.default_rng(7)
        pts = rng.standard_normal((40, 2))
        tree = SpTree(pts)
        # theta=0 → always recurse to leaves → exact
        neg = np.zeros(2)
        sum_q = tree.compute_non_edge_forces(0, 0.0, neg)
        diff = pts[0] - pts[1:]
        q = 1.0 / (1.0 + (diff ** 2).sum(1))
        np.testing.assert_allclose(sum_q, q.sum(), rtol=1e-8)
        np.testing.assert_allclose(neg, ((q ** 2)[:, None] * diff).sum(0),
                                   rtol=1e-8)

    def test_quadtree_is_2d(self):
        with pytest.raises(ValueError):
            QuadTree(np.zeros((3, 3)))
        qt = QuadTree(np.array([[0, 0], [1, 1], [0.2, 0.8]]))
        assert qt.cum_size == 3


class TestTsne:
    def test_exact_separates_blobs(self):
        from deeplearning4j_tpu.plot import Tsne
        pts = _blobs(n_per=20, d=10,
                     centers=((0,) * 10, (25,) * 10))
        ts = Tsne(perplexity=10.0, n_iter=300, seed=0)
        y = ts.fit_transform(pts)
        assert y.shape == (40, 2)
        a, b = y[:20], y[20:]
        intra = max(np.linalg.norm(a - a.mean(0), axis=1).mean(),
                    np.linalg.norm(b - b.mean(0), axis=1).mean())
        inter = np.linalg.norm(a.mean(0) - b.mean(0))
        assert inter > 2 * intra

    def test_barnes_hut_separates_blobs(self):
        from deeplearning4j_tpu.plot import BarnesHutTsne
        pts = _blobs(n_per=15, d=8, centers=((0,) * 8, (25,) * 8), seed=2)
        ts = BarnesHutTsne(theta=0.5, perplexity=5.0, n_iter=150, seed=0)
        y = ts.fit_transform(pts)
        assert y.shape == (30, 2)
        a, b = y[:15], y[15:]
        inter = np.linalg.norm(a.mean(0) - b.mean(0))
        intra = max(np.linalg.norm(a - a.mean(0), axis=1).mean(),
                    np.linalg.norm(b - b.mean(0), axis=1).mean())
        assert inter > 2 * intra

    def test_theta_zero_routes_to_exact(self):
        from deeplearning4j_tpu.plot import BarnesHutTsne
        pts = _blobs(n_per=10, d=4, seed=3)
        ts = BarnesHutTsne(theta=0.0, perplexity=5.0, n_iter=50)
        y = ts.fit_transform(pts)
        assert y.shape == (30, 2)


def test_kmeanspp_seeding_quality():
    """k-means++ D^2 seeding: across seeds, well-separated blobs should
    almost always be recovered perfectly (linear-weighted seeding kept
    collapsing two blobs into one center)."""
    import numpy as np
    from deeplearning4j_tpu.clustering.kmeans import KMeansClustering

    rng = np.random.default_rng(0)
    out, labels = [], []
    for ci, c in enumerate(((0, 0), (8, 8), (0, 8))):
        mu = np.zeros(16)
        mu[:2] = c
        out.append(rng.normal(size=(80, 16)) * 0.5 + mu)
        labels.extend([ci] * 80)
    x = np.concatenate(out).astype(np.float32)
    labels = np.array(labels)
    purities = []
    for seed in range(8):
        km = KMeansClustering.setup(cluster_count=3, max_iteration_count=50,
                                    seed=seed)
        km.fit(x)
        a = km.assignments
        purities.append(np.mean([
            np.bincount(labels[a == c]).max() / max(1, (a == c).sum())
            for c in range(3)]))
    assert np.mean(purities) > 0.95, purities


def test_kmeans_metric_aware_seeding():
    import numpy as np
    from deeplearning4j_tpu.clustering.kmeans import KMeansClustering

    rng = np.random.default_rng(0)
    x = np.concatenate([rng.normal(size=(40, 4)) * 0.3,
                        rng.normal(size=(40, 4)) * 0.3 + 6.0]).astype(np.float32)
    # sqeuclidean distances are already squared — must still cluster cleanly
    km = KMeansClustering.setup(cluster_count=2, max_iteration_count=50,
                                distance="sqeuclidean", seed=1)
    centers = km.fit(x)
    assert centers.shape == (2, 4)
    a = km.assignments
    assert (a[:40] == a[0]).all() and (a[40:] == a[40]).all() and a[0] != a[40]
    # 'dot' is not a metric: seeding must not crash (uniform fallback)
    km2 = KMeansClustering.setup(cluster_count=2, max_iteration_count=10,
                                 distance="dot", seed=1)
    km2.fit(x)
    # all-duplicate points: seeding falls back to uniform instead of raising
    dup = np.tile(np.ones((1, 4), np.float32), (5, 1))
    km3 = KMeansClustering.setup(cluster_count=2, max_iteration_count=5, seed=0)
    km3.fit(dup)


def test_kmeans_zero_max_iter_still_assigns():
    import numpy as np
    from deeplearning4j_tpu.clustering.kmeans import KMeansClustering

    x = np.random.default_rng(0).normal(size=(10, 3)).astype(np.float32)
    km = KMeansClustering.setup(cluster_count=2, max_iteration_count=0)
    centers = km.fit(x)   # clamped to one sweep: assignments always exist
    assert centers.shape == (2, 3)
    assert km.assignments.shape == (10,)
