"""VAE reconstruction distributions (nn/conf/layers/variational/ parity):
Bernoulli, Gaussian, Exponential, Composite, LossFunctionWrapper — gradient
checks for every distribution plus the reconstructionProbability /
reconstructionError API family (VariationalAutoencoder.java:985/998/1146).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.nn.layers import (
    BernoulliReconstructionDistribution,
    CompositeReconstructionDistribution,
    ExponentialReconstructionDistribution,
    GaussianReconstructionDistribution,
    LossFunctionWrapper,
    VariationalAutoencoderLayer,
)

RNG = np.random.default_rng(7)


def _vae(dist, n_in=6):
    return VariationalAutoencoderLayer(
        n_in=n_in, n_out=3, encoder_layer_sizes=(8,), decoder_layer_sizes=(8,),
        activation="tanh", weight_init="xavier",
        reconstruction_distribution=dist)


def _grad_check(layer, x):
    from deeplearning4j_tpu.util.gradient_check import check_gradients_fn
    with jax.enable_x64(True):
        params = layer.init_params(jax.random.PRNGKey(0), jnp.float64)
        key = jax.random.PRNGKey(5)
        return check_gradients_fn(lambda p: layer.pretrain_loss(p, jnp.asarray(x), key),
                                  params, subset=40, print_results=True)


class TestGradientChecks:
    """The reference's VAE gradient-check suite covers every reconstruction
    distribution (gradientcheck/VaeGradientCheckTests pattern)."""

    def test_bernoulli(self):
        x = (RNG.random((3, 6)) > 0.5).astype(np.float64)
        assert _grad_check(_vae(BernoulliReconstructionDistribution()), x)

    def test_gaussian(self):
        x = RNG.normal(size=(3, 6)).astype(np.float64)
        assert _grad_check(_vae(GaussianReconstructionDistribution()), x)

    def test_gaussian_tanh_activation(self):
        x = RNG.normal(size=(3, 6)).astype(np.float64)
        assert _grad_check(
            _vae(GaussianReconstructionDistribution(activation="tanh")), x)

    def test_exponential(self):
        x = RNG.exponential(1.0, size=(3, 6)).astype(np.float64)
        assert _grad_check(_vae(ExponentialReconstructionDistribution()), x)

    def test_loss_function_wrapper(self):
        x = RNG.random((3, 6)).astype(np.float64)
        assert _grad_check(
            _vae(LossFunctionWrapper(loss="mse", activation="sigmoid")), x)

    def test_composite(self):
        # first 2 cols binary, next 2 real-valued, last 2 non-negative —
        # the CompositeReconstructionDistribution.java:27 use case
        comp = CompositeReconstructionDistribution(distributions=[
            (2, BernoulliReconstructionDistribution()),
            (2, GaussianReconstructionDistribution()),
            (2, ExponentialReconstructionDistribution()),
        ])
        x = np.concatenate([
            (RNG.random((3, 2)) > 0.5).astype(np.float64),
            RNG.normal(size=(3, 2)),
            RNG.exponential(1.0, size=(3, 2)),
        ], axis=1)
        assert _grad_check(_vae(comp), x)


class TestDistributionMath:
    def test_exponential_neg_log_prob_formula(self):
        # -log p = λx − γ with γ = pre-out (identity activation)
        d = ExponentialReconstructionDistribution()
        gamma = jnp.asarray([[0.0, 1.0]])
        x = jnp.asarray([[2.0, 0.5]])
        want = (np.exp(0.0) * 2.0 - 0.0) + (np.exp(1.0) * 0.5 - 1.0)
        np.testing.assert_allclose(
            float(d.example_neg_log_prob(x, gamma)[0]), want, rtol=1e-6)
        # mean = 1/λ = exp(−γ)
        np.testing.assert_allclose(np.asarray(d.generate_at_mean(gamma)),
                                   np.exp([[-0.0, -1.0]]), rtol=1e-6)

    def test_exponential_sampling_mean(self):
        d = ExponentialReconstructionDistribution()
        gamma = jnp.full((50_000, 1), 0.7)
        samples = np.asarray(d.generate_random(jax.random.PRNGKey(0), gamma))
        assert (samples >= 0).all()
        np.testing.assert_allclose(samples.mean(), np.exp(-0.7), rtol=0.05)

    def test_gaussian_matches_manual_density(self):
        d = GaussianReconstructionDistribution()
        mean, log_var = 0.3, -0.5
        pre = jnp.asarray([[mean, log_var]])
        x = jnp.asarray([[1.1]])
        var = np.exp(log_var)
        want = 0.5 * (np.log(2 * np.pi) + log_var + (1.1 - mean) ** 2 / var)
        np.testing.assert_allclose(float(d.example_neg_log_prob(x, pre)[0]),
                                   want, rtol=1e-6)

    def test_composite_sizes_and_slicing(self):
        comp = CompositeReconstructionDistribution(distributions=[
            (2, BernoulliReconstructionDistribution()),
            (3, GaussianReconstructionDistribution()),
        ])
        assert comp.distribution_input_size(5) == 2 + 6
        with pytest.raises(ValueError):
            comp.distribution_input_size(4)
        # generate_at_mean returns data-sized output
        pre = jnp.asarray(RNG.normal(size=(4, 8)).astype(np.float32))
        assert comp.generate_at_mean(pre).shape == (4, 5)
        assert comp.generate_random(jax.random.PRNGKey(0), pre).shape == (4, 5)
        # neg log prob = sum of the parts
        x = jnp.asarray(np.concatenate(
            [(RNG.random((4, 2)) > 0.5).astype(np.float32),
             RNG.normal(size=(4, 3)).astype(np.float32)], axis=1))
        total = comp.example_neg_log_prob(x, pre)
        b = BernoulliReconstructionDistribution().example_neg_log_prob(
            x[:, :2], pre[:, :2])
        g = GaussianReconstructionDistribution().example_neg_log_prob(
            x[:, 2:], pre[:, 2:])
        np.testing.assert_allclose(np.asarray(total), np.asarray(b + g),
                                   rtol=1e-5)


class TestReconstructionAPIs:
    def _trained(self, dist, x, steps=200):
        layer = _vae(dist, n_in=x.shape[1])
        params = layer.init_params(jax.random.PRNGKey(0), jnp.float32)
        grad = jax.jit(jax.grad(lambda p, k: layer.pretrain_loss(
            p, jnp.asarray(x, jnp.float32), k)))
        key = jax.random.PRNGKey(1)
        for _ in range(steps):
            key, k = jax.random.split(key)
            g = grad(params, k)
            params = jax.tree_util.tree_map(lambda p, gg: p - 0.05 * gg,
                                            params, g)
        return layer, params

    def test_reconstruction_log_probability_ranks_in_vs_out(self):
        # after training on structured binary data, in-distribution examples
        # must score higher log p(x) than far-out-of-distribution ones
        x = np.zeros((64, 6), np.float32)
        x[:, 0] = 1.0  # the dataset: always [1,0,0,0,0,0]
        layer, params = self._trained(BernoulliReconstructionDistribution(), x)
        inlp = np.asarray(layer.reconstruction_log_probability(
            params, jnp.asarray(x[:4]), jax.random.PRNGKey(2), num_samples=16))
        out = np.ones((4, 6), np.float32) - x[:4]  # inverted pattern
        outlp = np.asarray(layer.reconstruction_log_probability(
            params, jnp.asarray(out), jax.random.PRNGKey(3), num_samples=16))
        assert inlp.shape == (4,)
        assert (inlp > outlp + 1.0).all(), (inlp, outlp)
        # probability form is exp of the log form
        p = np.asarray(layer.reconstruction_probability(
            params, jnp.asarray(x[:4]), jax.random.PRNGKey(2), num_samples=16))
        assert (p <= 1.0).all() and (p > 0).all()

    def test_loss_wrapper_error_api_and_probability_rejection(self):
        x = RNG.random((32, 6)).astype(np.float32)
        layer, params = self._trained(
            LossFunctionWrapper(loss="mse", activation="sigmoid"), x, steps=50)
        err = np.asarray(layer.reconstruction_error(params, jnp.asarray(x)))
        assert err.shape == (32,) and (err >= 0).all()
        with pytest.raises(ValueError, match="not probabilistic|LossFunction"):
            layer.reconstruction_log_probability(params, jnp.asarray(x),
                                                 jax.random.PRNGKey(0))
        # and the converse: probabilistic configs reject reconstruction_error
        layer2 = _vae(BernoulliReconstructionDistribution())
        params2 = layer2.init_params(jax.random.PRNGKey(0), jnp.float32)
        with pytest.raises(ValueError, match="loss-function"):
            layer2.reconstruction_error(params2, jnp.asarray(x))

    def test_generate_random_given_z(self):
        layer = _vae(BernoulliReconstructionDistribution())
        params = layer.init_params(jax.random.PRNGKey(0), jnp.float32)
        z = jnp.asarray(RNG.normal(size=(5, 3)).astype(np.float32))
        s = np.asarray(layer.generate_random(params, z, jax.random.PRNGKey(1)))
        assert s.shape == (5, 6) and set(np.unique(s)) <= {0.0, 1.0}

    def test_serde_round_trip(self):
        from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
        from deeplearning4j_tpu.nn.conf.network import MultiLayerConfiguration
        from deeplearning4j_tpu.nn.layers import OutputLayer
        comp = CompositeReconstructionDistribution(distributions=[
            (2, BernoulliReconstructionDistribution()),
            (4, LossFunctionWrapper(loss="mse", activation="tanh")),
        ])
        conf = (NeuralNetConfiguration.builder().seed(0).list()
                .layer(_vae(comp))
                .layer(OutputLayer(n_out=2))
                .set_input_type(InputType.feed_forward(6)).build())
        conf2 = MultiLayerConfiguration.from_json(conf.to_json())
        r = conf2.layers[0].recon
        assert isinstance(r, CompositeReconstructionDistribution)
        assert r.distributions[0][0] == 2
        assert isinstance(r.distributions[1][1], LossFunctionWrapper)
        assert r.distributions[1][1].loss == "mse"
