"""Memory report tests (nn/conf/memory parity + XLA compiled analysis)."""

import numpy as np

from deeplearning4j_tpu.nn.conf import (
    InputType,
    MemoryType,
    MemoryUseMode,
    NetworkMemoryReport,
    NeuralNetConfiguration,
    compiled_memory_analysis,
    network_memory_report,
)
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.updaters import Adam, Sgd


def _conf(updater):
    return (NeuralNetConfiguration.builder().seed(1).updater(updater).list()
            .layer(DenseLayer(n_out=32, activation="relu"))
            .layer(OutputLayer(n_out=10))
            .set_input_type(InputType.feed_forward(20))
            .build())


class TestAnalyticReport:
    def test_parameter_counts(self):
        rep = network_memory_report(_conf(Sgd(0.1)))
        # dense: 20*32+32 params; output: 32*10+10
        assert rep.layer_reports[0].parameters == 20 * 32 + 32
        assert rep.layer_reports[1].parameters == 32 * 10 + 10
        assert (rep.get_memory_bytes(MemoryType.PARAMETERS, 1)
                == (20 * 32 + 32 + 32 * 10 + 10) * 4)

    def test_updater_state_scaling(self):
        sgd = network_memory_report(_conf(Sgd(0.1)))
        adam = network_memory_report(_conf(Adam(1e-3)))
        assert sgd.get_memory_bytes(MemoryType.UPDATER_STATE, 1) == 0
        n_params = sum(r.parameters for r in adam.layer_reports)
        assert adam.get_memory_bytes(MemoryType.UPDATER_STATE, 1) == 2 * n_params * 4

    def test_inference_drops_training_memory(self):
        rep = network_memory_report(_conf(Adam(1e-3)))
        train = rep.get_total_memory_bytes(64, MemoryUseMode.TRAINING)
        infer = rep.get_total_memory_bytes(64, MemoryUseMode.INFERENCE)
        assert infer < train
        assert rep.get_memory_bytes(MemoryType.PARAMETER_GRADIENTS, 64,
                                    MemoryUseMode.INFERENCE) == 0

    def test_activations_scale_with_minibatch(self):
        rep = network_memory_report(_conf(Sgd(0.1)))
        a1 = rep.get_memory_bytes(MemoryType.ACTIVATIONS, 1)
        a8 = rep.get_memory_bytes(MemoryType.ACTIVATIONS, 8)
        assert a8 == 8 * a1 > 0

    def test_json_round_trip(self):
        rep = network_memory_report(_conf(Adam(1e-3)))
        rt = NetworkMemoryReport.from_json(rep.to_json())
        assert rt.get_total_memory_bytes(16) == rep.get_total_memory_bytes(16)
        assert "total training memory" in str(rt)


class TestCompiledAnalysis:
    def test_xla_memory_analysis(self):
        net = MultiLayerNetwork(_conf(Adam(1e-3))).init()
        ma = compiled_memory_analysis(net, batch=16)
        if not ma:  # backend may not support memory analysis
            return
        # arguments include params + updater state + x + y: must be > raw params
        n_params = net.conf.num_params()
        assert ma["argument_size"] >= n_params * 4
        assert ma["total"] > 0
