"""Import tier over the reference's 85 REAL bundled Keras fixtures.

``/root/reference/deeplearning4j-modelimport/src/test/resources`` ships
genuine Keras-1/Keras-2-era artifacts: 35 full-model weight h5 files saved
under both tensorflow and theano backends, 44 standalone JSON configs, and
6 TF-scope files. The reference exercises them in
``KerasWeightSettingTests.java`` (shape asserts) and
``KerasModelImportTest.java``; this tier drives OUR importer over every
single file, asserting strictly more than the reference does:

- every weight file imports with parameter/state element counts equal to
  the h5 weight datasets, runs a forward pass at the config's declared
  input shape, and (dense/conv families) matches raw h5 values exactly;
- every config file builds a configuration;
- the tfscope files import through both one-file and two-file paths with
  scoped == unscoped outputs.

The ONLY registration needed is the space_to_depth Lambda — the same
requirement the reference has (``KerasLayer.registerCustomLayer("Lambda",
KerasSpaceToDepth.class)`` in KerasWeightSettingTests.java).
"""

import glob
import json
import os

import numpy as np
import pytest

REF = "/root/reference/deeplearning4j-modelimport/src/test/resources"

pytestmark = pytest.mark.skipif(
    not os.path.isdir(REF), reason="reference fixture tree not present")

from deeplearning4j_tpu.modelimport.keras.importer import (  # noqa: E402
    KerasModelImport,
)


def _space_to_depth_x2(x):
    # NHWC block-2 space-to-depth (the YOLO2 passthrough Lambda)
    n, h, w, c = x.shape
    x = x.reshape(n, h // 2, 2, w // 2, 2, c)
    return x.transpose(0, 1, 3, 2, 4, 5).reshape(n, h // 2, w // 2, 4 * c)


@pytest.fixture()
def lambda_registry():
    from deeplearning4j_tpu.modelimport.keras import (
        clear_lambda_layers, register_lambda_layer)
    register_lambda_layer("space_to_depth_x2", _space_to_depth_x2)
    yield
    clear_lambda_layers()


def _h5_weight_element_count(path):
    """Total elements across weight datasets (optimizer state excluded)."""
    import h5py
    total = 0

    def walk(g):
        nonlocal total
        for k in g:
            o = g[k]
            if hasattr(o, "keys"):
                walk(o)
            elif o.shape != ():
                total += int(np.prod(o.shape))

    with h5py.File(path, "r") as f:
        root = f["model_weights"] if "model_weights" in f else f
        for k in root:
            if k == "optimizer_weights":
                continue
            o = root[k]
            walk(o) if hasattr(o, "keys") else None
    return total


def _net_param_element_count(net):
    params = net.params
    states = net.states
    if isinstance(params, dict):
        it_p = params.values()
        it_s = states.values()
    else:
        it_p, it_s = params, states
    n = sum(int(np.prod(v.shape)) for d in it_p for v in d.values())
    # BN running mean/var live in states here but in the h5 weight groups
    n += sum(int(np.prod(v.shape)) for d in it_s for v in (d or {}).values()
             if hasattr(v, "shape"))
    return n


def _declared_input_shapes(path):
    """[(shape-after-batch, is_embedding_input)] from the h5 model_config."""
    import h5py
    with h5py.File(path, "r") as f:
        mc = f.attrs["model_config"]
        cfg = json.loads(mc if isinstance(mc, str) else mc.decode())
    conf = cfg["config"]
    layers = conf if isinstance(conf, list) else conf["layers"]
    shapes = []
    for lc in layers:
        c = lc.get("config", {})
        s = c.get("batch_input_shape") or c.get("batch_shape")
        if s is not None:
            shapes.append((tuple(s[1:]),
                           lc["class_name"] == "Embedding"
                           or "embedding" in str(c.get("name", ""))))
        if not (isinstance(conf, dict) and "layers" in conf):
            # Sequential: only the first layer declares the input
            if shapes:
                break
    return shapes


def _sample_input(shape, is_embedding):
    concrete = tuple(8 if d is None else int(d) for d in shape)
    rng = np.random.RandomState(0)
    if is_embedding:
        # stay within ANY vocab (the smallest fixture vocab is 4)
        return rng.randint(0, 2, size=(2,) + concrete[:1]).astype(np.float32)
    return rng.rand(2, *concrete).astype(np.float32)


WEIGHT_FILES = sorted(
    os.path.basename(p) for p in glob.glob(REF + "/weights/*.h5"))
CONFIG_FILES = sorted(
    "/".join(p.split("/")[-2:]) for p in glob.glob(REF + "/configs/*/*.json"))


class TestAllWeightFixturesImport:
    @pytest.mark.parametrize("fname", WEIGHT_FILES)
    def test_import_count_and_forward(self, fname, lambda_registry):
        path = os.path.join(REF, "weights", fname)
        net = KerasModelImport.import_keras_model_and_weights(path)
        # every weight element in the file landed in the model
        assert _net_param_element_count(net) == _h5_weight_element_count(path)
        shapes = _declared_input_shapes(path)
        assert shapes, f"{fname}: no declared input shape"
        xs = [_sample_input(s, emb) for s, emb in shapes]
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        if isinstance(net, ComputationGraph):
            out = net.output(*xs)
            outs = out if isinstance(out, list) else [out]
        else:
            outs = [net.output(xs[0])]
        for o in outs:
            assert np.isfinite(np.asarray(o)).all(), f"{fname}: non-finite output"

    @pytest.mark.parametrize("backend", ["tensorflow", "theano"])
    @pytest.mark.parametrize("version", [1, 2])
    def test_dense_values_match_raw_h5(self, backend, version):
        """KerasWeightSettingTests.importDense asserts shapes (4x6); we
        assert the VALUES equal the raw h5 datasets."""
        import h5py
        path = os.path.join(REF, "weights", f"dense_{backend}_{version}.h5")
        net = KerasModelImport.import_keras_model_and_weights(path)
        w = np.asarray(net.params[0]["W"])
        b = np.asarray(net.params[0]["b"])
        assert w.shape == (4, 6) and b.shape == (6,)
        with h5py.File(path, "r") as f:
            root = f["model_weights"] if "model_weights" in f else f
            g = root[list(k for k in root if k != "optimizer_weights")[0]]
            raw = {}

            def walk(gr):
                for k in gr:
                    o = gr[k]
                    if hasattr(o, "keys"):
                        walk(o)
                    else:
                        raw[k.split(":")[0].rsplit("_", 1)[-1]
                            if not k.endswith("kernel") and not k.endswith("bias")
                            else ("W" if k.endswith("kernel") else "b")] = o[()]
            walk(g)
        np.testing.assert_array_equal(w, raw.get("W", raw.get("kernel")))
        np.testing.assert_array_equal(b, raw.get("b", raw.get("bias")))

    @pytest.mark.parametrize("backend", ["tensorflow", "theano"])
    @pytest.mark.parametrize("version", [1, 2])
    def test_conv2d_values_match_raw_h5(self, backend, version):
        """importConv2D asserts DL4J's [out,in,kh,kw]=[6,5,3,3]; our NHWC
        kernel is HWIO [3,3,5,6] and must equal the h5 dataset exactly
        (these fixtures are all saved channels-last)."""
        import h5py
        path = os.path.join(REF, "weights", f"conv2d_{backend}_{version}.h5")
        net = KerasModelImport.import_keras_model_and_weights(path)
        w = np.asarray(net.params[0]["W"])
        assert w.shape == (3, 3, 5, 6)
        with h5py.File(path, "r") as f:
            root = f["model_weights"] if "model_weights" in f else f
            vals = []

            def walk(gr):
                for k in gr:
                    o = gr[k]
                    walk(o) if hasattr(o, "keys") else vals.append((k, o[()]))
            walk(root)
        kernel = next(v for k, v in vals if v.ndim == 4)
        np.testing.assert_array_equal(w, kernel)

    def test_simple_space_to_depth_output_shape(self, lambda_registry):
        """importSimpleSpaceToDepth: input [10,4,6,6] NCHW → [10,16,3,3];
        ours is NHWC: [10,6,6,4] → [10,3,3,16]."""
        path = os.path.join(REF, "weights",
                            "space_to_depth_simple_tensorflow_2.h5")
        net = KerasModelImport.import_keras_model_and_weights(path)
        x = np.zeros((10, 6, 6, 4), np.float32)
        out = np.asarray(net.output(x))
        assert out.shape == (10, 3, 3, 16)

    def test_graph_space_to_depth_output_shape(self, lambda_registry):
        """importGraphSpaceToDepth: two inputs ([10,4,6,6],[10,16,3,3] NCHW)
        merge after the passthrough reorg; NHWC output [10,3,3,32]."""
        path = os.path.join(REF, "weights",
                            "space_to_depth_graph_tensorflow_2.h5")
        net = KerasModelImport.import_keras_model_and_weights(path)
        xs = [np.zeros((10, 6, 6, 4), np.float32),
              np.zeros((10, 3, 3, 16), np.float32)]
        out = net.output(*xs)
        out = out[0] if isinstance(out, list) else out
        assert np.asarray(out).shape == (10, 3, 3, 32)


class TestAllConfigFixturesBuild:
    @pytest.mark.parametrize("fname", CONFIG_FILES)
    def test_config_builds(self, fname, lambda_registry):
        path = os.path.join(REF, "configs", *fname.split("/"))
        conf = KerasModelImport.import_keras_model_configuration(path)
        layers = getattr(conf, "layers", None)
        if layers is None:  # graph configuration
            assert len(conf.vertices) > 0
        else:
            assert len(layers) > 0
        assert conf.num_params() > 0


class TestTfScopeFixtures:
    """KerasModelImportTest.java:38-56 — genuine TF-scope artifacts: layer
    names carrying scope slashes and weight groups nesting extra scope
    levels. The scoped and unscoped files are distinct snapshots of the
    same 70→256→2 architecture (different weight VALUES), so the assertion
    is structural equality + clean forwards, like the reference's."""

    def _assert_pair(self, a, b):
        for da, db in zip(a.params, b.params):
            assert {k: tuple(v.shape) for k, v in da.items()} == \
                   {k: tuple(v.shape) for k, v in db.items()}
        x = np.random.RandomState(0).rand(3, 70).astype(np.float32)
        for net in (a, b):
            out = np.asarray(net.output(x))
            assert out.shape == (3, 2) and np.isfinite(out).all()
        # different snapshots: the import must NOT collapse them
        assert not np.allclose(np.asarray(a.params[0]["W"]),
                               np.asarray(b.params[0]["W"]))

    def test_one_file_imports(self):
        self._assert_pair(
            KerasModelImport.import_keras_model_and_weights(
                os.path.join(REF, "tfscope", "model.h5")),
            KerasModelImport.import_keras_model_and_weights(
                os.path.join(REF, "tfscope", "model.h5.with.tensorflow.scope")))

    def test_two_file_imports(self):
        self._assert_pair(
            KerasModelImport.import_keras_model_and_weights(
                os.path.join(REF, "tfscope", "model.json"),
                os.path.join(REF, "tfscope", "model.weight")),
            KerasModelImport.import_keras_model_and_weights(
                os.path.join(REF, "tfscope", "model.json.with.tensorflow.scope"),
                os.path.join(REF, "tfscope",
                             "model.weight.with.tensorflow.scope")))


class TestReshapeImportEdgeCases:
    def _seq(self, *layer_dicts):
        return {"class_name": "Sequential",
                "config": {"name": "m", "layers": list(layer_dicts)}}

    def test_reshape_then_flatten_then_dense_composes(self, tmp_path):
        """Reshape→Flatten→Dense: the explicit reshape spec must compose
        with the flatten the dense layer needs (explicit specs override
        auto inference, so the flatten has to ride the same boundary)."""
        cfg = self._seq(
            {"class_name": "InputLayer",
             "config": {"name": "in", "batch_input_shape": [None, 32]}},
            {"class_name": "Reshape",
             "config": {"name": "r", "target_shape": [2, 2, 8]}},
            {"class_name": "Flatten", "config": {"name": "f"}},
            {"class_name": "Dense",
             "config": {"name": "d", "units": 10, "activation": "relu"}},
        )
        p = tmp_path / "rf.json"
        p.write_text(json.dumps(cfg))
        conf = KerasModelImport.import_keras_model_configuration(str(p))
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        net = MultiLayerNetwork(conf).init()
        x = np.random.RandomState(0).rand(3, 32).astype(np.float32)
        out = np.asarray(net.output(x))
        assert out.shape == (3, 10) and np.isfinite(out).all()

    def test_reshape_minus_one_rejected_loudly(self, tmp_path):
        from deeplearning4j_tpu.modelimport.keras import (
            UnsupportedKerasConfigurationException)
        cfg = self._seq(
            {"class_name": "InputLayer",
             "config": {"name": "in", "batch_input_shape": [None, 32]}},
            {"class_name": "Reshape",
             "config": {"name": "r", "target_shape": [-1, 8]}},
            {"class_name": "Dense", "config": {"name": "d", "units": 4}},
        )
        p = tmp_path / "rneg.json"
        p.write_text(json.dumps(cfg))
        with pytest.raises(UnsupportedKerasConfigurationException,
                           match="-1 wildcard"):
            KerasModelImport.import_keras_model_configuration(str(p))
