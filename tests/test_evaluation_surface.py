"""Evaluation count-map / rate / introspection surface.

Reference: Evaluation.java (truePositives()/falsePositives()/
falseNegatives()/trueNegatives(), positive()/negative(),
falseNegativeRate, falseAlarmRate, classCount, getNumRowCounter,
getClassLabel, confusionToString, reset, averageF1NumClassesExcluded).
"""

import numpy as np
import pytest

from deeplearning4j_tpu.eval.evaluation import Evaluation


def make_eval():
    """3-class eval with a known confusion matrix:
        actual 0: predicted [0,0,1]      -> tp0=2, one 0->1 error
        actual 1: predicted [1]          -> tp1=1
        actual 2: predicted [2,0]        -> tp2=1, one 2->0 error
    """
    e = Evaluation(labels_list=["ant", "bee", "cow"])
    labels = np.eye(3)[[0, 0, 0, 1, 2, 2]]
    preds = np.eye(3)[[0, 0, 1, 1, 2, 0]]
    e.eval(labels, preds)
    return e


class TestCountMaps:
    def test_tp_fp_fn_tn(self):
        e = make_eval()
        assert e.true_positives() == {0: 2, 1: 1, 2: 1}
        assert e.false_positives() == {0: 1, 1: 1, 2: 0}
        assert e.false_negatives() == {0: 1, 1: 0, 2: 1}
        tn = e.true_negatives()
        # n=6: tn_c = 6 - tp - fp - fn
        assert tn == {0: 2, 1: 4, 2: 4}

    def test_positive_negative_class_count(self):
        e = make_eval()
        assert e.positive() == {0: 3, 1: 1, 2: 2}
        assert e.negative() == {0: 3, 1: 5, 2: 4}
        assert e.class_count(0) == 3
        assert e.get_num_row_counter() == 6

    def test_rates(self):
        e = make_eval()
        assert e.false_negative_rate(0) == pytest.approx(1 / 3)
        assert e.false_negative_rate(1) == 0.0
        assert 0.0 < e.false_alarm_rate() < 1.0

    def test_class_labels_and_confusion_string(self):
        e = make_eval()
        assert e.get_class_label(0) == "ant"
        assert e.get_class_label(2) == "cow"
        s = e.confusion_to_string()
        assert "ant" in s and "bee" in s and "cow" in s
        assert "Actual (rowClass)" in s

    def test_reset(self):
        e = make_eval()
        e.reset()
        assert e.get_num_row_counter() == 0
        assert e.num_classes == 3  # labels_list keeps the class count
        # usable again after reset
        e.eval(np.eye(3)[[0, 1]], np.eye(3)[[0, 1]])
        assert e.accuracy() == 1.0

    def test_num_classes_excluded(self):
        e = Evaluation()
        # class 2 never appears (true or predicted)
        labels = np.eye(3)[[0, 1, 0]]
        preds = np.eye(3)[[0, 1, 1]]
        e.eval(labels, preds)
        assert e.average_f1_num_classes_excluded() == 1
        assert e.average_precision_num_classes_excluded() == 1

    def test_top_n_counters(self):
        e = Evaluation(top_n=2)
        labels = np.eye(3)[[0, 1]]
        preds = np.asarray([[0.2, 0.5, 0.3],   # true 0 is rank 3 -> not top2
                            [0.4, 0.5, 0.1]])  # true 1 is rank 1 -> top2
        e.eval(labels, preds)
        assert e.get_top_n_total_count() == 2
        assert e.get_top_n_correct_count() == 1


class TestRegressionSurface:
    """RegressionEvaluation averageX()/numColumns/reset/scoreForMetric."""

    def _ev(self):
        from deeplearning4j_tpu.eval.regression import RegressionEvaluation
        rng = np.random.RandomState(0)
        labels = rng.randn(50, 3)
        preds = labels + 0.1 * rng.randn(50, 3)
        e = RegressionEvaluation()
        e.eval(labels, preds)
        return e

    def test_averages_and_columns(self):
        e = self._ev()
        assert e.num_columns() == 3
        assert e.average_mean_squared_error() == pytest.approx(
            np.mean([e.mean_squared_error(c) for c in range(3)]))
        assert e.average_pearson_correlation() > 0.9
        assert e.average_r_squared() > 0.9
        assert e.average_root_mean_squared_error() > 0

    def test_score_for_metric(self):
        e = self._ev()
        assert e.score_for_metric("mse") == e.average_mean_squared_error()
        assert e.score_for_metric("R2") == e.average_r_squared()
        with pytest.raises(ValueError):
            e.score_for_metric("nope")

    def test_reset(self):
        e = self._ev()
        e.reset()
        assert e.num_columns() == 0 and e.n == 0
        e.eval(np.ones((4, 2)), np.ones((4, 2)))
        assert e.mean_squared_error(0) == 0.0


class TestEvaluateRocBinary:
    def test_masks_honored(self):
        """evaluate_roc_binary drops masked timesteps like evaluate_roc."""
        from deeplearning4j_tpu.nn.conf.network import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.layers.output import RnnOutputLayer
        from deeplearning4j_tpu.nn.layers.recurrent import SimpleRnnLayer
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        from deeplearning4j_tpu.datasets.dataset import DataSet, ListDataSetIterator

        conf = (NeuralNetConfiguration.builder().seed(5).updater("sgd").list()
                .layer(SimpleRnnLayer(n_in=2, n_out=4))
                .layer(RnnOutputLayer(n_in=4, n_out=2, activation="sigmoid",
                                      loss="xent"))
                .build())
        net = MultiLayerNetwork(conf).init()
        rng = np.random.RandomState(0)
        x = rng.randn(6, 5, 2).astype(np.float32)
        y = (rng.rand(6, 5, 2) > 0.5).astype(np.float32)
        mask = np.ones((6, 5), np.float32)
        mask[:, 3:] = 0  # last two steps padded
        it = ListDataSetIterator(DataSet(x, y, mask, mask), 6)
        roc = net.evaluate_roc_binary(it)
        assert roc.num_labels() == 2
        # masked eval == hand-trimmed eval (padded steps really dropped)
        from deeplearning4j_tpu.eval.roc import ROCBinary
        out = np.asarray(net.output(x))
        manual = ROCBinary()
        manual.eval(y[:, :3].reshape(-1, 2), out[:, :3].reshape(-1, 2))
        assert roc.calculate_auc(0) == pytest.approx(manual.calculate_auc(0))
        # and differs from the unmasked curve (padding would bias it)
        unmasked = ROCBinary()
        unmasked.eval(y.reshape(-1, 2), out.reshape(-1, 2))
        assert roc.calculate_auc(0) != pytest.approx(unmasked.calculate_auc(0))

    def test_reset_restores_constructor_classes(self):
        e = Evaluation(num_classes=5)
        e.eval(np.eye(3)[[0, 1]], np.eye(3)[[0, 1]])
        assert e.num_classes == 5
        e.reset()
        assert e.num_classes == 5
        with pytest.raises(ValueError):
            e.negative()  # consistent _check before data


class TestRocMultiClassTimeSeries:
    def test_3d_input_flattens_with_mask(self):
        from deeplearning4j_tpu.eval.roc import ROCMultiClass
        rng = np.random.RandomState(0)
        n, t, c = 4, 5, 3
        y = np.eye(c)[rng.randint(0, c, (n, t))]
        p = rng.rand(n, t, c)
        p /= p.sum(-1, keepdims=True)
        mask = np.ones((n, t)); mask[:, 3:] = 0
        roc = ROCMultiClass()
        roc.eval(y, p, mask=mask)
        assert roc.num_classes() == c
        manual = ROCMultiClass()
        manual.eval(y[:, :3].reshape(-1, c), p[:, :3].reshape(-1, c))
        for cls in range(c):
            assert roc.calculate_auc(cls) == pytest.approx(
                manual.calculate_auc(cls))
