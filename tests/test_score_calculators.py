"""Early-stopping score calculators beyond DataSetLoss/Classification.

Reference: earlystopping/scorecalc — RegressionScoreCalculator,
ROCScoreCalculator, AutoencoderScoreCalculator,
VAEReconErrorScoreCalculator, VAEReconProbScoreCalculator.
"""

import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import DataSet, ListDataSetIterator
from deeplearning4j_tpu.nn.conf.network import NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers.autoencoder import AutoEncoderLayer
from deeplearning4j_tpu.nn.layers.core import DenseLayer
from deeplearning4j_tpu.nn.layers.output import OutputLayer
from deeplearning4j_tpu.nn.layers.vae import VariationalAutoencoderLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.optimize.earlystopping import (
    AutoencoderScoreCalculator,
    ROCScoreCalculator,
    RegressionScoreCalculator,
    VAEReconErrorScoreCalculator,
    VAEReconProbScoreCalculator,
)


def regression_net():
    conf = (NeuralNetConfiguration.builder().seed(1).updater("adam").list()
            .layer(DenseLayer(n_in=3, n_out=8, activation="relu"))
            .layer(OutputLayer(n_in=8, n_out=2, activation="identity",
                               loss="mse"))
            .build())
    return MultiLayerNetwork(conf).init()


def classifier_net(n_out=2):
    conf = (NeuralNetConfiguration.builder().seed(1).updater("adam").list()
            .layer(DenseLayer(n_in=3, n_out=8, activation="relu"))
            .layer(OutputLayer(n_in=8, n_out=n_out))
            .build())
    return MultiLayerNetwork(conf).init()


def _reg_iter(seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(64, 3).astype(np.float32)
    y = np.stack([x.sum(1), x[:, 0] - x[:, 1]], axis=1).astype(np.float32)
    return ListDataSetIterator(DataSet(x, y), 32)


def _cls_iter(seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(64, 3).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[(x.sum(1) > 0).astype(int)]
    return ListDataSetIterator(DataSet(x, y), 32)


class TestRegressionScoreCalculator:
    def test_mse_improves_with_training(self):
        net = regression_net()
        calc = RegressionScoreCalculator(_reg_iter(), metric="MSE")
        before = calc.calculate_score(net)
        net.fit(_reg_iter(), epochs=30)
        after = calc.calculate_score(net)
        assert after < before

    def test_maximized_metrics_negated(self):
        net = regression_net()
        net.fit(_reg_iter(), epochs=30)
        r2 = RegressionScoreCalculator(_reg_iter(), metric="R2")
        score = r2.calculate_score(net)
        assert score < 0  # good R2 -> negative score (lower is better)


class TestROCScoreCalculator:
    def test_binary_auc(self):
        net = classifier_net()
        calc = ROCScoreCalculator(_cls_iter(), roc_type="roc", metric="auc")
        net.fit(_cls_iter(), epochs=60)
        score = calc.calculate_score(net)
        assert 0.0 <= score < 0.5  # AUC > 0.5 after training

    def test_multiclass(self):
        net = classifier_net()
        calc = ROCScoreCalculator(_cls_iter(), roc_type="multiclass")
        s = calc.calculate_score(net)
        assert 0.0 <= s <= 1.0

    def test_bad_args(self):
        with pytest.raises(ValueError):
            ROCScoreCalculator(None, roc_type="nope")
        with pytest.raises(ValueError):
            ROCScoreCalculator(None, metric="nope")


class TestAutoencoderScoreCalculator:
    def test_reconstruction_improves(self):
        conf = (NeuralNetConfiguration.builder().seed(3).updater("adam").list()
                .layer(AutoEncoderLayer(n_in=4, n_out=2))
                .layer(OutputLayer(n_in=2, n_out=4, activation="identity",
                                   loss="mse"))
                .build())
        net = MultiLayerNetwork(conf).init()
        rng = np.random.RandomState(0)
        x = rng.rand(64, 4).astype(np.float32)
        it = ListDataSetIterator(DataSet(x, x), 32)
        calc = AutoencoderScoreCalculator(it, layer_index=0)
        before = calc.calculate_score(net)
        net.pretrain_layer(0, ListDataSetIterator(DataSet(x, x), 32),
                           epochs=40)
        after = calc.calculate_score(net)
        assert np.isfinite(before) and np.isfinite(after)
        assert after < before


class TestVAECalculators:
    def _vae_net(self, recon):
        conf = (NeuralNetConfiguration.builder().seed(4).updater("adam").list()
                .layer(VariationalAutoencoderLayer(
                    n_in=4, n_out=2, encoder_layer_sizes=(8,),
                    decoder_layer_sizes=(8,),
                    reconstruction_distribution=recon))
                .layer(OutputLayer(n_in=2, n_out=2))
                .build())
        return MultiLayerNetwork(conf).init()

    def test_recon_error_loss_config(self):
        from deeplearning4j_tpu.nn.layers.vae_distributions import LossFunctionWrapper
        net = self._vae_net(LossFunctionWrapper(activation="sigmoid", loss="mse"))
        rng = np.random.RandomState(0)
        x = rng.rand(32, 4).astype(np.float32)
        it = ListDataSetIterator(DataSet(x, x), 16)
        score = VAEReconErrorScoreCalculator(it, 0).calculate_score(net)
        assert np.isfinite(score) and score >= 0

    def test_recon_prob_probabilistic_config(self):
        net = self._vae_net("bernoulli")
        rng = np.random.RandomState(0)
        x = (rng.rand(32, 4) > 0.5).astype(np.float32)
        it = ListDataSetIterator(DataSet(x, x), 16)
        score = VAEReconProbScoreCalculator(it, 0, num_samples=2)
        v = score.calculate_score(net)
        assert np.isfinite(v)
        assert v > 0  # -(negative log prob sum)/n of an untrained model


class TestCalculatorsOnComputationGraph:
    def test_autoencoder_calculator_on_graph_vertex(self):
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        conf = (NeuralNetConfiguration.builder().seed(3).updater("adam")
                .graph_builder()
                .add_inputs("in")
                .add_layer("ae", AutoEncoderLayer(n_in=4, n_out=2), "in")
                .add_layer("out", OutputLayer(n_in=2, n_out=4,
                                              activation="identity",
                                              loss="mse"), "ae")
                .set_outputs("out")
                .build())
        g = ComputationGraph(conf)
        g.init()
        rng = np.random.RandomState(0)
        x = rng.rand(32, 4).astype(np.float32)
        it = ListDataSetIterator(DataSet(x, x), 16)
        score = AutoencoderScoreCalculator(it, layer_index="ae").calculate_score(g)
        assert np.isfinite(score) and score >= 0

    def test_roc_binary_calculator_on_graph(self):
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        conf = (NeuralNetConfiguration.builder().seed(3).updater("adam")
                .graph_builder()
                .add_inputs("in")
                .add_layer("d", DenseLayer(n_in=3, n_out=8, activation="relu"), "in")
                .add_layer("out", OutputLayer(n_in=8, n_out=2,
                                              activation="sigmoid",
                                              loss="xent"), "d")
                .set_outputs("out")
                .build())
        g = ComputationGraph(conf)
        g.init()
        rng = np.random.RandomState(0)
        x = rng.randn(32, 3).astype(np.float32)
        y = (rng.rand(32, 2) > 0.5).astype(np.float32)
        it = ListDataSetIterator(DataSet(x, y), 16)
        s = ROCScoreCalculator(it, roc_type="binary").calculate_score(g)
        assert 0.0 <= s <= 1.0
