"""Orbax-backed checkpointing: save/restore round trips, async writes,
step-managed rotation.

The zip path (util/model_serializer.py) is the DL4J interchange; this is
the TPU-idiomatic path (sharding-aware orbax writes + CheckpointManager
retention, the CheckpointListener keepLast role at pod scale).
"""

import numpy as np
import pytest

from deeplearning4j_tpu.nn.conf.network import NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers.core import DenseLayer
from deeplearning4j_tpu.nn.layers.output import OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.util.orbax_checkpoint import (
    OrbaxCheckpointManager,
    restore_model,
    save_model,
)


def trained_net(steps=5, seed=1):
    conf = (NeuralNetConfiguration.builder().seed(seed).updater("adam").list()
            .layer(DenseLayer(n_in=3, n_out=8, activation="relu"))
            .layer(OutputLayer(n_in=8, n_out=2))
            .build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.RandomState(0)
    x = rng.randn(16, 3).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[rng.randint(0, 2, 16)]
    for _ in range(steps):
        net.fit(x, y)
    return net, x, y


class TestSaveRestore:
    def test_round_trip_outputs_and_counters(self, tmp_path):
        net, x, _ = trained_net()
        d = str(tmp_path / "ckpt")
        save_model(net, d)
        again = restore_model(d)
        np.testing.assert_allclose(np.asarray(again.output(x)),
                                   np.asarray(net.output(x)), rtol=1e-6)
        assert again.iteration == net.iteration
        assert again.epoch == net.epoch

    def test_updater_state_resume_equality(self, tmp_path):
        """Training after restore == training without the save/restore."""
        net, x, y = trained_net()
        d = str(tmp_path / "ckpt")
        save_model(net, d)
        for _ in range(3):
            net.fit(x, y)
        resumed = restore_model(d)
        for _ in range(3):
            resumed.fit(x, y)
        for a, b in zip(net.params, resumed.params):
            for k in a:
                np.testing.assert_allclose(np.asarray(a[k]),
                                           np.asarray(b[k]), rtol=2e-5,
                                           atol=1e-6)

    def test_async_write(self, tmp_path):
        net, x, _ = trained_net()
        d = str(tmp_path / "async")
        handle = save_model(net, d, async_write=True)
        assert handle is not None
        handle.wait_until_finished()
        again = restore_model(d)
        np.testing.assert_allclose(np.asarray(again.output(x)),
                                   np.asarray(net.output(x)), rtol=1e-6)

    def test_updater_flag_mismatch_both_directions(self, tmp_path):
        """Checkpoint without updater restores with default flags and
        vice versa (template matches what is actually on disk)."""
        net, x, _ = trained_net()
        d1 = str(tmp_path / "no_updater")
        save_model(net, d1, save_updater=False)
        again = restore_model(d1)  # load_updater=True against a bare ckpt
        np.testing.assert_allclose(np.asarray(again.output(x)),
                                   np.asarray(net.output(x)), rtol=1e-6)
        d2 = str(tmp_path / "with_updater")
        save_model(net, d2, save_updater=True)
        bare = restore_model(d2, load_updater=False)
        np.testing.assert_allclose(np.asarray(bare.output(x)),
                                   np.asarray(net.output(x)), rtol=1e-6)

    def test_manager_updater_flag_mismatch(self, tmp_path):
        net, x, y = trained_net(steps=1)
        with OrbaxCheckpointManager(str(tmp_path / "m")) as mgr:
            assert mgr.save(0, net, save_updater=False)
            mgr.wait_until_finished()
            restored = mgr.restore()
            np.testing.assert_allclose(np.asarray(restored.output(x)),
                                       np.asarray(net.output(x)), rtol=1e-6)

    def test_graph_round_trip(self, tmp_path):
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        conf = (NeuralNetConfiguration.builder().seed(2).updater("sgd")
                .graph_builder().add_inputs("in")
                .add_layer("d", DenseLayer(n_in=3, n_out=4), "in")
                .add_layer("out", OutputLayer(n_in=4, n_out=2), "d")
                .set_outputs("out").build())
        g = ComputationGraph(conf).init()
        d = str(tmp_path / "g")
        save_model(g, d)
        again = restore_model(d)
        x = np.ones((2, 3), np.float32)
        np.testing.assert_allclose(np.asarray(again.output_single(x)),
                                   np.asarray(g.output_single(x)), rtol=1e-6)


class TestManagerRotation:
    def test_keep_last_and_latest_restore(self, tmp_path):
        net, x, y = trained_net(steps=1)
        d = str(tmp_path / "mgr")
        with OrbaxCheckpointManager(d, max_to_keep=2) as mgr:
            for step in range(5):
                net.fit(x, y)
                assert mgr.save(step, net)
            mgr.wait_until_finished()
            assert mgr.latest_step() == 4
            assert len(mgr.all_steps()) == 2  # rotation kept last 2
            restored = mgr.restore()
            np.testing.assert_allclose(np.asarray(restored.output(x)),
                                       np.asarray(net.output(x)), rtol=1e-6)

    def test_save_interval(self, tmp_path):
        net, x, y = trained_net(steps=1)
        d = str(tmp_path / "mgr2")
        with OrbaxCheckpointManager(d, max_to_keep=None,
                                    save_interval_steps=2) as mgr:
            saved = [mgr.save(s, net) for s in range(4)]
            mgr.wait_until_finished()
            assert saved == [True, False, True, False]

    def test_restore_empty_raises(self, tmp_path):
        with OrbaxCheckpointManager(str(tmp_path / "empty")) as mgr:
            with pytest.raises(ValueError):
                mgr.restore()


class TestPreemptionOrbaxBackend:
    def test_orbax_backend_save_resume(self, tmp_path):
        from deeplearning4j_tpu.util.preemption import PreemptionHandler
        net, x, y = trained_net()
        d = str(tmp_path / "preempt_ckpt")
        handler = PreemptionHandler(net, d, backend="orbax")
        handler.save()
        model, state = PreemptionHandler.resume(d)
        assert state["iteration"] == net.iteration
        np.testing.assert_allclose(np.asarray(model.output(x)),
                                   np.asarray(net.output(x)), rtol=1e-6)

    def test_orbax_backend_second_save_keeps_previous(self, tmp_path):
        """Rotation means the earlier checkpoint is still on disk while
        (and after) the new one commits — the grace-window durability the
        zip path gets from tmp+os.replace."""
        import os
        from deeplearning4j_tpu.util.preemption import PreemptionHandler
        net, x, y = trained_net()
        d = str(tmp_path / "preempt2")
        handler = PreemptionHandler(net, d, backend="orbax")
        handler.save()
        net.fit(x, y)
        handler.save()
        steps = sorted(int(p) for p in os.listdir(d) if p.isdigit())
        assert len(steps) == 2  # both checkpoints retained (max_to_keep=2)
        model, state = PreemptionHandler.resume(d)
        assert state["iteration"] == net.iteration  # latest wins

    def test_bad_backend_rejected(self, tmp_path):
        from deeplearning4j_tpu.util.preemption import PreemptionHandler
        net, _, _ = trained_net(steps=1)
        with pytest.raises(ValueError):
            PreemptionHandler(net, str(tmp_path / "x"), backend="tape")


class TestSchemePaths:
    def test_url_paths_not_mangled(self):
        """gs:// checkpoint dirs must not be abspath'd into <cwd>/gs:/…
        (they route through etils epath instead)."""
        from deeplearning4j_tpu.util.orbax_checkpoint import _canonical_dir
        assert _canonical_dir("gs://bucket/ckpt") == "gs://bucket/ckpt"
        assert _canonical_dir("s3://bucket/ckpt") == "s3://bucket/ckpt"

    def test_local_paths_absolutized(self, tmp_path, monkeypatch):
        from deeplearning4j_tpu.util.orbax_checkpoint import _canonical_dir
        monkeypatch.chdir(tmp_path)
        assert _canonical_dir("ckpt") == str(tmp_path / "ckpt")
