"""Worker process for the 2-process jax.distributed test.

Launched twice by tests/test_multiprocess.py (process_id 0 and 1). Each
process owns ONE CPU device; together they form a 2-device global mesh and
run SharedTrainingMaster over it — the reference's multi-node gradient-
sharing topology (`SharedTrainingMaster.java:493`), with the JAX
coordination service standing in for the Aeron introduction protocol and
Gloo-backed CPU collectives for the UDP gradient messages.

Determinism contract: both processes generate identical data and seeds, so
the single-controller "broadcast" is plain identical host computation.
Process 0 writes the final params to OUT as npz.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("XLA_FLAGS", None)  # exactly ONE local CPU device

import jax

jax.config.update("jax_platforms", "cpu")


def main():
    coordinator, pid, out_path = sys.argv[1], int(sys.argv[2]), sys.argv[3]
    from deeplearning4j_tpu.parallel import init_distributed

    init_distributed(coordinator_address=coordinator, num_processes=2,
                     process_id=pid)
    assert jax.device_count() == 2, jax.devices()
    assert len(jax.local_devices()) == 1

    import numpy as np

    from deeplearning4j_tpu.datasets.dataset import DataSet, ListDataSetIterator
    from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.nn.updaters import Sgd
    from deeplearning4j_tpu.parallel import (
        DistributedMultiLayerNetwork,
        SharedTrainingMaster,
    )
    from deeplearning4j_tpu.parallel.mesh import make_mesh

    conf = (NeuralNetConfiguration.builder().seed(7).updater(Sgd(0.05)).list()
            .layer(DenseLayer(n_out=12, activation="tanh"))
            .layer(OutputLayer(n_out=3))
            .set_input_type(InputType.feed_forward(6)).build())
    net = MultiLayerNetwork(conf).init()

    rng = np.random.default_rng(0)
    yc = rng.integers(0, 3, 256)
    x = rng.normal(size=(256, 6)).astype(np.float32)
    x[np.arange(256), yc] += 2.5
    y = np.eye(3, dtype=np.float32)[yc]

    mesh = make_mesh({"data": 2})  # spans BOTH processes
    master = SharedTrainingMaster(batch_size_per_worker=16, threshold=1e-3,
                                  mesh=mesh)
    front = DistributedMultiLayerNetwork(net, master)
    it = ListDataSetIterator(DataSet(x, y), 32)
    front.fit(it, epochs=3)

    if pid == 0:
        flat = {}
        for i, layer in enumerate(net.params):
            for k, v in layer.items():
                flat[f"{i}:{k}"] = np.asarray(v)
        flat["score"] = np.float32(net.score_)
        np.savez(out_path, **flat)
        print("WORKER0_DONE", flush=True)
    else:
        print("WORKER1_DONE", flush=True)


if __name__ == "__main__":
    main()
