"""Unified observability layer: span correctness, exporters, metrics.

Covers the ISSUE-4 satellite checklist: nesting across threads (the
dispatcher handoff), Chrome trace-event schema validation of the exporter
output (via ``tools/validate_trace.py`` — the same checker the smoke
example runs), ``/metrics`` round-tripping the new ``training_*`` series
through ``parse_prometheus_text``, ``TraceListener`` surviving a throwing
peer listener, and the ``serving.metrics`` deprecation re-export.
"""

import json
import os
import sys
import threading
import time

import numpy as np
import pytest

from deeplearning4j_tpu.observe import (MetricsRegistry, Span, TraceListener,
                                        TraceRecorder, Tracer,
                                        disable_tracing, enable_tracing,
                                        get_active_tracer,
                                        parse_prometheus_text,
                                        parse_traceparent, text_timeline,
                                        to_chrome_trace, write_chrome_trace)
from deeplearning4j_tpu.observe import trace as trace_mod

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOLS = os.path.join(REPO, "tools")
if TOOLS not in sys.path:
    sys.path.insert(0, TOOLS)
from validate_trace import validate_events, validate_file  # noqa: E402


@pytest.fixture
def tracer():
    tr = enable_tracing(Tracer(TraceRecorder(capacity=4096)), jax_hook=False)
    yield tr
    disable_tracing()


def _by_name(tr):
    out = {}
    for s in tr.recorder.spans():
        out.setdefault(s.name, []).append(s)
    return out


def _await_span(tr, name, timeout=5.0):
    """Spans record at span EXIT; a server may still be closing its span
    when the client already has the response — poll briefly."""
    deadline = time.time() + timeout
    while time.time() < deadline:
        spans = _by_name(tr)
        if name in spans:
            return spans
        time.sleep(0.005)
    raise AssertionError(f"span {name!r} never recorded; "
                         f"saw {sorted(_by_name(tr))}")


# ---------------------------------------------------------------- span core
class TestSpanCore:
    def test_nesting_same_thread(self, tracer):
        with tracer.span("outer") as outer:
            with tracer.span("inner") as inner:
                assert inner.trace_id == outer.trace_id
                assert inner.parent_id == outer.span_id
            with tracer.span("inner2") as inner2:
                assert inner2.parent_id == outer.span_id
        assert outer.parent_id is None
        spans = tracer.recorder.spans()
        assert [s.name for s in spans] == ["inner", "inner2", "outer"]
        assert all(s.end_ns is not None and s.end_ns >= s.start_ns
                   for s in spans)

    def test_exception_closes_and_marks(self, tracer):
        with pytest.raises(ValueError):
            with tracer.span("bad"):
                raise ValueError("boom")
        (sp,) = tracer.recorder.spans()
        assert sp.end_ns is not None
        assert "boom" in sp.error
        # context is restored after the failed span
        assert tracer.current_context() is None

    def test_record_after_the_fact(self, tracer):
        t1 = time.perf_counter_ns()
        sp = tracer.record("window", t1 - 1000, t1, attrs={"k": 1})
        assert sp.end_ns - sp.start_ns == 1000
        assert tracer.recorder.spans()[0] is sp

    def test_ring_buffer_drops_oldest(self):
        rec = TraceRecorder(capacity=8)
        tr = Tracer(rec)
        for i in range(20):
            now = time.perf_counter_ns()
            tr.record(f"s{i}", now - 10, now)
        assert len(rec) == 8
        assert rec.total_recorded == 20
        assert rec.dropped == 12
        assert [s.name for s in rec.spans()] == [f"s{i}" for i in range(12, 20)]

    def test_inactive_module_span_is_noop(self):
        assert get_active_tracer() is None
        with trace_mod.span("nothing") as sp:
            assert sp is None


# ----------------------------------------------------------- W3C traceparent
class TestTraceparent:
    def test_round_trip(self, tracer):
        with tracer.span("a") as sp:
            header = tracer.current_traceparent()
            ctx = parse_traceparent(header)
            assert ctx.trace_id == sp.trace_id
            assert ctx.span_id == sp.span_id
            assert header == f"00-{sp.trace_id}-{sp.span_id}-01"

    @pytest.mark.parametrize("bad", [
        None, "", "garbage", "00-abc-def-01",
        "00-" + "0" * 32 + "-" + "1" * 16 + "-01",   # all-zero trace id
        "00-" + "1" * 32 + "-" + "0" * 16 + "-01",   # all-zero span id
        "ff-" + "1" * 32 + "-" + "2" * 16 + "-01",   # forbidden version
        "00-" + "x" * 32 + "-" + "2" * 16 + "-01",   # non-hex
        "00-" + "1" * 31 + "-" + "2" * 16 + "-01",   # short trace id
    ])
    def test_malformed_rejected(self, bad):
        assert parse_traceparent(bad) is None

    def test_remote_parent_adopted(self, tracer):
        remote = parse_traceparent("00-" + "ab" * 16 + "-" + "cd" * 8 + "-01")
        with tracer.span("server_side", parent=remote) as sp:
            assert sp.trace_id == "ab" * 16
            assert sp.parent_id == "cd" * 8


# ------------------------------------------------------- cross-thread handoff
class TestThreadHandoff:
    def test_explicit_handoff_parents_correctly(self, tracer):
        handed = {}

        def worker(ctx):
            # a fresh thread has NO inherited context...
            assert tracer.current_context() is None
            # ...until the handed-off parent is used explicitly
            with tracer.span("worker_task", parent=ctx) as sp:
                handed["span"] = sp

        with tracer.span("producer") as prod:
            t = threading.Thread(target=worker, args=(prod.context,))
            t.start()
            t.join()
        assert handed["span"].trace_id == prod.trace_id
        assert handed["span"].parent_id == prod.span_id

    def test_threads_do_not_leak_context(self, tracer):
        seen = []

        def worker():
            seen.append(tracer.current_context())
            with tracer.span("rooted") as sp:
                seen.append(sp.parent_id)

        with tracer.span("main_span"):
            t = threading.Thread(target=worker)
            t.start()
            t.join()
        assert seen == [None, None]  # new root, not a stolen parent


# ------------------------------------------------------------------ exporter
class TestChromeExporter:
    def _sample_spans(self, tracer):
        with tracer.span("root", attrs={"answer": 42, "obj": object()}):
            with tracer.span("child"):
                pass
        # a linked pair (the request → batch shape)
        with tracer.span("request") as req:
            pass
        sp = tracer.start_span("batch", category="serve")
        sp.add_link(req.context)
        tracer.end_span(sp)
        return tracer.recorder.spans()

    def test_schema_valid(self, tracer, tmp_path):
        spans = self._sample_spans(tracer)
        path = tmp_path / "t.json"
        obj = write_chrome_trace(path, spans)
        assert validate_file(str(path)) == []
        assert json.load(open(path)) == obj

    def test_event_contents(self, tracer):
        spans = self._sample_spans(tracer)
        obj = to_chrome_trace(spans)
        events = obj["traceEvents"]
        assert validate_events(obj) == []
        x = [e for e in events if e["ph"] == "X"]
        names = {e["name"] for e in x}
        assert {"root", "child", "request", "batch"} <= names
        child = next(e for e in x if e["name"] == "child")
        root = next(e for e in x if e["name"] == "root")
        assert child["args"]["parent_id"] == root["args"]["span_id"]
        # non-serializable attr values are stringified, never dropped
        assert isinstance(root["args"]["obj"], str)
        assert root["args"]["answer"] == 42
        # the link became one flow start + one flow finish with the same id
        flows = [e for e in events if e["ph"] in ("s", "f")]
        assert len(flows) == 2
        assert flows[0]["id"] == flows[1]["id"]
        # metadata names the process and each thread
        assert any(e["ph"] == "M" and e["name"] == "process_name"
                   for e in events)
        assert any(e["ph"] == "M" and e["name"] == "thread_name"
                   for e in events)

    def test_timestamps_normalized_microseconds(self, tracer):
        with tracer.span("a"):
            time.sleep(0.01)
        obj = to_chrome_trace(tracer.recorder.spans())
        (x,) = [e for e in obj["traceEvents"] if e["ph"] == "X"]
        assert x["ts"] == 0.0
        assert 5_000 < x["dur"] < 1_000_000  # ~10ms in us

    def test_empty_trace_still_valid(self):
        obj = to_chrome_trace([])
        assert validate_events(obj) == []

    def test_non_finite_attrs_stay_strict_json(self, tracer, tmp_path):
        # a diverged run's loss=NaN must not make the trace unloadable
        with tracer.span("diverged", attrs={"loss": float("nan"),
                                            "lr": float("inf")}):
            pass
        path = tmp_path / "nan.json"
        write_chrome_trace(path, tracer.recorder.spans())
        text = open(path).read()
        json.loads(text)  # and no bare NaN/Infinity tokens in the payload
        assert "NaN" not in text.replace('"nan"', "")
        assert validate_file(str(path)) == []
        x = next(e for e in json.load(open(path))["traceEvents"]
                 if e["ph"] == "X")
        assert x["args"]["loss"] == "nan"
        assert validate_events(  # the validator itself flags raw NaN
            {"traceEvents": [{"ph": "X", "name": "a", "ts": 0, "dur": 1,
                              "pid": 1, "tid": 1,
                              "args": {"v": float("nan")}}]})

    def test_text_timeline(self, tracer):
        with tracer.span("outer", attrs={"k": "v"}):
            with tracer.span("inner"):
                pass
        text = text_timeline(tracer.recorder.spans())
        lines = text.splitlines()  # time-ordered: outer first
        assert len(lines) == 2
        assert "outer" in lines[0] and "inner" in lines[1]
        assert lines[1].index("inner") > lines[0].index("outer")  # indent
        assert "k=v" in lines[0]

    def test_validator_flags_garbage(self):
        assert validate_events({"nope": []})
        assert validate_events({"traceEvents": [{"ph": "X", "name": "a"}]})
        assert validate_events(
            {"traceEvents": [{"ph": "Q", "pid": 1, "tid": 1, "ts": 0}]})
        assert validate_events(
            {"traceEvents": [{"ph": "f", "name": "l", "pid": 1, "tid": 1,
                              "ts": 0, "id": 7}]})  # flow end w/o start


# ------------------------------------------------------------ compile hook
class TestJaxCompileHook:
    def test_compile_becomes_span_and_metric(self):
        import jax
        import jax.numpy as jnp
        metrics = MetricsRegistry()
        tr = enable_tracing(Tracer(metrics=metrics))
        try:
            with tr.span("step"):
                # a distinctive shape/closure → guaranteed fresh compile
                jax.jit(lambda v: v * 1.7183 + 0.5772)(
                    jnp.ones((3, 5, 7))).block_until_ready()
            spans = _by_name(tr)
            assert tr.compile_count >= 1
            assert "xla_compile" in spans
            # nested under the span that triggered it (same thread context)
            step = spans["step"][0]
            assert any(s.trace_id == step.trace_id
                       for s in spans["xla_compile"])
            assert metrics.counter("jax_compiles_total").value() >= 1
            assert metrics.counter("jax_compile_seconds_total").value() > 0
            # attribution is per thread: this thread paid, others did not
            assert tr.thread_compile_count() >= 1
            assert tr.thread_compile_count(thread_id=-1) == 0
        finally:
            disable_tracing()

    def test_other_threads_compiles_not_attributed_here(self):
        import jax
        import jax.numpy as jnp
        tr = enable_tracing(Tracer())
        try:
            before = tr.thread_compile_count()

            def compile_elsewhere():
                jax.jit(lambda v: v * 2.71828 - 1.0)(
                    jnp.ones((2, 9))).block_until_ready()

            t = threading.Thread(target=compile_elsewhere)
            t.start()
            t.join()
            assert tr.compile_count >= 1          # globally visible...
            assert tr.thread_compile_count() == before  # ...not charged here
            assert tr.thread_compile_count(thread_id=t.ident) >= 1
        finally:
            disable_tracing()

    def test_enable_tracing_attaches_metrics_to_explicit_tracer(self):
        metrics = MetricsRegistry()
        tr = enable_tracing(Tracer(TraceRecorder(128)), metrics=metrics,
                            jax_hook=False)
        try:
            assert tr.metrics is metrics
        finally:
            disable_tracing()


# ------------------------------------------------------------- TraceListener
def _tiny_net(seed=1, n_in=9):
    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.nn.updaters import Sgd
    conf = (NeuralNetConfiguration.builder().seed(seed).updater(Sgd(0.1))
            .list()
            .layer(DenseLayer(n_in=n_in, n_out=8, activation="relu"))
            .layer(OutputLayer(n_in=8, n_out=2, activation="softmax",
                               loss="negativeloglikelihood"))
            .build())
    return MultiLayerNetwork(conf).init()


def _tiny_data(n=32, n_in=9):
    rng = np.random.default_rng(0)
    x = rng.normal(size=(n, n_in)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[(x.sum(axis=1) > 0).astype(int)]
    from deeplearning4j_tpu.datasets.dataset import DataSet
    return DataSet(x, y)


class TestTraceListener:
    def test_metrics_round_trip_through_exposition(self, tracer):
        metrics = MetricsRegistry()
        net = _tiny_net()
        net.add_listeners(TraceListener(tracer, metrics, model_name="t"))
        net.fit(_tiny_data(), epochs=3)
        series = parse_prometheus_text(metrics.exposition())
        key = (("model", "t"),)
        assert series["training_steps_total"][key] == 3.0
        assert series["training_examples_total"][key] == 96.0
        assert series["training_epochs_total"][key] == 3.0
        assert series["training_step_seconds_count"][key] >= 1.0
        assert series["training_step_seconds_sum"][key] > 0.0
        assert ("training_score" in series)
        buckets = {k: v for k, v in
                   series["training_step_seconds_bucket"].items()}
        inf_key = (("le", "+Inf"), ("model", "t"))
        assert buckets[inf_key] == series["training_step_seconds_count"][key]

    def test_records_iteration_spans(self, tracer):
        net = _tiny_net(seed=2)
        net.add_listeners(TraceListener(tracer, MetricsRegistry()))
        net.fit(_tiny_data(), epochs=2)
        spans = _by_name(tracer)["train_iteration"]
        assert len(spans) >= 1  # first window of each epoch anchors only
        assert all(s.end_ns is not None for s in spans)
        assert all(s.attrs["batch"] == 32 for s in spans)

    def test_survives_throwing_peer_listener(self, tracer):
        class Bomb:
            def iteration_done(self, model, iteration, epoch):
                raise RuntimeError("peer exploded")

        metrics = MetricsRegistry()
        net = _tiny_net(seed=3)
        tl = TraceListener(tracer, metrics, model_name="t")
        net.add_listeners(tl, Bomb())
        with pytest.raises(RuntimeError, match="peer exploded"):
            net.fit(_tiny_data(), epochs=1)
        # the listener owns no open span state: nothing dangles, metrics
        # are consistent, and the next fit keeps working
        assert all(s.end_ns is not None for s in tracer.recorder.spans())
        assert metrics.counter("training_steps_total",
                               label_names=("model",)).value(model="t") == 1
        net.listeners = [tl]
        net.fit(_tiny_data(), epochs=1)
        assert metrics.counter("training_steps_total",
                               label_names=("model",)).value(model="t") == 2

    def test_step0_compile_counts_as_training(self):
        # the baseline anchors at on_epoch_start, BEFORE the first step,
        # so the first iteration's compile lands in training_compile_total
        metrics = MetricsRegistry()
        tr = enable_tracing(Tracer())
        try:
            net = _tiny_net(seed=11, n_in=13)  # distinct shape → compiles
            net.add_listeners(TraceListener(tr, metrics, model_name="c0"))
            net.fit(_tiny_data(n_in=13), epochs=1)
            assert metrics.counter(
                "training_compile_total",
                label_names=("model",)).value(model="c0") >= 1
        finally:
            disable_tracing()

    def test_without_tracer_still_exports_metrics(self):
        assert get_active_tracer() is None
        metrics = MetricsRegistry()
        net = _tiny_net(seed=4)
        net.add_listeners(TraceListener(None, metrics, model_name="m"))
        net.fit(_tiny_data(), epochs=1)
        assert metrics.counter("training_steps_total",
                               label_names=("model",)).value(model="m") == 1


# --------------------------------------------------- ParallelWrapper tracing
class TestParallelWrapperTracing:
    def test_step_spans_and_transfer_bytes(self, tracer):
        from deeplearning4j_tpu.parallel import ParallelWrapper
        metrics = MetricsRegistry()
        net = _tiny_net(seed=5)
        pw = ParallelWrapper(net, metrics=metrics, metrics_name="pw")
        ds = _tiny_data(n=32)
        pw.fit([ds], epochs=2)
        spans = _by_name(tracer)
        assert len(spans["parallel_fit"]) == 1
        steps = spans["train_step"]
        assert len(steps) == 2
        fit_span = spans["parallel_fit"][0]
        assert all(s.parent_id == fit_span.span_id for s in steps)
        assert all("loss" in s.attrs for s in steps)
        expected = 2 * (ds.features.nbytes + ds.labels.nbytes)
        assert metrics.counter(
            "training_transfer_bytes_total",
            label_names=("model",)).value(model="pw") == expected

    def test_untraced_fit_unchanged(self):
        from deeplearning4j_tpu.parallel import ParallelWrapper
        assert get_active_tracer() is None
        net = _tiny_net(seed=6)
        ParallelWrapper(net).fit([_tiny_data()], epochs=1)
        assert net.iteration == 1


# ------------------------------------------------ dispatcher (handoff) spans
class TestInferenceTracing:
    class Model:
        def output(self, x):
            return np.asarray(x) * 3.0

    def test_queue_wait_and_batch_execute_linked(self, tracer):
        from deeplearning4j_tpu.parallel.inference import ParallelInference
        pi = ParallelInference(self.Model(), mode="batched", wait_ms=1.0)
        try:
            with tracer.span("caller") as caller:
                out = pi.output(np.ones((4, 3)))
            assert out.shape == (4, 3)
        finally:
            pi.shutdown()
        spans = _by_name(tracer)
        (req,) = spans["inference_request"]
        (qw,) = spans["queue_wait"]
        (be,) = spans["batch_execute"]
        # request nests under the caller; queue_wait was recorded on the
        # DISPATCHER thread yet parents to the request span (the handoff)
        assert req.parent_id == spans["caller"][0].span_id
        assert qw.parent_id == req.span_id
        assert qw.trace_id == caller.trace_id
        assert qw.thread_id != req.thread_id
        assert qw.start_ns <= be.start_ns
        # batch links back to the request it served
        assert [l.span_id for l in be.links] == [req.span_id]
        assert be.attrs["requests"] == 1 and be.attrs["rows"] == 4

    def test_inplace_mode_span(self, tracer):
        from deeplearning4j_tpu.parallel.inference import ParallelInference
        pi = ParallelInference(self.Model(), mode="inplace")
        pi.output(np.ones((2, 3)))
        (sp,) = _by_name(tracer)["inference_request"]
        assert sp.attrs["mode"] == "inplace"

    def test_model_error_marks_batch_span(self, tracer):
        from deeplearning4j_tpu.parallel.inference import ParallelInference

        class Broken:
            def output(self, x):
                raise RuntimeError("device on fire")

        pi = ParallelInference(Broken(), mode="batched", wait_ms=1.0)
        try:
            with pytest.raises(RuntimeError, match="device on fire"):
                pi.output(np.ones((2, 2)))
        finally:
            pi.shutdown()
        (be,) = _by_name(tracer)["batch_execute"]
        assert "device on fire" in be.error


# ------------------------------------------------------- serving traceparent
class TestServingTraceparent:
    @pytest.fixture
    def served(self):
        from deeplearning4j_tpu.serving import (ModelRegistry, ModelServer,
                                                ModelServingClient)
        metrics = MetricsRegistry()
        registry = ModelRegistry(metrics=metrics, wait_ms=1.0)
        registry.register("m", model=TestInferenceTracing.Model())
        server = ModelServer(registry, metrics=metrics)
        server.start()
        client = ModelServingClient(server.url)
        yield server, client, metrics
        server.stop(drain=False, shutdown_registry=True)

    def test_client_sends_server_joins_and_echoes(self, served, tracer):
        server, client, _ = served
        with tracer.span("user") as user:
            out = client.predict("m", np.ones((3, 2)))
        assert out.shape == (3, 2)
        spans = _await_span(tracer, "http_request")
        (cp,) = spans["client_predict"]
        (hr,) = spans["http_request"]
        # ONE trace across the wire: client span parents the server span
        assert hr.trace_id == user.trace_id
        assert hr.parent_id == cp.span_id
        assert hr.attrs["status"] == 200
        # dispatcher spans joined the same trace through the request ctx
        assert spans["queue_wait"][0].trace_id == user.trace_id
        assert spans["batch_execute"][0].links
        # the echo carried the trace id back
        assert client.last_trace_id == user.trace_id
        assert cp.attrs["server_trace_id"] == user.trace_id

    def test_server_echoes_trace_id_even_untraced(self, served):
        server, client, _ = served
        disable_tracing()
        import urllib.request
        tid = "ab" * 16
        req = urllib.request.Request(
            server.url + "/v1/models/m/predict",
            data=json.dumps({"inputs": [[1.0, 2.0]]}).encode(),
            headers={"Content-Type": "application/json",
                     "traceparent": f"00-{tid}-{'cd' * 8}-01"})
        with urllib.request.urlopen(req, timeout=10) as resp:
            assert resp.status == 200
            assert resp.headers["X-Trace-Id"] == tid

    def test_no_stale_trace_headers_on_keepalive(self, served):
        # one handler instance serves MANY requests on an HTTP/1.1
        # connection: correlation headers must not leak between them
        server, _, _ = served
        import http.client
        body = json.dumps({"inputs": [[1.0, 2.0]]})
        tid = "ab" * 16
        conn = http.client.HTTPConnection("127.0.0.1", server.port,
                                          timeout=10)
        try:
            conn.request("POST", "/v1/models/m/predict", body=body,
                         headers={"Content-Type": "application/json",
                                  "traceparent": f"00-{tid}-{'cd' * 8}-01"})
            r1 = conn.getresponse()
            r1.read()
            assert r1.getheader("X-Trace-Id") == tid
            conn.request("POST", "/v1/models/m/predict", body=body,
                         headers={"Content-Type": "application/json"})
            r2 = conn.getresponse()
            r2.read()
            assert r2.getheader("X-Trace-Id") is None
            conn.request("GET", "/v1/models")
            r3 = conn.getresponse()
            r3.read()
            assert r3.getheader("X-Trace-Id") is None
        finally:
            conn.close()

    def test_malformed_traceparent_is_harmless(self, served, tracer):
        server, client, _ = served
        import urllib.request
        req = urllib.request.Request(
            server.url + "/v1/models/m/predict",
            data=json.dumps({"inputs": [[1.0, 2.0]]}).encode(),
            headers={"Content-Type": "application/json",
                     "traceparent": "utter-garbage"})
        with urllib.request.urlopen(req, timeout=10) as resp:
            assert resp.status == 200  # bad header never fails the request
        (hr,) = _await_span(tracer, "http_request")["http_request"]
        assert hr.parent_id is None  # fresh root, not a bogus parent

    def test_metrics_endpoint_serves_training_series(self, served, tracer):
        server, client, metrics = served
        net = _tiny_net(seed=7)
        net.add_listeners(TraceListener(tracer, metrics, model_name="co"))
        net.fit(_tiny_data(), epochs=1)
        series = client.metrics()  # scrape + parse round trip
        assert series["training_steps_total"][(("model", "co"),)] == 1.0
        assert "training_step_seconds_bucket" in series
        assert "inference_dispatcher_up" in series  # serve + train, 1 scrape


# --------------------------------------------------------- deprecation shim
class TestServingMetricsShim:
    def test_reexport_warns_and_aliases(self):
        for mod in list(sys.modules):
            if mod == "deeplearning4j_tpu.serving.metrics":
                del sys.modules[mod]
        with pytest.warns(DeprecationWarning, match="observe.metrics"):
            import deeplearning4j_tpu.serving.metrics as shim
        import deeplearning4j_tpu.observe.metrics as real
        assert shim.MetricsRegistry is real.MetricsRegistry
        assert shim.default_registry() is real.default_registry()
        assert shim.parse_prometheus_text is real.parse_prometheus_text
        assert shim.instrument_http is real.instrument_http
        assert shim.HTTPObserverMixin is real.HTTPObserverMixin

    def test_serving_package_surface_unchanged(self):
        from deeplearning4j_tpu.serving import (Counter, Gauge, Histogram,
                                                MetricsRegistry,
                                                default_registry,
                                                parse_prometheus_text)
        assert MetricsRegistry is not None
        assert callable(default_registry) and callable(parse_prometheus_text)
        assert Counter and Gauge and Histogram


# --------------------------------------------------------- stats listener fix
class TestStatsListenerDeviceMemory:
    def test_aggregates_device_stats_when_exposed(self, monkeypatch):
        from deeplearning4j_tpu.ui.stats import StatsListener
        from deeplearning4j_tpu.ui.storage import InMemoryStatsStorage

        class FakeDev:
            def __init__(self, i):
                self.i = i

            def __str__(self):
                return f"FakeTPU({self.i})"

            def memory_stats(self):
                return {"bytes_in_use": 100 * (self.i + 1),
                        "bytes_limit": 1000, "peak_bytes_in_use": 500}

        import jax
        monkeypatch.setattr(jax, "local_devices",
                            lambda: [FakeDev(0), FakeDev(1)])
        info = StatsListener(InMemoryStatsStorage())._memory_info()
        assert info["device_bytes_in_use"] == 300
        assert info["device_bytes_limit"] == 2000
        assert info["device_count"] == 2
        assert info["devices"][1]["peak_bytes_in_use"] == 500

    def test_cpu_only_backend_stays_host_only(self):
        from deeplearning4j_tpu.ui.stats import StatsListener
        from deeplearning4j_tpu.ui.storage import InMemoryStatsStorage
        info = StatsListener(InMemoryStatsStorage())._memory_info()
        # CPU devices expose no memory_stats: no device keys, no crash
        assert "max_rss_kb" in info
        assert "device_count" not in info or info["device_count"] > 0
