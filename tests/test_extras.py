"""Tests for iterator completions, LFW fetcher, node2vec, and the
mesh-sharded distributed Word2Vec (dl4j-spark-nlp role)."""

import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import DataSet, ListDataSetIterator
from deeplearning4j_tpu.datasets.iterators import (
    AsyncShieldDataSetIterator,
    DefaultCallback,
    ExistingDataSetIterator,
    FileSplitDataSetIterator,
    JointParallelDataSetIterator,
    ViewIterator,
)


def _ds(n=10, f=3, seed=0):
    rng = np.random.default_rng(seed)
    return DataSet(rng.normal(size=(n, f)).astype(np.float32),
                   rng.normal(size=(n, 2)).astype(np.float32))


class TestIteratorCompletions:
    def test_existing_iterator(self):
        batches = [_ds(4), _ds(4), _ds(4)]
        it = ExistingDataSetIterator(batches, total=2)
        assert len(list(it)) == 2
        assert len(list(it)) == 2  # re-iterable

    def test_view_iterator_masks(self):
        ds = DataSet(np.zeros((10, 4, 3), np.float32),
                     np.zeros((10, 4, 2), np.float32),
                     np.ones((10, 4), np.float32), None)
        parts = list(ViewIterator(ds, 4))
        assert [p.features.shape[0] for p in parts] == [4, 4, 2]
        assert parts[0].features_mask.shape == (4, 4)

    def test_file_split_iterator_with_callback(self, tmp_path):
        for i in range(3):
            d = _ds(6, seed=i)
            np.savez(tmp_path / f"part{i}.npz", features=d.features,
                     labels=d.labels)
        seen = []

        class Cb:
            def call(self, ds):
                seen.append(ds.features.shape)

        out = list(FileSplitDataSetIterator(str(tmp_path), callback=Cb()))
        assert len(out) == 3 and len(seen) == 3

    def test_default_callback_moves_to_device(self):
        import jax
        ds = _ds(4)
        DefaultCallback().call(ds)
        assert isinstance(ds.features, jax.Array)

    def test_async_shield_passthrough(self):
        base = ListDataSetIterator(_ds(8), 4)
        shield = AsyncShieldDataSetIterator(base)
        assert shield.async_supported is False
        assert len(list(shield)) == 2

    def test_joint_parallel_round_robin(self):
        a = ListDataSetIterator(_ds(8, seed=1), 4)
        b = ListDataSetIterator(_ds(4, seed=2), 4)
        out = list(JointParallelDataSetIterator(a, b,
                                                stop_on_first_exhausted=False))
        assert len(out) == 3  # a,b,a
        out2 = list(JointParallelDataSetIterator(a, b))
        assert len(out2) == 3  # a,b,a then b exhausted → stop

    def test_lfw_fetcher(self):
        from deeplearning4j_tpu.datasets.fetchers import LFWDataSetIterator
        it = LFWDataSetIterator(16, n_classes=5, image_size=32)
        b = next(iter(it))
        assert b.features.shape == (16, 32, 32, 3)
        assert b.labels.shape == (16, 5)


class TestNode2Vec:
    def _barbell(self):
        from deeplearning4j_tpu.graph import Graph
        g = Graph(10)
        for c in (0, 5):
            for i in range(c, c + 5):
                for j in range(i + 1, c + 5):
                    g.add_edge(i, j)
        g.add_edge(4, 5)
        return g

    def test_biased_walks_valid(self):
        from deeplearning4j_tpu.graph import Node2Vec
        g = self._barbell()
        nv = Node2Vec(vector_size=8, p=0.5, q=2.0, walks_per_vertex=3, seed=4)
        walks = nv.generate_walks(g, 8, np.random.default_rng(0))
        assert walks.shape == (30, 9)
        for w in walks[:10]:
            for a, b in zip(w, w[1:]):
                assert b in set(g.get_connected_vertex_indices(a)) or a == b

    def test_p_bias_controls_backtracking(self):
        from deeplearning4j_tpu.graph import Graph, Node2Vec
        g = self._barbell()

        def backtrack_rate(p):
            nv = Node2Vec(p=p, q=1.0, walks_per_vertex=20, seed=7)
            walks = nv.generate_walks(g, 10, np.random.default_rng(1))
            back = total = 0
            for w in walks:
                for t in range(2, len(w)):
                    total += 1
                    back += int(w[t] == w[t - 2])
            return back / total

        assert backtrack_rate(0.05) > backtrack_rate(20.0)

    def test_clusters_embed_separately(self):
        from deeplearning4j_tpu.graph import Node2Vec
        g = self._barbell()
        nv = Node2Vec(vector_size=16, window_size=2, learning_rate=0.05,
                      seed=11, walks_per_vertex=8)
        nv.fit(g, walk_length=10, epochs=15)
        intra = np.mean([nv.similarity(0, j) for j in range(1, 5)])
        inter = np.mean([nv.similarity(0, j) for j in range(5, 10)])
        assert intra > inter


class TestDistributedWord2Vec:
    CORPUS = (["the quick brown fox jumps over the lazy dog",
               "the dog sleeps while the fox runs",
               "quick brown animals jump high",
               "lazy dogs sleep all day"] * 10)

    def test_text_pipeline_counts(self):
        from deeplearning4j_tpu.nlp.distributed import TextPipeline
        counts = TextPipeline(num_shards=3).word_counts(self.CORPUS)
        assert counts["the"] == 40
        assert counts["fox"] == 20

    def test_mesh_training_learns(self):
        from deeplearning4j_tpu.nlp.distributed import DistributedWord2Vec
        from deeplearning4j_tpu.parallel.mesh import make_mesh
        w2v = DistributedWord2Vec(layer_size=16, window=3, negative=4,
                                  learning_rate=0.05, seed=5,
                                  mesh=make_mesh({"data": 8}))
        w2v.fit(self.CORPUS, epochs=10)
        assert w2v.has_word("fox") and w2v.has_word("dog")
        assert isinstance(w2v.words_nearest("fox", 3), list)
        # co-occurring words more similar than non-co-occurring rare pair
        assert w2v.similarity("quick", "brown") > w2v.similarity("quick", "day")

    def test_matches_single_worker(self):
        """Sharded psum update == single-device update (same seed/batches)."""
        from deeplearning4j_tpu.nlp.distributed import DistributedWord2Vec
        from deeplearning4j_tpu.parallel.mesh import make_mesh

        def train(n_dev):
            w2v = DistributedWord2Vec(layer_size=8, window=2, negative=2,
                                      seed=3, mesh=make_mesh({"data": n_dev}))
            w2v.fit(self.CORPUS[:20], epochs=2, batch_pairs=64)
            return np.asarray(w2v.syn0)

        a, b = train(1), train(8)
        np.testing.assert_allclose(a, b, rtol=2e-3, atol=1e-5)


class TestProfilerListener:
    def _net(self):
        from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.conf.inputs import InputType
        from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        conf = (NeuralNetConfiguration.builder().seed(0).list()
                .layer(DenseLayer(n_in=4, n_out=8))
                .layer(OutputLayer(n_out=2))
                .set_input_type(InputType.feed_forward(4)).build())
        return MultiLayerNetwork(conf).init()

    def test_traces_a_window_of_iterations(self, tmp_path):
        import os

        import numpy as np

        from deeplearning4j_tpu.optimize.listeners import ProfilerListener

        net = self._net()
        pl = ProfilerListener(str(tmp_path / "trace"), start_iteration=2,
                              n_iterations=3)
        net.set_listeners(pl)
        x = np.random.default_rng(0).normal(size=(16, 4)).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[np.random.default_rng(1).integers(0, 2, 16)]
        for _ in range(10):
            net.fit(x, y)
        assert pl._done
        if pl.last_error is None:  # backend supports tracing
            assert os.path.isdir(tmp_path / "trace")
            found = [f for _, _, fs in os.walk(tmp_path / "trace") for f in fs]
            assert found, "trace directory is empty"

    def test_one_shot_and_rearm(self, tmp_path):
        import numpy as np

        from deeplearning4j_tpu.optimize.listeners import ProfilerListener

        net = self._net()
        pl = ProfilerListener(str(tmp_path / "t2"), start_iteration=1,
                              n_iterations=1)
        net.set_listeners(pl)
        x = np.zeros((4, 4), np.float32)
        y = np.eye(2, dtype=np.float32)[[0, 1, 0, 1]]
        for _ in range(5):
            net.fit(x, y)
        assert pl._done and not pl._active
        pl.reset()
        assert not pl._done


class TestRemainingIterators:
    def test_floats_doubles_and_reconstruction(self):
        from deeplearning4j_tpu.datasets.iterators import (
            DoublesDataSetIterator, FloatsDataSetIterator,
            ReconstructionDataSetIterator)
        pairs = [([1.0, 2.0], [1.0]), ([3.0, 4.0], [0.0]),
                 ([5.0, 6.0], [1.0])]
        fl = list(FloatsDataSetIterator(pairs, 2))
        assert np.asarray(fl[0].features).dtype == np.float32
        assert fl[0].num_examples() == 2 and fl[1].num_examples() == 1
        db = list(DoublesDataSetIterator(pairs, 3))
        assert np.asarray(db[0].features).dtype == np.float64
        rec = list(ReconstructionDataSetIterator(
            FloatsDataSetIterator(pairs, 2)))
        np.testing.assert_array_equal(np.asarray(rec[0].labels),
                                      np.asarray(rec[0].features))

    def test_mds_rebatch_and_wrapper(self):
        from deeplearning4j_tpu.datasets.dataset import MultiDataSet
        from deeplearning4j_tpu.datasets.iterators import (
            IteratorMultiDataSetIterator, MultiDataSetWrapperIterator)
        ms = [MultiDataSet([np.ones((1, 3))], [np.zeros((1, 2))])
              for _ in range(5)]
        rebatched = list(IteratorMultiDataSetIterator(ms, 2))
        assert [np.asarray(m.features[0]).shape[0] for m in rebatched] == \
            [2, 2, 1]
        wrapped = list(MultiDataSetWrapperIterator(rebatched))
        assert wrapped[0].num_examples() == 2
        bad = MultiDataSet([np.ones((1, 3))] * 2, [np.zeros((1, 2))])
        import pytest as _pytest
        with _pytest.raises(ValueError, match="single-input"):
            list(MultiDataSetWrapperIterator([bad]))

    def test_combined_and_dummy_preprocessors(self):
        from deeplearning4j_tpu.datasets.dataset import DataSet
        from deeplearning4j_tpu.datasets.iterators import (
            CombinedPreProcessor, DummyPreProcessor)
        from deeplearning4j_tpu.datasets.normalizers import (
            ImagePreProcessingScaler)
        ds = DataSet(np.full((2, 3), 255.0, np.float32),
                     np.zeros((2, 1), np.float32))
        combo = CombinedPreProcessor(DummyPreProcessor(),
                                     ImagePreProcessingScaler())
        out = combo.preprocess(ds)
        assert float(np.asarray(out.features).max()) == 1.0
