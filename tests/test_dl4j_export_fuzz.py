"""DL4J export↔import fuzz: random architectures round-trip the wire.

The reference locks its checkpoint format with fixed per-release zips
(RegressionTest050..080 — external artifacts absent from the snapshot);
with no ND4J runtime in this image, the exporter and importer can only be
cross-checked against each other — so that check is FUZZED: a seeded
generator sweeps the DL4J-expressible architecture space (dense / conv /
recurrent stacks, heterogeneous updaters, bias updaters, distribution
inits, scalar dropout, l1/l2), each sample trains, exports through
``dl4j_export``, re-imports through ``dl4j``, and must reproduce outputs
exactly AND keep training identically (updater-state round trip). A
shared misreading of the ND4J layout stays undetectable by construction
(documented in KNOWN_GAPS.md); what this tier rules out is any config
field, param permutation, or updater-block boundary that only one side
handles.
"""

import random

import numpy as np
import pytest

from deeplearning4j_tpu.modelimport.dl4j import restore_multi_layer_network
from deeplearning4j_tpu.modelimport.dl4j_export import (
    export_multi_layer_network,
)
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.network import NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers.conv import ConvolutionLayer
from deeplearning4j_tpu.nn.layers.core import DenseLayer
from deeplearning4j_tpu.nn.layers.norm import BatchNormalizationLayer
from deeplearning4j_tpu.nn.layers.output import OutputLayer, RnnOutputLayer
from deeplearning4j_tpu.nn.layers.pooling import SubsamplingLayer
from deeplearning4j_tpu.nn.layers.recurrent import (
    GravesLSTMLayer,
    LSTMLayer,
    SimpleRnnLayer,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.updaters import (
    Adam,
    AdaGrad,
    Nesterovs,
    RmsProp,
    Sgd,
)
from deeplearning4j_tpu.nn.weights import Distribution

ACTS = ["relu", "tanh", "sigmoid", "softsign"]


def rand_updater(rng):
    return rng.choice([Sgd(0.05), Adam(1e-3), RmsProp(5e-3),
                       Nesterovs(1e-2, momentum=0.9), AdaGrad(1e-2), None])


def layer_extras(rng):
    kw = {}
    if rng.random() < 0.3:
        kw["dropout"] = rng.choice([0.8, 0.9])
    if rng.random() < 0.3:
        kw["l2"] = 1e-4
    if rng.random() < 0.2:
        kw["l1"] = 1e-5
    u = rand_updater(rng)
    if u is not None:
        kw["updater"] = u
    if rng.random() < 0.2:
        kw["weight_init"] = "distribution"
        kw["distribution"] = Distribution(kind="normal", mean=0.0,
                                          std=rng.choice([0.1, 0.5]))
    return kw


def rand_dense(rng):
    nb = (NeuralNetConfiguration.builder().seed(rng.randint(0, 9999))
          .updater(Adam(1e-3)))
    if rng.random() < 0.3:
        nb = nb.bias_updater(Sgd(0.1))
    b = nb.list()
    width = rng.choice([4, 6, 8])
    b.layer(DenseLayer(n_in=5, n_out=width, activation=rng.choice(ACTS),
                       **layer_extras(rng)))
    for _ in range(rng.randint(0, 2)):
        b.layer(DenseLayer(n_in=width, n_out=width,
                           activation=rng.choice(ACTS), **layer_extras(rng)))
    b.layer(OutputLayer(n_in=width, n_out=3, **layer_extras(rng)))
    x = np.random.RandomState(rng.randint(0, 99)).randn(8, 5).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[
        np.random.RandomState(rng.randint(0, 99)).randint(0, 3, 8)]
    return b.build(), x, y


def rand_conv(rng):
    b = (NeuralNetConfiguration.builder().seed(rng.randint(0, 9999))
         .updater(Adam(1e-3)).list())
    ch = rng.choice([3, 4])
    b.layer(ConvolutionLayer(n_out=ch, kernel_size=(3, 3),
                             convolution_mode="same",
                             activation=rng.choice(ACTS),
                             **layer_extras(rng)))
    if rng.random() < 0.5:
        b.layer(BatchNormalizationLayer())
    if rng.random() < 0.5:
        b.layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
    b.layer(DenseLayer(n_out=6, activation=rng.choice(ACTS)))
    b.layer(OutputLayer(n_out=2, **layer_extras(rng)))
    b.set_input_type(InputType.convolutional(8, 8, 1))
    x = np.random.RandomState(rng.randint(0, 99)).rand(4, 8, 8, 1) \
        .astype(np.float32)
    y = np.eye(2, dtype=np.float32)[
        np.random.RandomState(rng.randint(0, 99)).randint(0, 2, 4)]
    return b.build(), x, y


def rand_recurrent(rng):
    b = (NeuralNetConfiguration.builder().seed(rng.randint(0, 9999))
         .updater(Adam(1e-3)).list())
    width = rng.choice([4, 6])
    cls = rng.choice([LSTMLayer, GravesLSTMLayer, SimpleRnnLayer])
    b.layer(cls(n_in=3, n_out=width, **layer_extras(rng)))
    if rng.random() < 0.4:
        b.layer(SimpleRnnLayer(n_in=width, n_out=width))
    b.layer(RnnOutputLayer(n_in=width, n_out=2, **layer_extras(rng)))
    x = np.random.RandomState(rng.randint(0, 99)).randn(4, 7, 3) \
        .astype(np.float32)
    y = np.eye(2, dtype=np.float32)[
        np.random.RandomState(rng.randint(0, 99)).randint(0, 2, (4, 7))]
    return b.build(), x, y


def rand_graph(rng):
    """Branchy DAG (merge/elementwise vertices) — exercises the shared
    topologicalSortOrder() parameter layout on both wire directions;
    sometimes a conv input with a dense head (the LayerVertex
    preProcessor + NHWC→NCHW permutation path)."""
    from deeplearning4j_tpu.nn.vertices import ElementWiseVertex, MergeVertex
    if rng.random() < 0.4:
        g = (NeuralNetConfiguration.builder().seed(rng.randint(0, 9999))
             .updater(Adam(1e-3)).graph_builder().add_inputs("img")
             .set_input_types(InputType.convolutional(6, 6, 1)))
        g.add_layer("conv", ConvolutionLayer(
            n_out=rng.choice([2, 3]), kernel_size=(3, 3),
            convolution_mode="same", activation=rng.choice(ACTS)), "img")
        if rng.random() < 0.5:
            g.add_layer("bn", BatchNormalizationLayer(), "conv")
            head_src = "bn"
        else:
            head_src = "conv"
        g.add_layer("dense", DenseLayer(n_out=6, activation=rng.choice(ACTS),
                                        **layer_extras(rng)), head_src)
        g.add_layer("out", OutputLayer(n_in=6, n_out=3), "dense")
        conf = g.set_outputs("out").build()
        x = np.random.RandomState(rng.randint(0, 99)).rand(6, 6, 6, 1) \
            .astype(np.float32)
        y = np.eye(3, dtype=np.float32)[
            np.random.RandomState(rng.randint(0, 99)).randint(0, 3, 6)]
        return conf, x, y
    g = (NeuralNetConfiguration.builder().seed(rng.randint(0, 9999))
         .updater(Adam(1e-3)).graph_builder().add_inputs("in")
         .set_input_types(InputType.feed_forward(5)))
    width = rng.choice([4, 6])
    g.add_layer("a", DenseLayer(n_out=width, activation=rng.choice(ACTS),
                                **layer_extras(rng)), "in")
    g.add_layer("b", DenseLayer(n_out=width, activation=rng.choice(ACTS)),
                "in")
    if rng.random() < 0.5:
        g.add_vertex("join", ElementWiseVertex(
            op=rng.choice(["add", "max", "average"])), "a", "b")
        head_in = width
    else:
        g.add_vertex("join", MergeVertex(), "a", "b")
        head_in = 2 * width
    g.add_layer("head", DenseLayer(n_in=head_in, n_out=4,
                                   activation=rng.choice(ACTS),
                                   **layer_extras(rng)), "join")
    g.add_layer("out", OutputLayer(n_in=4, n_out=3), "head")
    conf = g.set_outputs("out").build()
    x = np.random.RandomState(rng.randint(0, 99)).randn(8, 5).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[
        np.random.RandomState(rng.randint(0, 99)).randint(0, 3, 8)]
    return conf, x, y


FAMILIES = {"dense": rand_dense, "conv": rand_conv, "rnn": rand_recurrent,
            "graph": rand_graph}


@pytest.mark.parametrize("family", sorted(FAMILIES))
@pytest.mark.parametrize("seed", range(6))
def test_random_architecture_round_trips(family, seed, tmp_path):
    # deterministic across processes (str hash is PYTHONHASHSEED-random)
    rng = random.Random(1000 * sorted(FAMILIES).index(family) + seed)
    conf, x, y = FAMILIES[family](rng)
    if family == "graph":
        from deeplearning4j_tpu.modelimport.dl4j import (
            restore_computation_graph)
        from deeplearning4j_tpu.modelimport.dl4j_export import (
            export_computation_graph)
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        net = ComputationGraph(conf).init()
        export_fn, restore_fn = export_computation_graph, \
            restore_computation_graph
        out_of = lambda n: np.asarray(n.output_single(x))
    else:
        net = MultiLayerNetwork(conf).init()
        export_fn, restore_fn = export_multi_layer_network, \
            restore_multi_layer_network
        out_of = lambda n: np.asarray(n.output(x))
    for _ in range(3):
        net.fit(x, y)
    path = str(tmp_path / "rt.zip")
    export_fn(net, path)
    again = restore_fn(path)
    np.testing.assert_allclose(out_of(again), out_of(net),
                               rtol=2e-5, atol=1e-6)
    # updater state round trip: continued training stays identical. The
    # RNG stream is NOT part of the wire format (DL4J's isn't either), so
    # dropout masks would legitimately differ post-restore — sync both
    # nets to one key (and drop the cached device tick that holds the old
    # one) to make the equality exact even for dropout configs.
    import jax
    net._rng_key = again._rng_key = jax.random.PRNGKey(7)
    net._tick = again._tick = None
    for _ in range(2):
        net.fit(x, y)
        again.fit(x, y)
    # compare continued-training OUTPUTS, not raw params: across a cnn→ff
    # boundary the reimported model keeps DL4J's NCHW weight indexing with
    # an activation-side transpose (dl4j.py _convert_dl4j_preprocessor),
    # so the dense W lives in a permuted basis — functionally identical,
    # elementwise different
    np.testing.assert_allclose(
        out_of(again), out_of(net), rtol=2e-4, atol=1e-5,
        err_msg=f"{family}/{seed}: training diverged after restore")
    np.testing.assert_allclose(float(again.score_), float(net.score_),
                               rtol=2e-4, atol=1e-6)

    # normalizer.bin rides the same zip (r5): fuzz a random strategy into
    # the exported checkpoint and require the restored normalizer to
    # transform identically (ModelSerializer.java:654/707)
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.datasets.normalizers import (
        ImagePreProcessingScaler, NormalizerMinMaxScaler,
        NormalizerStandardize)
    from deeplearning4j_tpu.modelimport.dl4j import (
        add_normalizer_to_model, restore_normalizer)

    ds = DataSet(x, y)
    kind = rng.randrange(3)
    if kind == 0:
        norm = NormalizerStandardize()
        norm.fit_label = rng.random() < 0.5
        norm.fit(ds)
    elif kind == 1:
        norm = NormalizerMinMaxScaler(rng.uniform(-2, 0), rng.uniform(1, 3))
        norm.fit_label = rng.random() < 0.5
        norm.fit(ds)
    else:
        norm = ImagePreProcessingScaler(0.0, 1.0, rng.choice([1.0, 255.0]))
    add_normalizer_to_model(path, norm)
    back = restore_normalizer(path)
    assert type(back) is type(norm)
    t_ours, t_back = norm.transform(ds), back.transform(ds)
    np.testing.assert_allclose(np.asarray(t_back.features),
                               np.asarray(t_ours.features), rtol=1e-6,
                               atol=1e-6)
    if getattr(norm, "fit_label", False):
        np.testing.assert_allclose(np.asarray(t_back.labels),
                                   np.asarray(t_ours.labels), rtol=1e-6,
                                   atol=1e-6)
