"""normalizer.bin — ND4J NormalizerSerializer stream round trips.

Covers VERDICT r4 Missing #1: the last byte-stream of a DL4J
ModelSerializer zip (``ModelSerializer.java:40,165-168,654,707``). Like
coefficients.bin/updaterState.bin, fidelity to the exact ND4J byte layout
is self-consistency-verified (the ND4J serializer classes are outside the
reference snapshot) — these tests prove both directions share one precise,
documented layout and that every supported strategy restores to a working
normalizer.
"""

import io
import struct
import zipfile

import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import DataSet, MultiDataSet
from deeplearning4j_tpu.datasets.normalizers import (
    ImagePreProcessingScaler,
    MultiNormalizer,
    NormalizerMinMaxScaler,
    NormalizerStandardize,
    VGG16ImagePreProcessor,
)
from deeplearning4j_tpu.modelimport.normalizer_serde import (
    UnsupportedNormalizerException,
    normalizer_from_bytes,
    normalizer_to_bytes,
)

RNG = np.random.RandomState(42)


def _ds(n=16, f=5, c=3):
    x = RNG.randn(n, f).astype(np.float32) * 3 + 1
    y = RNG.randn(n, c).astype(np.float32) * 2 - 5
    return DataSet(x, y)


def _roundtrip(norm):
    return normalizer_from_bytes(normalizer_to_bytes(norm))


def test_standardize_roundtrip():
    norm = NormalizerStandardize().fit(_ds())
    back = _roundtrip(norm)
    np.testing.assert_allclose(back.mean, norm.mean)
    np.testing.assert_allclose(back.std, norm.std)
    assert back.fit_label is False and back.label_mean is None
    ds = _ds()
    np.testing.assert_allclose(back.transform(ds).features,
                               norm.transform(ds).features)


def test_standardize_fit_label_roundtrip():
    norm = NormalizerStandardize()
    norm.fit_label = True
    norm.fit(_ds())
    assert norm.label_mean is not None
    back = _roundtrip(norm)
    assert back.fit_label is True
    np.testing.assert_allclose(back.label_mean, norm.label_mean)
    np.testing.assert_allclose(back.label_std, norm.label_std)
    ds = _ds()
    t_ours, t_back = norm.transform(ds), back.transform(ds)
    np.testing.assert_allclose(t_back.labels, t_ours.labels)
    # labels actually changed (fitLabel is live, not just carried)
    assert not np.allclose(t_ours.labels, ds.labels)
    r = back.revert(t_back)
    np.testing.assert_allclose(r.labels, ds.labels, rtol=1e-4, atol=1e-4)


def test_minmax_roundtrip():
    norm = NormalizerMinMaxScaler(-1.0, 2.0).fit(_ds())
    back = _roundtrip(norm)
    assert back.min_range == -1.0 and back.max_range == 2.0
    np.testing.assert_allclose(back.data_min, norm.data_min)
    np.testing.assert_allclose(back.data_max, norm.data_max)
    ds = _ds()
    np.testing.assert_allclose(back.transform(ds).features,
                               norm.transform(ds).features)


def test_minmax_fit_label_roundtrip():
    norm = NormalizerMinMaxScaler()
    norm.fit_label = True
    norm.fit(_ds())
    back = _roundtrip(norm)
    assert back.fit_label is True
    ds = _ds()
    np.testing.assert_allclose(back.transform(ds).labels,
                               norm.transform(ds).labels)


def test_image_scaler_roundtrip():
    norm = ImagePreProcessingScaler(0.0, 1.0, 255.0)
    back = _roundtrip(norm)
    assert (back.min_range, back.max_range, back.max_pixel) == (0.0, 1.0,
                                                                255.0)


def test_vgg16_roundtrip():
    back = _roundtrip(VGG16ImagePreProcessor())
    assert isinstance(back, VGG16ImagePreProcessor)


def _mds(n=12):
    return MultiDataSet(
        [RNG.randn(n, 4).astype(np.float32) * 2 + 3,
         RNG.randn(n, 6).astype(np.float32) - 1],
        [RNG.randn(n, 2).astype(np.float32) * 4])


@pytest.mark.parametrize("kind", ["standardize", "minmax"])
def test_multi_roundtrip(kind):
    norm = MultiNormalizer(kind).fit(_mds())
    back = _roundtrip(norm)
    assert back.kind == kind and len(back.children) == 2
    mds = _mds()
    t_ours, t_back = norm.transform(mds), back.transform(mds)
    for a, b in zip(t_ours.features, t_back.features):
        np.testing.assert_allclose(a, b)


@pytest.mark.parametrize("kind", ["standardize", "minmax"])
def test_multi_fit_label_roundtrip(kind):
    norm = MultiNormalizer(kind, **({"min_range": -2.0, "max_range": 2.0}
                                    if kind == "minmax" else {}))
    norm.fit_label = True
    norm.fit(_mds())
    assert len(norm.label_children) == 1
    back = _roundtrip(norm)
    assert len(back.label_children) == 1
    mds = _mds()
    np.testing.assert_allclose(back.transform(mds).labels[0],
                               norm.transform(mds).labels[0])


# ---------------------------------------------------------------------------
# loud rejections

def _header(ntype, extra=b""):
    out = io.BytesIO()
    for s in ("NORMALIZER",):
        b = s.encode()
        out.write(struct.pack(">H", len(b)) + b)
    out.write(struct.pack(">i", 1))
    b = ntype.encode()
    out.write(struct.pack(">H", len(b)) + b)
    out.write(extra)
    return out.getvalue()


def test_custom_strategy_rejected_loudly():
    cls = "com.example.MyNormalizerStrategy".encode()
    payload = _header("CUSTOM", struct.pack(">H", len(cls)) + cls)
    with pytest.raises(UnsupportedNormalizerException, match="CUSTOM"):
        normalizer_from_bytes(payload)


def test_multi_hybrid_rejected_loudly():
    with pytest.raises(UnsupportedNormalizerException, match="MULTI_HYBRID"):
        normalizer_from_bytes(_header("MULTI_HYBRID"))


def test_truncated_stream_rejected_clearly():
    full = normalizer_to_bytes(NormalizerStandardize().fit(_ds()))
    # cut inside the LAST record (the std vector's data buffer): must
    # fail loudly, not return a silently short normalizer
    with pytest.raises(ValueError, match="truncated"):
        normalizer_from_bytes(full[: len(full) - 7])
    # cut inside the header
    with pytest.raises(ValueError):
        normalizer_from_bytes(full[:8])


def test_implausible_multi_count_rejected():
    payload = _header("MULTI_STANDARDIZE",
                      b"\x00"                       # fitLabel false
                      + struct.pack(">i", 1 << 20)  # absurd input count
                      + struct.pack(">i", -1))
    with pytest.raises(ValueError, match="implausible"):
        normalizer_from_bytes(payload)


def test_bad_magic_rejected():
    payload = _header("STANDARDIZE").replace(b"NORMALIZER", b"NORMALIZED", 1)
    with pytest.raises(ValueError, match="NormalizerSerializer"):
        normalizer_from_bytes(payload)


def test_unfitted_write_rejected():
    with pytest.raises(UnsupportedNormalizerException, match="unfitted"):
        normalizer_to_bytes(NormalizerStandardize())


# ---------------------------------------------------------------------------
# through the zip container (ModelSerializer surface)

def _tiny_net():
    from deeplearning4j_tpu.nn.conf.network import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers.core import DenseLayer
    from deeplearning4j_tpu.nn.layers.output import OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    conf = (NeuralNetConfiguration.builder().seed(7).list()
            .layer(DenseLayer(n_in=5, n_out=8, activation="relu"))
            .layer(OutputLayer(n_in=8, n_out=3,
                               loss="negativeloglikelihood",
                               activation="softmax"))
            .build())
    return MultiLayerNetwork(conf).init()


def test_export_and_restore_normalizer_via_zip(tmp_path):
    from deeplearning4j_tpu.modelimport.dl4j import (
        import_dl4j_zip, restore_multi_layer_network, restore_normalizer)
    from deeplearning4j_tpu.modelimport.dl4j_export import (
        export_multi_layer_network)

    net = _tiny_net()
    norm = NormalizerStandardize().fit(_ds())
    path = str(tmp_path / "model.zip")
    export_multi_layer_network(net, path, normalizer=norm)

    with zipfile.ZipFile(path) as z:
        assert "normalizer.bin" in z.namelist()

    back = restore_normalizer(path)
    np.testing.assert_allclose(back.mean, norm.mean)

    _, meta = import_dl4j_zip(path)
    assert meta["has_normalizer"] is True
    assert isinstance(meta["normalizer"], NormalizerStandardize)
    np.testing.assert_allclose(meta["normalizer"].std, norm.std)

    # the model itself still restores
    again = restore_multi_layer_network(path)
    x = RNG.randn(4, 5).astype(np.float32)
    np.testing.assert_allclose(np.asarray(again.output(x)),
                               np.asarray(net.output(x)), rtol=2e-5,
                               atol=1e-6)


def test_add_normalizer_to_model_replaces(tmp_path):
    from deeplearning4j_tpu.modelimport.dl4j import (
        add_normalizer_to_model, restore_normalizer)
    from deeplearning4j_tpu.modelimport.dl4j_export import (
        export_multi_layer_network)

    net = _tiny_net()
    path = str(tmp_path / "model.zip")
    export_multi_layer_network(net, path)
    assert restore_normalizer(path) is None

    add_normalizer_to_model(path, ImagePreProcessingScaler(0, 1, 255))
    first = restore_normalizer(path)
    assert isinstance(first, ImagePreProcessingScaler)

    # second add REPLACES (ModelSerializer.java:670 skips the old entry)
    norm2 = NormalizerMinMaxScaler().fit(_ds())
    add_normalizer_to_model(path, norm2)
    with zipfile.ZipFile(path) as z:
        assert z.namelist().count("normalizer.bin") == 1
    second = restore_normalizer(path)
    assert isinstance(second, NormalizerMinMaxScaler)
    np.testing.assert_allclose(second.data_min, norm2.data_min)


def test_unparseable_normalizer_does_not_fail_model_import(tmp_path):
    """A CUSTOM-strategy normalizer.bin must not break config/model
    restore — the reference's restoreMultiLayerNetwork never reads it."""
    from deeplearning4j_tpu.modelimport.dl4j import (
        import_dl4j_zip, restore_multi_layer_network)
    from deeplearning4j_tpu.modelimport.dl4j_export import (
        export_multi_layer_network)

    net = _tiny_net()
    path = str(tmp_path / "model.zip")
    export_multi_layer_network(net, path)
    cls = "com.example.MyStrategy".encode()
    custom = _header("CUSTOM", struct.pack(">H", len(cls)) + cls)
    with zipfile.ZipFile(path, "a") as z:
        z.writestr("normalizer.bin", custom)

    _, meta = import_dl4j_zip(path)
    assert meta["has_normalizer"] is True
    assert meta["normalizer"] is None
    assert "CUSTOM" in meta["normalizer_error"]
    restore_multi_layer_network(path)  # model restore unaffected


def test_multi_fit_label_without_labels_raises_clearly():
    m = MultiNormalizer("standardize")
    m.fit_label = True
    mds = MultiDataSet([RNG.randn(8, 4).astype(np.float32),
                        RNG.randn(8, 6).astype(np.float32)], [])
    with pytest.raises(ValueError, match="no MultiDataSet carried labels"):
        m.fit(mds)
    # mixed stream: label-less batches are skipped, labeled ones fit
    m2 = MultiNormalizer("standardize")
    m2.fit_label = True
    m2.fit([_mds(), mds, _mds()])
    assert len(m2.label_children) == 1


def test_fit_label_without_labels_raises_clearly():
    n = NormalizerStandardize()
    n.fit_label = True
    with pytest.raises(ValueError, match="no batch carried labels"):
        n.fit(DataSet(RNG.randn(8, 4).astype(np.float32), None))
    m = NormalizerMinMaxScaler()
    m.fit_label = True
    with pytest.raises(ValueError, match="no batch carried labels"):
        m.fit(DataSet(RNG.randn(8, 4).astype(np.float32), None))


def test_fit_streams_batches_one_pass():
    """fit over an iterator must not materialize it (O(batch) memory)."""
    seen = []

    def gen():
        for _ in range(5):
            ds = _ds(n=8)
            seen.append(ds)
            yield ds

    norm = NormalizerStandardize().fit(gen())
    all_x = np.concatenate([np.asarray(d.features) for d in seen])
    np.testing.assert_allclose(norm.mean, all_x.mean(0), rtol=1e-5,
                               atol=1e-5)


def test_guesser_load_normalizer_handles_dl4j_zip(tmp_path):
    from deeplearning4j_tpu.modelimport.dl4j_export import (
        export_multi_layer_network)
    from deeplearning4j_tpu.util.model_guesser import load_normalizer

    net = _tiny_net()
    norm = NormalizerStandardize().fit(_ds())
    path = str(tmp_path / "model.zip")
    export_multi_layer_network(net, path, normalizer=norm)
    back = load_normalizer(path)
    assert isinstance(back, NormalizerStandardize)
    np.testing.assert_allclose(back.mean, norm.mean)
