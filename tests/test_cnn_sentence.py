"""CnnSentenceDataSetIterator + LabeledSentenceProvider family.

Reference: deeplearning4j-nlp/iterator/CnnSentenceDataSetIterator.java,
iterator/provider/*.java.
"""

import numpy as np
import pytest

from deeplearning4j_tpu.nlp.cnn_sentence import (
    CnnSentenceDataSetIterator,
    CollectionLabeledSentenceProvider,
    FileLabeledSentenceProvider,
    LabelAwareConverter,
)
from deeplearning4j_tpu.nlp.sentence import LabelAwareIterator


class FakeWordVectors:
    """Minimal word-vector model: 3-dim one-hot-ish vectors."""

    _table = {
        "the": [2.0, 0.0, 0.0],
        "cat": [0.0, 2.0, 0.0],
        "sat": [0.0, 0.0, 2.0],
        "dog": [2.0, 2.0, 0.0],
    }

    class vocab:  # noqa: N801 - mimics .vocab.words()
        @staticmethod
        def words():
            return list(FakeWordVectors._table)

    def get_word_vector(self, w):
        v = self._table.get(w)
        return None if v is None else np.asarray(v, np.float32)

    def has_word(self, w):
        return w in self._table


def make_iterator(sentences, labels, **kw):
    provider = CollectionLabeledSentenceProvider(sentences, labels, rng=None)
    kw.setdefault("use_normalized_word_vectors", False)
    return CnnSentenceDataSetIterator(provider, FakeWordVectors(),
                                      minibatch_size=32, **kw)


class TestProviders:
    def test_collection_provider_shuffle_off_order(self):
        p = CollectionLabeledSentenceProvider(["a", "b"], ["x", "y"], rng=None)
        assert p.next_sentence() == ("a", "x")
        assert p.next_sentence() == ("b", "y")
        assert not p.has_next()
        p.reset()
        assert p.total_num_sentences() == 2
        assert p.all_labels() == ["x", "y"]

    def test_collection_provider_length_mismatch(self):
        with pytest.raises(ValueError):
            CollectionLabeledSentenceProvider(["a"], ["x", "y"])

    def test_file_provider(self, tmp_path):
        pos = tmp_path / "p.txt"; pos.write_text("the cat")
        neg = tmp_path / "n.txt"; neg.write_text("the dog")
        p = FileLabeledSentenceProvider(
            {"pos": [str(pos)], "neg": [str(neg)]}, rng=None)
        assert p.all_labels() == ["neg", "pos"]  # sorted
        seen = {p.next_sentence() for _ in range(2)}
        assert seen == {("the cat", "pos"), ("the dog", "neg")}

    def test_label_aware_converter(self):
        it = LabelAwareIterator([("doc one", ["a"]), ("doc two", ["b"])])
        p = LabelAwareConverter(it)
        assert p.all_labels() == ["a", "b"]
        assert p.next_sentence() == ("doc one", "a")


class TestCnnSentenceIterator:
    def test_feature_shape_along_height(self):
        it = make_iterator(["the cat sat", "the dog"], ["pos", "neg"])
        ds = it.next()
        assert ds.features.shape == (2, 1, 3, 3)  # [mb, 1, maxLen, wv]
        assert ds.labels.shape == (2, 2)
        # labels one-hot against sorted label list: neg=0, pos=1
        np.testing.assert_allclose(ds.labels, [[0, 1], [1, 0]])
        # mask present because lengths differ (3 vs 2)
        np.testing.assert_allclose(ds.features_mask, [[1, 1, 1], [1, 1, 0]])
        # word vectors in the right rows
        np.testing.assert_allclose(ds.features[0, 0, 1], [0, 2, 0])  # cat
        np.testing.assert_allclose(ds.features[1, 0, 2], [0, 0, 0])  # padding

    def test_feature_shape_along_width(self):
        it = make_iterator(["the cat sat"], ["pos"],
                           sentences_along_height=False)
        ds = it.next()
        assert ds.features.shape == (1, 1, 3, 3)  # [mb, 1, wv, maxLen]
        np.testing.assert_allclose(ds.features[0, 0, :, 1], [0, 2, 0])  # cat

    def test_no_mask_when_same_length(self):
        it = make_iterator(["the cat", "the dog"], ["a", "b"])
        ds = it.next()
        assert ds.features_mask is None

    def test_unknown_remove_and_skip_empty(self):
        # 'zzz qqq' tokenizes to nothing -> sentence skipped entirely
        it = make_iterator(["zzz qqq", "the cat"], ["a", "b"])
        ds = it.next()
        assert ds.features.shape[0] == 1
        np.testing.assert_allclose(ds.labels, [[0, 1]])
        assert not it.has_next()

    def test_unknown_use_unknown_vector(self):
        it = make_iterator(["zzz cat"], ["a"],
                           unknown_word_handling="use_unknown",
                           unknown_vector=np.array([9.0, 9.0, 9.0]))
        ds = it.next()
        assert ds.features.shape == (1, 1, 2, 3)
        np.testing.assert_allclose(ds.features[0, 0, 0], [9, 9, 9])

    def test_normalized_vectors(self):
        it = make_iterator(["the"], ["a"], use_normalized_word_vectors=True)
        ds = it.next()
        np.testing.assert_allclose(ds.features[0, 0, 0], [1, 0, 0])

    def test_max_sentence_length_truncates(self):
        it = make_iterator(["the cat sat the cat"], ["a"],
                           max_sentence_length=2)
        ds = it.next()
        assert ds.features.shape == (1, 1, 2, 3)

    def test_labels_and_class_map(self):
        it = make_iterator(["the"], ["b"], )
        # label map covers the provider's label set, sorted
        provider = CollectionLabeledSentenceProvider(
            ["x", "y"], ["m", "k"], rng=None)
        it2 = CnnSentenceDataSetIterator(
            provider, FakeWordVectors(), use_normalized_word_vectors=False)
        assert it2.get_labels() == ["k", "m"]
        assert it2.get_label_class_map() == {"k": 0, "m": 1}
        assert it2.input_columns() == 3
        assert it2.total_examples() == 2

    def test_iteration_and_reset(self):
        it = make_iterator(["the cat", "the dog", "sat"], ["a", "b", "a"])
        it.minibatch_size = 2
        batches = [ds.features.shape[0] for ds in it]
        assert batches == [2, 1]
        batches2 = [ds.features.shape[0] for ds in it]  # __iter__ resets
        assert batches2 == [2, 1]

    def test_load_single_sentence(self):
        it = make_iterator(["the cat"], ["a"])
        f = it.load_single_sentence("cat sat")
        assert f.shape == (1, 1, 2, 3)
        np.testing.assert_allclose(f[0, 0, 0], [0, 2, 0])
        with pytest.raises(ValueError):
            it.load_single_sentence("zzz")

    def test_nhwc_feature_format(self):
        it = make_iterator(["the cat sat", "the dog"], ["pos", "neg"],
                           feature_format="NHWC")
        ds = it.next()
        assert ds.features.shape == (2, 3, 3, 1)  # [mb, maxLen, wv, 1]
        np.testing.assert_allclose(ds.features[0, 1, :, 0], [0, 2, 0])  # cat
        f = it.load_single_sentence("cat")
        assert f.shape == (1, 1, 3, 1)

    def test_trainable_end_to_end(self):
        """A tiny conv+global-pool classifier fits CNN sentence batches."""
        from deeplearning4j_tpu.nn.conf.network import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.layers.conv import ConvolutionLayer
        from deeplearning4j_tpu.nn.layers.pooling import GlobalPoolingLayer
        from deeplearning4j_tpu.nn.layers.output import OutputLayer
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        from deeplearning4j_tpu.nn.conf.inputs import InputType

        sentences = ["the cat sat", "the dog sat", "cat cat sat", "dog the dog"]
        labels = ["animal", "pet", "animal", "pet"]
        it = make_iterator(sentences, labels, feature_format="NHWC",
                           max_sentence_length=3)
        conf = (NeuralNetConfiguration.builder().seed(7).updater("adam")
                .list()
                .layer(ConvolutionLayer(n_out=4, kernel_size=(2, 3),
                                        convolution_mode="same"))
                .layer(GlobalPoolingLayer(pooling_type="max"))
                .layer(OutputLayer(n_out=2, activation="softmax",
                                   loss="negativeloglikelihood"))
                .set_input_type(InputType.convolutional(3, 3, 1))
                .build())
        net = MultiLayerNetwork(conf)
        net.init()
        ds = it.next()
        net.fit(ds.features, ds.labels)  # just must run without shape errors
