"""Device-resident training tick (nn/tick.py) edge cases.

The fit loop's (iteration, epoch, rng) ride on device through the donated
train step; the host keeps int mirrors. These tests lock the invalidation
contract: any external mutation of the mirrors must fall back to a fresh
host placement (never a deleted donated buffer), and the on-device rng
chain must stay deterministic.
"""

import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, DropoutLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.updaters import Adam


def _net(seed=3, dropout=False):
    b = NeuralNetConfiguration.builder().seed(seed).updater(Adam(0.01)).list()
    b = b.layer(DenseLayer(n_in=6, n_out=12, activation="relu"))
    if dropout:
        b = b.layer(DropoutLayer(dropout=0.5))
    conf = b.layer(OutputLayer(n_in=12, n_out=3)).build()
    return MultiLayerNetwork(conf).init()


def _data(n=32, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 6)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, n)]
    return x, y


class TestTickInvalidation:
    def test_external_iteration_reset_replaces_tick(self):
        net = _net()
        x, y = _data()
        net.fit(x, y, epochs=3)
        assert net.iteration == 3
        net.iteration = 0  # external mutation (restore / manual reset)
        net.fit(x, y)      # must NOT touch the stale donated tick
        assert net.iteration == 1
        assert np.isfinite(net.score_)

    def test_epoch_boundaries_and_interleaved_inference(self):
        net = _net(dropout=True)
        x, y = _data()
        for _ in range(2):
            net.fit(x, y)          # epoch stays, tick chain continues
            _ = np.asarray(net.output(x))  # inference between steps is fine
        net.epoch += 1             # external epoch bump -> fresh tick
        net.fit(x, y)
        assert net.iteration == 3 and np.isfinite(net.score_)

    def test_clone_trains_independently(self):
        net = _net()
        x, y = _data()
        net.fit(x, y)
        other = net.clone()
        other.fit(x, y)
        net.fit(x, y)
        assert net.iteration == 2 and other.iteration == 2
        assert np.isfinite(net.score_) and np.isfinite(other.score_)

    def test_lr_schedule_sees_advancing_iteration(self):
        """The on-device `it` counter must actually advance: a step-decay
        schedule changes the update magnitude when it crosses its step."""
        from deeplearning4j_tpu.nn.updaters import Sgd, StepSchedule
        conf = (NeuralNetConfiguration.builder().seed(0)
                .updater(Sgd(StepSchedule("iteration", 1.0, 0.0, 2.0)))
                .list()
                .layer(DenseLayer(n_in=4, n_out=4, activation="identity"))
                .layer(OutputLayer(n_in=4, n_out=2, loss="mse",
                                   activation="identity"))
                .build())
        net = MultiLayerNetwork(conf).init()
        x = np.ones((4, 4), np.float32)
        y = np.zeros((4, 2), np.float32)
        w0 = np.asarray(net.params[0]["W"]).copy()
        net.fit(x, y)  # it=0: lr 1.0 -> params move
        w1 = np.asarray(net.params[0]["W"]).copy()
        assert np.abs(w1 - w0).max() > 0
        net.fit(x, y)  # it=1: lr 1.0
        net.fit(x, y)  # it=2: decayed to 0.0 -> params frozen
        w2 = np.asarray(net.params[0]["W"]).copy()
        net.fit(x, y)
        w3 = np.asarray(net.params[0]["W"])
        np.testing.assert_allclose(w3, w2)


class TestTickDeterminism:
    def test_dropout_chain_reproducible_across_fresh_nets(self):
        """Two identically-seeded nets must produce identical params after
        N dropout-training steps — locks the on-device rng split chain."""
        x, y = _data()
        a, b = _net(seed=11, dropout=True), _net(seed=11, dropout=True)
        for _ in range(4):
            a.fit(x, y)
            b.fit(x, y)
        for pa, pb in zip(a.params, b.params):
            for n in pa:
                np.testing.assert_array_equal(np.asarray(pa[n]),
                                              np.asarray(pb[n]))

    def test_mixed_wrapper_and_direct_fit(self):
        """ParallelWrapper bumps the host mirrors in its own ways; a direct
        fit afterwards must re-place the tick, not reuse a stale one."""
        import jax
        from deeplearning4j_tpu.parallel import ParallelWrapper, make_mesh
        net = _net()
        x, y = _data(n=64)
        pw = ParallelWrapper(net, make_mesh({"data": 8}), mode="averaging",
                             averaging_frequency=2)
        pw.fit([DataSet(x[:32], y[:32]), DataSet(x[32:], y[32:])])
        it_after = net.iteration
        net.fit(x, y)
        assert net.iteration == it_after + 1
        assert np.isfinite(net.score_)
