"""Fleet-observability acceptance worker (ISSUE 15).

The generic elastic runloop plus one cross-worker DCN exchange per
epoch over a dead-simple file transport (append-only length-prefixed
frames under ``--dcn-dir``; each worker publishes to ``slot<N>.bin`` and
polls every peer's file from a remembered offset). The exchanged tensor
is a toy — the point is that REAL ``CrossSliceGradientBridge`` frames
cross REAL process boundaries, so the merged job trace shows
``dcn_send → dcn_recv`` flow arrows between worker rows, exactly what
the supervisor's Perfetto timeline must render.
"""

from __future__ import annotations

import argparse
import os
import struct
import sys
from typing import List, Optional

import numpy as np


class FilePublisher:
    """Append length-prefixed frames to one file (single writer)."""

    def __init__(self, path: str):
        self.path = path

    def publish(self, frame: bytes) -> None:
        with open(self.path, "ab") as fh:
            fh.write(struct.pack(">I", len(frame)) + frame)
            fh.flush()


class FileConsumer:
    """Poll peers' frame files from remembered offsets; a frame still
    being appended (length prefix past EOF) is left for the next poll."""

    def __init__(self, paths: List[str]):
        self.paths = list(paths)
        self.offsets = {p: 0 for p in self.paths}

    def poll(self, timeout: float = 0.0):
        for p in self.paths:
            try:
                size = os.path.getsize(p)
            except OSError:
                continue
            off = self.offsets[p]
            if size < off + 4:
                continue
            with open(p, "rb") as fh:
                fh.seek(off)
                (n,) = struct.unpack(">I", fh.read(4))
                if size < off + 4 + n:
                    continue  # frame mid-write: not yet complete
                frame = fh.read(n)
            self.offsets[p] = off + 4 + n
            return frame
        return None


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser("fleet-worker")
    ap.add_argument("--modelPath", required=True)
    ap.add_argument("--dataPath", required=True)
    ap.add_argument("--out", required=True)
    ap.add_argument("--batchSize", type=int, default=32)
    ap.add_argument("--epochs", type=int, default=1)
    ap.add_argument("--threshold", type=float, default=1e-3)
    ap.add_argument("--dcn-dir", required=True, dest="dcn_dir")
    ap.add_argument("--peers", required=True,
                    help="comma-separated ORIGINAL slot ids of the job")
    args = ap.parse_args(argv)

    from deeplearning4j_tpu.datasets.dataset import (DataSet,
                                                     ListDataSetIterator)
    from deeplearning4j_tpu.parallel.dcn import CrossSliceGradientBridge
    from deeplearning4j_tpu.parallel.elastic import (ElasticWorkerContext,
                                                     run_elastic_worker)
    from deeplearning4j_tpu.util import model_serializer

    ctx = ElasticWorkerContext.from_env()
    if ctx is None:
        raise RuntimeError("fleet_worker must run under the supervisor")

    os.makedirs(args.dcn_dir, exist_ok=True)
    me = os.path.join(args.dcn_dir, f"slot{ctx.slot}.bin")
    peer_paths = [os.path.join(args.dcn_dir, f"slot{int(p)}.bin")
                  for p in args.peers.split(",") if int(p) != ctx.slot]
    bridge = CrossSliceGradientBridge(
        FilePublisher(me), FileConsumer(peer_paths), threshold=1e-4,
        slice_id=f"slot{ctx.slot}", host=ctx.host)
    toy = [{"w": np.zeros(32, np.float32)}]
    state = {"round": 0}

    z = np.load(args.dataPath)
    ds = DataSet(z["features"], z["labels"])

    def build_model():
        return model_serializer.restore_model(args.modelPath)

    def build_iterator():
        # one exchange per epoch: move the toy tensor so the threshold
        # clears, publish, then drain whatever the peers sent so far
        state["round"] += 1
        toy[0] = {"w": toy[0]["w"] + np.float32(state["round"])}
        bridge.publish_update(toy)
        for _ in range(16):
            new, applied = bridge.poll_and_apply(toy, timeout=0.0)
            toy[0] = {"w": np.asarray(new[0]["w"], np.float32)}
            if applied == 0:
                break
        return ListDataSetIterator(ds, args.batchSize)

    def on_done(net, c):
        if c.process_id == 0:
            out_dir = os.path.dirname(os.path.abspath(args.out))
            os.makedirs(out_dir, exist_ok=True)
            model_serializer.write_model(net, args.out)
            print(f"[slot {c.slot}] wrote {args.out}", flush=True)

    run_elastic_worker(
        build_model, build_iterator, epochs=args.epochs,
        master_kwargs={"batch_size_per_worker": args.batchSize,
                       "threshold": args.threshold},
        on_done=on_done, ctx=ctx)
    return 0


if __name__ == "__main__":
    sys.exit(main())
