"""Request-cost & SLO plane tests (`observe/cost.py`, `observe/slo.py`,
tail sampling in `observe/fleet.py`, the serving surfaces that expose
them): the OpenMetrics exemplar grammar round-trips (nasty label values,
+Inf buckets, federation relabeling), the cost ledger's row-weighted
apportionment conserves with compile time excluded (re-proven end to end
against a REAL cold-bucket XLA compile with `tracer.compile_count` as
the oracle), declarative SLOs compile into burn-rate rules that fire
exactly once and resolve on an injectable clock, the tail sampler's
keep/drop decision table is exercised with explicit-ns spans (no
sleeps), and the live `ModelServer` serves `/slo`, `/debug/capture` and
the `X-Device-Ms` header. The smoke tier re-proves the committed
BENCH_SERVING_r03 record's invariants on every CI run.
"""

import json
import math
import os
import subprocess
import sys
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.observe import (AlertManager, CallbackSink,
                                        CostLedger, Exemplar, FleetRegistry,
                                        MetricsRegistry, TailSampler, Tracer,
                                        disable_tracing, enable_tracing,
                                        exemplar_trace_ids, format_exemplar,
                                        load_slos, parse_prometheus_text)
from deeplearning4j_tpu.observe.slo import latency_counts
from deeplearning4j_tpu.observe.trace import Span
from deeplearning4j_tpu.parallel.time_source import ManualTimeSource
from deeplearning4j_tpu.serving import (ModelRegistry, ModelServer,
                                        ModelServingClient)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class _SumModel:
    """Numpy-only forward: serving-path tests without XLA in the way."""

    def output(self, x):
        x = np.asarray(x)
        return x.sum(axis=tuple(range(1, x.ndim)), keepdims=True)


# ----------------------------------------------------------------- exemplars
class TestExemplarGrammar:
    def test_observation_in_span_exposes_bucket_exemplar(self):
        m = MetricsRegistry()
        h = m.histogram("lat", "latency", ("model",), buckets=[0.1, 1.0])
        t = Tracer()
        with t.span("req") as sp:
            h.observe(0.5, model="a")
        text = m.exposition()
        assert "# {" in text and sp.trace_id in text
        parsed = parse_prometheus_text(text)
        ex = parsed.exemplars[("lat_bucket", (("le", "1"), ("model", "a")))]
        assert ex.labels["trace_id"] == sp.trace_id
        assert ex.value == pytest.approx(0.5)
        assert exemplar_trace_ids(m) == {sp.trace_id}
        # the exemplar annotates the bucket the observation FELL INTO,
        # not every cumulative bucket above it
        assert ("lat_bucket", (("le", "+Inf"), ("model", "a"))) \
            not in parsed.exemplars

    def test_observation_outside_any_span_has_no_exemplar(self):
        m = MetricsRegistry()
        m.histogram("lat", "latency").observe(0.5)
        assert "# {" not in m.exposition()
        assert exemplar_trace_ids(m) == set()

    def test_exemplar_lands_on_inf_bucket_for_tail_observations(self):
        m = MetricsRegistry()
        h = m.histogram("lat", "latency", buckets=[0.1])
        t = Tracer()
        with t.span("slowest") as sp:
            h.observe(9.0)
        parsed = parse_prometheus_text(m.exposition())
        ex = parsed.exemplars[("lat_bucket", (("le", "+Inf"),))]
        assert ex.labels["trace_id"] == sp.trace_id
        assert exemplar_trace_ids(m.exposition()) == {sp.trace_id}

    def test_last_write_wins_per_bucket(self):
        m = MetricsRegistry()
        h = m.histogram("lat", "latency", buckets=[1.0])
        t = Tracer()
        with t.span("a"):
            h.observe(0.2)
        with t.span("b") as sp_b:
            h.observe(0.3)
        assert h.exemplars()[1.0].labels["trace_id"] == sp_b.trace_id
        assert h.count() == 2  # the counts are untouched by exemplars

    def test_grammar_round_trips_escaped_label_values(self):
        # the suffix grammar must survive the same hostile values the
        # base exposition's escaping tests use
        for weird in ['a"b\\c\nd', '\\n-literal', '{brace}', 'a=b,c',
                      'trailing\\']:
            ex = Exemplar({"trace_id": weird}, 1.5, 12.25)
            line = 'h_bucket{le="1"} 3 ' + format_exemplar(ex)
            assert "\n" not in line  # one line per series, always
            parsed = parse_prometheus_text(line + "\n")
            assert parsed["h_bucket"][(("le", "1"),)] == 3
            got = parsed.exemplars[("h_bucket", (("le", "1"),))]
            assert got.labels["trace_id"] == weird
            assert got.value == pytest.approx(1.5)
            assert got.ts == pytest.approx(12.25)

    def test_federation_preserves_worker_exemplars(self, tmp_path):
        worker = MetricsRegistry()
        h = worker.histogram("serving_request_latency_seconds", "lat",
                             ("model",), buckets=[0.1, 1.0])
        t = Tracer()
        with t.span("worker_req") as sp:
            h.observe(0.5, model="m")
        snap = tmp_path / "w0.prom"
        snap.write_text(worker.exposition(), encoding="utf-8")

        fleet = FleetRegistry()
        fleet.set_source(0, str(snap), {"slot": "0", "host": "h0",
                                        "generation": "1"})
        text = fleet.exposition()
        # the relabeled bucket series still carries the annotation
        assert sp.trace_id in text
        assert exemplar_trace_ids(text) >= {sp.trace_id}
        parsed = parse_prometheus_text(text)
        keys = [k for k in parsed.exemplars
                if k[0] == "serving_request_latency_seconds_bucket"]
        assert keys, "federated bucket lost its exemplar"
        labels = dict(keys[0][1])
        assert labels["slot"] == "0" and labels["model"] == "m"


# --------------------------------------------------------------- cost ledger
class TestCostLedger:
    def test_row_weighted_apportionment_conserves(self):
        led = CostLedger()
        led.record_batch("m", span_ms=8.0,
                         requests=[("a", 6), ("b", 2)])
        assert led.device_ms("a") == pytest.approx(6.0)
        assert led.device_ms("b") == pytest.approx(2.0)
        cons = led.conservation("m")
        assert cons["ok"] and cons["error_ms"] == pytest.approx(0.0)
        assert cons["requests"] == 2 and cons["batches"] == 1

    def test_traceless_rows_land_unattributed(self):
        led = CostLedger()
        led.record_batch("m", span_ms=8.0,
                         requests=[("a", 3), (None, 1)])
        assert led.device_ms("a") == pytest.approx(6.0)
        t = led.totals("m")
        assert t["unattributed_device_ms"] == pytest.approx(2.0)
        assert led.conservation("m")["ok"]

    def test_compile_ms_excluded_and_attributed_to_model(self):
        m = MetricsRegistry()
        led = CostLedger(m)
        led.record_batch("m", span_ms=10.0, compile_ms=4.0,
                         requests=[("a", 1)])
        # the request pays the steady-state remainder, never the compile
        assert led.device_ms("a") == pytest.approx(6.0)
        t = led.totals("m")
        assert t["compile_ms"] == pytest.approx(4.0)
        assert t["device_ms"] == pytest.approx(6.0)
        assert m.get("request_compile_device_ms_total").value(
            model="m") == pytest.approx(4.0)
        assert led.conservation("m")["ok"]

    def test_compile_ms_clamped_to_span(self):
        led = CostLedger()
        led.record_batch("m", span_ms=3.0, compile_ms=30.0,
                         requests=[("a", 1)])
        assert led.device_ms("a") == pytest.approx(0.0)
        assert led.totals("m")["compile_ms"] == pytest.approx(3.0)
        assert led.conservation("m")["ok"]

    def test_bill_observes_once(self):
        m = MetricsRegistry()
        led = CostLedger(m)
        led.record_batch("m", span_ms=4.0, requests=[("a", 1)])
        assert led.bill("a", model="m") == pytest.approx(4.0)
        assert led.bill("a", model="m") == pytest.approx(4.0)
        hist = m.get("request_device_ms")
        assert hist.count(model="m", priority="1") == 1
        assert led.bill(None, model="m") is None
        assert led.bill("unknown", model="m") is None

    def test_retried_request_accumulates_across_batches(self):
        led = CostLedger()
        led.record_batch("m", span_ms=4.0, requests=[("a", 1)])
        led.record_batch("m", span_ms=6.0, requests=[("a", 1)])
        rc = led.recent(1)[0]
        assert rc["device_ms"] == pytest.approx(10.0)
        assert rc["batches"] == 2
        assert led.conservation("m")["ok"]

    def test_capacity_eviction_keeps_conservation(self):
        led = CostLedger(capacity=4)
        for i in range(6):
            led.record_batch("m", span_ms=1.0, requests=[(f"t{i}", 1)])
        assert led.evicted == 2
        assert led.device_ms("t0") is None  # oldest evicted
        assert led.device_ms("t5") == pytest.approx(1.0)
        # eviction forgets the per-request entry, not the totals
        cons = led.conservation("m")
        assert cons["ok"] and cons["attributed_device_ms"] == \
            pytest.approx(6.0)
        d = led.describe()
        assert d["tracked_requests"] == 4 and d["evicted_requests"] == 2
        assert d["conservation"]["ok"]

    def test_zero_row_batch_still_conserves(self):
        led = CostLedger()
        led.record_batch("m", span_ms=5.0, requests=())
        t = led.totals("m")
        assert t["unattributed_device_ms"] == pytest.approx(5.0)
        assert led.conservation("m")["ok"]


class TestCompileExclusionEndToEnd:
    def test_cold_bucket_compile_never_bills_the_request(self):
        """A real XLA compile inside `batch_execute` (cold bucket, no
        warmup) lands in the model's compile bucket — with
        `tracer.compile_count` as the independent oracle — and the
        triggering request's bill stays steady-state small."""
        from tests.test_serving import small_net

        m = MetricsRegistry()
        tracer = enable_tracing(Tracer(), metrics=m)
        registry = ModelRegistry(metrics=m, warmup="off")
        registry.register("cold", small_net(seed=5))
        server = ModelServer(registry, metrics=m)
        server.start()
        client = ModelServingClient(server.url)
        try:
            c0 = tracer.compile_count
            out = client.predict("cold", np.ones((1, 12), np.float32))
            assert np.asarray(out).shape == (1, 4)
            # the ledger entry lands just after the batch span closes on
            # the dispatcher thread; the oracle (compile_count) is
            # already final once the response is back
            deadline = time.time() + 10.0
            while server.cost.totals("cold")["batches"] < 1:
                assert time.time() < deadline, "batch never ledgered"
                time.sleep(0.005)
            assert tracer.compile_count > c0, \
                "cold-bucket predict did not compile; oracle broken"
            t = server.cost.totals("cold")
            assert t["compile_ms"] > 0.0, \
                "real compile not excluded from the batch span"
            assert m.get("request_compile_device_ms_total").value(
                model="cold") == pytest.approx(t["compile_ms"])
            billed = server.cost.device_ms(client.last_trace_id)
            assert billed is not None and billed < t["compile_ms"], \
                (billed, t["compile_ms"])
            assert server.cost.conservation("cold")["ok"]

            # warm path: same shape again must not grow the compile side
            c1, comp1 = tracer.compile_count, t["compile_ms"]
            client.predict("cold", np.ones((1, 12), np.float32))
            deadline = time.time() + 10.0
            while server.cost.totals("cold")["batches"] < 2:
                assert time.time() < deadline, "batch never ledgered"
                time.sleep(0.005)
            assert tracer.compile_count == c1
            assert server.cost.totals("cold")["compile_ms"] == \
                pytest.approx(comp1)
        finally:
            client.close()
            server.stop(drain=False)
            registry.shutdown()
            disable_tracing()


# ---------------------------------------------------------------------- SLOs
class TestSLOMath:
    def test_latency_counts_judges_against_bucket_bounds(self):
        m = MetricsRegistry()
        h = m.histogram("lat", "latency", buckets=[0.1, 1.0])
        for v in (0.05, 0.5, 5.0):
            h.observe(v)
        sample = parse_prometheus_text(m.exposition())
        assert latency_counts(sample, "lat", 0.1) == (1.0, 3.0)
        assert latency_counts(sample, "lat", 1.0) == (2.0, 3.0)
        # sub-bucket threshold: every event a violation, deliberately
        assert latency_counts(sample, "lat", 0.001) == (0.0, 3.0)
        assert latency_counts(sample, "absent", 0.1) is None

    def test_latency_label_subset_matching_sums_series(self):
        m = MetricsRegistry()
        h = m.histogram("lat", "latency", ("model", "route"),
                        buckets=[0.1])
        h.observe(0.05, model="a", route="x")
        h.observe(0.05, model="a", route="y")
        h.observe(0.05, model="b", route="x")
        sample = parse_prometheus_text(m.exposition())
        assert latency_counts(sample, "lat", 0.1,
                              {"model": "a"}) == (2.0, 2.0)

    def test_availability_compliance_from_error_labels(self):
        slo = load_slos({"slos": [{
            "name": "avail", "sli": "availability",
            "metric": "reqs_total", "error_labels": {"status": "500"},
            "objective": 0.9}]}).slos[0]
        m = MetricsRegistry()
        c = m.counter("reqs_total", "r", ("status",))
        c.inc(19, status="200")
        c.inc(1, status="500")
        comp = slo.compliance(parse_prometheus_text(m.exposition()))
        assert comp == {"good": 19.0, "total": 20.0, "ratio": 0.95,
                        "met": True}

    def test_load_slos_schema_errors_name_the_entry(self):
        cases = [
            ({"slos": [{"name": "x", "sli": "wat", "metric": "m"}]},
             "unknown sli"),
            ({"slos": [{"name": "x", "sli": "latency", "metric": "m",
                        "threshold_ms": 1, "objective": 1.5}]},
             "objective"),
            ({"slos": [{"name": "x", "sli": "latency", "metric": "m"}]},
             "threshold_ms"),
            ({"slos": [{"name": "x", "sli": "availability",
                        "metric": "m"}]}, "error_labels"),
            ({"slos": [{"name": "x", "sli": "latency", "metric": "m",
                        "threshold_ms": 1, "windows": []}]}, "windows"),
            ({"slos": [{"name": "x", "sli": "latency", "metric": "m",
                        "threshold_ms": 1,
                        "windows": [{"long_s": 60}]}]}, "long_s"),
            ({"slos": [{"sli": "latency", "metric": "m",
                        "threshold_ms": 1}]}, "name"),
            ({"slos": ["nope"]}, "not an object"),
            ({"nope": []}, "slos"),
        ]
        for spec, needle in cases:
            with pytest.raises(ValueError, match=needle):
                load_slos(spec)
        dup = {"name": "x", "sli": "latency", "metric": "m",
               "threshold_ms": 1}
        with pytest.raises(ValueError, match="duplicate"):
            load_slos({"slos": [dup, dict(dup)]})

    def test_burn_rule_fires_once_and_resolves_on_manual_clock(self):
        m = MetricsRegistry()
        h = m.histogram("serving_request_latency_seconds", "lat",
                        ("model",))
        slo_set = load_slos({"slos": [{
            "name": "lat", "sli": "latency",
            "metric": "serving_request_latency_seconds",
            "labels": {"model": "m"},
            "threshold_ms": 0.001, "objective": 0.99,
            "windows": [{"long_s": 3600, "short_s": 10, "factor": 2.0}]}]})
        clock = ManualTimeSource(0)
        notes = []
        mgr = AlertManager(m, slo_set.rules(), [CallbackSink(notes.append)],
                           time_source=clock)
        mgr.evaluate_once()                      # baseline, nothing yet
        for _ in range(20):                      # 20 violations
            h.observe(0.05, model="m")
        clock.advance(seconds=5)
        fired = mgr.evaluate_once()
        assert [n.state for n in fired] == ["firing"]
        status = slo_set.status(metrics=m, alerts=mgr)
        entry = status["slos"][0]
        assert entry["alert"]["state"] == "firing"
        assert entry["compliance"]["met"] is False
        b = entry["burn"][0]
        assert b["active"] and b["long"] == pytest.approx(100.0)
        # recovery is traffic silence: the short window drains to zero
        clock.advance(seconds=400)
        resolved = mgr.evaluate_once()
        assert [n.state for n in resolved] == ["resolved"]
        clock.advance(seconds=60)
        assert mgr.evaluate_once() == []         # deduped: no flapping
        assert [n.state for n in notes] == ["firing", "resolved"]

    def test_status_without_manager_reports_unmanaged(self):
        m = MetricsRegistry()
        m.histogram("serving_request_latency_seconds", "lat",
                    ("model",)).observe(0.01, model="m")
        slo_set = load_slos({"slos": [{
            "name": "lat", "sli": "latency",
            "metric": "serving_request_latency_seconds",
            "labels": {"model": "m"}, "threshold_ms": 250}]})
        entry = slo_set.status(metrics=m)["slos"][0]
        assert entry["alert"] == {"rule": "slo_burn:lat",
                                  "state": "unmanaged"}
        assert entry["compliance"]["met"] is True
        # one scrape has no deltas: burn is zero, never None-crashes
        assert all(b["long"] == 0.0 for b in entry["burn"])


# -------------------------------------------------------------- tail sampler
def _span(name, trace, *, span_id="s", parent=None, start_ns=0,
          dur_ms=1.0, error=None):
    sp = Span(name, trace_id=trace, span_id=span_id, parent_id=parent,
              start_ns=start_ns)
    sp.end_ns = start_ns + int(dur_ms * 1e6)
    sp.error = error
    return sp


class _ListSink:
    def __init__(self):
        self.spans = []

    def add(self, span):
        self.spans.append(span)


class TestTailSampler:
    def test_slow_root_kept_fast_dropped_complete_traces(self):
        m = MetricsRegistry()
        sink = _ListSink()
        ts = TailSampler(sink, default_slow_ms=100.0, metrics=m)
        # fast trace: child buffered, root decides → drop both spans
        ts.add(_span("work", "fast", parent="r", dur_ms=1.0))
        ts.add(_span("root", "fast", dur_ms=5.0))
        # slow trace: kept as a COMPLETE trace, child included
        ts.add(_span("work", "slow", parent="r", dur_ms=90.0))
        ts.add(_span("root", "slow", dur_ms=150.0))
        assert {s.trace_id for s in sink.spans} == {"slow"}
        assert len(sink.spans) == 2
        d = ts.describe()
        assert d["kept_traces"] == 1 and d["kept_spans"] == 2
        assert d["dropped_traces"] == 1 and d["dropped_spans"] == 2
        assert d["keep_reasons"] == {"slow": 1}
        dec = m.get("trace_tail_traces_total")
        assert dec.value(decision="slow") == 1
        assert dec.value(decision="drop") == 1

    def test_error_beats_slow_in_keep_order(self):
        sink = _ListSink()
        ts = TailSampler(sink, default_slow_ms=1.0)
        ts.add(_span("work", "t", parent="r", dur_ms=0.1, error="boom"))
        ts.add(_span("root", "t", dur_ms=500.0))
        assert ts.describe()["keep_reasons"] == {"error": 1}

    def test_named_root_kind_decides_with_own_threshold(self):
        # a server root with a remote traceparent HAS a parent; naming it
        # in slow_ms makes it the decision point
        sink = _ListSink()
        ts = TailSampler(sink, slow_ms={"http_request": 50.0},
                         default_slow_ms=10_000.0)
        ts.add(_span("http_request", "t", parent="remote", dur_ms=60.0))
        assert ts.describe()["keep_reasons"] == {"slow": 1}

    def test_exemplar_referenced_trace_kept(self):
        sink = _ListSink()
        ts = TailSampler(sink, default_slow_ms=10_000.0,
                         exemplar_source=lambda: {"hot"})
        ts.add(_span("root", "hot", dur_ms=1.0))
        ts.add(_span("root", "cold", span_id="s2", dur_ms=1.0))
        assert ts.describe()["keep_reasons"] == {"exemplar": 1}
        assert {s.trace_id for s in sink.spans} == {"hot"}

    def test_exemplar_source_as_registry(self):
        m = MetricsRegistry()
        h = m.histogram("lat", "latency")
        t = Tracer()
        with t.span("req") as sp:
            h.observe(0.5)
        sink = _ListSink()
        ts = TailSampler(sink, default_slow_ms=10_000.0, exemplar_source=m)
        ts.add(_span("root", sp.trace_id, dur_ms=1.0))
        assert ts.describe()["keep_reasons"] == {"exemplar": 1}

    def test_firing_alerts_keep_everything(self):
        class _Mgr:
            def firing(self):
                return ["latency_slo"]

        sink = _ListSink()
        ts = TailSampler(sink, default_slow_ms=10_000.0, alerts=_Mgr())
        ts.add(_span("root", "t", dur_ms=1.0))
        assert ts.describe()["keep_reasons"] == {"alert": 1}

    def test_probability_floor_is_deterministic_in_trace_id(self):
        sink = _ListSink()
        ts = TailSampler(sink, default_slow_ms=10_000.0, probability=0.5)
        ts.add(_span("root", "00000000aaaa", dur_ms=1.0))  # 0.0 < 0.5
        ts.add(_span("root", "ffffffffaaaa", span_id="s2",
                     dur_ms=1.0))                          # 1.0 >= 0.5
        d = ts.describe()
        assert d["keep_reasons"] == {"floor": 1}
        assert d["dropped_traces"] == 1
        with pytest.raises(ValueError):
            TailSampler(sink, probability=1.5)

    def test_disk_budget_drops_are_counted_separately(self):
        sink = _ListSink()
        ts = TailSampler(sink, default_slow_ms=1.0, max_bytes=10)
        ts.add(_span("root", "slow-but-broke", dur_ms=500.0))
        d = ts.describe()
        assert d["kept_traces"] == 0
        assert d["dropped_budget_traces"] == 1
        assert d["dropped_traces"] == 1
        assert not sink.spans

    def test_pending_eviction_bounds_unfinished_traces(self):
        sink = _ListSink()
        ts = TailSampler(sink, default_slow_ms=1.0, max_pending=2)
        for i in range(4):  # children only: roots never arrive
            ts.add(_span("work", f"t{i}", parent="r", dur_ms=1.0))
        d = ts.describe()
        assert d["dropped_pending_traces"] == 2
        assert d["pending_traces"] == 2
        # the evicted trace's verdict is remembered: its late root drops
        ts.add(_span("root", "t0", dur_ms=500.0))
        assert ts.describe()["kept_traces"] == 0

    def test_late_spans_follow_the_decided_verdict(self):
        sink = _ListSink()
        ts = TailSampler(sink, default_slow_ms=100.0)
        ts.add(_span("root", "keep", dur_ms=150.0))
        ts.add(_span("late", "keep", span_id="s2", parent="x", dur_ms=1.0))
        ts.add(_span("root", "drop", span_id="s3", dur_ms=1.0))
        ts.add(_span("late", "drop", span_id="s4", parent="x", dur_ms=1.0))
        d = ts.describe()
        assert d["kept_spans"] == 2 and len(sink.spans) == 2
        assert d["dropped_spans"] == 2

    def test_ring_records_everything_regardless_of_sink_verdict(self):
        sink = _ListSink()
        ts = TailSampler(sink, default_slow_ms=100.0)
        ts.add(_span("root", "drop", dur_ms=1.0))
        assert [s.trace_id for s in ts.spans()] == ["drop"]

    def test_close_drops_undecided_and_closes_sink(self):
        class _ClosableSink(_ListSink):
            closed = False

            def close(self):
                self.closed = True

        sink = _ClosableSink()
        ts = TailSampler(sink, default_slow_ms=1.0)
        ts.add(_span("work", "t", parent="r", dur_ms=1.0))
        ts.close()
        d = ts.describe()
        assert d["dropped_pending_traces"] == 1 and d["pending_traces"] == 0
        assert sink.closed


# --------------------------------------------------------- serving endpoints
class TestServingCostSLOEndpoints:
    def test_slo_endpoint_capture_and_device_ms_header(self):
        m = MetricsRegistry()
        enable_tracing(Tracer(), metrics=m)
        slo_set = load_slos({"slos": [{
            "name": "lat", "sli": "latency",
            "metric": "serving_request_latency_seconds",
            "labels": {"model": "m"}, "threshold_ms": 0.001,
            "objective": 0.99,
            "windows": [{"long_s": 3600, "short_s": 10, "factor": 2.0}]}]})
        clock = ManualTimeSource(0)
        mgr = AlertManager(m, slo_set.rules(), [], time_source=clock)
        registry = ModelRegistry(metrics=m)
        registry.register("m", _SumModel())
        server = ModelServer(registry, metrics=m, alerts=mgr, slo=slo_set)
        port = server.start()
        url = f"http://127.0.0.1:{port}"
        client = ModelServingClient(url)
        try:
            mgr.evaluate_once()
            for _ in range(4):
                client.predict("m", [[1.0, 2.0]])
            tid = client.last_trace_id
            assert tid is not None

            # X-Device-Ms rides the response once the batch is ledgered
            # (the entry lands just after the batch span closes, so the
            # first response may legitimately predate it)
            body = json.dumps({"inputs": [[1.0, 2.0]]}).encode()
            hdr = None
            for _ in range(10):
                with urllib.request.urlopen(
                        urllib.request.Request(
                            f"{url}/v1/models/m/predict", body),
                        timeout=10) as r:
                    hdr = r.headers.get("X-Device-Ms")
                if hdr is not None:
                    break
            assert hdr is not None and float(hdr) >= 0.0

            clock.advance(seconds=5)
            mgr.evaluate_once()
            status = json.load(urllib.request.urlopen(f"{url}/slo",
                                                      timeout=5))
            entry = status["slos"][0]
            assert entry["name"] == "lat"
            assert entry["alert"]["state"] == "firing"
            assert entry["compliance"]["met"] is False
            assert entry["burn"][0]["active"] is True

            bundle = json.load(urllib.request.urlopen(
                f"{url}/debug/capture?seconds=60", timeout=10))
            assert bundle["kind"] == "debug_capture"
            events = bundle["trace"]["traceEvents"]
            assert any(e.get("args", {}).get("trace_id") == tid
                       for e in events)
            assert bundle["cost"]["totals"]["conservation"]["ok"]
            recent_ids = {rc["trace_id"] for rc in bundle["cost"]["recent"]}
            assert tid in recent_ids
            assert bundle["metrics"] is not None

            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(
                    f"{url}/debug/capture?seconds=nope", timeout=5)
            assert ei.value.code == 400
        finally:
            client.close()
            server.stop(drain=False)
            registry.shutdown()
            disable_tracing()

    def test_slo_endpoint_404_without_config(self):
        m = MetricsRegistry()
        registry = ModelRegistry(metrics=m)
        server = ModelServer(registry, metrics=m)
        port = server.start()
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/slo", timeout=5)
            assert ei.value.code == 404
        finally:
            server.stop(drain=False)
            registry.shutdown()


# --------------------------------------------------------------- bench --slo
@pytest.mark.smoke
class TestBenchServingSLOCheck:
    def test_slo_check_mode_passes_against_committed_series(self):
        """The r03 cost/SLO record's invariants re-prove themselves on
        every CI run: burn-rate fire-once/resolve, ledger conservation
        with zero steady-state compiles, tail-sampler keeps AND drops,
        exemplar-to-trace retrievability."""
        committed = os.path.join(REPO_ROOT, "BENCH_SERVING_r03.json")
        assert os.path.exists(committed), \
            "BENCH_SERVING_r03.json must be committed with the series"
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO_ROOT, "bench_serving.py"),
             "--check", committed],
            capture_output=True, text=True, timeout=300,
            env=dict(os.environ, JAX_PLATFORMS="cpu"))
        assert proc.returncode == 0, \
            f"stdout: {proc.stdout}\nstderr: {proc.stderr}"
        assert "slo check OK" in proc.stdout

    def test_committed_slo_series_records_acceptance_numbers(self):
        with open(os.path.join(REPO_ROOT, "BENCH_SERVING_r03.json")) as f:
            rec = json.load(f)
        assert rec["series"] == "BENCH_SERVING" and rec["round"] == 3
        slo = rec["slo"]
        assert slo["alert_states"] == ["firing", "resolved"]
        assert slo["compliance"]["met"] is False
        assert slo["burn"]["active"] is True
        assert slo["cost"]["conservation_ok"] is True
        assert slo["cost"]["requests"] >= 1
        assert slo["steady_state_compiles"] == 0
        assert slo["sampler"]["kept_traces"] >= 1
        assert slo["sampler"]["dropped_traces"] >= 1
        assert slo["exemplar_trace_captured"] is True
