"""EvaluationTools: ROC/calibration chart HTML export.

Reference: deeplearning4j-core/evaluation/EvaluationTools.java.
"""

import numpy as np
import pytest

from deeplearning4j_tpu.eval.calibration import EvaluationCalibration
from deeplearning4j_tpu.eval.roc import ROC, ROCBinary, ROCMultiClass
from deeplearning4j_tpu.eval.tools import (
    calibration_chart_to_html,
    export_roc_charts_to_html_file,
    roc_chart_to_html,
)


def _binary_data(n=200, seed=0):
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, 2, n)
    scores = np.clip(labels * 0.5 + rng.rand(n) * 0.6, 0, 1)
    return labels, scores


class TestRocChartToHtml:
    def test_single_roc_page(self):
        labels, scores = _binary_data()
        roc = ROC()
        roc.eval(labels, scores)
        html = roc_chart_to_html(roc)
        assert html.startswith("<!DOCTYPE html>") or "<html" in html
        assert "AUC (ROC)" in html
        assert f"{roc.calculate_auc():.5f}" in html
        assert "Precision" in html and "svg" in html

    def test_multiclass_sections(self):
        rng = np.random.RandomState(1)
        n, c = 150, 3
        y = rng.randint(0, c, n)
        labels = np.eye(c)[y]
        logits = rng.rand(n, c) + labels * 1.5
        probs = logits / logits.sum(axis=1, keepdims=True)
        roc = ROCMultiClass()
        roc.eval(labels, probs)
        html = roc_chart_to_html(roc, class_names=["ant", "bee", "cow"])
        for name in ("ant", "bee", "cow"):
            assert f"Class: {name}" in html

    def test_rocbinary_sections(self):
        rng = np.random.RandomState(2)
        labels = rng.randint(0, 2, (100, 2))
        scores = np.clip(labels * 0.4 + rng.rand(100, 2) * 0.7, 0, 1)
        roc = ROCBinary()
        roc.eval(labels, scores)
        html = roc_chart_to_html(roc)
        assert "Class: 0" in html and "Class: 1" in html

    def test_export_to_file(self, tmp_path):
        labels, scores = _binary_data()
        roc = ROC()
        roc.eval(labels, scores)
        path = str(tmp_path / "roc.html")
        export_roc_charts_to_html_file(roc, path)
        with open(path) as fh:
            assert "AUC" in fh.read()

    def test_type_error(self):
        with pytest.raises(TypeError):
            roc_chart_to_html(object())


class TestCalibrationChart:
    def test_calibration_page(self):
        rng = np.random.RandomState(3)
        n = 300
        y = rng.randint(0, 2, n)
        labels = np.eye(2)[y]
        p1 = np.clip(0.3 + 0.4 * y + 0.3 * rng.rand(n), 0, 1)
        probs = np.stack([1 - p1, p1], axis=1)
        cal = EvaluationCalibration()
        cal.eval(labels, probs)
        html = calibration_chart_to_html(cal, class_idx=1)
        assert "Reliability Diagram" in html
        assert "svg" in html
