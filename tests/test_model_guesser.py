"""ModelGuesser: load models/configs of unknown provenance.

Reference: deeplearning4j-core/util/ModelGuesser.java (loadModelGuess,
loadConfigGuess, loadNormalizer).
"""

import numpy as np
import pytest

from deeplearning4j_tpu.nn.conf.network import NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers.core import DenseLayer
from deeplearning4j_tpu.nn.layers.output import OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.util import model_serializer as ms
from deeplearning4j_tpu.util.model_guesser import (
    ModelGuesserException,
    load_config_guess,
    load_model_guess,
    load_normalizer,
)


def small_net():
    conf = (NeuralNetConfiguration.builder().seed(1).updater("sgd").list()
            .layer(DenseLayer(n_in=3, n_out=4, activation="tanh"))
            .layer(OutputLayer(n_in=4, n_out=2))
            .build())
    net = MultiLayerNetwork(conf)
    net.init()
    return net


class TestModelGuesser:
    def test_guess_own_mln_zip(self, tmp_path):
        net = small_net()
        path = str(tmp_path / "m.zip")
        ms.write_model(net, path)
        loaded = load_model_guess(path)
        assert isinstance(loaded, MultiLayerNetwork)
        x = np.ones((2, 3), np.float32)
        np.testing.assert_allclose(np.asarray(loaded.output(x)),
                                   np.asarray(net.output(x)), rtol=1e-6)

    def test_guess_config_json(self, tmp_path):
        net = small_net()
        p = tmp_path / "conf.json"
        p.write_text(net.conf.to_json())
        conf = load_config_guess(str(p))
        assert len(conf.layers) == 2

    def test_guess_config_yaml(self, tmp_path):
        net = small_net()
        p = tmp_path / "conf.yaml"
        p.write_text(net.conf.to_yaml())
        conf = load_config_guess(str(p))
        assert len(conf.layers) == 2

    def test_guess_garbage_raises(self, tmp_path):
        p = tmp_path / "junk.bin"
        p.write_bytes(b"\x00\x01\x02 not a model")
        with pytest.raises(ModelGuesserException):
            load_model_guess(str(p))
        with pytest.raises(ModelGuesserException):
            load_config_guess(str(p))

    def test_load_normalizer(self, tmp_path):
        from deeplearning4j_tpu.datasets.normalizers import NormalizerStandardize
        net = small_net()
        path = str(tmp_path / "m.zip")
        ms.write_model(net, path)
        from deeplearning4j_tpu.datasets.dataset import DataSet
        feats = np.random.RandomState(0).randn(10, 3).astype(np.float32)
        norm = NormalizerStandardize()
        norm.fit(DataSet(feats, np.zeros((10, 2), np.float32)))
        ms.add_normalizer_to_model(path, norm)
        loaded = load_normalizer(path)
        assert isinstance(loaded, NormalizerStandardize)
