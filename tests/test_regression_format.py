"""Checkpoint-format regression tests (RegressionTest050/060/071/080 pattern):
a model zip produced by an earlier build is committed as a fixture; restoring
it must keep producing the exact recorded outputs, locking the serialization
format against drift."""

import os

import numpy as np
import pytest

from deeplearning4j_tpu.ui.components import (
    ChartHistogram,
    ChartHorizontalBar,
    ChartLine,
    ChartScatter,
    ChartStackedArea,
    ChartTimeline,
    ComponentDiv,
    ComponentTable,
    ComponentText,
    DecoratorAccordion,
)
from deeplearning4j_tpu.util import model_serializer

FIXTURE_DIR = os.path.join(os.path.dirname(__file__), "fixtures")


class TestCheckpointFormatRegression:
    def test_restore_v1_fixture_exact_outputs(self):
        zip_path = os.path.join(FIXTURE_DIR, "regression_model_v1.zip")
        expected = np.load(os.path.join(FIXTURE_DIR,
                                        "regression_model_v1_expected.npz"))
        net = model_serializer.restore_multi_layer_network(zip_path)
        out = np.asarray(net.output(expected["probe"]))
        np.testing.assert_allclose(out, expected["output"], rtol=1e-6,
                                   atol=1e-7)

    def test_restored_fixture_keeps_training(self):
        zip_path = os.path.join(FIXTURE_DIR, "regression_model_v1.zip")
        net = model_serializer.restore_multi_layer_network(zip_path)
        from deeplearning4j_tpu.datasets.dataset import DataSet
        rng = np.random.default_rng(1)
        x = rng.normal(size=(16, 8, 8, 1)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 16)]
        net.fit(DataSet(x, y))  # updater state restored → step must work
        assert np.isfinite(float(net.score_))


class TestUiComponents:
    def test_chart_line_json_and_svg(self):
        chart = (ChartLine("loss").add_series("train", [0, 1, 2], [3.0, 2.0, 1.5])
                 .add_series("val", [0, 1, 2], [3.2, 2.4, 1.9]))
        d = chart.to_dict()
        assert d["type"] == "chart_line" and len(d["series"]) == 2
        svg = chart.render()
        assert svg.startswith("<svg") and "polyline" in svg and "loss" in svg

    def test_scatter_and_histogram(self, rng):
        sc = ChartScatter("pts").add_series("a", [0, 1], [1, 0])
        assert "circle" in sc.render()
        hist = ChartHistogram.from_values(rng.normal(size=500), n_bins=12,
                                          title="weights")
        assert len(hist.bins) == 12
        assert "rect" in hist.render()
        assert sum(b["count"] for b in hist.to_dict()["bins"]) == 500

    def test_horizontal_bar(self):
        bar = (ChartHorizontalBar("per-class F1")
               .add_bar("cat", 0.9).add_bar("dog", -0.2))
        d = bar.to_dict()
        assert d["type"] == "chart_horizontal_bar" and len(d["bars"]) == 2
        svg = bar.render()
        assert svg.count("<rect") == 2 and "cat" in svg

    def test_stacked_area(self):
        area = (ChartStackedArea("memory")
                .set_x_values([0, 1, 2])
                .add_series("params", [1, 1, 1])
                .add_series("activations", [0, 2, 1]))
        svg = area.render()
        assert svg.count("<polygon") == 2
        with pytest.raises(ValueError):
            area.add_series("bad", [1, 2])  # length mismatch
        d = area.to_dict()
        assert d["x"] == [0, 1, 2] and len(d["series"]) == 2

    def test_timeline(self):
        tl = (ChartTimeline("phases")
              .add_lane("worker0", [(0, 5, "fit"), (5, 6, "sync")])
              .add_lane("worker1", [(0, 4, "fit")]))
        svg = tl.render()
        assert svg.count("<rect") == 3 and "worker1" in svg
        assert "<title>fit</title>" in svg  # hover labels

    def test_accordion(self):
        acc = DecoratorAccordion("details", False,
                                 ComponentText("hidden content"))
        out = acc.render()
        assert out.startswith("<details open>")
        assert "hidden content" in out
        closed = DecoratorAccordion("c", True).add(ComponentText("x")).render()
        assert closed.startswith("<details>")

    def test_table_text_div_page(self):
        page = ComponentDiv(
            ComponentText("Training report"),
            ComponentTable(["layer", "params"], [["dense", 128], ["out", 33]]),
            ChartLine("score").add_series("s", [0, 1], [1.0, 0.5]),
        )
        html_page = page.render_page("report")
        assert html_page.startswith("<!DOCTYPE html>")
        assert "<table" in html_page and "Training report" in html_page
        assert "<svg" in html_page
        # json composition round-trips
        import json
        d = json.loads(page.to_json())
        assert len(d["children"]) == 3

    def test_mismatched_series_raises(self):
        with pytest.raises(ValueError):
            ChartLine().add_series("bad", [1, 2], [1.0])


class TestGraphCheckpointFormatRegression:
    """ComputationGraph zip fixture (attention + LayerNorm + vertices +
    multi-input): the format the graph serializer writes today must keep
    restoring bit-exact in future builds."""

    def test_restore_graph_v1_fixture_exact_outputs(self):
        zip_path = os.path.join(FIXTURE_DIR, "regression_graph_v1.zip")
        expected = np.load(os.path.join(FIXTURE_DIR,
                                        "regression_graph_v1_expected.npz"))
        net = model_serializer.restore_computation_graph(zip_path)
        out = np.asarray(net.output(expected["probe_a"], expected["probe_b"]))
        np.testing.assert_allclose(out, expected["output"], rtol=1e-6,
                                   atol=1e-7)

    def test_restored_graph_fixture_keeps_training(self):
        from deeplearning4j_tpu.datasets.dataset import MultiDataSet
        zip_path = os.path.join(FIXTURE_DIR, "regression_graph_v1.zip")
        net = model_serializer.restore_computation_graph(zip_path)
        rng = np.random.default_rng(1)
        xa = rng.normal(size=(2, 10, 6)).astype(np.float32)
        xb = rng.normal(size=(2, 10, 4)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[
            rng.integers(0, 3, (2, 10)).reshape(-1)].reshape(2, 10, 3)
        net.fit([MultiDataSet([xa, xb], [y])])  # updater state restored too
        assert np.isfinite(float(net.score_))
