"""Mixture-of-experts tests: layer math, training, and expert-parallel
equivalence over the mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import DataSet, ListDataSetIterator
from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.layers.moe import (
    EXPERT_AXIS,
    MixtureOfExpertsLayer,
    ep_forward,
    load_balancing_loss,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.parallel.mesh import make_mesh


class TestMoELayer:
    def test_top1_selects_single_expert(self, rng):
        layer = MixtureOfExpertsLayer(n_in=6, n_out=6, n_experts=4, top_k=1)
        params = layer.init_params(jax.random.PRNGKey(0))
        x = jnp.asarray(rng.normal(size=(5, 6)).astype(np.float32))
        out, _ = layer.forward(params, x)
        assert out.shape == (5, 6)
        # manual: the argmax expert's FFN output
        logits = np.asarray(x @ params["Wg"])
        for i in range(5):
            e = int(np.argmax(logits[i]))
            manual = np.maximum(
                np.asarray(x[i]) @ np.asarray(params["W"][e])
                + np.asarray(params["b"][e]), 0.0)
            np.testing.assert_allclose(np.asarray(out[i]), manual,
                                       rtol=1e-5, atol=1e-6)

    def test_top2_gates_renormalized(self, rng):
        layer = MixtureOfExpertsLayer(n_in=4, n_experts=3, top_k=2)
        layer.set_n_in(InputType.feed_forward(4))
        params = layer.init_params(jax.random.PRNGKey(1))
        x = jnp.asarray(rng.normal(size=(7, 4)).astype(np.float32))
        from deeplearning4j_tpu.nn.layers.moe import _moe_apply
        _, gates = _moe_apply(params, x, 2, layer.act_fn())
        g = np.asarray(gates)
        np.testing.assert_allclose(g.sum(-1), 1.0, atol=1e-5)
        assert ((g > 1e-9).sum(-1) <= 2).all()  # at most 2 experts active

    def test_trains_in_network(self, rng):
        conf = (NeuralNetConfiguration.builder().seed(1).list()
                .layer(MixtureOfExpertsLayer(n_out=16, n_experts=4, top_k=2))
                .layer(OutputLayer(n_out=3))
                .set_input_type(InputType.feed_forward(8)).build())
        net = MultiLayerNetwork(conf).init()
        y_idx = rng.integers(0, 3, 256)
        x = rng.normal(size=(256, 8)).astype(np.float32)
        x[np.arange(256), y_idx] += 2.5
        ds = DataSet(x, np.eye(3, dtype=np.float32)[y_idx])
        net.fit(ListDataSetIterator(ds, 64, shuffle=True), epochs=12)
        assert net.evaluate(ListDataSetIterator(ds, 256)).accuracy() > 0.85

    def test_sequence_input(self, rng):
        layer = MixtureOfExpertsLayer(n_in=4, n_out=4, n_experts=2, top_k=1)
        params = layer.init_params(jax.random.PRNGKey(2))
        x = jnp.asarray(rng.normal(size=(3, 5, 4)).astype(np.float32))
        out, _ = layer.forward(params, x)
        assert out.shape == (3, 5, 4)

    def test_load_balancing_loss_prefers_uniform(self):
        uniform = jnp.full((10, 4), 0.25)
        skewed = jnp.zeros((10, 4)).at[:, 0].set(1.0)
        assert float(load_balancing_loss(skewed)) > \
            float(load_balancing_loss(uniform))


class TestExpertParallel:
    def test_ep_matches_single_device(self, rng):
        """Expert-sharded mesh execution == plain forward (the EP lock)."""
        layer = MixtureOfExpertsLayer(n_in=8, n_out=8, n_experts=8, top_k=2)
        params = layer.init_params(jax.random.PRNGKey(3))
        x = jnp.asarray(rng.normal(size=(6, 8)).astype(np.float32))
        plain, _ = layer.forward(params, x)
        mesh = make_mesh({EXPERT_AXIS: 8})
        sharded = ep_forward(layer, params, x, mesh)
        np.testing.assert_allclose(np.asarray(sharded), np.asarray(plain),
                                   rtol=2e-5, atol=2e-6)

    def test_ep_partial_shards(self, rng):
        """8 experts over 4 shards (2 experts per device)."""
        layer = MixtureOfExpertsLayer(n_in=6, n_out=6, n_experts=8, top_k=1)
        params = layer.init_params(jax.random.PRNGKey(4))
        x = jnp.asarray(rng.normal(size=(4, 6)).astype(np.float32))
        plain, _ = layer.forward(params, x)
        mesh = make_mesh({EXPERT_AXIS: 4})
        sharded = ep_forward(layer, params, x, mesh)
        np.testing.assert_allclose(np.asarray(sharded), np.asarray(plain),
                                   rtol=2e-5, atol=2e-6)

    def test_indivisible_raises(self):
        layer = MixtureOfExpertsLayer(n_in=4, n_out=4, n_experts=6)
        params = layer.init_params(jax.random.PRNGKey(5))
        mesh = make_mesh({EXPERT_AXIS: 4})
        with pytest.raises(ValueError):
            ep_forward(layer, params, jnp.zeros((2, 4)), mesh)
