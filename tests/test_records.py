"""Record reader + bridge iterator tests (datasets/datavec parity)."""

import numpy as np
import pytest

from deeplearning4j_tpu.datasets.records import (
    AlignmentMode,
    CollectionRecordReader,
    CollectionSequenceRecordReader,
    CSVRecordReader,
    CSVSequenceRecordReader,
    LineRecordReader,
    RecordReaderDataSetIterator,
    RecordReaderMultiDataSetIterator,
    SequenceRecordReaderDataSetIterator,
)


@pytest.fixture
def csv_file(tmp_path):
    p = tmp_path / "data.csv"
    lines = []
    rng = np.random.default_rng(0)
    for i in range(10):
        feats = rng.normal(size=3)
        label = i % 4
        lines.append(",".join(f"{v:.4f}" for v in feats) + f",{label}")
    p.write_text("\n".join(lines) + "\n")
    return str(p)


class TestReaders:
    def test_csv_reader(self, csv_file):
        r = CSVRecordReader(csv_file)
        recs = list(r)
        assert len(recs) == 10
        assert len(recs[0]) == 4
        assert isinstance(recs[0][0], float)

    def test_csv_skip_lines(self, tmp_path):
        p = tmp_path / "h.csv"
        p.write_text("a,b,c\n1,2,3\n4,5,6\n")
        assert len(list(CSVRecordReader(str(p), skip_lines=1))) == 2

    def test_line_reader(self, tmp_path):
        p = tmp_path / "l.txt"
        p.write_text("one\ntwo\nthree\n")
        assert [r[0] for r in LineRecordReader(str(p))] == ["one", "two", "three"]

    def test_reset(self, csv_file):
        r = CSVRecordReader(csv_file)
        a = list(r)
        b = list(r)  # __iter__ resets
        assert len(a) == len(b) == 10


class TestRecordReaderDataSetIterator:
    def test_classification(self, csv_file):
        it = RecordReaderDataSetIterator(CSVRecordReader(csv_file), batch_size=4,
                                         label_index=3, num_possible_labels=4)
        batches = list(it)
        assert [b.features.shape for b in batches] == [(4, 3), (4, 3), (2, 3)]
        assert batches[0].labels.shape == (4, 4)
        # one-hot correctness: row i has label i%4
        assert np.argmax(batches[0].labels[1]) == 1

    def test_regression_range(self):
        recs = [[1.0, 2.0, 3.0, 4.0] for _ in range(6)]
        it = RecordReaderDataSetIterator(CollectionRecordReader(recs), 3,
                                         label_index=2, label_index_to=3,
                                         regression=True)
        b = next(iter(it))
        assert b.features.shape == (3, 2)
        np.testing.assert_allclose(b.labels[0], [3.0, 4.0])

    def test_no_label_autoencoder(self):
        recs = [[1.0, 2.0] for _ in range(4)]
        it = RecordReaderDataSetIterator(CollectionRecordReader(recs), 2)
        b = next(iter(it))
        np.testing.assert_allclose(b.features, b.labels)

    def test_max_num_batches(self, csv_file):
        it = RecordReaderDataSetIterator(CSVRecordReader(csv_file), 2,
                                         label_index=3, num_possible_labels=4,
                                         max_num_batches=2)
        assert len(list(it)) == 2

    def test_bad_label_raises(self):
        it = RecordReaderDataSetIterator(CollectionRecordReader([[1.0, 7.0]]), 1,
                                         label_index=1, num_possible_labels=3)
        with pytest.raises(ValueError):
            list(it)

    def test_trains_network(self, csv_file):
        from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration, InputType
        from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        conf = (NeuralNetConfiguration.builder().seed(1).list()
                .layer(DenseLayer(n_out=8, activation="relu"))
                .layer(OutputLayer(n_out=4))
                .set_input_type(InputType.feed_forward(3)).build())
        net = MultiLayerNetwork(conf).init()
        it = RecordReaderDataSetIterator(CSVRecordReader(csv_file), 5,
                                         label_index=3, num_possible_labels=4)
        net.fit(it, epochs=2)  # smoke: shapes flow through the jitted step


class TestSequenceIterators:
    def test_single_reader_classification(self):
        seqs = [
            [[0.1, 0.2, 0], [0.3, 0.4, 1], [0.5, 0.6, 2]],
            [[0.7, 0.8, 1], [0.9, 1.0, 0]],
        ]
        it = SequenceRecordReaderDataSetIterator(
            CollectionSequenceRecordReader(seqs), batch_size=2,
            num_possible_labels=3, label_index=2)
        b = next(iter(it))
        assert b.features.shape == (2, 3, 2)
        assert b.labels.shape == (2, 3, 3)
        # second sequence padded at the end, mask marks it
        assert b.features_mask is not None
        np.testing.assert_allclose(b.features_mask[1], [1, 1, 0])

    def test_two_readers_align_end(self):
        f = [[[1.0], [2.0], [3.0]], [[4.0], [5.0]]]
        l = [[[0]], [[1]]]  # one label per sequence
        it = SequenceRecordReaderDataSetIterator(
            CollectionSequenceRecordReader(f), batch_size=2,
            num_possible_labels=2,
            labels_reader=CollectionSequenceRecordReader(l),
            alignment_mode=AlignmentMode.ALIGN_END)
        b = next(iter(it))
        assert b.labels.shape == (2, 3, 2)
        # label aligned to last step
        assert b.labels_mask is not None
        np.testing.assert_allclose(b.labels_mask[0], [0, 0, 1])
        assert np.argmax(b.labels[0, 2]) == 0

    def test_equal_length_mismatch_raises(self):
        f = [[[1.0], [2.0]]]
        l = [[[0]]]
        it = SequenceRecordReaderDataSetIterator(
            CollectionSequenceRecordReader(f), 1, num_possible_labels=2,
            labels_reader=CollectionSequenceRecordReader(l),
            alignment_mode=AlignmentMode.EQUAL_LENGTH)
        with pytest.raises(ValueError):
            list(it)

    def test_csv_sequence_files(self, tmp_path):
        for i, rows in enumerate([3, 5]):
            (tmp_path / f"seq{i}.csv").write_text(
                "\n".join(f"{t}.0,{(t + i) % 2}" for t in range(rows)) + "\n")
        reader = CSVSequenceRecordReader(str(tmp_path / "seq*.csv"))
        it = SequenceRecordReaderDataSetIterator(reader, 2, num_possible_labels=2,
                                                 label_index=1)
        b = next(iter(it))
        assert b.features.shape == (2, 5, 1)


class TestMultiDataSetIterator:
    def test_builder_multi_io(self):
        recs = [[0.1, 0.2, 0.3, 1, 9.0] for _ in range(4)]
        it = (RecordReaderMultiDataSetIterator.Builder(2)
              .add_reader("r", CollectionRecordReader(recs))
              .add_input("r", 0, 2)
              .add_output_one_hot("r", 3, 3)
              .add_output("r", 4, 4)
              .build())
        mds = next(iter(it))
        assert mds.features[0].shape == (2, 3)
        assert mds.labels[0].shape == (2, 3)
        assert np.argmax(mds.labels[0][0]) == 1
        np.testing.assert_allclose(mds.labels[1][:, 0], 9.0)

    def test_unknown_reader_raises(self):
        with pytest.raises(ValueError):
            (RecordReaderMultiDataSetIterator.Builder(2)
             .add_input("nope").build())

    def test_partial_final_batch_emitted(self):
        recs = [[float(i), i % 2] for i in range(10)]
        it = (RecordReaderMultiDataSetIterator.Builder(4)
              .add_reader("r", CollectionRecordReader(recs))
              .add_input("r", 0, 0)
              .add_output_one_hot("r", 1, 2)
              .build())
        sizes = [m.features[0].shape[0] for m in it]
        assert sizes == [4, 4, 2]  # no silently dropped tail

    def test_negative_label_raises(self):
        it = SequenceRecordReaderDataSetIterator(
            CollectionSequenceRecordReader([[[0.5, -1]]]), 1,
            num_possible_labels=2, label_index=1)
        with pytest.raises(ValueError):
            list(it)

    def test_label_reader_shorter_raises(self):
        f = [[[1.0]], [[2.0]]]
        l = [[[0]]]
        it = SequenceRecordReaderDataSetIterator(
            CollectionSequenceRecordReader(f), 2, num_possible_labels=2,
            labels_reader=CollectionSequenceRecordReader(l))
        with pytest.raises(ValueError, match="sequence counts differ"):
            list(it)


class TestImageRecordReader:
    """DataVec ImageRecordReader role: directory tree -> labeled image
    DataSets, with metadata pointing at the source files."""

    @staticmethod
    def _make_tree(tmp_path, n_per_class=4, size=(10, 8)):
        from PIL import Image
        for ci, cls in enumerate(("cats", "dogs")):
            d = tmp_path / cls
            d.mkdir()
            for i in range(n_per_class):
                arr = np.full((size[1], size[0], 3),
                              40 * ci + 10 * i, np.uint8)
                Image.fromarray(arr).save(str(d / f"img_{i}.png"))
        return str(tmp_path)

    def test_walks_labels_and_decodes(self, tmp_path):
        from deeplearning4j_tpu.datasets.records import ImageRecordReader
        root = self._make_tree(tmp_path)
        r = ImageRecordReader(6, 5, 3, path=root)
        assert r.labels == ["cats", "dogs"]
        recs = list(r)
        assert len(recs) == 8
        img, label = recs[0]
        assert img.shape == (6, 5, 3) and img.dtype == np.float32
        assert label == 0
        assert {lab for _, lab in recs} == {0, 1}
        # grayscale variant
        g = ImageRecordReader(6, 5, 1, path=root)
        img1, _ = next(iter(g))
        assert img1.shape == (6, 5, 1)

    def test_through_iterator_with_scaler_and_metadata(self, tmp_path):
        from deeplearning4j_tpu.datasets.normalizers import (
            ImagePreProcessingScaler)
        from deeplearning4j_tpu.datasets.records import (
            ImageRecordReader, RecordReaderDataSetIterator)
        root = self._make_tree(tmp_path)
        r = ImageRecordReader(6, 5, 3, path=root)
        it = RecordReaderDataSetIterator(
            r, 3, label_index=1, num_possible_labels=len(r.labels),
            preprocessor=ImagePreProcessingScaler(),
            collect_meta_data=True)
        batches = list(it)
        assert sum(b.num_examples() for b in batches) == 8
        f = np.asarray(batches[0].features)
        assert f.shape == (3, 6, 5, 3)
        assert 0.0 <= f.min() and f.max() <= 1.0  # scaled to [0,1]
        assert np.asarray(batches[0].labels).shape == (3, 2)
        meta = batches[0].example_meta_data
        assert meta[0].uri.endswith(".png")
        # drilldown reload returns the same decoded image
        ds = it.load_from_meta_data(meta[:1])
        np.testing.assert_allclose(np.asarray(ds.features)[0], f[0],
                                   atol=1e-6)

    def test_flat_directory_single_class(self, tmp_path):
        from PIL import Image
        from deeplearning4j_tpu.datasets.records import ImageRecordReader
        d = tmp_path / "flat"
        d.mkdir()
        Image.fromarray(np.zeros((4, 4, 3), np.uint8)).save(
            str(d / "a.png"))
        r = ImageRecordReader(4, 4, 3, path=str(d))
        assert r.labels == [""]
        assert next(iter(r))[1] == 0


class TestSequenceMetadata:
    def test_sequence_iterator_collects_and_reloads(self, tmp_path):
        from deeplearning4j_tpu.datasets.records import (
            CSVSequenceRecordReader, SequenceRecordReaderDataSetIterator)
        for i in range(3):
            (tmp_path / f"seq_{i}.csv").write_text(
                "\n".join(f"{t}.0,{t + i}.0,{i % 2}" for t in range(4 + i)))
        rdr = CSVSequenceRecordReader(str(tmp_path / "seq_*.csv"))
        it = SequenceRecordReaderDataSetIterator(
            rdr, 2, num_possible_labels=2, label_index=2,
            collect_meta_data=True)
        batches = list(it)
        meta = batches[0].example_meta_data
        assert len(meta) == 2 and meta[0].uri.endswith("seq_0.csv")
        # reload the original sequence behind the metadata
        seqs = rdr.load_sequence_from_meta_data(meta[1])
        assert len(seqs[0]) == 5  # seq_1 has 5 timesteps
        assert seqs[0][0][:2] == [0.0, 1.0]

    def test_collection_sequence_at(self):
        from deeplearning4j_tpu.datasets.records import (
            CollectionSequenceRecordReader)
        r = CollectionSequenceRecordReader([[[1, 0]], [[2, 1]], [[3, 0]]])
        seq, meta = r.next_sequence_with_meta()
        assert seq == [[1, 0]] and meta.index == 0
        assert r.load_sequence_from_meta_data(meta) == [[[1, 0]]]


def test_image_reader_nested_tree_uses_immediate_parent(tmp_path):
    """ParentPathLabelGenerator semantics: root/a/b/x.png is labeled 'b'
    (the file's IMMEDIATE parent), not the first path component."""
    from PIL import Image
    from deeplearning4j_tpu.datasets.records import ImageRecordReader
    for sub in ("cats/kittens", "cats/adults", "dogs"):
        d = tmp_path / sub
        d.mkdir(parents=True)
        Image.fromarray(np.zeros((4, 4, 3), np.uint8)).save(
            str(d / "img.png"))
    r = ImageRecordReader(4, 4, 3, path=str(tmp_path))
    assert r.labels == ["adults", "dogs", "kittens"]
    labels = {lab for _, lab in r}
    assert labels == {0, 1, 2}
