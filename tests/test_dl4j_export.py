"""Reverse migration: our model -> DL4J-format zip -> re-import equality.

The exporter emits the same dialect the importer parses (the only DL4J
oracle in this image), so every test is an export->import round trip
asserting output equality — including the NHWC->NCHW dense-weight
permutation at cnn->ff boundaries, BN running stats, and LSTM layouts.
"""

import numpy as np
import pytest

from deeplearning4j_tpu.modelimport.dl4j import (
    UnsupportedDl4jConfigurationException,
    restore_multi_layer_network,
)
from deeplearning4j_tpu.modelimport.dl4j_export import export_multi_layer_network
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.network import NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers.conv import ConvolutionLayer
from deeplearning4j_tpu.nn.layers.core import DenseLayer, EmbeddingLayer
from deeplearning4j_tpu.nn.layers.norm import BatchNormalizationLayer
from deeplearning4j_tpu.nn.layers.output import OutputLayer, RnnOutputLayer
from deeplearning4j_tpu.nn.layers.pooling import SubsamplingLayer
from deeplearning4j_tpu.nn.layers.recurrent import GravesLSTMLayer, LSTMLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.updaters import Adam


def round_trip(net, x, tmp_path, train_steps=0, y=None):
    if train_steps:
        for _ in range(train_steps):
            net.fit(x, y)
    path = str(tmp_path / "export.zip")
    export_multi_layer_network(net, path)
    again = restore_multi_layer_network(path)
    np.testing.assert_allclose(np.asarray(again.output(x)),
                               np.asarray(net.output(x)),
                               rtol=2e-5, atol=1e-6)
    return again


class TestDenseExport:
    def test_dense_round_trip(self, tmp_path):
        conf = (NeuralNetConfiguration.builder().seed(3).updater(Adam(1e-3))
                .list()
                .layer(DenseLayer(n_in=4, n_out=6, activation="tanh"))
                .layer(DenseLayer(n_in=6, n_out=5, activation="relu"))
                .layer(OutputLayer(n_in=5, n_out=3))
                .build())
        net = MultiLayerNetwork(conf).init()
        x = np.random.RandomState(0).randn(5, 4).astype(np.float32)
        round_trip(net, x, tmp_path)

    def test_trained_state_survives(self, tmp_path):
        conf = (NeuralNetConfiguration.builder().seed(3).updater("adam")
                .list()
                .layer(DenseLayer(n_in=4, n_out=6, activation="tanh"))
                .layer(OutputLayer(n_in=6, n_out=2))
                .build())
        net = MultiLayerNetwork(conf).init()
        rng = np.random.RandomState(0)
        x = rng.randn(16, 4).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[rng.randint(0, 2, 16)]
        round_trip(net, x, tmp_path, train_steps=5, y=y)


class TestConvExport:
    def test_conv_bn_pool_dense_round_trip(self, tmp_path):
        """The hard case: conv -> BN (running stats) -> pool -> dense over
        a cnn->ff boundary (NHWC->NCHW weight permutation)."""
        conf = (NeuralNetConfiguration.builder().seed(7).updater("adam")
                .list()
                .layer(ConvolutionLayer(n_out=4, kernel_size=(3, 3),
                                        activation="relu"))
                .layer(BatchNormalizationLayer())
                .layer(SubsamplingLayer())
                .layer(DenseLayer(n_out=10, activation="tanh"))
                .layer(OutputLayer(n_out=3))
                .set_input_type(InputType.convolutional(8, 8, 2))
                .build())
        net = MultiLayerNetwork(conf).init()
        rng = np.random.RandomState(1)
        x = rng.randn(6, 8, 8, 2).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.randint(0, 3, 6)]
        # train so BN running stats are non-trivial and must survive
        again = round_trip(net, x, tmp_path, train_steps=4, y=y)
        np.testing.assert_allclose(
            np.asarray(again.states[1]["mean"]),
            np.asarray(net.states[1]["mean"]), rtol=1e-5)

    def test_resumed_training_tracks_through_boundary(self, tmp_path):
        """Fine-tuning after handback == uninterrupted training, through
        the cnn->ff boundary (outputs compared — the imported net stores
        the boundary dense W in NCHW row order by design)."""
        conf = (NeuralNetConfiguration.builder().seed(11).updater("adam")
                .l2(1e-4).list()
                .layer(ConvolutionLayer(n_out=6, kernel_size=(3, 3),
                                        activation="relu"))
                .layer(BatchNormalizationLayer())
                .layer(SubsamplingLayer())
                .layer(DenseLayer(n_out=16, activation="tanh"))
                .layer(OutputLayer(n_out=4))
                .set_input_type(InputType.convolutional(10, 8, 3))
                .build())
        net = MultiLayerNetwork(conf).init()
        rng = np.random.RandomState(0)
        x = rng.randn(16, 10, 8, 3).astype(np.float32)
        y = np.eye(4, dtype=np.float32)[rng.randint(0, 4, 16)]
        for _ in range(6):
            net.fit(x, y)
        path = str(tmp_path / "hb.zip")
        export_multi_layer_network(net, path)
        back = restore_multi_layer_network(path)
        assert back.iteration == net.iteration  # Adam bias correction
        for _ in range(4):
            net.fit(x, y)
            back.fit(x, y)
        np.testing.assert_allclose(np.asarray(back.output(x)),
                                   np.asarray(net.output(x)),
                                   rtol=1e-4, atol=1e-5)

    def test_asymmetric_spatial_dims_permutation(self, tmp_path):
        """H != W makes a wrong NHWC/NCHW permutation impossible to hide."""
        conf = (NeuralNetConfiguration.builder().seed(9).updater("sgd")
                .list()
                .layer(ConvolutionLayer(n_out=3, kernel_size=(2, 2),
                                        activation="tanh"))
                .layer(DenseLayer(n_out=6, activation="relu"))
                .layer(OutputLayer(n_out=2))
                .set_input_type(InputType.convolutional(6, 4, 2))
                .build())
        net = MultiLayerNetwork(conf).init()
        x = np.random.RandomState(2).randn(4, 6, 4, 2).astype(np.float32)
        round_trip(net, x, tmp_path)


class TestRecurrentExport:
    @pytest.mark.parametrize("layer_cls", [LSTMLayer, GravesLSTMLayer])
    def test_lstm_round_trip(self, layer_cls, tmp_path):
        conf = (NeuralNetConfiguration.builder().seed(5).updater("sgd")
                .list()
                .layer(layer_cls(n_in=3, n_out=5))
                .layer(RnnOutputLayer(n_in=5, n_out=2))
                .build())
        net = MultiLayerNetwork(conf).init()
        x = np.random.RandomState(3).randn(4, 6, 3).astype(np.float32)
        round_trip(net, x, tmp_path)

    def test_updater_state_survives_handback(self, tmp_path):
        """Adam m/v moments travel in updaterState.bin: resumed training
        after export->import == uninterrupted training."""
        conf = (NeuralNetConfiguration.builder().seed(3).updater("adam")
                .list()
                .layer(DenseLayer(n_in=4, n_out=6, activation="tanh"))
                .layer(OutputLayer(n_in=6, n_out=2))
                .build())
        net = MultiLayerNetwork(conf).init()
        rng = np.random.RandomState(0)
        x = rng.randn(16, 4).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[rng.randint(0, 2, 16)]
        for _ in range(5):
            net.fit(x, y)
        path = str(tmp_path / "hb.zip")
        export_multi_layer_network(net, path)
        import zipfile
        assert "updaterState.bin" in zipfile.ZipFile(path).namelist()
        resumed = restore_multi_layer_network(path)
        for _ in range(3):
            net.fit(x, y)
            resumed.fit(x, y)
        for a, b in zip(net.params, resumed.params):
            for k in a:
                np.testing.assert_allclose(np.asarray(a[k]), np.asarray(b[k]),
                                           rtol=2e-4, atol=1e-6)

    def test_dense_between_rnns_emits_preprocessors(self, tmp_path):
        """DL4J needs rnnToFeedForward/feedForwardToRnn around a
        time-distributed dense layer; the export records them."""
        import json, zipfile
        conf = (NeuralNetConfiguration.builder().seed(5).updater("sgd")
                .list()
                .layer(LSTMLayer(n_in=3, n_out=5))
                .layer(DenseLayer(n_in=5, n_out=4, activation="tanh"))
                .layer(RnnOutputLayer(n_in=4, n_out=2))
                .build())
        net = MultiLayerNetwork(conf).init()
        path = str(tmp_path / "td.zip")
        export_multi_layer_network(net, path)
        doc = json.loads(zipfile.ZipFile(path).read("configuration.json"))
        pre = doc["inputPreProcessors"]
        assert "rnnToFeedForward" in pre["1"]
        assert "feedForwardToRnn" in pre["2"]
        x = np.random.RandomState(3).randn(4, 6, 3).astype(np.float32)
        again = restore_multi_layer_network(path)
        np.testing.assert_allclose(np.asarray(again.output(x)),
                                   np.asarray(net.output(x)), rtol=2e-5,
                                   atol=1e-6)

    def test_dilation_and_pool_padding_round_trip(self, tmp_path):
        conf = (NeuralNetConfiguration.builder().seed(9).updater("sgd")
                .list()
                .layer(ConvolutionLayer(n_out=3, kernel_size=(3, 3),
                                        dilation=(2, 2), activation="tanh"))
                .layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2),
                                        padding=(1, 1)))
                .layer(DenseLayer(n_out=6, activation="relu"))
                .layer(OutputLayer(n_out=2))
                .set_input_type(InputType.convolutional(10, 10, 2))
                .build())
        net = MultiLayerNetwork(conf).init()
        x = np.random.RandomState(2).randn(3, 10, 10, 2).astype(np.float32)
        again = round_trip(net, x, tmp_path)
        assert again.conf.layers[0].dilation == (2, 2)
        assert again.conf.layers[1].padding == (1, 1)

    def test_regularization_travels(self, tmp_path):
        import json, zipfile
        conf = (NeuralNetConfiguration.builder().seed(1).updater("sgd")
                .l2(1e-3).list()
                .layer(DenseLayer(n_in=3, n_out=4, l1=1e-4))
                .layer(OutputLayer(n_in=4, n_out=2))
                .build())
        net = MultiLayerNetwork(conf).init()
        path = str(tmp_path / "reg.zip")
        export_multi_layer_network(net, path)
        doc = json.loads(zipfile.ZipFile(path).read("configuration.json"))
        d0 = doc["confs"][0]["layer"]["dense"]
        assert d0["l1"] == pytest.approx(1e-4)
        assert d0["l2"] == pytest.approx(1e-3)  # global default applied
        again = restore_multi_layer_network(path)
        assert again.conf.layers[0].l1 == pytest.approx(1e-4)
        assert again.conf.layers[0].l2 == pytest.approx(1e-3)

    def test_embedding_lstm_tbptt_config(self, tmp_path):
        conf = (NeuralNetConfiguration.builder().seed(5).updater("sgd")
                .list()
                .layer(EmbeddingLayer(n_in=20, n_out=8))
                .layer(LSTMLayer(n_in=8, n_out=6))
                .layer(RnnOutputLayer(n_in=6, n_out=4))
                .t_bptt_length(5)
                .build())
        net = MultiLayerNetwork(conf).init()
        x = np.random.RandomState(4).randint(0, 20, (3, 7)).astype(np.float32)
        again = round_trip(net, x, tmp_path)
        assert again.conf.backprop_type == "truncated_bptt"
        assert again.conf.tbptt_fwd_length == 5


class TestExportRejections:
    def test_unsupported_layer_raises(self, tmp_path):
        from deeplearning4j_tpu.nn.layers.vae import VariationalAutoencoderLayer
        conf = (NeuralNetConfiguration.builder().seed(1).updater("sgd")
                .list()
                .layer(VariationalAutoencoderLayer(
                    n_in=4, n_out=2, encoder_layer_sizes=(4,),
                    decoder_layer_sizes=(4,)))
                .layer(OutputLayer(n_in=2, n_out=2))
                .build())
        net = MultiLayerNetwork(conf).init()
        with pytest.raises(UnsupportedDl4jConfigurationException):
            export_multi_layer_network(net, str(tmp_path / "x.zip"))

    def test_dropout_object_raises(self, tmp_path):
        from deeplearning4j_tpu.nn.dropout import AlphaDropout
        conf = (NeuralNetConfiguration.builder().seed(1).updater("sgd")
                .list()
                .layer(DenseLayer(n_in=3, n_out=4, dropout=AlphaDropout(0.9)))
                .layer(OutputLayer(n_in=4, n_out=2))
                .build())
        net = MultiLayerNetwork(conf).init()
        with pytest.raises(UnsupportedDl4jConfigurationException):
            export_multi_layer_network(net, str(tmp_path / "x.zip"))

    def test_explicit_preprocessor_raises(self, tmp_path):
        conf = (NeuralNetConfiguration.builder().seed(1).updater("sgd")
                .list()
                .layer(DenseLayer(n_in=3, n_out=4))
                .layer(OutputLayer(n_in=4, n_out=2))
                .input_pre_processor(0, "standardize")
                .build())
        net = MultiLayerNetwork(conf).init()
        with pytest.raises(UnsupportedDl4jConfigurationException):
            export_multi_layer_network(net, str(tmp_path / "x.zip"))


class TestDistributionWeightInit:
    def test_distribution_init_round_trips_with_payload(self, tmp_path):
        """DISTRIBUTION weightInit must export its dist payload (the
        config is otherwise un-reinitializable by DL4J)."""
        from deeplearning4j_tpu.nn.weights import Distribution
        conf = (NeuralNetConfiguration.builder().seed(7).updater("sgd")
                .list()
                .layer(DenseLayer(n_in=4, n_out=6, activation="tanh",
                                  weight_init="distribution",
                                  distribution=Distribution(
                                      kind="normal", mean=0.5, std=0.25)))
                .layer(OutputLayer(n_in=6, n_out=2))
                .build())
        net = MultiLayerNetwork(conf).init()
        x = np.random.RandomState(0).randn(5, 4).astype(np.float32)
        again = round_trip(net, x, tmp_path)
        lyr = again.conf.layers[0]
        assert lyr.weight_init == "distribution"
        assert lyr.distribution.kind == "normal"
        assert lyr.distribution.mean == 0.5
        assert lyr.distribution.std == 0.25

    def test_distribution_init_without_spec_raises(self, tmp_path):
        """A layer claiming DISTRIBUTION init with no spec is rejected
        loudly rather than exported as an unusable config."""
        from deeplearning4j_tpu.modelimport.dl4j_export import (
            _distribution_entry,
        )
        with pytest.raises(UnsupportedDl4jConfigurationException):
            _distribution_entry(None)


class TestHeterogeneousUpdaterMigration:
    def test_three_distinct_updaters_round_trip(self, tmp_path):
        """UpdaterBlock.java:25 / BaseMultiLayerUpdater.java:38: per-layer
        updater overrides split the state vector into blocks with DIFFERENT
        slot layouts (Adam m+v, RmsProp g2, Nesterovs v). Export must write
        them block-by-block and import must restore them exactly — resumed
        training equals uninterrupted training."""
        from deeplearning4j_tpu.nn.updaters import Nesterovs, RmsProp
        conf = (NeuralNetConfiguration.builder().seed(3).updater(Adam(1e-2))
                .list()
                .layer(DenseLayer(n_in=4, n_out=6, activation="tanh"))
                .layer(DenseLayer(n_in=6, n_out=5, activation="relu",
                                  updater=RmsProp(5e-3)))
                .layer(OutputLayer(n_in=5, n_out=2,
                                   updater=Nesterovs(1e-2, momentum=0.9)))
                .build())
        net = MultiLayerNetwork(conf).init()
        rng = np.random.RandomState(1)
        x = rng.randn(16, 4).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[rng.randint(0, 2, 16)]
        for _ in range(5):
            net.fit(x, y)
        path = str(tmp_path / "hetero.zip")
        export_multi_layer_network(net, path)
        import zipfile
        assert "updaterState.bin" in zipfile.ZipFile(path).namelist()
        resumed = restore_multi_layer_network(path)
        # restored per-layer updater configs survive the dialect
        assert type(resumed._updaters[1]["W"]).__name__ == "RmsProp"
        assert type(resumed._updaters[2]["W"]).__name__ == "Nesterovs"
        for _ in range(3):
            net.fit(x, y)
            resumed.fit(x, y)
        for a, b in zip(net.params, resumed.params):
            for k in a:
                np.testing.assert_allclose(np.asarray(a[k]), np.asarray(b[k]),
                                           rtol=2e-4, atol=1e-6)

    def test_bias_updater_override_round_trip(self, tmp_path):
        """A global bias updater (Sgd on biases, Adam on weights) doubles
        the block count; the wire layout must still round-trip."""
        from deeplearning4j_tpu.nn.updaters import Sgd
        conf = (NeuralNetConfiguration.builder().seed(5).updater(Adam(1e-2))
                .bias_updater(Sgd(0.1))
                .list()
                .layer(DenseLayer(n_in=4, n_out=6, activation="tanh"))
                .layer(OutputLayer(n_in=6, n_out=2))
                .build())
        net = MultiLayerNetwork(conf).init()
        rng = np.random.RandomState(2)
        x = rng.randn(16, 4).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[rng.randint(0, 2, 16)]
        for _ in range(4):
            net.fit(x, y)
        path = str(tmp_path / "biasupd.zip")
        export_multi_layer_network(net, path)
        resumed = restore_multi_layer_network(path)
        for _ in range(3):
            net.fit(x, y)
            resumed.fit(x, y)
        for a, b in zip(net.params, resumed.params):
            for k in a:
                np.testing.assert_allclose(np.asarray(a[k]), np.asarray(b[k]),
                                           rtol=2e-4, atol=1e-6)


class TestComputationGraphExport:
    """Reverse migration for graphs (ModelSerializer.writeModel, graph
    case): export -> restore_computation_graph -> output equality AND
    resumed-training equality; branchy DAGs exercise the shared
    topologicalSortOrder() parameter layout on both sides."""

    def _branchy_graph(self):
        from deeplearning4j_tpu.nn.conf.inputs import InputType
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        from deeplearning4j_tpu.nn.vertices import (ElementWiseVertex,
                                                    MergeVertex)
        g = (NeuralNetConfiguration.builder().seed(7).updater(Adam(1e-3))
             .graph_builder().add_inputs("in")
             .set_input_types(InputType.feed_forward(6)))
        g.add_layer("a", DenseLayer(n_out=8, activation="tanh"), "in")
        g.add_layer("b", DenseLayer(n_out=8, activation="relu"), "in")
        g.add_vertex("sum", ElementWiseVertex(op="add"), "a", "b")
        g.add_vertex("cat", MergeVertex(), "sum", "a")
        g.add_layer("head", DenseLayer(n_out=5, activation="tanh"), "cat")
        g.add_layer("out", OutputLayer(n_out=3), "head")
        net = ComputationGraph(g.set_outputs("out").build())
        return net.init()

    def test_branchy_graph_round_trip(self, tmp_path):
        from deeplearning4j_tpu.modelimport.dl4j import (
            restore_computation_graph)
        from deeplearning4j_tpu.modelimport.dl4j_export import (
            export_computation_graph)
        net = self._branchy_graph()
        rng = np.random.RandomState(0)
        x = rng.randn(12, 6).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.randint(0, 3, 12)]
        for _ in range(4):
            net.fit(x, y)
        path = str(tmp_path / "graph.zip")
        export_computation_graph(net, path)
        import zipfile
        names = set(zipfile.ZipFile(path).namelist())
        assert {"configuration.json", "coefficients.bin",
                "updaterState.bin"} <= names
        again = restore_computation_graph(path)
        out_a = np.asarray(net.output_single(x))
        out_b = np.asarray(again.output_single(x))
        np.testing.assert_allclose(out_b, out_a, rtol=2e-5, atol=1e-6)
        # resumed training stays identical (updater state crossed the wire)
        for _ in range(3):
            net.fit(x, y)
            again.fit(x, y)
        np.testing.assert_allclose(np.asarray(again.output_single(x)),
                                   np.asarray(net.output_single(x)),
                                   rtol=2e-4, atol=1e-5)

    def test_conv_globalpool_graph_round_trip(self, tmp_path):
        """Conv graph WITHOUT a flatten boundary (GlobalPooling head) —
        the supported conv spelling."""
        from deeplearning4j_tpu.modelimport.dl4j import (
            restore_computation_graph)
        from deeplearning4j_tpu.modelimport.dl4j_export import (
            export_computation_graph)
        from deeplearning4j_tpu.nn.conf.inputs import InputType
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        from deeplearning4j_tpu.nn.layers import GlobalPoolingLayer
        g = (NeuralNetConfiguration.builder().seed(5).updater(Adam(1e-3))
             .graph_builder().add_inputs("img")
             .set_input_types(InputType.convolutional(8, 8, 1)))
        g.add_layer("conv", ConvolutionLayer(n_out=4, kernel_size=(3, 3),
                                             convolution_mode="same",
                                             activation="relu"), "img")
        g.add_layer("bn", BatchNormalizationLayer(), "conv")
        g.add_layer("pool", GlobalPoolingLayer(pooling_type="avg"), "bn")
        g.add_layer("out", OutputLayer(n_in=4, n_out=2), "pool")
        net = ComputationGraph(g.set_outputs("out").build()).init()
        rng = np.random.RandomState(1)
        x = rng.rand(6, 8, 8, 1).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[rng.randint(0, 2, 6)]
        for _ in range(3):
            net.fit(x, y)
        path = str(tmp_path / "convgraph.zip")
        export_computation_graph(net, path)
        again = restore_computation_graph(path)
        np.testing.assert_allclose(np.asarray(again.output_single(x)),
                                   np.asarray(net.output_single(x)),
                                   rtol=2e-5, atol=1e-6)

    def test_conv_dense_boundary_round_trips(self, tmp_path):
        """Conv→dense flatten in a GRAPH: the exporter emits the
        cnnToFeedForward preprocessor INSIDE the LayerVertex
        (LayerVertex.java:45) with the NHWC→NCHW dense-weight row
        permutation; the importer installs the matching activation
        transpose — outputs and resumed training stay identical."""
        from deeplearning4j_tpu.modelimport.dl4j import (
            restore_computation_graph)
        from deeplearning4j_tpu.modelimport.dl4j_export import (
            export_computation_graph)
        from deeplearning4j_tpu.nn.conf.inputs import InputType
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        g = (NeuralNetConfiguration.builder().seed(5).updater(Adam(1e-3))
             .graph_builder().add_inputs("img")
             .set_input_types(InputType.convolutional(8, 8, 1)))
        g.add_layer("conv", ConvolutionLayer(n_out=4, kernel_size=(3, 3),
                                             convolution_mode="same",
                                             activation="relu"), "img")
        g.add_layer("dense", DenseLayer(n_out=6, activation="tanh"), "conv")
        g.add_layer("out", OutputLayer(n_out=2), "dense")
        net = ComputationGraph(g.set_outputs("out").build()).init()
        rng = np.random.RandomState(2)
        x = rng.rand(5, 8, 8, 1).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[rng.randint(0, 2, 5)]
        for _ in range(3):
            net.fit(x, y)
        path = str(tmp_path / "convdense.zip")
        export_computation_graph(net, path)
        import json as _json
        import zipfile
        doc = _json.loads(zipfile.ZipFile(path).read("configuration.json"))
        assert "preProcessor" in doc["vertices"]["dense"]["LayerVertex"]
        again = restore_computation_graph(path)
        np.testing.assert_allclose(np.asarray(again.output_single(x)),
                                   np.asarray(net.output_single(x)),
                                   rtol=2e-5, atol=1e-6)
        for _ in range(3):
            net.fit(x, y)
            again.fit(x, y)
        np.testing.assert_allclose(np.asarray(again.output_single(x)),
                                   np.asarray(net.output_single(x)),
                                   rtol=2e-4, atol=1e-5)

    def test_unsupported_boundary_rejected_loudly(self, tmp_path):
        """cnn_seq into a recurrent layer has no DL4J graph spelling."""
        from deeplearning4j_tpu.modelimport.dl4j_export import (
            export_computation_graph)
        from deeplearning4j_tpu.nn.conf.inputs import InputType
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        g = (NeuralNetConfiguration.builder().seed(5).updater("sgd")
             .graph_builder().add_inputs("seq")
             .set_input_types(InputType.recurrent_convolutional(6, 6, 1, 4)))
        g.add_layer("rnn", LSTMLayer(n_out=5), "seq")
        g.add_layer("out", RnnOutputLayer(n_in=5, n_out=2), "rnn")
        net = ComputationGraph(g.set_outputs("out").build()).init()
        with pytest.raises(UnsupportedDl4jConfigurationException,
                           match="no DL4J round-trip spelling"):
            export_computation_graph(net, str(tmp_path / "x.zip"))

    def test_restored_graph_re_exports(self, tmp_path):
        """restore → fine-tune → re-save (the natural handback loop): the
        restored conf carries the original preProcessor entries, so the
        second export emits them verbatim WITHOUT re-permuting weights."""
        from deeplearning4j_tpu.modelimport.dl4j import (
            restore_computation_graph)
        from deeplearning4j_tpu.modelimport.dl4j_export import (
            export_computation_graph)
        from deeplearning4j_tpu.nn.conf.inputs import InputType
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        g = (NeuralNetConfiguration.builder().seed(5).updater(Adam(1e-3))
             .graph_builder().add_inputs("img")
             .set_input_types(InputType.convolutional(8, 8, 1)))
        g.add_layer("conv", ConvolutionLayer(n_out=3, kernel_size=(3, 3),
                                             convolution_mode="same",
                                             activation="relu"), "img")
        g.add_layer("dense", DenseLayer(n_out=5, activation="tanh"), "conv")
        g.add_layer("out", OutputLayer(n_out=2), "dense")
        net = ComputationGraph(g.set_outputs("out").build()).init()
        rng = np.random.RandomState(3)
        x = rng.rand(4, 8, 8, 1).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[rng.randint(0, 2, 4)]
        net.fit(x, y)
        p1 = str(tmp_path / "one.zip")
        export_computation_graph(net, p1)
        mid = restore_computation_graph(p1)
        mid.fit(x, y)
        p2 = str(tmp_path / "two.zip")
        export_computation_graph(mid, p2)
        final = restore_computation_graph(p2)
        np.testing.assert_allclose(np.asarray(final.output_single(x)),
                                   np.asarray(mid.output_single(x)),
                                   rtol=2e-5, atol=1e-6)

    def test_cnn_flat_graph_boundary_rejected(self, tmp_path):
        """convolutional_flat inputs have no graph-dialect spelling (the
        imported transpose would crash on 2-D activations) — rejected."""
        from deeplearning4j_tpu.modelimport.dl4j_export import (
            export_computation_graph)
        from deeplearning4j_tpu.nn.conf.inputs import InputType
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        g = (NeuralNetConfiguration.builder().seed(5).updater("sgd")
             .graph_builder().add_inputs("img")
             .set_input_types(InputType.convolutional_flat(4, 4, 1)))
        g.add_layer("dense", DenseLayer(n_out=5), "img")
        g.add_layer("out", OutputLayer(n_out=2), "dense")
        net = ComputationGraph(g.set_outputs("out").build()).init()
        with pytest.raises(UnsupportedDl4jConfigurationException,
                           match="no DL4J round-trip spelling"):
            export_computation_graph(net, str(tmp_path / "x.zip"))


def test_plain_dropout_object_exports_as_scalar(tmp_path):
    """Dropout(0.9) the OBJECT is the same thing as dropout=0.9 — it
    exports as DL4J's scalar dropOut (scheduled/exotic IDropout still
    rejects loudly)."""
    from deeplearning4j_tpu.nn.dropout import Dropout
    conf = (NeuralNetConfiguration.builder().seed(1).updater("sgd").list()
            .layer(DenseLayer(n_in=3, n_out=4, dropout=Dropout(0.9)))
            .layer(OutputLayer(n_in=4, n_out=2))
            .build())
    net = MultiLayerNetwork(conf).init()
    path = str(tmp_path / "d.zip")
    export_multi_layer_network(net, path)
    import json as _json
    import zipfile
    doc = _json.loads(zipfile.ZipFile(path).read("configuration.json"))
    dense_cfg = doc["confs"][0]["layer"]["dense"]
    assert dense_cfg["dropOut"] == 0.9
    again = restore_multi_layer_network(path)
    x = np.random.RandomState(0).randn(4, 3).astype(np.float32)
    np.testing.assert_allclose(np.asarray(again.output(x)),
                               np.asarray(net.output(x)), rtol=1e-6)
