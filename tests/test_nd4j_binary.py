"""ND4J binary format + full DL4J checkpoint migration.

Reference: ``util/ModelSerializer.java:182`` (restoreMultiLayerNetwork
restores config AND the flattened coefficients.bin + updaterState.bin).
Fixtures in tests/fixtures/ were written with an INDEPENDENT hand-coded
flattening (see make_nd4j_checkpoint_fixtures.py) so the reader is inverted
against the documented DL4J layout, not round-tripped through itself.
"""

import io
import os

import numpy as np
import pytest

from deeplearning4j_tpu.modelimport.dl4j import (
    InvalidDl4jConfigurationException,
    UnsupportedDl4jConfigurationException,
    apply_coefficients,
    restore_multi_layer_network,
)
from deeplearning4j_tpu.modelimport.nd4j_binary import (
    nd4j_array_to_bytes,
    read_nd4j_array,
    read_nd4j_array_from_bytes,
    write_nd4j_array,
)

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures")


class TestBinaryCodec:
    def test_round_trip_orders_and_dtypes(self):
        rng = np.random.default_rng(0)
        for shape in ((7,), (3, 4), (2, 3, 4), (1, 10)):
            for order in ("c", "f"):
                for dt in (np.float32, np.float64):
                    a = rng.normal(size=shape).astype(dt)
                    back = read_nd4j_array_from_bytes(
                        nd4j_array_to_bytes(a, order))
                    np.testing.assert_array_equal(back, a)
                    assert back.dtype == dt

    def test_wire_layout_is_java_dataoutputstream(self):
        # lock the byte-level contract: UTF mode, i32 len, UTF dtype, BE data
        b = nd4j_array_to_bytes(np.asarray([[1.0, 2.0]], np.float32), "c")
        f = io.BytesIO(b)
        assert f.read(2) == b"\x00\x04" and f.read(4) == b"HEAP"
        assert f.read(4) == b"\x00\x00\x00\x08"   # shapeInfo length 2*2+4
        assert f.read(2) == b"\x00\x03" and f.read(3) == b"INT"
        shape_info = np.frombuffer(f.read(8 * 4), ">i4")
        assert list(shape_info) == [2, 1, 2, 2, 1, 0, 1, ord("c")]
        assert f.read(2) == b"\x00\x04" and f.read(4) == b"HEAP"
        assert f.read(4) == b"\x00\x00\x00\x02"
        assert f.read(2) == b"\x00\x05" and f.read(5) == b"FLOAT"
        np.testing.assert_array_equal(np.frombuffer(f.read(8), ">f4"),
                                      [1.0, 2.0])
        assert f.read() == b""

    def test_long_shape_buffer_accepted(self):
        # 1.0-era files store shapeInfo as LONG
        buf = io.BytesIO()
        a = np.asarray([[1.5, -2.0], [0.0, 3.0]], np.float32)
        from deeplearning4j_tpu.modelimport import nd4j_binary as nb
        shape_info = np.array([2, 2, 2, 2, 1, 0, 1, ord("c")], np.int64)
        nb._write_buffer(buf, shape_info, "LONG")
        nb._write_buffer(buf, a.reshape(-1), "FLOAT")
        np.testing.assert_array_equal(read_nd4j_array_from_bytes(buf.getvalue()), a)

    def test_truncation_and_garbage_rejected(self):
        good = nd4j_array_to_bytes(np.ones((2, 2), np.float32))
        with pytest.raises(ValueError):
            read_nd4j_array_from_bytes(good[:-3])
        with pytest.raises(ValueError):
            read_nd4j_array_from_bytes(b"not an nd4j stream at all")


class TestConvNetCheckpoint:
    ZIP = os.path.join(FIXTURES, "dl4j_checkpoint_convnet.zip")
    EXP = os.path.join(FIXTURES, "dl4j_checkpoint_convnet_expected.npz")

    def test_params_land_in_the_right_places(self):
        exp = np.load(self.EXP)
        net = restore_multi_layer_network(self.ZIP)
        np.testing.assert_allclose(np.asarray(net.params[0]["W"]),
                                   exp["conv_W"], rtol=1e-6)
        np.testing.assert_allclose(np.asarray(net.params[0]["b"]),
                                   exp["conv_b"], rtol=1e-6)
        np.testing.assert_allclose(np.asarray(net.params[1]["gamma"]),
                                   exp["bn_gamma"], rtol=1e-6)
        np.testing.assert_allclose(np.asarray(net.states[1]["mean"]),
                                   exp["bn_mean"], rtol=1e-6)
        np.testing.assert_allclose(np.asarray(net.states[1]["var"]),
                                   exp["bn_var"], rtol=1e-6)
        np.testing.assert_allclose(np.asarray(net.params[2]["W"]),
                                   exp["d_W"], rtol=1e-6)
        np.testing.assert_allclose(np.asarray(net.params[3]["W"]),
                                   exp["o_W"], rtol=1e-6)
        np.testing.assert_allclose(np.asarray(net.params[3]["b"]),
                                   exp["o_b"], rtol=1e-6)

    def test_output_matches_recorded_activations(self):
        exp = np.load(self.EXP)
        net = restore_multi_layer_network(self.ZIP)
        out = np.asarray(net.output(exp["x"]))
        np.testing.assert_allclose(out, exp["out"], rtol=1e-5, atol=1e-6)

    def test_updater_state_restored(self):
        exp = np.load(self.EXP)
        net = restore_multi_layer_network(self.ZIP)
        # Adam block layout [M(all), V(all)]: check a couple of params
        n_conv_w = 3 * 3 * 1 * 4
        m0 = np.asarray(net.updater_states[0]["W"]["m"])
        # conv W slice is first: M values 0..n-1 (scaled 1e-3), OIHW→HWIO
        want = (np.arange(n_conv_w, dtype=np.float32) * 1e-3).reshape(
            (4, 1, 3, 3)).transpose(2, 3, 1, 0)
        np.testing.assert_allclose(m0, want, rtol=1e-6)
        v3 = np.asarray(net.updater_states[3]["b"]["v"])
        assert v3.shape == (3,)
        np.testing.assert_allclose(v3, exp["v"][-3:], rtol=1e-6)

    def test_fine_tuning_continues_from_checkpoint(self):
        # the restored net must train (the "serve or fine-tune" bar from
        # the round-1 verdict)
        from deeplearning4j_tpu.datasets.dataset import DataSet
        exp = np.load(self.EXP)
        net = restore_multi_layer_network(self.ZIP)
        rng = np.random.default_rng(0)
        x = rng.normal(size=(16, 8, 8, 1)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 16)]
        net.fit(DataSet(x, y))
        assert np.isfinite(float(net.score_))

    def test_length_mismatch_rejected(self):
        net = restore_multi_layer_network(self.ZIP)
        with pytest.raises(InvalidDl4jConfigurationException,
                           match="too short|length mismatch"):
            apply_coefficients(net, np.zeros(10, np.float32))


class TestLstmCheckpoint:
    ZIP = os.path.join(FIXTURES, "dl4j_checkpoint_lstm.zip")
    EXP = os.path.join(FIXTURES, "dl4j_checkpoint_lstm_expected.npz")

    def test_lstm_params_including_peepholes(self):
        exp = np.load(self.EXP)
        net = restore_multi_layer_network(self.ZIP)
        np.testing.assert_allclose(np.asarray(net.params[0]["W"]), exp["W"],
                                   rtol=1e-6)
        np.testing.assert_allclose(np.asarray(net.params[0]["RW"]), exp["RW"],
                                   rtol=1e-6)  # [H, 4H+3] peephole columns
        np.testing.assert_allclose(np.asarray(net.params[0]["b"]), exp["b"],
                                   rtol=1e-6)

    def test_output_matches_recorded(self):
        exp = np.load(self.EXP)
        net = restore_multi_layer_network(self.ZIP)
        np.testing.assert_allclose(np.asarray(net.output(exp["x"])),
                                   exp["out"], rtol=1e-5, atol=1e-6)

    def test_nesterovs_single_slot_state(self):
        exp = np.load(self.EXP)
        net = restore_multi_layer_network(self.ZIP)
        w_size = 5 * 24
        v = np.asarray(net.updater_states[0]["W"]["v"])
        want = exp["upd"][:w_size].reshape((5, 24), order="F")
        np.testing.assert_allclose(v, want, rtol=1e-6)


class TestUnsupportedPaths:
    def test_graph_zip_rejected_clearly(self, tmp_path):
        import json
        import zipfile
        p = str(tmp_path / "graph.zip")
        with zipfile.ZipFile(p, "w") as z:
            z.writestr("configuration.json", json.dumps(
                {"vertices": {}, "networkInputs": [], "networkOutputs": []}))
        with pytest.raises(UnsupportedDl4jConfigurationException,
                           match="ComputationGraph"):
            restore_multi_layer_network(p)


class TestReviewDrivenEdgeCases:
    def test_lock_gamma_beta_shifts_layout_correctly(self, tmp_path):
        import json
        import zipfile
        from deeplearning4j_tpu.modelimport.nd4j_binary import nd4j_array_to_bytes
        conf = {"confs": [
            {"layer": {"dense": {"nin": 3, "nout": 2, "activationFn":
                {"@class": "org.nd4j.linalg.activations.impl.ActivationTanH"}}}},
            {"layer": {"batchNormalization": {"nin": 2, "lockGammaBeta": True}}},
            {"layer": {"output": {"nin": 2, "nout": 2, "activationFn":
                {"@class": "org.nd4j.linalg.activations.impl.ActivationSoftmax"},
                "lossFn": {"@class": "org.nd4j.linalg.lossfunctions.impl.LossMCXENT"}}}},
        ]}
        W = np.arange(6, dtype=np.float32).reshape(3, 2)
        b = np.array([0.5, -0.5], np.float32)
        mean = np.array([1.0, 2.0], np.float32)
        var = np.array([3.0, 4.0], np.float32)
        oW = np.arange(4, dtype=np.float32).reshape(2, 2) + 10
        ob = np.zeros(2, np.float32)
        flat = np.concatenate([W.flatten("F"), b, mean, var,
                               oW.flatten("F"), ob])
        p = str(tmp_path / "locked_bn.zip")
        with zipfile.ZipFile(p, "w") as z:
            z.writestr("configuration.json", json.dumps(conf))
            z.writestr("coefficients.bin",
                       nd4j_array_to_bytes(flat.reshape(1, -1)))
        net = restore_multi_layer_network(p)
        assert "gamma" not in net.params[1]  # locked: no gamma/beta params
        np.testing.assert_allclose(np.asarray(net.states[1]["mean"]), mean)
        np.testing.assert_allclose(np.asarray(net.params[2]["W"]), oW)

    def test_at_class_preprocessor_and_unknown_warns(self):
        import warnings
        from deeplearning4j_tpu.modelimport.dl4j import _convert_dl4j_preprocessor
        fn = _convert_dl4j_preprocessor(
            {"@class": "org.deeplearning4j.nn.conf.preprocessor."
                       "CnnToFeedForwardPreProcessor",
             "inputHeight": 4, "inputWidth": 4, "numChannels": 2})
        x = np.arange(2 * 4 * 4 * 2, dtype=np.float32).reshape(2, 4, 4, 2)
        np.testing.assert_array_equal(
            fn(x), x.transpose(0, 3, 1, 2).reshape(2, -1))
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            out = _convert_dl4j_preprocessor({"composableInputPreProcessor": {}})
        assert out is None and any("unsupported" in str(x.message) for x in w)

    def test_cnn_to_rnn_preprocessor(self):
        from deeplearning4j_tpu.modelimport.dl4j import _convert_dl4j_preprocessor
        fn = _convert_dl4j_preprocessor({"cnnToRnn": {}})
        x = np.arange(2 * 3 * 2 * 2 * 4, dtype=np.float32).reshape(2, 3, 2, 2, 4)
        got = fn(x)
        assert got.shape == (2, 3, 16)
        # NCHW-order per-step flatten
        np.testing.assert_array_equal(
            got, x.transpose(0, 1, 4, 2, 3).reshape(2, 3, -1))

    def test_restored_bn_stats_stay_f32_under_bf16(self, tmp_path):
        # BN running stats are pinned to f32 (nn/layers/norm.py); restoring
        # into a bf16-dtype net must not downcast them
        import json
        import zipfile
        import jax.numpy as jnp
        from deeplearning4j_tpu.modelimport.dl4j import apply_coefficients
        from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
        from deeplearning4j_tpu.nn.layers import (BatchNormalizationLayer,
                                                  DenseLayer, OutputLayer)
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        conf = (NeuralNetConfiguration.builder().seed(0).dtype("bfloat16")
                .list()
                .layer(DenseLayer(n_out=4, activation="tanh"))
                .layer(BatchNormalizationLayer())
                .layer(OutputLayer(n_out=2))
                .set_input_type(InputType.feed_forward(3)).build())
        net = MultiLayerNetwork(conf).init()
        f32_expected = net.states[1]["mean"].dtype == jnp.float32
        n = sum(int(np.prod(s)) for l in conf.layers
                for s in l.param_shapes().values()) + 2 * 4  # + BN stats
        apply_coefficients(net, np.arange(n, dtype=np.float32))
        if f32_expected:
            assert net.states[1]["mean"].dtype == jnp.float32
        assert net.params[0]["W"].dtype == jnp.bfloat16


class TestGraphCheckpoint:
    """restoreComputationGraph parity (ModelSerializer.java:389): graph
    config + coefficients + updater state, layers in topological order."""

    ZIP = os.path.join(FIXTURES, "dl4j_checkpoint_graph.zip")
    EXP = os.path.join(FIXTURES, "dl4j_checkpoint_graph_expected.npz")

    def test_params_follow_topological_order(self):
        from deeplearning4j_tpu.modelimport.dl4j import restore_computation_graph
        exp = np.load(self.EXP)
        net = restore_computation_graph(self.ZIP)
        np.testing.assert_allclose(np.asarray(net.params["a"]["W"]),
                                   exp["aW"], rtol=1e-6)
        np.testing.assert_allclose(np.asarray(net.params["b"]["W"]),
                                   exp["bW"], rtol=1e-6)
        np.testing.assert_allclose(np.asarray(net.params["out"]["b"]),
                                   exp["ob"], rtol=1e-6)

    def test_output_matches_recorded(self):
        from deeplearning4j_tpu.modelimport.dl4j import restore_computation_graph
        exp = np.load(self.EXP)
        net = restore_computation_graph(self.ZIP)
        np.testing.assert_allclose(np.asarray(net.output(exp["x"])),
                                   exp["out"], rtol=1e-5, atol=1e-6)

    def test_updater_state_and_fine_tune(self):
        from deeplearning4j_tpu.modelimport.dl4j import restore_computation_graph
        exp = np.load(self.EXP)
        net = restore_computation_graph(self.ZIP)
        v = np.asarray(net.updater_states["a"]["W"]["v"])
        np.testing.assert_allclose(v, exp["upd"][:24].reshape((4, 6), order="F"),
                                   rtol=1e-6)
        rng = np.random.default_rng(0)
        x = rng.normal(size=(8, 4)).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 8)]
        net.fit(x, y)
        assert np.isfinite(float(net.score_))

    def test_mln_zip_rejected(self, tmp_path):
        import json
        import zipfile
        from deeplearning4j_tpu.modelimport.dl4j import restore_computation_graph
        p = str(tmp_path / "mln.zip")
        with zipfile.ZipFile(p, "w") as z:
            z.writestr("configuration.json", json.dumps({"confs": []}))
        with pytest.raises(UnsupportedDl4jConfigurationException,
                           match="MultiLayerNetwork"):
            restore_computation_graph(p)


class TestGravesBidirectionalIngestion:
    def test_bidirectional_param_layout(self, tmp_path):
        import json
        import zipfile
        from deeplearning4j_tpu.modelimport.nd4j_binary import nd4j_array_to_bytes
        from deeplearning4j_tpu.modelimport.dl4j import restore_multi_layer_network
        rng = np.random.default_rng(3)
        h, nin = 3, 2
        conf = {"confs": [
            {"layer": {"gravesBidirectionalLSTM": {
                "activationFn": "tanh", "nin": nin, "nout": h}}},
            {"layer": {"rnnoutput": {"activationFn": "softmax",
                                     "lossFunction": "MCXENT",
                                     "nin": h, "nout": 2}}},
        ]}
        fW = rng.normal(0, 0.2, (nin, 4 * h)).astype(np.float32)
        fRW = rng.normal(0, 0.2, (h, 4 * h + 3)).astype(np.float32)
        fb = rng.normal(0, 0.1, (4 * h,)).astype(np.float32)
        bW = rng.normal(0, 0.2, (nin, 4 * h)).astype(np.float32)
        bRW = rng.normal(0, 0.2, (h, 4 * h + 3)).astype(np.float32)
        bb = rng.normal(0, 0.1, (4 * h,)).astype(np.float32)
        oW = rng.normal(0, 0.2, (h, 2)).astype(np.float32)
        ob = np.zeros(2, np.float32)
        flat = np.concatenate([  # WF, RWF, bF, WB, RWB, bB (initializer order)
            fW.flatten("F"), fRW.flatten("F"), fb,
            bW.flatten("F"), bRW.flatten("F"), bb,
            oW.flatten("F"), ob])
        p = str(tmp_path / "bi.zip")
        with zipfile.ZipFile(p, "w") as z:
            z.writestr("configuration.json", json.dumps(conf))
            z.writestr("coefficients.bin",
                       nd4j_array_to_bytes(flat.reshape(1, -1)))
        net = restore_multi_layer_network(p)
        np.testing.assert_allclose(np.asarray(net.params[0]["f_W"]), fW,
                                   rtol=1e-6)
        np.testing.assert_allclose(np.asarray(net.params[0]["b_RW"]), bRW,
                                   rtol=1e-6)
        out = net.output(np.zeros((1, 4, nin), np.float32))
        assert np.asarray(out).shape == (1, 4, 2)

    def test_ordering_warning_only_for_unforced_branches(self, tmp_path):
        import json
        import warnings
        import zipfile
        from deeplearning4j_tpu.modelimport.dl4j import restore_computation_graph
        from deeplearning4j_tpu.modelimport.nd4j_binary import nd4j_array_to_bytes
        dense = lambda nin, nout, name: {"dense": {
            "layerName": name, "nin": nin, "nout": nout,
            "activationFn": "tanh"}}
        # LINEAR chain (forced order): no warning even though branchless
        lin = {"networkInputs": ["in"], "networkOutputs": ["out"],
               "vertices": {
                   "h": {"LayerVertex": {"layerConf": {"layer": dense(3, 4, "h")}}},
                   "out": {"LayerVertex": {"layerConf": {"layer": {"output": {
                       "nin": 4, "nout": 2, "activationFn": "softmax",
                       "lossFunction": "MCXENT"}}}}}},
               "vertexInputs": {"h": ["in"], "out": ["h"]}}
        flat = np.zeros(3 * 4 + 4 + 4 * 2 + 2, np.float32)
        p1 = str(tmp_path / "lin.zip")
        with zipfile.ZipFile(p1, "w") as z:
            z.writestr("configuration.json", json.dumps(lin))
            z.writestr("coefficients.bin", nd4j_array_to_bytes(flat.reshape(1, -1)))
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            restore_computation_graph(p1)
        assert not any("bucket-order" in str(x.message) for x in w)
        # parallel branches no longer warn: the importer replicates DL4J's
        # topologicalSortOrder exactly (small graphs have no hash ambiguity)
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            restore_computation_graph(
                os.path.join(FIXTURES, "dl4j_checkpoint_graph.zip"))
        assert not any("bucket-order" in str(x.message) for x in w)


class TestUpdaterBlockBoundaries:
    """apply_updater_state must split UpdaterBlock boundaries on FULL
    config equality (UpdaterUtils.updaterConfigurationsEquals /
    BaseMultiLayerUpdater.java:92): per-layer learning rates and bias
    updaters change the state layout from [m(all), v(all)] to per-block
    [m(block), v(block)] segments — mapping must follow the blocks."""

    def _net(self, lr0=None, bias_updater=None):
        from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        from deeplearning4j_tpu.nn.updaters import Adam
        b = NeuralNetConfiguration.builder().seed(0).updater(Adam(0.01))
        if bias_updater is not None:
            b = b.bias_updater(bias_updater)
        conf = (b.list()
                .layer(DenseLayer(n_in=3, n_out=4, activation="relu",
                                  updater=None if lr0 is None else Adam(lr0)))
                .layer(OutputLayer(n_in=4, n_out=2))
                .build())
        return MultiLayerNetwork(conf).init()

    def _flat(self, net):
        n = sum(int(np.prod(v.shape)) for p in net.params for v in p.values())
        return np.arange(2 * n, dtype=np.float32)  # Adam: m + v per block

    def test_uniform_config_single_block(self):
        from deeplearning4j_tpu.modelimport.dl4j import apply_updater_state
        net = self._net()
        assert apply_updater_state(net, self._flat(net)) is True
        # one block over all 26 params: m = flat[0:26], v = flat[26:52]
        np.testing.assert_allclose(
            np.asarray(net.updater_states[0]["b"]["m"]),
            np.arange(12, 16, dtype=np.float32))
        np.testing.assert_allclose(
            np.asarray(net.updater_states[1]["b"]["v"]),
            np.arange(50, 52, dtype=np.float32))

    def test_per_layer_lr_splits_blocks(self):
        from deeplearning4j_tpu.modelimport.dl4j import apply_updater_state
        net = self._net(lr0=0.02)
        assert apply_updater_state(net, self._flat(net)) is True
        # blocks: [L0 W+b] (16 params) then [L1 W+b] (10 params)
        # block0: m=flat[0:16] (b=12..16), v=flat[16:32]
        # block1: m=flat[32:42] (b=40..42), v=flat[42:52] (b=50..52)
        np.testing.assert_allclose(
            np.asarray(net.updater_states[0]["b"]["m"]),
            np.arange(12, 16, dtype=np.float32))
        np.testing.assert_allclose(
            np.asarray(net.updater_states[0]["b"]["v"]),
            np.arange(28, 32, dtype=np.float32))
        np.testing.assert_allclose(
            np.asarray(net.updater_states[1]["b"]["m"]),
            np.arange(40, 42, dtype=np.float32))
        np.testing.assert_allclose(
            np.asarray(net.updater_states[1]["b"]["v"]),
            np.arange(50, 52, dtype=np.float32))

    def test_bias_updater_splits_every_param(self):
        from deeplearning4j_tpu.modelimport.dl4j import apply_updater_state
        from deeplearning4j_tpu.nn.updaters import Adam
        net = self._net(bias_updater=Adam(0.005))
        assert apply_updater_state(net, self._flat(net)) is True
        # blocks: [L0 W](24 state), [L0 b](8), [L1 W](16), [L1 b](4)
        np.testing.assert_allclose(
            np.asarray(net.updater_states[0]["b"]["m"]),
            np.arange(24, 28, dtype=np.float32))
        np.testing.assert_allclose(
            np.asarray(net.updater_states[0]["b"]["v"]),
            np.arange(28, 32, dtype=np.float32))
        np.testing.assert_allclose(
            np.asarray(net.updater_states[1]["b"]["m"]),
            np.arange(48, 50, dtype=np.float32))
        np.testing.assert_allclose(
            np.asarray(net.updater_states[1]["b"]["v"]),
            np.arange(50, 52, dtype=np.float32))


class TestBranchyGraphMigration:
    """Adversarial parallel-branch fixture: insertion order (z, m, a)
    disagrees with lexicographic name order, so only an exact
    ``topologicalSortOrder()`` emulation maps the coefficients correctly.
    The expected output was computed by a manual numpy forward pass,
    independent of the importer (tests/fixtures/make_nd4j_checkpoint_fixtures
    .branchy_graph_fixture)."""

    ZIP = os.path.join(FIXTURES, "dl4j_checkpoint_branchy_graph.zip")
    EXP = os.path.join(FIXTURES, "dl4j_checkpoint_branchy_graph_expected.npz")

    @staticmethod
    def _restore(path):
        from deeplearning4j_tpu.modelimport.dl4j import restore_computation_graph
        return restore_computation_graph(path)

    def test_branch_params_land_by_insertion_order(self):
        exp = np.load(self.EXP)
        net = self._restore(self.ZIP)
        np.testing.assert_allclose(np.asarray(net.params["z_branch"]["W"]),
                                   exp["zW"], rtol=1e-6)
        np.testing.assert_allclose(np.asarray(net.params["m_branch"]["W"]),
                                   exp["mW"], rtol=1e-6)
        np.testing.assert_allclose(np.asarray(net.params["a_branch"]["W"]),
                                   exp["aW"], rtol=1e-6)
        np.testing.assert_allclose(np.asarray(net.params["out"]["b"]),
                                   exp["ob"], rtol=1e-6)

    def test_restored_output_matches_manual_forward(self):
        exp = np.load(self.EXP)
        net = self._restore(self.ZIP)
        out = np.asarray(net.output(exp["x"]))
        np.testing.assert_allclose(out, exp["out"], rtol=1e-4, atol=1e-5)

    def test_no_ordering_warning(self):
        import warnings
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            self._restore(self.ZIP)
        assert not any("bucket-order" in str(x.message) for x in w)

    def test_updater_state_follows_same_order(self):
        exp = np.load(self.EXP)
        net = self._restore(self.ZIP)
        # Adam [M(all), V(all)] over layer order stem, z, m, a, out:
        # stem W 4*5=20, stem b 5 -> z W starts at 25
        upd = exp["upd"]
        zm = np.asarray(net.updater_states["z_branch"]["W"]["m"])
        want = upd[25:40].reshape((5, 3), order="F")
        np.testing.assert_allclose(zm, want, rtol=1e-6)
