"""NLP package tests: tokenization, vocab/Huffman, Word2Vec/SequenceVectors
learning behavior, ParagraphVectors, GloVe, serialization, vectorizers.

Corpus-learning tests follow the reference pattern (Word2VecTests.java):
train on a small corpus where some words share contexts and assert the
geometry (similar words closer than dissimilar ones).
"""

import numpy as np
import pytest

from deeplearning4j_tpu.nlp import (
    BagOfWordsVectorizer,
    CollectionSentenceIterator,
    CommonPreprocessor,
    DefaultTokenizerFactory,
    Glove,
    NGramTokenizerFactory,
    ParagraphVectors,
    TfidfVectorizer,
    VocabConstructor,
    Word2Vec,
    WordVectorSerializer,
)
from deeplearning4j_tpu.nlp.learning import CBOW, SkipGram
from deeplearning4j_tpu.nlp.vocab import build_huffman


# ------------------------------------------------------------- tokenization

def test_default_tokenizer_with_preprocessor():
    tf = DefaultTokenizerFactory(CommonPreprocessor())
    toks = tf.create("Hello, World! 123 foo-bar").get_tokens()
    assert toks == ["hello", "world", "foo-bar"]


def test_ngram_tokenizer():
    tf = NGramTokenizerFactory(1, 2)
    toks = tf.create("a b c").get_tokens()
    assert toks == ["a", "b", "c", "a b", "b c"]


# ------------------------------------------------------------------- vocab

def _toy_corpus():
    # cats/dogs share contexts; "quantum" does not
    sents = []
    for animal in ("cat", "dog"):
        for verb in ("runs", "sleeps", "eats", "plays"):
            sents.extend([f"the {animal} {verb} today",
                          f"a {animal} {verb} often",
                          f"my {animal} {verb} here"])
    sents.extend(["quantum physics is hard", "quantum theory is strange"] * 4)
    return sents * 3


def test_vocab_constructor_and_huffman():
    corpus = [s.split() for s in _toy_corpus()]
    cache = VocabConstructor(min_word_frequency=2).build_vocab(corpus)
    assert cache.contains_word("cat")
    assert cache.index_of("the") >= 0
    # indices sorted by descending frequency
    freqs = [cache.element_at_index(i).frequency
             for i in range(cache.num_words())]
    assert freqs == sorted(freqs, reverse=True)
    # Huffman property: codes are prefix-free and frequent words get
    # shorter-or-equal codes
    codes = {vw.word: "".join(map(str, vw.code))
             for vw in cache.vocab_words()}
    vals = list(codes.values())
    for i, c1 in enumerate(vals):
        for c2 in vals[i + 1:]:
            assert not c1.startswith(c2) and not c2.startswith(c1)
    most = cache.element_at_index(0)
    least = cache.element_at_index(cache.num_words() - 1)
    assert len(most.code) <= len(least.code)


def test_vocab_min_frequency_cutoff():
    corpus = [["a", "a", "a", "rare"], ["a", "b", "b"]]
    cache = VocabConstructor(min_word_frequency=2).build_vocab(corpus)
    assert cache.contains_word("a") and cache.contains_word("b")
    assert not cache.contains_word("rare")


# ---------------------------------------------------------------- word2vec

@pytest.mark.parametrize("algo", ["skipgram", "cbow"])
def test_word2vec_learns_similarity(algo):
    w2v = (Word2Vec.builder()
           .layer_size(32).window_size(3).negative_sample(5)
           .min_word_frequency(2).learning_rate(0.05).epochs(8)
           .seed(42).elements_learning_algorithm(algo).build())
    w2v.fit(_toy_corpus())
    assert w2v.has_word("cat") and w2v.has_word("dog")
    sim_animals = w2v.similarity("cat", "dog")
    sim_cross = w2v.similarity("cat", "quantum")
    assert sim_animals > sim_cross, (sim_animals, sim_cross)
    vec = w2v.get_word_vector("cat")
    assert vec.shape == (32,)
    assert np.isfinite(vec).all()


@pytest.mark.parametrize("algo", ["skipgram", "cbow"])
def test_word2vec_hierarchic_softmax(algo):
    w2v = Word2Vec(layer_size=24, window_size=3, negative_sample=0,
                   use_hierarchic_softmax=True, min_word_frequency=2,
                   learning_rate=0.05, epochs=8, seed=7, algorithm=algo)
    w2v.fit(_toy_corpus())
    # HS-only training must actually move the embeddings off their init
    init = (np.random.default_rng(12345)
            .random((w2v.vocab.num_words(), 24)) - 0.5) / 24
    moved = np.abs(w2v.lookup_table.all_vectors() - init).max()
    assert moved > 1e-3
    assert w2v.similarity("cat", "dog") > w2v.similarity("cat", "quantum")


def test_word2vec_words_nearest():
    w2v = Word2Vec(layer_size=32, window_size=3, min_word_frequency=2,
                   epochs=8, seed=3, learning_rate=0.05)
    w2v.fit(_toy_corpus())
    nearest = w2v.words_nearest("cat", top_n=5)
    assert "cat" not in nearest
    assert "dog" in nearest


def test_word2vec_sentence_iterator_path():
    it = CollectionSentenceIterator(_toy_corpus())
    w2v = Word2Vec(layer_size=16, window_size=2, min_word_frequency=2,
                   epochs=2, sentence_iterator=it)
    w2v.fit()
    assert w2v.has_word("cat")


def test_word2vec_determinism():
    a = Word2Vec(layer_size=16, window_size=2, min_word_frequency=2,
                 epochs=2, seed=11).fit(_toy_corpus())
    b = Word2Vec(layer_size=16, window_size=2, min_word_frequency=2,
                 epochs=2, seed=11).fit(_toy_corpus())
    np.testing.assert_allclose(a.get_word_vector("cat"),
                               b.get_word_vector("cat"), rtol=1e-6)


# ------------------------------------------------------------- serialization

def test_txt_roundtrip(tmp_path):
    w2v = Word2Vec(layer_size=16, window_size=2, min_word_frequency=2,
                   epochs=2, seed=5).fit(_toy_corpus())
    path = str(tmp_path / "vectors.txt")
    WordVectorSerializer.write_word_vectors(w2v, path)
    loaded = WordVectorSerializer.load_txt_vectors(path)
    np.testing.assert_allclose(loaded.get_word_vector("cat"),
                               w2v.get_word_vector("cat"), atol=1e-5)
    assert loaded.vocab.num_words() == w2v.vocab.num_words()


def test_txt_roundtrip_multiword_tokens(tmp_path):
    """N-gram tokens containing spaces must survive the text format (B64
    wrapping, WordVectorSerializer ReadHelper convention)."""
    from deeplearning4j_tpu.nlp.sequencevectors import SequenceVectors

    sv = SequenceVectors(layer_size=8, min_word_frequency=1, epochs=1)
    sv.fit([["new york", "city"], ["new york", "state"]])
    path = str(tmp_path / "ngrams.txt")
    WordVectorSerializer.write_word_vectors(sv, path)
    loaded = WordVectorSerializer.load_txt_vectors(path)
    vec = loaded.get_word_vector("new york")
    assert vec is not None
    np.testing.assert_allclose(vec, sv.get_word_vector("new york"), atol=1e-5)


def test_zip_model_roundtrip(tmp_path):
    w2v = Word2Vec(layer_size=16, window_size=2, min_word_frequency=2,
                   use_hierarchic_softmax=True, epochs=2, seed=5)
    w2v.fit(_toy_corpus())
    path = str(tmp_path / "model.zip")
    WordVectorSerializer.write_word2vec_model(w2v, path)
    loaded = WordVectorSerializer.read_word2vec_model(path)
    np.testing.assert_allclose(loaded.get_word_vector("dog"),
                               w2v.get_word_vector("dog"), rtol=1e-6)
    vw_orig = w2v.vocab.word_for("dog")
    vw_new = loaded.vocab.word_for("dog")
    assert vw_orig.code == vw_new.code and vw_orig.points == vw_new.points
    assert vw_orig.frequency == vw_new.frequency


# ------------------------------------------------------- paragraph vectors

def _labelled_docs():
    docs = []
    for i in range(6):
        docs.append((f"the cat sleeps on the mat number {i}", [f"pet_{i % 2}"]))
        docs.append((f"quantum theory lecture notes part {i}", [f"sci_{i % 2}"]))
    return docs


def test_paragraph_vectors_dm_and_labels():
    pv = ParagraphVectors(layer_size=24, window_size=3, epochs=10,
                          min_word_frequency=1, seed=9,
                          sequence_algorithm="dm")
    pv.fit(_labelled_docs())
    assert set(pv.labels) == {"pet_0", "pet_1", "sci_0", "sci_1"}
    v = pv.get_label_vector("pet_0")
    assert v is not None and np.isfinite(v).all()


def test_paragraph_vectors_dbow_infer():
    pv = ParagraphVectors(layer_size=24, window_size=3, epochs=10,
                          min_word_frequency=1, seed=9,
                          sequence_algorithm="dbow")
    pv.fit(_labelled_docs())
    n_before = pv.vocab.num_words()
    vec = pv.infer_vector("the cat sleeps quietly")
    assert vec.shape == (24,)
    assert np.isfinite(vec).all()
    # inference must not mutate the model
    assert pv.vocab.num_words() == n_before
    assert pv.lookup_table.syn0.shape[0] == n_before


# ------------------------------------------------------------------- glove

def test_glove_trains_and_geometry():
    g = Glove(layer_size=24, window=4, epochs=25, learning_rate=0.05,
              min_word_frequency=2, seed=13, batch_size=1024)
    g.fit(_toy_corpus())
    assert g.similarity("cat", "dog") > g.similarity("cat", "quantum")


# -------------------------------------------------------------- vectorizers

def test_bag_of_words():
    docs = ["a b a", "b c"]
    v = BagOfWordsVectorizer(min_word_frequency=1)
    mat = v.fit_transform(docs)
    assert mat.shape == (2, 3)
    ia, ib, ic = (v.vocab.index_of(w) for w in "abc")
    assert mat[0, ia] == 2 and mat[0, ib] == 1 and mat[0, ic] == 0
    assert mat[1, ib] == 1 and mat[1, ic] == 1


def test_tfidf():
    docs = ["a b", "a c", "a d"]
    v = TfidfVectorizer(min_word_frequency=1)
    mat = v.fit_transform(docs)
    ia = v.vocab.index_of("a")
    ib = v.vocab.index_of("b")
    # 'a' appears in every doc -> idf 0; 'b' only in doc0
    assert np.allclose(mat[:, ia], 0.0)
    assert mat[0, ib] > 0 and np.allclose(mat[1:, ib], 0.0)
    assert v.tfidf_word("b", ["a", "b"]) == pytest.approx(
        0.5 * np.log(3.0), rel=1e-6)


def test_paragraph_vectors_dm_no_negative_uses_hs():
    # regression: negative_sample=0 used to crash DM (syn1neg None)
    pv = ParagraphVectors(layer_size=16, window_size=3, epochs=3,
                          min_word_frequency=1, seed=9, negative_sample=0,
                          sequence_algorithm="dm")
    pv.fit(_labelled_docs())
    v = pv.get_label_vector("pet_0")
    assert v is not None and np.isfinite(v).all()


def test_label_colliding_with_rare_word_survives_cutoff():
    # regression: a label equal to a below-cutoff corpus word was dropped
    from deeplearning4j_tpu.nlp.vocab import VocabConstructor
    vc = VocabConstructor(min_word_frequency=2)
    cache = vc.build_vocab([["a", "a", "dog"]], labels=[["dog"]])
    assert cache.contains_word("dog")
    assert cache.index_of("dog") >= 0
    assert cache.word_for("dog").is_label


def test_special_tokens_survive_cutoff():
    # regression: special tokens used to be truncated below min frequency
    from deeplearning4j_tpu.nlp.vocab import VocabConstructor
    vc = VocabConstructor(min_word_frequency=3, special_tokens=("UNK",))
    cache = vc.build_vocab([["a", "a", "a", "UNK"]])
    assert cache.contains_word("UNK")
    assert cache.index_of("UNK") >= 0


def test_file_sentence_iterator_streams(tmp_path):
    d = tmp_path / "corpus"
    d.mkdir()
    (d / "a.txt").write_text("one\ntwo\n")
    (d / "b.txt").write_text("three\n")
    from deeplearning4j_tpu.nlp.sentence import FileSentenceIterator
    it = FileSentenceIterator(str(d))
    got = []
    while it.has_next():
        got.append(it.next_sentence())
    assert got == ["one", "two", "three"]
    it.reset()
    assert it.next_sentence() == "one"


class TestFileCorpusFastPath:
    """fit_file: native vocab scan + line-streamed training must reach the
    same quality as the in-memory sequence path."""

    def test_fit_file_learns_cooccurrence(self, tmp_path):
        import numpy as np
        from deeplearning4j_tpu.nlp.word2vec import Word2Vec

        rng = np.random.default_rng(7)
        # two topic clusters: words within a topic co-occur
        topics = [["cat", "dog", "pet", "fur"], ["car", "road", "wheel", "gas"]]
        lines = []
        for _ in range(400):
            t = topics[rng.integers(0, 2)]
            lines.append(" ".join(rng.choice(t, 6)))
        p = tmp_path / "corpus.txt"
        p.write_text("\n".join(lines))

        w2v = Word2Vec(layer_size=16, window_size=3, min_word_frequency=1,
                       epochs=3, seed=1)
        w2v.fit_file(str(p))
        assert w2v.similarity("cat", "dog") > w2v.similarity("cat", "road")
        assert w2v.similarity("car", "wheel") > w2v.similarity("car", "pet")

    def test_vocab_from_file_matches_sequences(self, tmp_path):
        from deeplearning4j_tpu.nlp.word2vec import Word2Vec

        p = tmp_path / "c.txt"
        p.write_text("a b b c c c\nd d a\n")
        a = Word2Vec(layer_size=4, min_word_frequency=2, seed=1)
        a.build_vocab_from_file(str(p))
        b = Word2Vec(layer_size=4, min_word_frequency=2, seed=1)
        b.build_vocab([l.split() for l in p.read_text().splitlines()])
        wa = sorted((w.word, w.frequency) for w in a.vocab._by_index)
        wb = sorted((w.word, w.frequency) for w in b.vocab._by_index)
        assert wa == wb

    def test_fit_file_nonascii_tokens_trainable(self, tmp_path):
        # byte-level tokenization in BOTH scan and training: non-ASCII
        # uppercase must not silently drop words from training
        from deeplearning4j_tpu.nlp.word2vec import Word2Vec

        p = tmp_path / "uni.txt"
        p.write_text("Éclair tarte Éclair\n" * 50 + "car road car\n" * 50)
        w2v = Word2Vec(layer_size=8, window_size=2, min_word_frequency=1,
                       epochs=2, seed=1)
        w2v.fit_file(str(p))
        # the scan's ASCII lowercasing leaves 'Éclair' intact — and so does
        # the training tokenizer, so its vector is trained, not random
        assert w2v.has_word("Éclair")
        assert w2v.similarity("Éclair", "tarte") > w2v.similarity("Éclair",
                                                                  "road")

    def test_fit_file_respects_configured_tokenizer(self, tmp_path):
        from deeplearning4j_tpu.nlp.tokenization import (
            CommonPreprocessor,
            DefaultTokenizerFactory,
        )
        from deeplearning4j_tpu.nlp.word2vec import Word2Vec

        p = tmp_path / "punct.txt"
        p.write_text("Word. word, WORD!\n" * 30)
        w2v = Word2Vec(layer_size=4, min_word_frequency=1, seed=1,
                       tokenizer_factory=DefaultTokenizerFactory(
                           CommonPreprocessor()))
        w2v.fit_file(str(p))
        # the pre-processor strips punctuation and lowercases: ONE vocab
        # entry, not 'word.'/'word,' variants
        assert w2v.has_word("word")
        assert not w2v.has_word("word.")


# ----------------------------------------- Google word2vec binary format

def _hand_built_bin(words_vecs, linebreaks=True):
    """Build the C binary format INDEPENDENTLY of the writer (struct.pack
    per the word2vec.c layout) so the reader is genuinely inverted."""
    import struct
    size = len(words_vecs[0][1])
    out = f"{len(words_vecs)} {size}\n".encode()
    for w, v in words_vecs:
        out += w.encode("utf-8") + b" "
        out += struct.pack(f"<{size}f", *v)
        if linebreaks:
            out += b"\n"
    return out


def test_binary_read_hand_built_fixture(tmp_path):
    vecs = [("the", [0.1, -0.2, 0.3]), ("cat", [1.0, 2.0, -3.0]),
            ("sat", [0.0, 0.5, 0.25])]
    p = str(tmp_path / "mini.bin")
    with open(p, "wb") as fh:
        fh.write(_hand_built_bin(vecs))
    m = WordVectorSerializer.read_binary_model(p)
    assert m.vocab.num_words() == 3
    assert m.layer_size == 3
    np.testing.assert_allclose(m.get_word_vector("cat"), [1.0, 2.0, -3.0],
                               rtol=1e-6)
    # file order preserved (readBinaryModel adds in stream order)
    assert m.vocab.word_at_index(0) == "the"
    assert m.vocab.word_at_index(2) == "sat"


def test_binary_read_no_linebreaks_variant(tmp_path):
    vecs = [("a", [0.5, 0.5]), ("b", [1.5, -1.5])]
    p = str(tmp_path / "nolb.bin")
    with open(p, "wb") as fh:
        fh.write(_hand_built_bin(vecs, linebreaks=False))
    m = WordVectorSerializer.read_binary_model(p)  # auto-detect
    np.testing.assert_allclose(m.get_word_vector("b"), [1.5, -1.5], rtol=1e-6)


def test_binary_normalize_matches_unitvec(tmp_path):
    vecs = [("x", [3.0, 4.0])]
    p = str(tmp_path / "n.bin")
    with open(p, "wb") as fh:
        fh.write(_hand_built_bin(vecs))
    m = WordVectorSerializer.read_binary_model(p, normalize=True)
    np.testing.assert_allclose(m.get_word_vector("x"), [0.6, 0.8], rtol=1e-6)


def test_binary_write_read_roundtrip_and_gzip(tmp_path):
    w2v = Word2Vec(layer_size=12, window_size=2, min_word_frequency=2,
                   epochs=1, seed=9).fit(_toy_corpus())
    for name in ("vec.bin", "vec.bin.gz"):
        p = str(tmp_path / name)
        WordVectorSerializer.write_binary_model(w2v, p)
        loaded = WordVectorSerializer.read_binary_model(p)
        assert loaded.vocab.num_words() == w2v.vocab.num_words()
        np.testing.assert_allclose(loaded.get_word_vector("cat"),
                                   np.asarray(w2v.get_word_vector("cat"),
                                              np.float32), rtol=1e-6)


def test_binary_utf8_words_survive(tmp_path):
    vecs = [("猫", [1.0, 0.0]), ("über", [0.0, 1.0])]
    p = str(tmp_path / "u.bin")
    with open(p, "wb") as fh:
        fh.write(_hand_built_bin(vecs))
    m = WordVectorSerializer.read_binary_model(p)
    np.testing.assert_allclose(m.get_word_vector("猫"), [1.0, 0.0])
    np.testing.assert_allclose(m.get_word_vector("über"), [0.0, 1.0])


def test_load_static_model_dispatches_all_three_formats(tmp_path):
    w2v = Word2Vec(layer_size=8, window_size=2, min_word_frequency=2,
                   epochs=1, seed=4).fit(_toy_corpus())
    zp = str(tmp_path / "model.zip")
    tp = str(tmp_path / "model.txt")
    bp = str(tmp_path / "model.bin")
    WordVectorSerializer.write_word2vec_model(w2v, zp)
    WordVectorSerializer.write_word_vectors(w2v, tp)
    WordVectorSerializer.write_binary_model(w2v, bp)
    ref = np.asarray(w2v.get_word_vector("cat"), np.float32)
    for p in (zp, tp, bp):
        m = WordVectorSerializer.load_static_model(p)
        np.testing.assert_allclose(
            np.asarray(m.get_word_vector("cat"), np.float32), ref,
            atol=1e-5)
    with open(str(tmp_path / "junk.xyz"), "wb") as fh:
        fh.write(b"\x00\x01 not a model \x02")
    with pytest.raises(ValueError, match="guess input file format"):
        WordVectorSerializer.load_static_model(str(tmp_path / "junk.xyz"))
