"""node2vec: biased second-order random walks + skip-gram embeddings.

Parity with the reference's ``models/node2vec/`` (under deeplearning4j-nlp;
Grover & Leskovec 2016): return parameter ``p`` and in-out parameter ``q``
bias the walk toward BFS- or DFS-like exploration. The walk generator is
vectorised over all active walks per step using a padded neighbour matrix
(candidates for every walk evaluated at once: back-to-previous gets weight
1/p, neighbours-of-previous weight 1, others 1/q), and training reuses the
batched hierarchical-softmax DeepWalk trainer.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from deeplearning4j_tpu.graph.deepwalk import DeepWalk
from deeplearning4j_tpu.graph.graph import Graph


class Node2Vec(DeepWalk):
    def __init__(self, vector_size: int = 100, window_size: int = 2,
                 learning_rate: float = 0.01, seed: int = 12345,
                 p: float = 1.0, q: float = 1.0, walks_per_vertex: int = 10,
                 batch_size: int = 8192):
        super().__init__(vector_size, window_size, learning_rate, seed,
                         batch_size)
        self.p = float(p)
        self.q = float(q)
        self.walks_per_vertex = walks_per_vertex

    # -- vectorised biased walks -----------------------------------------
    def _neighbor_matrix(self, graph: Graph):
        """Padded neighbour matrix [n, max_deg] (-1 pad) + sorted-neighbour
        CSR for O(log d) membership tests."""
        ptr, indices, _ = graph.csr()
        n = graph.num_vertices()
        degs = (ptr[1:] - ptr[:-1]).astype(np.int64)
        max_deg = int(degs.max()) if n else 0
        nbr = np.full((n, max(max_deg, 1)), -1, dtype=np.int64)
        for v in range(n):
            nbr[v, :degs[v]] = indices[ptr[v]:ptr[v + 1]]
        sorted_indices = indices.copy()
        for v in range(n):
            sorted_indices[ptr[v]:ptr[v + 1]].sort()
        return nbr, degs, ptr, sorted_indices

    def generate_walks(self, graph: Graph, walk_length: int,
                       rng: np.random.Generator) -> np.ndarray:
        """One biased walk per start vertex x walks_per_vertex."""
        # the adjacency layout is immutable across epochs: build once per graph
        if getattr(self, "_nbr_cache", None) is None or self._nbr_cache[0] is not graph:
            self._nbr_cache = (graph, self._neighbor_matrix(graph))
        nbr, degs, ptr, sorted_idx = self._nbr_cache[1]
        n = graph.num_vertices()
        starts = np.tile(np.arange(n), self.walks_per_vertex)
        rng.shuffle(starts)
        W = len(starts)
        walks = np.empty((W, walk_length + 1), dtype=np.int64)
        walks[:, 0] = starts
        if walk_length == 0:
            return walks
        if len(sorted_idx) == 0:
            # edgeless graph: every walk self-loops (DeepWalk's
            # SELF_LOOP_ON_DISCONNECTED contract)
            walks[:, 1:] = starts[:, None]
            return walks
        # first step: uniform neighbour (no previous vertex yet)
        d = degs[starts]
        safe = np.maximum(d, 1)
        first = nbr[starts, rng.integers(0, safe)]
        cur = np.where(d > 0, first, starts)
        walks[:, 1] = cur
        prev = starts.copy()
        max_deg = nbr.shape[1]
        for step in range(2, walk_length + 1):
            cand = nbr[cur]                              # [W, max_deg]
            valid = cand >= 0
            safe_cand = np.where(valid, cand, 0)
            # membership: is candidate a neighbour of prev? binary search in
            # prev's sorted adjacency row
            lo = ptr[prev][:, None]
            hi = ptr[prev + 1][:, None]
            # searchsorted on the global sorted-per-row array
            pos = np.empty_like(safe_cand)
            flat_c = safe_cand.ravel()
            flat_lo = np.broadcast_to(lo, safe_cand.shape).ravel()
            flat_hi = np.broadcast_to(hi, safe_cand.shape).ravel()
            # vectorised per-element binary search over row segments
            pos_flat = flat_lo.copy()
            lo_w, hi_w = flat_lo.copy(), flat_hi.copy()
            while np.any(lo_w < hi_w):
                mid = (lo_w + hi_w) // 2
                go_right = sorted_idx[np.minimum(mid, len(sorted_idx) - 1)] < flat_c
                active = lo_w < hi_w
                lo_w = np.where(active & go_right, mid + 1, lo_w)
                hi_w = np.where(active & ~go_right, mid, hi_w)
            pos_flat = lo_w
            in_prev = (pos_flat < flat_hi) & (
                sorted_idx[np.minimum(pos_flat, len(sorted_idx) - 1)] == flat_c)
            is_nbr_of_prev = in_prev.reshape(safe_cand.shape)
            w = np.where(safe_cand == prev[:, None], 1.0 / self.p,
                         np.where(is_nbr_of_prev, 1.0, 1.0 / self.q))
            w = np.where(valid, w, 0.0)
            totals = w.sum(axis=1)
            stuck = totals <= 0
            w_cum = np.cumsum(w, axis=1)
            u = rng.random(W) * np.maximum(totals, 1e-30)
            choice = (w_cum < u[:, None]).sum(axis=1).clip(0, max_deg - 1)
            nxt = cand[np.arange(W), choice]
            nxt = np.where(stuck | (nxt < 0), cur, nxt)
            prev, cur = cur, nxt
            walks[:, step] = cur
        return walks

    # -- training ---------------------------------------------------------
    def fit(self, graph: Optional[Graph] = None, walk_length: int = 10,
            epochs: int = 1, walks: Optional[np.ndarray] = None, **kw):
        if graph is None:
            graph = self.graph
        if graph is not None and not self._init_called:
            self.initialize(graph)
        if not self._init_called:
            raise RuntimeError("Node2Vec not initialized")
        rng = np.random.default_rng(self.seed)
        for _ in range(epochs):
            epoch_walks = (np.asarray(walks) if walks is not None
                           else self.generate_walks(graph, walk_length, rng))
            self.fit_walks(epoch_walks)
