"""DeepWalk graph embeddings with batched hierarchical softmax on device.

Capability parity with the reference's
``graph/models/deepwalk/DeepWalk.java`` (Perozzi et al. 2014 skip-gram over
random walks) and ``graph/models/embeddings/InMemoryGraphLookupTable.java``
(hierarchical-softmax lookup table), re-designed TPU-first:

- the reference runs one ``iterate(in, out)`` per skip-gram pair on JVM
  threads (hogwild row updates); here all pairs of a walk batch are trained in
  a single jitted gather → sigmoid → scatter-add step, so the MXU/VPU sees
  one large batched op instead of ~millions of 2-row BLAS calls;
- walk generation is vectorised over all start vertices
  (:meth:`Graph.random_walks`);
- ``vectors_and_gradients`` / ``calculate_prob`` / ``calculate_score`` keep
  the reference's per-pair math available for gradient checks
  (`InMemoryGraphLookupTable.java:79-160`).
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.graph.api import NoEdgeHandling
from deeplearning4j_tpu.graph.graph import Graph
from deeplearning4j_tpu.graph.huffman import GraphHuffman


@functools.partial(jax.jit, donate_argnums=(0, 1))
def _hs_batch_update(vertex_vectors, out_weights, firsts, nodes, bits, mask,
                     lr):
    """One batched hierarchical-softmax SGD step.

    firsts: (B,) input vertex ids; nodes/bits/mask: (B, L) padded Huffman path
    of the output vertex. Gradients of sum of -log P(out|in) over the batch,
    applied via scatter-add (deterministic minibatch redesign of the
    reference's sequential per-pair updates).
    """
    vec = vertex_vectors[firsts]                      # (B, D)
    inner = out_weights[nodes]                        # (B, L, D)
    dots = jnp.einsum("bld,bd->bl", inner, vec)       # (B, L)
    sig = jax.nn.sigmoid(dots)
    g = (sig - bits) * mask                           # (B, L) dL/d(dot)
    inner_grad = g[..., None] * vec[:, None, :]       # (B, L, D)
    vec_grad = jnp.einsum("bl,bld->bd", g, inner)     # (B, D)
    out_weights = out_weights.at[nodes].add(-lr * inner_grad)
    vertex_vectors = vertex_vectors.at[firsts].add(-lr * vec_grad)
    return vertex_vectors, out_weights


class InMemoryGraphLookupTable:
    """Vertex/inner-node embedding table with hierarchical softmax."""

    MAX_EXP = 6.0

    def __init__(self, n_vertices: int, vector_size: int, tree: Optional[GraphHuffman],
                 learning_rate: float, seed: int = 12345):
        self.n_vertices = n_vertices
        self._vector_size = vector_size
        self.tree = tree
        self.learning_rate = float(learning_rate)
        self._seed = seed
        self.reset_weights()
        if tree is not None:
            nodes, bits, mask = tree.path_arrays()
            self._path_nodes = jnp.asarray(nodes)
            self._path_bits = jnp.asarray(bits)
            self._path_mask = jnp.asarray(mask)

    # -- reference API ----------------------------------------------------
    def vector_size(self) -> int:
        return self._vector_size

    def get_num_vertices(self) -> int:
        return self.n_vertices

    def reset_weights(self):
        """U(-0.5, 0.5)/vector_size init, matching
        ``InMemoryGraphLookupTable.resetWeights`` (rand-0.5)/size. A full
        binary tree with L leaves has L-1 inner nodes."""
        k1, k2 = jax.random.split(jax.random.PRNGKey(self._seed))
        d = self._vector_size
        self.vertex_vectors = (
            (jax.random.uniform(k1, (self.n_vertices, d)) - 0.5) / d)
        self.out_weights = (
            (jax.random.uniform(k2, (max(self.n_vertices - 1, 1), d)) - 0.5) / d)

    def set_learning_rate(self, lr: float):
        self.learning_rate = float(lr)

    def get_vector(self, idx: int) -> np.ndarray:
        return np.asarray(self.vertex_vectors[idx])

    def get_vertex_vectors(self) -> np.ndarray:
        return np.asarray(self.vertex_vectors)

    def set_vertex_vectors(self, arr):
        self.vertex_vectors = jnp.asarray(arr)

    def get_inner_node_vector(self, inner_node: int) -> np.ndarray:
        return np.asarray(self.out_weights[inner_node])

    def get_tree(self) -> Optional[GraphHuffman]:
        return self.tree

    # -- training ---------------------------------------------------------
    def iterate(self, first: int, second: int):
        """Single-pair update (reference ``iterate``); prefer iterate_batch."""
        self.iterate_batch(np.array([first]), np.array([second]))

    def iterate_batch(self, firsts: np.ndarray, seconds: np.ndarray):
        firsts = jnp.asarray(firsts, dtype=jnp.int32)
        seconds = np.asarray(seconds)
        self.vertex_vectors, self.out_weights = _hs_batch_update(
            self.vertex_vectors, self.out_weights, firsts,
            self._path_nodes[seconds], self._path_bits[seconds],
            self._path_mask[seconds], self.learning_rate)

    # -- per-pair math (gradient-check parity) -----------------------------
    def vectors_and_gradients(self, first: int, second: int):
        """[vectors, grads] for (input vertex, inner nodes on path to second);
        mirrors ``InMemoryGraphLookupTable.vectorsAndGradients`` for tests."""
        vec = np.asarray(self.vertex_vectors[first], dtype=np.float64)
        code = self.tree.get_code(second)
        code_len = self.tree.get_code_length(second)
        path = self.tree.get_path_inner_nodes(second)
        vectors = [vec]
        grads = [np.zeros_like(vec)]
        accum = np.zeros_like(vec)
        for i in range(code_len):
            inner_vec = np.asarray(self.out_weights[path[i]], dtype=np.float64)
            bit = (code >> i) & 1
            sig = 1.0 / (1.0 + np.exp(-np.dot(inner_vec, vec)))
            g = sig - bit
            vectors.append(inner_vec)
            grads.append(g * vec)
            accum += g * inner_vec
        grads[0] = accum
        return vectors, grads

    def calculate_prob(self, first: int, second: int) -> float:
        """P(second | first) under hierarchical softmax."""
        vec = np.asarray(self.vertex_vectors[first], dtype=np.float64)
        code = self.tree.get_code(second)
        code_len = self.tree.get_code_length(second)
        path = self.tree.get_path_inner_nodes(second)
        prob = 1.0
        for i in range(code_len):
            inner_vec = np.asarray(self.out_weights[path[i]], dtype=np.float64)
            dot = float(np.dot(inner_vec, vec))
            bit = (code >> i) & 1
            z = dot if bit else -dot
            # numerically stable sigmoid(z)
            p = 1.0 / (1.0 + np.exp(-z)) if z >= 0 else np.exp(z) / (1.0 + np.exp(z))
            prob *= p
        return prob

    def calculate_score(self, first: int, second: int) -> float:
        return -float(np.log(self.calculate_prob(first, second)))


class GraphVectors:
    """Learned vertex representations: similarity and nearest-vertex queries
    (``graph/models/GraphVectors.java`` / ``embeddings/GraphVectorsImpl.java``)."""

    def __init__(self, lookup_table: InMemoryGraphLookupTable,
                 graph: Optional[Graph] = None):
        self.lookup_table = lookup_table
        self.graph = graph

    def num_vertices(self) -> int:
        return self.lookup_table.get_num_vertices()

    def get_vector_size(self) -> int:
        return self.lookup_table.vector_size()

    def get_vertex_vector(self, vertex_idx: int) -> np.ndarray:
        return self.lookup_table.get_vector(int(vertex_idx))

    def similarity(self, v1: int, v2: int) -> float:
        """Cosine similarity between two vertex vectors."""
        a = self.lookup_table.vertex_vectors[int(v1)]
        b = self.lookup_table.vertex_vectors[int(v2)]
        return float(jnp.dot(a, b)
                     / (jnp.linalg.norm(a) * jnp.linalg.norm(b) + 1e-12))

    def vertices_nearest(self, vertex_idx: int, top: int) -> np.ndarray:
        """Top-N nearest vertices by cosine similarity — one device matmul
        over the whole table instead of the reference's per-row loop."""
        vv = self.lookup_table.vertex_vectors
        q = vv[int(vertex_idx)]
        norms = jnp.linalg.norm(vv, axis=1) * (jnp.linalg.norm(q) + 1e-12)
        sims = (vv @ q) / jnp.maximum(norms, 1e-12)
        sims = sims.at[int(vertex_idx)].set(-jnp.inf)
        _, idx = jax.lax.top_k(sims, top)
        return np.asarray(idx)


class DeepWalk(GraphVectors):
    """DeepWalk: skip-gram with hierarchical softmax over random walks."""

    def __init__(self, vector_size: int = 100, window_size: int = 2,
                 learning_rate: float = 0.01, seed: int = 12345,
                 batch_size: int = 8192):
        self.vector_size = vector_size
        self.window_size = window_size
        self.learning_rate = learning_rate
        self.seed = seed
        self.batch_size = batch_size
        self.lookup_table: Optional[InMemoryGraphLookupTable] = None
        self.graph: Optional[Graph] = None
        self._init_called = False

    class Builder:
        """Fluent builder mirroring ``DeepWalk.Builder``."""

        def __init__(self):
            self._vector_size, self._window_size = 100, 2
            self._learning_rate, self._seed = 0.01, 12345

        def vector_size(self, v):
            self._vector_size = v
            return self

        def window_size(self, w):
            self._window_size = w
            return self

        def learning_rate(self, lr):
            self._learning_rate = lr
            return self

        def seed(self, s):
            self._seed = s
            return self

        def build(self) -> "DeepWalk":
            return DeepWalk(self._vector_size, self._window_size,
                            self._learning_rate, self._seed)

    # -- lifecycle --------------------------------------------------------
    def initialize(self, graph_or_degrees):
        """Build the Huffman tree from vertex degrees and allocate the table
        (``DeepWalk.initialize``)."""
        if isinstance(graph_or_degrees, Graph):
            self.graph = graph_or_degrees
            degrees = graph_or_degrees.vertex_degrees()
        else:
            degrees = np.asarray(graph_or_degrees, dtype=np.int64)
        tree = GraphHuffman(len(degrees)).build_tree(degrees)
        self.lookup_table = InMemoryGraphLookupTable(
            len(degrees), self.vector_size, tree, self.learning_rate, self.seed)
        self._init_called = True

    def set_learning_rate(self, lr: float):
        self.learning_rate = lr
        if self.lookup_table is not None:
            self.lookup_table.set_learning_rate(lr)

    def get_vector_size(self) -> int:
        return self.vector_size

    def get_window_size(self) -> int:
        return self.window_size

    def get_learning_rate(self) -> float:
        return self.learning_rate

    # -- training ---------------------------------------------------------
    def fit(self, graph: Optional[Graph] = None, walk_length: int = 10,
            epochs: int = 1, walks: Optional[np.ndarray] = None,
            no_edge_handling: NoEdgeHandling = NoEdgeHandling.SELF_LOOP_ON_DISCONNECTED,
            weighted: bool = False):
        """Fit on random walks (one walk per vertex per epoch, shuffled start
        order — ``RandomWalkIterator`` semantics), or on pre-generated
        ``walks`` of shape (n_walks, walk_len+1)."""
        if graph is None:
            graph = self.graph
        if graph is not None and not self._init_called:
            self.initialize(graph)
        if not self._init_called:
            raise RuntimeError("DeepWalk not initialized (call initialize before fit)")
        if graph is None and walks is None:
            raise ValueError("fit() needs a graph or pre-generated walks")
        rng = np.random.default_rng(self.seed)
        for _ in range(epochs):
            if walks is None:
                starts = rng.permutation(graph.num_vertices())
                epoch_walks = graph.random_walks(
                    starts, walk_length, rng, weighted=weighted,
                    self_loop_disconnected=(
                        no_edge_handling is NoEdgeHandling.SELF_LOOP_ON_DISCONNECTED))
            else:
                epoch_walks = np.asarray(walks)
            self.fit_walks(epoch_walks)

    def fit_walks(self, walks: np.ndarray):
        """Train on an array of walks: extract all (center, context) skip-gram
        pairs (``DeepWalk.skipGram``: centers range over positions with a full
        window on both sides) and apply them in device-sized batches."""
        walks = np.asarray(walks)
        L = walks.shape[1]
        w = self.window_size
        centers_pos = np.arange(w, L - w)
        if len(centers_pos) == 0:
            return
        offsets = np.concatenate([np.arange(-w, 0), np.arange(1, w + 1)])
        # (n_walks, n_centers, n_offsets)
        first = np.repeat(walks[:, centers_pos][..., None], len(offsets), axis=2)
        second = walks[:, (centers_pos[:, None] + offsets[None, :])]
        firsts = first.reshape(-1)
        seconds = second.reshape(-1)
        bs = self.batch_size
        for i in range(0, len(firsts), bs):
            self.lookup_table.iterate_batch(firsts[i:i + bs], seconds[i:i + bs])
