"""Walk iterators over a graph.

Parity with ``graph/iterator/RandomWalkIterator.java``,
``WeightedRandomWalkIterator.java`` and the parallel iterator providers in
``graph/iterator/parallel/``. Walks for a whole epoch are generated in one
vectorised call (:meth:`Graph.random_walks`); the iterator then yields
:class:`VertexSequence` views for API parity. The "provider" splits the vertex
range into partitions — in the reference this feeds one iterator per JVM
thread; here partitions become device-batch shards.
"""

from __future__ import annotations

from typing import Iterator, List, Optional

import numpy as np

from deeplearning4j_tpu.graph.api import NoEdgeHandling
from deeplearning4j_tpu.graph.graph import Graph, VertexSequence


class _BaseWalkIterator:
    weighted = False

    def __init__(self, graph: Graph, walk_length: int, seed: int = 12345,
                 mode: NoEdgeHandling = NoEdgeHandling.EXCEPTION_ON_DISCONNECTED,
                 first_vertex: int = 0, last_vertex: Optional[int] = None):
        self.graph = graph
        self._walk_length = walk_length
        self.seed = seed
        self.mode = mode
        self.first_vertex = first_vertex
        self.last_vertex = graph.num_vertices() if last_vertex is None else last_vertex
        self._rng = np.random.default_rng(seed)
        self.reset()

    def walk_length(self) -> int:
        return self._walk_length

    def reset(self):
        """Regenerate walks: one per start vertex, start order shuffled
        (``RandomWalkIterator.reset``)."""
        starts = np.arange(self.first_vertex, self.last_vertex)
        self._rng.shuffle(starts)
        self._walks = self.graph.random_walks(
            starts, self._walk_length, self._rng, weighted=self.weighted,
            self_loop_disconnected=(self.mode is NoEdgeHandling.SELF_LOOP_ON_DISCONNECTED))
        self._pos = 0

    def has_next(self) -> bool:
        return self._pos < len(self._walks)

    def next(self) -> VertexSequence:
        if not self.has_next():
            raise StopIteration
        seq = VertexSequence(self.graph, self._walks[self._pos])
        self._pos += 1
        return seq

    def __iter__(self) -> Iterator[VertexSequence]:
        while self.has_next():
            yield self.next()

    def walks_array(self) -> np.ndarray:
        """All remaining walks as one (n, walk_length+1) array — the batched
        path the DeepWalk trainer consumes directly."""
        return self._walks[self._pos:]


class RandomWalkIterator(_BaseWalkIterator):
    """Uniform random walks, one starting at every vertex exactly once per
    epoch (``iterator/RandomWalkIterator.java``)."""

    weighted = False


class WeightedRandomWalkIterator(_BaseWalkIterator):
    """Random walks with transition probability proportional to edge weight
    (``iterator/WeightedRandomWalkIterator.java``)."""

    weighted = True


class RandomWalkGraphIteratorProvider:
    """Splits start vertices into ``n`` contiguous ranges, one iterator each
    (``iterator/parallel/RandomWalkGraphIteratorProvider.java``)."""

    iterator_cls = RandomWalkIterator

    def __init__(self, graph: Graph, walk_length: int, seed: int = 12345,
                 mode: NoEdgeHandling = NoEdgeHandling.SELF_LOOP_ON_DISCONNECTED):
        self.graph = graph
        self.walk_length = walk_length
        self.seed = seed
        self.mode = mode

    def get_graph_walk_iterators(self, num_iterators: int) -> List[_BaseWalkIterator]:
        n = self.graph.num_vertices()
        num_iterators = min(num_iterators, n)
        bounds = np.linspace(0, n, num_iterators + 1, dtype=int)
        out = []
        for i in range(num_iterators):
            if bounds[i + 1] > bounds[i]:
                out.append(self.iterator_cls(
                    self.graph, self.walk_length, self.seed + i, self.mode,
                    int(bounds[i]), int(bounds[i + 1])))
        return out


class WeightedRandomWalkGraphIteratorProvider(RandomWalkGraphIteratorProvider):
    """Weighted variant (``parallel/WeightedRandomWalkGraphIteratorProvider.java``)."""

    iterator_cls = WeightedRandomWalkIterator
