"""Tab-delimited text serialisation of learned graph vectors.

Format parity with ``graph/models/loader/GraphVectorSerializer.java``:
one line per vertex — ``index<TAB>v0<TAB>v1...``; loading reconstructs a
:class:`GraphVectors` whose lookup table has no tree (inference only), exactly
like the reference's ``loadTxtVectors``.
"""

from __future__ import annotations

import numpy as np

from deeplearning4j_tpu.graph.deepwalk import GraphVectors, InMemoryGraphLookupTable

_DELIM = "\t"


class GraphVectorSerializer:
    @staticmethod
    def write_graph_vectors(model: GraphVectors, path: str) -> None:
        n = model.num_vertices()
        with open(path, "w", encoding="utf-8") as f:
            for i in range(n):
                vec = model.get_vertex_vector(i)
                f.write(str(i) + _DELIM
                        + _DELIM.join(repr(float(x)) for x in vec) + "\n")

    @staticmethod
    def load_txt_vectors(path: str) -> GraphVectors:
        rows = []
        with open(path, "r", encoding="utf-8") as f:
            for line in f:
                parts = line.rstrip("\n").split(_DELIM)
                if len(parts) > 1:
                    rows.append([float(x) for x in parts[1:]])
        if not rows:
            raise ValueError(f"No vectors found in {path!r}")
        arr = np.asarray(rows, dtype=np.float32)
        table = InMemoryGraphLookupTable(arr.shape[0], arr.shape[1], None, 0.01)
        table.set_vertex_vectors(arr)
        return GraphVectors(table)
