"""Graph structure with CSR adjacency for vectorised walk generation.

Behavioural parity with ``graph/graph/Graph.java`` (vertex values, directed and
undirected edges, multi-edge control, neighbour queries) re-designed so that
random walks over *all* start vertices are generated with vectorised NumPy
gathers over a CSR layout rather than per-vertex object traversal — the shape
that feeds the batched on-device DeepWalk trainer.
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence

import numpy as np

from deeplearning4j_tpu.graph.api import Edge, NoEdgesException, Vertex


class VertexSequence:
    """A sequence of vertices in a graph, e.g. one random walk
    (``graph/graph/VertexSequence.java``)."""

    def __init__(self, graph: "Graph", indices: Sequence[int]):
        self._graph = graph
        self._indices = list(int(i) for i in indices)

    def __len__(self) -> int:
        return len(self._indices)

    def __iter__(self):
        for i in self._indices:
            yield self._graph.get_vertex(i)

    def indices(self) -> List[int]:
        return list(self._indices)

    def sequence_length(self) -> int:
        return len(self._indices)


class Graph:
    """Graph with integer-indexed vertices carrying arbitrary values.

    ``add_edge`` accepts directed or undirected edges; undirected edges appear
    in both endpoints' adjacency (matching ``Graph.java:90-113``). Adjacency is
    materialised to CSR arrays on first use and invalidated on mutation.
    """

    def __init__(self, num_vertices: int = 0, allow_multiple_edges: bool = True,
                 vertices: Optional[Sequence[Any]] = None):
        if vertices is not None:
            self._values = list(vertices)
        else:
            self._values = [None] * num_vertices
        self.allow_multiple_edges = allow_multiple_edges
        self._edges_out: List[List[Edge]] = [[] for _ in self._values]
        self._csr = None  # (ptr, indices, weights) cache

    # -- construction ----------------------------------------------------
    def num_vertices(self) -> int:
        return len(self._values)

    def add_vertex(self, value: Any = None) -> int:
        self._values.append(value)
        self._edges_out.append([])
        self._csr = None
        return len(self._values) - 1

    def add_edge(self, edge_or_from, to: Optional[int] = None, value: Any = None,
                 directed: bool = False) -> None:
        if isinstance(edge_or_from, Edge):
            edge = edge_or_from
        else:
            edge = Edge(int(edge_or_from), int(to), value, directed)
        n = self.num_vertices()
        if not (0 <= edge.from_idx < n and 0 <= edge.to_idx < n):
            raise ValueError(
                f"edge {edge.from_idx}->{edge.to_idx} out of range for {n} vertices")
        if not self.allow_multiple_edges:
            for e in self._edges_out[edge.from_idx]:
                if e.to_idx == edge.to_idx or (not e.directed and e.from_idx == edge.to_idx):
                    return
        self._edges_out[edge.from_idx].append(edge)
        if not edge.directed:
            # Undirected edge is visible from both endpoints (Graph.java:105-112)
            self._edges_out[edge.to_idx].append(
                Edge(edge.to_idx, edge.from_idx, edge.value, False))
        self._csr = None

    # -- queries ---------------------------------------------------------
    def get_vertex(self, idx: int) -> Vertex:
        return Vertex(idx, self._values[idx])

    def get_vertices(self, indices: Sequence[int]) -> List[Vertex]:
        return [self.get_vertex(i) for i in indices]

    def get_edges_out(self, vertex: int) -> List[Edge]:
        return list(self._edges_out[vertex])

    def get_vertex_degree(self, vertex: int) -> int:
        return len(self._edges_out[vertex])

    def vertex_degrees(self) -> np.ndarray:
        return np.array([len(e) for e in self._edges_out], dtype=np.int64)

    def get_connected_vertex_indices(self, vertex: int) -> np.ndarray:
        return np.array([e.to_idx for e in self._edges_out[vertex]], dtype=np.int64)

    def get_connected_vertices(self, vertex: int) -> List[Vertex]:
        return [self.get_vertex(e.to_idx) for e in self._edges_out[vertex]]

    def get_random_connected_vertex(self, vertex: int, rng: np.random.Generator) -> Vertex:
        edges = self._edges_out[vertex]
        if not edges:
            raise NoEdgesException(f"Vertex {vertex} has no outgoing edges")
        e = edges[int(rng.integers(0, len(edges)))]
        return self.get_vertex(e.to_idx)

    # -- CSR + vectorised walks ------------------------------------------
    def csr(self):
        """(ptr, indices, weights) arrays; ptr has length n+1."""
        if self._csr is None:
            n = self.num_vertices()
            degs = self.vertex_degrees()
            ptr = np.zeros(n + 1, dtype=np.int64)
            np.cumsum(degs, out=ptr[1:])
            indices = np.empty(int(ptr[-1]), dtype=np.int64)
            weights = np.empty(int(ptr[-1]), dtype=np.float64)
            for v, edges in enumerate(self._edges_out):
                for k, e in enumerate(edges):
                    indices[ptr[v] + k] = e.to_idx
                    weights[ptr[v] + k] = e.weight()
            self._csr = (ptr, indices, weights)
        return self._csr

    def random_walks(self, starts: np.ndarray, walk_length: int,
                     rng: np.random.Generator, weighted: bool = False,
                     self_loop_disconnected: bool = True) -> np.ndarray:
        """Generate one walk per start vertex, vectorised over all starts.

        Returns an int array of shape ``(len(starts), walk_length + 1)`` — a
        walk of length L visits L+1 vertices (``RandomWalkIterator.java``
        constructor doc). Disconnected vertices self-loop when
        ``self_loop_disconnected`` (the reference's SELF_LOOP_ON_DISCONNECTED
        fills the remainder of the walk with the stuck vertex), else raise
        :class:`NoEdgesException`.
        """
        ptr, indices, weights = self.csr()
        starts = np.asarray(starts, dtype=np.int64)
        n_walks = starts.shape[0]
        walks = np.empty((n_walks, walk_length + 1), dtype=np.int64)
        walks[:, 0] = starts
        if walk_length == 0:
            return walks
        degs = (ptr[1:] - ptr[:-1])
        if not self_loop_disconnected:
            # check reachable-from-start vertices lazily during the walk
            if np.any(degs[starts] == 0):
                bad = int(starts[np.argmax(degs[starts] == 0)])
                raise NoEdgesException(
                    f"Cannot conduct random walk: vertex {bad} has no outgoing edges")
        if len(indices) == 0:
            # edgeless graph: every vertex is stuck
            if not self_loop_disconnected:
                raise NoEdgesException("Graph has no edges")
            walks[:, 1:] = starts[:, None]
            return walks
        weighted = weighted and len(weights) > 0
        if weighted:
            gw = np.cumsum(weights)  # global cumsum; rows are contiguous slices
            row_base = gw[ptr[:-1].clip(max=len(weights) - 1)] \
                - weights[ptr[:-1].clip(max=len(weights) - 1)]  # cum before row
            row_total = np.zeros(self.num_vertices())
            nz = degs > 0
            row_total[nz] = gw[ptr[1:][nz] - 1] - row_base[nz]
        cur = starts.copy()
        for step in range(1, walk_length + 1):
            d = degs[cur]
            stuck = d == 0
            if not self_loop_disconnected and np.any(stuck):
                bad = int(cur[np.argmax(stuck)])
                raise NoEdgesException(
                    f"Cannot conduct random walk: vertex {bad} has no outgoing edges")
            safe_d = np.maximum(d, 1)
            if weighted:
                u = rng.random(n_walks)
                begins = ptr[cur]
                target = row_base[cur] + u * row_total[cur]
                pos = np.searchsorted(gw, target, side="left")
                pos = np.clip(pos, begins, np.maximum(ptr[cur + 1] - 1, begins))
                nxt = indices[np.minimum(pos, len(indices) - 1)]
            else:
                offs = rng.integers(0, safe_d)
                # stuck vertices may index past the end (ptr[v]==len(indices));
                # their result is discarded by the where() below
                nxt = indices[np.minimum(ptr[cur] + offs, len(indices) - 1)]
            cur = np.where(stuck, cur, nxt)
            walks[:, step] = cur
        return walks

    # -- misc ------------------------------------------------------------
    def __eq__(self, other):
        if not isinstance(other, Graph):
            return NotImplemented
        return (self._values == other._values
                and [[(e.from_idx, e.to_idx, e.value, e.directed) for e in lst]
                     for lst in self._edges_out]
                == [[(e.from_idx, e.to_idx, e.value, e.directed) for e in lst]
                    for lst in other._edges_out])

    def __repr__(self):
        n_slots = sum(len(e) for e in self._edges_out)
        return f"Graph(numVertices={self.num_vertices()}, numEdgeSlots={n_slots})"
