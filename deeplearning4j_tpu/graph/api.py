"""Core graph API types.

Parity with the reference's ``graph/api/`` package: ``Vertex.java``,
``Edge.java``, ``NoEdgeHandling.java``, ``exception/NoEdgesException.java``,
``exception/ParseException.java``.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import Any, Optional


class NoEdgeHandling(enum.Enum):
    """What to do when a random walk reaches a vertex with no (outgoing) edges.

    Mirrors ``graph/api/NoEdgeHandling.java``.
    """

    SELF_LOOP_ON_DISCONNECTED = "self_loop"
    EXCEPTION_ON_DISCONNECTED = "exception"


class NoEdgesException(Exception):
    """Raised when a walk hits a vertex with no outgoing edges in
    EXCEPTION_ON_DISCONNECTED mode (``graph/exception/NoEdgesException.java``)."""


class ParseException(Exception):
    """Raised on malformed graph-file lines (``graph/exception/ParseException.java``)."""


@dataclass(frozen=True)
class Vertex:
    """A vertex in a graph: integer index plus an arbitrary value
    (``graph/api/Vertex.java``)."""

    idx: int
    value: Any = None

    def vertex_id(self) -> int:
        return self.idx

    def get_value(self) -> Any:
        return self.value


@dataclass(frozen=True)
class Edge:
    """An edge, directed or undirected, with an optional value/weight
    (``graph/api/Edge.java``)."""

    from_idx: int
    to_idx: int
    value: Any = None
    directed: bool = False

    def get_from(self) -> int:
        return self.from_idx

    def get_to(self) -> int:
        return self.to_idx

    def get_value(self) -> Any:
        return self.value

    def is_directed(self) -> bool:
        return self.directed

    def weight(self) -> float:
        """Numeric weight of the edge (1.0 when the value is not numeric)."""
        if isinstance(self.value, (int, float)):
            return float(self.value)
        return 1.0
