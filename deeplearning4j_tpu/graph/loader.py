"""Graph loading from delimited files.

Parity with ``graph/data/GraphLoader.java`` and the line processors in
``graph/data/impl/`` (``DelimitedEdgeLineProcessor``,
``WeightedEdgeLineProcessor``, ``DelimitedVertexLoader``).
"""

from __future__ import annotations

from typing import Callable, List, Optional, Sequence

from deeplearning4j_tpu.graph.api import Edge, ParseException
from deeplearning4j_tpu.graph.graph import Graph


class DelimitedEdgeLineProcessor:
    """Parses "from<delim>to" lines into unweighted edges
    (``data/impl/DelimitedEdgeLineProcessor.java``)."""

    def __init__(self, delim: str = ",", directed: bool = False,
                 skip_prefixes: Sequence[str] = ("//", "#")):
        self.delim = delim
        self.directed = directed
        self.skip_prefixes = tuple(skip_prefixes)

    def process_line(self, line: str) -> Optional[Edge]:
        line = line.strip()
        if not line or any(line.startswith(p) for p in self.skip_prefixes):
            return None
        parts = line.split(self.delim)
        if len(parts) != 2:
            raise ParseException(f"Invalid line: expected 2 fields, got {len(parts)}: {line!r}")
        return Edge(int(parts[0]), int(parts[1]), None, self.directed)


class WeightedEdgeLineProcessor:
    """Parses "from<delim>to<delim>weight" lines
    (``data/impl/WeightedEdgeLineProcessor.java``)."""

    def __init__(self, delim: str = ",", directed: bool = False,
                 skip_prefixes: Sequence[str] = ("//", "#")):
        self.delim = delim
        self.directed = directed
        self.skip_prefixes = tuple(skip_prefixes)

    def process_line(self, line: str) -> Optional[Edge]:
        line = line.strip()
        if not line or any(line.startswith(p) for p in self.skip_prefixes):
            return None
        parts = line.split(self.delim)
        if len(parts) != 3:
            raise ParseException(f"Invalid line: expected 3 fields, got {len(parts)}: {line!r}")
        return Edge(int(parts[0]), int(parts[1]), float(parts[2]), self.directed)


class DelimitedVertexLoader:
    """Parses "index<delim>value" vertex lines
    (``data/impl/DelimitedVertexLoader.java``)."""

    def __init__(self, delim: str = ":", skip_prefixes: Sequence[str] = ("//", "#")):
        self.delim = delim
        self.skip_prefixes = tuple(skip_prefixes)

    def load_vertices(self, path: str) -> List[str]:
        out = {}
        with open(path, "r", encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line or any(line.startswith(p) for p in self.skip_prefixes):
                    continue
                idx, _, value = line.partition(self.delim)
                out[int(idx)] = value
        return [out.get(i) for i in range(max(out) + 1)] if out else []


class GraphLoader:
    """Static loaders (``data/GraphLoader.java``)."""

    @staticmethod
    def load_undirected_graph_edge_list_file(path: str, num_vertices: int,
                                             delim: str = ",") -> Graph:
        """Each line "0<delim>1" is one undirected edge
        (`GraphLoader.java:34-51`)."""
        proc = DelimitedEdgeLineProcessor(delim, directed=False)
        return GraphLoader.load_graph(path, proc, num_vertices)

    @staticmethod
    def load_weighted_edge_list_file(path: str, num_vertices: int,
                                     delim: str = ",", directed: bool = False) -> Graph:
        """Each line "from<delim>to<delim>weight" (`GraphLoader.java:81-126`)."""
        proc = WeightedEdgeLineProcessor(delim, directed=directed)
        return GraphLoader.load_graph(path, proc, num_vertices)

    @staticmethod
    def load_graph(path, line_processor, num_vertices: int,
                   vertices: Optional[Sequence] = None,
                   allow_multiple_edges: bool = True) -> Graph:
        g = Graph(num_vertices, allow_multiple_edges, vertices=vertices)
        with open(path, "r", encoding="utf-8") as f:
            for line in f:
                edge = line_processor.process_line(line)
                if edge is not None:
                    g.add_edge(edge)
        return g

    @staticmethod
    def load_graph_from_vertex_and_edge_files(vertex_path: str, edge_path: str,
                                              vertex_loader=None, edge_processor=None,
                                              allow_multiple_edges: bool = True) -> Graph:
        """Two-file form (`GraphLoader.java:155`)."""
        vertex_loader = vertex_loader or DelimitedVertexLoader()
        values = vertex_loader.load_vertices(vertex_path)
        edge_processor = edge_processor or DelimitedEdgeLineProcessor()
        return GraphLoader.load_graph(edge_path, edge_processor, len(values),
                                      vertices=values,
                                      allow_multiple_edges=allow_multiple_edges)
