"""Graph embeddings — DeepWalk on random walks with hierarchical softmax.

TPU-native re-design of the reference's ``deeplearning4j-graph`` module
(`deeplearning4j-graph/src/main/java/org/deeplearning4j/graph/`): the graph
structure is CSR-backed so walk generation is vectorised over all start
vertices, and DeepWalk training runs as batched, jitted hierarchical-softmax
updates (scatter-add on device) instead of the reference's per-pair hogwild
loop (`models/deepwalk/DeepWalk.java`, `models/embeddings/InMemoryGraphLookupTable.java`).
"""

from deeplearning4j_tpu.graph.api import (  # noqa: F401
    Edge,
    NoEdgeHandling,
    NoEdgesException,
    ParseException,
    Vertex,
)
from deeplearning4j_tpu.graph.graph import Graph, VertexSequence  # noqa: F401
from deeplearning4j_tpu.graph.huffman import GraphHuffman  # noqa: F401
from deeplearning4j_tpu.graph.iterator import (  # noqa: F401
    RandomWalkGraphIteratorProvider,
    RandomWalkIterator,
    WeightedRandomWalkGraphIteratorProvider,
    WeightedRandomWalkIterator,
)
from deeplearning4j_tpu.graph.deepwalk import (  # noqa: F401
    DeepWalk,
    GraphVectors,
    InMemoryGraphLookupTable,
)
from deeplearning4j_tpu.graph.loader import GraphLoader  # noqa: F401
from deeplearning4j_tpu.graph.node2vec import Node2Vec  # noqa: F401
from deeplearning4j_tpu.graph.serializer import GraphVectorSerializer  # noqa: F401
