"""Huffman tree over graph vertices for hierarchical softmax.

Semantics-parity with ``graph/models/deepwalk/GraphHuffman.java``: vertices are
weighted by degree, codes are stored LSB-first (bit ``i`` of the code is the
branch taken at depth ``i``), inner nodes are numbered by pre-order traversal
(root = 0, ``n-1`` inner nodes for ``n`` leaves), and each leaf records the
inner-node path from the root.

Adds batched, padded array exports (:meth:`path_arrays`) so the whole
hierarchical-softmax update can run as one gather/scatter on device.
"""

from __future__ import annotations

import heapq
from typing import List, Tuple

import numpy as np


class GraphHuffman:
    def __init__(self, n_vertices: int, max_code_length: int = 64):
        self.max_code_length = max_code_length
        self.n_vertices = n_vertices
        self.codes = [0] * n_vertices
        self.code_length = [0] * n_vertices
        self.inner_node_path: List[List[int]] = [[] for _ in range(n_vertices)]

    def build_tree(self, vertex_degree) -> "GraphHuffman":
        """Build the tree from per-vertex counts (degrees for DeepWalk)."""
        vertex_degree = list(vertex_degree)
        assert len(vertex_degree) == self.n_vertices
        # heap entries: (count, tiebreak, leaf_idx_or_None, left, right)
        heap: List[Tuple[int, int, object]] = []
        tie = 0
        for i, d in enumerate(vertex_degree):
            heap.append((int(d), tie, (i, None, None)))
            tie += 1
        heapq.heapify(heap)
        while len(heap) > 1:
            c1, _, left = heapq.heappop(heap)
            c2, _, right = heapq.heappop(heap)
            heapq.heappush(heap, (c1 + c2, tie, (-1, left, right)))
            tie += 1
        _, _, tree = heap[0]

        # Pre-order traversal, iterative (graphs can exceed Python recursion
        # depth): inner nodes numbered in visit order, root first.
        inner_count = -1
        # stack entries: (node, code_so_far, code_len, path_tuple)
        stack = [(tree, 0, 0, ())]
        while stack:
            (leaf, left, right), code, length, path = stack.pop()
            if left is None and right is None:
                if length >= self.max_code_length:
                    raise RuntimeError(
                        f"Cannot generate code: code length exceeds {self.max_code_length} bits")
                self.codes[leaf] = code
                self.code_length[leaf] = length
                self.inner_node_path[leaf] = list(path)
                continue
            inner_count += 1
            new_path = path + (inner_count,)
            # push right first so left is visited first (pre-order), matching
            # the reference's left-then-right recursion
            stack.append((right, code | (1 << length), length + 1, new_path))
            stack.append((left, code, length + 1, new_path))
        return self

    # -- reference API ----------------------------------------------------
    def get_code(self, vertex: int) -> int:
        return self.codes[vertex]

    def get_code_length(self, vertex: int) -> int:
        return self.code_length[vertex]

    def get_code_string(self, vertex: int) -> str:
        code, n = self.codes[vertex], self.code_length[vertex]
        return "".join("1" if (code >> i) & 1 else "0" for i in range(n))

    def get_path_inner_nodes(self, vertex: int) -> List[int]:
        return list(self.inner_node_path[vertex])

    # -- batched export ---------------------------------------------------
    def path_arrays(self):
        """Padded arrays for on-device hierarchical softmax.

        Returns ``(path_nodes, bits, mask)`` each of shape
        ``(n_vertices, L)`` with ``L = max code length used``: inner-node row
        index (0-padded), branch bit, and validity mask.
        """
        L = max(self.code_length) if self.code_length else 0
        n = self.n_vertices
        nodes = np.zeros((n, L), dtype=np.int32)
        bits = np.zeros((n, L), dtype=np.float32)
        mask = np.zeros((n, L), dtype=np.float32)
        for v in range(n):
            cl = self.code_length[v]
            mask[v, :cl] = 1.0
            for i, inner in enumerate(self.inner_node_path[v]):
                nodes[v, i] = inner
            for i in range(cl):
                bits[v, i] = (self.codes[v] >> i) & 1
        return nodes, bits, mask
