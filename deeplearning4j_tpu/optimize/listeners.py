"""Training listeners — the observer SPI every fit loop invokes.

Reference: ``optimize/listeners/``: ``ScoreIterationListener``,
``PerformanceListener.java:22`` (samples/sec, batches/sec ``:87-88``),
``EvaluativeListener.java:34``, ``CollectScoresIterationListener``,
``TimeIterationListener``, ``SleepyTrainingListener.java:28`` (latency
injection), ``CheckpointListener.java:72`` (rotation: keepLast /
saveEveryNIterations).

Listener protocol (duck-typed, matching MultiLayerNetwork/ComputationGraph
fit loops): ``iteration_done(model, iteration, epoch)``,
``on_epoch_start(model)``, ``on_epoch_end(model)``.

Reading ``model.score_`` forces a device sync, so throughput-oriented
listeners (PerformanceListener) only do it when they're about to print.
"""

from __future__ import annotations

import logging
import time
from pathlib import Path
from typing import Callable, List, Optional, Tuple

log = logging.getLogger(__name__)


class TrainingListener:
    """Base (TrainingListener/IterationListener)."""

    def iteration_done(self, model, iteration: int, epoch: int) -> None:
        pass

    def on_epoch_start(self, model) -> None:
        pass

    def on_epoch_end(self, model) -> None:
        pass


class ScoreIterationListener(TrainingListener):
    """Log score every N iterations (ScoreIterationListener)."""

    def __init__(self, print_iterations: int = 10, printer: Callable = None):
        self.print_iterations = max(1, print_iterations)
        self.printer = printer or (lambda s: log.info(s))

    def iteration_done(self, model, iteration, epoch):
        if iteration % self.print_iterations == 0:
            self.printer(f"Score at iteration {iteration} is {model.score_}")


class PerformanceListener(TrainingListener):
    """Throughput reporting (PerformanceListener.java:87-88)."""

    def __init__(self, frequency: int = 10, report_score: bool = False,
                 printer: Callable = None):
        self.frequency = max(1, frequency)
        self.report_score = report_score
        self.printer = printer or (lambda s: log.info(s))
        self._last_time: Optional[float] = None
        self._last_iter = 0
        self.last_samples_per_sec: Optional[float] = None
        self.last_batches_per_sec: Optional[float] = None

    def iteration_done(self, model, iteration, epoch):
        now = time.perf_counter()
        if self._last_time is None:
            self._last_time, self._last_iter = now, iteration
            return
        if iteration - self._last_iter >= self.frequency:
            dt = now - self._last_time
            batches = iteration - self._last_iter
            self.last_batches_per_sec = batches / dt
            batch_size = getattr(model, "last_batch_size", None)
            msg = (f"iteration {iteration}; {self.last_batches_per_sec:.1f} "
                   f"batches/sec")
            if batch_size:
                self.last_samples_per_sec = self.last_batches_per_sec * batch_size
                msg += f"; {self.last_samples_per_sec:.1f} samples/sec"
            if self.report_score:
                msg += f"; score {model.score_}"
            self.printer(msg)
            self._last_time, self._last_iter = now, iteration


class CollectScoresIterationListener(TrainingListener):
    """Collect (iteration, score) pairs (CollectScoresIterationListener)."""

    def __init__(self, frequency: int = 1):
        self.frequency = max(1, frequency)
        self.scores: List[Tuple[int, float]] = []

    def iteration_done(self, model, iteration, epoch):
        if iteration % self.frequency == 0:
            self.scores.append((iteration, model.score_))

    def export_scores(self, path, delimiter: str = ",") -> None:
        """Write collected (iteration, score) pairs
        (``CollectScoresIterationListener.exportScores``)."""
        with open(path, "w", encoding="utf-8") as fh:
            fh.write(f"iteration{delimiter}score\n")
            for it, sc in self.scores:
                fh.write(f"{it}{delimiter}{sc}\n")


class TimeIterationListener(TrainingListener):
    """ETA logging over a planned iteration count (TimeIterationListener)."""

    def __init__(self, total_iterations: int, frequency: int = 10,
                 printer: Callable = None):
        self.total = total_iterations
        self.frequency = max(1, frequency)
        self.printer = printer or (lambda s: log.info(s))
        self.start = time.time()

    def iteration_done(self, model, iteration, epoch):
        if iteration % self.frequency == 0 and iteration > 0:
            elapsed = time.time() - self.start
            remaining = elapsed / iteration * max(self.total - iteration, 0)
            self.printer(f"iteration {iteration}/{self.total}; "
                         f"ETA {remaining:.0f}s")


class EvaluativeListener(TrainingListener):
    """Periodic evaluation on a held-out iterator (EvaluativeListener.java:34).

    By default runs classification :class:`Evaluation` via
    ``model.evaluate``; pass ``evaluations`` — factories of custom
    IEvaluation-style objects (EvaluationCalibration, ROC, …: anything with
    ``eval(labels, predictions, mask=…)``) — for the reference's
    ``evalWith(IEvaluation...)`` mode: each window builds fresh evaluators
    and streams the held-out predictions through all of them.
    """

    def __init__(self, iterator, frequency: int = 1, unit: str = "epoch",
                 printer: Callable = None, evaluations=None):
        if unit not in ("epoch", "iteration"):
            raise ValueError("unit must be 'epoch' or 'iteration'")
        self.iterator = iterator
        self.frequency = max(1, frequency)
        self.unit = unit
        self.printer = printer or (lambda s: log.info(s))
        self.eval_factories = list(evaluations) if evaluations else None
        self.evaluations: List = []

    def _evaluate(self, model):
        if self.eval_factories is None:
            e = model.evaluate(self.iterator)
            self.evaluations.append(e)
            self.printer(
                f"Evaluation: accuracy={e.accuracy():.4f} f1={e.f1():.4f}")
            return
        import inspect

        import numpy as np
        evs = [f() for f in self.eval_factories]
        # detect keyword support up front — catch-and-retry would double-
        # accumulate evaluators that fail mid-eval
        takes_mask = []
        for e in evs:
            try:
                takes_mask.append(
                    "mask" in inspect.signature(e.eval).parameters)
            except (TypeError, ValueError):
                takes_mask.append(False)
        try:
            out_params = inspect.signature(model.output).parameters
        except (TypeError, ValueError):
            out_params = {}
        it = self.iterator
        if hasattr(it, "reset"):
            it.reset()
        for ds in it:
            kw = {}
            if ds.features_mask is not None and "mask" in out_params:
                kw["mask"] = ds.features_mask  # padded steps stay masked
            preds = np.asarray(model.output(ds.features, **kw))
            labels = np.asarray(ds.labels)
            for e, tm in zip(evs, takes_mask):
                if tm:
                    e.eval(labels, preds, mask=ds.labels_mask)
                else:
                    e.eval(labels, preds)
        self.evaluations.append(evs)  # always a list: stable element type
        parts = [e.stats() if hasattr(e, "stats") else repr(e) for e in evs]
        self.printer("Evaluation: " + "; ".join(parts))

    def set_callback(self, callback) -> None:
        """Post-evaluation hook (``callbacks/EvaluationCallback.java``):
        ``callback(listener, evaluations, model)`` after each window.
        ``evaluations`` is always a LIST of evaluator objects (the
        reference passes an IEvaluation[]), in both default and
        ``evaluations=`` factory mode."""
        self._callback = callback

    def iteration_done(self, model, iteration, epoch):
        if self.unit == "iteration" and iteration % self.frequency == 0:
            self._evaluate(model)
            self._fire_callback(model)

    def on_epoch_end(self, model):
        # model.epoch is already the completed-epoch count here (the fit loop
        # increments it before firing on_epoch_end).
        if self.unit == "epoch" and model.epoch % self.frequency == 0:
            self._evaluate(model)
            self._fire_callback(model)

    def _fire_callback(self, model) -> None:
        cb = getattr(self, "_callback", None)
        if cb is not None:
            last = self.evaluations[-1]
            cb(self, last if isinstance(last, list) else [last], model)


class ComposableIterationListener(TrainingListener):
    """Bundles several listeners behind one handle
    (``ComposableIterationListener.java``)."""

    def __init__(self, *listeners):
        if len(listeners) == 1 and isinstance(listeners[0], (list, tuple)):
            listeners = tuple(listeners[0])
        self.listeners = list(listeners)

    def iteration_done(self, model, iteration, epoch):
        for l in self.listeners:
            l.iteration_done(model, iteration, epoch)

    def on_epoch_start(self, model):
        for l in self.listeners:
            l.on_epoch_start(model)

    def on_epoch_end(self, model):
        for l in self.listeners:
            l.on_epoch_end(model)


class ParamAndGradientIterationListener(TrainingListener):
    """Periodic per-parameter AND per-gradient statistics
    (``ParamAndGradientIterationListener.java``): mean magnitude (and
    optionally min/max) of every parameter tensor, and of its gradient,
    every N iterations, written through ``printer`` as tab-separated
    lines.

    Gradient columns need ``gradient_batch`` — a DataSet (or ``(x, y)``
    tuple) the gradients are computed on at each window via
    ``compute_gradient_and_score``. The reference reads the last training
    gradient off the model; here the jitted donated-buffer step never
    materializes gradients to host, so a fixed probe batch supplies the
    same vanishing/exploding-gradient signal deterministically. Without
    ``gradient_batch`` only parameter columns are emitted."""

    def __init__(self, iterations: int = 1, print_header: bool = True,
                 print_mean: bool = True, print_min_max: bool = False,
                 gradient_batch=None, printer: Callable = None):
        self.iterations = max(1, iterations)
        self.print_header = print_header
        self.print_mean = print_mean
        self.print_min_max = print_min_max
        self.gradient_batch = gradient_batch
        self.printer = printer or (lambda s: log.info(s))
        self._header_done = False

    def _param_items(self, model):
        if hasattr(model, "param_table"):
            return sorted(model.param_table().items())
        return []

    def _gradient_items(self, model):
        if self.gradient_batch is None:
            return []
        import numpy as np
        ds = self.gradient_batch
        if isinstance(ds, tuple):
            x, y = ds
            grads, _ = model.compute_gradient_and_score(x, y)
        else:
            grads, _ = model.compute_gradient_and_score(
                ds.features, ds.labels,
                features_mask=ds.features_mask, labels_mask=ds.labels_mask)
        out = []
        if isinstance(grads, dict):  # ComputationGraph: vertex-name keys
            for vname in sorted(grads):
                for pname in sorted(grads[vname]):
                    out.append((f"{vname}_{pname}",
                                np.asarray(grads[vname][pname])))
        else:  # MLN: per-layer list
            for i, g in enumerate(grads):
                for pname in sorted(g):
                    out.append((f"{i}_{pname}", np.asarray(g[pname])))
        return out

    def _stat_cols(self, key, suffix=""):
        cols = []
        if self.print_mean:
            cols.append(f"{key}_{suffix}mean_mag")
        if self.print_min_max:
            cols += [f"{key}_{suffix}min", f"{key}_{suffix}max"]
        return cols

    def _stat_vals(self, arr):
        import numpy as np
        a = np.asarray(arr)
        vals = []
        if self.print_mean:
            vals.append(f"{float(np.abs(a).mean()):.6e}")
        if self.print_min_max:
            vals += [f"{float(a.min()):.6e}", f"{float(a.max()):.6e}"]
        return vals

    def iteration_done(self, model, iteration, epoch):
        if iteration % self.iterations != 0:
            return
        items = self._param_items(model)
        if not items:
            return
        grad_items = self._gradient_items(model)
        if self.print_header and not self._header_done:
            cols = ["iteration", "score"]
            for key, _ in items:
                cols += self._stat_cols(key)
            for key, _ in grad_items:
                cols += self._stat_cols(key, "grad_")
            self.printer("\t".join(cols))
            self._header_done = True
        vals = [str(iteration), f"{model.score_:.6f}"]
        for _, arr in items:
            vals += self._stat_vals(arr)
        for _, arr in grad_items:
            vals += self._stat_vals(arr)
        self.printer("\t".join(vals))


class SleepyTrainingListener(TrainingListener):
    """Latency injection for debugging/fault testing
    (SleepyTrainingListener.java:28, wired via debugLongerIterations in
    SharedTrainingWrapper:250-253)."""

    def __init__(self, timer_iteration_ms: float = 0.0, timer_epoch_ms: float = 0.0):
        self.timer_iteration_ms = timer_iteration_ms
        self.timer_epoch_ms = timer_epoch_ms

    def iteration_done(self, model, iteration, epoch):
        if self.timer_iteration_ms > 0:
            time.sleep(self.timer_iteration_ms / 1e3)

    def on_epoch_end(self, model):
        if self.timer_epoch_ms > 0:
            time.sleep(self.timer_epoch_ms / 1e3)


class CheckpointListener(TrainingListener):
    """Periodic checkpointing with rotation (CheckpointListener.java:72-144).

    ``keep_last=n`` keeps the newest n checkpoints; ``keep_every_n`` also
    retains every n-th (keepLastAndEvery). Save cadence:
    ``save_every_n_iterations`` or ``save_every_n_epochs``.
    """

    def __init__(self, model_dir, *, save_every_n_iterations: Optional[int] = None,
                 save_every_n_epochs: Optional[int] = None,
                 keep_last: Optional[int] = None,
                 keep_every_n: Optional[int] = None,
                 save_updater: bool = True):
        if save_every_n_iterations is None and save_every_n_epochs is None:
            raise ValueError("set save_every_n_iterations or save_every_n_epochs")
        if keep_last is not None and keep_last < 1:
            raise ValueError("keep_last must be >= 1 (or None to keep all)")
        self.dir = Path(model_dir)
        self.dir.mkdir(parents=True, exist_ok=True)
        self.save_every_n_iterations = save_every_n_iterations
        self.save_every_n_epochs = save_every_n_epochs
        self.keep_last = keep_last
        self.keep_every_n = keep_every_n
        self.save_updater = save_updater
        self._counter = 0

    # -- persistence ---------------------------------------------------------
    def _save(self, model, iteration, epoch):
        from deeplearning4j_tpu.util.model_serializer import write_model
        self._counter += 1
        name = f"checkpoint_{self._counter}_iter_{iteration}_epoch_{epoch}.zip"
        write_model(model, self.dir / name, save_updater=self.save_updater)
        self._rotate()

    def _checkpoints(self) -> List[Path]:
        return sorted(self.dir.glob("checkpoint_*.zip"),
                      key=lambda p: int(p.name.split("_")[1]))

    def _rotate(self):
        if self.keep_last is None:
            return
        cps = self._checkpoints()
        excess = cps[:-self.keep_last] if self.keep_last else cps
        for p in excess:
            num = int(p.name.split("_")[1])
            if self.keep_every_n and num % self.keep_every_n == 0:
                continue
            p.unlink()

    def last_checkpoint(self) -> Optional[Path]:
        cps = self._checkpoints()
        return cps[-1] if cps else None

    # -- static loaders (CheckpointListener.loadCheckpointMLN:…) ------------
    @staticmethod
    def available_checkpoints(model_dir) -> List[dict]:
        """List saved checkpoints with parsed (number, iteration, epoch)
        (``CheckpointListener.availableCheckpoints``)."""
        out = []
        for p in sorted(Path(model_dir).glob("checkpoint_*.zip"),
                        key=lambda q: int(q.name.split("_")[1])):
            parts = p.stem.split("_")
            out.append({"number": int(parts[1]), "iteration": int(parts[3]),
                        "epoch": int(parts[5]), "path": p})
        return out

    @staticmethod
    def load_checkpoint(model_dir, number: Optional[int] = None):
        """Restore a checkpointed model — the newest, or checkpoint
        ``number`` (``loadCheckpointMLN`` / ``loadLastCheckpointMLN``)."""
        from deeplearning4j_tpu.util.model_serializer import restore_model
        cps = CheckpointListener.available_checkpoints(model_dir)
        if not cps:
            raise FileNotFoundError(f"no checkpoints under {model_dir}")
        if number is None:
            return restore_model(cps[-1]["path"])
        for c in cps:
            if c["number"] == number:
                return restore_model(c["path"])
        raise FileNotFoundError(
            f"no checkpoint number {number} under {model_dir} "
            f"(available: {[c['number'] for c in cps]})")

    # -- hooks ---------------------------------------------------------------
    def iteration_done(self, model, iteration, epoch):
        if (self.save_every_n_iterations and
                iteration % self.save_every_n_iterations == 0):
            self._save(model, iteration, epoch)

    def on_epoch_end(self, model):
        # model.epoch is already the completed-epoch count here.
        ep = model.epoch
        if self.save_every_n_epochs and ep % self.save_every_n_epochs == 0:
            self._save(model, model.iteration, ep)


class OneTimeLogger:
    """Deduplicating logger (``util/OneTimeLogger.java``): each distinct
    message is emitted once per process; repeats are dropped."""

    _seen = set()

    @classmethod
    def warn(cls, message: str, *args) -> None:
        cls._log(logging.WARNING, message, args)

    @classmethod
    def info(cls, message: str, *args) -> None:
        cls._log(logging.INFO, message, args)

    @classmethod
    def _log(cls, level, message, args) -> None:
        key = (level, message)
        if key in cls._seen:
            return
        cls._seen.add(key)
        log.log(level, message, *args)

    @classmethod
    def reset(cls) -> None:
        cls._seen.clear()


class ProfilerListener(TrainingListener):
    """Captures a jax profiler trace over a window of training iterations
    (the SURVEY §5 plan: "jax profiler + per-step timing listener"; the
    reference's nearest analog is ND4J's OpExecutioner profiling modes
    toggled around runs).

    Starts tracing at iteration ``start_iteration`` and stops after
    ``n_iterations`` more, writing a TensorBoard-loadable trace directory —
    XLA op timelines, fusion boundaries, and host/device activity for the
    jitted train step. One-shot by default: re-arm with ``reset()``.
    """

    def __init__(self, log_dir: str, start_iteration: int = 3,
                 n_iterations: int = 5):
        self.log_dir = str(log_dir)
        self.start_iteration = int(start_iteration)
        self.n_iterations = max(1, int(n_iterations))
        self._active = False
        self._done = False
        self._stop_at = None
        self.last_error = None

    def reset(self) -> None:
        self._done = False

    def _start(self):
        import jax
        try:
            jax.profiler.start_trace(self.log_dir)
            self._active = True
        except Exception as e:  # backend may not support tracing (tunnels)
            self.last_error = f"{type(e).__name__}: {e}"
            self._done = True

    def _stop(self):
        import jax
        try:
            jax.profiler.stop_trace()
        except Exception as e:
            self.last_error = f"{type(e).__name__}: {e}"
        self._active = False
        self._done = True

    def iteration_done(self, model, iteration, epoch):
        # the iteration counter is cumulative across fit calls and epochs,
        # so the window spans them; epoch boundaries deliberately do NOT
        # close the trace (single-batch fit loops fire one epoch per step)
        if self._done:
            return
        if not self._active and iteration >= self.start_iteration:
            self._start()
            self._stop_at = iteration + self.n_iterations
        elif self._active and iteration >= self._stop_at:
            # block so the traced window contains real device work, not
            # just async dispatches
            try:
                model.score_
            except Exception:
                pass
            self._stop()

    def close(self) -> None:
        """Stop tracing now if the window is still open (training ended
        before ``n_iterations`` more steps ran)."""
        if self._active:
            self._stop()
