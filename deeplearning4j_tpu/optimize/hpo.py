"""Hyperparameter search (the Arbiter role in the reference ecosystem:
ParameterSpace → CandidateGenerator → ScoreFunction → OptimizationRunner).

Compact TPU-native take: a search space is a dict of named
:class:`ParameterSpace` primitives; a ``model_fn(params)`` builds a fresh
model from one sampled assignment; a ``score_fn(model, params)`` returns the
value to MINIMIZE (e.g. validation loss, ``1 - accuracy``, or an
EarlyStoppingTrainer's best score). ``RandomSearch`` samples assignments;
``GridSearch`` enumerates the product of discrete spaces. Each trial is an
independent build-train-score — on a mesh, trials can use ParallelWrapper
inside ``model_fn``/``score_fn`` like any other training code.
"""

from __future__ import annotations

import itertools
import math
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np


class ParameterSpace:
    def sample(self, rng: np.random.Generator):
        raise NotImplementedError

    def grid_values(self) -> Sequence:
        raise NotImplementedError(
            f"{type(self).__name__} is continuous; grid search needs "
            "Choice/IntRange spaces (or pass explicit grid_points)")


class Choice(ParameterSpace):
    """Discrete set of values."""

    def __init__(self, *values):
        if len(values) == 1 and isinstance(values[0], (list, tuple)):
            values = tuple(values[0])
        self.values = list(values)

    def sample(self, rng):
        return self.values[int(rng.integers(0, len(self.values)))]

    def grid_values(self):
        return list(self.values)


class IntRange(ParameterSpace):
    """Integers in [low, high] inclusive."""

    def __init__(self, low: int, high: int):
        self.low, self.high = int(low), int(high)

    def sample(self, rng):
        return int(rng.integers(self.low, self.high + 1))

    def grid_values(self):
        return list(range(self.low, self.high + 1))


class Uniform(ParameterSpace):
    """Float uniform in [low, high)."""

    def __init__(self, low: float, high: float):
        self.low, self.high = float(low), float(high)

    def sample(self, rng):
        return float(rng.uniform(self.low, self.high))


class LogUniform(ParameterSpace):
    """Log-uniform in [low, high) — the learning-rate space."""

    def __init__(self, low: float, high: float):
        self.low, self.high = float(low), float(high)

    def sample(self, rng):
        return float(math.exp(rng.uniform(math.log(self.low),
                                          math.log(self.high))))


class Trial:
    def __init__(self, params: Dict[str, Any], score: float, model=None):
        self.params = params
        self.score = score
        self.model = model

    def __repr__(self):
        return f"Trial(score={self.score:.6f}, params={self.params})"


class _BaseSearch:
    def __init__(self, space: Dict[str, ParameterSpace],
                 model_fn: Callable[[Dict[str, Any]], Any],
                 score_fn: Callable[[Any, Dict[str, Any]], float],
                 keep_models: bool = False):
        self.space = space
        self.model_fn = model_fn
        self.score_fn = score_fn
        self.keep_models = keep_models
        self.trials: List[Trial] = []

    def _run_one(self, params: Dict[str, Any]) -> Trial:
        model = self.model_fn(params)
        score = float(self.score_fn(model, params))
        t = Trial(params, score, model if self.keep_models else None)
        self.trials.append(t)
        return t

    @property
    def best(self) -> Optional[Trial]:
        done = [t for t in self.trials if np.isfinite(t.score)]
        return min(done, key=lambda t: t.score) if done else None


class RandomSearch(_BaseSearch):
    """Sample ``n_trials`` independent assignments (Arbiter's
    RandomSearchGenerator)."""

    def optimize(self, n_trials: int, seed: int = 0) -> Trial:
        rng = np.random.default_rng(seed)
        for _ in range(int(n_trials)):
            params = {k: s.sample(rng) for k, s in self.space.items()}
            self._run_one(params)
        return self.best


class GridSearch(_BaseSearch):
    """Exhaustive product over discrete spaces (GridSearchCandidateGenerator)."""

    def optimize(self) -> Trial:
        names = list(self.space)
        for combo in itertools.product(
                *(self.space[n].grid_values() for n in names)):
            self._run_one(dict(zip(names, combo)))
        return self.best
