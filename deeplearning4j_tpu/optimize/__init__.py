"""Training-loop observers and checkpoint rotation.

Reference: ``optimize/listeners/`` + ``optimize/api/TrainingListener``.
"""

from deeplearning4j_tpu.optimize.earlystopping import (  # noqa: F401
    BestScoreEpochTerminationCondition,
    ClassificationScoreCalculator,
    DataSetLossCalculator,
    EarlyStoppingConfiguration,
    EarlyStoppingResult,
    EarlyStoppingParallelTrainer,
    EarlyStoppingTrainer,
    InMemoryModelSaver,
    InvalidScoreIterationTerminationCondition,
    LocalFileModelSaver,
    MaxEpochsTerminationCondition,
    MaxScoreIterationTerminationCondition,
    MaxTimeIterationTerminationCondition,
    ScoreImprovementEpochTerminationCondition,
)
from deeplearning4j_tpu.optimize.solvers import (  # noqa: F401
    BackTrackLineSearch,
    ConjugateGradient,
    ConvexOptimizer,
    EpsTermination,
    LBFGS,
    LineGradientDescent,
    Norm2Termination,
    Solver,
    StochasticGradientDescent,
    ZeroDirection,
)
from deeplearning4j_tpu.optimize.listeners import (  # noqa: F401
    CheckpointListener,
    CollectScoresIterationListener,
    ComposableIterationListener,
    EvaluativeListener,
    ParamAndGradientIterationListener,
    PerformanceListener,
    ProfilerListener,
    ScoreIterationListener,
    SleepyTrainingListener,
    TimeIterationListener,
)
