"""Early stopping: termination conditions, score calculators, savers, trainer.

Reference: ``deeplearning4j-nn/.../earlystopping/``:
``EarlyStoppingConfiguration.java`` (builder), ``termination/`` (Max*Epochs,
MaxTime, ScoreImprovement, BestScore, InvalidScore), ``scorecalc/``
(DataSetLossCalculator, ClassificationScoreCalculator), ``saver/``
(InMemoryModelSaver, LocalFileModelSaver), and
``BaseEarlyStoppingTrainer.java:46`` (``fit():76``).
"""

from __future__ import annotations

import math
import time
from pathlib import Path
from typing import Callable, List, Optional


# ---------------------------------------------------------------- calculators
class ScoreCalculator:
    """Score to MINIMIZE on held-out data (``scorecalc/ScoreCalculator.java``)."""

    def calculate_score(self, model) -> float:
        raise NotImplementedError


class DataSetLossCalculator(ScoreCalculator):
    """Average loss over an iterator (``DataSetLossCalculator.java``)."""

    def __init__(self, iterator, average: bool = True):
        self.iterator = iterator
        self.average = average

    def calculate_score(self, model) -> float:
        total, n = 0.0, 0
        if hasattr(self.iterator, "reset"):
            self.iterator.reset()
        for ds in self.iterator:
            total += model.score(ds) * ds.num_examples()
            n += ds.num_examples()
        if n == 0:
            return float("nan")
        return total / n if self.average else total


class ClassificationScoreCalculator(ScoreCalculator):
    """1 - metric, so that better classification minimizes the score
    (``ClassificationScoreCalculator.java``). metric: accuracy | f1 |
    precision | recall."""

    def __init__(self, iterator, metric: str = "accuracy"):
        self.iterator = iterator
        self.metric = metric

    def calculate_score(self, model) -> float:
        e = model.evaluate(self.iterator)
        # the reference selects via Evaluation.Metric / scoreForMetric;
        # accept both the enum-style names (GMEASURE, MCC) and the
        # method-style ones (accuracy, f1, ...)
        try:
            return 1.0 - e.score_for_metric(self.metric)
        except ValueError:
            return 1.0 - getattr(e, self.metric)()


class RegressionScoreCalculator(ScoreCalculator):
    """Column-averaged regression metric to minimize
    (``RegressionScoreCalculator.java``). metric: MSE | MAE | RMSE | RSE |
    PC | R2 — correlation-style metrics (PC, R2) are negated so lower
    stays better."""

    _MAXIMIZED = {"PC", "R2"}

    def __init__(self, iterator, metric: str = "MSE"):
        self.iterator = iterator
        self.metric = metric

    def calculate_score(self, model) -> float:
        e = model.evaluate_regression(self.iterator)
        v = e.score_for_metric(self.metric)
        return -v if self.metric.upper() in self._MAXIMIZED else v


class ROCScoreCalculator(ScoreCalculator):
    """1 - AUC so better ranking minimizes (``ROCScoreCalculator.java``).
    roc_type: "roc" (binary), "binary" (per-output ROCBinary average) or
    "multiclass" (ROCMultiClass average); metric: "auc" or "auprc"."""

    def __init__(self, iterator, roc_type: str = "roc",
                 metric: str = "auc"):
        if roc_type not in ("roc", "binary", "multiclass"):
            raise ValueError("roc_type must be roc|binary|multiclass")
        if metric not in ("auc", "auprc"):
            raise ValueError("metric must be auc|auprc")
        self.iterator = iterator
        self.roc_type = roc_type
        self.metric = metric

    def calculate_score(self, model) -> float:
        import numpy as _np
        if self.roc_type == "roc":
            roc = model.evaluate_roc(self.iterator)
            auc = (roc.calculate_auc() if self.metric == "auc"
                   else roc.calculate_auc_pr())
        elif self.roc_type == "multiclass":
            roc = model.evaluate_roc_multi_class(self.iterator)
            n = roc.num_classes()
            vals = [(roc._single(c).calculate_auc() if self.metric == "auc"
                     else roc._single(c).calculate_auc_pr())
                    for c in range(n)]
            auc = float(_np.mean(vals)) if vals else 0.0
        else:
            roc = model.evaluate_roc_binary(self.iterator)
            n = roc.num_labels()
            vals = [(roc._single(c).calculate_auc() if self.metric == "auc"
                     else roc._single(c).calculate_auc_pr())
                    for c in range(n)]
            auc = float(_np.mean(vals)) if vals else 0.0
        return 1.0 - auc


def _resolve_layer(model, layer_ref):
    """(layer, params) for an MLN layer index or a ComputationGraph
    vertex name — the AE/VAE calculators work on both model types."""
    if isinstance(layer_ref, str):
        return model.get_layer(layer_ref), model.params[layer_ref]
    return model.layers[layer_ref], model.params[layer_ref]


def _activation_into_layer(model, layer_ref, x):
    """The exact activation the target layer sees in a normal forward:
    preceding layers applied, plus the input preprocessor configured AT
    the layer itself. ``layer_ref`` is an MLN layer index or a CG vertex
    name."""
    import numpy as _np
    if isinstance(layer_ref, str):
        # ComputationGraph: gather the vertex's input activations
        vd = model.conf.vertices[layer_ref]
        acts = model.feed_forward(x)
        ins = [_np.asarray(acts[s]) for s in vd.inputs]
        x = ins[0] if len(ins) == 1 else _np.concatenate(ins, axis=-1)
    elif layer_ref > 0:
        x = _np.asarray(model.feed_forward_to_layer(layer_ref - 1, x)[-1])
    pre = model.conf.preprocessors.get(layer_ref)
    if pre is not None:
        x = _np.asarray(pre(x))
    return x


class AutoencoderScoreCalculator(ScoreCalculator):
    """Mean reconstruction error of an AutoEncoder layer on held-out data
    (``AutoencoderScoreCalculator.java``): forward to the layer, decode,
    and score reconstruction vs input."""

    def __init__(self, iterator, layer_index=0, metric: str = "mse"):
        # layer_index: MLN layer index, or a ComputationGraph vertex name
        self.iterator = iterator
        self.layer_index = layer_index
        self.metric = metric.lower()

    def calculate_score(self, model) -> float:
        import numpy as _np
        total, n = 0.0, 0
        if hasattr(self.iterator, "reset"):
            self.iterator.reset()
        layer, params = _resolve_layer(model, self.layer_index)
        for ds in self.iterator:
            x = _activation_into_layer(model, self.layer_index,
                                       _np.asarray(ds.features))
            h = _np.asarray(layer.encode(params, x))
            recon = _np.asarray(layer.decode(params, h))
            err = ((recon - x) ** 2 if self.metric == "mse"
                   else _np.abs(recon - x))
            total += float(err.sum())
            n += x.shape[0]
        return total / n if n else float("nan")


class VAEReconErrorScoreCalculator(ScoreCalculator):
    """Mean deterministic reconstruction error of a VAE layer
    (``VAEReconErrorScoreCalculator.java``; loss-function configs only)."""

    def __init__(self, iterator, layer_index: int = 0):
        self.iterator = iterator
        self.layer_index = layer_index

    def calculate_score(self, model) -> float:
        import numpy as _np
        total, n = 0.0, 0
        if hasattr(self.iterator, "reset"):
            self.iterator.reset()
        layer, params = _resolve_layer(model, self.layer_index)
        for ds in self.iterator:
            x = _activation_into_layer(model, self.layer_index,
                                       _np.asarray(ds.features))
            err = _np.asarray(layer.reconstruction_error(params, x))
            total += float(err.sum())
            n += x.shape[0]
        return total / n if n else float("nan")


class VAEReconProbScoreCalculator(ScoreCalculator):
    """Negative mean reconstruction log-probability of a VAE layer
    (``VAEReconProbScoreCalculator.java``; probabilistic reconstruction
    distributions only) — negated so higher likelihood minimizes."""

    def __init__(self, iterator, layer_index: int = 0,
                 num_samples: int = 1, log_prob: bool = True, seed: int = 0):
        self.iterator = iterator
        self.layer_index = layer_index
        self.num_samples = num_samples
        self.log_prob = log_prob
        self.seed = seed

    def calculate_score(self, model) -> float:
        import jax as _jax
        import numpy as _np
        total, n = 0.0, 0
        if hasattr(self.iterator, "reset"):
            self.iterator.reset()
        layer, params = _resolve_layer(model, self.layer_index)
        rng = _jax.random.PRNGKey(self.seed)
        for i, ds in enumerate(self.iterator):
            x = _activation_into_layer(model, self.layer_index,
                                       _np.asarray(ds.features))
            lp = _np.asarray(layer.reconstruction_log_probability(
                params, x, _jax.random.fold_in(rng, i),
                num_samples=self.num_samples))
            if not self.log_prob:
                lp = _np.exp(lp)
            total += float(lp.sum())
            n += x.shape[0]
        return -(total / n) if n else float("nan")


# ---------------------------------------------------------------- termination
class EpochTerminationCondition:
    def initialize(self) -> None:
        """Reset state; called at the start of every fit() (the reference's
        ``TerminationCondition.initialize()``)."""

    def terminate(self, epoch: int, score: float) -> bool:
        raise NotImplementedError


class IterationTerminationCondition:
    def initialize(self) -> None:
        """Reset state; called at the start of every fit()."""

    def terminate(self, last_score: float) -> bool:
        raise NotImplementedError


class MaxEpochsTerminationCondition(EpochTerminationCondition):
    def __init__(self, max_epochs: int):
        self.max_epochs = max_epochs

    def terminate(self, epoch, score):
        return epoch + 1 >= self.max_epochs


class ScoreImprovementEpochTerminationCondition(EpochTerminationCondition):
    """Stop when no improvement for ``max_epochs_without_improvement`` epochs
    (with optional ``min_improvement`` delta)."""

    def __init__(self, max_epochs_without_improvement: int,
                 min_improvement: float = 0.0):
        self.patience = max_epochs_without_improvement
        self.min_improvement = min_improvement
        self._best: Optional[float] = None
        self._stale = 0

    def initialize(self) -> None:
        self._best = None
        self._stale = 0

    def terminate(self, epoch, score):
        if math.isnan(score):
            return False  # no fresh evaluation this epoch; don't advance staleness
        if self._best is None or (self._best - score) > self.min_improvement:
            self._best = score
            self._stale = 0
            return False
        self._stale += 1
        return self._stale >= self.patience


class BestScoreEpochTerminationCondition(EpochTerminationCondition):
    """Stop as soon as the score is at/below a target."""

    def __init__(self, best_expected_score: float):
        self.target = best_expected_score

    def terminate(self, epoch, score):
        return score <= self.target


class MaxTimeIterationTerminationCondition(IterationTerminationCondition):
    def __init__(self, max_seconds: float):
        self.max_seconds = max_seconds
        self._start = time.monotonic()

    def initialize(self) -> None:
        self._start = time.monotonic()

    def terminate(self, last_score):
        return (time.monotonic() - self._start) >= self.max_seconds


class InvalidScoreIterationTerminationCondition(IterationTerminationCondition):
    """Stop on NaN/Inf score (``InvalidScoreIterationTerminationCondition``)."""

    def terminate(self, last_score):
        return math.isnan(last_score) or math.isinf(last_score)


class MaxScoreIterationTerminationCondition(IterationTerminationCondition):
    def __init__(self, max_score: float):
        self.max_score = max_score

    def terminate(self, last_score):
        return last_score > self.max_score


# ---------------------------------------------------------------------- saver
class InMemoryModelSaver:
    """Keeps the best/latest model in memory (``saver/InMemoryModelSaver.java``).
    jax params are immutable, so 'saving' is sharing the param containers."""

    def __init__(self):
        self._best = None
        self._latest = None

    def save_best_model(self, model, score):
        self._best = (model.clone() if hasattr(model, "clone") else model, score)

    def save_latest_model(self, model, score):
        self._latest = (model.clone() if hasattr(model, "clone") else model, score)

    def get_best_model(self):
        return self._best[0] if self._best else None

    def get_latest_model(self):
        return self._latest[0] if self._latest else None


class LocalFileModelSaver:
    """Writes bestModel.zip / latestModel.zip (``saver/LocalFileModelSaver.java``)."""

    def __init__(self, directory):
        self.dir = Path(directory)
        self.dir.mkdir(parents=True, exist_ok=True)

    def save_best_model(self, model, score):
        from deeplearning4j_tpu.util.model_serializer import write_model
        write_model(model, self.dir / "bestModel.zip")

    def save_latest_model(self, model, score):
        from deeplearning4j_tpu.util.model_serializer import write_model
        write_model(model, self.dir / "latestModel.zip")

    def get_best_model(self):
        from deeplearning4j_tpu.util.model_serializer import restore_model
        p = self.dir / "bestModel.zip"
        return restore_model(p) if p.exists() else None

    def get_latest_model(self):
        from deeplearning4j_tpu.util.model_serializer import restore_model
        p = self.dir / "latestModel.zip"
        return restore_model(p) if p.exists() else None


# ------------------------------------------------------------------ config
class EarlyStoppingListener:
    """Hooks into the early-stopping loop
    (``earlystopping/listener/EarlyStoppingListener.java``)."""

    def on_start(self, config, model) -> None:
        pass

    def on_epoch(self, epoch: int, score: float, config, model) -> None:
        pass

    def on_completion(self, result) -> None:
        pass


class EarlyStoppingConfiguration:
    """Builder-style config (``EarlyStoppingConfiguration.java``)."""

    def __init__(self, *, score_calculator: ScoreCalculator,
                 epoch_termination_conditions: Optional[List[EpochTerminationCondition]] = None,
                 iteration_termination_conditions: Optional[List[IterationTerminationCondition]] = None,
                 model_saver=None,
                 evaluate_every_n_epochs: int = 1,
                 save_last_model: bool = False):
        self.score_calculator = score_calculator
        self.epoch_conditions = epoch_termination_conditions or []
        self.iteration_conditions = iteration_termination_conditions or []
        self.saver = model_saver or InMemoryModelSaver()
        self.evaluate_every_n_epochs = max(1, evaluate_every_n_epochs)
        self.save_last_model = save_last_model


class EarlyStoppingResult:
    """Outcome record (``EarlyStoppingResult.java``)."""

    def __init__(self, termination_reason: str, termination_details: str,
                 score_vs_epoch: dict, best_model_epoch: int, best_model_score: float,
                 total_epochs: int, best_model):
        self.termination_reason = termination_reason  # "EpochTerminationCondition" | "IterationTerminationCondition" | "Error"
        self.termination_details = termination_details
        self.score_vs_epoch = score_vs_epoch
        self.best_model_epoch = best_model_epoch
        self.best_model_score = best_model_score
        self.total_epochs = total_epochs
        self.best_model = best_model


class EarlyStoppingTrainer:
    """Epoch loop with held-out scoring and best-model tracking
    (``BaseEarlyStoppingTrainer.java:46``, ``fit():76``). Works for both
    MultiLayerNetwork and ComputationGraph."""

    def __init__(self, config: EarlyStoppingConfiguration, model, train_iterator):
        self.config = config
        self.model = model
        self.iterator = train_iterator

    def _fit_epoch(self) -> None:
        """One training epoch — the overridable hook subclasses reroute
        (the parallel trainer sends it through a ParallelWrapper)."""
        self.model.fit(self.iterator, epochs=1)

    def set_listener(self, listener: Optional[EarlyStoppingListener]) -> None:
        """Attach an EarlyStoppingListener (``BaseEarlyStoppingTrainer
        .setListener``)."""
        self._es_listener = listener

    def fit(self) -> EarlyStoppingResult:
        cfg = self.config
        listener = getattr(self, "_es_listener", None)
        if listener is not None:
            listener.on_start(cfg, self.model)
        for c in cfg.epoch_conditions:
            c.initialize()
        for c in cfg.iteration_conditions:
            c.initialize()
        scores: dict = {}
        best_score, best_epoch = float("inf"), -1
        epoch = 0
        last_eval = float("nan")
        reason, details = "EpochTerminationCondition", "max epochs"
        while True:
            try:
                self._fit_epoch()
            except Exception as e:
                # a raise-policy TrainingWatchdog (observe/health.py) firing
                # mid-fit ends the run as an Error termination with the best
                # model so far — the reference's BaseEarlyStoppingTrainer
                # "Error" reason, wired to real divergence detection
                from deeplearning4j_tpu.observe.health import WatchdogAlarm
                if not isinstance(e, WatchdogAlarm):
                    raise
                reason, details = "Error", str(e)
                epoch += 1
                break
            last = self.model.score_
            stop_iter = next((c for c in cfg.iteration_conditions if c.terminate(last)), None)
            if stop_iter is not None:
                reason = "IterationTerminationCondition"
                details = type(stop_iter).__name__
                epoch += 1
                break
            if cfg.save_last_model:
                # latest is saved every epoch, independent of eval cadence
                cfg.saver.save_latest_model(self.model, last_eval)
            if (epoch + 1) % cfg.evaluate_every_n_epochs == 0:
                last_eval = cfg.score_calculator.calculate_score(self.model)
                scores[epoch] = last_eval
                if last_eval < best_score:
                    best_score, best_epoch = last_eval, epoch
                    cfg.saver.save_best_model(self.model, last_eval)
                if listener is not None:
                    # fired only on epochs that actually evaluated, with the
                    # fresh score (BaseEarlyStoppingTrainer onEpoch timing)
                    listener.on_epoch(epoch, last_eval, cfg, self.model)
            # epoch termination is checked EVERY epoch (with the most recent
            # eval score), so MaxEpochs cannot overshoot when
            # evaluate_every_n_epochs > 1 (BaseEarlyStoppingTrainer.fit parity)
            stop_epoch = next(
                (c for c in cfg.epoch_conditions if c.terminate(epoch, last_eval)), None)
            if stop_epoch is not None:
                reason = "EpochTerminationCondition"
                details = type(stop_epoch).__name__
                epoch += 1
                break
            epoch += 1
        best = cfg.saver.get_best_model() or self.model
        result = EarlyStoppingResult(reason, details, scores, best_epoch,
                                     best_score, epoch, best)
        if listener is not None:
            listener.on_completion(result)
        return result


class EarlyStoppingParallelTrainer(EarlyStoppingTrainer):
    """Early stopping with mesh-parallel epoch fitting
    (``EarlyStoppingParallelTrainer.java:51``: the reference wraps the model
    in a ParallelWrapper for each epoch; here each epoch runs the
    data-parallel sharded step over the mesh)."""

    def __init__(self, config: EarlyStoppingConfiguration, model,
                 train_iterator, mesh=None, mode: str = "shared_gradients",
                 averaging_frequency: int = 5):
        super().__init__(config, model, train_iterator)
        from deeplearning4j_tpu.parallel.trainer import ParallelWrapper
        self._pw = ParallelWrapper(model, mesh, mode=mode,
                                   averaging_frequency=averaging_frequency)

    def _fit_epoch(self) -> None:
        # epochs run sharded over the mesh; the user's model is not mutated
        self._pw.fit(self.iterator, epochs=1)
