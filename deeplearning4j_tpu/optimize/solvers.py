"""Convex optimizers: SGD, line-search gradient descent, conjugate gradient,
L-BFGS, with backtracking line search.

Capability parity with the reference's ``optimize/solvers/`` family
(`BaseOptimizer.java`, `StochasticGradientDescent.java:42`,
`LineGradientDescent.java`, `ConjugateGradient.java` (Polak-Ribiere+, the
max(gamma,0) descent guarantee), `LBFGS.java` (m=4 two-loop recursion),
`BackTrackLineSearch.java` (Armijo backtracking with quadratic/cubic
interpolation, relTolx/absTolx exits)) — redesigned for XLA: the optimizer
state is a single flat parameter vector (``jax.flatten_util.ravel_pytree``),
score/gradient evaluations are one jitted closure, and the line-search loop
runs on host because its trip count is data-dependent and tiny (≤5 evals)
while each eval is a full compiled forward pass on device.
"""

from __future__ import annotations

import math
from typing import Callable, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array
ValueAndGrad = Callable[[Array], Tuple[Array, Array]]


# --------------------------------------------------------------------------
# termination conditions (optimize/terminations/ parity)
# --------------------------------------------------------------------------
class TerminationCondition:
    def terminate(self, new_score: float, old_score: float, grad: np.ndarray) -> bool:
        raise NotImplementedError


class EpsTermination(TerminationCondition):
    """Stop when the relative score improvement drops below eps."""

    def __init__(self, eps: float = 1e-5, tolerance: float = 1e-8):
        self.eps = eps
        self.tolerance = tolerance

    def terminate(self, new_score, old_score, grad):
        return (2.0 * abs(old_score - new_score)
                <= self.tolerance + self.eps * (abs(old_score) + abs(new_score)))


class Norm2Termination(TerminationCondition):
    """Stop when the gradient 2-norm drops below the threshold."""

    def __init__(self, gradient_norm_threshold: float = 1e-6):
        self.threshold = gradient_norm_threshold

    def terminate(self, new_score, old_score, grad):
        return float(np.linalg.norm(grad)) < self.threshold


class ZeroDirection(TerminationCondition):
    """Stop when the search direction is numerically zero."""

    def terminate(self, new_score, old_score, grad):
        return float(np.max(np.abs(grad))) == 0.0


# --------------------------------------------------------------------------
# line search
# --------------------------------------------------------------------------
class BackTrackLineSearch:
    """Armijo backtracking with quadratic-then-cubic interpolation.

    Minimises phi(step) = f(x - step * d) where d is a descent-compatible
    direction (slope -d.g < 0). Exits: sufficient decrease (Armijo,
    ALF=1e-4), step below the relative-tolerance floor (returns 0 → caller
    keeps x), or max iterations (returns the best step seen if it improved).
    Mirrors ``BackTrackLineSearch.java:159`` behaviourally.
    """

    ALF = 1e-4

    def __init__(self, value_fn: Callable[[Array], Array], max_iterations: int = 5,
                 step_max: float = 100.0, rel_tolx: float = 1e-7,
                 abs_tolx: float = 1e-4):
        self.value_fn = value_fn
        self.max_iterations = max_iterations
        self.step_max = step_max
        self.rel_tolx = rel_tolx
        self.abs_tolx = abs_tolx

    def optimize(self, x: Array, score0: float, grad: np.ndarray,
                 direction: np.ndarray) -> float:
        d = np.asarray(direction, dtype=np.float64)
        g = np.asarray(grad, dtype=np.float64)
        dnorm = float(np.linalg.norm(d))
        if dnorm == 0.0:
            return 0.0
        scale = 1.0
        if dnorm > self.step_max:
            # attempted step too big: scale (BackTrackLineSearch.java:195-198).
            # The returned step is rescaled so callers can apply it to the
            # ORIGINAL direction.
            scale = self.step_max / dnorm
            d = d * scale
        slope = -float(np.dot(d, g))
        if slope >= 0.0:
            return 0.0  # not a descent direction
        xs = np.asarray(x, dtype=np.float64)
        # tolerance floor from the search DIRECTION (the quantity actually
        # scaled by step), as in NR lnsrch
        test = float(np.max(np.abs(d) / np.maximum(np.abs(xs), 1.0)))
        step_min = self.rel_tolx / max(test, 1e-300)
        step, step2 = 1.0, 0.0
        score2 = score0
        best_score, best_step = score0, 0.0
        d_dev = jnp.asarray(d, dtype=x.dtype)
        for _ in range(self.max_iterations):
            if step < step_min:
                return 0.0  # jump too small; keep original params
            cand = x - step * d_dev
            if float(np.max(np.abs(step * d))) < self.abs_tolx:
                return 0.0
            score = float(self.value_fn(cand))
            if math.isfinite(score) and score < best_score:
                best_score, best_step = score, step
            # Armijo sufficient decrease
            if score <= score0 + self.ALF * step * slope:
                return step * scale
            # backtrack: quadratic on first shrink, cubic after
            if not math.isfinite(score):
                tmp = 0.1 * step
            elif step == 1.0:
                denom = 2.0 * (score - score0 - slope)
                tmp = -slope / denom if denom != 0 else 0.5 * step
            else:
                rhs1 = score - score0 - step * slope
                rhs2 = score2 - score0 - step2 * slope
                denom = step - step2
                a = (rhs1 / step**2 - rhs2 / step2**2) / denom
                b = (-step2 * rhs1 / step**2 + step * rhs2 / step2**2) / denom
                if a == 0.0:
                    tmp = -slope / (2.0 * b) if b != 0 else 0.5 * step
                else:
                    disc = b * b - 3.0 * a * slope
                    if disc < 0.0:
                        tmp = 0.5 * step
                    else:
                        tmp = (-b + math.sqrt(disc)) / (3.0 * a)
                tmp = min(tmp, 0.5 * step)
            step2, score2 = step, score
            step = max(tmp, 0.1 * step)
        # exited on maxIterations: use best step if it improved the score
        return best_step * scale if best_score < score0 else 0.0


# --------------------------------------------------------------------------
# optimizers
# --------------------------------------------------------------------------
class ConvexOptimizer:
    """Full-batch iterative optimizer over a flat parameter vector.

    ``optimize(value_and_grad, x0)`` runs up to ``max_iterations`` outer
    iterations: compute score+gradient (one jitted device call), choose a
    search direction, line-search along it, update history. Subclasses define
    the direction (``BaseOptimizer.optimize`` structure).
    """

    def __init__(self, max_iterations: int = 100, line_search_iterations: int = 5,
                 step_max: float = 100.0,
                 termination_conditions: Optional[List[TerminationCondition]] = None):
        self.max_iterations = max_iterations
        self.line_search_iterations = line_search_iterations
        self.step_max = step_max
        self.terminations = (termination_conditions
                             if termination_conditions is not None
                             else [EpsTermination(), Norm2Termination(), ZeroDirection()])
        self.score_history: List[float] = []

    # subclass hooks
    def _reset(self, n: int):
        pass

    def _direction(self, grad: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def _post_step(self, x_old, x_new, g_old, g_new, direction, step):
        pass

    def optimize(self, value_and_grad: ValueAndGrad, x0: Array) -> Array:
        x = jnp.asarray(x0)
        self._reset(x.shape[0])
        self.score_history = []
        score_dev, grad_dev = value_and_grad(x)
        score, grad = float(score_dev), np.asarray(grad_dev, dtype=np.float64)
        self.score_history.append(score)
        ls = BackTrackLineSearch(lambda p: value_and_grad(p)[0],
                                 self.line_search_iterations, self.step_max)
        for _ in range(self.max_iterations):
            d = self._direction(grad)
            step = ls.optimize(x, score, grad, d)
            if step == 0.0 and d is not grad:
                # stale curvature can make the direction fail the line search
                # (Armijo is inexact): restart from steepest descent
                self._reset(x.shape[0])
                d = grad
                step = ls.optimize(x, score, grad, d)
            if step == 0.0:
                break
            x_new = x - step * jnp.asarray(d, dtype=x.dtype)
            new_score_dev, new_grad_dev = value_and_grad(x_new)
            new_score = float(new_score_dev)
            new_grad = np.asarray(new_grad_dev, dtype=np.float64)
            self._post_step(np.asarray(x, dtype=np.float64),
                            np.asarray(x_new, dtype=np.float64),
                            grad, new_grad, d, step)
            stop = any(t.terminate(new_score, score, new_grad)
                       for t in self.terminations)
            x, score, grad = x_new, new_score, new_grad
            self.score_history.append(score)
            if stop:
                break
        self.final_score = score
        return x


class StochasticGradientDescent(ConvexOptimizer):
    """Fixed-step SGD on the flat vector (``StochasticGradientDescent.java:42``
    runs one gradient step per call; the in-network jitted train step is the
    production path — this class exists for the solver SPI)."""

    def __init__(self, learning_rate: float = 0.1, max_iterations: int = 100,
                 **kw):
        super().__init__(max_iterations=max_iterations, **kw)
        self.learning_rate = learning_rate

    def optimize(self, value_and_grad: ValueAndGrad, x0: Array) -> Array:
        x = jnp.asarray(x0)
        self.score_history = []
        score = None
        for _ in range(self.max_iterations):
            score_dev, grad_dev = value_and_grad(x)
            new_score = float(score_dev)
            if self.score_history and any(
                    t.terminate(new_score, self.score_history[-1],
                                np.asarray(grad_dev)) for t in self.terminations):
                self.score_history.append(new_score)
                break
            self.score_history.append(new_score)
            x = x - self.learning_rate * grad_dev
            score = new_score
        self.final_score = self.score_history[-1] if self.score_history else score
        return x


class LineGradientDescent(ConvexOptimizer):
    """Steepest descent with backtracking line search
    (``LineGradientDescent.java``: search direction == gradient)."""

    def _direction(self, grad):
        return grad


class ConjugateGradient(ConvexOptimizer):
    """Nonlinear CG, Polak-Ribiere+ (``ConjugateGradient.java``): gamma =
    max(((g_new - g_old) . g_new) / (g_old . g_old), 0) guarantees a descent
    direction (Nocedal & Wright Ch5); gamma == 0 degrades to steepest
    descent."""

    def _reset(self, n):
        self._search_dir = None

    def _direction(self, grad):
        return grad if self._search_dir is None else self._search_dir

    def _post_step(self, x_old, x_new, g_old, g_new, direction, step):
        gg = float(np.dot(g_old, g_old))
        dgg = float(np.dot(g_new - g_old, g_new))
        gamma = max(dgg / gg, 0.0) if gg > 0 else 0.0
        self._search_dir = g_new + gamma * np.asarray(direction, dtype=np.float64)


class LBFGS(ConvexOptimizer):
    """Limited-memory BFGS with the standard two-loop recursion
    (``LBFGS.java``, m=4; Nocedal & Wright 7.2). History pairs with
    non-positive curvature (s.y <= 0) are skipped to keep the implicit
    Hessian positive-definite."""

    def __init__(self, m: int = 4, **kw):
        super().__init__(**kw)
        self.m = m

    def _reset(self, n):
        self._s: List[np.ndarray] = []  # param diffs, most recent first
        self._y: List[np.ndarray] = []  # grad diffs, most recent first
        self._rho: List[float] = []

    def _direction(self, grad):
        q = np.array(grad, dtype=np.float64)
        if not self._s:
            return q
        alpha = []
        for s, y_, rho in zip(self._s, self._y, self._rho):
            a = rho * float(np.dot(s, q))
            q -= a * y_
            alpha.append(a)
        # initial Hessian scaling gamma = (s.y)/(y.y) of most recent pair
        s0, y0 = self._s[0], self._y[0]
        gamma = float(np.dot(s0, y0)) / max(float(np.dot(y0, y0)), 1e-300)
        r = gamma * q
        for (s, y_, rho), a in zip(
                reversed(list(zip(self._s, self._y, self._rho))), reversed(alpha)):
            beta = rho * float(np.dot(y_, r))
            r += (a - beta) * s
        return r

    def _post_step(self, x_old, x_new, g_old, g_new, direction, step):
        s = x_new - x_old
        y_ = g_new - g_old
        sy = float(np.dot(s, y_))
        if sy <= 1e-10:
            return  # curvature condition failed; skip pair
        self._s.insert(0, s)
        self._y.insert(0, y_)
        self._rho.insert(0, 1.0 / sy)
        if len(self._s) > self.m:
            self._s.pop()
            self._y.pop()
            self._rho.pop()


# --------------------------------------------------------------------------
# Solver: model-level front end
# --------------------------------------------------------------------------
_ALGOS = {
    "stochastic_gradient_descent": StochasticGradientDescent,
    "line_gradient_descent": LineGradientDescent,
    "conjugate_gradient": ConjugateGradient,
    "lbfgs": LBFGS,
}


class Solver:
    """Full-batch solver for a network, dispatching on
    ``conf.optimization_algo`` (``Solver.Builder`` →
    ``NeuralNetConfiguration.optimizationAlgo`` parity). Flattens the param
    pytree once, builds one jitted (score, grad) closure over the DataSet,
    runs the chosen optimizer, and writes the result back."""

    def __init__(self, model, algo: Optional[str] = None,
                 max_iterations: int = 100, **opt_kwargs):
        self.model = model
        self.algo = algo or getattr(model.conf.global_conf, "optimization_algo",
                                    "stochastic_gradient_descent")
        self.max_iterations = max_iterations
        self.opt_kwargs = opt_kwargs

    class Builder:
        def __init__(self):
            self._model = None
            self._algo = None
            self._max_iterations = 100

        def model(self, m):
            self._model = m
            return self

        def configure(self, conf):
            self._algo = getattr(conf, "optimization_algo", None)
            return self

        def max_iterations(self, n):
            self._max_iterations = n
            return self

        def build(self) -> "Solver":
            return Solver(self._model, self._algo, self._max_iterations)

    def optimize(self, ds) -> float:
        """Optimize the model's params on the (full-batch) DataSet; returns
        the final score."""
        from jax.flatten_util import ravel_pytree

        net = self.model
        if net.params is None:
            net.init()
        dtype = net.conf.global_conf.jnp_dtype()
        x = jnp.asarray(np.asarray(ds.features), dtype)
        y = jnp.asarray(np.asarray(ds.labels), dtype)
        mask = None if ds.features_mask is None else jnp.asarray(ds.features_mask)
        lmask = None if ds.labels_mask is None else jnp.asarray(ds.labels_mask)
        flat0, unravel = ravel_pytree(net.params)

        @jax.jit
        def vag(flat):
            def lf(f):
                loss, _ = net._loss_fn(unravel(f), net.states, x, y, None,
                                       mask, lmask, train=False)
                return loss
            return jax.value_and_grad(lf)(flat)

        if self.algo not in _ALGOS:
            raise ValueError(f"Unknown optimization algorithm: {self.algo!r} "
                             f"(choose from {sorted(_ALGOS)})")
        kwargs = dict(self.opt_kwargs)
        if self.algo != "stochastic_gradient_descent":
            kwargs.setdefault(
                "line_search_iterations",
                getattr(net.conf.global_conf, "max_num_line_search_iterations", 5))
        opt = _ALGOS[self.algo](max_iterations=self.max_iterations, **kwargs)
        flat = opt.optimize(vag, flat0)
        net.params = unravel(flat)
        self.score_history = opt.score_history
        return opt.final_score
