"""Generic sequence-embedding trainer.

Parity with `models/sequencevectors/SequenceVectors.java` (1,245 LoC;
`fit():192`, `buildVocab():108`): orchestrates vocab construction, the
elements learning algorithm, and the epoch loop with word2vec's linear
learning-rate decay. The reference's AsyncSequencer producer thread +
hogwild consumers (`:288`) are replaced by deterministic host-side batch
generation feeding jitted updates (see :mod:`learning`).

Query surface parity (`wordVectors()` side of WordVectorsImpl):
``similarity``, ``words_nearest``, ``get_word_vector``.
"""

from __future__ import annotations

from typing import Iterable, List, Optional, Sequence

import numpy as np

from deeplearning4j_tpu.nlp.learning import (
    CBOW,
    ElementsLearningAlgorithm,
    SkipGram,
    make_keep_prob,
)
from deeplearning4j_tpu.nlp.lookup import InMemoryLookupTable
from deeplearning4j_tpu.nlp.vocab import VocabCache, VocabConstructor


class SequenceVectors:
    """Trains embeddings over generic element sequences.

    Subclasses (Word2Vec, ParagraphVectors, DeepWalk's vectors) provide the
    sequence source; anything yielding lists of string tokens works.
    """

    def __init__(self, layer_size: int = 100, window: int = 5,
                 negative: int = 5, use_hierarchic_softmax: bool = False,
                 learning_rate: float = 0.025,
                 min_learning_rate: float = 1e-4,
                 min_word_frequency: int = 1, sample: float = 0.0,
                 epochs: int = 1, iterations: int = 1, seed: int = 12345,
                 elements_algorithm: Optional[ElementsLearningAlgorithm] = None):
        self.layer_size = layer_size
        self.window = window
        self.negative = negative
        # word2vec convention: with no negative sampling, hierarchical
        # softmax is the only objective left — force it on
        self.use_hs = use_hierarchic_softmax or negative <= 0
        self.learning_rate = learning_rate
        self.min_learning_rate = min_learning_rate
        self.min_word_frequency = min_word_frequency
        self.sample = sample
        self.epochs = epochs
        self.iterations = iterations
        self.seed = seed
        self.elements_algorithm = elements_algorithm
        self.vocab: Optional[VocabCache] = None
        self.lookup_table: Optional[InMemoryLookupTable] = None

    # -------------------------------------------------------------- vocab

    def build_vocab(self, sequences: Iterable[Sequence[str]]) -> VocabCache:
        """Corpus scan → VocabCache (SequenceVectors.buildVocab():108)."""
        constructor = VocabConstructor(min_word_frequency=self.min_word_frequency)
        self._set_vocab(constructor.build_vocab(sequences))
        return self.vocab

    def build_vocab_from_file(self, path: str, *, n_threads: int = 4,
                              to_lower: bool = False) -> VocabCache:
        """File-corpus fast path: the native multithreaded scan counts the
        whole file outside the GIL (whitespace tokenization — matching
        ``DefaultTokenizerFactory``), then the standard cutoff/Huffman/
        lookup pipeline runs."""
        constructor = VocabConstructor(min_word_frequency=self.min_word_frequency)
        self._set_vocab(constructor.build_vocab_from_file(
            path, n_threads=n_threads, to_lower=to_lower))
        return self.vocab

    def _set_vocab(self, vocab: VocabCache) -> None:
        self.vocab = vocab
        self.lookup_table = InMemoryLookupTable(
            self.vocab, self.layer_size, seed=self.seed,
            use_hs=self.use_hs, negative=self.negative)

    def _plain_whitespace_tokenization(self) -> bool:
        """The native scan's byte-level whitespace tokenization only matches
        an unconfigured DefaultTokenizerFactory (no pre-processor)."""
        from deeplearning4j_tpu.nlp.tokenization import DefaultTokenizerFactory
        tf = getattr(self, "tokenizer_factory", None)
        return tf is None or (type(tf) is DefaultTokenizerFactory
                              and tf._pre is None)

    def fit_file(self, path: str, *, n_threads: int = 4,
                 to_lower: bool = False) -> "SequenceVectors":
        """Train from a text file (one sentence per line).

        ``to_lower`` defaults to False — the plain DefaultTokenizerFactory
        that fit() would apply does NOT lowercase, and the two entry points
        must build the same vocabulary from the same text. Opt into
        lowercasing explicitly (ASCII-only, matching the native scan).

        With plain whitespace tokenization, vocabulary counting uses the
        native multithreaded scan and the training pass tokenizes the SAME
        way (byte-level ASCII whitespace/lowercasing), so every vocab word
        is trainable. A configured tokenizer_factory/pre-processor instead
        routes every line through that tokenizer for both vocab and
        training — identical results to the in-memory path, without the
        native counting fast path. Note the training pass materializes the
        encoded sequences in memory (as fit() always does — epochs iterate
        over them); the native scan only removes the counting pass.
        """
        if not self._plain_whitespace_tokenization():
            tf = self.tokenizer_factory  # type: ignore[attr-defined]
            with open(path, encoding="utf-8", errors="replace") as f:
                seqs = [toks for line in f
                        if (toks := tf.create(line).get_tokens())]
            return self.fit(seqs)

        if self.vocab is None:
            self.build_vocab_from_file(path, n_threads=n_threads,
                                       to_lower=to_lower)

        def lines():
            # byte-level split/lower: EXACTLY the scan's tokenization, so
            # vocab keys and training tokens can never diverge (Unicode
            # case/whitespace handled identically)
            with open(path, "rb") as f:
                for raw in f:
                    if to_lower:
                        raw = raw.lower()
                    toks = [t.decode("utf-8", errors="replace")
                            for t in raw.split()]
                    if toks:
                        yield toks

        return self.fit(lines())

    # ------------------------------------------------------------ training

    def _make_algorithm(self) -> ElementsLearningAlgorithm:
        algo = self.elements_algorithm or SkipGram()
        algo.configure(self.lookup_table, self.window, self.negative,
                       self.seed)
        return algo

    def _encode(self, seq: Sequence[str]) -> np.ndarray:
        idx = [self.vocab.index_of(w) for w in seq]
        return np.array([i for i in idx if i >= 0], np.int64)

    def fit(self, sequences: Iterable[Sequence[str]]) -> "SequenceVectors":
        seqs: List[Sequence[str]] = list(sequences)
        if self.vocab is None:
            self.build_vocab(seqs)
        algo = self._make_algorithm()
        keep = make_keep_prob(self.vocab, self.sample)
        encoded = [self._encode(s) for s in seqs]
        total_words = sum(len(s) for s in encoded) * self.epochs * self.iterations
        seen = 0
        for _epoch in range(self.epochs):
            for seq in encoded:
                if len(seq) < 1:
                    continue
                for _it in range(self.iterations):
                    frac = seen / max(total_words, 1)
                    lr = max(self.learning_rate * (1.0 - frac),
                             self.min_learning_rate)
                    algo.train_sequence(seq, lr, keep)
                    seen += len(seq)
        return self

    # -------------------------------------------------------------- query

    def get_word_vector(self, word: str) -> Optional[np.ndarray]:
        return self.lookup_table.vector(word)

    def has_word(self, word: str) -> bool:
        return self.vocab is not None and self.vocab.contains_word(word)

    def similarity(self, w1: str, w2: str) -> float:
        v1, v2 = self.get_word_vector(w1), self.get_word_vector(w2)
        if v1 is None or v2 is None:
            return float("nan")
        denom = np.linalg.norm(v1) * np.linalg.norm(v2)
        if denom == 0:
            return 0.0
        return float(np.dot(v1, v2) / denom)

    def words_nearest(self, word_or_vec, top_n: int = 10,
                      exclude: Sequence[str] = ()) -> List[str]:
        if isinstance(word_or_vec, str):
            vec = self.get_word_vector(word_or_vec)
            exclude = list(exclude) + [word_or_vec]
            if vec is None:
                return []
        else:
            vec = np.asarray(word_or_vec)
        mat = self.lookup_table.all_vectors()
        norms = np.linalg.norm(mat, axis=1) * (np.linalg.norm(vec) or 1.0)
        sims = mat @ vec / np.where(norms == 0, 1.0, norms)
        order = np.argsort(-sims)
        out = []
        excluded = set(exclude)
        for idx in order:
            w = self.vocab.word_at_index(int(idx))
            if w in excluded:
                continue
            out.append(w)
            if len(out) >= top_n:
                break
        return out

    def words_nearest_sum(self, positive: Sequence[str],
                          negative: Sequence[str], top_n: int = 10) -> List[str]:
        """Analogy query: argmax cos(v, sum(pos) - sum(neg))."""
        vec = np.zeros(self.layer_size, np.float32)
        for w in positive:
            v = self.get_word_vector(w)
            if v is not None:
                vec += v
        for w in negative:
            v = self.get_word_vector(w)
            if v is not None:
                vec -= v
        return self.words_nearest(vec, top_n,
                                  exclude=list(positive) + list(negative))
