"""SentiWordNet sentiment scoring.

Parity with ``text/corpora/sentiwordnet/SWN3.java``: loads the standard
SentiWordNet 3.0 tab-separated format (POS, id, PosScore, NegScore,
SynsetTerms, ...), aggregates per ``word#pos`` with the 1/rank-weighted
average the reference computes, and scores token lists with the same
negation-flip and seven-class polarity buckets. The data file is not
vendored (it carries its own license) — point ``SWN3`` at a local copy;
a tiny built-in lexicon keeps the class usable for tests/demos.
"""

from __future__ import annotations

import gzip
from typing import Dict, Iterable, List, Optional, Sequence

__all__ = ["SWN3"]

# minimal fallback lexicon (word#pos -> polarity in [-1, 1]) so the API
# works without the 20 MB SentiWordNet download
_BUILTIN = {
    "good#a": 0.625, "great#a": 0.75, "excellent#a": 0.875,
    "happy#a": 0.625, "love#v": 0.625, "love#n": 0.625, "like#v": 0.375,
    "wonderful#a": 0.75, "best#a": 0.875, "nice#a": 0.5,
    "bad#a": -0.625, "terrible#a": -0.75, "awful#a": -0.75,
    "horrible#a": -0.75, "hate#v": -0.75, "hate#n": -0.75,
    "worst#a": -0.875, "sad#a": -0.625, "poor#a": -0.5, "wrong#a": -0.5,
}


class SWN3:
    """Word/sentence polarity from SentiWordNet (``SWN3.java``)."""

    NEGATION_WORDS = {"could", "would", "should", "not", "isn't", "aren't",
                      "wasn't", "weren't", "haven't", "doesn't", "didn't",
                      "don't"}

    def __init__(self, senti_word_net_path: Optional[str] = None):
        if senti_word_net_path is None:
            self._dict: Dict[str, float] = dict(_BUILTIN)
        else:
            self._dict = self._load(senti_word_net_path)

    @staticmethod
    def _load(path: str) -> Dict[str, float]:
        temp: Dict[str, Dict[int, float]] = {}
        opener = gzip.open if path.endswith(".gz") else open
        with opener(path, "rt", encoding="utf-8") as fh:
            for line in fh:
                line = line.rstrip("\n")
                if not line or line.startswith("#"):
                    continue
                data = line.split("\t")
                if len(data) < 5 or not data[2] or not data[3]:
                    continue
                try:
                    score = float(data[2]) - float(data[3])
                except ValueError:
                    continue
                for w in data[4].split(" "):
                    if not w or "#" not in w:
                        continue
                    term, rank_s = w.rsplit("#", 1)
                    try:
                        rank = int(rank_s)
                    except ValueError:
                        continue
                    temp.setdefault(f"{term}#{data[0]}", {})[rank] = score
        out: Dict[str, float] = {}
        for key, ranks in temp.items():
            # 1/rank-weighted mean over synset senses (SWN3.java tail)
            total = sum(s / r for r, s in ranks.items())
            norm = sum(1.0 / r for r in ranks)
            out[key] = total / norm if norm else 0.0
        return out

    # -- scoring -------------------------------------------------------------
    def extract(self, word: str) -> float:
        """Summed polarity of a word over the n/a/r/v POS entries
        (``extract``)."""
        return sum(self._dict.get(f"{word}#{pos}", 0.0)
                   for pos in ("n", "a", "r", "v"))

    def score_tokens(self, tokens: Sequence[str]) -> float:
        """Sentence score with the reference's negation flip
        (``scoreTokens``): any negation word present inverts the sum."""
        total = sum(self.extract(t.lower()) for t in tokens)
        if any(t.lower() in self.NEGATION_WORDS for t in tokens):
            total *= -1.0
        return total

    def score(self, text: str, tokenizer_factory=None) -> float:
        if tokenizer_factory is not None:
            tokens = tokenizer_factory.create(text).get_tokens()
        else:
            tokens = text.split()
        return self.score_tokens(tokens)

    def classify(self, text: str, tokenizer_factory=None) -> str:
        return self.class_for_score(self.score(text, tokenizer_factory))

    @staticmethod
    def class_for_score(score: float) -> str:
        """Seven-bucket polarity label (``classForScore``). The
        reference's conditionals overlap ("> 0.25 && <= 0.5" vs
        "> 0 && >= 0.25"); rationalized here to contiguous monotone
        buckets with the same thresholds."""
        if score >= 0.75:
            return "strong_positive"
        if score > 0.25:
            return "positive"
        if score > 0:
            return "weak_positive"
        if score == 0:
            return "neutral"
        if score >= -0.25:
            return "weak_negative"
        if score > -0.75:
            return "negative"
        return "strong_negative"
