"""SentiWordNet sentiment scoring.

Parity with ``text/corpora/sentiwordnet/SWN3.java``: loads the standard
SentiWordNet 3.0 tab-separated format (POS, id, PosScore, NegScore,
SynsetTerms, ...), aggregates per ``word#pos`` with the 1/rank-weighted
average the reference computes, and scores token lists with the same
negation-flip and seven-class polarity buckets. The data file is not
vendored (it carries its own license) — point ``SWN3`` at a local copy;
a built-in ~220-word fallback lexicon (the common opinion core) keeps
the class usable without it.
"""

from __future__ import annotations

import gzip
from typing import Dict, Iterable, List, Optional, Sequence

__all__ = ["SWN3"]

# compact fallback lexicon (word#pos -> polarity in [-1, 1]) so the API
# works without the 20 MB SentiWordNet download: ~220 of the commonest
# English sentiment words with hand-assigned polarities (the standard
# opinion-word core every public lexicon shares; magnitudes follow the
# strong/plain/weak convention 0.875 / 0.625 / 0.375)
_BUILTIN = {
    # strong positive adjectives
    "excellent#a": 0.875, "outstanding#a": 0.875, "superb#a": 0.875,
    "magnificent#a": 0.875, "exceptional#a": 0.875, "perfect#a": 0.875,
    "brilliant#a": 0.875, "amazing#a": 0.875, "fantastic#a": 0.875,
    "best#a": 0.875, "marvelous#a": 0.875, "flawless#a": 0.875,
    "stunning#a": 0.75, "terrific#a": 0.75, "awesome#a": 0.75,
    "wonderful#a": 0.75, "great#a": 0.75, "superior#a": 0.75,
    "remarkable#a": 0.75, "impressive#a": 0.75, "delightful#a": 0.75,
    "beautiful#a": 0.75, "incredible#a": 0.75, "extraordinary#a": 0.75,
    # plain positive adjectives
    "good#a": 0.625, "happy#a": 0.625, "glad#a": 0.625, "joyful#a": 0.625,
    "pleasant#a": 0.625, "enjoyable#a": 0.625, "favorable#a": 0.625,
    "positive#a": 0.625, "reliable#a": 0.625, "friendly#a": 0.625,
    "generous#a": 0.625, "honest#a": 0.625, "successful#a": 0.625,
    "effective#a": 0.625, "efficient#a": 0.625, "elegant#a": 0.625,
    "helpful#a": 0.625, "useful#a": 0.625, "valuable#a": 0.625,
    "comfortable#a": 0.625, "clean#a": 0.5, "fresh#a": 0.5,
    "smooth#a": 0.5, "strong#a": 0.5, "safe#a": 0.5, "healthy#a": 0.5,
    "nice#a": 0.5, "lovely#a": 0.625, "fine#a": 0.5,
    "solid#a": 0.5, "fast#a": 0.375, "modern#a": 0.375, "rich#a": 0.375,
    # weak positive adjectives
    "decent#a": 0.375, "adequate#a": 0.375, "acceptable#a": 0.375,
    "satisfactory#a": 0.375, "fair#a": 0.375, "okay#a": 0.25,
    "interesting#a": 0.375, "worthy#a": 0.375, "capable#a": 0.375,
    # positive verbs
    "love#v": 0.625, "enjoy#v": 0.625, "admire#v": 0.625,
    "appreciate#v": 0.625, "delight#v": 0.625, "praise#v": 0.625,
    "recommend#v": 0.625, "adore#v": 0.75, "like#v": 0.375,
    "impress#v": 0.5, "improve#v": 0.375, "succeed#v": 0.5,
    "win#v": 0.5, "help#v": 0.375, "support#v": 0.375, "thank#v": 0.5,
    "celebrate#v": 0.5, "satisfy#v": 0.5,
    # positive nouns
    "love#n": 0.625, "joy#n": 0.625, "happiness#n": 0.625,
    "pleasure#n": 0.625, "success#n": 0.625, "triumph#n": 0.625,
    "benefit#n": 0.5, "advantage#n": 0.5,
    "masterpiece#n": 0.75, "gem#n": 0.625, "winner#n": 0.5,
    "hope#n": 0.375, "friend#n": 0.375, "gift#n": 0.375,
    "comfort#n": 0.375, "strength#n": 0.375, "quality#n": 0.375,
    # positive adverbs
    "well#r": 0.5, "nicely#r": 0.5, "perfectly#r": 0.75,
    "beautifully#r": 0.625, "happily#r": 0.5, "gladly#r": 0.5,
    "smoothly#r": 0.375, "easily#r": 0.375,
    # strong negative adjectives
    "terrible#a": -0.75, "awful#a": -0.75, "horrible#a": -0.75,
    "dreadful#a": -0.75, "atrocious#a": -0.875, "abysmal#a": -0.875,
    "appalling#a": -0.875, "worst#a": -0.875, "disastrous#a": -0.875,
    "horrendous#a": -0.875, "unbearable#a": -0.75, "disgusting#a": -0.75,
    "hideous#a": -0.75, "pathetic#a": -0.75, "useless#a": -0.75,
    "worthless#a": -0.75, "dire#a": -0.625,
    # plain negative adjectives
    "bad#a": -0.625, "poor#a": -0.5, "wrong#a": -0.5, "sad#a": -0.625,
    "unhappy#a": -0.625, "angry#a": -0.625, "ugly#a": -0.625,
    "nasty#a": -0.625, "painful#a": -0.625, "unpleasant#a": -0.625,
    "negative#a": -0.5, "harmful#a": -0.625, "dangerous#a": -0.625,
    "broken#a": -0.5, "faulty#a": -0.625, "defective#a": -0.625,
    "inferior#a": -0.625, "disappointing#a": -0.625, "annoying#a": -0.625,
    "frustrating#a": -0.625, "boring#a": -0.5, "dull#a": -0.5,
    "weak#a": -0.5, "dirty#a": -0.5, "cheap#a": -0.375, "slow#a": -0.375,
    "unreliable#a": -0.625, "dishonest#a": -0.625, "rude#a": -0.625,
    "cruel#a": -0.75, "evil#a": -0.75, "toxic#a": -0.625,
    # weak negative adjectives
    "mediocre#a": -0.375, "flawed#a": -0.375, "questionable#a": -0.375,
    "awkward#a": -0.375, "messy#a": -0.375, "noisy#a": -0.25,
    "uncomfortable#a": -0.375, "confusing#a": -0.375,
    # negative verbs
    "hate#v": -0.75, "despise#v": -0.75, "loathe#v": -0.875,
    "detest#v": -0.75, "dislike#v": -0.5, "fail#v": -0.5,
    "disappoint#v": -0.625, "annoy#v": -0.5, "irritate#v": -0.5,
    "hurt#v": -0.5, "harm#v": -0.5, "damage#v": -0.5,
    "ruin#v": -0.625, "destroy#v": -0.625,
    "complain#v": -0.375, "suffer#v": -0.5, "worry#v": -0.375,
    "regret#v": -0.5, "blame#v": -0.375, "deceive#v": -0.625,
    "mislead#v": -0.5, "break#v": -0.375,
    # negative nouns
    "hate#n": -0.75, "hatred#n": -0.75, "failure#n": -0.625,
    "disaster#n": -0.75, "catastrophe#n": -0.75, "tragedy#n": -0.75,
    "problem#n": -0.375, "issue#n": -0.25, "defect#n": -0.5,
    "flaw#n": -0.375, "fault#n": -0.375,
    "loss#n": -0.5, "pain#n": -0.5, "misery#n": -0.625, "grief#n": -0.625,
    "anger#n": -0.5, "fear#n": -0.5, "disgust#n": -0.625,
    "disappointment#n": -0.625, "complaint#n": -0.375, "waste#n": -0.5,
    "garbage#n": -0.625, "junk#n": -0.5, "scam#n": -0.75, "fraud#n": -0.75,
    "liar#n": -0.625, "enemy#n": -0.5, "threat#n": -0.5, "crisis#n": -0.5,
    # negative adverbs
    "badly#r": -0.5, "poorly#r": -0.5, "terribly#r": -0.625,
    "horribly#r": -0.75, "sadly#r": -0.5, "painfully#r": -0.5,
    "wrongly#r": -0.5,
}


class SWN3:
    """Word/sentence polarity from SentiWordNet (``SWN3.java``)."""

    NEGATION_WORDS = {"could", "would", "should", "not", "hardly",
                      "barely", "isn't", "aren't",
                      "wasn't", "weren't", "haven't", "doesn't", "didn't",
                      "don't"}

    def __init__(self, senti_word_net_path: Optional[str] = None):
        if senti_word_net_path is None:
            self._dict: Dict[str, float] = dict(_BUILTIN)
        else:
            self._dict = self._load(senti_word_net_path)

    @staticmethod
    def _load(path: str) -> Dict[str, float]:
        temp: Dict[str, Dict[int, float]] = {}
        opener = gzip.open if path.endswith(".gz") else open
        with opener(path, "rt", encoding="utf-8") as fh:
            for line in fh:
                line = line.rstrip("\n")
                if not line or line.startswith("#"):
                    continue
                data = line.split("\t")
                if len(data) < 5 or not data[2] or not data[3]:
                    continue
                try:
                    score = float(data[2]) - float(data[3])
                except ValueError:
                    continue
                for w in data[4].split(" "):
                    if not w or "#" not in w:
                        continue
                    term, rank_s = w.rsplit("#", 1)
                    try:
                        rank = int(rank_s)
                    except ValueError:
                        continue
                    temp.setdefault(f"{term}#{data[0]}", {})[rank] = score
        out: Dict[str, float] = {}
        for key, ranks in temp.items():
            # 1/rank-weighted mean over synset senses (SWN3.java tail)
            total = sum(s / r for r, s in ranks.items())
            norm = sum(1.0 / r for r in ranks)
            out[key] = total / norm if norm else 0.0
        return out

    # -- scoring -------------------------------------------------------------
    def extract(self, word: str) -> float:
        """Summed polarity of a word over the n/a/r/v POS entries
        (``extract``)."""
        return sum(self._dict.get(f"{word}#{pos}", 0.0)
                   for pos in ("n", "a", "r", "v"))

    def score_tokens(self, tokens: Sequence[str]) -> float:
        """Sentence score with the reference's negation flip
        (``scoreTokens``): any negation word present inverts the sum."""
        total = sum(self.extract(t.lower()) for t in tokens)
        if any(t.lower() in self.NEGATION_WORDS for t in tokens):
            total *= -1.0
        return total

    def score(self, text: str, tokenizer_factory=None) -> float:
        if tokenizer_factory is not None:
            tokens = tokenizer_factory.create(text).get_tokens()
        else:
            tokens = text.split()
        return self.score_tokens(tokens)

    def classify(self, text: str, tokenizer_factory=None) -> str:
        return self.class_for_score(self.score(text, tokenizer_factory))

    @staticmethod
    def class_for_score(score: float) -> str:
        """Seven-bucket polarity label (``classForScore``). The
        reference's conditionals overlap ("> 0.25 && <= 0.5" vs
        "> 0 && >= 0.25"); rationalized here to contiguous monotone
        buckets with the same thresholds."""
        if score >= 0.75:
            return "strong_positive"
        if score > 0.25:
            return "positive"
        if score > 0:
            return "weak_positive"
        if score == 0:
            return "neutral"
        if score >= -0.25:
            return "weak_negative"
        if score > -0.75:
            return "negative"
        return "strong_negative"
