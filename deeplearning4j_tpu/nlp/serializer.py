"""Word-vector serialization.

Parity with `models/embeddings/loader/WordVectorSerializer.java:92`: the
word2vec C text format (`writeWordVectors` / `loadTxtVectors`) and a full
zip model format (vocab with frequencies + Huffman structure + syn0/syn1)
mirroring `writeWord2VecModel`/`readWord2Vec`.
"""

from __future__ import annotations

import base64
import json
import os
import zipfile
from typing import Optional

import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp.lookup import InMemoryLookupTable
from deeplearning4j_tpu.nlp.sequencevectors import SequenceVectors
from deeplearning4j_tpu.nlp.vocab import VocabCache, VocabWord
from deeplearning4j_tpu.nlp.word2vec import Word2Vec


def _escape(word: str) -> str:
    """Words with whitespace/colons get DL4J's "B64:" wrapping
    (WordVectorSerializer's ReadHelper convention) so the text format stays
    space-delimited and lossless."""
    if any(c in word for c in " \t:"):
        return "B64:" + base64.b64encode(word.encode("utf-8")).decode("ascii")
    return word


def _unescape(word: str) -> str:
    if word.startswith("B64:"):
        return base64.b64decode(word[4:]).decode("utf-8")
    return word


class WordVectorSerializer:
    # ------------------------------------------------------- text format

    @staticmethod
    def write_word_vectors(model: SequenceVectors, path: str) -> None:
        """word2vec C text format: header 'vocab dim', then 'word v1 ...'."""
        mat = model.lookup_table.all_vectors()
        with open(path, "w", encoding="utf-8") as fh:
            fh.write(f"{mat.shape[0]} {mat.shape[1]}\n")
            for i in range(mat.shape[0]):
                word = _escape(model.vocab.word_at_index(i))
                vec = " ".join(f"{v:.6f}" for v in mat[i])
                fh.write(f"{word} {vec}\n")

    @staticmethod
    def load_txt_vectors(path: str) -> SequenceVectors:
        with open(path, "r", encoding="utf-8") as fh:
            first = fh.readline().split()
            has_header = len(first) == 2 and all(t.isdigit() for t in first)
            rows = []
            words = []
            if not has_header:
                words.append(_unescape(first[0]))
                rows.append([float(v) for v in first[1:]])
            for line in fh:
                parts = line.rstrip("\n").split(" ")
                if len(parts) < 2:
                    continue
                words.append(_unescape(parts[0]))
                rows.append([float(v) for v in parts[1:]])
        mat = np.asarray(rows, np.float32)
        cache = VocabCache()
        for w in words:
            cache.add_token(VocabWord(w))
        # preserve file order, not frequency order
        cache._by_index = [cache.word_for(w) for w in words]
        for i, vw in enumerate(cache._by_index):
            vw.index = i
        model = SequenceVectors(layer_size=mat.shape[1])
        model.vocab = cache
        model.lookup_table = InMemoryLookupTable(cache, mat.shape[1],
                                                 init_syn0=False)
        model.lookup_table.syn0 = jnp.asarray(mat)
        return model

    # ----------------------------------------- Google word2vec binary format

    @staticmethod
    def write_binary_model(model: SequenceVectors, path: str,
                           compress: Optional[bool] = None) -> None:
        """Write the Google word2vec C binary format (the original
        ``word2vec.c`` layout, the de-facto pretrained-embedding
        interchange): ASCII header ``"<words> <size>\\n"``, then per word
        ``word + b' '`` followed by ``size`` packed little-endian float32s
        and a newline. ``compress`` (default: from a ``.gz`` suffix) gzips
        the stream, the GoogleNews-vectors distribution style."""
        import gzip as _gzip

        if compress is None:
            compress = path.endswith(".gz")
        mat = np.asarray(model.lookup_table.all_vectors(), np.float32)
        opener = _gzip.open if compress else open
        with opener(path, "wb") as fh:
            fh.write(f"{mat.shape[0]} {mat.shape[1]}\n".encode("utf-8"))
            for i in range(mat.shape[0]):
                word = model.vocab.word_at_index(i)
                fh.write(word.encode("utf-8") + b" ")
                fh.write(mat[i].astype("<f4").tobytes())
                fh.write(b"\n")

    @staticmethod
    def read_binary_model(path: str, linebreaks: Optional[bool] = None,
                          normalize: bool = False) -> SequenceVectors:
        """Read a Google word2vec C binary file
        (``WordVectorSerializer.readBinaryModel``,
        ``WordVectorSerializer.java:165``): header words/size as ASCII,
        each word terminated by a space (``readString:282`` stops at space
        or newline), then packed little-endian float32s (``getFloat:265``).

        ``linebreaks=None`` auto-detects the per-word trailing newline
        variant (the C tool writes one; some exporters don't — the
        reference makes the caller choose, ``loadGoogleModel:117``).
        ``normalize=True`` unit-normalizes each vector on load, matching
        the reference's ``Transforms.unitVec`` path."""
        import gzip as _gzip

        with open(path, "rb") as probe:
            magic = probe.read(2)
        opener = _gzip.open if magic == b"\x1f\x8b" else open
        with opener(path, "rb") as fh:
            data = fh.read()

        def token(pos):
            end = pos
            while data[end] not in (0x20, 0x0A):
                end += 1
            return data[pos:end], end + 1

        head, pos = token(0)
        n_words = int(head)
        head, pos = token(pos)
        size = int(head)
        words, rows = [], np.empty((n_words, size), np.float32)
        for i in range(n_words):
            # skip the previous row's newline (linebreaks variant); words
            # themselves can't start with \n
            if linebreaks is not False and pos < len(data) \
                    and data[pos] == 0x0A:
                pos += 1
            raw, pos = token(pos)
            words.append(raw.decode("utf-8"))
            rows[i] = np.frombuffer(data, "<f4", count=size, offset=pos)
            pos += 4 * size
            if linebreaks is True:
                pos += 1
        if normalize:
            norms = np.linalg.norm(rows, axis=1, keepdims=True)
            rows = rows / np.maximum(norms, 1e-12)
        cache = VocabCache()
        for w in words:
            cache.add_token(VocabWord(w))
        cache._by_index = [cache.word_for(w) for w in words]
        for i, vw in enumerate(cache._by_index):
            vw.index = i
        model = SequenceVectors(layer_size=size)
        model.vocab = cache
        model.lookup_table = InMemoryLookupTable(cache, size, init_syn0=False)
        model.lookup_table.syn0 = jnp.asarray(rows)
        return model

    @staticmethod
    def load_static_model(path: str) -> SequenceVectors:
        """``WordVectorSerializer.loadStaticModel:2481``: inference-only
        word vectors from ANY supported artifact — tries this framework's
        zip model, then the C text format, then the Google binary format
        (the reference's exact fallback order: dl4j zip → csv → binary)."""
        try:
            return WordVectorSerializer.read_word2vec_model(path)
        except (zipfile.BadZipFile, KeyError, OSError):
            pass
        try:
            return WordVectorSerializer.load_txt_vectors(path)
        except (UnicodeDecodeError, ValueError, IndexError):
            pass
        try:
            return WordVectorSerializer.read_binary_model(path)
        except Exception as e:
            raise ValueError(
                f"Unable to guess input file format for {path!r} (tried "
                "zip model, text vectors, Google binary)") from e

    # -------------------------------------------------------- zip format

    @staticmethod
    def write_word2vec_model(model: SequenceVectors, path: str) -> None:
        """Zip with config + vocab (freq/huffman) + syn0/syn1neg npy."""
        meta = {
            "layer_size": model.layer_size,
            "window": model.window,
            "negative": model.negative,
            "use_hs": model.use_hs,
            "learning_rate": model.learning_rate,
            "min_word_frequency": model.min_word_frequency,
        }
        vocab = [{
            "word": vw.word, "frequency": vw.frequency,
            "code": vw.code, "points": vw.points, "is_label": vw.is_label,
        } for vw in model.vocab.vocab_words()]
        with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as zf:
            zf.writestr("config.json", json.dumps(meta))
            zf.writestr("vocab.json", json.dumps(vocab))
            import io
            for name, arr in [("syn0", model.lookup_table.syn0),
                              ("syn1", model.lookup_table.syn1),
                              ("syn1neg", model.lookup_table.syn1neg)]:
                if arr is None:
                    continue
                buf = io.BytesIO()
                np.save(buf, np.asarray(arr))
                zf.writestr(f"{name}.npy", buf.getvalue())

    @staticmethod
    def read_word2vec_model(path: str) -> Word2Vec:
        with zipfile.ZipFile(path, "r") as zf:
            meta = json.loads(zf.read("config.json"))
            vocab_entries = json.loads(zf.read("vocab.json"))
            arrays = {}
            import io
            for name in ("syn0", "syn1", "syn1neg"):
                try:
                    arrays[name] = np.load(io.BytesIO(zf.read(f"{name}.npy")))
                except KeyError:
                    arrays[name] = None
        cache = VocabCache()
        for e in vocab_entries:
            vw = VocabWord(e["word"], e["frequency"], e.get("is_label", False))
            vw.code = e["code"]
            vw.points = e["points"]
            cache.add_token(vw)
        cache._by_index = [cache.word_for(e["word"]) for e in vocab_entries]
        for i, vw in enumerate(cache._by_index):
            vw.index = i
        model = Word2Vec(
            layer_size=meta["layer_size"], window_size=meta["window"],
            negative_sample=meta["negative"],
            use_hierarchic_softmax=meta["use_hs"],
            learning_rate=meta["learning_rate"],
            min_word_frequency=meta["min_word_frequency"])
        model.vocab = cache
        model.lookup_table = InMemoryLookupTable(
            cache, meta["layer_size"], use_hs=meta["use_hs"],
            negative=meta["negative"], init_syn0=False)
        model.lookup_table.syn0 = jnp.asarray(arrays["syn0"])
        if arrays["syn1"] is not None:
            model.lookup_table.syn1 = jnp.asarray(arrays["syn1"])
        if arrays["syn1neg"] is not None:
            model.lookup_table.syn1neg = jnp.asarray(arrays["syn1neg"])
        return model
