"""Dictionary-backed morphological tokenizer — MeCab-format lattice Viterbi.

The reference vendors kuromoji (``deeplearning4j-nlp-japanese``, 6.9k LoC)
and ansj (``deeplearning4j-nlp-chinese``) — dictionary-driven lattice
segmenters. This module provides the same MECHANISM behind the existing
:class:`~deeplearning4j_tpu.nlp.tokenization.TokenizerFactory` SPI: load a
MeCab-format dictionary (the format kuromoji/ipadic/unidic ship in) and
segment by minimum-cost Viterbi over the word lattice — word costs plus
left/right connection costs, exactly kuromoji's decoding objective.

What is NOT bundled: the dictionaries themselves. ipadic/unidic are tens of
MB; kuromoji-level ACCURACY requires pointing ``MorphologicalDictionary.load``
at a real dictionary directory (``*.csv`` entries + ``matrix.def``). With the
small test dictionary in ``tests/fixtures/mini_ja_dict/`` the lattice
machinery is exercised end to end (including the classic
すもももももももものうち disambiguation that greedy longest-match gets
wrong).

File formats (MeCab conventions):

- entries CSV: ``surface,left_id,right_id,word_cost,feature1,feature2,…``
  — for ipadic the 7th feature (index 6) is the base form.
- ``matrix.def``: first line ``L R``; then ``right_id left_id cost`` rows;
  the connection cost between adjacent words a→b is
  ``matrix[a.right_id][b.left_id]``.
"""

from __future__ import annotations

import csv
import os
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from deeplearning4j_tpu.nlp.tokenization import (
    TokenPreProcess,
    Tokenizer,
    TokenizerFactory,
)


@dataclass
class DictEntry:
    surface: str
    left_id: int
    right_id: int
    cost: int
    features: Tuple[str, ...] = ()

    @property
    def base_form(self) -> str:
        """ipadic convention: feature index 6; '*' or absent → surface."""
        if len(self.features) > 6 and self.features[6] not in ("", "*"):
            return self.features[6]
        return self.surface


@dataclass
class CharCategoryDef:
    """One character category's unknown-word behavior — the kuromoji
    ``char.def`` attribute triple plus the ``unk.def`` entry costs:

    - ``invoke``: propose unknown nodes at this position even when the
      dictionary has entries there (kuromoji INVOKE; katakana/alpha runs
      must compete with partial dictionary matches).
    - ``group``: one unknown node spanning the whole same-category run
      (kuromoji GROUP; the behavior that keeps an out-of-lexicon
      テレビゲーム one token instead of six).
    - ``length``: additionally propose prefixes of 1..length chars
      (kuromoji LENGTH; kanji sequences segment best in short pieces).
    - ``cost``/``left_id``/``right_id``: the unk.def lattice entry.
    """

    invoke: bool = False
    group: bool = True
    length: int = 0
    cost: Optional[int] = None  # None → the dictionary's unk_cost
    left_id: int = 0
    right_id: int = 0


def char_category(ch: str) -> str:
    """kuromoji char.def category (the subset that changes segmentation)."""
    o = ord(ch)
    if 0x4E00 <= o <= 0x9FFF or 0x3400 <= o <= 0x4DBF or o == 0x3005:
        return "KANJI"
    if 0x3040 <= o <= 0x309F:
        return "HIRAGANA"
    if 0x30A0 <= o <= 0x30FF or o == 0x30FC:
        return "KATAKANA"
    if 0xAC00 <= o <= 0xD7AF:
        return "HANGUL"
    if ch.isdigit():
        return "NUMERIC"
    if ch.isalpha() and o < 0x3000:
        return "ALPHA"
    if ch.isspace():
        return "SPACE"
    return "DEFAULT"


#: kuromoji's stock char.def attributes for the categories above (ipadic
#: defaults: KANJI 0 0 2, HIRAGANA/KATAKANA grouped, ALPHA/NUMERIC 1 1 0).
DEFAULT_CATEGORIES: Dict[str, CharCategoryDef] = {
    "KANJI": CharCategoryDef(invoke=False, group=False, length=2,
                             cost=22000),
    "HIRAGANA": CharCategoryDef(invoke=False, group=True, length=2,
                                cost=21000),
    "KATAKANA": CharCategoryDef(invoke=True, group=True, length=0,
                                cost=14000),
    "HANGUL": CharCategoryDef(invoke=False, group=True, length=2,
                              cost=21000),
    "NUMERIC": CharCategoryDef(invoke=True, group=True, length=0,
                               cost=14000),
    "ALPHA": CharCategoryDef(invoke=True, group=True, length=0, cost=14000),
    "SPACE": CharCategoryDef(invoke=False, group=True, length=0, cost=0),
    "DEFAULT": CharCategoryDef(invoke=False, group=True, length=1,
                               cost=22000),
}

UNK_FEATURE = "UNK"


class MorphologicalDictionary:
    """Entries indexed by first character + connection-cost matrix."""

    def __init__(self, entries: Iterable[DictEntry],
                 connections: Optional[Dict[Tuple[int, int], int]] = None,
                 unk_cost: int = 20000,
                 categories: Optional[Dict[str, CharCategoryDef]] = None):
        # surface-keyed index: lookup is O(max_len) hash probes per text
        # position, independent of dictionary size — scales to real
        # ipadic/unidic builds (~400k entries)
        self._by_surface: Dict[str, List[DictEntry]] = {}
        self.max_len = 1
        for e in entries:
            if not e.surface:
                continue
            self._by_surface.setdefault(e.surface, []).append(e)
            self.max_len = max(self.max_len, len(e.surface))
        self.connections = connections or {}
        self.unk_cost = unk_cost
        self.categories = dict(DEFAULT_CATEGORIES if categories is None
                               else categories)

    # ------------------------------------------------------------- loading
    @staticmethod
    def load(path: str, encoding: str = "utf-8",
             unk_cost: int = 20000) -> "MorphologicalDictionary":
        """Load a MeCab-format dictionary directory (or a single CSV file):
        every ``*.csv`` holds entries; ``matrix.def`` holds connection costs.
        Point this at a real ipadic/unidic build for kuromoji-level accuracy.
        """
        csv_paths: List[str] = []
        matrix_path = None
        if os.path.isdir(path):
            for name in sorted(os.listdir(path)):
                p = os.path.join(path, name)
                if name.endswith(".csv"):
                    csv_paths.append(p)
                elif name == "matrix.def":
                    matrix_path = p
        else:
            csv_paths.append(path)
        entries: List[DictEntry] = []
        for p in csv_paths:
            with open(p, encoding=encoding, newline="") as f:
                for row in csv.reader(f):
                    if len(row) < 4 or row[0].startswith("#"):
                        continue
                    entries.append(DictEntry(
                        surface=row[0], left_id=int(row[1]),
                        right_id=int(row[2]), cost=int(row[3]),
                        features=tuple(row[4:])))
        connections: Dict[Tuple[int, int], int] = {}
        if matrix_path is not None:
            with open(matrix_path, encoding=encoding) as f:
                first = True
                for line in f:
                    parts = line.split()
                    if not parts:
                        continue
                    if first:
                        first = False  # "L R" size header
                        continue
                    r, l, c = int(parts[0]), int(parts[1]), int(parts[2])
                    connections[(r, l)] = c
        return MorphologicalDictionary(entries, connections, unk_cost)

    # ------------------------------------------------------------- lookup
    def lookup(self, text: str, i: int) -> List[DictEntry]:
        """Dictionary entries whose surface starts at ``text[i]`` — longest
        first, bounded by ``max_len``."""
        out: List[DictEntry] = []
        top = min(self.max_len, len(text) - i)
        for L in range(top, 0, -1):
            out.extend(self._by_surface.get(text[i:i + L], ()))
        return out

    def connection(self, right_id: int, left_id: int) -> int:
        return self.connections.get((right_id, left_id), 0)

    def unknown_candidates(self, text: str, i: int,
                           has_dict_entries: bool) -> List[DictEntry]:
        """kuromoji's unknown-word processing (char.def + unk.def role):
        typed unknown nodes proposed from the character category at ``i``.
        Without this, out-of-lexicon spans degrade to per-character soup
        regardless of dictionary quality. Unknown entries carry features
        ``(UNK_FEATURE, category)`` so downstream consumers can tell them
        from lexicon hits."""
        cat = char_category(text[i])
        cfg = self.categories.get(cat)
        if cfg is None:
            cfg = self.categories.get("DEFAULT", CharCategoryDef())
        if has_dict_entries and not cfg.invoke:
            return []
        # maximal same-category run from i (the GROUP span)
        end = i + 1
        n = len(text)
        while end < n and char_category(text[end]) == cat:
            end += 1
        run_len = end - i
        lengths = []
        if cfg.group:
            lengths.append(run_len)
        for k in range(1, min(cfg.length, run_len) + 1):
            if k not in lengths:
                lengths.append(k)
        if not lengths:  # never dead-end the lattice
            lengths = [1]
        base = cfg.cost if cfg.cost is not None else self.unk_cost
        return [DictEntry(text[i:i + k], cfg.left_id, cfg.right_id,
                          base, features=(UNK_FEATURE, cat))
                for k in lengths]


_BOS_EOS_ID = 0


@dataclass
class _Node:
    entry: DictEntry
    total: int = 0
    prev: Optional["_Node"] = None


def viterbi_segment(text: str,
                    dictionary: MorphologicalDictionary) -> List[DictEntry]:
    """Minimum-cost path through the word lattice (kuromoji's decoding):
    cost = Σ word_cost + Σ connection(prev.right_id, next.left_id).
    Out-of-lexicon spans are covered by TYPED unknown nodes from the
    character-category config (``MorphologicalDictionary.unknown_candidates``
    — kuromoji's char.def/unk.def processing: grouped katakana/alpha/numeric
    runs, short kanji pieces), so unknown text yields one node per unknown
    WORD, not per character."""
    n = len(text)
    bos = _Node(DictEntry("", _BOS_EOS_ID, _BOS_EOS_ID, 0))
    # ends_at[i]: best nodes whose surface ends at position i
    ends_at: List[List[_Node]] = [[] for _ in range(n + 1)]
    ends_at[0] = [bos]
    for i in range(n):
        if not ends_at[i]:
            continue  # unreachable position
        candidates = dictionary.lookup(text, i)
        candidates = candidates + dictionary.unknown_candidates(
            text, i, bool(candidates))
        for entry in candidates:
            best_prev, best_total = None, None
            for prev in ends_at[i]:
                total = (prev.total + entry.cost
                         + dictionary.connection(prev.entry.right_id,
                                                 entry.left_id))
                if best_total is None or total < best_total:
                    best_prev, best_total = prev, total
            node = _Node(entry, best_total, best_prev)
            end = i + len(entry.surface)
            ends_at[end].append(node)
    # EOS: pick the cheapest path reaching n (counting the final connection)
    best, best_total = None, None
    for node in ends_at[n]:
        total = node.total + dictionary.connection(node.entry.right_id,
                                                   _BOS_EOS_ID)
        if best_total is None or total < best_total:
            best, best_total = node, total
    if best is None:  # only possible for empty text
        return []
    path: List[DictEntry] = []
    cur = best
    while cur is not None and cur.prev is not None:
        path.append(cur.entry)
        cur = cur.prev
    path.reverse()
    return path


class DictionaryTokenizerFactory(TokenizerFactory):
    """MeCab-dictionary Viterbi tokenizer behind the TokenizerFactory SPI
    (the kuromoji ``JapaneseTokenizerFactory`` / ansj role, with a LOADED
    dictionary instead of a vendored one).

    ``use_base_form`` mirrors the kuromoji factory's baseform mode: emit
    the dictionary's base form (ipadic feature 7) instead of the surface.
    """

    def __init__(self, dictionary: MorphologicalDictionary,
                 use_base_form: bool = False,
                 keep_whitespace: bool = False,
                 pre_processor: Optional[TokenPreProcess] = None):
        self.dictionary = dictionary
        self.use_base_form = use_base_form
        self.keep_whitespace = keep_whitespace
        self._pre = pre_processor

    @staticmethod
    def from_path(path: str, **kw) -> "DictionaryTokenizerFactory":
        return DictionaryTokenizerFactory(MorphologicalDictionary.load(path),
                                          **kw)

    def create(self, sentence: str) -> Tokenizer:
        entries = viterbi_segment(sentence, self.dictionary)
        tokens = []
        for e in entries:
            if not self.keep_whitespace and e.surface.isspace():
                continue
            tokens.append(e.base_form if self.use_base_form else e.surface)
        return Tokenizer(tokens, self._pre)


# --------------------------------------------------------------------------
# Lexicon bootstrap + segmentation evaluation (the kuromoji-accuracy
# measurement the reference gets from its vendored ipadic build;
# deeplearning4j-nlp-japanese tests exercise real-dictionary decoding).


def derive_dictionary_from_tagged_corpus(
        path, encoding: str = "utf-8", scale: float = 100.0,
        bigram: bool = True, alpha: float = 0.1) -> MorphologicalDictionary:
    """Bootstrap a MeCab-style lexicon from a segmented corpus (TSV lines
    ``raw<TAB>tok|tok|…``) — the same word-cost + connection-cost
    decomposition a real MeCab dictionary encodes (its costs come from a
    CRF trained on exactly this kind of tagged corpus).

    ``bigram=True`` (default): every token type is its own left/right
    class and the connection matrix carries ``scale * -log p(b | a)``
    (add-α smoothed) including BOS/EOS transitions; word costs are zero,
    so the lattice Viterbi decodes the maximum-likelihood BIGRAM
    segmentation. A unigram-only lexicon (``bigram=False``: word cost
    ``scale * -log p(token)``, no matrix) over-splits — frequent short
    particles are so cheap that two of them undercut one longer word
    (measured on the fixture corpus: the greedy baseline BEAT unigram
    Viterbi 0.973 vs 0.968; bigram costs are what make the lattice win)."""
    import math

    counts: Dict[str, int] = {}
    bigrams: Dict[Tuple[str, str], int] = {}
    ctx_totals: Dict[str, int] = {}
    total = 0
    _BOS = "\x00"
    paths = [path] if isinstance(path, str) else list(path)
    for p in paths:
        with open(p, encoding=encoding) as f:
            for line in f:
                line = line.rstrip("\n")
                if not line or "\t" not in line:
                    continue
                toks = [t for t in line.split("\t")[1].split("|") if t]
                if not toks:
                    continue  # a tab with no tokens is not a BOS→EOS bigram
                for tok in toks:
                    counts[tok] = counts.get(tok, 0) + 1
                    total += 1
                for a, b in zip([_BOS] + toks, toks + [_BOS]):
                    bigrams[(a, b)] = bigrams.get((a, b), 0) + 1
                    ctx_totals[a] = ctx_totals.get(a, 0) + 1

    if not bigram:
        entries = [
            DictEntry(surface=tok, left_id=0, right_id=0,
                      cost=int(scale * -math.log(c / total)))
            for tok, c in counts.items()
        ]
        return MorphologicalDictionary(entries)

    # class id per token type; 0 is BOS/EOS (and the unknown-node class)
    ids = {tok: i + 1 for i, tok in enumerate(sorted(counts))}
    ids[_BOS] = _BOS_EOS_ID
    v = len(ids)
    entries = [DictEntry(surface=tok, left_id=ids[tok], right_id=ids[tok],
                         cost=0) for tok in counts]
    # seen bigrams only — a realistic corpus has ~O(corpus) distinct
    # bigrams but v^2 would be billions of iterations
    connections: Dict[Tuple[int, int], int] = {}
    for (a, b), c in bigrams.items():
        denom = ctx_totals.get(a, 0) + alpha * v
        connections[(ids[a], ids[b])] = int(
            scale * -math.log((c + alpha) / denom))
    # unseen class pairs fall back to the PER-CONTEXT add-α probability
    # α/(ctx_total(a)+αv) — a context-free uniform floor would undercharge
    # unseen transitions out of frequent contexts (sparse map returning 0
    # would make them outright free)
    floors = {ids[a]: int(scale * -math.log(
        alpha / (ctx_totals.get(a, 0) + alpha * v))) for a in ids}
    d = MorphologicalDictionary(entries, connections)
    d.connections = _FloorConnections(connections, floors,
                                      int(scale * math.log(v)))
    return d


class _FloorConnections(dict):
    """Connection map with per-left-class add-α floors for unseen pairs."""

    def __init__(self, base: Dict[Tuple[int, int], int],
                 floors: Dict[int, int], default_floor: int):
        super().__init__(base)
        self._floors = floors
        self._default = default_floor

    def get(self, key, default=None):  # noqa: A003 - dict interface
        hit = super().get(key)
        if hit is not None:
            return hit
        return self._floors.get(key[0], self._default)


def greedy_segment(text: str,
                   dictionary: MorphologicalDictionary) -> List[str]:
    """Longest-match-first segmentation — the baseline the lattice decoder
    is measured against (what a non-lattice tokenizer would do with the
    same lexicon)."""
    out: List[str] = []
    i = 0
    n = len(text)
    while i < n:
        hits = dictionary.lookup(text, i)  # longest-first, same lexicon
        if hits:
            out.append(hits[0].surface)
            i += len(hits[0].surface)
        else:
            out.append(text[i])
            i += 1
    return out


def segmentation_f1(pred: Sequence[str], gold: Sequence[str]) -> float:
    """Token-span F1 (the standard word-segmentation metric): a predicted
    token scores iff its exact character span appears in the gold
    segmentation."""
    def spans(tokens):
        out, pos = set(), 0
        for t in tokens:
            out.add((pos, pos + len(t)))
            pos += len(t)
        return out

    p, g = spans(pred), spans(gold)
    if not p or not g:
        return 0.0
    inter = len(p & g)
    return 2.0 * inter / (len(p) + len(g))


def evaluate_segmentation(corpus_path,
                          dictionary: MorphologicalDictionary,
                          encoding: str = "utf-8") -> Dict[str, float]:
    """Macro-averaged span F1 of the lattice Viterbi AND the greedy
    longest-match baseline over a tagged corpus (one path or a list).
    Returns ``{"viterbi_f1": …, "greedy_f1": …, "sentences": n}``."""
    v_scores: List[float] = []
    g_scores: List[float] = []
    paths = [corpus_path] if isinstance(corpus_path, str) else list(corpus_path)
    for p in paths:
        with open(p, encoding=encoding) as f:
            for line in f:
                line = line.rstrip("\n")
                if not line or "\t" not in line:
                    continue
                raw, tagged = line.split("\t")[:2]
                gold = [t for t in tagged.split("|") if t]
                if not gold:
                    continue
                pred_v = [e.surface for e in viterbi_segment(raw, dictionary)]
                pred_g = greedy_segment(raw, dictionary)
                v_scores.append(segmentation_f1(pred_v, gold))
                g_scores.append(segmentation_f1(pred_g, gold))
    n = len(v_scores)
    return {"viterbi_f1": sum(v_scores) / n if n else 0.0,
            "greedy_f1": sum(g_scores) / n if n else 0.0,
            "sentences": float(n)}
