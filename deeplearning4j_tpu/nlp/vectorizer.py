"""Bag-of-words / TF-IDF vectorizers.

Parity with `bagofwords/vectorizer/` (BagOfWordsVectorizer,
TfidfVectorizer): text corpus → count or tf-idf feature matrices usable as
DataSet features.
"""

from __future__ import annotations

import math
from typing import Iterable, List, Optional, Sequence, Union

import numpy as np

from deeplearning4j_tpu.nlp.tokenization import (
    DefaultTokenizerFactory,
    TokenizerFactory,
)
from deeplearning4j_tpu.nlp.vocab import VocabCache, VocabConstructor


class BaseTextVectorizer:
    def __init__(self, min_word_frequency: int = 1,
                 tokenizer_factory: Optional[TokenizerFactory] = None):
        self.min_word_frequency = min_word_frequency
        self.tokenizer_factory = tokenizer_factory or DefaultTokenizerFactory()
        self.vocab: Optional[VocabCache] = None

    def _tokenize(self, docs: Iterable[Union[str, Sequence[str]]]
                  ) -> List[List[str]]:
        out = []
        for d in docs:
            if isinstance(d, str):
                out.append(self.tokenizer_factory.create(d).get_tokens())
            else:
                out.append(list(d))
        return out

    def fit(self, docs: Iterable[Union[str, Sequence[str]]]):
        corpus = self._tokenize(docs)
        self.vocab = VocabConstructor(
            min_word_frequency=self.min_word_frequency).build_vocab(corpus)
        self._post_fit(corpus)
        return self

    def _post_fit(self, corpus: List[List[str]]) -> None:
        pass

    def _counts(self, tokens: List[str]) -> np.ndarray:
        vec = np.zeros(self.vocab.num_words(), np.float32)
        for t in tokens:
            idx = self.vocab.index_of(t)
            if idx >= 0:
                vec[idx] += 1.0
        return vec

    def transform(self, docs: Iterable[Union[str, Sequence[str]]]
                  ) -> np.ndarray:
        corpus = self._tokenize(docs)
        return np.stack([self._transform_one(t) for t in corpus])

    def fit_transform(self, docs) -> np.ndarray:
        docs = list(docs)
        self.fit(docs)
        return self.transform(docs)

    def _transform_one(self, tokens: List[str]) -> np.ndarray:
        raise NotImplementedError


class BagOfWordsVectorizer(BaseTextVectorizer):
    """Raw term counts (BagOfWordsVectorizer.java)."""

    def _transform_one(self, tokens: List[str]) -> np.ndarray:
        return self._counts(tokens)


class TfidfVectorizer(BaseTextVectorizer):
    """tf·idf with idf = log(N / df) (TfidfVectorizer.java semantics)."""

    def _post_fit(self, corpus: List[List[str]]) -> None:
        n_docs = len(corpus)
        df = np.zeros(self.vocab.num_words(), np.float64)
        for tokens in corpus:
            seen = {self.vocab.index_of(t) for t in tokens}
            for idx in seen:
                if idx >= 0:
                    df[idx] += 1
        with np.errstate(divide="ignore"):
            self.idf = np.where(df > 0, np.log(n_docs / np.maximum(df, 1)), 0.0)

    def tfidf_word(self, word: str, doc_tokens: List[str]) -> float:
        idx = self.vocab.index_of(word)
        if idx < 0:
            return 0.0
        tf = doc_tokens.count(word) / max(len(doc_tokens), 1)
        return float(tf * self.idf[idx])

    def _transform_one(self, tokens: List[str]) -> np.ndarray:
        counts = self._counts(tokens)
        tf = counts / max(len(tokens), 1)
        return (tf * self.idf).astype(np.float32)
