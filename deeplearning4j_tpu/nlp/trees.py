"""Constituency-tree toolkit.

Parity with the reference's tree stack: the ``Tree`` data structure
(``deeplearning4j-nn/.../autoencoder/recursive/Tree.java``) and the
``text/corpora/treeparser/`` package (TreeParser role via Penn-treebank
parsing, BinarizeTreeTransformer, CollapseUnaries, HeadWordFinder with
the classic Charniak head-rule tables, TreeVectorizer). The reference
obtains parses from an OpenNLP UIMA annotator; no parser models exist in
this image, so trees enter through the standard PTB bracketed format
(``Tree.from_penn``) — the interchange every treebank ships in.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence, Tuple

__all__ = [
    "Tree",
    "BinarizeTreeTransformer",
    "CollapseUnaries",
    "HeadWordFinder",
    "TreeVectorizer",
]


class Tree:
    """An n-ary labeled tree; leaves carry tokens (``Tree.java``)."""

    def __init__(self, label: str, children: Optional[List["Tree"]] = None,
                 value: Optional[str] = None):
        self.label = label
        self.children: List[Tree] = list(children or [])
        self.value = value          # token text for leaves
        self.gold_label: Optional[int] = None
        self.parent: Optional[Tree] = None
        for c in self.children:
            c.parent = self

    # -- structure -----------------------------------------------------------
    def is_leaf(self) -> bool:
        return not self.children

    def is_pre_terminal(self) -> bool:
        return len(self.children) == 1 and self.children[0].is_leaf()

    def first_child(self) -> Optional["Tree"]:
        return self.children[0] if self.children else None

    def last_child(self) -> Optional["Tree"]:
        return self.children[-1] if self.children else None

    def depth(self) -> int:
        if self.is_leaf():
            return 0
        return 1 + max(c.depth() for c in self.children)

    def leaves(self) -> List["Tree"]:
        if self.is_leaf():
            return [self]
        out: List[Tree] = []
        for c in self.children:
            out.extend(c.leaves())
        return out

    def yield_words(self) -> List[str]:
        """Token sequence under this node (``Tree.yield``)."""
        return [l.value for l in self.leaves() if l.value is not None]

    def tags(self) -> List[str]:
        """Pre-terminal labels left to right."""
        if self.is_pre_terminal():
            return [self.label]
        out: List[str] = []
        for c in self.children:
            out.extend(c.tags())
        return out

    def connect(self, children: Sequence["Tree"]) -> "Tree":
        """Replace children, fixing parent pointers (``Tree.connect``)."""
        self.children = list(children)
        for c in self.children:
            c.parent = self
        return self

    def clone(self) -> "Tree":
        t = Tree(self.label, [c.clone() for c in self.children], self.value)
        t.gold_label = self.gold_label
        return t

    # -- serde ---------------------------------------------------------------
    def to_penn(self) -> str:
        if self.is_leaf():
            return self.value or ""
        inner = " ".join(c.to_penn() for c in self.children)
        return f"({self.label} {inner})"

    def __repr__(self) -> str:
        return f"Tree({self.to_penn()!r})"

    @staticmethod
    def from_penn(s: str) -> "Tree":
        """Parse one Penn-treebank bracketed sentence (TreeParser role)."""
        tokens = s.replace("(", " ( ").replace(")", " ) ").split()
        if not tokens:
            raise ValueError("empty tree string")
        pos = 0

        def parse() -> Tree:
            nonlocal pos
            if tokens[pos] != "(":
                # bare token = leaf
                leaf = Tree(label=tokens[pos], value=tokens[pos])
                pos += 1
                return leaf
            pos += 1  # consume '('
            if tokens[pos] == "(":
                # PTB empty-label wrapper: ( (S ...) )
                label = ""
            else:
                label = tokens[pos]
                pos += 1
            children: List[Tree] = []
            while pos < len(tokens) and tokens[pos] != ")":
                children.append(parse())
            if pos >= len(tokens):
                raise ValueError(f"unbalanced parens in {s!r}")
            pos += 1  # consume ')'
            return Tree(label, children)

        tree = parse()
        if pos != len(tokens):
            raise ValueError(f"trailing content in {s!r}")
        # unwrap the empty-label / ROOT wrapper down to the real clause
        while tree.label in ("", "ROOT") and len(tree.children) == 1:
            tree = tree.children[0]
            tree.parent = None
        return tree


class CollapseUnaries:
    """Skip through unary chains, keeping the top label
    (``CollapseUnaries.java``)."""

    def transform(self, tree: Tree) -> Tree:
        # leaves/pre-terminals are cloned so the result never aliases (and
        # never re-parents) nodes of the source tree
        if tree.is_pre_terminal() or tree.is_leaf():
            return tree.clone()
        children = tree.children
        while len(children) == 1 and not children[0].is_leaf() \
                and not children[0].is_pre_terminal():
            children = children[0].children
        out = Tree(tree.label, [self.transform(c) for c in children],
                   tree.value)
        out.gold_label = tree.gold_label
        return out


class BinarizeTreeTransformer:
    """Binarize n-ary nodes (``BinarizeTreeTransformer.java``).

    ``factor="right"`` (default here and in practice) splits
    ``A -> c1 c2 c3 c4`` into a right-branching chain whose intermediate
    nodes are labeled ``A@c2-c3-c4`` (the sibling labels truncated to
    ``horizontal_markov``). The reference embeds a bare ``(`` in these
    labels; a paren-free separator is used here so binarized trees stay
    round-trippable through ``to_penn``/``from_penn``.
    """

    def __init__(self, factor: str = "right", horizontal_markov: int = 999):
        if factor not in ("left", "right"):
            raise ValueError("factor must be 'left' or 'right'")
        self.factor = factor
        self.horizontal_markov = horizontal_markov

    def transform(self, tree: Tree) -> Tree:
        children = [self.transform(c) for c in tree.children]
        out = Tree(tree.label, children, tree.value)
        out.gold_label = tree.gold_label
        node = out
        while len(node.children) > 2:  # descend into each new inner node
            kids = node.children
            if self.factor == "right":
                rest = kids[1:]
                labels = [k.label for k in rest[: self.horizontal_markov]]
                inner = Tree(f"{tree.label}@{'-'.join(labels)}", rest)
                node.connect([kids[0], inner])
            else:
                rest = kids[:-1]
                labels = [k.label for k in rest[-self.horizontal_markov:]][::-1]
                inner = Tree(f"{tree.label}@{'-'.join(labels)}", rest)
                node.connect([inner, kids[-1]])
            node = inner
        return out


class HeadWordFinder:
    """Charniak-style head-percolation rules
    (``HeadWordFinder.java`` head1/head2/terminal tables)."""

    _HEAD1 = {tuple(r.split()) for r in [
        "ADJP JJ", "ADJP JJR", "ADJP JJS", "ADVP RB", "ADVP RBB", "LST LS",
        "NAC NNS", "NAC NN", "NAC PRP", "NAC NNPS", "NAC NNP", "NX NNS",
        "NX NN", "NX PRP", "NX NNPS", "NX NNP", "NP NNS", "NP NN", "NP PRP",
        "NP NNPS", "NP NNP", "NP POS", "NP $", "PP IN", "PP TO", "PP RP",
        "PRT RP", "S VP", "S1 S", "SBAR IN", "SBAR WHNP", "SBARQ SQ",
        "SBARQ VP", "SINV VP", "SQ MD", "SQ AUX", "VP VB", "VP VBZ",
        "VP VBP", "VP VBG", "VP VBN", "VP VBD", "VP AUX", "VP AUXG",
        "VP TO", "VP MD", "WHADJP WRB", "WHADVP WRB", "WHNP WP", "WHNP WDT",
        "WHNP WP$", "WHPP IN", "WHPP TO"]}
    _HEAD2 = {tuple(r.split()) for r in [
        "ADJP VBN", "ADJP RB", "NAC NP", "NAC CD", "NAC FW", "NAC ADJP",
        "NAC JJ", "NX NP", "NX CD", "NX FW", "NX ADJP", "NX JJ", "NP CD",
        "NP ADJP", "NP JJ", "S SINV", "S SBARQ", "S X", "PRT RB", "PRT IN",
        "SBAR WHADJP", "SBAR WHADVP", "SBAR WHPP", "SBARQ S", "SBARQ SINV",
        "SBARQ X", "SINV SBAR", "SQ VP"]}
    _PUNC = {"#", "$", ".", ",", ":", "-RRB-", "-LRB-", "``", "''"}

    def find_head(self, tree: Tree) -> Optional[Tree]:
        """The head WORD (leaf) of a parse tree (``findHead``)."""
        node = tree
        while not node.is_leaf():
            child = self.find_head_child(node)
            if child is None:
                return None
            node = child
        return node

    def find_head_child(self, tree: Tree) -> Optional[Tree]:
        if tree.is_leaf():
            return None
        if tree.is_pre_terminal():
            return tree.children[0]
        parent = tree.label
        for c in tree.children:                      # rule table 1, L->R
            if (parent, c.label) in self._HEAD1:
                return c
        for c in reversed(tree.children):            # rule table 2, R->L
            if (parent, c.label) in self._HEAD2:
                return c
        for c in tree.children:                      # first non-punctuation
            if c.label not in self._PUNC:
                return c
        return tree.children[0]


class TreeVectorizer:
    """Parse + normalize trees for recursive models
    (``TreeVectorizer.java``: parse, binarize, collapse unaries, attach
    gold labels)."""

    def __init__(self, binarizer: Optional[BinarizeTreeTransformer] = None,
                 collapser: Optional[CollapseUnaries] = None):
        self.binarizer = binarizer or BinarizeTreeTransformer()
        self.collapser = collapser or CollapseUnaries()

    def get_trees(self, penn_strings: Sequence[str]) -> List[Tree]:
        out = []
        for s in penn_strings:
            t = Tree.from_penn(s)
            t = self.binarizer.transform(t)
            t = self.collapser.transform(t)
            out.append(t)
        return out

    def get_trees_with_labels(self, penn_strings: Sequence[str],
                              label: str, labels: Sequence[str]) -> List[Tree]:
        """Attach the sentence label's index as gold_label on every node
        (``getTreesWithLabels``). Unknown labels raise."""
        if label not in labels:
            raise ValueError(f"label {label!r} not in label set {list(labels)}")
        idx = list(labels).index(label)
        trees = self.get_trees(penn_strings)
        for t in trees:
            self._label_all(t, idx)
        return trees

    def _label_all(self, tree: Tree, idx: int) -> None:
        tree.gold_label = idx
        for c in tree.children:
            self._label_all(c, idx)
