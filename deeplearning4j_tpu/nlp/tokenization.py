"""Tokenizer SPI.

Parity with the reference's `text/tokenization/` package: a
`TokenizerFactory` creates a `Tokenizer` per sentence; an optional
`TokenPreProcess` normalises each token
(`tokenization/tokenizer/preprocessor/CommonPreprocessor.java` lowercases and
strips punctuation). Language packs (kuromoji/ansj/UIMA, SURVEY.md §2 "NLP
language packs") plug in by implementing ``TokenizerFactory``.
"""

from __future__ import annotations

import re
from typing import Callable, Iterator, List, Optional


class TokenPreProcess:
    """Normalises one token; return "" to drop it."""

    def pre_process(self, token: str) -> str:
        raise NotImplementedError


class CommonPreprocessor(TokenPreProcess):
    """Lowercase + strip punctuation/digits (CommonPreprocessor.java)."""

    _PUNCT = re.compile(r"[\d\.:,\"'\(\)\[\]|/?!;]+")

    def pre_process(self, token: str) -> str:
        return self._PUNCT.sub("", token).lower()


class LowCasePreProcessor(TokenPreProcess):
    def pre_process(self, token: str) -> str:
        return token.lower()


class Tokenizer:
    """Iterator over the tokens of one sentence."""

    def __init__(self, tokens: List[str],
                 pre_processor: Optional[TokenPreProcess] = None):
        self._tokens = tokens
        self._pre = pre_processor

    def set_token_pre_processor(self, pre: TokenPreProcess) -> None:
        self._pre = pre

    def get_tokens(self) -> List[str]:
        if self._pre is None:
            return list(self._tokens)
        out = []
        for t in self._tokens:
            t = self._pre.pre_process(t)
            if t:
                out.append(t)
        return out

    def count_tokens(self) -> int:
        return len(self.get_tokens())

    def __iter__(self) -> Iterator[str]:
        return iter(self.get_tokens())


class TokenizerFactory:
    def create(self, sentence: str) -> Tokenizer:
        raise NotImplementedError

    def set_token_pre_processor(self, pre: TokenPreProcess) -> None:
        self._pre = pre


class DefaultTokenizerFactory(TokenizerFactory):
    """Whitespace tokenizer (DefaultTokenizerFactory.java)."""

    def __init__(self, pre_processor: Optional[TokenPreProcess] = None):
        self._pre = pre_processor

    def create(self, sentence: str) -> Tokenizer:
        return Tokenizer(sentence.split(), self._pre)


def DefaultTokenizer(sentence: str) -> Tokenizer:
    return DefaultTokenizerFactory().create(sentence)


class NGramTokenizerFactory(TokenizerFactory):
    """Emits word n-grams joined by spaces (NGramTokenizerFactory.java)."""

    def __init__(self, min_n: int = 1, max_n: int = 2,
                 pre_processor: Optional[TokenPreProcess] = None):
        self.min_n = min_n
        self.max_n = max_n
        self._pre = pre_processor

    def create(self, sentence: str) -> Tokenizer:
        words = Tokenizer(sentence.split(), self._pre).get_tokens()
        grams: List[str] = []
        for n in range(self.min_n, self.max_n + 1):
            for i in range(len(words) - n + 1):
                grams.append(" ".join(words[i:i + n]))
        return Tokenizer(grams)
