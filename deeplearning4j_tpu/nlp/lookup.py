"""Weight lookup table for embedding models.

Parity with `models/embeddings/inmemory/InMemoryLookupTable.java:56`:
syn0 (input vectors), syn1 (hierarchical-softmax inner nodes), syn1neg
(negative-sampling output vectors), and the unigram sampling table. Arrays
are device-resident jnp arrays updated functionally by the jitted training
steps in :mod:`learning`; the reference's lock-free row races become
deterministic scatter-adds.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp.vocab import VocabCache


class InMemoryLookupTable:
    def __init__(self, cache: VocabCache, vector_length: int,
                 seed: int = 12345, use_hs: bool = False,
                 negative: int = 5, dtype=jnp.float32,
                 init_syn0: bool = True):
        """``init_syn0=False`` skips the random init for callers about to
        install weights (loaders, GloVe)."""
        self.cache = cache
        self.vector_length = vector_length
        self.use_hs = use_hs
        self.negative = negative
        n = cache.num_words()
        if init_syn0:
            rng = np.random.default_rng(seed)
            # word2vec init: uniform(-0.5, 0.5)/dim for syn0, zeros for outputs
            self.syn0 = jnp.asarray(
                (rng.random((n, vector_length)) - 0.5) / vector_length,
                dtype=dtype)
        else:
            self.syn0 = None
        self.syn1 = (jnp.zeros((max(n - 1, 1), vector_length), dtype)
                     if use_hs else None)
        self.syn1neg = (jnp.zeros((n, vector_length), dtype)
                        if negative > 0 else None)
        self._unigram: Optional[np.ndarray] = None
        self._unigram_size = 0

    def unigram_table(self, table_size: int = 100_000,
                      power: float = 0.75) -> np.ndarray:
        """Negative-sampling table: word i appears ∝ freq(i)^0.75."""
        if self._unigram is None or self._unigram_size != table_size:
            self._unigram_size = table_size
            freqs = np.array([vw.frequency for vw in self.cache.vocab_words()],
                             np.float64)
            probs = freqs ** power
            probs /= probs.sum()
            counts = np.maximum((probs * table_size).astype(np.int64), 1)
            self._unigram = np.repeat(np.arange(len(freqs)), counts)
        return self._unigram

    def vector(self, word: str) -> Optional[np.ndarray]:
        idx = self.cache.index_of(word)
        if idx < 0:
            return None
        return np.asarray(self.syn0[idx])

    def all_vectors(self) -> np.ndarray:
        return np.asarray(self.syn0)

    def resize(self, new_rows: int, seed: int = 0) -> None:
        """Grow syn0/syn1neg for newly added vocab rows (ParagraphVectors
        label insertion)."""
        n, d = self.syn0.shape
        if new_rows <= n:
            return
        rng = np.random.default_rng(seed)
        extra = jnp.asarray((rng.random((new_rows - n, d)) - 0.5) / d,
                            self.syn0.dtype)
        self.syn0 = jnp.concatenate([self.syn0, extra], axis=0)
        if self.syn1neg is not None:
            self.syn1neg = jnp.concatenate(
                [self.syn1neg, jnp.zeros((new_rows - n, d),
                                         self.syn1neg.dtype)], axis=0)
        self._unigram = None
