"""Embedding learning algorithms: SkipGram / CBOW (+ DM / DBOW on top).

Parity with `models/embeddings/learning/impl/elements/{SkipGram,CBOW}.java`
and `.../sequence/{DM,DBOW}.java`. The reference updates syn0/syn1 rows one
(word, context) pair at a time from racing threads; here pairs are generated
on host (vectorised numpy), packed into fixed-size batches (static shapes →
one XLA program), and applied as a single gather→dot→scatter-add jit step.
Negative sampling and hierarchical softmax both supported, matching word2vec
gradient math: g = (label − σ(h·v)) · lr.
"""

from __future__ import annotations

import functools
from typing import Iterator, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp.lookup import InMemoryLookupTable


# ---------------------------------------------------------------- jit steps

@functools.partial(jax.jit, donate_argnums=(0, 1))
def _ns_step(syn0, syn1neg, centers, targets, labels, valid, lr):
    """Negative-sampling update for a batch of center→target rows.

    centers: [B] rows of syn0 (context word for SG; mean handled by _cbow).
    targets: [B, K] rows of syn1neg (1 positive + K-1 negatives).
    labels:  [B, K] 1.0 for the positive column, else 0.0.
    valid:   [B, K] 0.0 masks padding and self-collision negatives.
    """
    h = syn0[centers]                                        # [B, D]
    vt = syn1neg[targets]                                    # [B, K, D]
    logits = jnp.einsum("bd,bkd->bk", h, vt)
    g = (labels - jax.nn.sigmoid(logits)) * valid * lr       # [B, K]
    dh = jnp.einsum("bk,bkd->bd", g, vt)
    dvt = g[..., None] * h[:, None, :]
    syn0 = syn0.at[centers].add(dh, mode="drop")
    syn1neg = syn1neg.at[targets].add(dvt, mode="drop")
    return syn0, syn1neg


@functools.partial(jax.jit, donate_argnums=(0, 1))
def _hs_step(syn0, syn1, centers, points, codes, valid, lr):
    """Hierarchical-softmax update: points are inner-node rows of syn1,
    label for node j is (1 - code_j)."""
    h = syn0[centers]
    vt = syn1[points]
    logits = jnp.einsum("bd,bkd->bk", h, vt)
    g = ((1.0 - codes) - jax.nn.sigmoid(logits)) * valid * lr
    dh = jnp.einsum("bk,bkd->bd", g, vt)
    dvt = g[..., None] * h[:, None, :]
    syn0 = syn0.at[centers].add(dh, mode="drop")
    syn1 = syn1.at[points].add(dvt, mode="drop")
    return syn0, syn1


@functools.partial(jax.jit, donate_argnums=(0, 1))
def _cbow_hs_step(syn0, syn1, contexts, ctx_valid, points, codes, valid, lr):
    """CBOW with hierarchical softmax: h = mean of context vectors, labels
    from Huffman codes, input gradient spread to every context word."""
    cv = syn0[contexts] * ctx_valid[..., None]               # [B, C, D]
    n_ctx = jnp.maximum(jnp.sum(ctx_valid, axis=1), 1.0)
    h = jnp.sum(cv, axis=1) / n_ctx[:, None]
    vt = syn1[points]
    logits = jnp.einsum("bd,bkd->bk", h, vt)
    g = ((1.0 - codes) - jax.nn.sigmoid(logits)) * valid * lr
    dh = jnp.einsum("bk,bkd->bd", g, vt)
    dvt = g[..., None] * h[:, None, :]
    dctx = jnp.broadcast_to(dh[:, None, :], cv.shape) * ctx_valid[..., None]
    syn0 = syn0.at[contexts].add(dctx, mode="drop")
    syn1 = syn1.at[points].add(dvt, mode="drop")
    return syn0, syn1


@functools.partial(jax.jit, donate_argnums=(0, 1))
def _cbow_ns_step(syn0, syn1neg, contexts, ctx_valid, targets, labels,
                  valid, lr):
    """CBOW: h = mean of context vectors; input gradient spread equally."""
    cv = syn0[contexts] * ctx_valid[..., None]               # [B, C, D]
    n_ctx = jnp.maximum(jnp.sum(ctx_valid, axis=1), 1.0)     # [B]
    h = jnp.sum(cv, axis=1) / n_ctx[:, None]                 # [B, D]
    vt = syn1neg[targets]                                    # [B, K, D]
    logits = jnp.einsum("bd,bkd->bk", h, vt)
    g = (labels - jax.nn.sigmoid(logits)) * valid * lr
    dh = jnp.einsum("bk,bkd->bd", g, vt)                     # [B, D]
    dvt = g[..., None] * h[:, None, :]
    # word2vec applies the full dh to every context word
    dctx = jnp.broadcast_to(dh[:, None, :], cv.shape) * ctx_valid[..., None]
    syn0 = syn0.at[contexts].add(dctx, mode="drop")
    syn1neg = syn1neg.at[targets].add(dvt, mode="drop")
    return syn0, syn1neg


# ------------------------------------------------------- pair generation

def _reduced_window(L: int, window: int, rng: np.random.Generator
                    ) -> Tuple[np.ndarray, np.ndarray]:
    """word2vec's random reduced-window machinery, shared by SG and CBOW:
    (idx, ok) where idx[L, 2W] are neighbor positions and ok masks
    out-of-range positions and those beyond the per-center random width."""
    b = rng.integers(1, window + 1, size=L)
    offsets = np.concatenate([np.arange(-window, 0), np.arange(1, window + 1)])
    idx = np.arange(L)[:, None] + offsets[None, :]            # [L, 2W]
    ok = (idx >= 0) & (idx < L) & (np.abs(offsets)[None, :] <= b[:, None])
    return idx, ok


def generate_sg_pairs(seq: np.ndarray, window: int,
                      rng: np.random.Generator) -> Tuple[np.ndarray, np.ndarray]:
    """(center, context) index pairs with word2vec's random reduced window."""
    L = len(seq)
    if L < 2:
        return np.empty(0, np.int32), np.empty(0, np.int32)
    idx, ok = _reduced_window(L, window, rng)
    ii, jj = np.nonzero(ok)
    return seq[ii].astype(np.int32), seq[idx[ii, jj]].astype(np.int32)


def generate_cbow_groups(seq: np.ndarray, window: int,
                         rng: np.random.Generator
                         ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """(target, context_matrix, context_valid): contexts padded to 2*window."""
    L = len(seq)
    if L < 2:
        z = np.empty((0,), np.int32)
        return z, np.empty((0, 2 * window), np.int32), np.empty((0, 2 * window), np.float32)
    idx, ok = _reduced_window(L, window, rng)
    ctx = np.where(ok, seq[np.clip(idx, 0, L - 1)], 0).astype(np.int32)
    return seq.astype(np.int32), ctx, ok.astype(np.float32)


def subsample(seq: np.ndarray, keep_prob: np.ndarray,
              rng: np.random.Generator) -> np.ndarray:
    """Frequent-word subsampling (word2vec `sample` parameter)."""
    if keep_prob is None:
        return seq
    return seq[rng.random(len(seq)) < keep_prob[seq]]


def make_keep_prob(cache, sample: float) -> Optional[np.ndarray]:
    if not sample or sample <= 0:
        return None
    freqs = np.array([vw.frequency for vw in cache.vocab_words()], np.float64)
    total = freqs.sum()
    ratio = freqs / (sample * total)
    keep = (np.sqrt(ratio) + 1.0) / ratio
    return np.minimum(keep, 1.0)


def _pad_rows(n: int, minimum: int = 256) -> int:
    """Round the batch up to a power of two so XLA compiles once per bucket,
    not once per sentence length."""
    size = minimum
    while size < n:
        size *= 2
    return size


def _pad_to(arr: np.ndarray, rows: int, fill=0) -> np.ndarray:
    if len(arr) == rows:
        return arr
    pad_shape = (rows - len(arr),) + arr.shape[1:]
    return np.concatenate([arr, np.full(pad_shape, fill, arr.dtype)], axis=0)


# ------------------------------------------------------ learning algorithms

class ElementsLearningAlgorithm:
    """SPI mirroring `learning/ElementsLearningAlgorithm.java`."""

    name: str = "base"

    def configure(self, table: InMemoryLookupTable, window: int,
                  negative: int, seed: int) -> None:
        self.table = table
        self.window = window
        self.negative = negative
        self.rng = np.random.default_rng(seed)
        self._max_code = max(
            (len(vw.code) for vw in table.cache.vocab_words()), default=1) or 1
        if table.use_hs:
            n = table.cache.num_words()
            self._points = np.zeros((n, self._max_code), np.int32)
            self._codes = np.zeros((n, self._max_code), np.float32)
            self._code_valid = np.zeros((n, self._max_code), np.float32)
            for vw in table.cache.vocab_words():
                L = len(vw.code)
                self._points[vw.index, :L] = vw.points
                self._codes[vw.index, :L] = vw.code
                self._code_valid[vw.index, :L] = 1.0

    def _sample_negatives(self, positives: np.ndarray) -> Tuple[np.ndarray, ...]:
        """targets [B, 1+neg], labels, valid — col 0 is the positive."""
        B = len(positives)
        table = self.table.unigram_table()
        negs = table[self.rng.integers(0, len(table), size=(B, self.negative))]
        targets = np.concatenate([positives[:, None], negs], axis=1).astype(np.int32)
        labels = np.zeros_like(targets, np.float32)
        labels[:, 0] = 1.0
        valid = np.ones_like(labels)
        valid[:, 1:] = (negs != positives[:, None]).astype(np.float32)
        return targets, labels, valid

    def train_pairs(self, centers: np.ndarray, predicted: np.ndarray,
                    lr: float) -> None:
        """Update tables for (input-row, predicted-word) pairs."""
        B = len(centers)
        if B == 0:
            return
        rows = _pad_rows(B)
        if self.table.use_hs:
            pts = _pad_to(self._points[predicted], rows)
            cds = _pad_to(self._codes[predicted], rows)
            val = _pad_to(self._code_valid[predicted], rows)
            self.table.syn0, self.table.syn1 = _hs_step(
                self.table.syn0, self.table.syn1, _pad_to(centers, rows),
                pts, cds, val, jnp.float32(lr))
        if self.negative > 0:
            targets, labels, valid = self._sample_negatives(predicted)
            self.table.syn0, self.table.syn1neg = _ns_step(
                self.table.syn0, self.table.syn1neg, _pad_to(centers, rows),
                _pad_to(targets, rows), _pad_to(labels, rows),
                _pad_to(valid, rows), jnp.float32(lr))


class SkipGram(ElementsLearningAlgorithm):
    """Predict each context word from the center word (SkipGram.java).

    word2vec convention: the *context* word's syn0 row is the input and the
    center word is predicted — equivalent by symmetry; we follow the
    original C code (input = center of the pair list below)."""

    name = "SkipGram"

    def train_sequence(self, seq: np.ndarray, lr: float,
                       keep_prob: Optional[np.ndarray] = None) -> int:
        seq = subsample(seq, keep_prob, self.rng)
        centers, contexts = generate_sg_pairs(seq, self.window, self.rng)
        self.train_pairs(contexts, centers, lr)  # input=context, predict=center
        return len(centers)


class CBOW(ElementsLearningAlgorithm):
    """Predict the center word from the mean of its context (CBOW.java)."""

    name = "CBOW"

    def train_sequence(self, seq: np.ndarray, lr: float,
                       keep_prob: Optional[np.ndarray] = None) -> int:
        seq = subsample(seq, keep_prob, self.rng)
        targets, ctx, ctx_valid = generate_cbow_groups(seq, self.window, self.rng)
        return self._train_groups(targets, ctx, ctx_valid, lr)

    def _train_groups(self, targets: np.ndarray, ctx: np.ndarray,
                      ctx_valid: np.ndarray, lr: float) -> int:
        """HS and/or negative-sampling update for (context-group → target)
        batches; shared by CBOW and DM."""
        if len(targets) == 0:
            return 0
        rows = _pad_rows(len(targets))
        if self.table.use_hs:
            self.table.syn0, self.table.syn1 = _cbow_hs_step(
                self.table.syn0, self.table.syn1, _pad_to(ctx, rows),
                _pad_to(ctx_valid, rows), _pad_to(self._points[targets], rows),
                _pad_to(self._codes[targets], rows),
                _pad_to(self._code_valid[targets], rows), jnp.float32(lr))
        if self.negative > 0:
            t, labels, valid = self._sample_negatives(targets)
            self.table.syn0, self.table.syn1neg = _cbow_ns_step(
                self.table.syn0, self.table.syn1neg, _pad_to(ctx, rows),
                _pad_to(ctx_valid, rows), _pad_to(t, rows),
                _pad_to(labels, rows), _pad_to(valid, rows), jnp.float32(lr))
        return len(targets)


class DBOW(SkipGram):
    """Distributed bag-of-words for ParagraphVectors (DBOW.java): the
    document/label row predicts each word in the document."""

    name = "DBOW"

    def train_document(self, label_idx: int, seq: np.ndarray, lr: float,
                       keep_prob: Optional[np.ndarray] = None) -> int:
        seq = subsample(seq, keep_prob, self.rng)
        if len(seq) == 0:
            return 0
        labels = np.full(len(seq), label_idx, np.int32)
        self.train_pairs(labels, seq.astype(np.int32), lr)
        return len(seq)


class DM(CBOW):
    """Distributed memory (DM.java): label row joins the context average."""

    name = "DM"

    def train_document(self, label_idx: int, seq: np.ndarray, lr: float,
                       keep_prob: Optional[np.ndarray] = None) -> int:
        seq = subsample(seq, keep_prob, self.rng)
        targets, ctx, ctx_valid = generate_cbow_groups(seq, self.window, self.rng)
        if len(targets) == 0:
            return 0
        # append the label row as an always-valid context column
        lab_col = np.full((len(targets), 1), label_idx, np.int32)
        ctx = np.concatenate([ctx, lab_col], axis=1)
        ctx_valid = np.concatenate(
            [ctx_valid, np.ones((len(targets), 1), np.float32)], axis=1)
        return self._train_groups(targets, ctx, ctx_valid, lr)
