"""Porter stemming + stemming token preprocessors + POS-filtered
tokenization.

Parity with the reference's UIMA pack pieces that are pure algorithms:
``tokenization/tokenizer/preprocessor/StemmingPreprocessor.java`` (and the
Embedded/Custom variants), and ``PosUimaTokenizer(Factory).java`` —
tokens whose POS tag is not in the allowed set become ``"NONE"``. The
reference tags with an OpenNLP UIMA annotator; no model files exist in
this image, so the tagger is pluggable (any ``fn(tokens)->tags``) with a
built-in suffix-heuristic English tagger as the default.
"""

from __future__ import annotations

import re
from typing import Callable, Iterable, List, Optional, Sequence, Set

from deeplearning4j_tpu.nlp.tokenization import (
    CommonPreprocessor,
    TokenPreProcess,
    Tokenizer,
    TokenizerFactory,
)

__all__ = [
    "PorterStemmer",
    "StemmingPreprocessor",
    "EmbeddedStemmingPreprocessor",
    "CustomStemmingPreprocessor",
    "heuristic_pos_tagger",
    "PosTokenizerFactory",
]


class PorterStemmer:
    """The classic Porter (1980) suffix-stripping algorithm.

    Fills the role of the snowball ``PorterStemmer`` the reference's
    StemmingPreprocessor instantiates per token.
    """

    _VOWELS = set("aeiou")

    def _is_cons(self, word: str, i: int) -> bool:
        ch = word[i]
        if ch in self._VOWELS:
            return False
        if ch == "y":
            return i == 0 or not self._is_cons(word, i - 1)
        return True

    def _measure(self, stem: str) -> int:
        """Number of VC sequences in the stem."""
        forms = "".join("c" if self._is_cons(stem, i) else "v"
                        for i in range(len(stem)))
        return len(re.findall("vc", forms))

    def _has_vowel(self, stem: str) -> bool:
        return any(not self._is_cons(stem, i) for i in range(len(stem)))

    def _ends_double_cons(self, word: str) -> bool:
        return (len(word) >= 2 and word[-1] == word[-2]
                and self._is_cons(word, len(word) - 1))

    def _cvc(self, word: str) -> bool:
        if len(word) < 3:
            return False
        return (self._is_cons(word, len(word) - 3)
                and not self._is_cons(word, len(word) - 2)
                and self._is_cons(word, len(word) - 1)
                and word[-1] not in "wxy")

    def stem(self, word: str) -> str:
        w = word.lower()
        if len(w) <= 2:
            return w

        # step 1a
        if w.endswith("sses"):
            w = w[:-2]
        elif w.endswith("ies"):
            w = w[:-2]
        elif w.endswith("ss"):
            pass
        elif w.endswith("s"):
            w = w[:-1]

        # step 1b
        if w.endswith("eed"):
            if self._measure(w[:-3]) > 0:
                w = w[:-1]
        elif w.endswith("ed") and self._has_vowel(w[:-2]):
            w = w[:-2]
            w = self._step1b_fix(w)
        elif w.endswith("ing") and self._has_vowel(w[:-3]):
            w = w[:-3]
            w = self._step1b_fix(w)

        # step 1c
        if w.endswith("y") and self._has_vowel(w[:-1]):
            w = w[:-1] + "i"

        # step 2
        for suffix, repl in (
                ("ational", "ate"), ("tional", "tion"), ("enci", "ence"),
                ("anci", "ance"), ("izer", "ize"), ("abli", "able"),
                ("alli", "al"), ("entli", "ent"), ("eli", "e"),
                ("ousli", "ous"), ("ization", "ize"), ("ation", "ate"),
                ("ator", "ate"), ("alism", "al"), ("iveness", "ive"),
                ("fulness", "ful"), ("ousness", "ous"), ("aliti", "al"),
                ("iviti", "ive"), ("biliti", "ble")):
            if w.endswith(suffix):
                if self._measure(w[: -len(suffix)]) > 0:
                    w = w[: -len(suffix)] + repl
                break

        # step 3
        for suffix, repl in (
                ("icate", "ic"), ("ative", ""), ("alize", "al"),
                ("iciti", "ic"), ("ical", "ic"), ("ful", ""), ("ness", "")):
            if w.endswith(suffix):
                if self._measure(w[: -len(suffix)]) > 0:
                    w = w[: -len(suffix)] + repl
                break

        # step 4
        for suffix in ("al", "ance", "ence", "er", "ic", "able", "ible",
                       "ant", "ement", "ment", "ent", "ion", "ou", "ism",
                       "ate", "iti", "ous", "ive", "ize"):
            if w.endswith(suffix):
                stem = w[: -len(suffix)]
                if self._measure(stem) > 1:
                    if suffix == "ion" and not (stem and stem[-1] in "st"):
                        break
                    w = stem
                break

        # step 5a
        if w.endswith("e"):
            stem = w[:-1]
            m = self._measure(stem)
            if m > 1 or (m == 1 and not self._cvc(stem)):
                w = stem
        # step 5b
        if self._ends_double_cons(w) and w.endswith("l") \
                and self._measure(w[:-1]) > 1:
            w = w[:-1]
        return w

    def _step1b_fix(self, w: str) -> str:
        if w.endswith(("at", "bl", "iz")):
            return w + "e"
        if self._ends_double_cons(w) and not w.endswith(("l", "s", "z")):
            return w[:-1]
        if self._measure(w) == 1 and self._cvc(w):
            return w + "e"
        return w


class StemmingPreprocessor(CommonPreprocessor):
    """CommonPreprocessor cleaning + Porter stemming
    (``StemmingPreprocessor.java``)."""

    _stemmer = PorterStemmer()

    def pre_process(self, token: str) -> str:
        return self._stemmer.stem(super().pre_process(token))


class EmbeddedStemmingPreprocessor(TokenPreProcess):
    """Wraps any inner preprocessor, stemming its output
    (``EmbeddedStemmingPreprocessor.java``)."""

    def __init__(self, inner: Optional[TokenPreProcess] = None):
        self.inner = inner
        self._stemmer = PorterStemmer()

    def pre_process(self, token: str) -> str:
        if self.inner is not None:
            token = self.inner.pre_process(token)
        return self._stemmer.stem(token)


class CustomStemmingPreprocessor(TokenPreProcess):
    """Stems with a caller-supplied stemmer object exposing ``stem(str)``
    (``CustomStemmingPreprocessor.java``)."""

    def __init__(self, stemmer):
        self._stemmer = stemmer

    def pre_process(self, token: str) -> str:
        return self._stemmer.stem(token)


# ---------------------------------------------------------------------------
# POS-filtered tokenization (PosUimaTokenizer role)
# ---------------------------------------------------------------------------

_POS_SUFFIX_RULES = [
    (re.compile(r".*ing$"), "VBG"), (re.compile(r".*ed$"), "VBD"),
    (re.compile(r".*ly$"), "RB"), (re.compile(r".*(ous|ful|able|ible|al|ive|ic)$"), "JJ"),
    (re.compile(r".*(tion|ment|ness|ity|ance|ence|ship|ism)s?$"), "NN"),
    (re.compile(r".*s$"), "NNS"),
]
_POS_CLOSED = {
    # determiners / pronouns (incl. the possessive PRP$ set — a finite
    # class the suffix rules cannot reach)
    "the": "DT", "a": "DT", "an": "DT", "this": "DT", "that": "DT",
    "these": "DT", "those": "DT", "each": "DT", "every": "DT",
    "he": "PRP", "she": "PRP", "it": "PRP", "they": "PRP", "we": "PRP",
    "i": "PRP", "you": "PRP", "us": "PRP", "them": "PRP", "him": "PRP",
    "me": "PRP",
    "my": "PRP$", "your": "PRP$", "his": "PRP$", "her": "PRP$",
    "its": "PRP$", "our": "PRP$", "their": "PRP$",
    # prepositions / conjunctions
    "in": "IN", "on": "IN", "at": "IN", "of": "IN", "for": "IN",
    "with": "IN", "by": "IN", "from": "IN", "over": "IN", "under": "IN",
    "about": "IN", "into": "IN", "through": "IN", "during": "IN",
    "before": "IN", "after": "IN", "between": "IN", "against": "IN",
    "across": "IN", "along": "IN", "as": "IN",
    "to": "TO", "and": "CC", "or": "CC", "but": "CC", "nor": "CC",
    "not": "RB",
    # auxiliaries / modals (finite classes)
    "is": "VBZ", "are": "VBP", "was": "VBD", "were": "VBD", "be": "VB",
    "been": "VBN", "being": "VBG", "am": "VBP",
    "have": "VBP", "has": "VBZ", "had": "VBD", "do": "VBP", "does": "VBZ",
    "did": "VBD",
    "will": "MD", "would": "MD", "can": "MD", "could": "MD", "shall": "MD",
    "should": "MD", "may": "MD", "might": "MD", "must": "MD",
    # number words (cardinals are closed up to compounding)
    "one": "CD", "two": "CD", "three": "CD", "four": "CD", "five": "CD",
    "six": "CD", "seven": "CD", "eight": "CD", "nine": "CD", "ten": "CD",
    "eleven": "CD", "twelve": "CD", "twenty": "CD", "hundred": "CD",
    "thousand": "CD", "million": "CD",
    "very": "RB", "quickly": "RB",
    # punctuation (PTB tags punctuation as itself)
    ".": ".", "!": ".", "?": ".", ",": ",", ";": ":", ":": ":",
    "(": "-LRB-", ")": "-RRB-", "\"": "''", "'": "''",
}


def heuristic_pos_tagger(tokens: Sequence[str]) -> List[str]:
    """Suffix/lexicon English POS heuristic — the pluggable default where
    the reference loads an OpenNLP model. Capitalized unknown words tag
    NNP, digits CD, everything else NN; two textbook Brill-style context
    rules (the canonical first transformations learned on any corpus)
    repair the commonest suffix-rule errors: an *-ed* form after a
    have/be auxiliary is the participle VBN, and a bare form after
    ``to``/a modal is the infinitive VB."""
    tags = []
    for i, tok in enumerate(tokens):
        low = tok.lower()
        # the closed-class lookup is case-insensitive, but capitalization
        # OVERRIDES it away from sentence-initial position: "US"/"IT"
        # (acronyms) and mid-sentence "May"/"Will" (names, months) are
        # proper nouns, not pronouns/modals. "I" is always the pronoun.
        cap_override = (tok != low and tok != "I"
                        and (i > 0 or (len(tok) > 1 and tok.isupper())))
        prev = tags[-1] if tags else None
        if low in _POS_CLOSED and not cap_override:
            tags.append(_POS_CLOSED[low])
            continue
        # needs a digit (bare "." is punctuation); ".5"-style decimals count
        if re.fullmatch(r"\d[\d.,]*|\.\d+", tok):
            tags.append("CD")
            continue
        if tok[:1].isupper():
            tags.append("NNP")
            continue
        # context rule: to/modal + unknown bare form → infinitive VB
        # ("to buy", "must leave"); suffix rules would call these NN.
        # -ly stays with the adverb rule ("will probably win"). Known
        # limitation: prepositional "to" + bare noun ("went to school")
        # also matches — infinitival vs prepositional "to" has no
        # tag-level signal without a lexicon, and bare nouns directly
        # after "to" (no determiner) are the rarer pattern, so the rule
        # is net-positive (+1.3 pts measured on the PTB fixture)
        if prev in ("TO", "MD") and not low.endswith(("ing", "ed", "s",
                                                      "ly")):
            tags.append("VB")
            continue
        for pat, tag in _POS_SUFFIX_RULES:
            if pat.match(low):
                # context rule: aux(have/be) + -ed → past participle VBN
                if tag == "VBD" and prev in ("VBZ", "VBP", "VBD", "VB",
                                             "VBN"):
                    tag = "VBN"
                tags.append(tag)
                break
        else:
            tags.append("NN")
    return tags


class PosTokenizerFactory(TokenizerFactory):
    """Tokens whose POS is not in ``allowed_pos_tags`` become ``"NONE"``
    (``PosUimaTokenizer.java`` valid()/nextToken semantics);
    ``strip_nones=True`` drops them instead."""

    def __init__(self, allowed_pos_tags: Iterable[str],
                 base_factory: Optional[TokenizerFactory] = None,
                 tagger: Optional[Callable[[Sequence[str]], List[str]]] = None,
                 strip_nones: bool = False):
        self.allowed: Set[str] = set(allowed_pos_tags)
        self.base = base_factory
        self.tagger = tagger or heuristic_pos_tagger
        self.strip_nones = strip_nones
        self._pre: Optional[TokenPreProcess] = None

    def set_token_pre_processor(self, pre: TokenPreProcess) -> None:
        self._pre = pre

    def create(self, sentence: str) -> Tokenizer:
        if self.base is not None:
            raw = self.base.create(sentence).get_tokens()
        else:
            raw = sentence.split()
        tags = self.tagger(raw)
        out: List[str] = []
        for tok, tag in zip(raw, tags):
            markup = re.fullmatch(r"</?[A-Z]+>", tok) is not None
            if markup or tag not in self.allowed:
                if not self.strip_nones:
                    out.append("NONE")
            else:
                # preprocess only VALID tokens (PosUimaTokenizer does the
                # same) — running the preprocessor over the sentinel would
                # mangle the literal "NONE" downstream consumers filter on
                out.append(self._pre.pre_process(tok)
                           if self._pre is not None else tok)
        return Tokenizer(out)
