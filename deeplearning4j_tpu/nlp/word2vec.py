"""Word2Vec.

Parity with `models/word2vec/Word2Vec.java` (633 LoC): a SequenceVectors
specialisation whose input is sentences via a SentenceIterator +
TokenizerFactory, with the familiar builder surface (layerSize, windowSize,
negativeSample, minWordFrequency, …).
"""

from __future__ import annotations

from typing import Iterable, List, Optional, Sequence, Union

from deeplearning4j_tpu.nlp.learning import CBOW, SkipGram
from deeplearning4j_tpu.nlp.sentence import SentenceIterator
from deeplearning4j_tpu.nlp.sequencevectors import SequenceVectors
from deeplearning4j_tpu.nlp.tokenization import (
    DefaultTokenizerFactory,
    TokenizerFactory,
)


class Word2Vec(SequenceVectors):
    def __init__(self, layer_size: int = 100, window_size: int = 5,
                 negative_sample: int = 5,
                 use_hierarchic_softmax: bool = False,
                 learning_rate: float = 0.025,
                 min_learning_rate: float = 1e-4,
                 min_word_frequency: int = 5, sampling: float = 0.0,
                 epochs: int = 1, iterations: int = 1, seed: int = 12345,
                 algorithm: str = "skipgram",
                 tokenizer_factory: Optional[TokenizerFactory] = None,
                 sentence_iterator: Optional[SentenceIterator] = None):
        algo = CBOW() if algorithm.lower() == "cbow" else SkipGram()
        super().__init__(
            layer_size=layer_size, window=window_size,
            negative=negative_sample,
            use_hierarchic_softmax=use_hierarchic_softmax,
            learning_rate=learning_rate, min_learning_rate=min_learning_rate,
            min_word_frequency=min_word_frequency, sample=sampling,
            epochs=epochs, iterations=iterations, seed=seed,
            elements_algorithm=algo)
        self.tokenizer_factory = tokenizer_factory or DefaultTokenizerFactory()
        self.sentence_iterator = sentence_iterator

    class Builder:
        """Fluent builder (Word2Vec.Builder parity)."""

        def __init__(self):
            self._kw = {}

        def layer_size(self, n): self._kw["layer_size"] = n; return self
        def window_size(self, n): self._kw["window_size"] = n; return self
        def negative_sample(self, n): self._kw["negative_sample"] = n; return self
        def use_hierarchic_softmax(self, b): self._kw["use_hierarchic_softmax"] = b; return self
        def learning_rate(self, v): self._kw["learning_rate"] = v; return self
        def min_learning_rate(self, v): self._kw["min_learning_rate"] = v; return self
        def min_word_frequency(self, n): self._kw["min_word_frequency"] = n; return self
        def sampling(self, v): self._kw["sampling"] = v; return self
        def epochs(self, n): self._kw["epochs"] = n; return self
        def iterations(self, n): self._kw["iterations"] = n; return self
        def seed(self, n): self._kw["seed"] = n; return self
        def elements_learning_algorithm(self, name):
            self._kw["algorithm"] = name; return self
        def tokenizer_factory(self, tf): self._kw["tokenizer_factory"] = tf; return self
        def iterate(self, it): self._kw["sentence_iterator"] = it; return self

        def build(self) -> "Word2Vec":
            return Word2Vec(**self._kw)

    @classmethod
    def builder(cls) -> "Word2Vec.Builder":
        return cls.Builder()

    # ------------------------------------------------------------ training

    def _tokenize_corpus(
            self, sentences: Optional[Iterable[Union[str, Sequence[str]]]]
    ) -> List[List[str]]:
        src: Iterable = sentences if sentences is not None else self.sentence_iterator
        if src is None:
            raise ValueError("no sentences: pass them to fit() or set "
                             "sentence_iterator")
        out = []
        for s in src:
            if isinstance(s, str):
                out.append(self.tokenizer_factory.create(s).get_tokens())
            else:
                out.append(list(s))
        return out

    def fit(self, sentences: Optional[Iterable[Union[str, Sequence[str]]]] = None
            ) -> "Word2Vec":
        return super().fit(self._tokenize_corpus(sentences))

    def build_vocab(self, sentences=None):
        return super().build_vocab(self._tokenize_corpus(sentences))
