"""Sliding context windows over tokenized text.

Parity with the reference's ``text/movingwindow/`` package
(``Windows.java`` window generation, ``Window.java`` the window unit,
``WindowConverter.java`` window -> example array, and
``ContextLabelRetriever.java`` inline ``<LABEL> ... </LABEL>`` extraction).
Used for window-based training examples (e.g. NER-style classification
over word contexts).
"""

from __future__ import annotations

import math
import re
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = [
    "Window",
    "windows",
    "window_for_word_in_position",
    "as_example_array",
    "as_example_matrix",
    "string_with_labels",
]

_BEGIN_LABEL = re.compile(r"<([A-Za-z]+|\d+)>$")
_END_LABEL = re.compile(r"</([A-Za-z]+|\d+)>$")


class Window:
    """A context window around a focus word (``Window.java``).

    ``words`` has odd length; the median element is the focus. Inline
    ``<LABEL>`` / ``</LABEL>`` markers in the left/right context set
    ``label`` and the begin/end flags, as in the reference.
    """

    def __init__(self, words: Sequence[str], window_size: int,
                 begin: int, end: int):
        if not words:
            raise ValueError("Words must be non-empty")
        self.words = list(words)
        self.window_size = window_size
        self.begin = begin
        self.end = end
        self.label = "NONE"
        self.begin_label = False
        self.end_label = False
        self.median = int(math.floor(len(self.words) / 2))
        self._init_context()

    def _init_context(self) -> None:
        context = self.words[: self.median] + self.words[self.median + 1:]
        for s in context:
            if _BEGIN_LABEL.match(s):
                self.label = re.sub(r"[<>/]", "", s)
                self.begin_label = True
            elif _END_LABEL.match(s):
                self.end_label = True
                self.label = re.sub(r"[<>/]", "", s)

    def focus_word(self) -> str:
        return self.words[self.median]

    def as_tokens(self) -> str:
        return " ".join(self.words)

    def __repr__(self) -> str:
        return f"Window({self.as_tokens()!r}, label={self.label!r})"


def window_for_word_in_position(window_size: int, word_pos: int,
                                sentence: Sequence[str]) -> Window:
    """One window centred at ``word_pos``, padded with <s> / </s>
    (``Windows.java`` windowForWordInPosition)."""
    context = int(math.floor((window_size - 1) / 2))
    words: List[str] = []
    for i in range(word_pos - context, word_pos + context + 1):
        if i < 0:
            words.append("<s>")
        elif i >= len(sentence):
            words.append("</s>")
        else:
            words.append(sentence[i])
    return Window(words, window_size, max(0, word_pos - context),
                  min(len(sentence), word_pos + context + 1))


def windows(text_or_tokens, window_size: int = 5,
            tokenizer_factory=None) -> List[Window]:
    """All windows over a sentence (``Windows.java`` windows overloads).

    Accepts a raw string (whitespace-split, or via ``tokenizer_factory``)
    or a pre-tokenized list.
    """
    if isinstance(text_or_tokens, str):
        if tokenizer_factory is not None:
            tokens = tokenizer_factory.create(text_or_tokens).get_tokens()
        else:
            tokens = text_or_tokens.split()
    else:
        tokens = list(text_or_tokens)
    if not tokens:
        raise ValueError("No tokens found for windows")
    return [window_for_word_in_position(window_size, i, tokens)
            for i in range(len(tokens))]


def _vector_for(word_vectors, word: str, normalize: bool) -> Optional[np.ndarray]:
    getter = getattr(word_vectors, "vector", None)
    if getter is None:
        getter = getattr(word_vectors, "get_word_vector_matrix")
    v = getter(word)
    if v is None:
        return None
    v = np.asarray(v, dtype=np.float32).reshape(-1)
    if normalize:
        n = float(np.linalg.norm(v))
        if n > 0:
            v = v / n
    return v


def as_example_array(window: Window, word_vectors,
                     normalize: bool = False) -> np.ndarray:
    """Concatenated word vectors for a window
    (``WindowConverter.java`` asExampleArray). Missing words raise."""
    vecs = []
    for w in window.words:
        v = _vector_for(word_vectors, w, normalize)
        if v is None:
            raise ValueError(f"Word {w!r} has no vector")
        vecs.append(v)
    return np.concatenate(vecs)


def as_example_matrix(window: Window, word_vectors) -> np.ndarray:
    """Like :func:`as_example_array` but unknown words map to zeros
    (``WindowConverter.java`` asExampleMatrix)."""
    dim = None
    vecs: List[Optional[np.ndarray]] = []
    for w in window.words:
        v = _vector_for(word_vectors, w, False)
        vecs.append(v)
        if v is not None:
            dim = v.shape[0]
    if dim is None:
        raise ValueError("No known words in window")
    return np.concatenate([v if v is not None else np.zeros(dim, np.float32)
                           for v in vecs])


def string_with_labels(sentence: str, tokenizer_factory=None
                       ) -> Tuple[str, Dict[Tuple[int, int], str]]:
    """Strip inline ``<LABEL> ... </LABEL>`` spans from a sentence
    (``ContextLabelRetriever.java`` stringWithLabels).

    Returns ``(stripped_sentence, {(begin_token, end_token): label})``
    where the span indexes token positions in the stripped sentence.
    """
    if tokenizer_factory is not None:
        tokens = tokenizer_factory.create(sentence).get_tokens()
    else:
        tokens = sentence.split()

    segments: List[Tuple[str, List[str]]] = []
    curr: List[str] = []
    curr_label: Optional[str] = None
    for tok in tokens:
        if _BEGIN_LABEL.match(tok):
            if curr_label is not None:
                raise ValueError("Nested begin label before previous closed")
            if curr:
                segments.append(("NONE", curr))
                curr = []
            curr_label = re.sub(r"[<>/]", "", tok)
        elif _END_LABEL.match(tok):
            end = re.sub(r"[<>/]", "", tok)
            if curr_label is None:
                raise ValueError("Found an ending label with no matching begin label")
            if curr_label != end:
                raise ValueError(f"Begin/end label mismatch: {curr_label} vs {end}")
            segments.append((curr_label, curr))
            curr = []
            curr_label = None
        else:
            curr.append(tok)
    if curr_label is not None:
        raise ValueError(f"Unclosed label {curr_label}")
    if curr:
        segments.append(("NONE", curr))

    out_tokens: List[str] = []
    spans: Dict[Tuple[int, int], str] = {}
    for label, seg in segments:
        start = len(out_tokens)
        out_tokens.extend(seg)
        if label != "NONE" and seg:
            spans[(start, len(out_tokens))] = label
    return " ".join(out_tokens), spans
