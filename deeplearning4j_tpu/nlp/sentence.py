"""Sentence / document iterators.

Parity with `text/sentenceiterator/` (BasicLineIterator, Collection-,
File-, Line-, StreamLine-, Aggregating-, MutipleEpochs-, Prefetching-,
Synchronized- variants plus SentencePreProcessor) and
`text/documentiterator/` (DocumentIterator, FileDocumentIterator,
LabelsSource, Basic/File/FilenamesLabelAwareIterator — the labelled
document sources used by ParagraphVectors).
"""

from __future__ import annotations

import os
import queue
import threading
from typing import Callable, Iterable, Iterator, List, Optional, Sequence, Tuple

SentencePreProcessor = Callable[[str], str]


class SentenceIterator:
    """Streams sentences; reset() restarts from the beginning.

    An optional pre-processor (``SentencePreProcessor.java``) is applied
    inside ``next_sentence`` itself, as the reference iterators do — both
    the iteration protocol and explicit has_next()/next_sentence() loops
    see processed text.
    """

    _pre_processor: Optional[SentencePreProcessor] = None

    def next_sentence(self) -> Optional[str]:
        raise NotImplementedError

    def has_next(self) -> bool:
        raise NotImplementedError

    def reset(self) -> None:
        raise NotImplementedError

    def set_pre_processor(self, fn: Optional[SentencePreProcessor]) -> None:
        self._pre_processor = fn

    def get_pre_processor(self) -> Optional[SentencePreProcessor]:
        return self._pre_processor

    def _apply_pre(self, s: Optional[str]) -> Optional[str]:
        if s is not None and self._pre_processor is not None:
            return self._pre_processor(s)
        return s

    def __iter__(self) -> Iterator[str]:
        self.reset()
        while self.has_next():
            s = self.next_sentence()
            if s is not None:
                yield s


class CollectionSentenceIterator(SentenceIterator):
    def __init__(self, sentences: Sequence[str]):
        self._sentences = list(sentences)
        self._pos = 0

    def next_sentence(self) -> Optional[str]:
        if self._pos >= len(self._sentences):
            return None
        s = self._sentences[self._pos]
        self._pos += 1
        return self._apply_pre(s)

    def has_next(self) -> bool:
        return self._pos < len(self._sentences)

    def reset(self) -> None:
        self._pos = 0


class BasicLineIterator(SentenceIterator):
    """One sentence per line from a text file (BasicLineIterator.java)."""

    def __init__(self, path: str):
        self._path = path
        self._fh = None
        self._next: Optional[str] = None
        self.reset()

    def _advance(self) -> None:
        line = self._fh.readline()
        self._next = line.rstrip("\n") if line else None

    def next_sentence(self) -> Optional[str]:
        s = self._next
        self._advance()
        return self._apply_pre(s)

    def has_next(self) -> bool:
        return self._next is not None

    def reset(self) -> None:
        if self._fh:
            self._fh.close()
        self._fh = open(self._path, "r", encoding="utf-8")
        self._advance()


class FileSentenceIterator(SentenceIterator):
    """All lines of all files under a directory."""

    def __init__(self, root: str):
        self._root = root
        self.reset()

    def _paths(self) -> List[str]:
        if os.path.isfile(self._root):
            return [self._root]
        out = []
        for base, dirs, files in os.walk(self._root):
            dirs.sort()  # deterministic traversal order across platforms
            for f in sorted(files):
                out.append(os.path.join(base, f))
        return out

    def reset(self) -> None:
        # stream file-by-file, line-by-line — never materialize the corpus
        if getattr(self, "_fh", None) is not None:
            self._fh.close()
        self._file_queue: List[str] = self._paths()
        self._fh = None
        self._next: Optional[str] = None
        self._advance()

    def _advance(self) -> None:
        while True:
            if self._fh is not None:
                line = self._fh.readline()
                if line:
                    self._next = line.rstrip("\n")
                    return
                self._fh.close()
                self._fh = None
            if not self._file_queue:
                self._next = None
                return
            self._fh = open(self._file_queue.pop(0), "r", encoding="utf-8")

    def next_sentence(self) -> Optional[str]:
        s = self._next
        if s is not None:
            self._advance()
        return self._apply_pre(s)

    def has_next(self) -> bool:
        return self._next is not None


class LabelledDocument:
    """A document with labels (ParagraphVectors input unit)."""

    def __init__(self, content: str, labels: Sequence[str]):
        self.content = content
        self.labels = list(labels)


class LabelAwareIterator:
    """Streams LabelledDocuments (LabelAwareSentenceIterator parity)."""

    def __init__(self, docs: Iterable[Tuple[str, Sequence[str]]]):
        self._docs = [LabelledDocument(c, l) for c, l in docs]

    def __iter__(self) -> Iterator[LabelledDocument]:
        return iter(self._docs)


class LineSentenceIterator(BasicLineIterator):
    """One sentence per line of a single file (``LineSentenceIterator.java``;
    same contract as BasicLineIterator, kept as its own name for parity)."""


class StreamLineIterator(SentenceIterator):
    """Adapts a document stream to sentences line-by-line
    (``StreamLineIterator.java``).

    ``source`` is a DocumentIterator, a file-like object, or any iterable
    of document strings.
    """

    def __init__(self, source):
        # one-shot sources (generators, non-seekable streams) are
        # snapshotted here so reset() can restart them
        if isinstance(source, DocumentIterator):
            self._source: Optional[DocumentIterator] = source
            self._docs: List[str] = []
        else:
            self._source = None
            if hasattr(source, "read"):
                self._docs = [source.read()]
            else:
                self._docs = list(source)
        self.reset()

    def reset(self) -> None:
        if self._source is not None:
            self._source.reset()
            self._docs = list(self._source)
        self._lines: List[str] = []
        for doc in self._docs:
            self._lines.extend(doc.splitlines())
        self._pos = 0

    def next_sentence(self) -> Optional[str]:
        if self._pos >= len(self._lines):
            return None
        s = self._lines[self._pos]
        self._pos += 1
        return self._apply_pre(s)

    def has_next(self) -> bool:
        return self._pos < len(self._lines)


class AggregatingSentenceIterator(SentenceIterator):
    """Chains several backing sentence iterators
    (``AggregatingSentenceIterator.java``; the reference exposes a
    Builder.addSentenceIterator — pass the list here)."""

    def __init__(self, iterators: Sequence[SentenceIterator]):
        self._iterators = list(iterators)
        self.reset()

    @classmethod
    def builder(cls) -> "AggregatingSentenceIterator._Builder":
        return cls._Builder()

    class _Builder:
        def __init__(self):
            self._its: List[SentenceIterator] = []
            self._pre: Optional[SentencePreProcessor] = None

        def add_sentence_iterator(self, it: SentenceIterator) -> "AggregatingSentenceIterator._Builder":
            self._its.append(it)
            return self

        def add_sentence_pre_processor(self, fn: SentencePreProcessor) -> "AggregatingSentenceIterator._Builder":
            self._pre = fn
            return self

        def build(self) -> "AggregatingSentenceIterator":
            out = AggregatingSentenceIterator(self._its)
            out.set_pre_processor(self._pre)
            return out

    def reset(self) -> None:
        for it in self._iterators:
            it.reset()
        self._idx = 0

    def has_next(self) -> bool:
        while self._idx < len(self._iterators):
            if self._iterators[self._idx].has_next():
                return True
            self._idx += 1
        return False

    def next_sentence(self) -> Optional[str]:
        if not self.has_next():
            return None
        return self._apply_pre(self._iterators[self._idx].next_sentence())


class MutipleEpochsSentenceIterator(SentenceIterator):
    """Repeats the underlying iterator for N epochs
    (``MutipleEpochsSentenceIterator.java`` — reference spelling kept)."""

    def __init__(self, iterator: SentenceIterator, num_epochs: int):
        if num_epochs < 1:
            raise ValueError("num_epochs must be >= 1")
        self._it = iterator
        self._num_epochs = num_epochs
        self.reset()

    def reset(self) -> None:
        self._it.reset()
        self._epoch = 0

    def has_next(self) -> bool:
        if self._it.has_next():
            return True
        if self._epoch + 1 < self._num_epochs:
            self._epoch += 1
            self._it.reset()
            return self._it.has_next()
        return False

    def next_sentence(self) -> Optional[str]:
        if not self.has_next():
            return None
        return self._apply_pre(self._it.next_sentence())


class PrefetchingSentenceIterator(SentenceIterator):
    """Background-thread prefetch of an underlying iterator
    (``PrefetchingSentenceIterator.java``; the AsyncDataSetIterator idea
    applied to text)."""

    _END = object()

    def __init__(self, iterator: SentenceIterator, fetch_size: int = 10_000):
        self._it = iterator
        self._fetch_size = max(1, fetch_size)
        self._thread: Optional[threading.Thread] = None
        self.reset()

    @classmethod
    def builder(cls) -> "PrefetchingSentenceIterator._Builder":
        return cls._Builder()

    class _Builder:
        def __init__(self):
            self._it: Optional[SentenceIterator] = None
            self._size = 10_000
            self._pre: Optional[SentencePreProcessor] = None

        def set_sentence_iterator(self, it: SentenceIterator) -> "PrefetchingSentenceIterator._Builder":
            self._it = it
            return self

        def set_fetch_size(self, n: int) -> "PrefetchingSentenceIterator._Builder":
            self._size = n
            return self

        def set_sentence_pre_processor(self, fn: SentencePreProcessor) -> "PrefetchingSentenceIterator._Builder":
            self._pre = fn
            return self

        def build(self) -> "PrefetchingSentenceIterator":
            out = PrefetchingSentenceIterator(self._it, self._size)
            out.set_pre_processor(self._pre)
            return out

    def _producer(self, q: "queue.Queue", gen_id: int) -> None:
        # the underlying iterator is touched only under _it_lock so a
        # stale producer can't race reset()'s _it.reset(); the finally
        # guarantees _END even if the source raises mid-stream (a hung
        # consumer would otherwise block forever on q.get())
        try:
            while True:
                with self._it_lock:
                    if gen_id != self._gen:
                        return
                    if not self._it.has_next():
                        return
                    s = self._it.next_sentence()
                while True:
                    try:
                        q.put(s, timeout=0.1)
                        break
                    except queue.Full:
                        if gen_id != self._gen:  # orphaned by reset()
                            return
        except BaseException as e:  # noqa: BLE001 - re-raised on the consumer side
            self._error = e
        finally:
            while gen_id == self._gen:  # orphaned generations just drop _END
                try:
                    q.put(self._END, timeout=0.1)
                    break
                except queue.Full:
                    continue

    def reset(self) -> None:
        if not hasattr(self, "_it_lock"):
            self._it_lock = threading.Lock()
            self._gen = 0
        self._gen += 1  # orphans any in-flight producer
        old = getattr(self, "_thread", None)
        if old is not None and old.is_alive():
            old.join(timeout=2.0)
        with self._it_lock:
            self._it.reset()
        self._queue: "queue.Queue" = queue.Queue(maxsize=self._fetch_size)
        self._peeked: Optional[object] = None
        self._done = False
        self._error: Optional[BaseException] = None
        self._thread = threading.Thread(
            target=self._producer, args=(self._queue, self._gen), daemon=True)
        self._thread.start()

    def _peek(self):
        if self._done:
            return None
        if self._peeked is None:
            item = self._queue.get()
            if item is self._END:
                self._done = True
                if self._error is not None:  # source raised mid-stream
                    raise self._error
                return None
            self._peeked = item
        return self._peeked

    def has_next(self) -> bool:
        return self._peek() is not None

    def next_sentence(self) -> Optional[str]:
        s = self._peek()
        self._peeked = None
        return self._apply_pre(s) if s is not None else None


class SynchronizedSentenceIterator(SentenceIterator):
    """Lock-guarded wrapper making any iterator safe for concurrent
    consumers (``SynchronizedSentenceIterator.java``)."""

    def __init__(self, iterator: SentenceIterator):
        self._it = iterator
        self._lock = threading.RLock()

    def reset(self) -> None:
        with self._lock:
            self._it.reset()

    def has_next(self) -> bool:
        with self._lock:
            return self._it.has_next()

    def next_sentence(self) -> Optional[str]:
        with self._lock:
            if not self._it.has_next():
                return None
            return self._apply_pre(self._it.next_sentence())


# ---------------------------------------------------------------------------
# Document iterators (text/documentiterator/)
# ---------------------------------------------------------------------------


class DocumentIterator:
    """Streams whole documents (``DocumentIterator.java``)."""

    def next_document(self) -> str:
        raise NotImplementedError

    def has_next(self) -> bool:
        raise NotImplementedError

    def reset(self) -> None:
        raise NotImplementedError

    def __iter__(self) -> Iterator[str]:
        self.reset()
        while self.has_next():
            yield self.next_document()


class FileDocumentIterator(DocumentIterator):
    """Each file under ``root`` is one document
    (``FileDocumentIterator.java``)."""

    def __init__(self, root: str):
        self._root = root
        self.reset()

    def _paths(self) -> List[str]:
        if os.path.isfile(self._root):
            return [self._root]
        out = []
        for base, dirs, files in os.walk(self._root):
            dirs.sort()  # deterministic traversal order across platforms
            for f in sorted(files):
                out.append(os.path.join(base, f))
        return out

    def reset(self) -> None:
        self._queue = self._paths()

    def has_next(self) -> bool:
        return bool(self._queue)

    def next_document(self) -> str:
        path = self._queue.pop(0)
        with open(path, "r", encoding="utf-8", errors="replace") as fh:
            return fh.read()


class LabelsSource:
    """Generates or stores document labels (``LabelsSource.java``).

    Template mode (``LabelsSource("DOC_%d")`` or a prefix without ``%d``)
    hands out ``next_label()`` sequentially; list mode serves a fixed
    label list. ``store_label`` collects unique labels either way.
    """

    def __init__(self, template_or_labels=None):
        self._counter = 0
        self._template: Optional[str] = None
        self._labels: Optional[List[str]] = None
        self._uniq: set = set()
        # fixed at construction: store_label must not flip a template
        # source into list mode (next_label would then serve stored
        # labels and run off the end of the list)
        self._list_mode = False
        if isinstance(template_or_labels, str):
            self._template = template_or_labels
        elif template_or_labels is not None:
            self._labels = list(template_or_labels)
            self._uniq.update(self._labels)
            self._list_mode = True

    def _format(self, value: int) -> str:
        if self._template and "%d" in self._template:
            return self._template % value
        return f"{self._template or 'DOC_'}{value}"

    def next_label(self) -> str:
        if self._list_mode:
            label = self._labels[self._counter]
            self._counter += 1
            return label
        label = self._format(self._counter)
        self._counter += 1
        return label

    def store_label(self, label: str) -> None:
        if self._labels is None:
            self._labels = []
        if label not in self._uniq:
            self._uniq.add(label)
            self._labels.append(label)

    def index_of(self, label: str) -> int:
        return (self._labels or []).index(label)

    def size(self) -> int:
        return len(self.get_labels())

    def get_labels(self) -> List[str]:
        if self._labels:
            return list(self._labels)
        return [self._format(i) for i in range(self._counter)]

    def reset(self) -> None:
        self._counter = 0


class BasicLabelAwareIterator(LabelAwareIterator):
    """Wraps a sentence/document source, auto-generating one label per
    document from a LabelsSource template
    (``BasicLabelAwareIterator.java``)."""

    def __init__(self, source, labels_source: Optional[LabelsSource] = None):
        self.labels_source = labels_source or LabelsSource("doc_%d")
        docs: List[LabelledDocument] = []
        if isinstance(source, SentenceIterator) or isinstance(source, DocumentIterator):
            contents: Iterable[str] = source
        else:
            contents = source
        for content in contents:
            # template mode reconstructs get_labels() from the counter, so
            # no store_label here (SentenceIteratorConverter behavior)
            label = self.labels_source.next_label()
            docs.append(LabelledDocument(content, [label]))
        self._docs = docs


class FileLabelAwareIterator(LabelAwareIterator):
    """Documents from label-named subfolders: ``root/<label>/<file>``
    (``FileLabelAwareIterator.java``; addSourceFolder semantics)."""

    def __init__(self, roots: Sequence[str]):
        if isinstance(roots, str):
            roots = [roots]
        docs: List[LabelledDocument] = []
        self.labels_source = LabelsSource([])
        for root in roots:
            for label in sorted(os.listdir(root)):
                sub = os.path.join(root, label)
                if not os.path.isdir(sub):
                    continue
                for fname in sorted(os.listdir(sub)):
                    path = os.path.join(sub, fname)
                    if not os.path.isfile(path):
                        continue
                    with open(path, "r", encoding="utf-8", errors="replace") as fh:
                        docs.append(LabelledDocument(fh.read(), [label]))
                    self.labels_source.store_label(label)
        self._docs = docs

    @classmethod
    def builder(cls) -> "FileLabelAwareIterator._Builder":
        return cls._Builder()

    class _Builder:
        def __init__(self):
            self._roots: List[str] = []

        def add_source_folder(self, path: str) -> "FileLabelAwareIterator._Builder":
            self._roots.append(path)
            return self

        def build(self) -> "FileLabelAwareIterator":
            return FileLabelAwareIterator(self._roots)


class FilenamesLabelAwareIterator(LabelAwareIterator):
    """Each file is a document whose label is its filename
    (``FilenamesLabelAwareIterator.java``)."""

    def __init__(self, roots: Sequence[str], absolute_path_as_label: bool = False):
        if isinstance(roots, str):
            roots = [roots]
        docs: List[LabelledDocument] = []
        self.labels_source = LabelsSource([])
        for root in roots:
            for base, dirs, files in os.walk(root):
                dirs.sort()  # deterministic label order
                for fname in sorted(files):
                    path = os.path.join(base, fname)
                    label = path if absolute_path_as_label else fname
                    with open(path, "r", encoding="utf-8", errors="replace") as fh:
                        docs.append(LabelledDocument(fh.read(), [label]))
                    self.labels_source.store_label(label)
        self._docs = docs
