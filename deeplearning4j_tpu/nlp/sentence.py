"""Sentence / document iterators.

Parity with `text/sentenceiterator/` (BasicLineIterator, Collection-,
File-, and the labelled document variants used by ParagraphVectors).
"""

from __future__ import annotations

import os
from typing import Iterable, Iterator, List, Optional, Sequence, Tuple


class SentenceIterator:
    """Streams sentences; reset() restarts from the beginning."""

    def next_sentence(self) -> Optional[str]:
        raise NotImplementedError

    def has_next(self) -> bool:
        raise NotImplementedError

    def reset(self) -> None:
        raise NotImplementedError

    def __iter__(self) -> Iterator[str]:
        self.reset()
        while self.has_next():
            s = self.next_sentence()
            if s is not None:
                yield s


class CollectionSentenceIterator(SentenceIterator):
    def __init__(self, sentences: Sequence[str]):
        self._sentences = list(sentences)
        self._pos = 0

    def next_sentence(self) -> Optional[str]:
        if self._pos >= len(self._sentences):
            return None
        s = self._sentences[self._pos]
        self._pos += 1
        return s

    def has_next(self) -> bool:
        return self._pos < len(self._sentences)

    def reset(self) -> None:
        self._pos = 0


class BasicLineIterator(SentenceIterator):
    """One sentence per line from a text file (BasicLineIterator.java)."""

    def __init__(self, path: str):
        self._path = path
        self._fh = None
        self._next: Optional[str] = None
        self.reset()

    def _advance(self) -> None:
        line = self._fh.readline()
        self._next = line.rstrip("\n") if line else None

    def next_sentence(self) -> Optional[str]:
        s = self._next
        self._advance()
        return s

    def has_next(self) -> bool:
        return self._next is not None

    def reset(self) -> None:
        if self._fh:
            self._fh.close()
        self._fh = open(self._path, "r", encoding="utf-8")
        self._advance()


class FileSentenceIterator(SentenceIterator):
    """All lines of all files under a directory."""

    def __init__(self, root: str):
        self._root = root
        self.reset()

    def _paths(self) -> List[str]:
        if os.path.isfile(self._root):
            return [self._root]
        out = []
        for base, _, files in os.walk(self._root):
            for f in sorted(files):
                out.append(os.path.join(base, f))
        return out

    def reset(self) -> None:
        # stream file-by-file, line-by-line — never materialize the corpus
        if getattr(self, "_fh", None) is not None:
            self._fh.close()
        self._file_queue: List[str] = self._paths()
        self._fh = None
        self._next: Optional[str] = None
        self._advance()

    def _advance(self) -> None:
        while True:
            if self._fh is not None:
                line = self._fh.readline()
                if line:
                    self._next = line.rstrip("\n")
                    return
                self._fh.close()
                self._fh = None
            if not self._file_queue:
                self._next = None
                return
            self._fh = open(self._file_queue.pop(0), "r", encoding="utf-8")

    def next_sentence(self) -> Optional[str]:
        s = self._next
        if s is not None:
            self._advance()
        return s

    def has_next(self) -> bool:
        return self._next is not None


class LabelledDocument:
    """A document with labels (ParagraphVectors input unit)."""

    def __init__(self, content: str, labels: Sequence[str]):
        self.content = content
        self.labels = list(labels)


class LabelAwareIterator:
    """Streams LabelledDocuments (LabelAwareSentenceIterator parity)."""

    def __init__(self, docs: Iterable[Tuple[str, Sequence[str]]]):
        self._docs = [LabelledDocument(c, l) for c, l in docs]

    def __iter__(self) -> Iterator[LabelledDocument]:
        return iter(self._docs)
