"""NLP: embedding trainers, tokenization, vocab, vectorizers.

TPU-native re-design of the reference's `deeplearning4j-nlp` module
(SURVEY.md §2 "NLP: SequenceVectors family", 26.5k LoC):
`SequenceVectors.java` / `Word2Vec.java` / `ParagraphVectors.java` /
`Glove.java`. The reference trains embeddings hogwild-style — N JVM threads
racing on `InMemoryLookupTable` rows with no locks. Hogwild has no jit
analog; here training is deterministic minibatched scatter-add under a
single jit step (SURVEY.md §7 "Hard parts"), which keeps the MXU busy with
one big gather→dot→scatter per batch instead of millions of tiny row ops.
"""

from deeplearning4j_tpu.nlp.tokenization import (
    CommonPreprocessor,
    DefaultTokenizer,
    DefaultTokenizerFactory,
    NGramTokenizerFactory,
)
from deeplearning4j_tpu.nlp.dictionary_tokenizer import (
    DictionaryTokenizerFactory,
    MorphologicalDictionary,
)
from deeplearning4j_tpu.nlp.sentence import (
    AggregatingSentenceIterator,
    BasicLabelAwareIterator,
    BasicLineIterator,
    CollectionSentenceIterator,
    FileDocumentIterator,
    FileLabelAwareIterator,
    FilenamesLabelAwareIterator,
    FileSentenceIterator,
    LabelsSource,
    LineSentenceIterator,
    MutipleEpochsSentenceIterator,
    PrefetchingSentenceIterator,
    StreamLineIterator,
    SynchronizedSentenceIterator,
)
from deeplearning4j_tpu.nlp.cnn_sentence import (
    CnnSentenceDataSetIterator,
    CollectionLabeledSentenceProvider,
    FileLabeledSentenceProvider,
    LabelAwareConverter,
    LabeledSentenceProvider,
)
from deeplearning4j_tpu.nlp.stemming import (
    CustomStemmingPreprocessor,
    EmbeddedStemmingPreprocessor,
    PorterStemmer,
    PosTokenizerFactory,
    StemmingPreprocessor,
)
from deeplearning4j_tpu.nlp.sentiment import SWN3
from deeplearning4j_tpu.nlp.trees import (
    BinarizeTreeTransformer,
    CollapseUnaries,
    HeadWordFinder,
    Tree,
    TreeVectorizer,
)
from deeplearning4j_tpu.nlp.text_utils import (
    InMemoryInvertedIndex,
    InputHomogenization,
    StopWords,
)
from deeplearning4j_tpu.nlp.vocab import VocabCache, VocabConstructor, VocabWord
from deeplearning4j_tpu.nlp.lookup import InMemoryLookupTable
from deeplearning4j_tpu.nlp.sequencevectors import SequenceVectors
from deeplearning4j_tpu.nlp.word2vec import Word2Vec
from deeplearning4j_tpu.nlp.paragraph_vectors import ParagraphVectors
from deeplearning4j_tpu.nlp.glove import Glove
from deeplearning4j_tpu.nlp.serializer import WordVectorSerializer
from deeplearning4j_tpu.nlp.vectorizer import (
    BagOfWordsVectorizer,
    TfidfVectorizer,
)

__all__ = [
    "AggregatingSentenceIterator", "BagOfWordsVectorizer",
    "CnnSentenceDataSetIterator", "CollectionLabeledSentenceProvider",
    "FileLabeledSentenceProvider", "LabelAwareConverter",
    "LabeledSentenceProvider",
    "BinarizeTreeTransformer", "CollapseUnaries", "CustomStemmingPreprocessor",
    "EmbeddedStemmingPreprocessor", "HeadWordFinder", "PorterStemmer",
    "PosTokenizerFactory", "SWN3", "StemmingPreprocessor", "Tree",
    "TreeVectorizer",
    "BasicLabelAwareIterator", "BasicLineIterator",
    "CollectionSentenceIterator", "CommonPreprocessor", "DefaultTokenizer",
    "DefaultTokenizerFactory", "DictionaryTokenizerFactory",
    "FileDocumentIterator", "FileLabelAwareIterator",
    "FilenamesLabelAwareIterator", "FileSentenceIterator", "Glove",
    "InMemoryInvertedIndex", "InMemoryLookupTable", "InputHomogenization",
    "LabelsSource", "LineSentenceIterator", "MorphologicalDictionary",
    "MutipleEpochsSentenceIterator", "NGramTokenizerFactory",
    "ParagraphVectors", "PrefetchingSentenceIterator", "SequenceVectors",
    "StopWords", "StreamLineIterator", "SynchronizedSentenceIterator",
    "TfidfVectorizer", "VocabCache", "VocabConstructor", "VocabWord",
    "Word2Vec", "WordVectorSerializer",
]
