"""Language-pack tokenizer factories: Chinese, Japanese, Korean, UIMA-style.

Capability parity with the reference's language modules
(`deeplearning4j-nlp-chinese` — ansj segmenter, `deeplearning4j-nlp-japanese`
— bundled kuromoji, `deeplearning4j-nlp-korean`, `deeplearning4j-nlp-uima`
— `UimaTokenizerFactory.java` sentence/token pipeline). The reference vendors
JVM morphological analysers (~20k LoC); here each language gets a compact,
dependency-free segmenter with the same SPI (:class:`TokenizerFactory`) and
an optional user dictionary for the dictionary-driven languages:

- Chinese: forward-maximum-matching over a user dictionary when given,
  falling back to single-character (hanzi) tokens — the standard baseline
  ansj degrades to without its bundled dictionary.
- Japanese: script-transition segmentation (kanji/hiragana/katakana/latin
  runs), splitting where the writing system changes — the shape kuromoji's
  lattice produces for dictionary-less text, plus maximum-matching when a
  user dictionary is supplied.
- Korean: whitespace eojeol splitting with optional particle (josa)
  stripping.
- UIMA: regex sentence segmentation + per-sentence tokenization, mirroring
  the SentenceAnnotator→TokenizerAnnotator pipeline.
"""

from __future__ import annotations

import re
import unicodedata
from typing import Iterable, List, Optional, Sequence, Set

from deeplearning4j_tpu.nlp.tokenization import (
    TokenPreProcess,
    Tokenizer,
    TokenizerFactory,
)


def _max_match(text: str, dictionary: Set[str], max_len: int) -> List[str]:
    """Forward maximum matching: greedily take the longest dictionary word."""
    out, i, n = [], 0, len(text)
    while i < n:
        match = None
        for L in range(min(max_len, n - i), 1, -1):
            cand = text[i:i + L]
            if cand in dictionary:
                match = cand
                break
        if match is None:
            match = text[i]
        out.append(match)
        i += len(match)
    return out


def _char_class(ch: str) -> str:
    o = ord(ch)
    if 0x4E00 <= o <= 0x9FFF or 0x3400 <= o <= 0x4DBF:
        return "han"
    if 0x3040 <= o <= 0x309F:
        return "hiragana"
    if 0x30A0 <= o <= 0x30FF or o == 0x30FC:  # incl. long-vowel mark
        return "katakana"
    if 0xAC00 <= o <= 0xD7AF:
        return "hangul"
    if ch.isspace():
        return "space"
    if unicodedata.category(ch).startswith("P"):
        return "punct"
    if ch.isdigit():
        return "digit"
    return "latin"


def _split_scripts(sentence: str) -> List[str]:
    """Runs of identical character class; space/punct runs are dropped."""
    out: List[str] = []
    cur, cur_cls = "", None
    for ch in sentence:
        cls = _char_class(ch)
        if cls != cur_cls and cur:
            if cur_cls not in ("space", "punct"):
                out.append(cur)
            cur = ""
        cur += ch
        cur_cls = cls
    if cur and cur_cls not in ("space", "punct"):
        out.append(cur)
    return out


class ChineseTokenizerFactory(TokenizerFactory):
    """Chinese segmentation (`deeplearning4j-nlp-chinese` ansj role).

    ``dictionary`` accepts either a word list (forward maximum matching —
    ansj's min-mode shape) or a
    :class:`~deeplearning4j_tpu.nlp.dictionary_tokenizer.MorphologicalDictionary`
    (cost-lattice Viterbi — ansj's n-gram CRF shape; measured against the
    greedy baseline in ``tests/test_dictionary_tokenizer.py::
    TestChineseSegmentationAccuracy``: viterbi 1.000 vs greedy 0.967 span
    F1 on the tagged fixture corpus)."""

    def __init__(self, dictionary: Optional[Iterable[str]] = None,
                 pre_processor: Optional[TokenPreProcess] = None):
        from deeplearning4j_tpu.nlp.dictionary_tokenizer import (
            MorphologicalDictionary)
        self._pre = pre_processor
        self._lattice = (dictionary
                         if isinstance(dictionary, MorphologicalDictionary)
                         else None)
        self._dict: Set[str] = (set() if self._lattice is not None
                                else set(dictionary or ()))
        self._max_len = max((len(w) for w in self._dict), default=1)

    def create(self, sentence: str) -> Tokenizer:
        from deeplearning4j_tpu.nlp.dictionary_tokenizer import (
            viterbi_segment)
        tokens: List[str] = []
        for run in _split_scripts(sentence):
            if _char_class(run[0]) == "han":
                if self._lattice is not None:
                    tokens.extend(e.surface for e in
                                  viterbi_segment(run, self._lattice))
                elif self._dict:
                    tokens.extend(_max_match(run, self._dict, self._max_len))
                else:
                    tokens.extend(run)  # per-hanzi fallback
            else:
                tokens.append(run)
        return Tokenizer(tokens, self._pre)


class JapaneseTokenizerFactory(TokenizerFactory):
    """Japanese segmentation (`deeplearning4j-nlp-japanese` kuromoji role).

    ``use_base_form`` is accepted for API parity with the kuromoji factory's
    baseform mode; without a morphological dictionary surface forms are
    returned either way.
    """

    def __init__(self, dictionary: Optional[Iterable[str]] = None,
                 use_base_form: bool = False,
                 pre_processor: Optional[TokenPreProcess] = None):
        self._pre = pre_processor
        self.use_base_form = use_base_form
        self._dict: Set[str] = set(dictionary or ())
        self._max_len = max((len(w) for w in self._dict), default=1)

    def create(self, sentence: str) -> Tokenizer:
        tokens: List[str] = []
        for run in _split_scripts(sentence):
            cls = _char_class(run[0])
            if cls in ("han", "hiragana") and self._dict:
                tokens.extend(_max_match(run, self._dict, self._max_len))
            else:
                tokens.append(run)
        return Tokenizer(tokens, self._pre)


# common single-character josa + a few frequent two-character particles
_KOREAN_JOSA = ("은", "는", "이", "가", "을", "를", "에", "의", "도", "로",
                "과", "와", "만", "께", "에서", "에게", "으로", "까지", "부터",
                "하고", "이다", "입니다")


class KoreanTokenizerFactory(TokenizerFactory):
    """Korean eojeol tokenizer (`deeplearning4j-nlp-korean` role): whitespace
    splitting with optional particle stripping."""

    def __init__(self, strip_josa: bool = False,
                 pre_processor: Optional[TokenPreProcess] = None):
        self._pre = pre_processor
        self.strip_josa = strip_josa
        self._josa = sorted(_KOREAN_JOSA, key=len, reverse=True)

    def create(self, sentence: str) -> Tokenizer:
        tokens: List[str] = []
        for run in _split_scripts(sentence):
            if self.strip_josa and _char_class(run[0]) == "hangul" and len(run) > 1:
                for josa in self._josa:
                    if run.endswith(josa) and len(run) > len(josa):
                        run = run[:-len(josa)]
                        break
            tokens.append(run)
        return Tokenizer(tokens, self._pre)


_SENTENCE_RE = re.compile(r"(?<=[.!?。！？])\s+")


class UimaTokenizerFactory(TokenizerFactory):
    """Sentence-annotating tokenizer (`deeplearning4j-nlp-uima/.../UimaTokenizerFactory.java`):
    segments into sentences first, then tokenizes each — the UIMA
    SentenceAnnotator → TokenizerAnnotator pipeline as plain functions."""

    def __init__(self, base_factory: Optional[TokenizerFactory] = None,
                 pre_processor: Optional[TokenPreProcess] = None):
        from deeplearning4j_tpu.nlp.tokenization import DefaultTokenizerFactory
        self._pre = pre_processor
        self.base = base_factory or DefaultTokenizerFactory()

    @staticmethod
    def segment_sentences(text: str) -> List[str]:
        return [s for s in _SENTENCE_RE.split(text.strip()) if s]

    def create(self, text: str) -> Tokenizer:
        tokens: List[str] = []
        for sent in self.segment_sentences(text):
            tokens.extend(self.base.create(sent).get_tokens())
        return Tokenizer(tokens, self._pre)
