"""GloVe embeddings.

Parity with `models/glove/Glove.java` (429 LoC): builds a co-occurrence
table from windowed corpus scans, then fits with the GloVe weighted
least-squares objective under AdaGrad. The reference loops nonzero cells in
shuffled order across threads; here the nonzeros are flat arrays and each
epoch is a sequence of fixed-size jitted AdaGrad steps.
"""

from __future__ import annotations

import functools
from collections import defaultdict
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp.sequencevectors import SequenceVectors
from deeplearning4j_tpu.nlp.tokenization import DefaultTokenizerFactory
from deeplearning4j_tpu.nlp.vocab import VocabConstructor


@functools.partial(jax.jit, donate_argnums=(0, 1, 2, 3, 4, 5, 6, 7))
def _glove_step(w, wc, b, bc, hw, hwc, hb, hbc,
                rows, cols, logx, weight, valid, lr):
    """One AdaGrad minibatch on nonzero co-occurrence cells.

    w/wc: center/context embeddings; b/bc their biases; h*: AdaGrad
    accumulators. diff = w_i·wc_j + b_i + bc_j − log X_ij;
    loss = f(X_ij)·diff²."""
    wi, wj = w[rows], wc[cols]
    diff = jnp.sum(wi * wj, axis=1) + b[rows] + bc[cols] - logx
    fdiff = weight * diff * valid
    gw = fdiff[:, None] * wj
    gwc = fdiff[:, None] * wi
    # AdaGrad
    new_hw = hw.at[rows].add(gw * gw, mode="drop")
    new_hwc = hwc.at[cols].add(gwc * gwc, mode="drop")
    new_hb = hb.at[rows].add(fdiff * fdiff, mode="drop")
    new_hbc = hbc.at[cols].add(fdiff * fdiff, mode="drop")
    eps = 1e-8
    w = w.at[rows].add(-lr * gw / jnp.sqrt(new_hw[rows] + eps), mode="drop")
    wc = wc.at[cols].add(-lr * gwc / jnp.sqrt(new_hwc[cols] + eps), mode="drop")
    b = b.at[rows].add(-lr * fdiff / jnp.sqrt(new_hb[rows] + eps), mode="drop")
    bc = bc.at[cols].add(-lr * fdiff / jnp.sqrt(new_hbc[cols] + eps), mode="drop")
    return w, wc, b, bc, new_hw, new_hwc, new_hb, new_hbc


class Glove(SequenceVectors):
    def __init__(self, layer_size: int = 100, window: int = 5,
                 learning_rate: float = 0.05, epochs: int = 5,
                 min_word_frequency: int = 1, x_max: float = 100.0,
                 alpha: float = 0.75, batch_size: int = 8192,
                 symmetric: bool = True, seed: int = 12345,
                 tokenizer_factory=None):
        super().__init__(layer_size=layer_size, window=window,
                         learning_rate=learning_rate, epochs=epochs,
                         min_word_frequency=min_word_frequency, seed=seed)
        self.x_max = x_max
        self.alpha = alpha
        self.batch_size = batch_size
        self.symmetric = symmetric
        self.tokenizer_factory = tokenizer_factory or DefaultTokenizerFactory()

    def _tokenize(self, sentences) -> List[List[str]]:
        out = []
        for s in sentences:
            if isinstance(s, str):
                out.append(self.tokenizer_factory.create(s).get_tokens())
            else:
                out.append(list(s))
        return out

    def build_cooccurrence(self, corpus: List[List[str]]
                           ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Windowed counts weighted 1/distance (AbstractCoOccurrences.java)."""
        counts: Dict[Tuple[int, int], float] = defaultdict(float)
        for tokens in corpus:
            idx = [self.vocab.index_of(t) for t in tokens]
            idx = [i for i in idx if i >= 0]
            for i, wi in enumerate(idx):
                for off in range(1, self.window + 1):
                    j = i + off
                    if j >= len(idx):
                        break
                    inc = 1.0 / off
                    counts[(wi, idx[j])] += inc
                    if self.symmetric:
                        counts[(idx[j], wi)] += inc
        rows = np.fromiter((k[0] for k in counts), np.int32, len(counts))
        cols = np.fromiter((k[1] for k in counts), np.int32, len(counts))
        vals = np.fromiter(counts.values(), np.float32, len(counts))
        return rows, cols, vals

    def fit(self, sentences: Iterable) -> "Glove":
        corpus = self._tokenize(sentences)
        if self.vocab is None:
            constructor = VocabConstructor(
                min_word_frequency=self.min_word_frequency)
            self.vocab = constructor.build_vocab(corpus)
        rows, cols, vals = self.build_cooccurrence(corpus)
        n, d = self.vocab.num_words(), self.layer_size
        rng = np.random.default_rng(self.seed)
        w = jnp.asarray((rng.random((n, d)) - 0.5) / d, jnp.float32)
        wc = jnp.asarray((rng.random((n, d)) - 0.5) / d, jnp.float32)
        b = jnp.zeros((n,), jnp.float32)
        bc = jnp.zeros((n,), jnp.float32)
        hw = jnp.zeros((n, d), jnp.float32)
        hwc = jnp.zeros((n, d), jnp.float32)
        hb = jnp.zeros((n,), jnp.float32)
        hbc = jnp.zeros((n,), jnp.float32)

        logx = np.log(vals)
        weight = np.minimum((vals / self.x_max) ** self.alpha, 1.0).astype(np.float32)
        m = len(rows)
        bs = self.batch_size
        for _epoch in range(self.epochs):
            order = rng.permutation(m)
            for start in range(0, m, bs):
                sel = order[start:start + bs]
                pad = bs - len(sel)
                r = np.concatenate([rows[sel], np.zeros(pad, np.int32)])
                c = np.concatenate([cols[sel], np.zeros(pad, np.int32)])
                lx = np.concatenate([logx[sel], np.zeros(pad, np.float32)])
                wt = np.concatenate([weight[sel], np.zeros(pad, np.float32)])
                vl = np.concatenate([np.ones(len(sel), np.float32),
                                     np.zeros(pad, np.float32)])
                (w, wc, b, bc, hw, hwc, hb, hbc) = _glove_step(
                    w, wc, b, bc, hw, hwc, hb, hbc,
                    r, c, lx.astype(np.float32), wt, vl,
                    jnp.float32(self.learning_rate))

        # final embedding = w + wc (standard GloVe)
        from deeplearning4j_tpu.nlp.lookup import InMemoryLookupTable
        self.lookup_table = InMemoryLookupTable(self.vocab, d, seed=self.seed,
                                                negative=0, use_hs=False,
                                                init_syn0=False)
        self.lookup_table.syn0 = w + wc
        return self
