"""Sentence-classification CNN data pipeline.

Parity with the reference's ``iterator/CnnSentenceDataSetIterator.java``
and ``iterator/provider/`` (CollectionLabeledSentenceProvider,
FileLabeledSentenceProvider, LabelAwareConverter): sentences are encoded
as stacked word vectors — features ``[mb, 1, maxLen, wordVectorSize]``
(``sentences_along_height=True``, the default) or
``[mb, 1, wordVectorSize, maxLen]`` — with one-hot 2d labels and a
``[mb, maxLen]`` feature mask when lengths differ, ready for a Conv2D +
GlobalPooling classifier (Kim-2014 style).
"""

from __future__ import annotations

import os
import random as _random
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

_DEFAULT_RNG = object()  # sentinel: "shuffle with a fresh per-instance rng"

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet

__all__ = [
    "LabeledSentenceProvider",
    "CollectionLabeledSentenceProvider",
    "FileLabeledSentenceProvider",
    "LabelAwareConverter",
    "CnnSentenceDataSetIterator",
]


class LabeledSentenceProvider:
    """Source of (sentence, label) pairs (``LabeledSentenceProvider.java``)."""

    def has_next(self) -> bool:
        raise NotImplementedError

    def next_sentence(self) -> Tuple[str, str]:
        raise NotImplementedError

    def reset(self) -> None:
        raise NotImplementedError

    def total_num_sentences(self) -> int:
        raise NotImplementedError

    def all_labels(self) -> List[str]:
        """Distinct labels, sorted alphabetically."""
        raise NotImplementedError

    def num_label_classes(self) -> int:
        return len(self.all_labels())


class CollectionLabeledSentenceProvider(LabeledSentenceProvider):
    """In-memory sentences + labels, optionally shuffled
    (``CollectionLabeledSentenceProvider.java``)."""

    def __init__(self, sentences: Sequence[str], labels: Sequence[str],
                 rng=_DEFAULT_RNG):
        if len(sentences) != len(labels):
            raise ValueError(
                f"Sentence size ({len(sentences)}) must match label size ({len(labels)})")
        self._sentences = list(sentences)
        self._labels = list(labels)
        self._rng = _random.Random() if rng is _DEFAULT_RNG else rng
        self._all_labels = sorted(set(labels))
        self._order = list(range(len(sentences)))
        self._cursor = 0
        self.reset()

    def reset(self) -> None:
        self._cursor = 0
        if self._rng is not None:
            self._rng.shuffle(self._order)

    def has_next(self) -> bool:
        return self._cursor < len(self._sentences)

    def next_sentence(self) -> Tuple[str, str]:
        i = self._order[self._cursor]
        self._cursor += 1
        return self._sentences[i], self._labels[i]

    def total_num_sentences(self) -> int:
        return len(self._sentences)

    def all_labels(self) -> List[str]:
        return list(self._all_labels)


class FileLabeledSentenceProvider(LabeledSentenceProvider):
    """One sentence/document per file, label -> list-of-files mapping
    (``FileLabeledSentenceProvider.java``)."""

    def __init__(self, files_by_label: Dict[str, Sequence[str]],
                 rng=_DEFAULT_RNG):
        self._all_labels = sorted(files_by_label.keys())
        label_to_idx = {l: i for i, l in enumerate(self._all_labels)}
        self._paths: List[str] = []
        self._label_idx: List[int] = []
        for label, paths in files_by_label.items():
            for p in paths:
                self._paths.append(os.fspath(p))
                self._label_idx.append(label_to_idx[label])
        self._rng = _random.Random() if rng is _DEFAULT_RNG else rng
        self._order = list(range(len(self._paths)))
        self._cursor = 0
        self.reset()

    def reset(self) -> None:
        self._cursor = 0
        if self._rng is not None:
            self._rng.shuffle(self._order)

    def has_next(self) -> bool:
        return self._cursor < len(self._paths)

    def next_sentence(self) -> Tuple[str, str]:
        i = self._order[self._cursor]
        self._cursor += 1
        with open(self._paths[i], "r", encoding="utf-8", errors="replace") as fh:
            return fh.read(), self._all_labels[self._label_idx[i]]

    def total_num_sentences(self) -> int:
        return len(self._paths)

    def all_labels(self) -> List[str]:
        return list(self._all_labels)


class LabelAwareConverter(LabeledSentenceProvider):
    """Adapts a LabelAwareIterator (LabelledDocument stream) to the
    provider interface (``LabelAwareConverter.java``)."""

    def __init__(self, iterator, labels: Optional[Sequence[str]] = None):
        self._docs = [(d.content, d.labels[0]) for d in iterator]
        if labels is None:
            labels = sorted({l for _, l in self._docs})
        self._all_labels = sorted(labels)
        self._cursor = 0

    def reset(self) -> None:
        self._cursor = 0

    def has_next(self) -> bool:
        return self._cursor < len(self._docs)

    def next_sentence(self) -> Tuple[str, str]:
        pair = self._docs[self._cursor]
        self._cursor += 1
        return pair

    def total_num_sentences(self) -> int:
        return len(self._docs)

    def all_labels(self) -> List[str]:
        return list(self._all_labels)


_UNKNOWN_SENTINEL = object()


class CnnSentenceDataSetIterator:
    """Word-vector-encoded sentence batches for CNN classifiers
    (``CnnSentenceDataSetIterator.java``).

    ``unknown_word_handling``: ``"remove"`` drops out-of-vocab tokens,
    ``"use_unknown"`` substitutes ``unknown_vector`` (zeros by default).
    Labels are one-hot against the provider's alphabetically sorted label
    list (``getLabels``/``getLabelClassMap`` parity). A feature mask
    ``[mb, max_len]`` is attached only when batch lengths differ.

    ``feature_format``: ``"NCHW"`` (reference layout, the default) or
    ``"NHWC"`` — this framework's conv layers take NHWC, so pass
    ``"NHWC"`` to feed a Conv2D+GlobalPooling classifier directly.
    """

    def __init__(self, sentence_provider: LabeledSentenceProvider,
                 word_vectors, tokenizer_factory=None,
                 unknown_word_handling: str = "remove",
                 use_normalized_word_vectors: bool = True,
                 minibatch_size: int = 32,
                 max_sentence_length: int = -1,
                 sentences_along_height: bool = True,
                 data_set_pre_processor=None,
                 unknown_vector: Optional[np.ndarray] = None,
                 feature_format: str = "NCHW"):
        if unknown_word_handling not in ("remove", "use_unknown"):
            raise ValueError("unknown_word_handling must be 'remove' or 'use_unknown'")
        if feature_format not in ("NCHW", "NHWC"):
            raise ValueError("feature_format must be 'NCHW' or 'NHWC'")
        self.provider = sentence_provider
        self.word_vectors = word_vectors
        self.tokenizer_factory = tokenizer_factory
        self.unknown_word_handling = unknown_word_handling
        self.use_normalized = use_normalized_word_vectors
        self.minibatch_size = minibatch_size
        self.max_sentence_length = max_sentence_length
        self.sentences_along_height = sentences_along_height
        self.pre_processor = data_set_pre_processor
        self.feature_format = feature_format

        probe = self._raw_vector_any()
        self.word_vector_size = int(probe.shape[0])
        if unknown_vector is None:
            unknown_vector = np.zeros(self.word_vector_size, np.float32)
        self.unknown_vector = np.asarray(unknown_vector, np.float32)

        labels = self.provider.all_labels()
        self.num_classes = len(labels)
        self._label_class_map = {l: i for i, l in enumerate(sorted(labels))}
        self._preloaded: Optional[Tuple[List[object], str]] = None
        self._cursor = 0

    @classmethod
    def builder(cls) -> "CnnSentenceDataSetIterator._Builder":
        return cls._Builder()

    class _Builder:
        def __init__(self):
            self._kw = {}

        def sentence_provider(self, p): self._kw["sentence_provider"] = p; return self
        def word_vectors(self, wv): self._kw["word_vectors"] = wv; return self
        def tokenizer_factory(self, tf): self._kw["tokenizer_factory"] = tf; return self
        def unknown_word_handling(self, h): self._kw["unknown_word_handling"] = h; return self
        def use_normalized_word_vectors(self, b): self._kw["use_normalized_word_vectors"] = b; return self
        def minibatch_size(self, n): self._kw["minibatch_size"] = n; return self
        def max_sentence_length(self, n): self._kw["max_sentence_length"] = n; return self
        def sentences_along_height(self, b): self._kw["sentences_along_height"] = b; return self
        def data_set_pre_processor(self, p): self._kw["data_set_pre_processor"] = p; return self
        def unknown_vector(self, v): self._kw["unknown_vector"] = v; return self
        def feature_format(self, f): self._kw["feature_format"] = f; return self
        def build(self) -> "CnnSentenceDataSetIterator":
            return CnnSentenceDataSetIterator(**self._kw)

    # -- word vector access ------------------------------------------------
    def _raw_vector_any(self) -> np.ndarray:
        wv = self.word_vectors
        vocab = getattr(wv, "vocab", None)
        words = None
        if vocab is not None and hasattr(vocab, "words"):
            words = list(vocab.words())
        if not words:
            raise ValueError("word_vectors has an empty vocabulary")
        return np.asarray(self._lookup(words[0]), np.float32).reshape(-1)

    def _lookup(self, word: str) -> Optional[np.ndarray]:
        wv = self.word_vectors
        if hasattr(wv, "get_word_vector"):
            return wv.get_word_vector(word)
        return wv.vector(word)

    def _has_word(self, word: str) -> bool:
        wv = self.word_vectors
        if hasattr(wv, "has_word"):
            return wv.has_word(word)
        return self._lookup(word) is not None

    def _get_vector(self, token) -> np.ndarray:
        if token is _UNKNOWN_SENTINEL:
            return self.unknown_vector
        v = np.asarray(self._lookup(token), np.float32).reshape(-1)
        if self.use_normalized:
            n = float(np.linalg.norm(v))
            if n > 0:
                v = v / n
        return v

    def _tokenize(self, sentence: str) -> List[object]:
        if self.tokenizer_factory is not None:
            tokens = self.tokenizer_factory.create(sentence).get_tokens()
        else:
            tokens = sentence.split()
        out: List[object] = []
        for tok in tokens:
            if not self._has_word(tok):
                if self.unknown_word_handling == "remove":
                    continue
                out.append(_UNKNOWN_SENTINEL)
            else:
                out.append(tok)
        return out

    # -- iterator protocol -------------------------------------------------
    def get_labels(self) -> List[str]:
        out = [""] * self.num_classes
        for label, idx in self._label_class_map.items():
            out[idx] = label
        return out

    def get_label_class_map(self) -> Dict[str, int]:
        return dict(self._label_class_map)

    def input_columns(self) -> int:
        return self.word_vector_size

    def total_examples(self) -> int:
        return self.provider.total_num_sentences()

    def reset(self) -> None:
        self.provider.reset()
        self._preloaded = None
        self._cursor = 0

    def _preload(self) -> None:
        while self._preloaded is None and self.provider.has_next():
            sentence, label = self.provider.next_sentence()
            tokens = self._tokenize(sentence)
            if tokens:
                self._preloaded = (tokens, label)

    def has_next(self) -> bool:
        self._preload()
        return self._preloaded is not None

    def __iter__(self):
        self.reset()
        while self.has_next():
            yield self.next()

    def next(self, num: Optional[int] = None) -> DataSet:
        if not self.has_next():
            raise StopIteration("No next element")
        num = num or self.minibatch_size

        batch: List[Tuple[List[object], str]] = []
        max_len, min_len = -1, 1 << 30
        if self._preloaded is not None:
            batch.append(self._preloaded)
            max_len = max(max_len, len(self._preloaded[0]))
            min_len = min(min_len, len(self._preloaded[0]))
            self._preloaded = None
        while len(batch) < num and self.provider.has_next():
            sentence, label = self.provider.next_sentence()
            tokens = self._tokenize(sentence)
            if tokens:
                max_len = max(max_len, len(tokens))
                min_len = min(min_len, len(tokens))
                batch.append((tokens, label))

        if self.max_sentence_length > 0:
            max_len = min(max_len, self.max_sentence_length)

        mb = len(batch)
        labels = np.zeros((mb, self.num_classes), np.float32)
        for i, (_, label) in enumerate(batch):
            if label not in self._label_class_map:
                raise ValueError(
                    f'Got label "{label}" that is not present in list of '
                    "LabeledSentenceProvider labels")
            labels[i, self._label_class_map[label]] = 1.0

        if self.sentences_along_height:
            features = np.zeros((mb, 1, max_len, self.word_vector_size), np.float32)
        else:
            features = np.zeros((mb, 1, self.word_vector_size, max_len), np.float32)
        for i, (tokens, _) in enumerate(batch):
            for j, tok in enumerate(tokens[:max_len]):
                vec = self._get_vector(tok)
                if self.sentences_along_height:
                    features[i, 0, j, :] = vec
                else:
                    features[i, 0, :, j] = vec
        if self.feature_format == "NHWC":
            features = np.transpose(features, (0, 2, 3, 1))

        features_mask = None
        if min_len != max_len:
            features_mask = np.zeros((mb, max_len), np.float32)
            for i, (tokens, _) in enumerate(batch):
                features_mask[i, : min(len(tokens), max_len)] = 1.0

        ds = DataSet(features, labels, features_mask, None)
        if self.pre_processor is not None:
            self.pre_processor(ds)
        self._cursor += mb
        return ds

    def load_single_sentence(self, sentence: str) -> np.ndarray:
        """Features for one sentence, mb=1 (``loadSingleSentence``)."""
        tokens = self._tokenize(sentence)
        if not tokens:
            raise ValueError(
                "Cannot convert sentence: no tokens (all words unknown?)")
        if self.max_sentence_length > 0:
            tokens = tokens[: self.max_sentence_length]
        n = len(tokens)
        if self.sentences_along_height:
            out = np.zeros((1, 1, n, self.word_vector_size), np.float32)
            for j, tok in enumerate(tokens):
                out[0, 0, j, :] = self._get_vector(tok)
        else:
            out = np.zeros((1, 1, self.word_vector_size, n), np.float32)
            for j, tok in enumerate(tokens):
                out[0, 0, :, j] = self._get_vector(tok)
        if self.feature_format == "NHWC":
            out = np.transpose(out, (0, 2, 3, 1))
        return out
