"""ParagraphVectors (doc2vec).

Parity with `models/paragraphvectors/ParagraphVectors.java` (1,461 LoC):
documents carry labels; label rows live in the same lookup table as words
and are trained by DM (label joins the context window) or DBOW (label
predicts document words). ``infer_vector`` trains a fresh row for an unseen
document with the table frozen.
"""

from __future__ import annotations

from typing import Iterable, List, Optional, Sequence, Tuple, Union

import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp.learning import DBOW, DM, make_keep_prob
from deeplearning4j_tpu.nlp.sentence import LabelAwareIterator, LabelledDocument
from deeplearning4j_tpu.nlp.sequencevectors import SequenceVectors
from deeplearning4j_tpu.nlp.tokenization import DefaultTokenizerFactory
from deeplearning4j_tpu.nlp.vocab import VocabConstructor, VocabWord


class ParagraphVectors(SequenceVectors):
    def __init__(self, layer_size: int = 100, window_size: int = 5,
                 negative_sample: int = 5, learning_rate: float = 0.025,
                 min_learning_rate: float = 1e-4,
                 min_word_frequency: int = 1, sampling: float = 0.0,
                 epochs: int = 1, iterations: int = 1, seed: int = 12345,
                 sequence_algorithm: str = "dm",
                 use_hierarchic_softmax: bool = False,
                 tokenizer_factory=None):
        algo = DBOW() if sequence_algorithm.lower() == "dbow" else DM()
        super().__init__(
            layer_size=layer_size, window=window_size,
            negative=negative_sample, learning_rate=learning_rate,
            min_learning_rate=min_learning_rate,
            min_word_frequency=min_word_frequency, sample=sampling,
            epochs=epochs, iterations=iterations, seed=seed,
            use_hierarchic_softmax=use_hierarchic_softmax,
            elements_algorithm=algo)
        self.tokenizer_factory = tokenizer_factory or DefaultTokenizerFactory()
        self.labels: List[str] = []

    def _to_docs(self, documents) -> List[Tuple[List[str], List[str]]]:
        """→ [(tokens, labels)]. Plain strings are auto-labelled DOC_i (the
        reference's behaviour for unlabelled sentence iterators)."""
        out = []
        for i, d in enumerate(documents):
            if isinstance(d, LabelledDocument):
                content, labels = d.content, d.labels
            elif isinstance(d, str):
                content, labels = d, [f"DOC_{i}"]
            else:
                content, labels = d
            if isinstance(content, str):
                tokens = self.tokenizer_factory.create(content).get_tokens()
            else:
                tokens = list(content)
            out.append((tokens, list(labels)))
        return out

    def fit(self, documents: Union[LabelAwareIterator, Iterable]
            ) -> "ParagraphVectors":
        docs = self._to_docs(documents)
        constructor = VocabConstructor(min_word_frequency=self.min_word_frequency)
        self.vocab = constructor.build_vocab(
            (tokens for tokens, _ in docs),
            labels=(labels for _, labels in docs))
        self.labels = [vw.word for vw in self.vocab.vocab_words() if vw.is_label]
        from deeplearning4j_tpu.nlp.lookup import InMemoryLookupTable
        self.lookup_table = InMemoryLookupTable(
            self.vocab, self.layer_size, seed=self.seed,
            use_hs=self.use_hs, negative=self.negative)
        algo = self._make_algorithm()
        keep = make_keep_prob(self.vocab, self.sample)
        encoded = [(self._label_indices(labels), self._encode(tokens))
                   for tokens, labels in docs]
        total = (sum(len(seq) for _, seq in encoded)
                 * self.epochs * self.iterations) or 1
        seen = 0
        for _epoch in range(self.epochs):
            for label_idx, seq in encoded:
                if len(seq) == 0:
                    continue
                for _it in range(self.iterations):
                    lr = max(self.learning_rate * (1.0 - seen / total),
                             self.min_learning_rate)
                    for li in label_idx:
                        algo.train_document(li, seq, lr, keep)
                    seen += len(seq)
        return self

    def _label_indices(self, labels: Sequence[str]) -> List[int]:
        return [self.vocab.index_of(l) for l in labels
                if self.vocab.index_of(l) >= 0]

    # --------------------------------------------------------------- query

    def get_label_vector(self, label: str) -> Optional[np.ndarray]:
        return self.get_word_vector(label)

    def infer_vector(self, text: Union[str, Sequence[str]],
                     steps: int = 10, lr: float = 0.025) -> np.ndarray:
        """Train a fresh document row against the frozen table
        (ParagraphVectors.inferVector parity)."""
        if isinstance(text, str):
            tokens = self.tokenizer_factory.create(text).get_tokens()
        else:
            tokens = list(text)
        seq = self._encode(tokens)
        if len(seq) == 0:
            return np.zeros(self.layer_size, np.float32)
        # Temp row appended to the table; restore afterwards. The saved
        # arrays are never donated: resize() reassigns table.syn0/.syn1neg
        # to fresh concatenated buffers before any donating jit step runs.
        import zlib
        table = self.lookup_table
        n = table.cache.num_words()
        saved_syn0, saved_syn1neg = table.syn0, table.syn1neg
        saved_syn1 = table.syn1
        content_seed = zlib.crc32(" ".join(tokens).encode("utf-8"))
        table.resize(n + 1, seed=content_seed)
        # resize() reallocates syn0/syn1neg but not syn1: the HS path (DM with
        # negative=0) would otherwise donate-and-train the frozen inner-node
        # weights during inference — copy so the model table stays untouched
        if table.syn1 is not None:
            table.syn1 = jnp.array(table.syn1)
        algo = self._make_algorithm()
        for step in range(steps):
            step_lr = max(lr * (1.0 - step / steps), self.min_learning_rate)
            algo.train_document(n, seq, step_lr)
        vec = np.asarray(table.syn0[n])
        table.syn0, table.syn1neg = saved_syn0, saved_syn1neg
        table.syn1 = saved_syn1
        table._unigram = None
        return vec
