"""Vocabulary construction + Huffman coding.

Parity with `models/word2vec/wordstore/VocabConstructor.java:31` (parallel
corpus scan, frequency cutoffs) and `models/embeddings/loader/` Huffman tree
construction: each vocab word gets a binary `code` and the list of inner-node
`points` used by hierarchical softmax.
"""

from __future__ import annotations

import heapq
from collections import Counter
from typing import Dict, Iterable, List, Optional, Sequence


class VocabWord:
    """One vocabulary element (VocabWord.java): word, frequency, Huffman
    code/points, unigram-table sampling weight."""

    __slots__ = ("word", "frequency", "index", "code", "points", "is_label",
                 "is_special")

    def __init__(self, word: str, frequency: float = 1.0,
                 is_label: bool = False, is_special: bool = False):
        self.word = word
        self.frequency = frequency
        self.index = -1
        self.code: List[int] = []
        self.points: List[int] = []
        self.is_label = is_label
        self.is_special = is_special

    def increment(self, by: float = 1.0) -> None:
        self.frequency += by

    def __repr__(self) -> str:
        return f"VocabWord({self.word!r}, f={self.frequency})"


class VocabCache:
    """In-memory vocab store (AbstractCache.java parity): word↔index maps,
    frequencies, total token count."""

    def __init__(self):
        self._words: Dict[str, VocabWord] = {}
        self._by_index: List[VocabWord] = []
        self.total_word_occurrences = 0.0

    def add_token(self, vw: VocabWord) -> None:
        if vw.word in self._words:
            self._words[vw.word].increment(vw.frequency)
        else:
            self._words[vw.word] = vw
        self.total_word_occurrences += vw.frequency

    def contains_word(self, word: str) -> bool:
        return word in self._words

    def word_for(self, word: str) -> Optional[VocabWord]:
        return self._words.get(word)

    def word_frequency(self, word: str) -> float:
        vw = self._words.get(word)
        return vw.frequency if vw else 0.0

    def index_of(self, word: str) -> int:
        vw = self._words.get(word)
        return vw.index if vw else -1

    def word_at_index(self, index: int) -> Optional[str]:
        if 0 <= index < len(self._by_index):
            return self._by_index[index].word
        return None

    def element_at_index(self, index: int) -> VocabWord:
        return self._by_index[index]

    def num_words(self) -> int:
        return len(self._by_index)

    def words(self) -> List[str]:
        return [vw.word for vw in self._by_index]

    def vocab_words(self) -> List[VocabWord]:
        return list(self._by_index)

    def truncate(self, min_frequency: float) -> None:
        """Drop words below the cutoff, keeping labels and special tokens
        (VocabConstructor pins special tokens through the cutoff)."""
        kept = {w: vw for w, vw in self._words.items()
                if vw.frequency >= min_frequency or vw.is_label
                or vw.is_special}
        self._words = kept
        self._by_index = []

    def update_indices(self) -> None:
        """Assign indices by descending frequency (word2vec convention)."""
        self._by_index = sorted(self._words.values(),
                                key=lambda vw: (-vw.frequency, vw.word))
        for i, vw in enumerate(self._by_index):
            vw.index = i

    def __len__(self) -> int:
        return len(self._by_index)


def build_huffman(cache: VocabCache) -> None:
    """Assign Huffman ``code`` / ``points`` to every word in the cache.

    Mirrors word2vec's tree build (reference `Huffman.java`): leaves are
    vocab words weighted by frequency; each word's code is its path of
    left/right choices, points are the inner-node ids along the path
    (usable as rows of syn1).
    """
    n = cache.num_words()
    if n == 0:
        return
    # heap of (freq, tiebreak, node_id); leaves are 0..n-1, inner n..2n-2
    heap = [(cache.element_at_index(i).frequency, i, i) for i in range(n)]
    heapq.heapify(heap)
    parent = {}
    binary = {}
    next_id = n
    while len(heap) > 1:
        f1, _, n1 = heapq.heappop(heap)
        f2, _, n2 = heapq.heappop(heap)
        parent[n1] = next_id
        parent[n2] = next_id
        binary[n1] = 0
        binary[n2] = 1
        heapq.heappush(heap, (f1 + f2, next_id, next_id))
        next_id += 1
    root = heap[0][2]
    for i in range(n):
        code: List[int] = []
        points: List[int] = []
        node = i
        while node != root:
            code.append(binary[node])
            node = parent[node]
            # inner node id relative to n (syn1 row); root included
            points.append(node - n)
        vw = cache.element_at_index(i)
        vw.code = list(reversed(code))
        vw.points = list(reversed(points))


def scan_corpus_file(path: str, *, n_threads: int = 4,
                     to_lower: bool = False) -> Dict[str, int]:
    """Word frequencies over a text file, split on ASCII whitespace.

    ``to_lower`` defaults to False, matching ``build_vocab_from_file`` /
    ``fit_file`` (the plain DefaultTokenizerFactory behavior) so counting
    directly and training with defaults key the vocabulary identically.

    The reference's parallel corpus scan (``VocabConstructor.java:31``) as a
    native component: C++ worker threads count per-chunk outside the GIL
    (``native/src/corpus_scan.cpp``), merged and returned in (count desc,
    word asc) order. Falls back to a single-pass Python count with the SAME
    tokenization (``bytes.split()`` = ASCII whitespace, ASCII lowercasing)
    when the native library is unavailable.
    """
    import ctypes

    from deeplearning4j_tpu import native as _n

    def _merge(pairs):
        # distinct byte tokens can decode (errors='replace') to the same
        # string — SUM collisions rather than keep the last one
        out: Dict[str, int] = {}
        for w, c in pairs:
            out[w] = out.get(w, 0) + int(c)
        return out

    lib = _n._load()  # prototypes declared in native._load()
    if lib is not None and hasattr(lib, "corpus_scan_file"):
        out = (ctypes.c_longlong * 3)()
        h = lib.corpus_scan_file(path.encode(), int(n_threads),
                                 1 if to_lower else 0, out)
        if h:  # nullptr = IO failure -> fall through to the Python path
            try:
                n_unique, _total, nbytes = out[0], out[1], out[2]
                words_buf = ctypes.create_string_buffer(int(nbytes))
                counts = (ctypes.c_longlong * int(n_unique))()
                lib.corpus_scan_fill(h, words_buf, counts)
                words = words_buf.raw[:int(nbytes)].decode(
                    "utf-8", errors="replace").split("\n")
                return _merge(zip(words, counts))
            finally:
                lib.corpus_scan_free(h)

    with open(path, "rb") as f:
        data = f.read()
    if to_lower:
        data = data.lower()
    counts = Counter(data.split())
    items = sorted(counts.items(), key=lambda kv: (-kv[1], kv[0]))
    return _merge((w.decode("utf-8", errors="replace"), c)
                  for w, c in items)


class VocabConstructor:
    """Builds a VocabCache from token sequences (VocabConstructor.java:31)."""

    def __init__(self, min_word_frequency: int = 1,
                 special_tokens: Sequence[str] = ()):
        self.min_word_frequency = min_word_frequency
        self.special_tokens = list(special_tokens)

    def build_vocab_from_file(self, path: str, *, n_threads: int = 4,
                              to_lower: bool = False) -> "VocabCache":
        """Fast path for file corpora: the native multithreaded scan feeds
        the same cutoff/Huffman pipeline as :meth:`build_vocab`."""
        counts = scan_corpus_file(path, n_threads=n_threads,
                                  to_lower=to_lower)
        total = sum(counts.values())
        cache = VocabCache()
        for tok in self.special_tokens:
            cache.add_token(VocabWord(tok,
                                      frequency=max(counts.get(tok, 1), 1),
                                      is_special=True))
            counts.pop(tok, None)
        for word, c in counts.items():
            cache.add_token(VocabWord(word, frequency=c))
        cache.truncate(self.min_word_frequency)
        cache.update_indices()
        cache.total_word_occurrences = float(total)
        build_huffman(cache)
        return cache

    def build_vocab(self, sequences: Iterable[Sequence[str]],
                    labels: Iterable[Sequence[str]] = ()) -> VocabCache:
        counts: Counter = Counter()
        total = 0
        for seq in sequences:
            counts.update(seq)
            total += len(seq)
        cache = VocabCache()
        for tok in self.special_tokens:
            cache.add_token(VocabWord(tok, frequency=max(counts.get(tok, 1), 1),
                                      is_special=True))
            counts.pop(tok, None)
        for word, c in counts.items():
            cache.add_token(VocabWord(word, frequency=c))
        for label_set in labels:
            for lab in label_set:
                existing = cache.word_for(lab)
                if existing is None:
                    cache.add_token(VocabWord(lab, frequency=1, is_label=True))
                else:
                    # label collides with a corpus word: pin it so the
                    # document keeps a trainable label row past the cutoff
                    existing.is_label = True
        cache.truncate(self.min_word_frequency)
        cache.update_indices()
        cache.total_word_occurrences = float(total)
        build_huffman(cache)
        return cache
