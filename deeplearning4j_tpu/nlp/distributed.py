"""Distributed NLP: text pipeline + mesh-sharded Word2Vec.

Capability parity with ``dl4j-spark-nlp`` (`TextPipeline.java` — tokenize,
count words with accumulators, build vocab/Huffman on the driver;
`Word2VecPerformer.java` — per-partition skip-gram updates): the corpus is
processed in shards (counting composes by dict-merge, exactly the Spark
accumulator pattern), and the skip-gram negative-sampling update for each
global batch of pairs runs sharded over the mesh 'data' axis — every device
computes dense gradient contributions for its pair shard and one ``psum``
combines them (replacing the reference's parameter-averaged per-partition
training with an *exactly* synchronous update).
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from deeplearning4j_tpu.nlp.learning import generate_sg_pairs
from deeplearning4j_tpu.nlp.tokenization import (
    CommonPreprocessor,
    DefaultTokenizerFactory,
    TokenizerFactory,
)
from deeplearning4j_tpu.parallel.mesh import DATA_AXIS, make_mesh, shard_map


class TextPipeline:
    """Sharded tokenize-and-count (`TextPipeline.java` role)."""

    def __init__(self, tokenizer_factory: Optional[TokenizerFactory] = None,
                 num_shards: int = 4):
        self.tf = tokenizer_factory or DefaultTokenizerFactory(CommonPreprocessor())
        self.num_shards = max(1, num_shards)

    def tokenize(self, sentences: Sequence[str]) -> List[List[str]]:
        return [self.tf.create(s).get_tokens() for s in sentences]

    @staticmethod
    def _count_shard(token_lists: Sequence[List[str]]) -> Dict[str, int]:
        counts: Dict[str, int] = {}
        for toks in token_lists:
            for t in toks:
                counts[t] = counts.get(t, 0) + 1
        return counts

    def word_counts(self, sentences: Sequence[str]) -> Dict[str, int]:
        """Shard → count → merge (the Spark accumulator pattern; shard counts
        are independent so this parallelises across processes/hosts)."""
        tokened = self.tokenize(sentences)
        shards = [tokened[i::self.num_shards] for i in range(self.num_shards)]
        total: Dict[str, int] = {}
        for shard in shards:
            for w, c in self._count_shard(shard).items():
                total[w] = total.get(w, 0) + c
        return total


class DistributedWord2Vec:
    """Skip-gram negative-sampling Word2Vec whose per-batch update is sharded
    over the mesh data axis.

    Each device gets a shard of the (center, context) pairs, computes the
    dense syn0/syn1neg gradient contribution by scatter-add into zeros, and a
    ``psum`` merges them — numerically identical to single-device training on
    the whole batch, scaled across ICI. (A table-sharded variant partitions
    rows instead when the vocab outgrows HBM replication.)
    """

    def __init__(self, layer_size: int = 100, window: int = 5,
                 negative: int = 5, learning_rate: float = 0.025,
                 min_word_frequency: int = 1, seed: int = 12345,
                 mesh: Optional[Mesh] = None, data_axis: str = DATA_AXIS,
                 tokenizer_factory: Optional[TokenizerFactory] = None):
        self.layer_size = layer_size
        self.window = window
        self.negative = negative
        self.learning_rate = learning_rate
        self.min_word_frequency = min_word_frequency
        self.seed = seed
        self.mesh = mesh if mesh is not None else make_mesh()
        self.data_axis = data_axis
        self.n_workers = int(self.mesh.shape[data_axis])
        self.pipeline = TextPipeline(tokenizer_factory,
                                     num_shards=self.n_workers)
        self.vocab: Dict[str, int] = {}
        self.index2word: List[str] = []
        self.syn0 = None
        self.syn1neg = None
        self._step = None
        self._unigram = None

    # -- vocab ------------------------------------------------------------
    def build_vocab(self, sentences: Sequence[str]) -> None:
        counts = self.pipeline.word_counts(sentences)
        vocab = sorted(
            ((w, c) for w, c in counts.items() if c >= self.min_word_frequency),
            key=lambda wc: (-wc[1], wc[0]))
        self.index2word = [w for w, _ in vocab]
        self.vocab = {w: i for i, w in enumerate(self.index2word)}
        self._counts = np.array([c for _, c in vocab], np.float64)
        k1, k2 = jax.random.split(jax.random.PRNGKey(self.seed))
        n, d = len(self.index2word), self.layer_size
        self.syn0 = (jax.random.uniform(k1, (max(n, 1), d)) - 0.5) / d
        self.syn1neg = jnp.zeros((max(n, 1), d))
        # unigram^0.75 negative-sampling table (word2vec convention)
        probs = self._counts ** 0.75
        probs /= probs.sum() if probs.sum() > 0 else 1.0
        self._unigram = probs

    # -- sharded step ------------------------------------------------------
    def _build_step(self):
        daxis = self.data_axis
        nw = self.n_workers

        def worker(syn0, syn1neg, centers, targets, labels, valid, lr):
            # centers [B/nw], targets/labels/valid [B/nw, 1+neg]
            h = syn0[centers]                             # [b, D]
            ctx = syn1neg[targets]                        # [b, K, D]
            dots = jnp.einsum("bkd,bd->bk", ctx, h)
            g = (jax.nn.sigmoid(dots) - labels) * valid   # [b, K]
            gh = jnp.einsum("bk,bkd->bd", g, ctx)         # d/dh
            gctx = g[..., None] * h[:, None, :]           # d/dctx
            d_syn0 = jnp.zeros_like(syn0).at[centers].add(-lr * gh)
            d_syn1 = jnp.zeros_like(syn1neg).at[targets].add(-lr * gctx)
            d_syn0 = jax.lax.psum(d_syn0, daxis)
            d_syn1 = jax.lax.psum(d_syn1, daxis)
            return syn0 + d_syn0, syn1neg + d_syn1

        rep = P()
        shard0 = P(self.data_axis)
        mapped = shard_map(worker, mesh=self.mesh,
                           in_specs=(rep, rep, shard0, shard0, shard0, shard0,
                                     rep),
                           out_specs=(rep, rep))
        return jax.jit(mapped, donate_argnums=(0, 1))

    def fit(self, sentences: Sequence[str], epochs: int = 1,
            batch_pairs: int = 8192) -> "DistributedWord2Vec":
        if not self.vocab:
            self.build_vocab(sentences)
        if self._step is None:
            self._step = self._build_step()
        rng = np.random.default_rng(self.seed)
        tokened = self.pipeline.tokenize(sentences)
        encoded = [np.array([self.vocab[t] for t in toks if t in self.vocab],
                            np.int32) for toks in tokened]
        n_vocab = len(self.index2word)
        cum = np.cumsum(self._unigram)
        for _ in range(epochs):
            centers_all, ctx_all = [], []
            for seq in encoded:
                if len(seq) < 2:
                    continue
                c, x = generate_sg_pairs(seq, self.window, rng)
                centers_all.append(c)
                ctx_all.append(x)
            if not centers_all:
                return self
            centers = np.concatenate(centers_all).astype(np.int32)
            contexts = np.concatenate(ctx_all).astype(np.int32)
            perm = rng.permutation(len(centers))
            centers, contexts = centers[perm], contexts[perm]
            # fixed-size chunks: the tail is padded with valid=0 rows so the
            # update math and the RNG stream are identical for ANY worker
            # count (distributed == single-device, bit-for-bit modulo psum
            # reduction order)
            step_rows = max(self.n_workers,
                            batch_pairs - batch_pairs % self.n_workers)
            for s in range(0, len(centers), step_rows):
                c = centers[s:s + step_rows]
                x = contexts[s:s + step_rows]
                real = len(c)
                if real < step_rows:
                    pad = step_rows - real
                    c = np.concatenate([c, np.zeros(pad, np.int32)])
                    x = np.concatenate([x, np.zeros(pad, np.int32)])
                negs = np.searchsorted(
                    cum, rng.random((len(c), self.negative))).astype(np.int32)
                negs = np.minimum(negs, n_vocab - 1)
                targets = np.concatenate([x[:, None], negs], axis=1)
                labels = np.zeros_like(targets, np.float32)
                labels[:, 0] = 1.0
                valid = np.ones_like(labels)
                valid[:, 1:] = (negs != x[:, None]).astype(np.float32)
                valid[real:] = 0.0
                self.syn0, self.syn1neg = self._step(
                    self.syn0, self.syn1neg, jnp.asarray(c),
                    jnp.asarray(targets), jnp.asarray(labels),
                    jnp.asarray(valid), jnp.float32(self.learning_rate))
        return self

    # -- queries -----------------------------------------------------------
    def has_word(self, w: str) -> bool:
        return w in self.vocab

    def get_word_vector(self, w: str) -> np.ndarray:
        return np.asarray(self.syn0[self.vocab[w]])

    def similarity(self, a: str, b: str) -> float:
        va = self.syn0[self.vocab[a]]
        vb = self.syn0[self.vocab[b]]
        return float(jnp.dot(va, vb)
                     / (jnp.linalg.norm(va) * jnp.linalg.norm(vb) + 1e-12))

    def words_nearest(self, w: str, top: int = 10) -> List[str]:
        v = self.syn0[self.vocab[w]]
        norms = jnp.linalg.norm(self.syn0, axis=1) * (jnp.linalg.norm(v) + 1e-12)
        sims = (self.syn0 @ v) / jnp.maximum(norms, 1e-12)
        sims = sims.at[self.vocab[w]].set(-jnp.inf)
        _, idx = jax.lax.top_k(sims, min(top, len(self.index2word) - 1))
        return [self.index2word[int(i)] for i in np.asarray(idx)]
