"""Text preparation utilities.

Parity targets (reference, deeplearning4j-nlp):
- ``text/inputsanitation/InputHomogenization.java`` — character-level text
  normalization (digits -> 'd', lowercasing, punctuation stripping, NFD).
- ``text/stopwords/StopWords.java`` — canonical English stop-word list.
- ``text/invertedindex/InvertedIndex.java`` — document/word posting index
  SPI (the reference ships the interface; the LuceneInvertedIndex impl
  lived outside this snapshot). Here: an in-memory implementation with the
  same query surface.
"""

from __future__ import annotations

import re
import threading
import unicodedata
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Set

__all__ = [
    "InputHomogenization",
    "StopWords",
    "InvertedIndex",
    "InMemoryInvertedIndex",
]

# A standard English stop-word list (function words + contractions), the
# role of the reference's bundled stopwords.txt resource.
_ENGLISH_STOP_WORDS = """
a about above after again against all am an and any are aren't as at be
because been before being below between both but by can't cannot could
couldn't did didn't do does doesn't doing don't down during each few for
from further had hadn't has hasn't have haven't having he he'd he'll he's
her here here's hers herself him himself his how how's i i'd i'll i'm i've
if in into is isn't it it's its itself let's me more most mustn't my myself
no nor not of off on once only or other ought our ours ourselves out over
own same shan't she she'd she'll she's should shouldn't so some such than
that that's the their theirs them themselves then there there's these they
they'd they'll they're they've this those through to too under until up
very was wasn't we we'd we'll we're we've were weren't what what's when
when's where where's which while who who's whom why why's with won't would
wouldn't you you'd you'll you're you've your yours yourself yourselves
""".split()


class StopWords:
    """English stop-word list (``StopWords.java`` getStopWords)."""

    _words: Optional[List[str]] = None

    @classmethod
    def get_stop_words(cls) -> List[str]:
        if cls._words is None:
            cls._words = list(_ENGLISH_STOP_WORDS)
        return cls._words


class InputHomogenization:
    """Normalizes raw text (``InputHomogenization.java:41`` transform()).

    - digits become ``d``
    - uppercase lowered unless ``preserve_case``
    - characters in ``ignore_characters_containing`` pass through untouched
    - NFD-normalized, then common punctuation stripped, runs of ``!``
      collapsed to one
    """

    _STRIP = '.,"\'()“”…|/\\[]‘’'

    def __init__(self, input_text: str, preserve_case: bool = False,
                 ignore_characters_containing: Optional[Sequence[str]] = None):
        self.input = input_text
        self.preserve_case = preserve_case
        self.ignore = set(ignore_characters_containing or ())

    def transform(self) -> str:
        out = []
        for ch in self.input:
            if ch in self.ignore:
                out.append(ch)
            elif ch.isdigit():
                out.append("d")
            elif ch.isupper() and not self.preserve_case:
                out.append(ch.lower())
            else:
                out.append(ch)
        s = unicodedata.normalize("NFD", "".join(out))
        # ignored characters survive the punctuation strip too
        s = s.translate({ord(c): None for c in self._STRIP
                         if c not in self.ignore})
        if "!" not in self.ignore:
            s = re.sub(r"!+", "!", s)
        return s


class InvertedIndex:
    """Word -> posting-list index SPI (``InvertedIndex.java``).

    The reference interface speaks VocabWord objects; here words are plain
    strings and documents are integer ids.
    """

    def document(self, index: int) -> List[str]:
        raise NotImplementedError

    def documents(self, word: str) -> List[int]:
        raise NotImplementedError

    def num_documents(self) -> int:
        raise NotImplementedError

    def words(self) -> Set[str]:
        raise NotImplementedError

    def add_word_to_doc(self, doc: int, word: str) -> None:
        raise NotImplementedError

    def add_words_to_doc(self, doc: int, words: Iterable[str]) -> None:
        for w in words:
            self.add_word_to_doc(doc, w)

    def finish(self) -> None:
        """Flush / seal the index (no-op for the in-memory impl)."""

    def total_words(self) -> int:
        raise NotImplementedError


class InMemoryInvertedIndex(InvertedIndex):
    """Thread-safe in-memory inverted index."""

    def __init__(self):
        self._docs: Dict[int, List[str]] = {}
        self._postings: Dict[str, List[int]] = {}
        self._total = 0
        self._lock = threading.Lock()

    def document(self, index: int) -> List[str]:
        return list(self._docs.get(index, []))

    def documents(self, word: str) -> List[int]:
        return list(self._postings.get(word, []))

    def num_documents(self) -> int:
        return len(self._docs)

    def words(self) -> Set[str]:
        return set(self._postings.keys())

    def add_word_to_doc(self, doc: int, word: str) -> None:
        with self._lock:
            self._docs.setdefault(doc, []).append(word)
            posting = self._postings.setdefault(word, [])
            if not posting or posting[-1] != doc:
                posting.append(doc)
            self._total += 1

    def total_words(self) -> int:
        return self._total

    def batch_iter(self, batch_size: int) -> Iterable[List[List[str]]]:
        """Yield documents in batches (the reference's batchDocs role)."""
        batch: List[List[str]] = []
        for idx in sorted(self._docs):
            batch.append(self._docs[idx])
            if len(batch) == batch_size:
                yield batch
                batch = []
        if batch:
            yield batch

    def each_doc(self, fn: Callable[[List[str]], None]) -> None:
        for idx in sorted(self._docs):
            fn(self._docs[idx])
