from deeplearning4j_tpu.autodiff.samediff import (  # noqa: F401
    SDVariable,
    SameDiff,
    TrainingConfig,
)
