"""SameDiff — standalone declarative graph-builder with SDVariable algebra.

Reference capability: ND4J's ``SameDiff``/``SDVariable`` API — the layer
below the reference repo (SURVEY.md §2.12, L0) that backs its SameDiff layer
SPI (``nn/conf/layers/samediff/AbstractSameDiffLayer.java``,
``nn/layers/samediff/SameDiffLayer.java:209`` builds a ``SameDiff`` graph per
layer). Users declare placeholders/variables, compose ops symbolically, and
the engine supplies execution, autodiff, and training.

TPU-first redesign: the reference engine interprets its op graph node by node
through libnd4j kernels and hand-written backprop ops. Here the graph is pure
metadata — a topologically ordered op tape — and ``_build_fn`` lowers it to
ONE pure JAX function ``f(variables, placeholders) -> outputs``. Execution is
``jax.jit(f)`` (XLA fuses the whole graph), gradients are ``jax.grad`` (no
per-op backward definitions), and ``fit`` is a single donated-buffer jitted
train step reusing the framework's updater transforms. Shapes are inferred
with ``jax.eval_shape`` (no FLOPs).

Example::

    sd = SameDiff.create()
    x = sd.place_holder("x", shape=(None, 4))
    w = sd.var("w", shape=(4, 3))
    b = sd.var("b", shape=(3,))
    out = sd.nn.softmax(x @ w + b, name="out")
    preds = sd.output({"x": features}, "out")["out"]
"""

from __future__ import annotations

import json
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp


def _normalize_dims(dims, keepdims_default=False):
    if dims is None or dims == ():
        return None
    if isinstance(dims, int):
        return (dims,)
    return tuple(int(d) for d in dims)


# ---------------------------------------------------------------------------
# Op registry: name -> fn(*input_arrays, **attrs) in jnp. One place, so the
# whole op set is visible and serializable by name.
# ---------------------------------------------------------------------------

def _conv2d(x, w, stride, padding):
    return jax.lax.conv_general_dilated(
        x, w, window_strides=tuple(stride), padding=padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def _pool2d(x, kind, size, stride, padding):
    init = -jnp.inf if kind == "max" else 0.0
    op = jax.lax.max if kind == "max" else jax.lax.add
    y = jax.lax.reduce_window(
        x, init, op, (1,) + tuple(size) + (1,), (1,) + tuple(stride) + (1,),
        padding)
    if kind == "avg":
        ones = jnp.ones(x.shape[:3] + (1,), x.dtype)
        counts = jax.lax.reduce_window(
            ones, 0.0, jax.lax.add, (1,) + tuple(size) + (1,),
            (1,) + tuple(stride) + (1,), padding)
        y = y / counts
    return y


def _conv1d(x, w, stride=1, padding="SAME"):
    """x [N,T,C], w [k,Cin,Cout] (ND4J Conv1D in NWC here — the repo's
    sequence layout)."""
    return jax.lax.conv_general_dilated(
        x, w, (int(stride),), padding,
        dimension_numbers=("NWC", "WIO", "NWC"))


def _depthwise_conv2d(x, w, stride=(1, 1), padding="SAME"):
    """x [N,H,W,C], w [kH,kW,C,mult] (ND4J DepthwiseConv2D, NHWC)."""
    c = x.shape[-1]
    w2 = jnp.reshape(w, (w.shape[0], w.shape[1], 1, c * w.shape[3]))
    return jax.lax.conv_general_dilated(
        x, w2, tuple(int(s) for s in stride), padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"), feature_group_count=c)


def _deconv2d(x, w, stride=(2, 2), padding="SAME"):
    """Transposed conv (ND4J DeConv2D): x [N,H,W,Cin], w [kH,kW,Cin,Cout].
    The kernel is spatially FLIPPED (gradient-of-conv semantics, matching
    DL4J and this repo's Deconvolution2DLayer — ``nn/layers/conv.py:222``);
    ``lax.conv_transpose`` alone computes the un-flipped variant."""
    return jax.lax.conv_transpose(
        x, jnp.flip(w, (0, 1)), tuple(int(s) for s in stride), padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def _space_to_depth(x, block_size=2):
    n, h, w, c = x.shape
    b = int(block_size)
    x = jnp.reshape(x, (n, h // b, b, w // b, b, c))
    return jnp.reshape(jnp.transpose(x, (0, 1, 3, 2, 4, 5)),
                       (n, h // b, w // b, b * b * c))


def _depth_to_space(x, block_size=2):
    n, h, w, c = x.shape
    b = int(block_size)
    x = jnp.reshape(x, (n, h, w, b, b, c // (b * b)))
    return jnp.reshape(jnp.transpose(x, (0, 1, 3, 2, 4, 5)),
                       (n, h * b, w * b, c // (b * b)))


def _gather_nd(params, indices):
    """ND4J ``gatherNd``: indices [..., D] index the first D dims."""
    idx = jnp.asarray(indices).astype(jnp.int32)
    return params[tuple(jnp.moveaxis(idx, -1, 0))]


def _ids(idx):
    return jnp.asarray(idx).astype(jnp.int32)


def _nseg(num_segments, op: str) -> int:
    """Segment ops need a STATIC segment count (it is the output shape —
    XLA cannot infer it from the ids at trace time the way ND4J's eager
    segmentSum does from max(ids))."""
    if num_segments is None:
        raise ValueError(
            f"{op} requires num_segments (static output size), e.g. "
            f"sd.math.{op}(data, ids, 5) or num_segments=5")
    return int(num_segments)


def _segment_mean(data, segment_ids, num_segments=None):
    ids = _ids(segment_ids)
    n = _nseg(num_segments, "segment_mean")
    tot = jax.ops.segment_sum(data, ids, n)
    cnt = jax.ops.segment_sum(jnp.ones(ids.shape, data.dtype), ids, n)
    return tot / jnp.maximum(cnt.reshape(cnt.shape + (1,) * (tot.ndim - cnt.ndim)), 1.0)


OPS: Dict[str, Callable] = {
    # arithmetic
    "add": lambda a, b: a + b,
    "sub": lambda a, b: a - b,
    "mul": lambda a, b: a * b,
    "div": lambda a, b: a / b,
    "pow": lambda a, b: a ** b,
    "neg": lambda a: -a,
    "rsub": lambda a, b: b - a,
    "rdiv": lambda a, b: b / a,
    "matmul": lambda a, b: a @ b,
    "maximum": jnp.maximum,
    "minimum": jnp.minimum,
    # structure
    "transpose": lambda a, axes=None: jnp.transpose(a, axes),
    "reshape": lambda a, shape=None: jnp.reshape(a, shape),
    "concat": lambda *xs, axis=0: jnp.concatenate(xs, axis=axis),
    "stack": lambda *xs, axis=0: jnp.stack(xs, axis=axis),
    "slice": lambda a, begin=None, size=None: jax.lax.dynamic_slice(a, begin, size),
    "strided_slice": lambda a, slices=None: a[tuple(slice(*s) for s in slices)],
    "gather": lambda a, idx, axis=0: jnp.take(a, idx.astype(jnp.int32), axis=axis),
    "one_hot": lambda a, depth=None: jax.nn.one_hot(a.astype(jnp.int32), depth),
    "cast": lambda a, dtype=None: a.astype(dtype),
    "where": lambda c, a, b: jnp.where(c, a, b),
    "tile": lambda a, reps=None: jnp.tile(a, reps),
    "expand_dims": lambda a, axis=0: jnp.expand_dims(a, axis),
    "squeeze": lambda a, axis=None: jnp.squeeze(a, axis),
    # reductions
    "sum": lambda a, dims=None, keepdims=False: jnp.sum(a, axis=dims, keepdims=keepdims),
    "mean": lambda a, dims=None, keepdims=False: jnp.mean(a, axis=dims, keepdims=keepdims),
    "max": lambda a, dims=None, keepdims=False: jnp.max(a, axis=dims, keepdims=keepdims),
    "min": lambda a, dims=None, keepdims=False: jnp.min(a, axis=dims, keepdims=keepdims),
    "prod": lambda a, dims=None, keepdims=False: jnp.prod(a, axis=dims, keepdims=keepdims),
    "std": lambda a, dims=None, keepdims=False, bias_corrected=True:
        jnp.std(a, axis=dims, keepdims=keepdims, ddof=1 if bias_corrected else 0),
    "variance": lambda a, dims=None, keepdims=False, bias_corrected=True:
        jnp.var(a, axis=dims, keepdims=keepdims, ddof=1 if bias_corrected else 0),
    "argmax": lambda a, dims=None: jnp.argmax(a, axis=dims),
    "argmin": lambda a, dims=None: jnp.argmin(a, axis=dims),
    "norm2": lambda a, dims=None, keepdims=False:
        jnp.sqrt(jnp.sum(a * a, axis=dims, keepdims=keepdims)),
    "norm1": lambda a, dims=None, keepdims=False:
        jnp.sum(jnp.abs(a), axis=dims, keepdims=keepdims),
    # elementwise math
    "abs": jnp.abs, "exp": jnp.exp, "log": jnp.log, "sqrt": jnp.sqrt,
    "square": jnp.square, "sin": jnp.sin, "cos": jnp.cos, "tan": jnp.tan,
    "floor": jnp.floor, "ceil": jnp.ceil, "round": jnp.round,
    "sign": jnp.sign, "erf": jax.scipy.special.erf,
    "clip_by_value": lambda a, clip_min=None, clip_max=None: jnp.clip(a, clip_min, clip_max),
    "reciprocal": lambda a: 1.0 / a,
    "log1p": jnp.log1p, "expm1": jnp.expm1,
    # comparisons (float outputs, ND4J-style)
    "cumsum": lambda a, axis=0: jnp.cumsum(a, axis=axis),
    "cumprod": lambda a, axis=0: jnp.cumprod(a, axis=axis),
    "sort": lambda a, axis=-1, descending=False: (
        -jnp.sort(-a, axis=axis) if descending else jnp.sort(a, axis=axis)),
    "logsumexp": lambda a, axis=None, keepdims=False: (
        jax.scipy.special.logsumexp(a, axis=axis, keepdims=keepdims)),
    "l2_normalize": lambda a, axis=-1, eps=1e-12: a / jnp.sqrt(
        jnp.maximum(jnp.sum(jnp.square(a), axis=axis, keepdims=True), eps)),
    "mod": lambda a, b: jnp.mod(a, b),
    "floor_div": lambda a, b: jnp.floor_divide(a, b),
    "atan": jnp.arctan,
    "asin": jnp.arcsin,
    "acos": jnp.arccos,
    "sinh": jnp.sinh,
    "cosh": jnp.cosh,
    "asinh": jnp.arcsinh,
    "acosh": jnp.arccosh,
    "atanh": jnp.arctanh,
    "atan2": lambda a, b: jnp.arctan2(a, b),
    "isnan": lambda a: jnp.isnan(a).astype(jnp.float32),
    "isinf": lambda a: jnp.isinf(a).astype(jnp.float32),
    "top_k": lambda a, k=1: jax.lax.top_k(a, k),
    "diag": jnp.diag,
    "trace": jnp.trace,
    "gt": lambda a, b: (a > b).astype(jnp.float32),
    "gte": lambda a, b: (a >= b).astype(jnp.float32),
    "lt": lambda a, b: (a < b).astype(jnp.float32),
    "lte": lambda a, b: (a <= b).astype(jnp.float32),
    "eq": lambda a, b: (a == b).astype(jnp.float32),
    "neq": lambda a, b: (a != b).astype(jnp.float32),
    # activations / nn
    "tanh": jnp.tanh, "sigmoid": jax.nn.sigmoid, "relu": jax.nn.relu,
    "relu6": jax.nn.relu6, "elu": jax.nn.elu, "selu": jax.nn.selu,
    "gelu": jax.nn.gelu, "softplus": jax.nn.softplus,
    "softsign": jax.nn.soft_sign, "swish": jax.nn.swish,
    "hard_sigmoid": jax.nn.hard_sigmoid,
    "leaky_relu": lambda a, alpha=0.01: jax.nn.leaky_relu(a, alpha),
    "softmax": lambda a, dims=-1: jax.nn.softmax(a, axis=dims),
    "log_softmax": lambda a, dims=-1: jax.nn.log_softmax(a, axis=dims),
    "linear": lambda x, w, b=None: (x @ w + b) if b is not None else x @ w,
    "layer_norm": lambda x, gain, bias=None, eps=1e-5: (
        (x - jnp.mean(x, axis=-1, keepdims=True))
        / jnp.sqrt(jnp.var(x, axis=-1, keepdims=True) + eps) * gain
        + (0.0 if bias is None else bias)),
    "dropout": lambda a, p=0.5: a,  # inference semantics; fit() handles train
    "conv2d": _conv2d,
    "max_pooling2d": lambda x, size=(2, 2), stride=(2, 2), padding="VALID":
        _pool2d(x, "max", size, stride, padding),
    "avg_pooling2d": lambda x, size=(2, 2), stride=(2, 2), padding="VALID":
        _pool2d(x, "avg", size, stride, padding),
    "batch_mmul": lambda a, b: jnp.einsum("...ij,...jk->...ik", a, b),
    # losses (mean-reduced scalars, matching ND4J loss op defaults)
    "loss_mse": lambda labels, preds: jnp.mean((preds - labels) ** 2),
    "loss_mae": lambda labels, preds: jnp.mean(jnp.abs(preds - labels)),
    "loss_softmax_ce": lambda labels, logits:
        jnp.mean(-jnp.sum(labels * jax.nn.log_softmax(logits, axis=-1), axis=-1)),
    "loss_sigmoid_ce": lambda labels, logits: jnp.mean(
        jnp.maximum(logits, 0) - logits * labels + jnp.log1p(jnp.exp(-jnp.abs(logits)))),
    "loss_log": lambda labels, preds, eps=1e-7: jnp.mean(
        -(labels * jnp.log(preds + eps) + (1 - labels) * jnp.log(1 - preds + eps))),
    "loss_huber": lambda labels, preds, delta=1.0: jnp.mean(jnp.where(
        jnp.abs(preds - labels) <= delta,
        0.5 * (preds - labels) ** 2,
        delta * jnp.abs(preds - labels) - 0.5 * delta ** 2)),
    "loss_cosine": lambda labels, preds, dims=-1: jnp.mean(1.0 - jnp.sum(
        labels * preds, axis=dims)
        / (jnp.linalg.norm(labels, axis=dims) * jnp.linalg.norm(preds, axis=dims)
           + 1e-12)),
    "loss_hinge": lambda labels, preds: jnp.mean(
        jnp.maximum(0.0, 1.0 - (2.0 * labels - 1.0) * preds)),
    # scatter family (ND4J ScatterUpdate/ScatterAdd/… — indices address dim
    # 0 of ref; updates shape = indices.shape + ref.shape[1:]). Duplicate
    # indices: the arithmetic ops (add/sub/mul/div) accumulate all updates
    # like ND4J; scatter_update's winner among duplicates is undefined.
    "scatter_update": lambda ref, idx, upd: ref.at[_ids(idx)].set(upd),
    "scatter_add": lambda ref, idx, upd: ref.at[_ids(idx)].add(upd),
    "scatter_sub": lambda ref, idx, upd: ref.at[_ids(idx)].add(-upd),
    "scatter_mul": lambda ref, idx, upd: ref.at[_ids(idx)].multiply(upd),
    "scatter_div": lambda ref, idx, upd: ref.at[_ids(idx)].divide(upd),
    "scatter_max": lambda ref, idx, upd: ref.at[_ids(idx)].max(upd),
    "scatter_min": lambda ref, idx, upd: ref.at[_ids(idx)].min(upd),
    "gather_nd": _gather_nd,
    # segment reductions (ND4J (unsorted)segment* — one op serves both; the
    # sorted precondition is not required by the XLA lowering)
    "segment_sum": lambda d, ids, num_segments=None:
        jax.ops.segment_sum(d, _ids(ids), _nseg(num_segments, "segment_sum")),
    "segment_mean": _segment_mean,
    "segment_max": lambda d, ids, num_segments=None:
        jax.ops.segment_max(d, _ids(ids), _nseg(num_segments, "segment_max")),
    "segment_min": lambda d, ids, num_segments=None:
        jax.ops.segment_min(d, _ids(ids), _nseg(num_segments, "segment_min")),
    "segment_prod": lambda d, ids, num_segments=None:
        jax.ops.segment_prod(d, _ids(ids), _nseg(num_segments, "segment_prod")),
    # conv builder ops beyond conv2d
    "conv1d": _conv1d,
    "depthwise_conv2d": _depthwise_conv2d,
    "deconv2d": _deconv2d,
    "space_to_depth": _space_to_depth,
    "depth_to_space": _depth_to_space,
    # control-flow plumbing: a while_loop node's value is the carried tuple;
    # tuple_get projects one element out at the top level
    "tuple_get": lambda t, index=0: t[index],
}


class SDVariable:
    """A symbolic node: placeholder, trainable variable, constant, or op
    result (ND4J ``SDVariable``). Supports operator algebra; every operation
    records a new node on the owning ``SameDiff`` tape."""

    def __init__(self, sd: "SameDiff", name: str, kind: str,
                 op: Optional[str] = None, inputs: Sequence[str] = (),
                 attrs: Optional[dict] = None,
                 shape: Optional[Tuple] = None,
                 scope: Optional[str] = None):
        self.sd = sd
        self.name = name
        self.kind = kind  # "placeholder" | "variable" | "constant" | "op"
        self.op = op
        self.inputs = tuple(inputs)
        self.attrs = attrs or {}
        self._declared_shape = shape
        # non-None: node belongs to a control-flow branch/loop body and only
        # executes inside its owning if_cond/while_loop node
        self.scope = scope

    # -- algebra ------------------------------------------------------------
    def _bin(self, other, op, name=None):
        other = self.sd._as_var(other)
        return self.sd._op(op, [self, other], name=name)

    def __add__(self, o):
        return self._bin(o, "add")

    __radd__ = __add__

    def __sub__(self, o):
        return self._bin(o, "sub")

    def __rsub__(self, o):
        return self._bin(o, "rsub")

    def __mul__(self, o):
        return self._bin(o, "mul")

    __rmul__ = __mul__

    def __truediv__(self, o):
        return self._bin(o, "div")

    def __rtruediv__(self, o):
        return self._bin(o, "rdiv")

    def __pow__(self, o):
        return self._bin(o, "pow")

    def __matmul__(self, o):
        return self._bin(o, "matmul")

    def __neg__(self):
        return self.sd._op("neg", [self])

    # named algebra (ND4J method spellings)
    def add(self, o, name=None):
        return self._bin(o, "add", name)

    def sub(self, o, name=None):
        return self._bin(o, "sub", name)

    def mul(self, o, name=None):
        return self._bin(o, "mul", name)

    def div(self, o, name=None):
        return self._bin(o, "div", name)

    def mmul(self, o, name=None):
        return self._bin(o, "matmul", name)

    def rsub(self, o, name=None):
        return self._bin(o, "rsub", name)

    def rdiv(self, o, name=None):
        return self._bin(o, "rdiv", name)

    # reductions
    def _reduce(self, op, dims, keepdims, name=None):
        return self.sd._op(op, [self], name=name,
                           attrs={"dims": _normalize_dims(dims),
                                  "keepdims": keepdims})

    def sum(self, dims=None, keepdims=False, name=None):
        return self._reduce("sum", dims, keepdims, name)

    def mean(self, dims=None, keepdims=False, name=None):
        return self._reduce("mean", dims, keepdims, name)

    def max(self, dims=None, keepdims=False, name=None):
        return self._reduce("max", dims, keepdims, name)

    def min(self, dims=None, keepdims=False, name=None):
        return self._reduce("min", dims, keepdims, name)

    def prod(self, dims=None, keepdims=False, name=None):
        return self._reduce("prod", dims, keepdims, name)

    def std(self, dims=None, bias_corrected=True, keepdims=False, name=None):
        return self.sd._op("std", [self], name=name,
                           attrs={"dims": _normalize_dims(dims),
                                  "keepdims": keepdims,
                                  "bias_corrected": bias_corrected})

    def norm2(self, dims=None, keepdims=False, name=None):
        return self._reduce("norm2", dims, keepdims, name)

    def norm1(self, dims=None, keepdims=False, name=None):
        return self._reduce("norm1", dims, keepdims, name)

    def argmax(self, dims=None, name=None):
        return self.sd._op("argmax", [self], name=name,
                           attrs={"dims": dims})

    # structure
    def T(self, *axes, name=None):
        return self.sd._op("transpose", [self], name=name,
                           attrs={"axes": axes or None})

    transpose = T

    def reshape(self, *shape, name=None):
        if len(shape) == 1 and isinstance(shape[0], (list, tuple)):
            shape = tuple(shape[0])
        return self.sd._op("reshape", [self], name=name,
                           attrs={"shape": shape})

    def get(self, *slices, name=None):
        if len(slices) == 1 and isinstance(slices[0], tuple):
            slices = slices[0]  # x[0:1, 2:5] arrives as one tuple
        spec = [[s.start, s.stop, s.step if s.step else 1]
                if isinstance(s, slice) else [s, s + 1, 1] for s in slices]
        return self.sd._op("strided_slice", [self], name=name,
                           attrs={"slices": spec})

    __getitem__ = get

    # -- execution ----------------------------------------------------------
    @property
    def shape(self):
        """Inferred shape (``jax.eval_shape`` — no compute). ``None`` dims in
        placeholder shapes are treated as 1 for inference."""
        return self.sd.infer_shape(self.name)

    def eval(self, placeholders: Optional[Dict[str, np.ndarray]] = None):
        return self.sd.output(placeholders or {}, self.name)[self.name]

    def gradient(self) -> "SDVariable":
        return self.sd.grad(self.name)

    def rename(self, name: str) -> "SDVariable":
        return self.sd.rename(self.name, name)

    def __repr__(self):
        return f"SDVariable(name={self.name!r}, kind={self.kind!r}, op={self.op!r})"


class _Namespace:
    """Op namespace (``sd.math``, ``sd.nn``, ``sd.loss``) exposing registry
    ops as methods, mirroring ND4J's ``sd.math()``/``sd.nn()``/``sd.loss()``."""

    def __init__(self, sd: "SameDiff", ops: Dict[str, str], attr_names: Dict[str, tuple]):
        self._sd = sd
        self._ops = ops
        self._attr_names = attr_names

    def __getattr__(self, item):
        if item.startswith("_"):
            raise AttributeError(item)
        op = self._ops.get(item)
        if op is None:
            raise AttributeError(f"unknown op {item!r}; available: {sorted(self._ops)}")

        def call(*args, name=None, **kwargs):
            # SDVariable args are graph inputs. A plain-SCALAR positional
            # arg fills the op's declared positional attrs (e.g.
            # nn.leaky_relu(x, 0.2)) — but only once the op's required
            # tensor inputs are satisfied (_MIN_INPUTS), so a scalar gather
            # index (gather(x, 2, 0)) binds as the indices INPUT, not the
            # axis. Arrays/lists always lift to constant inputs, as do
            # scalars of ops without declared attrs (math.maximum(x, 0.0)).
            # An attr already given as a kwarg is never overwritten.
            import numbers
            pos_attrs = [a for a in self._attr_names.get(item, ())
                         if a not in kwargs]
            need = _MIN_INPUTS.get(item, 1)
            inputs, attrs, attr_i = [], dict(kwargs), 0
            for a in args:
                if isinstance(a, SDVariable):
                    inputs.append(a)
                elif (attr_i < len(pos_attrs) and len(inputs) >= need
                      and isinstance(a, (numbers.Number, str))):
                    attrs[pos_attrs[attr_i]] = a
                    attr_i += 1
                else:
                    inputs.append(self._sd._as_var(a))
            return self._sd._op(op, inputs, name=name, attrs=attrs)

        return call


# ops whose leading positional args are TENSOR inputs even when spelled as
# plain scalars/lists (a scalar after that still fills positional attrs)
_MIN_INPUTS = {
    "gather": 2, "gather_nd": 2,
    "segment_sum": 2, "segment_mean": 2, "segment_max": 2,
    "segment_min": 2, "segment_prod": 2,
    "scatter_update": 3, "scatter_add": 3, "scatter_sub": 3,
    "scatter_mul": 3, "scatter_div": 3, "scatter_max": 3, "scatter_min": 3,
}

_MATH_OPS = {n: n for n in (
    "abs exp log sqrt square sin cos tan floor ceil round sign erf "
    "reciprocal log1p expm1 neg maximum minimum pow clip_by_value "
    "sum mean max min prod std variance argmax argmin norm2 norm1 "
    "gt gte lt lte eq neq where cast tanh "
    "cumsum cumprod sort logsumexp l2_normalize mod floor_div "
    "atan asin acos sinh cosh asinh acosh atanh atan2 isnan isinf "
    "diag trace "
    "gather gather_nd scatter_update scatter_add scatter_sub scatter_mul "
    "scatter_div scatter_max scatter_min "
    "segment_sum segment_mean segment_max segment_min segment_prod").split()}
_NN_OPS = {n: n for n in (
    "relu relu6 elu selu gelu softplus softsign swish hard_sigmoid "
    "leaky_relu softmax log_softmax sigmoid tanh linear layer_norm dropout "
    "conv2d max_pooling2d avg_pooling2d batch_mmul "
    "conv1d depthwise_conv2d deconv2d space_to_depth depth_to_space").split()}
_LOSS_OPS = {
    "mean_squared_error": "loss_mse",
    "mse": "loss_mse",
    "absolute_difference": "loss_mae",
    "softmax_cross_entropy": "loss_softmax_ce",
    "sigmoid_cross_entropy": "loss_sigmoid_ce",
    "log_loss": "loss_log",
    "huber_loss": "loss_huber",
    "cosine_distance": "loss_cosine",
    "hinge_loss": "loss_hinge",
}
# positional attr spellings for namespace calls like nn.leaky_relu(x, 0.2)
_ATTRS = {
    "leaky_relu": ("alpha",),
    "clip_by_value": ("clip_min", "clip_max"),
    "dropout": ("p",),
    "huber_loss": ("delta",),
    "gather": ("axis",),
    "segment_sum": ("num_segments",),
    "segment_mean": ("num_segments",),
    "segment_max": ("num_segments",),
    "segment_min": ("num_segments",),
    "segment_prod": ("num_segments",),
    "space_to_depth": ("block_size",),
    "depth_to_space": ("block_size",),
}


class TrainingConfig:
    """Training configuration (ND4J ``TrainingConfig``): updater +
    regularization + which DataSet slots feed which placeholders."""

    def __init__(self, updater=None, l1: float = 0.0, l2: float = 0.0,
                 data_set_feature_mapping: Sequence[str] = ("input",),
                 data_set_label_mapping: Sequence[str] = ("label",)):
        from deeplearning4j_tpu.nn.updaters import Sgd
        self.updater = updater if updater is not None else Sgd(1e-2)
        self.l1 = float(l1)
        self.l2 = float(l2)
        self.feature_mapping = list(data_set_feature_mapping)
        self.label_mapping = list(data_set_label_mapping)


class SameDiff:
    """The graph container (ND4J ``SameDiff``).

    Nodes are appended in creation order, which IS a topological order (ops
    can only reference existing nodes), so lowering is a single pass."""

    def __init__(self):
        self._nodes: Dict[str, SDVariable] = {}
        self._order: List[str] = []
        self.variables_map: Dict[str, jnp.ndarray] = {}   # trainable values
        self.constants_map: Dict[str, jnp.ndarray] = {}
        self._loss_variables: List[str] = []
        self._training_config: Optional[TrainingConfig] = None
        self._updater_state = None
        self._grads: Dict[str, np.ndarray] = {}
        self._jit_cache: Dict[tuple, Callable] = {}
        self._counter = 0
        self._active_scope: Optional[str] = None
        self.math = _Namespace(self, _MATH_OPS, _ATTRS)
        self.nn = _Namespace(self, _NN_OPS, _ATTRS)
        self.loss = _Namespace(self, _LOSS_OPS, _ATTRS)

    # -- construction -------------------------------------------------------
    @staticmethod
    def create() -> "SameDiff":
        return SameDiff()

    def _fresh_name(self, base: str) -> str:
        while True:
            self._counter += 1
            name = f"{base}_{self._counter}"
            if name not in self._nodes:
                return name

    def _register(self, v: SDVariable) -> SDVariable:
        if v.name in self._nodes:
            raise ValueError(f"duplicate variable name {v.name!r}")
        if v.scope is None:
            v.scope = self._active_scope
        self._nodes[v.name] = v
        self._order.append(v.name)
        self._jit_cache.clear()
        return v

    def place_holder(self, name: str, shape: Optional[Sequence] = None,
                     dtype=jnp.float32) -> SDVariable:
        if self._active_scope is not None:
            raise ValueError("create placeholders outside control-flow bodies")
        return self._register(SDVariable(
            self, name, "placeholder",
            shape=None if shape is None else tuple(shape)))

    placeHolder = place_holder  # ND4J spelling

    def var(self, name: str, value=None, shape: Optional[Sequence] = None,
            weight_init: str = "xavier", seed: int = 0,
            dtype=jnp.float32) -> SDVariable:
        """Trainable variable: pass an initial array OR a shape (+init)."""
        if self._active_scope is not None:
            raise ValueError("create variables outside control-flow bodies "
                             "(close over them instead)")
        if value is None:
            if shape is None:
                raise ValueError("var() needs an initial value or a shape")
            from deeplearning4j_tpu.nn.weights import init_weight
            shape = tuple(int(s) for s in shape)
            fan_in = shape[0] if shape else 1
            fan_out = shape[-1] if len(shape) >= 2 else (shape[0] if shape else 1)
            value = init_weight(jax.random.PRNGKey(seed + len(self._order)),
                                shape, weight_init, fan_in, fan_out,
                                dtype=dtype)
        value = jnp.asarray(value, dtype=dtype)
        self.variables_map[name] = value
        return self._register(SDVariable(self, name, "variable",
                                         shape=tuple(value.shape)))

    def constant(self, name: str, value) -> SDVariable:
        value = jnp.asarray(value)
        self.constants_map[name] = value
        return self._register(SDVariable(self, name, "constant",
                                         shape=tuple(value.shape)))

    def _as_var(self, v) -> SDVariable:
        if isinstance(v, SDVariable):
            if v.sd is not self:
                raise ValueError("SDVariable belongs to a different SameDiff")
            return v
        return self.constant(self._fresh_name("const"), v)

    def _op(self, op: str, inputs: Sequence[SDVariable], name: Optional[str] = None,
            attrs: Optional[dict] = None) -> SDVariable:
        if op not in OPS:
            raise ValueError(f"unknown op {op!r}")
        name = name or self._fresh_name(op)
        return self._register(SDVariable(
            self, name, "op", op=op,
            inputs=[self._as_var(i).name for i in inputs],
            attrs={k: v for k, v in (attrs or {}).items() if v is not None}))

    # -- control flow -------------------------------------------------------
    def _scoped_build(self, scope_id: str, build: Callable) -> Tuple:
        """Run a body-builder with ``scope_id`` active; returns (result,
        names of the nodes it created). Scoped nodes execute only inside
        their owning control-flow node."""
        if self._active_scope is not None:
            raise NotImplementedError(
                "nested control flow (if/while inside a branch/body) is not "
                "supported")
        start = len(self._order)
        self._active_scope = scope_id
        try:
            result = build()
        finally:
            self._active_scope = None
        return result, self._order[start:]

    def _outer_deps(self, scope_names: Sequence[str],
                    outs: Sequence[str] = ()) -> List[str]:
        """Top-level nodes a scope reads (closure captures), in tape order.
        ``outs`` are the branch/body result names: a passthrough result
        (an outer node returned directly, e.g. ``lambda s: c``) is a
        capture too even though no scoped node reads it."""
        scope_set = set(scope_names)
        deps = {o for o in outs if o not in scope_set}
        for m in scope_names:
            for i in self._nodes[m].inputs:
                if i not in scope_set:
                    deps.add(i)
        return [n for n in self._order if n in deps]

    def if_cond(self, cond: "SDVariable", true_fn: Callable,
                false_fn: Callable, name: Optional[str] = None) -> "SDVariable":
        """Conditional execution (ND4J ``sd.ifCond(cond, trueBody,
        falseBody)``): ``true_fn(sd)``/``false_fn(sd)`` each build a
        subgraph (closing over outer variables is fine) and return one
        SDVariable; only the taken branch executes, both must produce the
        same shape/dtype. Lowered to ``jax.lax.cond`` — compiled once,
        branch chosen on device, differentiable (``jax.grad`` flows through
        the taken branch)."""
        name = name or self._fresh_name("if")
        t_out, t_scope = self._scoped_build(name, lambda: true_fn(self))
        f_out, f_scope = self._scoped_build(name, lambda: false_fn(self))
        outer = self._outer_deps(list(t_scope) + list(f_scope),
                                 outs=(t_out.name, f_out.name))
        return self._register(SDVariable(
            self, name, "op", op="if_cond",
            inputs=[cond.name] + outer,
            attrs={"true_scope": list(t_scope), "false_scope": list(f_scope),
                   "true_out": t_out.name, "false_out": f_out.name,
                   "n_outer": len(outer)}))

    def top_k(self, x, k: int, name: Optional[str] = None):
        """(values, indices) of the k largest along the last axis
        (ND4J ``sd.nn.topK`` / ``lax.top_k``). The node's value is the
        pair; the returned SDVariables are its ``tuple_get`` views."""
        name = name or self._fresh_name("topk")
        node = self._register(SDVariable(
            self, name, "op", op="top_k", inputs=[self._as_var(x).name],
            attrs={"k": int(k)}))
        values = self._op("tuple_get", [node], name=f"{name}_values",
                          attrs={"index": 0})
        indices = self._op("tuple_get", [node], name=f"{name}_indices",
                           attrs={"index": 1})
        return values, indices

    topK = top_k  # ND4J spelling

    ifCond = if_cond  # ND4J spelling

    def while_loop(self, loop_vars: Sequence["SDVariable"],
                   cond_fn: Callable, body_fn: Callable,
                   name: Optional[str] = None,
                   max_iterations: Optional[int] = None) -> List["SDVariable"]:
        """Carried loop (ND4J ``sd.whileLoop(loopVars, cond, body)``):
        ``cond_fn(sd, *vars) -> scalar`` and ``body_fn(sd, *vars) ->
        [vars']`` build subgraphs over symbolic loop variables (closing over
        outer variables is fine); shapes must be loop-invariant. Returns the
        final loop variables.

        Two lowerings:

        - ``max_iterations=None``: ``jax.lax.while_loop`` — the trip count
          is decided on device at run time, NO host round-trips per
          iteration. Forward-only (XLA cannot reverse-differentiate a
          dynamic trip count).
        - ``max_iterations=K``: ``jax.lax.scan`` over K steps with an
          active-flag mask — iterations after the condition first fails are
          identity. Same results whenever the true trip count is <= K, and
          REVERSE-MODE DIFFERENTIABLE: gradients flow through the executed
          iterations (masked steps pass them through unchanged), so loops
          can sit inside trained graphs. XLA unrolls nothing — one compiled
          scan body regardless of K."""
        name = name or self._fresh_name("while")
        init = [self._as_var(v) for v in loop_vars]

        def build():
            syms = [SDVariable(self, f"{name}_lv{i}", "op", op="loop_input",
                               attrs={"index": i}, scope=name)
                    for i in range(len(init))]
            for s in syms:
                self._register(s)
            c_out = cond_fn(self, *syms)
            b_outs = body_fn(self, *syms)
            if not isinstance(b_outs, (list, tuple)):
                b_outs = [b_outs]
            if len(b_outs) != len(init):
                raise ValueError(
                    f"while_loop body returned {len(b_outs)} values for "
                    f"{len(init)} loop variables")
            return c_out, list(b_outs)

        (c_out, b_outs), scope = self._scoped_build(name, build)
        outer = self._outer_deps(
            scope, outs=[c_out.name] + [b.name for b in b_outs])
        attrs = {"scope": list(scope), "cond_out": c_out.name,
                 "body_outs": [b.name for b in b_outs],
                 "n_loop_vars": len(init)}
        if max_iterations is not None:
            attrs["max_iterations"] = int(max_iterations)
        self._register(SDVariable(
            self, name, "op", op="while_loop",
            inputs=[v.name for v in init] + outer, attrs=attrs))
        return [self._op("tuple_get", [self._nodes[name]],
                         name=f"{name}_out{i}", attrs={"index": i})
                for i in range(len(init))]

    whileLoop = while_loop  # ND4J spelling

    def rename(self, old: str, new: str) -> SDVariable:
        node = self._nodes[old]
        if node.scope is not None or node.op in ("if_cond", "while_loop"):
            # control-flow attrs reference subgraph nodes by name
            raise ValueError("cannot rename control-flow nodes")
        self._jit_cache.clear()
        v = self._nodes.pop(old)
        v.name = new
        self._nodes[new] = v
        self._order[self._order.index(old)] = new
        for n in self._nodes.values():
            if old in n.inputs:
                n.inputs = tuple(new if i == old else i for i in n.inputs)
            if n.op in ("if_cond", "while_loop"):
                # control-flow attrs carry node NAMES (branch outputs may be
                # passthrough references to top-level nodes) — keep them live
                for k, val in n.attrs.items():
                    if val == old:
                        n.attrs[k] = new
                    elif isinstance(val, (list, tuple)) and old in val:
                        n.attrs[k] = [new if m == old else m for m in val]
        if old in self.variables_map:
            self.variables_map[new] = self.variables_map.pop(old)
        if old in self.constants_map:
            self.constants_map[new] = self.constants_map.pop(old)
        self._loss_variables = [new if x == old else x for x in self._loss_variables]
        return v

    def get_variable(self, name: str) -> SDVariable:
        return self._nodes[name]

    # -- lowering -----------------------------------------------------------
    def _build_fn(self, output_names: Sequence[str]):
        """Lower the tape to one pure function
        ``f(variables_dict, placeholders_dict) -> [outputs]``."""
        needed = set()
        stack = list(output_names)
        while stack:
            n = stack.pop()
            if n in needed:
                continue
            needed.add(n)
            stack.extend(self._nodes[n].inputs)
        # scoped nodes run only inside their owning control-flow node
        order = [n for n in self._order
                 if n in needed and self._nodes[n].scope is None]

        def run_scope(scope_names, operands, carry=None):
            """Execute a control-flow subgraph: operands = captured outer
            values; carry = loop-variable tuple (while_loop only)."""
            env2 = dict(operands)
            for m in scope_names:
                nd = self._nodes[m]
                if nd.kind == "constant":
                    env2[m] = self.constants_map[m]
                elif nd.op == "loop_input":
                    env2[m] = carry[nd.attrs["index"]]
                else:
                    env2[m] = OPS[nd.op](*(env2[i] for i in nd.inputs),
                                         **nd.attrs)
            return env2

        def fn(variables, placeholders):
            env = {}
            for n in order:
                node = self._nodes[n]
                if node.kind == "placeholder":
                    env[n] = placeholders[n]
                elif node.kind == "variable":
                    env[n] = variables[n]
                elif node.kind == "constant":
                    env[n] = self.constants_map[n]
                elif node.op == "if_cond":
                    a = node.attrs
                    pred = jnp.reshape(env[node.inputs[0]], ()) != 0
                    operands = {d: env[d] for d in node.inputs[1:]}
                    env[n] = jax.lax.cond(
                        pred,
                        lambda ops_, _a=a: run_scope(
                            _a["true_scope"], ops_)[_a["true_out"]],
                        lambda ops_, _a=a: run_scope(
                            _a["false_scope"], ops_)[_a["false_out"]],
                        operands)
                elif node.op == "while_loop":
                    a = node.attrs
                    nlv = a["n_loop_vars"]
                    init = tuple(env[i] for i in node.inputs[:nlv])
                    operands = {d: env[d] for d in node.inputs[nlv:]}
                    max_it = a.get("max_iterations")
                    if max_it:
                        # bounded loop → lax.scan with an active-flag mask:
                        # reverse-mode differentiable (scan has a VJP;
                        # masked steps are identity for value AND gradient)
                        def step(carry, _x, _a=a, _o=operands):
                            vars_, active = carry
                            env2 = run_scope(_a["scope"], _o, vars_)
                            cond = jnp.reshape(
                                env2[_a["cond_out"]], ()) != 0
                            act = jnp.logical_and(active, cond)
                            new_vars = tuple(
                                jnp.where(act, env2[m], v) for m, v
                                in zip(_a["body_outs"], vars_))
                            return (new_vars, act), None
                        (final, _), _ = jax.lax.scan(
                            step, (init, jnp.asarray(True)), None,
                            length=int(max_it))
                        env[n] = final
                    else:
                        env[n] = jax.lax.while_loop(
                            lambda carry, _a=a, _o=operands: jnp.reshape(
                                run_scope(_a["scope"], _o, carry)[_a["cond_out"]],
                                ()) != 0,
                            lambda carry, _a=a, _o=operands: tuple(
                                run_scope(_a["scope"], _o, carry)[m]
                                for m in _a["body_outs"]),
                            init)
                else:
                    env[n] = OPS[node.op](*(env[i] for i in node.inputs),
                                          **node.attrs)
            return [env[n] for n in output_names]

        return fn

    # -- execution ----------------------------------------------------------
    def output(self, placeholders: Dict[str, np.ndarray],
               *output_names: str) -> Dict[str, np.ndarray]:
        """Execute the graph (ND4J ``sd.output(map, names)``), jit-compiled."""
        if not output_names:
            raise ValueError("no output names given")
        key = ("out",) + tuple(output_names)
        jf = self._jit_cache.get(key)
        if jf is None:
            jf = self._jit_cache[key] = jax.jit(self._build_fn(output_names))
        ph = {k: jnp.asarray(v) for k, v in placeholders.items()}
        outs = jf(self.variables_map, ph)
        return {n: np.asarray(o) for n, o in zip(output_names, outs)}

    exec = output

    def infer_shape(self, name: str):
        node = self._nodes[name]
        if node._declared_shape is not None and node.kind != "op":
            return node._declared_shape
        fn = self._build_fn([name])
        ph = {}
        for n in self._nodes.values():
            if n.kind == "placeholder":
                s = n._declared_shape or (1,)
                ph[n.name] = jax.ShapeDtypeStruct(
                    tuple(1 if d is None else d for d in s), jnp.float32)
        out = jax.eval_shape(fn, self.variables_map, ph)
        return tuple(out[0].shape)

    # -- autodiff -----------------------------------------------------------
    def set_loss_variables(self, *names: str) -> None:
        self._loss_variables = [n if isinstance(n, str) else n.name for n in names]

    def _loss_fn(self):
        if not self._loss_variables:
            raise ValueError("no loss variables set (set_loss_variables)")
        inner = self._build_fn(self._loss_variables)

        def loss(variables, placeholders):
            outs = inner(variables, placeholders)
            return sum(jnp.sum(o) for o in outs)

        return loss

    def calculate_gradients(self, placeholders: Dict[str, np.ndarray],
                            *wrt: str) -> Dict[str, np.ndarray]:
        """d(sum of loss variables)/d(wrt) (ND4J ``calculateGradients``)."""
        wrt = [w if isinstance(w, str) else w.name for w in wrt] or \
            list(self.variables_map)
        key = ("grad",) + tuple(self._loss_variables)
        jf = self._jit_cache.get(key)
        if jf is None:
            jf = self._jit_cache[key] = jax.jit(jax.grad(self._loss_fn()))
        ph = {k: jnp.asarray(v) for k, v in placeholders.items()}
        grads = jf(self.variables_map, ph)
        self._grads = {k: np.asarray(v) for k, v in grads.items() if k in wrt}
        return dict(self._grads)

    def grad(self, name: str) -> np.ndarray:
        if name not in self._grads:
            raise ValueError(
                f"no gradient for {name!r}; run calculate_gradients first")
        return self._grads[name]

    # -- training -----------------------------------------------------------
    def set_training_config(self, cfg: TrainingConfig) -> None:
        self._training_config = cfg
        self._updater_state = None

    def fit(self, dataset=None, epochs: int = 1, features=None, labels=None):
        """Train on a DataSet / iterator (ND4J ``sd.fit``): jitted step with
        donated variable buffers; loss = sum of loss variables (+l1/l2)."""
        cfg = self._training_config
        if cfg is None:
            raise ValueError("set_training_config first")
        from deeplearning4j_tpu.datasets.dataset import DataSet

        if dataset is None:
            dataset = DataSet(np.asarray(features), np.asarray(labels))
        batches = [dataset] if isinstance(dataset, DataSet) else list(dataset)

        loss_fn = self._loss_fn()

        def step_loss(variables, ph):
            loss = loss_fn(variables, ph)
            if cfg.l2:
                loss = loss + cfg.l2 * sum(
                    jnp.sum(v * v) for v in variables.values())
            if cfg.l1:
                loss = loss + cfg.l1 * sum(
                    jnp.sum(jnp.abs(v)) for v in variables.values())
            return loss

        upd = cfg.updater

        @jax.jit
        def train_step(variables, opt_state, ph, lr, t):
            loss, grads = jax.value_and_grad(step_loss)(variables, ph)
            new_vars, new_state = {}, {}
            for k, v in variables.items():
                delta, s = upd.update(grads[k], opt_state[k], lr, t)
                new_vars[k] = v - delta
                new_state[k] = s
            return new_vars, new_state, loss

        if self._updater_state is None:
            self._updater_state = {k: upd.init_state(v)
                                   for k, v in self.variables_map.items()}
        it = 0
        last = None
        for epoch in range(int(epochs)):
            for ds in batches:
                ph = self._feed(cfg, ds, with_labels=True)
                lr = jnp.asarray(upd.lr_at(it, epoch), jnp.float32)
                # t is 1-based: Adam-family bias correction divides by
                # (1 - beta^t), which is 0 at t=0
                self.variables_map, self._updater_state, last = train_step(
                    self.variables_map, self._updater_state, ph, lr,
                    jnp.asarray(it + 1))
                it += 1
        return None if last is None else float(last)

    @staticmethod
    def _feed(cfg: TrainingConfig, ds, with_labels: bool) -> dict:
        """DataSet → placeholder dict via the TrainingConfig mappings (shared
        by fit and evaluate so the feeding convention cannot diverge)."""
        feats = ds.features if isinstance(ds.features, (list, tuple)) \
            else [ds.features]
        ph = {n: jnp.asarray(a) for n, a in zip(cfg.feature_mapping, feats)}
        if with_labels:
            labs = ds.labels if isinstance(ds.labels, (list, tuple)) \
                else [ds.labels]
            for n, a in zip(cfg.label_mapping, labs):
                ph[n] = jnp.asarray(a)
        return ph

    def evaluate(self, iterator, output_name: str, evaluation=None):
        """Evaluate an output variable against labels from a DataSet iterator
        (ND4J ``sd.evaluate``): feeds placeholders via the TrainingConfig
        mappings, accumulates into an Evaluation (or the given metric)."""
        cfg = self._training_config
        if cfg is None:
            raise ValueError("set_training_config first (placeholder mappings)")
        from deeplearning4j_tpu.datasets.dataset import DataSet
        from deeplearning4j_tpu.eval.evaluation import Evaluation
        ev = evaluation if evaluation is not None else Evaluation()
        batches = [iterator] if isinstance(iterator, DataSet) else iterator
        for ds in batches:
            preds = self.output(self._feed(cfg, ds, with_labels=False),
                                output_name)[output_name]
            labs = ds.labels if isinstance(ds.labels, (list, tuple)) \
                else [ds.labels]
            ev.eval(np.asarray(labs[0]), preds)
        return ev

    # -- serde --------------------------------------------------------------
    def to_json(self) -> str:
        return json.dumps({
            "nodes": [{
                "name": n, "kind": v.kind, "op": v.op,
                "inputs": list(v.inputs), "attrs": v.attrs,
                "shape": None if v._declared_shape is None
                else list(v._declared_shape),
                "scope": v.scope,
            } for n, v in ((n, self._nodes[n]) for n in self._order)],
            "loss_variables": self._loss_variables,
        })

    def save(self, path: str) -> None:
        """Graph JSON + variable/constant values in one npz (the capability of
        ND4J's flatbuffers ``sd.save``; format is npz, TPU-host friendly)."""
        arrays = {f"var__{k}": np.asarray(v) for k, v in self.variables_map.items()}
        arrays |= {f"const__{k}": np.asarray(v) for k, v in self.constants_map.items()}
        np.savez(path, __graph__=np.frombuffer(
            self.to_json().encode(), dtype=np.uint8), **arrays)

    @staticmethod
    def load(path: str) -> "SameDiff":
        data = np.load(path if str(path).endswith(".npz") else str(path) + ".npz",
                       allow_pickle=False)
        spec = json.loads(bytes(data["__graph__"]).decode())
        sd = SameDiff()
        for nd in spec["nodes"]:
            name, kind = nd["name"], nd["kind"]
            shape = None if nd["shape"] is None else tuple(nd["shape"])
            if kind == "placeholder":
                sd.place_holder(name, shape)
            elif kind == "variable":
                sd.var(name, value=data[f"var__{name}"])
            elif kind == "constant":
                sd.constant(name, data[f"const__{name}"])
                sd._nodes[name].scope = nd.get("scope")
            elif nd["op"] in ("if_cond", "while_loop", "loop_input"):
                # control-flow attrs hold name lists that must stay lists
                sd._register(SDVariable(sd, name, "op", op=nd["op"],
                                        inputs=nd["inputs"], attrs=nd["attrs"],
                                        scope=nd.get("scope")))
            else:
                attrs = {k: (tuple(v) if isinstance(v, list) else v)
                         for k, v in (nd["attrs"] or {}).items()}
                sd._register(SDVariable(sd, name, "op", op=nd["op"],
                                        inputs=nd["inputs"], attrs=attrs,
                                        scope=nd.get("scope")))
        sd._loss_variables = spec.get("loss_variables", [])
        return sd
