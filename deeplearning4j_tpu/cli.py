"""Command-line entry points.

The reference exposes exactly two ``main()``s (SURVEY.md §1): training via
``ParallelWrapperMain`` (`deeplearning4j-scaleout/.../parallelism/main/ParallelWrapperMain.java`,
JCommander flags: modelPath, workers, averagingFrequency, prefetchSize,
modelOutputPath, uiUrl) and serving via ``NearestNeighborsServer``
(`NearestNeighborsServer.java:3-10`). This module provides both:

- ``python -m deeplearning4j_tpu.cli train ...`` — load a serialized model,
  train it data-parallel over the mesh, save the result.
- ``python -m deeplearning4j_tpu.cli nn-server ...`` — serve k-NN queries
  (delegates to :meth:`NearestNeighborsServer.main`).
"""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional

import numpy as np


def parallel_wrapper_main(argv: Optional[List[str]] = None):
    """ParallelWrapperMain parity: train a saved model over the mesh."""
    ap = argparse.ArgumentParser("parallel-wrapper-train")
    ap.add_argument("--modelPath", required=True,
                    help="model zip written by ModelSerializer")
    ap.add_argument("--dataPath", required=True,
                    help=".npz with 'features' and 'labels' arrays")
    ap.add_argument("--modelOutputPath", required=True)
    ap.add_argument("--workers", type=int, default=None,
                    help="mesh data-axis size (default: all devices)")
    ap.add_argument("--mode", choices=("shared_gradients", "averaging"),
                    default="shared_gradients")
    ap.add_argument("--averagingFrequency", type=int, default=5)
    ap.add_argument("--batchSize", type=int, default=32)
    ap.add_argument("--epochs", type=int, default=1)
    ap.add_argument("--prefetchSize", type=int, default=2,
                    help="async prefetch buffer (AsyncDataSetIterator)")
    ap.add_argument("--uiUrl", default=None,
                    help="remote UI /remote endpoint to report stats to")
    args = ap.parse_args(argv)

    from deeplearning4j_tpu.datasets.dataset import DataSet, ListDataSetIterator
    from deeplearning4j_tpu.datasets.iterators import AsyncDataSetIterator
    from deeplearning4j_tpu.parallel import ParallelWrapper
    from deeplearning4j_tpu.parallel.mesh import make_mesh
    from deeplearning4j_tpu.util import model_serializer

    net = model_serializer.restore_model(args.modelPath)
    z = np.load(args.dataPath)
    ds = DataSet(z["features"], z["labels"])
    it = ListDataSetIterator(ds, args.batchSize, shuffle=True)
    if args.prefetchSize > 0:
        it = AsyncDataSetIterator(it, queue_size=args.prefetchSize)
    if args.uiUrl:
        from deeplearning4j_tpu.ui import StatsListener
        from deeplearning4j_tpu.ui.remote import RemoteUIStatsStorageRouter
        net.listeners.append(
            StatsListener(RemoteUIStatsStorageRouter(args.uiUrl)))
    mesh = None
    if args.workers:
        mesh = make_mesh({"data": args.workers})
    pw = ParallelWrapper(net, mesh, mode=args.mode,
                         averaging_frequency=args.averagingFrequency)
    pw.fit(it, epochs=args.epochs)
    model_serializer.write_model(net, args.modelOutputPath)
    return net


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] in ("-h", "--help"):
        print("usage: python -m deeplearning4j_tpu.cli {train,nn-server} ...")
        return 0 if argv else 2
    cmd, rest = argv[0], argv[1:]
    if cmd == "train":
        parallel_wrapper_main(rest)
        return 0
    if cmd == "nn-server":
        from deeplearning4j_tpu.clustering.server import NearestNeighborsServer
        server = NearestNeighborsServer.main(rest)
        print(f"nearest-neighbors server listening on port {server.port}")
        try:
            server._thread.join()
        except KeyboardInterrupt:
            server.stop()
        return 0
    print(f"unknown command {cmd!r}; expected 'train' or 'nn-server'")
    return 2


if __name__ == "__main__":
    raise SystemExit(main())
